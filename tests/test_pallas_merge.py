"""Pallas merge-path kernel: interpret-mode equivalence with the XLA merge.

Runs on the CPU mesh in pallas interpret mode (the tunnel-independent
correctness pin); the Mosaic-lowered TPU build was byte-equality
validated on hardware (r3) and is the default on real TPU backends —
PEGASUS_PALLAS=0/1 forces it off/on (=1 means interpret mode on CPU).
"""

import numpy as np
import pytest

from pegasus_tpu.ops import pallas_merge
from pegasus_tpu.ops.device_sort import merge_two_sorted

NCOLS = 4


def make_sorted(rng, n, lo=0, hi=1 << 20):
    prim = np.sort(rng.integers(lo, hi, size=n, dtype=np.uint32))
    rest = [rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
            for _ in range(NCOLS - 1)]
    order = np.lexsort(tuple(reversed([prim] + rest)))
    return [c[order] for c in [prim] + rest]


@pytest.mark.parametrize("la,lb,seed", [
    (1000, 1000, 0),
    (1, 5000, 1),
    (5000, 1, 2),
    (3000, 7001, 3),
    (2048, 2048, 4),          # exact chunk multiples
    (pallas_merge.CHUNK * 2 + 17, pallas_merge.CHUNK - 3, 5),
])
def test_pallas_merge_matches_xla_merge(la, lb, seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    A, B = make_sorted(rng, la), make_sorted(rng, lb)
    pad_fill = tuple([np.uint32(0xFFFFFFFF)] * NCOLS + [np.int32(-1)])
    a_ops = [jnp.asarray(c) for c in A] + [jnp.arange(la, dtype=jnp.int32)]
    b_ops = [jnp.asarray(c) for c in B] + [
        jnp.arange(la, la + lb, dtype=jnp.int32)]
    got = pallas_merge.merge_two_sorted_pallas(a_ops, b_ops, NCOLS, pad_fill)
    want = merge_two_sorted(a_ops, b_ops, NCOLS, pad_fill)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g)[: la + lb],
                                      np.asarray(w)[: la + lb])


def test_pallas_merge_skewed_distributions():
    """Disjoint ranges + heavy overlap: diagonal search edge cases."""
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    pad_fill = tuple([np.uint32(0xFFFFFFFF)] * NCOLS + [np.int32(-1)])
    for A, B in [
        (make_sorted(rng, 4000, 0, 1000), make_sorted(rng, 4000, 10_000, 11_000)),
        (make_sorted(rng, 4000, 10_000, 11_000), make_sorted(rng, 4000, 0, 1000)),
        (make_sorted(rng, 4096, 5, 6), make_sorted(rng, 4096, 5, 6)),
    ]:
        la, lb = len(A[0]), len(B[0])
        a_ops = [jnp.asarray(c) for c in A] + [jnp.arange(la, dtype=jnp.int32)]
        b_ops = [jnp.asarray(c) for c in B] + [
            jnp.arange(la, la + lb, dtype=jnp.int32)]
        got = pallas_merge.merge_two_sorted_pallas(a_ops, b_ops, NCOLS, pad_fill)
        want = merge_two_sorted(a_ops, b_ops, NCOLS, pad_fill)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g)[: la + lb],
                                          np.asarray(w)[: la + lb])
