"""Compaction-pipeline telemetry tests: stage spans (nesting, ring-buffer
bounds, counter export), the device-health watchdog (timeout path with a
deliberately-hung fake backend, wedge-stage attribution), and the
/metrics + compact-trace-dump round trip against a running service app.
"""

import json
import threading
import time
import urllib.request

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.engine.block import KVBlock
from pegasus_tpu.ops.device_watchdog import DeviceHealthWatchdog
from pegasus_tpu.runtime.perf_counters import counters
from pegasus_tpu.runtime.tracing import COMPACT_TRACER, StageTracer


def _make_block(n):
    return KVBlock.from_records(
        [(generate_key(b"h%d" % i, b"s"),
          SCHEMAS[2].generate_value(0, 0, b"v"), 0, False)
         for i in range(n)])


# --------------------------------------------------------------- span API


def test_span_nesting_records_depth_and_close_order():
    tr = StageTracer(prefix="t_nest")
    with tr.span("outer"):
        with tr.span("inner"):
            pass
        with tr.span("inner2"):
            pass
    rows = tr.trace()
    # children close before their parents; depth counts enclosing spans
    assert [(r["stage"], r["depth"]) for r in rows] == [
        ("inner", 1), ("inner2", 1), ("outer", 0)]
    assert all(r["duration_us"] >= 0 for r in rows)


def test_span_box_takes_mid_span_counts():
    tr = StageTracer(prefix="t_box")
    with tr.span("gather", records=1) as sp:
        sp["records"] = 41
        sp["bytes"] = 1000
    (row,) = tr.trace()
    assert row["records"] == 41 and row["bytes"] == 1000


def test_ring_buffer_bounded():
    tr = StageTracer(capacity=8, prefix="t_ring")
    for i in range(50):
        with tr.span(f"s{i}"):
            pass
    rows = tr.trace(last=1000)
    assert len(rows) == 8
    assert [r["stage"] for r in rows] == [f"s{i}" for i in range(42, 50)]
    # dump() renders every retained row
    assert tr.dump(1000).count("\n") == 7


def test_session_aggregates_per_stage():
    tr = StageTracer(prefix="t_sess")
    with tr.session() as sess:
        for _ in range(3):
            with tr.span("pack", records=10, nbytes=100):
                pass
        with tr.span("device", records=30):
            pass
    assert sess.stages["pack"]["calls"] == 3
    assert sess.stages["pack"]["records"] == 30
    assert sess.stages["pack"]["bytes"] == 300
    assert sess.stages["device"]["calls"] == 1
    summary = sess.summary()
    assert set(summary) == {"pack", "device"}
    assert summary["pack"]["s"] >= 0


def test_sessions_nest_and_are_thread_local():
    tr = StageTracer(prefix="t_tl")
    with tr.session() as outer:
        with tr.span("a"):
            pass
        with tr.session() as inner:
            with tr.span("b"):
                pass

            # a span closed on ANOTHER thread lands in neither session
            def other():
                with tr.span("c"):
                    pass

            t = threading.Thread(target=other)
            t.start()
            t.join()
    assert set(outer.stages) == {"a", "b"}
    assert set(inner.stages) == {"b"}
    stages = [r["stage"] for r in tr.trace()]
    assert "c" in stages  # the ring buffer itself is process-wide


def test_spans_export_rate_and_percentile_counters():
    tr = StageTracer(prefix="t_exp")
    with tr.span("device", records=7, nbytes=64):
        time.sleep(0.002)
    snap = counters.snapshot(prefix="t_exp.stage.device.")
    assert set(snap) == {"t_exp.stage.device.count",
                         "t_exp.stage.device.duration_us",
                         "t_exp.stage.device.records",
                         "t_exp.stage.device.bytes"}
    # the duration percentile keeps its sample (a rate would decay on read)
    assert counters.percentile(
        "t_exp.stage.device.duration_us").percentile(0.5) >= 2000


def test_open_stages_and_innermost_open():
    tr = StageTracer(prefix="t_open")
    release = threading.Event()
    entered = threading.Event()

    def worker():
        with tr.span("compact"):
            with tr.span("device"):
                entered.set()
                release.wait(10)

    t = threading.Thread(target=worker)
    t.start()
    try:
        assert entered.wait(10)
        (stack,) = tr.open_stages().values()
        assert stack == ["compact", "device"]
        stage, t0 = tr.innermost_open()
        assert stage == "device" and t0 <= time.time()
    finally:
        release.set()
        t.join()
    assert tr.open_stages() == {}
    assert tr.innermost_open() is None


def test_compact_pipeline_emits_stage_spans():
    """The real cpu pipeline threads pack/device/gather spans through the
    process-wide tracer — the breakdown bench.py records."""
    from pegasus_tpu.ops import CompactOptions, compact_blocks

    blk = _make_block(64)
    with COMPACT_TRACER.session() as sess:
        res = compact_blocks([blk], CompactOptions(backend="cpu", now=100))
    assert res.block.n == 64
    for stage in ("compact", "pack", "device", "gather"):
        assert stage in sess.stages, f"missing {stage}: {sess.summary()}"
    assert sess.stages["compact"]["records"] == 64
    assert sess.stages["pack"]["bytes"] > 0


# --------------------------------------------------------------- watchdog


def test_watchdog_ok_probe_records_last_ok():
    wd = DeviceHealthWatchdog(probe_fn=lambda: True,
                              tracer=StageTracer(prefix="t_wd0"))
    assert wd.probe() is True
    st = wd.state()
    assert st["last_ok"] is not None
    assert st["wedged_at_stage"] is None and st["last_error"] is None
    assert counters.number("compact.watchdog.wedged").value() == 0


def test_watchdog_timeout_attributes_wedged_stage():
    """A deliberately-hung fake backend: the probe must time out (not
    hang), refuse to stack a second probe behind the hung one, attribute
    the wedge to the innermost open span only once fail_threshold
    CONSECUTIVE probes failed (one starved probe is an error, not a
    wedge), and recover once the backend unwedges."""
    tr = StageTracer(prefix="t_wd1")
    hang = threading.Event()
    entered = threading.Event()
    wd = DeviceHealthWatchdog(probe_timeout_s=0.2, tracer=tr,
                              probe_fn=lambda: hang.wait(30) or True,
                              fail_threshold=2)

    def pipeline():
        with tr.span("compact"):
            with tr.span("h2d"):
                entered.set()
                hang.wait(30)

    t = threading.Thread(target=pipeline, daemon=True)
    t.start()
    try:
        assert entered.wait(10)
        t0 = time.monotonic()
        assert wd.probe() is False
        assert time.monotonic() - t0 < 5  # bounded, never the probe's 30s
        st = wd.state()
        # one failure is an error, NOT yet a wedge verdict (threshold=2)
        assert st["wedged_at_stage"] is None
        assert "timed out" in st["last_error"]
        assert ["compact", "h2d"] in st["open_stages"].values()
        # the first probe's thread is still wedged: fail fast, don't
        # stack — and the SECOND consecutive failure flips the verdict,
        # attributed to the innermost open span
        assert wd.probe() is False
        st = wd.state()
        assert "still hung" in st["last_error"]
        assert st["wedged_at_stage"] == "h2d"
        assert counters.number("compact.watchdog.wedged").value() == 1
    finally:
        hang.set()
        t.join()
    deadline = time.monotonic() + 10  # let the abandoned probe drain
    while wd.probe() is not True:
        assert time.monotonic() < deadline, wd.state()
        time.sleep(0.05)
    st = wd.state()
    assert st["wedged_at_stage"] is None and st["last_ok"] is not None


def test_watchdog_idle_attribution():
    wd = DeviceHealthWatchdog(probe_timeout_s=0.1,
                              tracer=StageTracer(prefix="t_wd2"),
                              probe_fn=lambda: threading.Event().wait(30),
                              fail_threshold=1)
    assert wd.probe() is False
    assert wd.state()["wedged_at_stage"] == "idle"


def test_watchdog_probe_error_is_a_failure_not_a_crash():
    def boom():
        raise RuntimeError("tunnel reset")

    wd = DeviceHealthWatchdog(probe_fn=boom,
                              tracer=StageTracer(prefix="t_wd3"))
    assert wd.probe() is False
    assert "tunnel reset" in wd.state()["last_error"]


def test_watchdog_loop_heartbeats_status_file(tmp_path):
    """start() probes + heartbeats on its interval; the status file is the
    cross-process channel bench.py's parent reads after abandoning a
    wedged lane child."""
    path = tmp_path / "wd.status"
    wd = DeviceHealthWatchdog(interval_s=0.05, probe_fn=lambda: True,
                              tracer=StageTracer(prefix="t_wd4"),
                              status_path=str(path))
    wd.start()
    try:
        deadline = time.monotonic() + 10
        while not path.exists():
            assert time.monotonic() < deadline
            time.sleep(0.02)
        payload = json.loads(path.read_text())
        assert payload["last_ok"] is not None
        assert payload["wedged_at_stage"] is None
        assert "ts" in payload
    finally:
        wd.stop()


# ---------------------------------------------- service-app round trip


@pytest.fixture
def service_pair(tmp_path):
    from pegasus_tpu.runtime.config import Config
    from pegasus_tpu.runtime.service_app import MetaApp, ReplicaApp

    ini = tmp_path / "app.ini"
    ini.write_text(f"""
[apps.meta]
type = meta
port = 0
state_dir = {tmp_path}/meta
http_port = 0

[apps.replica1]
type = replica
port = 0
data_dir = {tmp_path}/replica1
http_port = 0

[pegasus.server]
meta_servers = 127.0.0.1:0

[failure_detector]
beacon_interval_seconds = 0.2
""")
    cfg = Config(str(ini))
    meta_app = MetaApp("meta", cfg, "apps.meta")
    meta_app.start()
    cfg._parser.set("pegasus.server", "meta_servers", meta_app.address)
    rep_app = ReplicaApp("replica1", cfg, "apps.replica1").start()
    try:
        yield meta_app, rep_app
    finally:
        rep_app.stop()
        meta_app.stop()


def _http_get(reporter, path):
    host, port = reporter.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=5) as r:
        return r.read().decode()


def _seed_pipeline_counters(tmp_path):
    """Run the real cpu pipeline + an sst write so the process-wide
    registry holds compact.* and engine.* counters to scrape."""
    from pegasus_tpu.engine.sstable import write_sst
    from pegasus_tpu.ops import CompactOptions, compact_blocks

    blk = _make_block(32)
    res = compact_blocks([blk], CompactOptions(backend="cpu", now=100))
    write_sst(str(tmp_path / "seed.sst"), res.block)


def test_metrics_route_serves_compact_and_engine_counters(
        service_pair, tmp_path):
    """Acceptance: GET /metrics on a replica app serves Prometheus text
    including engine.* and compact.* counters (dots mangled to '_')."""
    _, rep_app = service_pair
    _seed_pipeline_counters(tmp_path)
    body = _http_get(rep_app.reporter, "/metrics")
    assert "# TYPE compact_stage_pack_count gauge" in body
    assert "compact_stage_device_count" in body
    assert "compact_stage_gather_count" in body
    assert "engine_sst_write_count" in body
    for line in body.splitlines():
        if not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            float(value)  # every sample line is name SP float


def test_compact_trace_routes_and_remote_command(service_pair, tmp_path):
    """The three trace surfaces read one tracer: the /compact/trace HTTP
    route (meta + replica), the compact-trace-dump remote command, and
    device-health — all reporting the spans the pipeline just emitted."""
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection
    from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                    RemoteCommandResponse)

    meta_app, rep_app = service_pair
    _seed_pipeline_counters(tmp_path)

    for reporter in (meta_app.reporter, rep_app.reporter):
        out = json.loads(_http_get(reporter, "/compact/trace?last=500"))
        stages = {s["stage"] for s in out["spans"]}
        assert {"pack", "device", "gather"} <= stages
        assert "wedged_at_stage" in out["watchdog"]
    # ?last=N bounds the dump
    out = json.loads(_http_get(rep_app.reporter, "/compact/trace?last=2"))
    assert len(out["spans"]) == 2

    host, _, port = rep_app.address.rpartition(":")
    conn = RpcConnection((host, int(port)))
    try:
        def cli(cmd, *args):
            _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
                RemoteCommandRequest(cmd, list(args))), timeout=10)
            return codec.decode(RemoteCommandResponse, body).output

        dump = cli("compact-trace-dump", "500")
        assert "pack" in dump and "device" in dump and "gather" in dump
        health = json.loads(cli("device-health"))
        assert "last_ok" in health and "wedged_at_stage" in health
        # the same registry the /metrics route serves
        snap = json.loads(cli("perf-counters-by-prefix", "compact.stage."))
        assert any(k.startswith("compact.stage.pack.") for k in snap)
    finally:
        conn.close()
