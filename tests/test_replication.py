"""PacificA replication tests: log replay, 2PC, failover, kill-and-recover.

The reference validates multi-node fault tolerance with a kill test
(src/test/kill_test: data_verifier writes self-checking rows while
killer_handler random-kills nodes; SURVEY §4.3). Here the same loop runs
against the in-process ReplicaGroup: every acknowledged write must survive
arbitrary kills/restarts.
"""

import threading
import time

import numpy as np
import pytest

from pegasus_tpu.base import key_schema
from pegasus_tpu.engine.server_impl import RPC_MULTI_PUT, RPC_PUT, RPC_REMOVE
from pegasus_tpu.replication import MutationLog, LogMutation, ReplicaGroup, ReplicaError
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.messages import Status
from pegasus_tpu.runtime import fail_points as fp


def K(i):
    return key_schema.generate_key(b"h%d" % (i % 17), b"s%05d" % i)


def put_req(i, gen=0):
    return msg.UpdateRequest(K(i), b"val%d.%d" % (i, gen), 0)


# ------------------------------------------------------------- mutation log

def test_mutation_log_roundtrip_and_torn_tail(tmp_path):
    log = MutationLog(str(tmp_path / "plog"))
    for d in range(1, 21):
        log.append(LogMutation(decree=d, ballot=1, codes=["RPC_RRDB_RRDB_PUT"],
                               bodies=[b"body%d" % d]))
    got = list(log.replay(5))
    assert [m.decree for m in got] == list(range(6, 21))
    assert got[0].bodies == [b"body6"]
    log.close()
    # torn tail: append garbage; replay must stop cleanly at the tear
    seg = sorted((tmp_path / "plog").glob("log.*"))[0]
    with open(seg, "ab") as f:
        f.write(b"\x99" * 7)
    log2 = MutationLog(str(tmp_path / "plog"))
    assert [m.decree for m in log2.replay(0)] == list(range(1, 21))
    log2.close()


def test_mutation_log_gc_keeps_undurable(tmp_path):
    log = MutationLog(str(tmp_path / "plog"), segment_bytes=256)
    for d in range(1, 40):
        log.append(LogMutation(decree=d, codes=["c"], bodies=[b"x" * 64]))
    assert len(log._segments) > 2
    log.gc(durable_decree=20)
    remaining = [m.decree for m in log.replay(0)]
    # everything after the durable point must survive
    assert set(range(21, 40)) <= set(remaining)
    log.close()


# ---------------------------------------------------------------- 2PC core

@pytest.fixture
def group(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    yield g
    g.close()


def test_write_replicates_to_quorum(group):
    r = group.write(RPC_PUT, put_req(1))
    assert r.error == Status.OK
    # all three replicas hold the mutation in their logs
    for rep in group.alive.values():
        assert rep.last_prepared >= 1
    # primary applied it
    assert group.read(K(1)).error == Status.OK


def test_write_path_exports_replication_counters(group):
    """The PacificA write path is no longer counter-blind: a committed
    write populates replica.prepare/commit latency percentiles, the
    plog.append.* counters and the per-partition inflight/backlog
    gauges."""
    from pegasus_tpu.runtime.perf_counters import counters

    for i in range(5):
        group.write(RPC_PUT, put_req(100 + i))
    snap = counters.snapshot(prefix="replica.")
    prep = snap["replica.prepare_latency_us"]
    commit = snap["replica.commit_latency_us"]
    # percentile counters export the full quantile dict with real samples
    assert set(prep) == {"p50", "p90", "p95", "p99", "p999"}
    assert prep["p99"] > 0 and commit["p99"] > 0
    # per-partition pressure gauges exist and drained after commit
    backlog = {k: v for k, v in snap.items() if k.endswith(".backlog")}
    assert backlog and all(v == 0 for v in backlog.values())
    assert any(k.endswith(".inflight") for k in snap)
    plog = counters.snapshot(prefix="plog.append.")
    assert plog["plog.append.count"] > 0
    assert plog["plog.append.bytes"] > 0
    assert plog["plog.append.duration_us"]["p99"] > 0


def test_secondary_commit_lags_until_next_prepare(group):
    group.write(RPC_PUT, put_req(1))
    group.write(RPC_PUT, put_req(2))
    prim = group.primary_replica()
    for name, rep in group.alive.items():
        if name != prim.name:
            # committed-decree piggyback: secondary has applied decree 1
            assert rep.last_committed >= 1


def test_primary_failover_preserves_committed(group):
    for i in range(10):
        group.write(RPC_PUT, put_req(i))
    old_primary = group.primary
    group.kill(old_primary)
    assert group.primary != old_primary
    for i in range(10):
        resp = group.read(K(i))
        assert resp.error == Status.OK, f"lost write {i} after failover"
    # group still writable with quorum 2/2
    group.write(RPC_PUT, put_req(99))
    assert group.read(K(99)).error == Status.OK


def test_duplicate_committed_prepares_not_staged(group):
    """Prepares at decrees <= last_committed (normal during catch-up
    overlap) must be dropped, not staged: _apply_up_to only pops decrees
    above last_committed, so staged duplicates would leak forever
    (ADVICE r2 low)."""
    for i in range(5):
        group.write(RPC_PUT, put_req(i))
    prim = group.primary_replica()
    sec = next(r for n, r in group.alive.items() if n != prim.name)
    # force-commit everything on the secondary, then re-deliver old decrees
    sec.on_prepare(prim.ballot,
                   LogMutation(decree=sec.last_prepared, ballot=prim.ballot,
                               codes=["RPC_RRDB_RRDB_PUT"], bodies=[b"x"]),
                   sec.last_prepared)
    assert sec.last_committed == sec.last_prepared
    before = len(sec._uncommitted)
    for d in range(1, sec.last_committed + 1):
        sec.on_prepare(prim.ballot,
                       LogMutation(decree=d, ballot=prim.ballot,
                                   codes=["RPC_RRDB_RRDB_PUT"], bodies=[b"x"]),
                       sec.last_committed)
    assert len(sec._uncommitted) == before


def test_quorum_loss_rejects_writes(group):
    names = list(group.alive)
    group.kill(names[0])
    group.kill(names[1])
    with pytest.raises(ReplicaError):
        group.write(RPC_PUT, put_req(1))


def test_restart_rejoins_as_learner(group):
    for i in range(20):
        group.write(RPC_PUT, put_req(i))
    victim = [n for n in group.alive if n != group.primary][0]
    group.kill(victim)
    for i in range(20, 40):
        group.write(RPC_PUT, put_req(i))
    rep = group.restart(victim)
    assert rep.last_committed >= 39 or rep.last_prepared >= 39
    # learner caught up: kill the old primary, learner may win election
    group.kill(group.primary)
    for i in range(40):
        assert group.read(K(i)).error == Status.OK


def test_full_group_crash_recovers_all_committed(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    for i in range(25):
        g.write(RPC_PUT, put_req(i))
    # simulate whole-cluster power loss: no flush, no close
    names = list(g.alive)
    for n in names:
        g.alive[n].plog.close()
    g.alive.clear()
    g2 = ReplicaGroup(str(tmp_path), n=3)
    for i in range(25):
        assert g2.read(K(i)).error == Status.OK, f"lost committed write {i}"
    g2.close()


def test_kill_loop_no_committed_write_lost(tmp_path):
    """The kill-test proper: randomized kills/restarts under load."""
    rng = np.random.default_rng(7)
    g = ReplicaGroup(str(tmp_path), n=3)
    acked = {}
    i = 0
    for step in range(12):
        # burst of writes
        for _ in range(15):
            gen = int(rng.integers(0, 100))
            try:
                r = g.write(RPC_PUT, put_req(i, gen))
                if r.error == Status.OK:
                    acked[i] = gen
            except ReplicaError:
                pass
            i += 1
        # random chaos
        action = rng.integers(0, 3)
        live = list(g.alive)
        if action == 0 and len(live) > 2:
            g.kill(live[int(rng.integers(0, len(live)))])
        elif action == 1:
            dead = [n for n in g.names if n not in g.alive]
            if dead:
                g.restart(dead[int(rng.integers(0, len(dead)))])
        elif action == 2 and len(live) > 2:
            # kill + immediate restart (fast bounce)
            victim = live[int(rng.integers(0, len(live)))]
            g.kill(victim)
            g.restart(victim)
    # bring everyone back and verify every acknowledged write
    for n in g.names:
        if n not in g.alive:
            g.restart(n)
    for i, gen in acked.items():
        resp = g.read(K(i))
        assert resp.error == Status.OK, f"acked write {i} lost"
    g.close()


# ------------------------------------------- group commit / decree windows

def test_concurrent_writers_form_plog_groups(tmp_path):
    """Acceptance: >= 4 client threads on ONE partition -> decree windows
    form, so the plog appends-per-flush ratio exceeds 1 (one group flush
    covers a whole prepare window) while every write still commits."""
    g = ReplicaGroup(str(tmp_path), n=3)
    n_threads, per = 4, 25
    errs = []

    def w(tid):
        for i in range(per):
            try:
                g.write(RPC_PUT, put_req(tid * 1000 + i))
            except ReplicaError as e:
                errs.append(e)

    threads = [threading.Thread(target=w, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    prim = g.primary_replica()
    # one decree per mutation: the window layer must not coalesce decrees
    assert prim.last_committed == n_threads * per
    assert prim.plog.append_count == n_threads * per
    assert prim.plog.flush_count < prim.plog.append_count, \
        "no plog groups formed under 4 concurrent writers"
    # every replica holds every decree
    for rep in g.alive.values():
        assert rep.last_prepared == n_threads * per
    g.close()


def test_single_writer_groups_of_one(tmp_path):
    """A solo low-QPS writer must see group size 1 — the group-commit
    machinery never lingers (and so never adds latency) without
    concurrency."""
    g = ReplicaGroup(str(tmp_path), n=3)
    for i in range(20):
        g.write(RPC_PUT, put_req(i))
    prim = g.primary_replica()
    assert prim.plog.append_count == 20
    assert prim.plog.flush_count == 20  # every group was exactly one append
    g.close()


def test_window_gap_triggers_catch_up(group):
    """A secondary that missed windows while unreachable rejects the next
    window with `gap`; the primary streams the backlog as chunked windows
    and the peer ends fully caught up (ack = highest contiguous decree)."""
    for i in range(3):
        group.write(RPC_PUT, put_req(i))
    prim = group.primary_replica()
    sec_name = next(n for n in group.alive if n != group.primary)
    sec = group.alive.pop(sec_name)  # unreachable (not killed: no election)
    for i in range(3, 6):
        group.write(RPC_PUT, put_req(i))
    group.alive[sec_name] = sec      # back, with a decree gap
    group.write(RPC_PUT, put_req(6))
    assert sec.last_prepared == prim.last_prepared
    assert sec.last_committed >= 6  # committed point piggybacked


def test_batched_vs_serial_byte_identical(tmp_path, monkeypatch):
    """Equivalence acceptance: the same client trace through the
    decree-pipelined path (concurrent writers, mixed put/remove/multi_put,
    a secondary killed and re-seeded mid-stream) and through the serial
    path (single-threaded: every window is one decree) produces
    byte-identical plog files and identical engine state."""
    import pegasus_tpu.replication.replica as rp
    from pegasus_tpu.engine.replica_service import WRITE_CODES
    from pegasus_tpu.rpc import codec

    class _FrozenTime:
        """time.time() frozen so LogMutation timestamps are reproducible
        across the two runs; everything else passes through."""

        def __init__(self, real):
            self._real = real

        def time(self):
            return 1.7e9

        def __getattr__(self, name):
            return getattr(self._real, name)

    monkeypatch.setattr(rp, "time", _FrozenTime(time))

    def multi_put_req(j):
        return msg.MultiPutRequest(
            hash_key=b"mh%d" % (j % 7),
            kvs=[msg.KeyValue(b"s%d" % k, b"mv%d.%d" % (j, k))
                 for k in range(3)],
            expire_ts_seconds=0)

    # ---- run A: batched (4 concurrent writers, kill+re-seed mid-stream)
    ga = ReplicaGroup(str(tmp_path / "a"), n=3)
    victim = next(n for n in ga.alive if n != ga.primary)

    def writer(tid):
        for i in range(18):
            j = tid * 100 + i
            kind = j % 5
            if kind < 3:
                ga.write(RPC_PUT, put_req(j))
            elif kind == 3:
                ga.write(RPC_REMOVE, msg.KeyRequest(K(j)))
            else:
                ga.write(RPC_MULTI_PUT, multi_put_req(j))

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    ga.kill(victim)          # mid-stream secondary failure
    time.sleep(0.05)
    ga.restart(victim)       # learner re-seed while traffic continues
    for t in threads:
        t.join()
    prim_a = ga.primary_replica()
    trace = sorted(prim_a.plog.replay(0), key=lambda m: m.decree)
    assert len(trace) == 4 * 18
    keys = _trace_keys(trace)
    # reads, not memtable internals: the mid-stream learner re-seed
    # checkpoints the primary, so A and B legitimately differ in how much
    # state already flushed to L0 — the visible contents must not
    state_a = {k: _read(prim_a, k) for k in keys}
    committed_a = prim_a.last_committed
    plog_a = _plog_bytes(prim_a.plog.dir)

    # ---- run B: serial (single thread => every window is one decree)
    gb = ReplicaGroup(str(tmp_path / "b"), n=3)
    for idx, m in enumerate(trace):
        if idx == len(trace) // 2:
            gb.kill(victim)
            gb.restart(victim)
        (code,) = m.codes
        req = codec.decode(WRITE_CODES[code][0], m.bodies[0])
        gb.write(code, req)
    prim_b = gb.primary_replica()
    assert prim_b.last_committed == committed_a
    assert {k: _read(prim_b, k) for k in keys} == state_a
    assert _plog_bytes(prim_b.plog.dir) == plog_a
    ga.close()
    gb.close()


def _plog_bytes(plog_dir):
    import os

    out = {}
    for name in sorted(os.listdir(plog_dir)):
        if name.startswith("log."):
            with open(os.path.join(plog_dir, name), "rb") as f:
                out[name] = f.read()
    return out


def _trace_keys(trace) -> set:
    """Every stored key a replayed client trace touches."""
    from pegasus_tpu.engine.replica_service import WRITE_CODES
    from pegasus_tpu.rpc import codec

    keys = set()
    for m in trace:
        for code, body in zip(m.codes, m.bodies):
            req = codec.decode(WRITE_CODES[code][0], body)
            if code == RPC_MULTI_PUT:
                keys.update(key_schema.generate_key(req.hash_key, kv.key)
                            for kv in req.kvs)
            else:
                keys.add(req.key)
    return keys


def _read(rep, key):
    resp = rep.server.on_get(key)
    return (resp.error, bytes(resp.value))


# --------------------------------------------- group-commit chaos (plog.group)

def test_plog_group_raise_never_acks_lost_writes(tmp_path):
    """Chaos: `plog.group` armed with raise() fails every group BEFORE the
    buffered write. No failed write may be acked, no acked write may be
    lost after a full-group power loss, and the log must heal once the
    fault clears."""
    fp.setup()
    try:
        g = ReplicaGroup(str(tmp_path), n=3)
        g.write(RPC_PUT, put_req(0))
        fp.cfg("plog.group", "raise(chaos)")
        for i in range(1, 6):
            with pytest.raises(ReplicaError):
                g.write(RPC_PUT, put_req(i))
        fp.cfg("plog.group", "off()")
        g.write(RPC_PUT, put_req(9))
        # whole-cluster power loss: no flush, no close
        for n in list(g.alive):
            g.alive[n].plog.close()
        g.alive.clear()
        g2 = ReplicaGroup(str(tmp_path), n=3)
        assert g2.read(K(0)).error == Status.OK
        assert g2.read(K(9)).error == Status.OK
        for i in range(1, 6):
            assert g2.read(K(i)).error == Status.NOT_FOUND, \
                f"write {i} failed its ack but appeared after replay"
        g2.close()
    finally:
        fp.teardown()


def test_plog_wedged_group_writer_degrades_not_hangs(tmp_path):
    """Chaos: a group leader wedged between claim and flush (sleep verb)
    must NOT hang the partition — appends it never claimed steal
    themselves back after the stall bound and land per-append; the wedged
    group itself still lands (and only then acks)."""
    fp.setup()
    try:
        log = MutationLog(str(tmp_path / "plog"))
        log._stall_s = 0.2
        fp.cfg("plog.group", "1*sleep(2500)")
        errs = []

        def w(d):
            try:
                log.append(LogMutation(decree=d, codes=["c"], bodies=[b"x"]))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t_wedge = threading.Thread(target=w, args=(1,))
        t_wedge.start()
        time.sleep(0.3)  # the leader claimed decree 1 and is now wedged
        others = [threading.Thread(target=w, args=(d,)) for d in range(2, 6)]
        t0 = time.monotonic()
        for t in others:
            t.start()
        for t in others:
            t.join(timeout=10)
            assert not t.is_alive(), "append hung behind the wedged leader"
        assert time.monotonic() - t0 < 2.0, \
            "degraded appends waited for the wedged group writer"
        t_wedge.join(timeout=10)
        assert not t_wedge.is_alive()
        assert not errs
        assert sorted(m.decree for m in log.replay(0)) == [1, 2, 3, 4, 5]
        log.close()
    finally:
        fp.teardown()


def test_remove_and_reopen_replays_tombstone(group):
    group.write(RPC_PUT, put_req(5))
    group.write(RPC_REMOVE, msg.KeyRequest(K(5)))
    assert group.read(K(5)).error == Status.NOT_FOUND
    prim = group.primary
    group.kill(prim)
    assert group.read(K(5)).error == Status.NOT_FOUND


def test_log_gc_after_flush(group):
    for i in range(30):
        group.write(RPC_PUT, put_req(i))
    prim = group.primary_replica()
    prim.gc_log(flush=True)
    assert prim.server.engine.last_durable_decree() >= 30
    # after gc the log still replays anything undurable (nothing here)
    for i in range(30):
        assert group.read(K(i)).error == Status.OK
