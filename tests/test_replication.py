"""PacificA replication tests: log replay, 2PC, failover, kill-and-recover.

The reference validates multi-node fault tolerance with a kill test
(src/test/kill_test: data_verifier writes self-checking rows while
killer_handler random-kills nodes; SURVEY §4.3). Here the same loop runs
against the in-process ReplicaGroup: every acknowledged write must survive
arbitrary kills/restarts.
"""

import numpy as np
import pytest

from pegasus_tpu.base import key_schema
from pegasus_tpu.engine.server_impl import RPC_PUT, RPC_REMOVE
from pegasus_tpu.replication import MutationLog, LogMutation, ReplicaGroup, ReplicaError
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.messages import Status


def K(i):
    return key_schema.generate_key(b"h%d" % (i % 17), b"s%05d" % i)


def put_req(i, gen=0):
    return msg.UpdateRequest(K(i), b"val%d.%d" % (i, gen), 0)


# ------------------------------------------------------------- mutation log

def test_mutation_log_roundtrip_and_torn_tail(tmp_path):
    log = MutationLog(str(tmp_path / "plog"))
    for d in range(1, 21):
        log.append(LogMutation(decree=d, ballot=1, codes=["RPC_RRDB_RRDB_PUT"],
                               bodies=[b"body%d" % d]))
    got = list(log.replay(5))
    assert [m.decree for m in got] == list(range(6, 21))
    assert got[0].bodies == [b"body6"]
    log.close()
    # torn tail: append garbage; replay must stop cleanly at the tear
    seg = sorted((tmp_path / "plog").glob("log.*"))[0]
    with open(seg, "ab") as f:
        f.write(b"\x99" * 7)
    log2 = MutationLog(str(tmp_path / "plog"))
    assert [m.decree for m in log2.replay(0)] == list(range(1, 21))
    log2.close()


def test_mutation_log_gc_keeps_undurable(tmp_path):
    log = MutationLog(str(tmp_path / "plog"), segment_bytes=256)
    for d in range(1, 40):
        log.append(LogMutation(decree=d, codes=["c"], bodies=[b"x" * 64]))
    assert len(log._segments) > 2
    log.gc(durable_decree=20)
    remaining = [m.decree for m in log.replay(0)]
    # everything after the durable point must survive
    assert set(range(21, 40)) <= set(remaining)
    log.close()


# ---------------------------------------------------------------- 2PC core

@pytest.fixture
def group(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    yield g
    g.close()


def test_write_replicates_to_quorum(group):
    r = group.write(RPC_PUT, put_req(1))
    assert r.error == Status.OK
    # all three replicas hold the mutation in their logs
    for rep in group.alive.values():
        assert rep.last_prepared >= 1
    # primary applied it
    assert group.read(K(1)).error == Status.OK


def test_write_path_exports_replication_counters(group):
    """The PacificA write path is no longer counter-blind: a committed
    write populates replica.prepare/commit latency percentiles, the
    plog.append.* counters and the per-partition inflight/backlog
    gauges."""
    from pegasus_tpu.runtime.perf_counters import counters

    for i in range(5):
        group.write(RPC_PUT, put_req(100 + i))
    snap = counters.snapshot(prefix="replica.")
    prep = snap["replica.prepare_latency_us"]
    commit = snap["replica.commit_latency_us"]
    # percentile counters export the full quantile dict with real samples
    assert set(prep) == {"p50", "p90", "p95", "p99", "p999"}
    assert prep["p99"] > 0 and commit["p99"] > 0
    # per-partition pressure gauges exist and drained after commit
    backlog = {k: v for k, v in snap.items() if k.endswith(".backlog")}
    assert backlog and all(v == 0 for v in backlog.values())
    assert any(k.endswith(".inflight") for k in snap)
    plog = counters.snapshot(prefix="plog.append.")
    assert plog["plog.append.count"] > 0
    assert plog["plog.append.bytes"] > 0
    assert plog["plog.append.duration_us"]["p99"] > 0


def test_secondary_commit_lags_until_next_prepare(group):
    group.write(RPC_PUT, put_req(1))
    group.write(RPC_PUT, put_req(2))
    prim = group.primary_replica()
    for name, rep in group.alive.items():
        if name != prim.name:
            # committed-decree piggyback: secondary has applied decree 1
            assert rep.last_committed >= 1


def test_primary_failover_preserves_committed(group):
    for i in range(10):
        group.write(RPC_PUT, put_req(i))
    old_primary = group.primary
    group.kill(old_primary)
    assert group.primary != old_primary
    for i in range(10):
        resp = group.read(K(i))
        assert resp.error == Status.OK, f"lost write {i} after failover"
    # group still writable with quorum 2/2
    group.write(RPC_PUT, put_req(99))
    assert group.read(K(99)).error == Status.OK


def test_duplicate_committed_prepares_not_staged(group):
    """Prepares at decrees <= last_committed (normal during catch-up
    overlap) must be dropped, not staged: _apply_up_to only pops decrees
    above last_committed, so staged duplicates would leak forever
    (ADVICE r2 low)."""
    for i in range(5):
        group.write(RPC_PUT, put_req(i))
    prim = group.primary_replica()
    sec = next(r for n, r in group.alive.items() if n != prim.name)
    # force-commit everything on the secondary, then re-deliver old decrees
    sec.on_prepare(prim.ballot,
                   LogMutation(decree=sec.last_prepared, ballot=prim.ballot,
                               codes=["RPC_RRDB_RRDB_PUT"], bodies=[b"x"]),
                   sec.last_prepared)
    assert sec.last_committed == sec.last_prepared
    before = len(sec._uncommitted)
    for d in range(1, sec.last_committed + 1):
        sec.on_prepare(prim.ballot,
                       LogMutation(decree=d, ballot=prim.ballot,
                                   codes=["RPC_RRDB_RRDB_PUT"], bodies=[b"x"]),
                       sec.last_committed)
    assert len(sec._uncommitted) == before


def test_quorum_loss_rejects_writes(group):
    names = list(group.alive)
    group.kill(names[0])
    group.kill(names[1])
    with pytest.raises(ReplicaError):
        group.write(RPC_PUT, put_req(1))


def test_restart_rejoins_as_learner(group):
    for i in range(20):
        group.write(RPC_PUT, put_req(i))
    victim = [n for n in group.alive if n != group.primary][0]
    group.kill(victim)
    for i in range(20, 40):
        group.write(RPC_PUT, put_req(i))
    rep = group.restart(victim)
    assert rep.last_committed >= 39 or rep.last_prepared >= 39
    # learner caught up: kill the old primary, learner may win election
    group.kill(group.primary)
    for i in range(40):
        assert group.read(K(i)).error == Status.OK


def test_full_group_crash_recovers_all_committed(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    for i in range(25):
        g.write(RPC_PUT, put_req(i))
    # simulate whole-cluster power loss: no flush, no close
    names = list(g.alive)
    for n in names:
        g.alive[n].plog.close()
    g.alive.clear()
    g2 = ReplicaGroup(str(tmp_path), n=3)
    for i in range(25):
        assert g2.read(K(i)).error == Status.OK, f"lost committed write {i}"
    g2.close()


def test_kill_loop_no_committed_write_lost(tmp_path):
    """The kill-test proper: randomized kills/restarts under load."""
    rng = np.random.default_rng(7)
    g = ReplicaGroup(str(tmp_path), n=3)
    acked = {}
    i = 0
    for step in range(12):
        # burst of writes
        for _ in range(15):
            gen = int(rng.integers(0, 100))
            try:
                r = g.write(RPC_PUT, put_req(i, gen))
                if r.error == Status.OK:
                    acked[i] = gen
            except ReplicaError:
                pass
            i += 1
        # random chaos
        action = rng.integers(0, 3)
        live = list(g.alive)
        if action == 0 and len(live) > 2:
            g.kill(live[int(rng.integers(0, len(live)))])
        elif action == 1:
            dead = [n for n in g.names if n not in g.alive]
            if dead:
                g.restart(dead[int(rng.integers(0, len(dead)))])
        elif action == 2 and len(live) > 2:
            # kill + immediate restart (fast bounce)
            victim = live[int(rng.integers(0, len(live)))]
            g.kill(victim)
            g.restart(victim)
    # bring everyone back and verify every acknowledged write
    for n in g.names:
        if n not in g.alive:
            g.restart(n)
    for i, gen in acked.items():
        resp = g.read(K(i))
        assert resp.error == Status.OK, f"acked write {i} lost"
    g.close()


def test_remove_and_reopen_replays_tombstone(group):
    group.write(RPC_PUT, put_req(5))
    group.write(RPC_REMOVE, msg.KeyRequest(K(5)))
    assert group.read(K(5)).error == Status.NOT_FOUND
    prim = group.primary
    group.kill(prim)
    assert group.read(K(5)).error == Status.NOT_FOUND


def test_log_gc_after_flush(group):
    for i in range(30):
        group.write(RPC_PUT, put_req(i))
    prim = group.primary_replica()
    prim.gc_log(flush=True)
    assert prim.server.engine.last_durable_decree() >= 30
    # after gc the log still replays anything undurable (nothing here)
    for i in range(30):
        assert group.read(K(i)).error == Status.OK
