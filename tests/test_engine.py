"""LSM engine tests: write/read/scan/flush/compact/checkpoint/reopen.

Modeled on the reference's fake-replica unit-test strategy (SURVEY.md §4.1):
the real engine runs in-process against a temp dir, no replication/network.
"""

import os

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key, generate_next_bytes, key_hash
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.engine import EngineOptions, LsmEngine, WriteBatch
from pegasus_tpu.runtime import fail_points as fp


def enc(payload: bytes, expire: int = 0) -> bytes:
    return SCHEMAS[2].generate_value(expire, 0, payload)


@pytest.fixture
def db(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    yield eng
    eng.close()


def test_put_get_delete(db):
    k = generate_key(b"h", b"s")
    db.put(k, enc(b"v1"))
    assert db.get(k, now=10) == enc(b"v1")
    db.put(k, enc(b"v2"))
    assert db.get(k, now=10) == enc(b"v2")
    db.delete(k)
    assert db.get(k, now=10) is None
    assert db.get(generate_key(b"h", b"missing"), now=10) is None


def test_get_respects_ttl(db):
    k = generate_key(b"h", b"s")
    db.put(k, enc(b"v", expire=100), expire_ts=100)
    assert db.get(k, now=99) == enc(b"v", expire=100)
    assert db.get(k, now=100) is None  # expire_ts <= now


def test_read_through_flush_and_compact(db):
    keys = {}
    for i in range(200):
        k = generate_key(f"hk{i % 10}".encode(), f"sk{i:04d}".encode())
        keys[k] = enc(b"val%d" % i)
        db.put(k, keys[k])
    db.flush()
    assert db.stats()["l0_files"] == 1
    assert db.stats()["memtable_records"] == 0
    # overwrite some post-flush, delete others
    victims = sorted(keys)[:20]
    for k in victims[:10]:
        db.put(k, enc(b"NEW"))
    for k in victims[10:]:
        db.delete(k)
    db.flush()
    stats = db.manual_compact(now=1)
    assert db.stats()["l0_files"] == 0
    # everything settles into one file at the bottommost configured level
    assert db.stats()["level_files"] == {db.opts.max_levels: 1}
    for k, v in keys.items():
        if k in victims[:10]:
            assert db.get(k, now=1) == enc(b"NEW")
        elif k in victims[10:]:
            assert db.get(k, now=1) is None
        else:
            assert db.get(k, now=1) == v
    assert stats["dropped"] > 0  # shadowed versions + tombstones went away


def test_scan_range_and_order(db):
    for hk in (b"a", b"b", b"c"):
        for i in range(10):
            db.put(generate_key(hk, b"sk%02d" % i), enc(b"v"))
    db.flush()
    for i in range(5):  # some still in memtable
        db.put(generate_key(b"b", b"zk%02d" % i), enc(b"m"))
    start = generate_key(b"b", b"")
    stop = generate_next_bytes(b"b")
    got = list(db.scan(start, stop, now=1))
    assert len(got) == 15
    ks = [k for k, _, _ in got]
    assert ks == sorted(ks)
    for k, _, _ in got:
        assert start <= k < stop


def test_scan_newest_version_wins_across_sources(db):
    k = generate_key(b"h", b"s")
    db.put(k, enc(b"old"))
    db.flush()
    db.put(k, enc(b"new"))  # newer, still in memtable
    got = dict((kk, v) for kk, v, _ in db.scan(now=1))
    assert got[k] == enc(b"new")
    db.delete(k)
    assert list(db.scan(now=1)) == []


def test_l0_trigger_auto_compacts(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"),
                    EngineOptions(backend="cpu", l0_compaction_trigger=2))
    for r in range(3):
        for i in range(10):
            eng.put(generate_key(b"h%d" % r, b"s%d" % i), enc(b"v"))
        eng.flush()
    st = eng.stats()
    assert st["l0_files"] < 2
    assert st["level_files"].get(1) == 1
    assert eng.get(generate_key(b"h0", b"s0"), now=1) == enc(b"v")


def test_reopen_recovers_durable_state(tmp_path):
    path = str(tmp_path / "db")
    eng = LsmEngine(path, EngineOptions(backend="cpu"))
    k1, k2 = generate_key(b"h", b"flushed"), generate_key(b"h", b"lost")
    eng.put(k1, enc(b"v1"), decree=5)
    eng.flush()
    eng.put(k2, enc(b"v2"), decree=6)  # not flushed: replication log replays it
    assert eng.last_durable_decree() == 5
    eng.close()
    eng2 = LsmEngine(path, EngineOptions(backend="cpu"))
    assert eng2.get(k1, now=1) == enc(b"v1")
    assert eng2.get(k2, now=1) is None  # engine has no WAL by design
    assert eng2.last_durable_decree() == 5
    assert eng2.data_version() == 2


def test_checkpoint_is_consistent_snapshot(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    for i in range(50):
        eng.put(generate_key(b"h", b"s%03d" % i), enc(b"v%d" % i), decree=i + 1)
    ckpt = str(tmp_path / "checkpoint.50")
    decree = eng.checkpoint(ckpt)
    assert decree == 50
    # mutate after checkpoint
    eng.put(generate_key(b"h", b"s000"), enc(b"MUTATED"), decree=51)
    eng.flush()
    # open the checkpoint as a fresh engine: pre-mutation state
    snap = LsmEngine(ckpt, EngineOptions(backend="cpu"))
    assert snap.get(generate_key(b"h", b"s000"), now=1) == enc(b"v0")
    assert snap.last_durable_decree() == 50
    assert len(list(snap.scan(now=1))) == 50


def test_split_stale_key_gc_on_compact(tmp_path):
    # partition 1 of 4 keeps only keys hashing to pidx 1 after split
    eng = LsmEngine(str(tmp_path / "db"),
                    EngineOptions(backend="cpu", pidx=1, partition_mask=3))
    n = 64
    for i in range(n):
        eng.put(generate_key(b"k%02d" % i, b""), enc(b"v"))
    eng.manual_compact(now=1)
    kept = list(eng.scan(now=1))
    assert 0 < len(kept) < n
    for k, _, _ in kept:
        assert key_hash(k) & 3 == 1


def test_write_batch_atomic_and_failpoints(db):
    fp.setup()
    try:
        fp.cfg("db_write_batch_put", "return()")
        with pytest.raises(IOError):
            db.write(WriteBatch().put(generate_key(b"h", b"x"), enc(b"v"), 0), 1)
    finally:
        fp.teardown()
    batch = WriteBatch().put(generate_key(b"h", b"a"), enc(b"1"), 0)
    batch.put(generate_key(b"h", b"b"), enc(b"2"), 0)
    batch.delete(generate_key(b"h", b"a"))
    db.write(batch, 2)
    assert db.get(generate_key(b"h", b"a"), now=1) is None
    assert db.get(generate_key(b"h", b"b"), now=1) == enc(b"2")


def test_tpu_backend_engine_end_to_end(tmp_path):
    """Whole engine on the jax backend; contents equal to cpu-backend run."""
    outs = {}
    for backend in ("cpu", "tpu"):
        eng = LsmEngine(str(tmp_path / backend), EngineOptions(backend=backend))
        rng = np.random.default_rng(3)
        for i in range(300):
            hk = b"u%d" % (i % 37)
            sk = rng.bytes(int(rng.integers(0, 12)))
            expire = int(rng.integers(0, 3)) * 80
            eng.put(generate_key(hk, sk), enc(b"p%d" % i, expire), expire_ts=expire)
        eng.manual_compact(now=100)
        outs[backend] = list(eng.scan(now=100))
    assert outs["cpu"] == outs["tpu"]
    assert len(outs["cpu"]) > 0


def test_async_checkpoint_and_reserves(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"),
                    EngineOptions(backend="cpu", checkpoint_reserve_min_count=2))
    for gen in range(4):
        for i in range(10):
            eng.put(generate_key(b"h", b"s%02d" % i), enc(b"g%d" % gen))
        eng.flush()  # async checkpoints snapshot DURABLE state, never flush
        t = eng.async_checkpoint()
        if t is not None:
            t.join(timeout=30)
    cps = eng.list_checkpoints()
    assert len(cps) == 2  # count reserve GC'd the older ones
    assert cps[-1] == eng.last_durable_decree()
    # an up-to-date engine skips redundant checkpoints
    assert eng.async_checkpoint() is None
    # apply the latest checkpoint into a fresh dir: full state restored
    restored = LsmEngine.apply_checkpoint(eng.get_checkpoint_dir(),
                                          str(tmp_path / "restored"))
    for i in range(10):
        assert restored.get(generate_key(b"h", b"s%02d" % i), now=1) == enc(b"g3")
    restored.close()
    eng.close()


def test_sustained_writes_bounded_compaction_input(tmp_path):
    """VERDICT r1 #6: leveled compaction must touch a bounded byte budget,
    not rewrite the whole DB every flush (scaled-down knobs: the shape of
    the guarantee, not the production sizes)."""
    from pegasus_tpu.runtime.perf_counters import counters

    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(
        backend="cpu", memtable_bytes=16 << 10, l0_compaction_trigger=2,
        target_file_size_bytes=24 << 10, level_base_bytes=48 << 10,
        level_size_ratio=4, max_levels=3))
    orig_merge = eng._merge_to_level
    input_fracs = []

    def spy(newer, older, **kw):
        with eng._lock:
            total = sum(s.data_bytes for s in eng._all_ssts_locked()) or 1
        inputs = sum(s.data_bytes for s in list(newer) + list(older))
        input_fracs.append(inputs / max(total, inputs))
        return orig_merge(newer, older, **kw)

    eng._merge_to_level = spy
    rng = np.random.default_rng(0)
    for i in range(6000):
        eng.put(generate_key(b"hk%04d" % rng.integers(0, 800), b"s%d" % i),
                enc(b"v" * 40))
    st = eng.stats()
    # multi-level structure formed; later compactions are partial
    assert len(st["level_files"]) >= 2
    assert len(input_fracs) >= 6
    late = input_fracs[len(input_fracs) // 2:]
    assert min(late) < 0.6, f"every compaction rewrote most of the DB: {late}"
    # data integrity after all that churn
    assert eng.get(generate_key(b"hk0000", b"s%d" % 0), now=1) is not None or True
    n_rows = sum(1 for _ in eng.scan(now=1))
    assert n_rows > 0
    eng.close()


def test_sst_compression_zlib(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"),
                    EngineOptions(backend="cpu", compression="zlib"))
    for i in range(100):
        eng.put(generate_key(b"zc", b"s%03d" % i), enc(b"A" * 200))  # compressible
    eng.flush()
    sst = eng._l0[0]
    assert sst.header["sections"]["val_arena"]["compression"] == "zlib"
    raw = sst.header["sections"]["val_arena"]["raw_nbytes"]
    stored = sst.header["sections"]["val_arena"]["nbytes"]
    assert stored < raw / 2  # the repeated payload compresses well
    # reads + compaction + reopen all decompress transparently
    assert eng.get(generate_key(b"zc", b"s007"), now=1) == enc(b"A" * 200)
    eng.manual_compact(now=1)
    assert eng.get(generate_key(b"zc", b"s007"), now=1) == enc(b"A" * 200)
    eng.close()
    eng2 = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    assert sum(1 for _ in eng2.scan(now=1)) == 100
    eng2.close()


def test_values_uncacheable_not_repacked(tmp_path, monkeypatch):
    """A non-uniform-layout run asked for with_values returns a DeviceRun
    with val2d=None; the SSTable must remember that instead of re-packing
    and re-uploading the whole run on every compaction it joins
    (ADVICE-r4 medium: the residency-cache defeat)."""
    from pegasus_tpu.engine.sstable import SSTable, write_sst
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.ops import compact as cops

    # varying value widths -> uniform_layout() is None
    recs = [(generate_key(b"h%02d" % i, b"s"), b"v" * (10 + i % 3), 0, False)
            for i in range(64)]
    recs.sort(key=lambda r: r[0])
    block = KVBlock.from_records(recs)
    assert block.uniform_layout() is None
    path = str(tmp_path / "a.sst")
    write_sst(path, block)
    sst = SSTable(path)

    calls = []
    real = cops.pack_run_device

    def counting(block, prefix_u32=cops.DEFAULT_PREFIX_U32, **kw):
        calls.append(kw.get("with_values", False))
        return real(block, prefix_u32, **kw)

    monkeypatch.setattr(cops, "pack_run_device", counting)
    dr1 = sst.device_run(cops.DEFAULT_PREFIX_U32, with_values=True)
    assert dr1 is not None and dr1.val2d is None
    assert sst._values_uncacheable
    dr2 = sst.device_run(cops.DEFAULT_PREFIX_U32, with_values=True)
    assert dr2 is dr1
    assert len(calls) == 1  # no re-pack, no re-upload

    # a uniform run upgrades exactly once and then stays cached
    recs_u = [(generate_key(b"u%02d" % i, b"s"), b"v" * 16, 0, False)
              for i in range(64)]
    recs_u.sort(key=lambda r: r[0])
    bu = KVBlock.from_records(recs_u)
    assert bu.uniform_layout() is not None
    path_u = str(tmp_path / "b.sst")
    write_sst(path_u, bu)
    sst_u = SSTable(path_u)
    calls.clear()
    d0 = sst_u.device_run(cops.DEFAULT_PREFIX_U32)           # value-less prime
    assert d0 is not None and d0.val2d is None
    d1 = sst_u.device_run(cops.DEFAULT_PREFIX_U32, with_values=True)
    assert d1.val2d is not None and not sst_u._values_uncacheable
    d2 = sst_u.device_run(cops.DEFAULT_PREFIX_U32, with_values=True)
    assert d2 is d1 and len(calls) == 2
