"""Duplication DDL, backup policies, and disaster-recovery admin.

VERDICT-r2 items 6 (dup lifecycle + backup policies + shell families):
the reference surfaces are src/shell/commands/duplication.cpp:32-260
(add/query/start/pause/remove/set_dup_fail_mode), cold_backup.cpp's policy
schedule + retention, and recovery.cpp (`recover`, `ddd_diagnose`). Here
each is driven end-to-end over real sockets through the Shell command
layer, including a full dup setup between two onebox clusters.
"""

import io
import time

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.meta import MetaServer
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.rpc.transport import RpcServer
from pegasus_tpu.shell.main import Shell
from tests.test_cluster import Cluster, make_client


def wait_until(fn, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def shell_run(cluster, line: str) -> str:
    out = io.StringIO()
    sh = Shell([cluster.meta_addr], out=out)
    sh.run_line(line)
    return out.getvalue()


# ------------------------------------------------------------- duplication


@pytest.fixture
def two_clusters(tmp_path):
    b = Cluster(tmp_path / "west", n_nodes=3, cluster_id=2)
    a = Cluster(tmp_path / "east", n_nodes=3, cluster_id=1,
                remote_clusters={"west": [b.meta_addr]})
    try:
        yield a, b
    finally:
        a.stop()
        b.stop()


def test_duplication_lifecycle_between_clusters(two_clusters):
    a, b = two_clusters
    ca = make_client(a, app="dt", partitions=2)
    cb = make_client(b, app="dt", partitions=2)

    # --- add_dup via shell; entries queryable
    out = shell_run(a, "add_dup dt west")
    assert "succeed" in out and "dupid: 1" in out
    out = shell_run(a, "query_dup dt")
    assert "dupid=1" in out and "status=start" in out and "remote=west" in out
    # duplicate add rejected
    assert "already exists" in shell_run(a, "add_dup dt west")

    # --- writes on A ship to B (history + live)
    for i in range(10):
        ca.set(b"dk%d" % i, b"s", b"v%d" % i)
    assert wait_until(lambda: all(
        cb.get(b"dk%d" % i, b"s") == b"v%d" % i for i in range(10)))

    # --- pause: new writes are queued, not shipped
    assert "succeed" in shell_run(a, "pause_dup dt 1")
    time.sleep(0.3)  # let the pause reach the shippers
    for i in range(10, 15):
        ca.set(b"dk%d" % i, b"s", b"v%d" % i)
    time.sleep(1.0)
    assert all(cb.get(b"dk%d" % i, b"s") is None for i in range(10, 15))

    # --- start again: the retained backlog ships
    assert "succeed" in shell_run(a, "start_dup dt 1")
    assert wait_until(lambda: all(
        cb.get(b"dk%d" % i, b"s") == b"v%d" % i for i in range(10, 15)))

    # --- fail-mode propagates to the live shippers
    assert "succeed" in shell_run(a, "set_dup_fail_mode dt 1 skip")
    assert wait_until(lambda: any(
        d.fail_mode == "skip"
        for stub in a.nodes.values()
        for rep in stub._replicas.values()
        for d in rep.duplicators.values()))

    # --- remove: shippers torn down, writes stop flowing
    assert "succeed" in shell_run(a, "remove_dup dt 1")
    assert wait_until(lambda: all(
        not rep.duplicators for stub in a.nodes.values()
        for rep in stub._replicas.values()))
    ca.set(b"post_remove", b"s", b"x")
    time.sleep(0.8)
    assert cb.get(b"post_remove", b"s") is None
    assert "dupid" not in shell_run(a, "query_dup dt").replace("(none)", "")
    ca.close()
    cb.close()


def test_duplicator_bootstrap_via_block_ship(two_clusters, tmp_path):
    """ISSUE 13: a fresh remote cluster seeds by BLOCK SHIP — source
    checkpoints stream (same pin/manifest/chunk protocol learners use)
    into a bulk-load provider layout, the destination ingests them
    replicated — and the cross-cluster decree-anchored audit is still
    conclusive (and matching) after the bootstrap + a live dup leg."""
    from pegasus_tpu.collector.cluster_doctor import run_cross_cluster_audit
    from pegasus_tpu.replication.bootstrap import bootstrap_remote_cluster

    a, b = two_clusters
    ca = make_client(a, app="bs", partitions=2)
    cb = make_client(b, app="bs", partitions=2)
    for i in range(60):
        ca.set(b"bk%03d" % i, b"s", b"bv%d" % i)
    # durable SSTs on the source so the checkpoints carry the history
    for stub in a.nodes.values():
        for rep in list(stub._replicas.values()):
            rep.server.engine.flush()
    stats = bootstrap_remote_cluster(
        [a.meta_addr], [b.meta_addr], "bs",
        provider_root=str(tmp_path / "provider"))
    assert stats["partitions"] == 2
    assert stats["blocks"] > 0 and stats["bytes"] > 0
    assert stats["ingested_records"] == 60
    # the bootstrap alone (no duplication yet) delivered the history
    assert all(cb.get(b"bk%03d" % i, b"s") == b"bv%d" % i
               for i in range(60))
    # a re-run is delta/resume: the provider dir already holds the
    # blocks, so nothing re-ships
    stats2 = bootstrap_remote_cluster(
        [a.meta_addr], [b.meta_addr], "bs",
        provider_root=str(tmp_path / "provider"))
    assert stats2["blocks"] == 0 and stats2["resumed"] > 0
    # now the live leg: dup ships the post-bootstrap window
    assert "succeed" in shell_run(a, "add_dup bs west")
    for i in range(60, 80):
        ca.set(b"bk%03d" % i, b"s", b"bv%d" % i)
    assert wait_until(lambda: all(
        cb.get(b"bk%03d" % i, b"s") == b"bv%d" % i for i in range(60, 80)))
    x = run_cross_cluster_audit([a.meta_addr], [b.meta_addr], "bs")
    assert x["match"] is True, x
    assert x["src"]["records"] == x["dst"]["records"] > 0
    ca.close()
    cb.close()


def test_duplication_freeze_then_start(two_clusters):
    a, b = two_clusters
    ca = make_client(a, app="fz", partitions=1)
    cb = make_client(b, app="fz", partitions=1)
    out = shell_run(a, "add_dup fz west -f")
    assert "freeze: true" in out
    ca.set(b"h", b"s", b"frozen")
    time.sleep(0.8)
    assert cb.get(b"h", b"s") is None            # DS_INIT: not shipping
    assert "succeed" in shell_run(a, "start_dup fz 1")
    # catch_up replays the plog history written while frozen
    assert wait_until(lambda: cb.get(b"h", b"s") == b"frozen")
    ca.close()
    cb.close()


def test_duplication_survives_primary_failover(two_clusters):
    a, b = two_clusters
    ca = make_client(a, app="fo", partitions=1)
    cb = make_client(b, app="fo", partitions=1)
    shell_run(a, "add_dup fo west")
    for i in range(5):
        ca.set(b"pre%d" % i, b"s", b"v%d" % i)
    assert wait_until(lambda: cb.get(b"pre4", b"s") == b"v4")
    # beacons fold the primary's confirmed decree into the meta's dup entry;
    # the promoted primary will start its shipper at that floor
    app_id = ca.resolver.app_id
    assert wait_until(lambda: any(
        int(v) > 0 for e in a.meta._dups.get(app_id, [])
        for v in e.get("confirmed", {}).values()))
    victim = a.meta._parts[app_id][0].primary
    a.kill_node(victim)
    # the promoted primary rebuilds its shipper (catch_up from its plog)
    for i in range(5, 10):
        ca.set(b"pre%d" % i, b"s", b"v%d" % i)
    assert wait_until(lambda: all(
        cb.get(b"pre%d" % i, b"s") == b"v%d" % i for i in range(10)))
    ca.close()
    cb.close()


# ---------------------------------------------------------- backup policies


def test_backup_policy_schedule_and_retention(tmp_path):
    c = Cluster(tmp_path / "c")
    try:
        cl = make_client(c, app="bp", partitions=2)
        for i in range(20):
            cl.set(b"bk%d" % i, b"s", b"v%d" % i)
        root = str(tmp_path / "backups")
        out = shell_run(c, f"add_backup_policy daily {root} bp 100 2")
        assert "OK" in out
        assert "name=daily" in shell_run(c, "ls_backup_policy")
        # three due runs with an advancing pinned clock; retention = 2
        ran1 = c.meta.run_backup_policies(now=1000)
        ran2 = c.meta.run_backup_policies(now=1100)
        ran3 = c.meta.run_backup_policies(now=1200)
        assert all(bid for _, _, bid in ran1 + ran2 + ran3)
        # not due again until interval passes
        assert c.meta.run_backup_policies(now=1201) == []
        import os

        kept = sorted(os.listdir(os.path.join(root, "daily")))
        assert kept == ["1100000", "1200000"], kept
        # restore from the newest retained backup into a new table
        out = shell_run(c, f"restore_app {root}/daily 1200000 bp bp_restored")
        assert "succeed" in out
        cr = PegasusClient(MetaResolver([c.meta_addr], "bp_restored"))
        for i in range(20):
            assert cr.get(b"bk%d" % i, b"s") == b"v%d" % i
        cr.close()
        # disable stops the schedule
        assert "OK" in shell_run(c, "disable_backup_policy daily")
        assert c.meta.run_backup_policies(now=5000) == []
        # modify: interval + history + app set
        assert "OK" in shell_run(c, "modify_backup_policy daily -i 7 -c 5")
        pol = c.meta._policies["daily"]
        assert pol["interval_seconds"] == 7 and pol["history_count"] == 5
        cl.close()
    finally:
        c.stop()


def test_backup_policy_validation(tmp_path):
    c = Cluster(tmp_path / "c", n_nodes=1)
    try:
        out = shell_run(c, "add_backup_policy p1 /tmp/x nosuchapp 60")
        assert "no such app" in out
    finally:
        c.stop()


# ------------------------------------------------------- disaster recovery


def test_recover_rebuilds_meta_from_nodes(tmp_path):
    c = Cluster(tmp_path / "c")
    try:
        cl = make_client(c, app="rc", partitions=2)
        for i in range(30):
            cl.set(b"rk%d" % i, b"s", b"v%d" % i)
        cl.close()
        nodes = list(c.nodes)
        # a BRAND NEW meta with empty state (the disaster): knows nothing
        m2 = MetaServer(str(tmp_path / "meta2" / "state.json"))
        rpc2 = RpcServer().start()
        for code, fn in m2.rpc_handlers().items():
            rpc2.register(code, fn)
        addr2 = f"{rpc2.address[0]}:{rpc2.address[1]}"
        try:
            out = io.StringIO()
            sh = Shell([addr2], out=out)
            sh.run_line("recover " + " ".join(nodes))
            assert "rc" in out.getvalue()
            assert "rc" in m2._apps
            assert len(m2._parts[m2._apps["rc"].app_id]) == 2
            # the recovered table serves reads through the NEW meta
            cr = PegasusClient(MetaResolver([addr2], "rc"))
            for i in range(30):
                assert cr.get(b"rk%d" % i, b"s") == b"v%d" % i
            cr.close()
        finally:
            rpc2.stop()
    finally:
        c.stop()


def test_ddd_diagnose_finds_and_fixes(tmp_path):
    c = Cluster(tmp_path / "c")
    try:
        cl = make_client(c, app="dd", partitions=1)
        for i in range(10):
            cl.set(b"ddk%d" % i, b"s", b"v%d" % i)
        app_id = cl.resolver.app_id
        pc = c.meta._parts[app_id][0]
        members = [pc.primary] + list(pc.secondaries)
        # every member "dies" (lease-expired) -> partition left memberless;
        # the processes themselves keep running and keep beaconing, the
        # classic double-dead state after a rolling outage
        for m in members:
            c.meta.mark_node_dead(m)
        assert pc.primary == "" and pc.secondaries == []
        # beacons revive the nodes as FD-alive
        assert wait_until(lambda: len(c.meta._alive_nodes_locked()) == 3,
                          timeout=5)
        out = shell_run(c, "ddd_diagnose dd")
        assert "no alive member" in out and "candidate:" in out
        assert "(none; rerun with -f to fix)" in out
        out = shell_run(c, "ddd_diagnose dd -f")
        assert "promoted" in out
        assert pc.primary in members
        # a fresh client reads everything back
        cr = PegasusClient(MetaResolver([c.meta_addr], "dd"))
        for i in range(10):
            assert cr.get(b"ddk%d" % i, b"s") == b"v%d" % i
        cr.close()
        assert "no double-dead partitions" in shell_run(c, "ddd_diagnose dd")
        cl.close()
    finally:
        c.stop()


def test_drop_with_reserve_and_recall(tmp_path):
    """Reference drop -r + recall_app (table_management.cpp:680-736): a
    soft-dropped table disappears from routing but its data survives on
    disk; recall restores it (optionally renamed) until the hold expires."""
    c = Cluster(tmp_path / "c")
    try:
        cl = make_client(c, app="dr", partitions=2)
        app_id = cl.resolver.app_id
        for i in range(15):
            cl.set(b"drk%d" % i, b"s", b"v%d" % i)
        cl.close()
        assert "succeed" in shell_run(c, "drop dr -r 3600")
        # invisible to routing/DDL
        from pegasus_tpu.rpc.transport import RpcError

        with pytest.raises(RpcError):
            MetaResolver([c.meta_addr], "dr").app_id  # noqa: B018
        assert "dr" not in c.meta._apps and app_id in c.meta._dropped
        # name free for reuse while dropped; recall under a NEW name then
        out = shell_run(c, f"recall {app_id} dr2")
        assert "succeed" in out
        cr = PegasusClient(MetaResolver([c.meta_addr], "dr2"))
        for i in range(15):
            assert cr.get(b"drk%d" % i, b"s") == b"v%d" % i
        cr.close()
        # recall again fails (already recalled)
        assert "failed" in shell_run(c, f"recall {app_id}")
        # hold expiry purges recallability
        cl2 = make_client(c, app="dr3", partitions=1)
        cl2.set(b"x", b"s", b"y")
        cl2.close()
        aid3 = c.meta._apps["dr3"].app_id
        shell_run(c, "drop dr3 -r 5")
        assert c.meta.purge_expired_dropped(now=2**31) == [aid3]
        assert "failed" in shell_run(c, f"recall {aid3}")
        # plain drop stays immediate (no recall possible)
        cl3 = make_client(c, app="dr4", partitions=1)
        cl3.close()
        aid4 = c.meta._apps["dr4"].app_id
        shell_run(c, "drop dr4")
        assert aid4 not in c.meta._dropped
    finally:
        c.stop()


def test_admin_shell_utilities(tmp_path):
    """The round-3 shell sweep: version/timeout/hash/app_stat/app_disk/
    multi_get_sortkeys/range ops/clear_app_envs/clear_data/meta levels."""
    c = Cluster(tmp_path / "c")
    try:
        cl = make_client(c, app="ut", partitions=2)
        for i in range(12):
            cl.set(b"uh", b"sk%02d" % i, b"v%d" % i)
        cl.set(b"other", b"s", b"x")
        assert "pegasus-tpu" in shell_run(c, "version")
        out = shell_run(c, "use ut\nhash uh sk01")  # single-line runner:
        # run_line handles one line; drive via Shell object instead
        import io

        from pegasus_tpu.shell.main import Shell

        buf = io.StringIO()
        sh = Shell([c.meta_addr], out=buf)
        sh.run_line("use ut")
        sh.run_line("hash uh sk01")
        assert "partition:" in buf.getvalue()
        sh.run_line("timeout 2500")
        assert "2500 ms" in buf.getvalue()
        sh.run_line("multi_get_sortkeys uh")
        assert "12 sortkeys" in buf.getvalue()
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("multi_get_range uh sk03 sk06")
        assert "3 rows" in buf.getvalue()
        sh.run_line("multi_del_range uh sk03 sk06")
        assert "deleted 3 rows" in buf.getvalue()
        assert cl.get(b"uh", b"sk04") is None
        assert cl.get(b"uh", b"sk07") == b"v7"
        # env set + clear round-trip
        sh.run_line("set_app_envs default_ttl 99")
        sh.run_line("clear_app_envs")
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("get_app_envs")
        import json as _json

        envs = _json.loads(buf.getvalue())
        assert envs.get("default_ttl", "") == ""
        # app_disk sees the table's replicas; app_stat aggregates
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("app_disk ut")
        assert "total ut:" in buf.getvalue()
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("app_stat")
        assert "ut" in buf.getvalue()
        # meta levels: freezed blocks balancing AND redundancy rebuild
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("get_meta_level")
        assert "lively" in buf.getvalue()
        sh.run_line("set_meta_level freezed")
        pc = c.meta._parts[cl.resolver.app_id][0]
        victim = pc.secondaries[0]
        c.kill_node(victim)
        assert len([m for m in [pc.primary] + pc.secondaries if m]) == 2
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("balance")
        # freezed -> balance REFUSES loudly (regression: deleting the level
        # gate in _on_balance must fail here)
        assert "ERROR" in buf.getvalue() and "freezed" in buf.getvalue()
        sh.run_line("set_meta_level lively")
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("get_meta_level")
        assert "lively" in buf.getvalue()
        # clear_data with confirmation wipes the table
        buf.seek(0)
        buf.truncate(0)
        sh.run_line("clear_data ut")
        assert "refusing" in buf.getvalue()
        sh.run_line("clear_data ut yes")
        assert cl.get(b"uh", b"sk07") is None
        assert cl.get(b"other", b"s") is None
        cl.close()
    finally:
        c.stop()
