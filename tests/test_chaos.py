"""ISSUE 11: chaos scenario engine — units + the production-sim tier.

Three layers:

  * scenario-engine units: schedule validation/expansion (ordering,
    arm/heal pairing), runner semantics against fake actors (windows
    open/close around faults, a recovery-deadline breach is a NAMED
    failure, an actor that cannot arm/heal is a named failure), the
    bounded latency reservoir's pinned percentile semantics, and the
    fault-window error classification;
  * a bounded tier-1 chaos smoke: `pressure_test --scenario smoke`
    (group-worker kill + remote fail-point wedge under self-verifying
    load) must exit 0 with the doctor healthy — and the SAME command
    with undeclared `audit.digest` corruption injected must exit 1
    with `audit.mismatch` named in the journal (self-falsification:
    a harness that cannot catch a planted fault proves nothing);
  * a `slow`-marked full scenario: node kill+restart, mid-load split,
    balancer move, scheduler flips, duplication leg + cross-cluster
    digest compare at the duplicator's confirmed decree.
"""

import json
import time

import pytest

from pegasus_tpu.chaos.journal import EventJournal, FaultWindows
from pegasus_tpu.chaos.scenario import (FaultAction, Scenario,
                                        ScenarioError, ScenarioRunner,
                                        full_scenario, smoke_scenario)

from tools.pressure_test import LatencyReservoir, run_pressure


# ----------------------------------------------------- schedule validation


def test_validate_rejects_duplicate_action_names():
    s = Scenario("s", [FaultAction("a", "x", at_s=0),
                       FaultAction("a", "x", at_s=1)])
    with pytest.raises(ScenarioError, match="duplicate"):
        s.validate()


def test_validate_rejects_negative_times_and_zero_deadline():
    with pytest.raises(ScenarioError, match="negative"):
        Scenario("s", [FaultAction("a", "x", at_s=-1)]).validate()
    with pytest.raises(ScenarioError, match="recovery_deadline"):
        Scenario("s", [FaultAction("a", "x", at_s=0,
                                   recovery_deadline_s=0)]).validate()


def test_validate_rejects_overlapping_periodic_occurrences():
    # every_s <= duration_s would arm the next occurrence before the
    # previous one healed — the arm/heal pairing invariant
    with pytest.raises(ScenarioError, match="every_s"):
        Scenario("s", [FaultAction("a", "x", at_s=0, duration_s=5,
                                   every_s=4)]).validate()


def test_validate_rejects_unknown_actor():
    s = Scenario("s", [FaultAction("a", "nope", at_s=0)])
    with pytest.raises(ScenarioError, match="unknown actor"):
        s.validate(actor_keys={"failpoint"})
    s.validate(actor_keys={"nope"})  # known = fine


def test_builtin_scenarios_validate():
    keys = {"failpoint", "group_kill", "node_kill", "split", "balance",
            "sched_flip"}
    smoke_scenario().validate(keys)
    full_scenario().validate(keys)


# ----------------------------------------------------- timeline expansion


def test_timeline_sorted_with_arm_before_heal():
    s = Scenario("s", [
        FaultAction("instant", "x", at_s=2.0, duration_s=0.0),
        FaultAction("early", "x", at_s=1.0, duration_s=5.0),
    ])
    tl = s.timeline(run_s=10.0)
    assert [t for t, _, _, _ in tl] == sorted(t for t, _, _, _ in tl)
    # zero-duration action: arm and heal share t=2.0 but arm comes FIRST
    pair = [(what, a.name) for t, what, a, _ in tl if a.name == "instant"]
    assert pair == [("arm", "instant"), ("heal", "instant")]


def test_timeline_periodic_expansion_and_pairing():
    s = Scenario("s", [FaultAction("p", "x", at_s=1.0, duration_s=2.0,
                                   every_s=4.0)])
    tl = s.timeline(run_s=10.0)  # arms at 1, 5, 9
    arms = [(t, k) for t, what, _, k in tl if what == "arm"]
    heals = [(t, k) for t, what, _, k in tl if what == "heal"]
    assert arms == [(1.0, 0), (5.0, 1), (9.0, 2)]
    # every occurrence heals, including the one armed near the end
    assert heals == [(3.0, 0), (7.0, 1), (11.0, 2)]


def test_timeline_single_shot_past_run_end_still_emitted():
    s = Scenario("s", [FaultAction("a", "x", at_s=0.0, duration_s=99.0)])
    tl = s.timeline(run_s=10.0)
    assert [(t, what) for t, what, _, _ in tl] == [(0.0, "arm"),
                                                  (99.0, "heal")]


# --------------------------------------------------------- runner semantics


class FakeActor:
    def __init__(self, recover_after_heals: int = 0, arm_error=None,
                 heal_error=None):
        self.armed = []
        self.healed = 0
        self.recover_after_heals = recover_after_heals
        self.arm_error = arm_error
        self.heal_error = heal_error

    def arm(self, **args):
        if self.arm_error:
            raise self.arm_error
        self.armed.append(args)

    def heal(self):
        if self.heal_error:
            raise self.heal_error
        self.healed += 1

    def recovered(self):
        return self.healed >= self.recover_after_heals


def _run(scenario, actors, run_s=0.1):
    journal = EventJournal()
    runner = ScenarioRunner(scenario, actors, journal)
    runner.start(run_s)
    runner.join(timeout=30)
    return runner, journal


def test_runner_arms_heals_and_closes_windows():
    actor = FakeActor()
    s = Scenario("s", [FaultAction("a", "x", at_s=0.0, duration_s=0.05,
                                   settle_s=0.0, args={"k": 1})])
    runner, journal = _run(s, {"x": actor})
    assert actor.armed == [{"k": 1}] and actor.healed == 1
    assert runner.failures == []
    kinds = [e["kind"] for e in journal.events()]
    assert kinds.count("fault.armed") == 1
    assert kinds.count("fault.healed") == 1
    assert kinds.count("fault.recovered") == 1
    assert kinds[-1] == "scenario.done"
    # the declared window is closed and bounded
    (w,) = runner.windows.bounds()
    assert w["name"] == "a" and w["end"] is not None


def test_runner_periodic_occurrences_pair_and_name():
    actor = FakeActor()
    s = Scenario("s", [FaultAction("p", "x", at_s=0.0, duration_s=0.02,
                                   every_s=0.06, settle_s=0.0)])
    runner, journal = _run(s, {"x": actor}, run_s=0.15)
    assert len(actor.armed) == actor.healed >= 2
    names = [e["action"] for e in journal.events("fault.armed")]
    assert names[:2] == ["p#0", "p#1"]   # occurrence-indexed
    assert all(w["end"] is not None for w in runner.windows.bounds())


def test_runner_deadline_breach_is_named_failure():
    actor = FakeActor(recover_after_heals=99)   # never recovers
    s = Scenario("s", [FaultAction("wedge", "x", at_s=0.0, duration_s=0.0,
                                   recovery_deadline_s=0.4)])
    runner, _ = _run(s, {"x": actor})
    assert [f["failure"] for f in runner.failures] \
        == ["recovery.deadline:wedge"]


def test_runner_arm_and_heal_errors_are_named_failures():
    s = Scenario("s", [FaultAction("boom", "x", at_s=0.0, duration_s=0.0)])
    runner, _ = _run(s, {"x": FakeActor(arm_error=RuntimeError("nope"))})
    assert "actor.arm:boom" in [f["failure"] for f in runner.failures]
    runner, _ = _run(s, {"x": FakeActor(heal_error=RuntimeError("nope"))})
    assert "actor.heal:boom" in [f["failure"] for f in runner.failures]


def test_runner_arm_failure_skips_heal_and_recovery():
    """An occurrence whose arm() raised has nothing to heal: healing the
    unarmed actor would cascade ONE failure into spurious actor.heal +
    recovery.deadline ones, and the recovery wait would stall every
    later action by the full deadline."""
    actor = FakeActor(arm_error=RuntimeError("nope"),
                      heal_error=RuntimeError("unarmed"),
                      recover_after_heals=99)
    s = Scenario("s", [FaultAction("boom", "x", at_s=0.0, duration_s=0.0,
                                   recovery_deadline_s=30.0, settle_s=0.0)])
    t0 = time.monotonic()
    runner, _ = _run(s, {"x": actor})
    assert [f["failure"] for f in runner.failures] == ["actor.arm:boom"]
    assert actor.healed == 0
    assert time.monotonic() - t0 < 5.0   # no recovery-deadline stall
    (w,) = runner.windows.bounds()
    assert w["end"] is not None          # the declared window still closes


# ------------------------------------------- windows + error classification


def test_fault_windows_classify_in_vs_out():
    j = EventJournal()
    w = FaultWindows(j)
    assert not w.in_window()
    wid = w.open("blip")
    assert w.in_window()
    w.close(wid, settle_s=100.0)         # settle keeps the window open
    assert w.in_window()
    w2 = w.open("other")
    w.close(w2, settle_s=0.0)
    # an instant before any window opened stays OUT
    assert not w.in_window(t=-1.0)


# ----------------------------------------------------- latency reservoir


def test_reservoir_below_cap_pins_old_percentile_semantics():
    vals = [float(v) for v in range(100, 0, -1)]   # 100..1, unsorted-ish
    r = LatencyReservoir(cap=1000)
    for v in vals:
        r.add(v)
    s = sorted(vals)
    for p in (0.5, 0.95, 0.99):
        # the exact index rule the old unbounded sorted list used
        assert r.percentile(p) == round(s[min(len(s) - 1,
                                              int(len(s) * p))], 2)
    assert r.avg() == round(sum(vals) / len(vals), 2)


def test_reservoir_bounded_past_cap():
    r = LatencyReservoir(cap=64, seed=7)
    for v in range(10_000):
        r.add(float(v))
    assert len(r._sample) == 64 and r.count == 10_000
    assert r.total == float(sum(range(10_000)))
    # a uniform sample of 0..9999: p95 lands in the upper region
    assert 8000 < r.percentile(0.95) <= 9999


# ------------------------------------------------- tier-1 chaos smoke (e2e)


def _journal(path):
    with open(path) as f:
        return json.load(f)


def test_chaos_smoke_survives_and_doctor_healthy(tmp_path):
    """The bounded production-sim smoke: self-verifying load while a
    group-worker process is SIGKILLed (+ restart_group replay) and a
    dispatch wedge is armed remotely over set-fail-point, under a
    periodic decree-anchored audit cadence — zero lost acked writes,
    every error in a declared window, doctor ends healthy."""
    out = tmp_path / "journal.json"
    rc = run_pressure(["--scenario", "smoke", "--qps", "40", "--seconds",
                       "12", "--threads", "2", "--audit-every", "4",
                       "--journal", str(out)])
    j = _journal(out)
    assert rc == 0, f"chaos smoke failed: {j['failures']}"
    assert j["failures"] == []
    kinds = {e["kind"] for e in j["events"]}
    assert {"fault.armed", "fault.healed", "fault.recovered",
            "audit.round", "doctor.final"} <= kinds
    (doc,) = [e for e in j["events"] if e["kind"] == "doctor.final"]
    assert doc["verdict"] == "healthy"
    # the cadence ran MORE than one round, and at least one concluded
    rounds = [e for e in j["events"] if e["kind"] == "audit.round"]
    assert len(rounds) >= 2
    assert any(r["conclusive"] for r in rounds)
    assert not any(r["mismatches"] for r in rounds)


def test_chaos_smoke_catches_planted_audit_corruption(tmp_path):
    """Self-falsification: the SAME command with undeclared audit-digest
    corruption armed on one node must exit 1 with the failure NAMED —
    a green harness that cannot catch a planted fault proves nothing."""
    out = tmp_path / "journal.json"
    rc = run_pressure(["--scenario", "smoke", "--qps", "30", "--seconds",
                       "8", "--threads", "2", "--audit-every", "3",
                       "--inject-fault", "audit.digest=return()",
                       "--journal", str(out)])
    j = _journal(out)
    assert rc == 1
    failures = [f["failure"] for f in j["failures"]]
    assert "audit.mismatch" in failures, failures


# ------------------------------------------------- full scenario (kill tier)


@pytest.mark.slow
def test_chaos_full_scenario_survives(tmp_path):
    """The flagship: scheduler flips, dispatch wedge, mid-load partition
    split, group-worker kill, balancer primary move, node kill+restart,
    duplication to a second cluster — exit 0 requires zero lost acked
    writes, in-window-only errors, mismatch-free non-vacuous audits, a
    matching cross-cluster digest at the duplicator's confirmed decree,
    and a healthy final doctor verdict."""
    out = tmp_path / "journal.json"
    rc = run_pressure(["--scenario", "full", "--qps", "60", "--seconds",
                       "30", "--threads", "2", "--audit-every", "5",
                       "--journal", str(out)])
    j = _journal(out)
    assert rc == 0, f"full scenario failed: {j['failures']}"
    assert j["failures"] == []
    (xc,) = [e for e in j["events"] if e["kind"] == "cross_cluster.audit"]
    assert xc["match"] is True
    assert xc["src"]["records"] == xc["dst"]["records"] > 0
    (doc,) = [e for e in j["events"] if e["kind"] == "doctor.final"]
    assert doc["verdict"] == "healthy"
    armed = {e["action"] for e in j["events"] if e["kind"] == "fault.armed"}
    assert {"sched-defer-urgent", "dispatch-wedge", "split-double",
            "kill-group", "primary-move", "kill-node",
            "learn-ship-abort"} <= armed
