"""Multi-PROCESS kill test: real server processes, SIGKILL, recovery.

The reference's chaos tier (SURVEY §4.3, src/test/kill_test): a
data_verifier writes self-checking rows while killer_handler_shell
hard-kills and restarts node processes, then verifies every acknowledged
write. Here the onebox is 1 meta + 3 replica `python -m pegasus_tpu.server`
processes on real ports; kills are SIGKILL (no flush, no goodbye) so
recovery exercises the mutation-log replay + meta FD + learner rebuild
paths end to end.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError
from pegasus_tpu.rpc.transport import RpcError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INI = """
[apps.{name}]
type = {type}
run = true
port = {port}
state_dir = {root}/meta
data_dir = {root}/{name}
election_lease_seconds = 1.5

[pegasus.server]
meta_servers = {meta_servers}

[failure_detector]
beacon_interval_seconds = 0.3
grace_seconds = 2.5
check_interval_seconds = 0.5
"""


class ProcNode:
    def __init__(self, root, name, type_, port, meta_servers):
        self.root, self.name = root, name
        self.cfg = os.path.join(root, f"{name}.ini")
        with open(self.cfg, "w") as f:
            f.write(INI.format(name=name, type=type_, port=port, root=root,
                               meta_servers=meta_servers))
        self.proc = None

    def start(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        self.log = open(os.path.join(self.root, f"{self.name}.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server", "--config", self.cfg,
             "--app", self.name],
            env=env, stdout=self.log, stderr=self.log, cwd=self.root)
        return self

    def kill9(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()

    def stop(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.log.close()


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_nodes(meta_addr, want, timeout=30):
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_LIST_NODES
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    host, _, port = meta_addr.rpartition(":")
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            conn = RpcConnection((host, int(port)))
            _, body = conn.call(RPC_CM_LIST_NODES,
                                codec.encode(mm.ListNodesRequest()), timeout=3)
            conn.close()
            nodes = codec.decode(mm.ListNodesResponse, body).nodes
            if sum(1 for n in nodes if n.alive) >= want:
                return True
        except (RpcError, OSError):
            pass
        time.sleep(0.5)
    return False


@pytest.mark.slow
def test_process_kill_recovery(tmp_path):
    root = str(tmp_path)
    meta_port, p1, p2, p3 = _free_ports(4)
    meta_list = f"127.0.0.1:{meta_port}"
    meta = ProcNode(root, "meta", "meta", meta_port, meta_list).start()
    replicas = {
        "replica1": ProcNode(root, "replica1", "replica", p1, meta_list).start(),
        "replica2": ProcNode(root, "replica2", "replica", p2, meta_list).start(),
        "replica3": ProcNode(root, "replica3", "replica", p3, meta_list).start(),
    }
    meta_addr = f"127.0.0.1:{meta_port}"
    try:
        assert _wait_nodes(meta_addr, 3), "replica processes never registered"
        from pegasus_tpu.meta import messages as mm
        from pegasus_tpu.meta.meta_server import RPC_CM_CREATE_APP, RPC_CM_QUERY_CONFIG
        from pegasus_tpu.rpc import codec
        from pegasus_tpu.rpc.transport import RpcConnection

        host, _, port = meta_addr.rpartition(":")
        conn = RpcConnection((host, int(port)))
        _, body = conn.call(RPC_CM_CREATE_APP,
                            codec.encode(mm.CreateAppRequest("kt", 2, 3)),
                            timeout=15)
        assert codec.decode(mm.CreateAppResponse, body).error == 0

        cli = PegasusClient(MetaResolver([meta_addr], "kt"), timeout=15)
        acked = {}
        i = 0

        def write_burst(n):
            nonlocal i
            for _ in range(n):
                try:
                    cli.set(b"pk%d" % i, b"s", b"pv%d" % i)
                    acked[i] = True
                except PegasusError:
                    pass
                i += 1

        write_burst(30)
        # find + SIGKILL the node that is primary for partition 0
        _, body = conn.call(RPC_CM_QUERY_CONFIG,
                            codec.encode(mm.QueryConfigRequest("kt")), timeout=5)
        cfg = codec.decode(mm.QueryConfigResponse, body)
        victim_addr = cfg.partitions[0].primary
        victim = None
        for name, node in replicas.items():
            with open(os.path.join(root, f"{name}.log"), "rb") as f:
                if victim_addr.encode() in f.read():
                    victim = name
        assert victim is not None
        replicas[victim].kill9()
        # FD grace is 2.5s; wait for the meta to reconfigure
        time.sleep(4)
        write_burst(20)
        for k in sorted(acked):
            assert cli.get(b"pk%d" % k, b"s") == b"pv%d" % k, f"lost pk{k}"
        # restart the killed process: it must rejoin and beacon again
        replicas[victim].start()
        assert _wait_nodes(meta_addr, 3), "killed replica never rejoined"
        write_burst(10)
        for k in sorted(acked):
            assert cli.get(b"pk%d" % k, b"s") == b"pv%d" % k
        assert len(acked) >= 55
        cli.close()
        conn.close()
    finally:
        for r in replicas.values():
            r.stop()
        meta.stop()


def _find_meta_leader(meta_addrs, timeout=15):
    """Probe every meta with a read RPC: the leader answers, followers
    refuse with ERR_FORWARD_TO_PRIMARY (err 8)."""
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_LIST_APPS
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    deadline = time.time() + timeout
    while time.time() < deadline:
        for m in meta_addrs:
            host, _, port = m.rpartition(":")
            try:
                conn = RpcConnection((host, int(port)))
                try:
                    conn.call(RPC_CM_LIST_APPS,
                              codec.encode(mm.ListAppsRequest()), timeout=3)
                    return m
                finally:
                    conn.close()
            except (RpcError, OSError):
                continue
        time.sleep(0.3)
    return None


@pytest.mark.slow
def test_meta_leader_kill(tmp_path):
    """VERDICT-r3 missing #1 done-criterion: acknowledged writes (DDL and
    data) survive SIGKILL of the active meta. 3 metas share a state dir
    and elect a leader; the leader is hard-killed; a standby takes over
    with every acknowledged DDL intact; the killed meta rejoins as a
    follower."""
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import (RPC_CM_CREATE_APP,
                                              RPC_CM_QUERY_CONFIG)
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    root = str(tmp_path)
    m1, m2, m3, p1, p2, p3 = _free_ports(6)
    meta_addrs = [f"127.0.0.1:{m}" for m in (m1, m2, m3)]
    meta_list = ",".join(meta_addrs)
    metas = {f"127.0.0.1:{port}": ProcNode(root, f"meta{i + 1}", "meta",
                                           port, meta_list).start()
             for i, port in enumerate((m1, m2, m3))}
    replicas = [ProcNode(root, f"replica{i + 1}", "replica", port,
                         meta_list).start()
                for i, port in enumerate((p1, p2, p3))]

    def meta_call(addr, code, req, resp_cls, timeout=10):
        host, _, port = addr.rpartition(":")
        conn = RpcConnection((host, int(port)))
        try:
            _, body = conn.call(code, codec.encode(req), timeout=timeout)
            return codec.decode(resp_cls, body)
        finally:
            conn.close()

    try:
        leader = _find_meta_leader(meta_addrs)
        assert leader is not None, "no meta leader elected"
        assert _wait_nodes(leader, 3), "replicas never registered"
        resp = meta_call(leader, RPC_CM_CREATE_APP,
                         mm.CreateAppRequest("ht", 2, 3),
                         mm.CreateAppResponse, timeout=15)
        assert resp.error == 0

        cli = PegasusClient(MetaResolver(meta_addrs, "ht"), timeout=15)
        for i in range(20):
            cli.set(b"hk%d" % i, b"s", b"hv%d" % i)  # all acknowledged

        # hard-kill the active meta: no flush, no lease release
        metas[leader].kill9()
        new_leader = _find_meta_leader([m for m in meta_addrs if m != leader],
                                       timeout=20)
        assert new_leader is not None, "no takeover after leader SIGKILL"
        assert new_leader != leader

        # acknowledged DDL survived into the new leader
        got = meta_call(new_leader, RPC_CM_QUERY_CONFIG,
                        mm.QueryConfigRequest("ht"), mm.QueryConfigResponse)
        assert got.error == 0 and got.app.partition_count == 2

        # acknowledged data survived (and the data path still serves)
        for i in range(20):
            assert cli.get(b"hk%d" % i, b"s") == b"hv%d" % i

        # the cluster accepts NEW DDL under the new leader
        resp = meta_call(new_leader, RPC_CM_CREATE_APP,
                         mm.CreateAppRequest("ht2", 2, 3),
                         mm.CreateAppResponse, timeout=15)
        assert resp.error == 0

        # the killed meta restarts and rejoins as a FOLLOWER
        metas[leader].start()
        deadline = time.time() + 15
        rejoined = False
        while time.time() < deadline and not rejoined:
            try:
                meta_call(leader, RPC_CM_QUERY_CONFIG,
                          mm.QueryConfigRequest("ht"), mm.QueryConfigResponse,
                          timeout=3)
                rejoined = True  # it answered: it re-won leadership (ok too,
                # but only if the old leader actually lost it first)
            except RpcError as e:
                if e.err == 8:
                    rejoined = True  # follower redirect: rejoined cleanly
                else:
                    time.sleep(0.3)
            except OSError:
                time.sleep(0.3)
        assert rejoined, "killed meta never rejoined"
        cli.close()
    finally:
        for node in metas.values():
            node.stop()
        for node in replicas:
            node.stop()
