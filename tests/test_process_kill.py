"""Multi-PROCESS kill test: real server processes, SIGKILL, recovery.

The reference's chaos tier (SURVEY §4.3, src/test/kill_test): a
data_verifier writes self-checking rows while killer_handler_shell
hard-kills and restarts node processes, then verifies every acknowledged
write. Here the onebox is 1 meta + 3 replica `python -m pegasus_tpu.server`
processes on real ports; kills are SIGKILL (no flush, no goodbye) so
recovery exercises the mutation-log replay + meta FD + learner rebuild
paths end to end.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError
from pegasus_tpu.rpc.transport import RpcError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INI = """
[apps.{name}]
type = {type}
run = true
port = {port}
state_dir = {root}/meta
data_dir = {root}/{name}

[pegasus.server]
meta_servers = 127.0.0.1:{meta_port}

[failure_detector]
beacon_interval_seconds = 0.3
grace_seconds = 2.5
check_interval_seconds = 0.5
"""


class ProcNode:
    def __init__(self, root, name, type_, port, meta_port):
        self.root, self.name = root, name
        self.cfg = os.path.join(root, f"{name}.ini")
        with open(self.cfg, "w") as f:
            f.write(INI.format(name=name, type=type_, port=port, root=root,
                               meta_port=meta_port))
        self.proc = None

    def start(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env["JAX_PLATFORMS"] = "cpu"
        self.log = open(os.path.join(self.root, f"{self.name}.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server", "--config", self.cfg,
             "--app", self.name],
            env=env, stdout=self.log, stderr=self.log, cwd=self.root)
        return self

    def kill9(self):
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()

    def stop(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.log.close()


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_nodes(meta_addr, want, timeout=30):
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_LIST_NODES
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    host, _, port = meta_addr.rpartition(":")
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            conn = RpcConnection((host, int(port)))
            _, body = conn.call(RPC_CM_LIST_NODES,
                                codec.encode(mm.ListNodesRequest()), timeout=3)
            conn.close()
            nodes = codec.decode(mm.ListNodesResponse, body).nodes
            if sum(1 for n in nodes if n.alive) >= want:
                return True
        except (RpcError, OSError):
            pass
        time.sleep(0.5)
    return False


@pytest.mark.slow
def test_process_kill_recovery(tmp_path):
    root = str(tmp_path)
    meta_port, p1, p2, p3 = _free_ports(4)
    meta = ProcNode(root, "meta", "meta", meta_port, meta_port).start()
    replicas = {
        "replica1": ProcNode(root, "replica1", "replica", p1, meta_port).start(),
        "replica2": ProcNode(root, "replica2", "replica", p2, meta_port).start(),
        "replica3": ProcNode(root, "replica3", "replica", p3, meta_port).start(),
    }
    meta_addr = f"127.0.0.1:{meta_port}"
    try:
        assert _wait_nodes(meta_addr, 3), "replica processes never registered"
        from pegasus_tpu.meta import messages as mm
        from pegasus_tpu.meta.meta_server import RPC_CM_CREATE_APP, RPC_CM_QUERY_CONFIG
        from pegasus_tpu.rpc import codec
        from pegasus_tpu.rpc.transport import RpcConnection

        host, _, port = meta_addr.rpartition(":")
        conn = RpcConnection((host, int(port)))
        _, body = conn.call(RPC_CM_CREATE_APP,
                            codec.encode(mm.CreateAppRequest("kt", 2, 3)),
                            timeout=15)
        assert codec.decode(mm.CreateAppResponse, body).error == 0

        cli = PegasusClient(MetaResolver([meta_addr], "kt"), timeout=15)
        acked = {}
        i = 0

        def write_burst(n):
            nonlocal i
            for _ in range(n):
                try:
                    cli.set(b"pk%d" % i, b"s", b"pv%d" % i)
                    acked[i] = True
                except PegasusError:
                    pass
                i += 1

        write_burst(30)
        # find + SIGKILL the node that is primary for partition 0
        _, body = conn.call(RPC_CM_QUERY_CONFIG,
                            codec.encode(mm.QueryConfigRequest("kt")), timeout=5)
        cfg = codec.decode(mm.QueryConfigResponse, body)
        victim_addr = cfg.partitions[0].primary
        victim = None
        for name, node in replicas.items():
            with open(os.path.join(root, f"{name}.log"), "rb") as f:
                if victim_addr.encode() in f.read():
                    victim = name
        assert victim is not None
        replicas[victim].kill9()
        # FD grace is 2.5s; wait for the meta to reconfigure
        time.sleep(4)
        write_burst(20)
        for k in sorted(acked):
            assert cli.get(b"pk%d" % k, b"s") == b"pv%d" % k, f"lost pk{k}"
        # restart the killed process: it must rejoin and beacon again
        replicas[victim].start()
        assert _wait_nodes(meta_addr, 3), "killed replica never rejoined"
        write_burst(10)
        for k in sorted(acked):
            assert cli.get(b"pk%d" % k, b"s") == b"pv%d" % k
        assert len(acked) >= 55
        cli.close()
        conn.close()
    finally:
        for r in replicas.values():
            r.stop()
        meta.stop()
