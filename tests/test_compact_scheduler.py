"""ISSUE 10: cluster-wide compaction scheduler — decision fold units,
the engine-side policy gate, debt-driven admission control, scheduler
chaos, and the onebox acceptance.

Pinned here:
  - the decision fold is deterministic: hot-read partitions defer,
    backlogged partitions promote, breaker-open nodes are never
    promoted, the hard debt ceiling overrides defer, and the per-node
    urgent budget demotes overflow;
  - the engine gate honors tokens but can never be wedged by them:
    tokens expire back to engine-local triggers, the debt ceiling always
    wins, and with no scheduler the trigger behavior (and the resulting
    data) is identical to the pre-scheduler engine;
  - a wedged or crashed scheduler tick (`compact.sched` fail point)
    never blocks writes or compactions;
  - the debt throttle delays writes on a graduated slope before the L0
    stall cliff and rejects only past the configured ratio;
  - onebox: a read-hot partition's compaction defers and a debt-driving
    partition's promotes, decisions delivered end-to-end with reasons
    visible via compact-sched-status / the shell's compact_sched.
"""

import io
import json
import threading
import time

import pytest

from pegasus_tpu.collector.cluster_doctor import ClusterCaller
from pegasus_tpu.collector.compact_scheduler import (CompactScheduler,
                                                     fold_decisions,
                                                     run_scheduler_tick)
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.db import SCHED_GATE, LsmEngine
from pegasus_tpu.engine.throttling import DebtThrottle, ThrottleReject
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.perf_counters import counters

KNOBS = {"urgent_l0": 4, "backlog_urgent": 64, "max_urgent_per_node": 2,
         "max_device": 0, "ttl_s": 30.0}


def _part(node="n1:1", l0=0, debt=0, gap=0, ceiling=12):
    return {"node": node, "l0_files": l0, "debt_bytes": debt,
            "apply_gap": gap, "ceiling_files": ceiling,
            "pending_installs": 0}


@pytest.fixture
def failpoints():
    fp.setup()
    yield fp
    fp.teardown()


# ------------------------------------------------------ decision fold


def test_fold_hot_read_partition_deferred():
    parts = {"1.0": _part(l0=5), "1.1": _part(l0=0)}
    out = fold_decisions(parts, hot={"1.0"}, knobs=KNOBS)
    assert out["1.0"]["policy"] == "defer"
    assert out["1.0"]["reasons"] == ["hot_read"]
    assert out["1.1"]["policy"] == "normal"


def test_fold_backlogged_partition_promoted():
    parts = {"1.0": _part(gap=100), "1.1": _part(gap=10)}
    out = fold_decisions(parts, slow_count=3, knobs=KNOBS)
    assert out["1.0"]["policy"] == "urgent"
    assert out["1.0"]["reasons"] == ["apply_backlog", "slow_requests"]
    assert out["1.1"]["policy"] == "normal"
    # without a slow-request rollup the backlog still promotes, but the
    # slow_requests attribution is not claimed
    out = fold_decisions(parts, slow_count=0, knobs=KNOBS)
    assert out["1.0"]["reasons"] == ["apply_backlog"]


def test_fold_l0_debt_promotes():
    out = fold_decisions({"1.0": _part(l0=4)}, knobs=KNOBS)
    assert out["1.0"]["policy"] == "urgent"
    assert "l0_debt" in out["1.0"]["reasons"]


def test_breaker_open_node_never_promoted():
    """Breaker skipping binds per RECEIVER at delivery, never globally:
    the fold keeps the cluster-level urgency; localize demotes it only
    on the breaker-open node, and a healthy receiver of the same
    partition keeps the promotion."""
    from pegasus_tpu.collector.compact_scheduler import localize_decisions

    parts = {"1.0": _part(node="bad:1", l0=6, gap=999),
             "1.1": _part(node="ok:1", l0=6)}
    out = fold_decisions(parts, slow_count=1, knobs=KNOBS)
    assert out["1.0"]["policy"] == "urgent"   # cluster truth: it needs it
    hosts = {"1.0": ["bad:1", "ok:1"], "1.1": ["ok:1"]}
    on_bad = localize_decisions(out, hosts, "bad:1", breaker_open=True,
                                cap=2)
    on_ok = localize_decisions(out, hosts, "ok:1", breaker_open=False,
                               cap=2)
    assert on_bad["1.0"]["policy"] == "normal"
    assert "breaker_open" in on_bad["1.0"]["reasons"]
    assert on_ok["1.0"]["policy"] == "urgent"  # healthy secondary keeps it
    assert on_ok["1.1"]["policy"] == "urgent"


def test_fold_debt_ceiling_overrides_defer_and_breaker():
    from pegasus_tpu.collector.compact_scheduler import localize_decisions

    parts = {"1.0": _part(node="bad:1", l0=12)}
    out = fold_decisions(parts, hot={"1.0"}, knobs=KNOBS)
    assert out["1.0"]["policy"] == "urgent"
    assert out["1.0"]["reasons"] == ["debt_ceiling"]
    # even a breaker-open receiver keeps a ceiling urgent: the engine-
    # local trigger fires there regardless, the token just agrees
    mine = localize_decisions(out, {"1.0": ["bad:1"]}, "bad:1",
                              breaker_open=True, cap=1)
    assert mine["1.0"]["policy"] == "urgent"


def test_fold_keeps_cluster_urgency_cap_binds_at_receiver():
    """The fold never demotes for node budget — that would strip a
    partition's urgency for EVERY receiver; the cap is localize's job."""
    parts = {"1.0": _part(l0=6, debt=600), "1.1": _part(l0=6, debt=400),
             "1.2": _part(l0=6, debt=500)}
    out = fold_decisions(parts, knobs=KNOBS)
    assert all(d["policy"] == "urgent" for d in out.values())
    assert all("node_cap" not in d["reasons"] for d in out.values())


def test_localize_demotes_urgent_on_breaker_open_receiver():
    """A secondary on a breaker-open node must not receive the urgent
    token its (healthy-primary-keyed) fold decision granted."""
    from pegasus_tpu.collector.compact_scheduler import localize_decisions

    decisions = fold_decisions({"1.0": _part(node="A:1", l0=6, debt=600)},
                               knobs=KNOBS)
    assert decisions["1.0"]["policy"] == "urgent"
    hosts = {"1.0": ["A:1", "B:1"]}
    ok = localize_decisions(decisions, hosts, "A:1", breaker_open=False,
                            cap=2)
    bad = localize_decisions(decisions, hosts, "B:1", breaker_open=True,
                             cap=2)
    assert ok["1.0"]["policy"] == "urgent"
    assert bad["1.0"]["policy"] == "normal"
    assert "breaker_open" in bad["1.0"]["reasons"]


def test_localize_defer_lands_on_primary_only():
    """The read-residency pin behind a hot_read defer lives on the
    primary's engine; secondaries keep compacting normally."""
    from pegasus_tpu.collector.compact_scheduler import localize_decisions

    decisions = fold_decisions({"1.0": _part(node="prim:1", l0=3)},
                               hot={"1.0"}, knobs=KNOBS)
    assert decisions["1.0"]["policy"] == "defer"
    hosts = {"1.0": ["prim:1", "sec:1"]}
    on_prim = localize_decisions(decisions, hosts, "prim:1")
    on_sec = localize_decisions(decisions, hosts, "sec:1")
    assert on_prim["1.0"]["policy"] == "defer"
    assert on_sec["1.0"]["policy"] == "normal"
    assert "defer_primary_only" in on_sec["1.0"]["reasons"]


def test_localize_applies_urgent_cap_per_receiver():
    """A node hosting many secondaries of urgent partitions is still
    bounded by the per-node urgent budget at delivery time; ceiling
    urgents pass through untouched."""
    from pegasus_tpu.collector.compact_scheduler import localize_decisions

    parts = {f"1.{i}": _part(node=f"p{i}:1", l0=6, debt=600 - i)
             for i in range(4)}
    parts["1.9"] = _part(node="p9:1", l0=12)       # ceiling urgent
    decisions = fold_decisions(parts, knobs=dict(KNOBS,
                                                 max_urgent_per_node=8))
    hosts = {g: ["sec:1"] for g in parts}          # all on one secondary
    mine = localize_decisions(decisions, hosts, "sec:1", cap=2)
    urgents = [g for g, d in mine.items() if d["policy"] == "urgent"]
    assert "1.9" in urgents                        # ceiling exempt
    assert len(urgents) == 3                       # 2 capped + ceiling
    capped = [g for g, d in mine.items() if "node_cap" in d["reasons"]]
    assert len(capped) == 2
    assert mine["1.0"]["policy"] == "urgent"       # highest debt kept


# ------------------------------------------------------ engine gate


def _engine(tmp_path, name="e", trigger=2, **env_opts):
    return LsmEngine(str(tmp_path / name),
                     EngineOptions(backend="cpu", memtable_bytes=1,
                                   l0_compaction_trigger=trigger,
                                   **env_opts))


def _key(i):
    from pegasus_tpu.base.key_schema import generate_key

    return generate_key(b"hk%04d" % i, b"s")


def _flush_one(eng, i):
    eng.put(_key(i), b"v" * 32)
    eng.flush()


def test_engine_defer_token_holds_trigger_and_expires(tmp_path):
    eng = _engine(tmp_path, trigger=2)
    c0 = counters.rate("engine.compact.sched.deferred_count")._value
    eng.set_compact_policy("defer", reasons=["hot_read"], ttl_s=60)
    for i in range(3):
        _flush_one(eng, i)
    assert eng.stats()["l0_files"] == 3, "defer token must hold the trigger"
    assert counters.rate(
        "engine.compact.sched.deferred_count")._value > c0
    policy, reasons, expires_in = eng.compact_policy()
    assert policy == "defer" and reasons == ["hot_read"] and expires_in > 0
    # lease expiry: the engine-local trigger takes back over
    eng.set_compact_policy("defer", ttl_s=0.05)
    time.sleep(0.1)
    assert eng.compact_policy()[0] == "normal"
    _flush_one(eng, 99)
    assert eng.stats()["l0_files"] <= 1, \
        "expired token must revert to the engine-local trigger"
    eng.close()


def test_engine_debt_ceiling_overrides_defer(tmp_path, monkeypatch):
    monkeypatch.setenv("PEGASUS_SCHED_DEBT_CEILING_FILES", "4")
    eng = _engine(tmp_path, trigger=2)
    c0 = counters.rate(
        "engine.compact.sched.ceiling_override_count")._value
    eng.set_compact_policy("defer", ttl_s=60)
    for i in range(4):
        _flush_one(eng, i)
    assert eng.stats()["l0_files"] <= 1, \
        "the hard ceiling must compact through a defer token"
    assert counters.rate(
        "engine.compact.sched.ceiling_override_count")._value > c0
    eng.close()


def test_engine_urgent_fires_below_trigger(tmp_path):
    eng = _engine(tmp_path, trigger=4)   # urgent threshold = 2
    eng.set_compact_policy("urgent", ttl_s=60)
    for i in range(2):
        _flush_one(eng, i)
    assert eng.stats()["l0_files"] <= 1, "urgent must fire at trigger//2"
    eng.close()


def test_engine_bad_policy_rejected(tmp_path):
    eng = _engine(tmp_path)
    with pytest.raises(ValueError):
        eng.set_compact_policy("yolo")
    eng.close()


def test_engine_no_token_byte_identical_data(tmp_path):
    """Scheduler off (or dead): the resulting data is identical to a
    never-scheduled engine — the defer-then-expire engine converges to
    the same logical digest AND serves the same reads."""
    a = _engine(tmp_path, "a", trigger=2)
    b = _engine(tmp_path, "b", trigger=2)
    b.set_compact_policy("defer", ttl_s=0.2)
    rows = [(_key(i), b"val%d" % i) for i in range(40)]
    for i, (k, v) in enumerate(rows):
        a.put(k, v)
        b.put(k, v)
        if i % 8 == 7:
            a.flush()
            b.flush()
    time.sleep(0.25)  # token expires: engine-local trigger takes over
    a.flush()
    b.flush()
    b._maybe_trigger_l0()
    assert a.state_digest(now=1)["digest"] == b.state_digest(now=1)["digest"]
    for k, v in rows:
        assert a.get(k) == v and b.get(k) == v
    a.close()
    b.close()


def test_engine_stats_and_debt_fold(tmp_path):
    eng = _engine(tmp_path, trigger=8)
    for i in range(3):
        _flush_one(eng, i)
    st = eng.stats()
    debt = eng.compaction_debt()
    assert st["l0_files"] == debt["l0_files"] == 3
    assert st["compact_debt_bytes"] == debt["debt_bytes"] > 0
    assert st["pending_installs"] == debt["pending_installs"] == 0
    assert st["compact_policy"] == "normal"
    assert debt["ceiling_files"] == eng._sched_ceiling == 24
    assert 0 < eng.compact_debt_ratio() == 3 / 24
    eng.close()


def test_device_gate_defers_elective_trigger(tmp_path):
    """At the per-node device-compaction cap, an elective L0 trigger
    holds (counted) instead of convoying; urgent and the ceiling still
    proceed; cap 0 disables the gate."""
    eng = _engine(tmp_path, trigger=2)
    c0 = counters.rate(
        "engine.compact.sched.gate_deferred_count")._value
    eng.put(_key(0), b"v" * 32)
    eng.flush()
    # build L0 >= trigger without firing: temporarily defer
    eng.set_compact_policy("defer", ttl_s=60)
    eng.put(_key(1), b"v" * 32)
    eng.flush()
    assert eng.stats()["l0_files"] >= 2
    eng.set_compact_policy("normal", ttl_s=60)
    try:
        SCHED_GATE.set_max(1)
        SCHED_GATE.enter()          # saturate the node's device lanes
        eng.opts.backend = "tpu"    # gate only applies to device engines
        eng._maybe_trigger_l0()
        assert eng.stats()["l0_files"] >= 2, "elective merge must hold"
        assert counters.rate(
            "engine.compact.sched.gate_deferred_count")._value > c0
        assert SCHED_GATE.at_cap() and SCHED_GATE.state()["running"] == 1
    finally:
        SCHED_GATE.exit()
        SCHED_GATE.set_max(0)
        eng.opts.backend = "cpu"
    eng._maybe_trigger_l0()         # gate released: compacts normally
    assert eng.stats()["l0_files"] <= 1
    eng.close()


def test_device_gate_cap_lease_expires_to_default(tmp_path):
    """A scheduler-delivered cap is a lease: expiry reverts the gate to
    the env default, so a dead scheduler cannot leave a node capped."""
    assert SCHED_GATE.state()["max"] == SCHED_GATE.state()["default"] == 0
    SCHED_GATE.enter()
    try:
        SCHED_GATE.set_max(1, ttl_s=0.05)
        assert SCHED_GATE.at_cap()
        time.sleep(0.1)
        assert not SCHED_GATE.at_cap(), "expired cap must lapse to default"
        assert SCHED_GATE.state()["max"] == 0
        # a ttl-less set leases too (the hand-delivery footgun): only
        # the env default is permanent
        SCHED_GATE.set_max(3)
        assert SCHED_GATE._max_expire is not None
    finally:
        SCHED_GATE.exit()
        SCHED_GATE.set_max(0)


def test_grouped_policy_delivery_splits_device_cap():
    """In partition-group mode the command fans out to every worker and
    the gate is per-process: each worker takes cap // groups (min 1),
    not the whole node cap."""
    from pegasus_tpu.replication.replica_stub import ReplicaStub

    class _Stub:
        _lock = threading.RLock()
        _replicas = {}
        group_spec = {"group_count": 4}
        address = "x:1"

    try:
        out = ReplicaStub._cmd_compact_sched_policy(
            _Stub(), [json.dumps({"ttl_s": 5, "max_device": 4,
                                  "decisions": {}})])
        assert json.loads(out) == {}
        assert SCHED_GATE.state()["max"] == 1, "4 // 4 groups = 1"
        _Stub.group_spec = {"group_count": 8}
        ReplicaStub._cmd_compact_sched_policy(
            _Stub(), [json.dumps({"ttl_s": 5, "max_device": 4,
                                  "decisions": {}})])
        assert SCHED_GATE.state()["max"] == 1, "share floors at 1, not 0"
    finally:
        SCHED_GATE.set_max(0)


def test_poke_compaction_retries_after_token_lapse(tmp_path):
    """Idle engine: debt a defer token held past the trigger compacts on
    the maintenance poke once the token expires — no flush required."""
    eng = _engine(tmp_path, trigger=2)
    eng.set_compact_policy("defer", ttl_s=60)  # generous: flushes under
    for i in range(3):                         # load must not outlive it
        _flush_one(eng, i)
    assert eng.stats()["l0_files"] == 3
    eng.set_compact_policy("defer", ttl_s=0.05)
    time.sleep(0.1)
    eng.poke_compaction()   # what replica_stub's maintenance timer calls
    assert eng.stats()["l0_files"] <= 1
    eng.close()


# ------------------------------------------------- manual-compact queue


def test_manual_compact_urgent_jumps_queue(tmp_path):
    from pegasus_tpu.base import consts
    from pegasus_tpu.engine.manual_compact_service import GATE
    from pegasus_tpu.engine.server_impl import PegasusServer

    srv = PegasusServer(str(tmp_path / "mc"), app_id=7, pidx=0)
    srv.engine.put(_key(0), b"v")
    envs = {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "1",
            consts.MANUAL_COMPACT_MAX_CONCURRENT_RUNNING_COUNT_KEY: "1"}
    svc = srv.manual_compact_service
    svc.set_mock_now(10)
    assert GATE.try_acquire(0)  # an unrelated running compaction
    try:
        # at the cap with a normal token: queued behind the cap
        assert svc.start_manual_compact_if_needed(dict(envs)) is False
        # urgent token: jumps the queue and runs
        srv.engine.set_compact_policy("urgent", ttl_s=60)
        c0 = counters.rate("manual_compact.queue_jump_count")._value
        assert svc.start_manual_compact_if_needed(dict(envs)) is True
        assert counters.rate(
            "manual_compact.queue_jump_count")._value > c0
    finally:
        GATE.release()
    srv.close()


# --------------------------------------------------- debt throttle


class _RatioEngine:
    def __init__(self, ratio, policy="normal"):
        self.ratio = ratio
        self.policy = policy

    def compact_debt_ratio(self):
        return self.ratio

    def compact_policy_fast(self):
        return self.policy


def test_debt_throttle_graduated_slope(monkeypatch):
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_SOFT", "0.5")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_MAX_MS", "10")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_REJECT", "2.0")
    eng = _RatioEngine(0.25)
    th = DebtThrottle(eng)
    th.consume()
    assert th.delayed_count == 0, "below the soft ratio writes are free"
    eng.ratio = 0.75
    t0 = time.monotonic()
    th.consume()
    took = time.monotonic() - t0
    assert th.delayed_count == 1
    assert took < 0.5, "the graduated delay is bounded by max_ms"
    eng.ratio = 2.5
    with pytest.raises(ThrottleReject):
        th.consume()
    assert th.rejected_count == 1


def test_debt_throttle_defer_token_frees_the_slope(monkeypatch):
    """Under a live defer token the scheduler is deliberately growing
    the debt (read-hot hold): the throttle must not tax every write for
    it — the slope starts only in the last eighth before the ceiling."""
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_SOFT", "0.5")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_MAX_MS", "1")
    eng = _RatioEngine(0.75, policy="defer")
    th = DebtThrottle(eng)
    th.consume()
    assert th.delayed_count == 0, "mid-defer debt must ride free"
    eng.ratio = 0.9          # past 7/8: the ceiling cliff is imminent
    th.consume()
    assert th.delayed_count == 1
    eng.policy, eng.ratio = "normal", 0.75   # no token: normal slope
    th.consume()
    assert th.delayed_count == 2


def test_debt_throttle_disabled_and_default_no_reject(monkeypatch):
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE", "0")
    th = DebtThrottle(_RatioEngine(5.0))
    th.consume()  # disabled: free even at absurd debt
    assert th.delayed_count == 0 and th.rejected_count == 0
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE", "1")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_MAX_MS", "1")
    th = DebtThrottle(_RatioEngine(5.0))
    th.consume()  # default reject ratio 0 = never reject, only delay
    assert th.delayed_count == 1 and th.rejected_count == 0


def test_debt_throttle_engages_before_stall(tmp_path, monkeypatch):
    """The acceptance shape at engine level: a write burst that drives
    L0 debt toward the ceiling picks up measured delay (counter + sleep)
    while every write still completes — backpressure, not a stall."""
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_SOFT", "0.25")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_MAX_MS", "2")
    eng = _engine(tmp_path, trigger=64)  # ceiling 192: no inline compaction
    th = DebtThrottle(eng)
    c0 = counters.rate("engine.throttle.debt_delay_count")._value
    for i in range(80):
        th.consume()
        _flush_one(eng, i)
    assert th.delayed_count > 0, "debt crossing soft must delay writes"
    assert th.rejected_count == 0
    assert counters.rate(
        "engine.throttle.debt_delay_count")._value > c0
    assert eng.get(_key(0)) == b"v" * 32  # no write lost, no stall
    eng.close()


# ------------------------------------------------------ chaos: fail point


def test_wedged_scheduler_tick_never_blocks_compaction(tmp_path,
                                                       failpoints):
    """`compact.sched` sleep = a wedged tick: while it blocks, engines
    keep flushing and compacting from their local triggers, and a
    previously delivered defer token expires on its own."""
    failpoints.cfg("compact.sched", "sleep(1500)")
    done = threading.Event()
    result = {}

    def tick():
        # no meta at this address: the tick (after its wedge) degrades
        # to an errors-only report, never an exception
        result["r"] = run_scheduler_tick(["127.0.0.1:1"])
        done.set()

    t = threading.Thread(target=tick, daemon=True)
    t0 = time.monotonic()
    t.start()
    eng = _engine(tmp_path, trigger=2)
    eng.set_compact_policy("defer", ttl_s=0.2)
    time.sleep(0.25)
    for i in range(3):
        _flush_one(eng, i)
    assert eng.stats()["l0_files"] <= 1, \
        "a wedged scheduler must not hold the engine-local trigger"
    eng.close()
    assert done.wait(30)
    assert time.monotonic() - t0 >= 1.0, "the tick really was wedged"
    assert result["r"]["errors"], "no meta => errors, not decisions"


def test_crashed_scheduler_tick_loop_survives(failpoints):
    """`compact.sched` raise = a crashing tick: the CompactScheduler
    loop records the error and keeps ticking; run_scheduler_tick itself
    surfaces the raise to direct callers."""
    from pegasus_tpu.runtime.fail_points import FailPointError

    failpoints.cfg("compact.sched", "raise(sched-chaos)")
    with pytest.raises(FailPointError):
        run_scheduler_tick(["127.0.0.1:1"])
    c0 = counters.rate("sched.tick_errors")._value
    sched = CompactScheduler(["127.0.0.1:1"], interval_seconds=0.05)
    sched.start()
    try:
        deadline = time.monotonic() + 10
        while counters.rate("sched.tick_errors")._value <= c0:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert sched._thread.is_alive()
        assert sched.status() == {}, "a crashed tick publishes nothing"
    finally:
        sched.stop()
    assert not sched._thread.is_alive(), "stop() joins the loop"


# ------------------------------------------------------ onebox acceptance


@pytest.fixture
def cluster(tmp_path):
    """MiniCluster with tiny memtables and a high L0 trigger so client
    writes build real, visible compaction debt."""
    from tests.test_satellites import MiniCluster

    class _DebtCluster(MiniCluster):
        def __init__(self, root):
            from pegasus_tpu.meta import MetaServer
            from pegasus_tpu.replication.replica_stub import ReplicaStub
            from pegasus_tpu.rpc.transport import RpcConnection, RpcServer

            self.meta = MetaServer(str(root / "meta.json"),
                                   fd_grace_seconds=60)
            self.rpc = RpcServer().start()
            for code, fn in self.meta.rpc_handlers().items():
                self.rpc.register(code, fn)
            self.meta_addr = f"{self.rpc.address[0]}:{self.rpc.address[1]}"
            self.stubs = [
                ReplicaStub(str(root / f"n{i}"), [self.meta_addr],
                            options_factory=lambda: EngineOptions(
                                backend="cpu", memtable_bytes=512,
                                l0_compaction_trigger=32)).start(0.2)
                for i in range(3)]
            self._conn = RpcConnection(self.rpc.address)

    c = _DebtCluster(tmp_path)
    yield c
    c.stop()


def _wait_for_beacon_debt(caller, min_l0, deadline_s=20.0):
    """Wait until the meta snapshot carries beacon-folded compact debt."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        state = caller.meta_state()
        if state:
            by_gpid = {}
            for states in state.get("replica_states", {}).values():
                for gpid, st in states.items():
                    debt = st.get("compact") or {}
                    by_gpid[gpid] = max(by_gpid.get(gpid, 0),
                                        debt.get("l0_files", 0))
            if by_gpid and max(by_gpid.values()) >= min_l0:
                return state, by_gpid
        time.sleep(0.2)
    raise AssertionError("beacons never carried the compaction debt")


def test_onebox_decisions_end_to_end(cluster):
    """The acceptance shape: a read-hot partition defers, a debt-driving
    partition promotes, reasons ride the whole path — fold -> delivery ->
    engine token -> compact-sched-status -> shell compact_sched."""
    cli = cluster.create("sched", partitions=4)
    for i in range(160):
        cli.set(b"user%05d" % i, b"f0", b"v" * 64)
    caller = ClusterCaller([cluster.meta_addr])
    try:
        state, by_gpid = _wait_for_beacon_debt(caller, min_l0=2)
        # the per-partition debt gauges are live on the serving nodes
        gauge_hits = 0
        for stub in cluster.stubs:
            snap = json.loads(caller.remote_command(
                stub.address, "perf-counters-by-prefix",
                ["engine.compact."]))
            gauge_hits += sum(1 for k in snap
                              if k.endswith(".l0_files") and snap[k] > 0)
        assert gauge_hits > 0, "debt gauges must be exported per beacon"
        app = state["apps"]["sched"]
        gpids = sorted(f"{app['app_id']}.{pc['pidx']}"
                       for pc in app["partitions"])
        hot = max(by_gpid, key=lambda g: by_gpid[g])   # confirmed-hot pin
        debty = [g for g in gpids if g != hot and by_gpid.get(g, 0) >= 2]
        assert debty, "workload must spread debt over >1 partition"
        report = run_scheduler_tick(
            [cluster.meta_addr], hot_gpids={hot}, slow_count=0,
            caller=caller,
            knobs={"urgent_l0": 2, "max_urgent_per_node": 8, "ttl_s": 30.0,
                   "max_device": 2})
        assert not report["errors"], report["errors"]
        assert report["decisions"][hot]["policy"] == "defer"
        assert report["decisions"][hot]["reasons"] == ["hot_read"]
        for g in debty:
            assert report["decisions"][g]["policy"] == "urgent"
            assert "l0_debt" in report["decisions"][g]["reasons"]
        assert report["delivered"], "decisions must reach the nodes"
        # the tokens landed in the engines, reasons intact
        seen = {}
        for stub in cluster.stubs:
            out = json.loads(caller.remote_command(
                stub.address, "compact-sched-status", []))
            for gpid, st in out.items():
                seen.setdefault(gpid, []).append(st)
        assert set(seen) == set(gpids)
        hot_primary = report["decisions"][hot]["node"]
        for st in seen[hot]:
            if st["node"] == hot_primary:
                # only the primary holds the residency pin the defer
                # protects — it alone receives the defer token
                assert st["policy"] == "defer"
                assert st["reasons"] == ["hot_read"]
                assert st["expires_in_s"] > 0
            else:
                assert st["policy"] == "normal"
                assert "defer_primary_only" in st["reasons"]
        for g in debty:
            assert all(st["policy"] == "urgent" for st in seen[g])
        # the delivered cap armed the node device gate
        assert SCHED_GATE.state()["max"] == 2
        SCHED_GATE.set_max(0)  # restore the process-wide default
        # shell surface: one line per gpid with the reasons visible
        from pegasus_tpu.shell.main import Shell

        out = io.StringIO()
        sh = Shell([cluster.meta_addr], out=out)
        sh.cmd_compact_sched([])
        sh.pool.close()
        text = out.getvalue()
        assert "hot_read" in text and "defer" in text and "urgent" in text
        # disabling the scheduler = tokens lapse back to engine-local
        stub0 = cluster.stubs[0]
        caller.remote_command(
            stub0.address, "compact-sched-policy",
            [json.dumps({"ttl_s": 0.05,
                         "decisions": {g: {"policy": "normal"}
                                       for g in gpids}})])
        time.sleep(0.1)
        out = json.loads(caller.remote_command(
            stub0.address, "compact-sched-status", []))
        assert all(st["policy"] == "normal" for st in out.values())
    finally:
        caller.close()
    cli.close()


def test_collector_scheduler_status_surface(cluster, monkeypatch):
    """PEGASUS_SCHED=1 arms the loop inside the CollectorApp; its
    compact-sched-status command and collector-info expose the rounds."""
    from pegasus_tpu.runtime.config import Config
    from pegasus_tpu.runtime.service_app import CollectorApp

    cli = cluster.create("schedc", partitions=2)
    for i in range(40):
        cli.set(b"c%04d" % i, b"f", b"v" * 64)
    monkeypatch.setenv("PEGASUS_SCHED", "1")
    monkeypatch.setenv("PEGASUS_SCHED_INTERVAL_S", "0.2")
    cfg = Config(text=(f"[pegasus.server]\n"
                       f"meta_servers = {cluster.meta_addr}\n"
                       f"[apps.collector]\ntype = collector\n"))
    app = CollectorApp("collector", cfg, "apps.collector")
    app.start()
    try:
        assert app.scheduler is not None
        deadline = time.monotonic() + 20
        while not app.scheduler.status().get("decisions"):
            assert time.monotonic() < deadline, "no scheduler round ran"
            time.sleep(0.1)
        caller = ClusterCaller([cluster.meta_addr])
        try:
            out = json.loads(caller.remote_command(
                app.address, "compact-sched-status", []))
            assert out["enabled"] is True and out["decisions"]
            info = json.loads(caller.remote_command(
                app.address, "collector-info", []))
            assert info["compact_sched"]["decisions"]
        finally:
            caller.close()
    finally:
        app.stop()
    cli.close()


def test_onebox_placement_and_autotune_end_to_end(cluster, monkeypatch,
                                                  tmp_path):
    """ISSUE 14 acceptance: the fold's (when, where) pairs ride the live
    surfaces — service budget scraped over offload-status, placement
    delivered with the policy tokens, visible (with the
    `offload_budget` reason) through compact-sched-status, and the
    autotune report emitted when the feedback tuner is armed."""
    from pegasus_tpu.replication.compact_offload import \
        CompactOffloadService

    svc = CompactOffloadService(str(tmp_path / "svc"),
                                backend="cpu").start()
    cli = cluster.create("placed", partitions=4)
    try:
        for i in range(160):
            cli.set(b"user%05d" % i, b"f0", b"v" * 64)
        caller = ClusterCaller([cluster.meta_addr])
        try:
            _wait_for_beacon_debt(caller, min_l0=2)
            monkeypatch.setenv("PEGASUS_OFFLOAD_SERVICES", svc.address)
            monkeypatch.setenv("PEGASUS_SCHED_AUTOTUNE", "1")
            tune_state = {}
            report = run_scheduler_tick(
                [cluster.meta_addr], caller=caller, tune_state=tune_state,
                knobs={"urgent_l0": 2, "max_urgent_per_node": 8,
                       "ttl_s": 30.0, "max_device": 0})
            assert not report["errors"], report["errors"]
            assert report["services"][svc.address]["free_slots"] > 0
            placed = [g for g, d in report["decisions"].items()
                      if d["where"] == svc.address]
            assert placed, "free budget but nothing placed"
            for g in placed:
                assert "offload_budget" in report["decisions"][g]["reasons"]
            # budget-bounded: never more placements than free slots
            assert len(placed) <= svc.max_concurrent
            assert "autotune" in report  # armed -> report present
            # the placement landed on the serving engines, lease-held
            seen = {}
            for stub in cluster.stubs:
                out = json.loads(caller.remote_command(
                    stub.address, "compact-sched-status", []))
                for gpid, st in out.items():
                    seen.setdefault(gpid, []).append(st)
            for g in placed:
                assert any(st["offload"] == svc.address
                           for st in seen[g]), seen[g]
            # lease expiry reverts to local: deliver where with a tiny ttl
            stub0 = cluster.stubs[0]
            caller.remote_command(
                stub0.address, "compact-sched-policy",
                [json.dumps({"ttl_s": 0.05, "decisions": {
                    g: {"policy": "normal", "where": svc.address}
                    for g in report["decisions"]}})])
            time.sleep(0.1)
            out = json.loads(caller.remote_command(
                stub0.address, "compact-sched-status", []))
            assert all(st["offload"] == "" for st in out.values())
        finally:
            caller.close()
    finally:
        cli.close()
        svc.stop()
