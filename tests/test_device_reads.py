"""Device-served reads: HBM-resident point lookups (ISSUE 7) and
fence-bounded range reads (ISSUE 19).

Acceptance: device-vs-host read BYTE-IDENTITY on cpu — identical
ReadResponse/MultiGetResponse wire bytes for mixed hit/miss/TTL-expired/
tombstoned keys across flushed+compacted state, including a mid-read
fallback (wedge/raise in the device probe) — plus the fence index
unit-level contract, the HBM residency gauges, and the collector's
read-residency drive. The range half extends the same contract to
multi_get ranges / sortkey_count / scanner batches (forward, reverse,
inclusivity, limits, split-pmask, boundary-dense single-hashkey runs)
and to the `read.range` fail point. The read-lane chaos/breaker-
isolation cases live in tests/test_lane_guard.py next to the compact
lane's.
"""

import threading

import numpy as np
import pytest

from pegasus_tpu.base import key_schema
from pegasus_tpu.engine.db import EngineOptions, LsmEngine
from pegasus_tpu.engine.server_impl import PegasusServer
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.messages import Status
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.lane_guard import READ_LANE_GUARD, LaneGuardConfig
from pegasus_tpu.runtime.perf_counters import counters

NOW = 1000
V = b"\x82" + b"\x00" * 12  # v2 value header, no TTL


@pytest.fixture
def read_guard():
    """Deterministic read-lane config; fail points armed; restored after
    (READ_LANE_GUARD is process-wide)."""
    saved = READ_LANE_GUARD.config
    READ_LANE_GUARD.config = LaneGuardConfig(
        deadline_s=30.0, max_retries=1, backoff_base_s=0.001,
        backoff_max_s=0.002, breaker_threshold=99, breaker_cooldown_s=60.0)
    READ_LANE_GUARD.probe_fn = lambda: True
    READ_LANE_GUARD.reset()
    fp.setup()
    yield READ_LANE_GUARD
    fp.teardown()
    READ_LANE_GUARD.config = saved
    READ_LANE_GUARD.probe_fn = None
    READ_LANE_GUARD.reset()


def _engine_opts(device_reads):
    return EngineOptions(backend="tpu", device_reads=device_reads,
                         device_read_min_batch=1, l0_compaction_trigger=100)


def _load_mixed(engine):
    """Flushed+compacted L1, a newer L0 with shadowing tombstones, live
    memtable records, TTL-expired and tombstoned rows at every layer."""
    for i in range(40):
        engine.put(key_schema.generate_key(b"h%d" % (i % 3), b"s%03d" % i),
                   V + b"v%d" % i)
    engine.put(key_schema.generate_key(b"h0", b"expired"), V + b"old",
               expire_ts=NOW - 100)
    engine.put(key_schema.generate_key(b"h0", b"gone"), V + b"dead")
    engine.flush()
    engine.compact()                 # -> L1
    engine.delete(key_schema.generate_key(b"h0", b"gone"))     # tombstone
    engine.put(key_schema.generate_key(b"h1", b"s001"), V + b"newer")
    for i in range(40, 50):
        engine.put(key_schema.generate_key(b"h%d" % (i % 3), b"s%03d" % i),
                   V + b"v%d" % i)
    engine.flush()                   # -> newer L0 shadowing L1
    engine.put(key_schema.generate_key(b"h2", b"memonly"), V + b"mem")


def _prime_all(engine):
    """Deterministic residency for tests: the flush-time prime is
    fire-and-forget, so force every SST's upload inline."""
    with engine._lock:
        ssts = engine._all_ssts_locked()
    for sst in ssts:
        engine._device_run_budgeted(sst)
    return ssts


def _query_keys():
    keys = [key_schema.generate_key(b"h%d" % (i % 3), b"s%03d" % i)
            for i in range(55)]                        # hits + misses
    keys += [key_schema.generate_key(b"h0", b"expired"),
             key_schema.generate_key(b"h0", b"gone"),
             key_schema.generate_key(b"h2", b"memonly"),
             key_schema.generate_key(b"zz", b"missing")]
    return keys


# ------------------------------------------------------ engine-level identity


def test_get_batch_byte_identical_to_single_gets(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        _load_mixed(eng)
        ssts = _prime_all(eng)
        assert any(s.device_index is not None for s in ssts)
        keys = _query_keys()
        before = counters.number("read.device.lookup_count").value()
        batch = eng.get_batch(keys, now=NOW)
        assert batch == [eng.get(k, now=NOW) for k in keys]
        # the device path actually served (not a silent host walk)
        assert counters.number("read.device.lookup_count").value() > before
        assert counters.number("read.device.hits").value() > 0
    finally:
        eng.close()


def test_fence_index_built_as_prime_byproduct(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        _load_mixed(eng)
        for sst in _prime_all(eng):
            dr = sst.device_index
            if dr is None:
                continue
            assert dr.fence_len > 0 and dr.fence_step > 0
            assert dr.fence_len * dr.fence_step >= dr.n
            fence = np.asarray(dr.fence)
            assert len(fence) == dr.fence_len
            assert bool(np.all(fence[1:] >= fence[:-1]))  # sorted samples
    finally:
        eng.close()


def test_lookup_batch_exact_rows(tmp_path):
    """The kernel's row indexes equal the host binary search's for every
    present key, and -1 for absent/truncating-prefix queries."""
    from pegasus_tpu.ops.device_lookup import lookup_batch

    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        _load_mixed(eng)
        ssts = [s for s in _prime_all(eng) if s.device_index is not None]
        assert ssts
        sst = max(ssts, key=lambda s: s.n)
        block = sst.block()
        present = [block.key(i) for i in range(0, block.n, 3)]
        absent = [b"\x00\x07nothere" + b"x" * 9,
                  present[0] + b"longer-than-any-resident-key-window" * 2]
        rows = lookup_batch(sst.device_index, present + absent)
        for k, r in zip(present, rows[: len(present)]):
            assert int(r) == sst.find(k)
        assert all(int(r) == -1 for r in rows[len(present):])
    finally:
        eng.close()


# ------------------------------------------------------ server wire identity


def _server_pair(tmp_path, load=_load_mixed):
    pair = []
    for name, dev in (("on", True), ("off", False)):
        srv = PegasusServer(str(tmp_path / name), options=_engine_opts(dev))
        load(srv.engine)
        _prime_all(srv.engine)
        pair.append(srv)
    return pair


def _assert_wire_identical(srv_on, srv_off):
    for k in _query_keys():
        assert codec.encode(srv_on.on_get(k, now=NOW)) == \
            codec.encode(srv_off.on_get(k, now=NOW)), k
    req = msg.MultiGetRequest(
        hash_key=b"h0",
        sort_keys=[b"s%03d" % i for i in range(0, 50, 3)]
        + [b"expired", b"gone", b"nope"])
    assert codec.encode(srv_on.on_multi_get(req, now=NOW)) == \
        codec.encode(srv_off.on_multi_get(req, now=NOW))


def test_responses_byte_identical_device_vs_host(tmp_path, read_guard):
    """Acceptance: identical ReadResponse/MultiGetResponse bytes for
    mixed hit/miss/TTL-expired/tombstoned keys across flushed+compacted
    state, device-served vs host-served."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        before = counters.number("read.device.lookup_count").value()
        _assert_wire_identical(srv_on, srv_off)
        assert counters.number("read.device.lookup_count").value() > before
        assert read_guard.state()["fallbacks"] == 0
    finally:
        srv_on.close()
        srv_off.close()


def test_responses_byte_identical_through_mid_read_fallback(tmp_path,
                                                            read_guard):
    """Acceptance: the fallback path serves the same bytes — a raising
    device probe (retry -> host fallback) and a wedged one (deadline
    abandon -> host fallback) both leave responses identical."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        fp.cfg("read.device", "raise(transient probe error)")
        _assert_wire_identical(srv_on, srv_off)
        st = read_guard.state()
        assert st["fallbacks"] >= 1 and st["retries"] >= 1
        fp.cfg("read.device", "off()")

        # the raise storm walked the consecutive-failure count past any
        # threshold; close the breaker so the wedge phase probes again
        read_guard.reset()
        read_guard.config.deadline_s = 0.3
        fp.cfg("read.device", "1*sleep(1500)")
        k = key_schema.generate_key(b"h0", b"s000")
        assert codec.encode(srv_on.on_get(k, now=NOW)) == \
            codec.encode(srv_off.on_get(k, now=NOW))
        st = read_guard.state()
        assert st["deadline_abandons"] == 1
        assert "read.device" in st["last_failure"]["error"]  # attribution
    finally:
        srv_on.close()
        srv_off.close()


def test_concurrent_gets_coalesce_and_match(tmp_path, read_guard):
    """Concurrent point reads group through the server's coalescer into
    device batches; every response still matches the host-served twin."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        expected = {k: codec.encode(srv_off.on_get(k, now=NOW))
                    for k in _query_keys()}
        errors = []

        def worker(t):
            try:
                for i, (k, want) in enumerate(expected.items()):
                    if (i + t) % 3 == 0:
                        assert codec.encode(srv_on.on_get(k, now=NOW)) == want
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # groups actually formed (p99 of the coalesced batch size > 1
        # would be flaky on a loaded box; the size histogram existing and
        # the engine's batch span firing is the mechanical assertion)
        assert counters.percentile("read.batch.size").percentiles()["p50"] >= 1
    finally:
        srv_on.close()
        srv_off.close()


# ------------------------------------------------------------- HBM gauges


def test_hbm_residency_gauges(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        budget0 = counters.number("engine.hbm.budget_bytes").value()
        assert budget0 >= eng.opts.device_cache_bytes  # registered at init
        bytes0 = counters.number("engine.hbm.resident_bytes").value()
        ssts0 = counters.number("engine.hbm.resident_ssts").value()
        _load_mixed(eng)
        primed = [s for s in _prime_all(eng) if s._device_budgeted]
        assert primed
        assert counters.number("engine.hbm.resident_bytes").value() \
            >= bytes0 + sum(s._device_run.nbytes() for s in primed)
        assert counters.number("engine.hbm.resident_ssts").value() \
            >= ssts0 + len(primed)
        st = eng.stats()
        assert st["device_resident_ssts"] == len(primed)
        assert st["device_resident_bytes"] > 0
        # compaction consumes the inputs: accounting releases, never
        # underflows
        eng.compact()
        assert eng.stats()["device_resident_bytes"] >= 0
    finally:
        eng.close()
    # close() drops this engine's contribution from the process gauges
    assert counters.number("engine.hbm.budget_bytes").value() <= budget0


def test_set_read_residency_primes_ssts(tmp_path):
    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        _load_mixed(eng)
        assert eng.stats()["read_hot"] is False
        eng.set_read_residency(True)
        assert eng.stats()["read_hot"] is True
        # primes ride the pipeline pool fire-and-forget; wait bounded
        import time

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with eng._lock:
                ssts = eng._all_ssts_locked()
            if any(s.device_index is not None for s in ssts):
                break
            time.sleep(0.02)
        assert any(s.device_index is not None for s in ssts)
        eng.set_read_residency(False)
        assert eng.stats()["read_hot"] is False
    finally:
        eng.close()


def test_read_hot_claims_reserved_budget_headroom(tmp_path):
    """The residency flag is a real budget input: a cold partition's
    primes stop at 7/8 of the HBM budget (reserved headroom), a read-hot
    pin may fill it."""
    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        eng._prime_async = lambda sst: None  # deterministic: prime inline
        for batch in range(2):
            for i in range(20):
                eng.put(key_schema.generate_key(b"h%d" % batch,
                                                b"s%03d" % i), V + b"v")
            eng.flush()
        with eng._lock:
            ssts = eng._all_ssts_locked()
        assert len(ssts) >= 2
        assert eng._device_run_budgeted(ssts[0]) is not None
        used = eng._device_cache_used
        assert used > 8
        # budget sized so only the FULL budget admits the second run
        eng.opts.device_cache_bytes = used + 1
        assert not ssts[1]._device_budgeted
        eng._device_run_budgeted(ssts[1])
        assert not ssts[1]._device_budgeted  # cold: stopped at 7/8
        eng.set_read_residency(True)
        assert eng._device_run_budgeted(ssts[1]) is not None
        assert ssts[1]._device_budgeted      # hot: headroom claimed
    finally:
        eng.close()


# --------------------------------------------- collector residency drive


def test_collector_hotkey_verdict_drives_read_residency():
    """A confirmed read-hotspot verdict turns the partition's device
    read residency ON via the set-read-residency remote command; the
    partition calming turns it OFF — the loop that decides which
    partitions' SSTs stay HBM-resident."""
    from pegasus_tpu.collector.info_collector import InfoCollector

    ic = InfoCollector([], interval_seconds=3600, hotkey_rounds=2)
    calls = []

    def fake_rc(node, command, args):
        calls.append((node, command, list(args)))
        if command == "detect_hotkey":
            return {"start": "started",
                    "query": "hotkey: user42",
                    "stop": "stopped"}[args[2]]
        return "read residency %s for %s" % (args[1], args[0])

    ic.remote_command = fake_rc
    primaries = {0: "n1:1", 1: "n1:1", 2: "n1:1", 3: "n1:1"}
    read_qps = {0: 500.0, 1: 1.0, 2: 1.0, 3: 1.0}
    for _ in range(ic.hotkey_rounds):
        ic.drive_hotkey_loop("t", 7, [0], primaries, read_qps, {})
    assert ("n1:1", "set-read-residency", ["7.0", "on"]) in calls
    assert ("t", 0) in ic.read_residency
    assert counters.number(
        "collector.app.t.hotkey.0.device_resident").value() == 1
    # partition calms, but the release RPC drops: bookkeeping must stay
    # so the NEXT calm round resends the off (a dropped RPC cannot leave
    # the server's residency flag hot forever)
    from pegasus_tpu.rpc.transport import RpcError

    fail_next = [True]
    real_rc = ic.remote_command

    def flaky_rc(node, command, args):
        if command == "set-read-residency" and fail_next[0]:
            fail_next[0] = False
            raise RpcError(7, "connection refused")
        return real_rc(node, command, args)

    ic.remote_command = flaky_rc
    ic.drive_hotkey_loop("t", 7, [], primaries, read_qps, {})
    assert ("t", 0) in ic.read_residency  # failed release kept for retry
    ic.drive_hotkey_loop("t", 7, [], primaries, read_qps, {})
    assert ("n1:1", "set-read-residency", ["7.0", "off"]) in calls
    assert ("t", 0) not in ic.read_residency
    assert counters.number(
        "collector.app.t.hotkey.0.device_resident").value() == 0


def test_replica_stub_set_read_residency_command(tmp_path):
    """The remote-command handler flips the engine flag (unit-level: a
    stub-shaped object with one replica)."""
    from pegasus_tpu.replication.replica_stub import ReplicaStub

    class _Rep:
        pass

    srv = PegasusServer(str(tmp_path / "db"),
                        options=_engine_opts(device_reads=True))
    try:
        stub = ReplicaStub.__new__(ReplicaStub)
        stub._lock = threading.Lock()
        rep = _Rep()
        rep.server = srv
        stub._replicas = {(1, 0): rep}
        out = stub._cmd_set_read_residency(["1.0", "on"])
        assert "on" in out
        assert srv.engine.stats()["read_hot"] is True
        out = stub._cmd_set_read_residency(["1.0", "off"])
        assert "off" in out
        assert srv.engine.stats()["read_hot"] is False
        assert "usage" in stub._cmd_set_read_residency(["1.0"])
        assert "no replica" in stub._cmd_set_read_residency(["9.9", "on"])
    finally:
        srv.close()


# ------------------------------------------- range reads (ISSUE 19)


DENSE_P = b"p" * 9  # long shared sortkey prefix: composite keys agree
#                     deep into the packed lanes (all-equal-first-lane)


def _load_dense(engine):
    """The range-read edge loader: ONE hash key whose sortkeys share a
    long prefix, so every packed first lane (and several more) is EQUAL
    and only deep lanes / the klen tiebreak discriminate — plus
    boundary-dense neighbors (keys differing in the last byte, and
    proper-prefix pairs exercising the klen tiebreak), TTL-expired and
    tombstoned rows, split across L1 / L0 / memtable."""
    for i in range(120):
        engine.put(key_schema.generate_key(b"hx", DENSE_P + b"%04d" % i),
                   V + b"d%d" % i)
    # proper-prefix pair: same lanes where they overlap, klen decides
    engine.put(key_schema.generate_key(b"hx", DENSE_P + b"0050x"), V + b"px")
    engine.put(key_schema.generate_key(b"hx", DENSE_P + b"expired"),
               V + b"old", expire_ts=NOW - 100)
    engine.put(key_schema.generate_key(b"hx", DENSE_P + b"gone"), V + b"dead")
    engine.flush()
    engine.compact()                 # -> L1
    engine.delete(key_schema.generate_key(b"hx", DENSE_P + b"gone"))
    engine.put(key_schema.generate_key(b"hx", DENSE_P + b"0001"), V + b"new")
    for i in range(120, 150):
        engine.put(key_schema.generate_key(b"hx", DENSE_P + b"%04d" % i),
                   V + b"d%d" % i)
    engine.flush()                   # -> newer L0 shadowing L1
    engine.put(key_schema.generate_key(b"hx", DENSE_P + b"zzmem"), V + b"mem")


def _range_combos(prefix=b""):
    """(start, stop, start_inclusive, stop_inclusive, reverse,
    max_kv_count) sweeps: open/bounded/inverted/absent bounds, both
    inclusivities, both directions, limited and unlimited."""
    combos = []
    for start, stop in ((b"", b""), (b"", prefix + b"0047"),
                        (prefix + b"0010", prefix + b"0047"),
                        (prefix + b"0010", b""),
                        (prefix + b"0046x", prefix + b"0123"),  # absent bounds
                        (prefix + b"0050", prefix + b"0050"),   # point range
                        (prefix + b"0090", prefix + b"0010")):  # inverted
        for si in (True, False):
            for ti in (True, False):
                for rev in (False, True):
                    for maxn in (0, 5):
                        combos.append((start, stop, si, ti, rev, maxn))
    return combos


def _assert_range_wire_identical(srv_on, srv_off, hash_keys, prefix=b""):
    for hk in hash_keys:
        assert codec.encode(srv_on.on_sortkey_count(hk, now=NOW)) == \
            codec.encode(srv_off.on_sortkey_count(hk, now=NOW)), hk
        for start, stop, si, ti, rev, maxn in _range_combos(prefix):
            req = msg.MultiGetRequest(
                hash_key=hk, sort_keys=[], max_kv_count=maxn,
                start_sortkey=start, stop_sortkey=stop,
                start_inclusive=si, stop_inclusive=ti, reverse=rev)
            assert codec.encode(srv_on.on_multi_get(req, now=NOW)) == \
                codec.encode(srv_off.on_multi_get(req, now=NOW)), \
                (hk, start, stop, si, ti, rev, maxn)
    assert _scan_wire(srv_on) == _scan_wire(srv_off)
    assert _scan_wire(srv_on, batch_size=7) == \
        _scan_wire(srv_off, batch_size=7)


def _scan_wire(srv, **req_kw):
    """Drain a full scanner session into normalized wire blobs (the
    context id is a server-local session handle, not wire contract —
    normalized to its completed/continuing sign)."""
    out = []
    resp = srv.on_get_scanner(msg.GetScannerRequest(**req_kw), now=NOW)
    for _ in range(10_000):
        out.append(codec.encode(msg.ScanResponse(
            error=resp.error, kvs=resp.kvs,
            context_id=min(resp.context_id, 0), app_id=resp.app_id,
            partition_index=resp.partition_index, server=resp.server)))
        if resp.error != Status.OK or resp.context_id < 0:
            return out
        resp = srv.on_scan(msg.ScanRequest(resp.context_id), now=NOW)
    raise AssertionError("scanner session never completed")


def test_range_responses_byte_identical_device_vs_host(tmp_path, read_guard):
    """Acceptance (ISSUE 19): identical MultiGetResponse/CountResponse/
    ScanResponse bytes for range reads over mixed hit/miss/TTL-expired/
    tombstoned state — and the forward queries actually took the device
    path while reverse ones were counted host-side."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        dev0 = counters.number("read.range.device_count").value()
        rev0 = counters.number("read.range.reverse_host_count").value()
        rows0 = counters.number("read.range.rows").value()
        _assert_range_wire_identical(srv_on, srv_off,
                                     [b"h0", b"h1", b"h2", b"zz"],
                                     prefix=b"s0")
        assert counters.number("read.range.device_count").value() > dev0
        assert counters.number("read.range.reverse_host_count").value() > rev0
        assert counters.number("read.range.rows").value() > rows0
        assert read_guard.state()["fallbacks"] == 0
    finally:
        srv_on.close()
        srv_off.close()


def test_range_identity_dense_single_hashkey(tmp_path, read_guard):
    """The boundary-dense edge: one hash key, equal first lanes
    everywhere, proper-prefix sortkeys, shadowing layers — the fence
    degenerates to near-equal samples and only deep lanes / klen
    discriminate."""
    srv_on, srv_off = _server_pair(tmp_path, load=_load_dense)
    try:
        dev0 = counters.number("read.range.device_count").value()
        _assert_range_wire_identical(srv_on, srv_off, [b"hx"],
                                     prefix=DENSE_P)
        assert counters.number("read.range.device_count").value() > dev0
    finally:
        srv_on.close()
        srv_off.close()


def test_range_identity_under_split_pmask(tmp_path, read_guard):
    """Post-split state (partition_mask > 0): the scanner's filter-free
    fast path must correctly NOT engage (rows need the per-row partition
    hash check) and every response stays identical to the host twin."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        for srv in (srv_on, srv_off):
            srv.engine.opts.partition_mask = 1
        _assert_range_wire_identical(srv_on, srv_off, [b"h0", b"h1"],
                                     prefix=b"s0")
    finally:
        srv_on.close()
        srv_off.close()


def test_range_responses_identical_through_mid_read_fallback(tmp_path,
                                                             read_guard):
    """The `read.range` fail point: a raising interval resolve (retry ->
    host fallback) and a wedged one (deadline abandon -> host fallback)
    both serve identical bytes, and the failed attempts land in
    host_count, not device_count."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        req = msg.MultiGetRequest(hash_key=b"h0", sort_keys=[],
                                  start_sortkey=b"s000",
                                  stop_sortkey=b"s040")
        fp.cfg("read.range", "raise(transient resolve error)")
        dev0 = counters.number("read.range.device_count").value()
        host0 = counters.number("read.range.host_count").value()
        assert codec.encode(srv_on.on_multi_get(req, now=NOW)) == \
            codec.encode(srv_off.on_multi_get(req, now=NOW))
        st = read_guard.state()
        assert st["fallbacks"] >= 1 and st["retries"] >= 1
        assert counters.number("read.range.device_count").value() == dev0
        assert counters.number("read.range.host_count").value() > host0
        fp.cfg("read.range", "off()")

        # close the breaker the raise storm walked up, then wedge once:
        # the 0.3 s deadline abandons the kernel mid-flight
        read_guard.reset()
        read_guard.config.deadline_s = 0.3
        fp.cfg("read.range", "1*sleep(1500)")
        assert codec.encode(srv_on.on_multi_get(req, now=NOW)) == \
            codec.encode(srv_off.on_multi_get(req, now=NOW))
        st = read_guard.state()
        assert st["deadline_abandons"] == 1
        assert "read.range" in st["last_failure"]["error"]  # attribution
    finally:
        srv_on.close()
        srv_off.close()


def test_concurrent_ranges_coalesce_and_match(tmp_path, read_guard):
    """Concurrent range reads group through the server's range coalescer
    into one scan_range_batch; every response still matches the
    host-served twin."""
    srv_on, srv_off = _server_pair(tmp_path)
    try:
        reqs = []
        for i in range(0, 40, 4):
            reqs.append(msg.MultiGetRequest(
                hash_key=b"h%d" % (i % 3), sort_keys=[],
                start_sortkey=b"s%03d" % i, stop_sortkey=b"s%03d" % (i + 9)))
        expected = [codec.encode(srv_off.on_multi_get(r, now=NOW))
                    for r in reqs]
        batch0 = counters.number("read.range.batch_count").value()
        errors = []

        def worker(t):
            try:
                for i, (r, want) in enumerate(zip(reqs, expected)):
                    if (i + t) % 2 == 0:
                        assert codec.encode(
                            srv_on.on_multi_get(r, now=NOW)) == want
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # grouping cut the engine calls below the request count (30 range
        # reads issued; followers ride the leader's batch), and the batch
        # size histogram recorded the groups
        served = sum(1 for t in range(6) for i in range(len(reqs))
                     if (i + t) % 2 == 0)
        assert counters.number("read.range.batch_count").value() - batch0 \
            <= served
        assert counters.percentile(
            "read.range.batch.size").percentiles()["p50"] >= 1
    finally:
        srv_on.close()
        srv_off.close()


def test_scan_context_eviction_closes_iterator():
    """An evicted or cleared scan session releases its engine snapshot
    NOW — iterator.close() fires the generator's finally (where the
    range iterators flush read.range.rows) instead of waiting on GC."""
    from pegasus_tpu.engine.scan_context import (ScanContext,
                                                 ScanContextCache)

    closed = []

    def gen(tag):
        try:
            yield tag
        finally:
            closed.append(tag)

    cache = ScanContextCache(max_contexts=2)
    ctxs = [ScanContext(gen(i), None) for i in range(3)]
    for c in ctxs:
        next(c.iterator)            # enter the body so finally is armed
    ids = [cache.put(c) for c in ctxs]
    assert closed == [0]            # LRU overflow closed the oldest
    cache.remove(ids[1])
    assert closed == [0, 1]         # explicit clear_scanner closes too
    assert cache.fetch(ids[2]) is ctxs[2]
    assert closed == [0, 1]         # the live session untouched


def test_range_batch_intervals_match_host_lower_bound(tmp_path):
    """Unit contract of the kernel: for arbitrary (start, stop) byte
    strings — present, absent, open, inverted, longer than the packed
    lane window — the device interval equals the host lower_bound pair
    (clamped to hi >= lo)."""
    from pegasus_tpu.ops.device_lookup import range_batch

    eng = LsmEngine(str(tmp_path / "db"), _engine_opts(device_reads=True))
    try:
        _load_dense(eng)
        ssts = [s for s in _prime_all(eng) if s.device_index is not None]
        assert ssts
        sst = max(ssts, key=lambda s: s.n)
        block = sst.block()
        k = [block.key(i) for i in range(block.n)]
        ranges = [(b"", None), (b"", k[3]), (k[2], k[-2]),
                  (k[5] + b"\x00", k[9] + b"zz"),        # absent bounds
                  (k[-1] + b"\xff", None),               # past the end
                  (k[9], k[2]),                          # inverted
                  (k[4], k[4]),                          # empty point
                  (k[0] + b"longer-than-any-lane-window" * 3, None)]
        iv = range_batch(sst.device_index, ranges)
        for (start, stop), (lo, hi) in zip(ranges, iv):
            want_lo = sst.lower_bound(start)
            want_hi = sst.n if stop is None else sst.lower_bound(stop)
            assert (int(lo), int(hi)) == (want_lo, max(want_hi, want_lo)), \
                (start, stop)
    finally:
        eng.close()
