"""Concurrency stress: parallel clients over real sockets.

The reference has no in-tree race detector; correctness under concurrency
is tested behaviorally (SURVEY §5.2 — sanitizer builds + kill test). This
tier drives many client threads at one onebox and asserts the atomicity
contracts PacificA's per-partition write serialization must provide:
incr is atomic, check_and_set admits exactly one winner, and multi_put
batches are observed whole.
"""

import threading

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.rpc.messages import CasCheckType, Status
from tests.test_satellites import MiniCluster

N_THREADS = 8
N_OPS = 25


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniCluster(tmp_path_factory.mktemp("conc"), n_nodes=3)
    yield c
    c.stop()


def run_parallel(fn):
    errs = []
    threads = []
    for t in range(N_THREADS):
        def body(tid=t):
            try:
                # one client per thread: separate sockets, real contention
                fn(tid)
            except Exception as e:  # noqa: BLE001 - collected and asserted
                errs.append(e)

        threads.append(threading.Thread(target=body))
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert not errs, errs[:3]


def test_concurrent_incr_is_atomic(cluster):
    cluster.create("conc_incr", partitions=2).close()

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_incr"))
        for _ in range(N_OPS):
            cli.incr(b"shared", b"counter", 1)
        cli.close()

    run_parallel(body)
    cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_incr"))
    assert cli.get(b"shared", b"counter") == str(N_THREADS * N_OPS).encode()
    cli.close()


def test_concurrent_cas_single_winner_per_round(cluster):
    cluster.create("conc_cas", partitions=2).close()
    winners = [[] for _ in range(N_OPS)]

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_cas"))
        for rnd in range(N_OPS):
            r = cli.check_and_set(b"lock", b"r%d" % rnd,
                                  CasCheckType.VALUE_NOT_EXIST, b"",
                                  b"r%d" % rnd, b"owner%d" % tid)
            if r.error == Status.OK:
                winners[rnd].append(tid)
        cli.close()

    run_parallel(body)
    for rnd, w in enumerate(winners):
        assert len(w) == 1, f"round {rnd}: winners {w}"
    cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_cas"))
    for rnd, w in enumerate(winners):
        assert cli.get(b"lock", b"r%d" % rnd) == b"owner%d" % w[0]
    cli.close()


def test_concurrent_multi_put_reads_are_whole(cluster):
    """A reader never observes a half-applied multi_put batch."""
    cluster.create("conc_mp", partitions=1).close()
    stop = threading.Event()
    bad = []

    def writer():
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_mp"))
        for i in range(60):
            cli.multi_set(b"row", {b"a": b"g%d" % i, b"b": b"g%d" % i,
                                   b"c": b"g%d" % i})
        cli.close()
        stop.set()

    def reader():
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_mp"))
        while not stop.is_set():
            _, kvs = cli.multi_get(b"row")
            if kvs and len(set(kvs.values())) != 1:
                bad.append(dict(kvs))
        cli.close()

    ths = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader) for _ in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    assert not bad, bad[:2]


def test_concurrent_disjoint_writers_no_interference(cluster):
    cluster.create("conc_disj", partitions=4).close()

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_disj"))
        for i in range(N_OPS):
            cli.set(b"t%d" % tid, b"s%d" % i, b"v%d.%d" % (tid, i))
        for i in range(N_OPS):
            assert cli.get(b"t%d" % tid, b"s%d" % i) == b"v%d.%d" % (tid, i)
        cli.close()

    run_parallel(body)


def test_concurrent_flush_and_manual_compact_no_duplicates(tmp_path):
    """Flush-triggered compact() racing manual_compact() must not double-
    merge the same input files (duplicated/resurrected records) — they are
    serialized by the engine compaction lock (ADVICE r2 medium)."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(
        backend="cpu", memtable_bytes=4 << 10, l0_compaction_trigger=2,
        level_base_bytes=8 << 10, target_file_size_bytes=8 << 10))
    n_writers, n_keys = 4, 120
    errs = []

    def writer(tid):
        try:
            for i in range(n_keys):
                eng.put(generate_key(b"w%d" % tid, b"s%05d" % i),
                        SCHEMAS[2].generate_value(0, 0, b"v%d.%d" % (tid, i)))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def compactor():
        try:
            for _ in range(6):
                eng.manual_compact()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ths = ([threading.Thread(target=writer, args=(t,)) for t in range(n_writers)]
           + [threading.Thread(target=compactor) for _ in range(2)])
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    # a deadlocked writer/compactor must FAIL here, not hang the next call
    assert not any(t.is_alive() for t in ths), "worker threads deadlocked"
    assert not errs, errs[:3]
    eng.manual_compact()
    assert eng.stats()["total_sst_records"] == n_writers * n_keys
    for tid in range(n_writers):
        for i in range(0, n_keys, 17):
            rec = eng.get(generate_key(b"w%d" % tid, b"s%05d" % i))
            assert rec is not None
    eng.close()
