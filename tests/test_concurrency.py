"""Concurrency stress: parallel clients over real sockets.

The reference has no in-tree race detector; correctness under concurrency
is tested behaviorally (SURVEY §5.2 — sanitizer builds + kill test). This
tier drives many client threads at one onebox and asserts the atomicity
contracts PacificA's per-partition write serialization must provide:
incr is atomic, check_and_set admits exactly one winner, and multi_put
batches are observed whole.
"""

import threading

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.rpc.messages import CasCheckType, Status
from tests.test_satellites import MiniCluster

N_THREADS = 8
N_OPS = 25


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniCluster(tmp_path_factory.mktemp("conc"), n_nodes=3)
    yield c
    c.stop()


def run_parallel(fn):
    errs = []
    threads = []
    for t in range(N_THREADS):
        def body(tid=t):
            try:
                # one client per thread: separate sockets, real contention
                fn(tid)
            except Exception as e:  # noqa: BLE001 - collected and asserted
                errs.append(e)

        threads.append(threading.Thread(target=body))
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert not errs, errs[:3]


def test_concurrent_incr_is_atomic(cluster):
    cluster.create("conc_incr", partitions=2).close()

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_incr"))
        for _ in range(N_OPS):
            cli.incr(b"shared", b"counter", 1)
        cli.close()

    run_parallel(body)
    cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_incr"))
    assert cli.get(b"shared", b"counter") == str(N_THREADS * N_OPS).encode()
    cli.close()


def test_concurrent_cas_single_winner_per_round(cluster):
    cluster.create("conc_cas", partitions=2).close()
    winners = [[] for _ in range(N_OPS)]

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_cas"))
        for rnd in range(N_OPS):
            r = cli.check_and_set(b"lock", b"r%d" % rnd,
                                  CasCheckType.VALUE_NOT_EXIST, b"",
                                  b"r%d" % rnd, b"owner%d" % tid)
            if r.error == Status.OK:
                winners[rnd].append(tid)
        cli.close()

    run_parallel(body)
    for rnd, w in enumerate(winners):
        assert len(w) == 1, f"round {rnd}: winners {w}"
    cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_cas"))
    for rnd, w in enumerate(winners):
        assert cli.get(b"lock", b"r%d" % rnd) == b"owner%d" % w[0]
    cli.close()


def test_concurrent_multi_put_reads_are_whole(cluster):
    """A reader never observes a half-applied multi_put batch."""
    cluster.create("conc_mp", partitions=1).close()
    stop = threading.Event()
    bad = []

    def writer():
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_mp"))
        for i in range(60):
            cli.multi_set(b"row", {b"a": b"g%d" % i, b"b": b"g%d" % i,
                                   b"c": b"g%d" % i})
        cli.close()
        stop.set()

    def reader():
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_mp"))
        while not stop.is_set():
            _, kvs = cli.multi_get(b"row")
            if kvs and len(set(kvs.values())) != 1:
                bad.append(dict(kvs))
        cli.close()

    ths = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader) for _ in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    assert not bad, bad[:2]


def test_concurrent_disjoint_writers_no_interference(cluster):
    cluster.create("conc_disj", partitions=4).close()

    def body(tid):
        cli = PegasusClient(MetaResolver([cluster.meta_addr], "conc_disj"))
        for i in range(N_OPS):
            cli.set(b"t%d" % tid, b"s%d" % i, b"v%d.%d" % (tid, i))
        for i in range(N_OPS):
            assert cli.get(b"t%d" % tid, b"s%d" % i) == b"v%d.%d" % (tid, i)
        cli.close()

    run_parallel(body)


def test_concurrent_flush_and_manual_compact_no_duplicates(tmp_path):
    """Flush-triggered compact() racing manual_compact() must not double-
    merge the same input files (duplicated/resurrected records) — they are
    serialized by the engine compaction lock (ADVICE r2 medium)."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(
        backend="cpu", memtable_bytes=4 << 10, l0_compaction_trigger=2,
        level_base_bytes=8 << 10, target_file_size_bytes=8 << 10))
    n_writers, n_keys = 4, 120
    errs = []

    def writer(tid):
        try:
            for i in range(n_keys):
                eng.put(generate_key(b"w%d" % tid, b"s%05d" % i),
                        SCHEMAS[2].generate_value(0, 0, b"v%d.%d" % (tid, i)))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def compactor():
        try:
            for _ in range(6):
                eng.manual_compact()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ths = ([threading.Thread(target=writer, args=(t,)) for t in range(n_writers)]
           + [threading.Thread(target=compactor) for _ in range(2)])
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    # a deadlocked writer/compactor must FAIL here, not hang the next call
    assert not any(t.is_alive() for t in ths), "worker threads deadlocked"
    assert not errs, errs[:3]
    eng.manual_compact()
    assert eng.stats()["total_sst_records"] == n_writers * n_keys
    for tid in range(n_writers):
        for i in range(0, n_keys, 17):
            rec = eng.get(generate_key(b"w%d" % tid, b"s%05d" % i))
            assert rec is not None
    eng.close()


# ------------------------------------------------- concurrent-scan scaling


def _scan_all(srv, batch=500):
    """Drive the server's scan session to completion; -> row count."""
    from pegasus_tpu.base import consts
    from pegasus_tpu.rpc import messages as msg

    resp = srv.on_get_scanner(msg.GetScannerRequest(batch_size=batch))
    n = len(resp.kvs)
    while resp.context_id != consts.SCAN_CONTEXT_ID_COMPLETED:
        resp = srv.on_scan(msg.ScanRequest(resp.context_id))
        n += len(resp.kvs)
    return n


def test_concurrent_scans_not_slower_than_serial(tmp_path):
    """BASELINE regression: 4-thread scan was SLOWER than 1-thread — the
    scan path sorted the memtable under the engine lock, resolved its perf
    counters through the registry lock per RPC, and restore_key()'d every
    row for filterless scans, so concurrent scanners convoyed instead of
    overlapping. Post-fix, N independent partitions scanned concurrently
    must cost no more wall-clock than scanning them serially (the GIL
    bounds the speedup at ~1x; the regression bound is what matters)."""
    import time

    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine.server_impl import PegasusServer

    n_parts, rows = 4, 8000
    servers = []
    for p in range(n_parts):
        srv = PegasusServer(str(tmp_path / f"p{p}"), app_id=1, pidx=p)
        for i in range(rows):
            srv.engine.put(
                generate_key(b"hk%d.%d" % (p, i % 50), b"s%05d" % i),
                SCHEMAS[2].generate_value(0, 0, b"v%d" % i))
            if i == rows // 2:
                srv.engine.flush()  # scans must merge memtable + SSTs
        servers.append(srv)

    for srv in servers:        # warmup: plans, counters, code paths
        assert _scan_all(srv) == rows

    errs = []

    def worker(srv):
        try:
            assert _scan_all(srv) == rows
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def serial_round():
        t0 = time.monotonic()
        for srv in servers:
            assert _scan_all(srv) == rows
        return time.monotonic() - t0

    def concurrent_round():
        ths = [threading.Thread(target=worker, args=(srv,))
               for srv in servers]
        t0 = time.monotonic()
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ths), "scan threads hung"
        return time.monotonic() - t0

    # best-of-3 each, interleaved: scheduler noise at ~100ms scale must
    # not decide a regression gate
    serial_s = min(serial_round() for _ in range(3))
    concurrent_s = min(concurrent_round() for _ in range(3))
    assert not errs, errs[:2]
    # generous margin: a ratio gate plus absolute slack so sub-100ms
    # scheduler noise (suite background threads) can never fail it — the
    # BASELINE regression was a clean multiple of a much larger base
    assert concurrent_s <= serial_s * 1.35 + 0.2, (
        f"concurrent scans regressed: {concurrent_s:.2f}s concurrent vs "
        f"{serial_s:.2f}s serial")
    for srv in servers:
        srv.close()
