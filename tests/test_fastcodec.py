"""Differential fuzz of the C wire codec (native/fastcodec.c) against the
pure-Python closures: same bytes out, same objects back, cross-decodable.
The C path carries every RPC frame when available, so byte-for-byte parity
IS the compatibility contract (a mixed cluster runs both)."""

import dataclasses
import random
import typing

import pytest

from pegasus_tpu import native
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc import messages as rm
from pegasus_tpu.rpc.transport import RpcHeader

fc = native.fastcodec()
pytestmark = pytest.mark.skipif(fc is None, reason="fastcodec unavailable")

INT_EDGES = [0, 1, -1, 63, 64, 127, 128, 300, -300, 2**31, -(2**31),
             2**63 - 1, -(2**63), 2**64 - 1]


def _rand_value(t, rng, depth=0):
    origin = typing.get_origin(t)
    if origin is typing.Union:
        inner = [a for a in typing.get_args(t) if a is not type(None)][0]
        return None if rng.random() < 0.3 else _rand_value(inner, rng, depth)
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        if item_t is tuple:  # the lazy-unsupported case: stays empty
            return []
        return [_rand_value(item_t, rng, depth + 1)
                for _ in range(rng.randrange(0, 3 if depth else 4))]
    if t is bytes:
        return rng.randbytes(rng.randrange(0, 300))
    if t is str:
        return "".join(rng.choice("aé日\0z") for _ in range(rng.randrange(8)))
    if t is bool:
        return rng.random() < 0.5
    if t is int:
        return rng.choice(INT_EDGES) if rng.random() < 0.5 \
            else rng.randrange(-10**6, 10**6)
    if isinstance(t, type) and issubclass(t, int):  # IntEnum
        return rng.choice(list(t))
    if dataclasses.is_dataclass(t):
        return _rand_instance(t, rng, depth + 1)
    raise AssertionError(f"unhandled {t!r}")


def _rand_instance(cls, rng, depth=0):
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if depth > 3:
            break  # bound recursive structures
        kwargs[f.name] = _rand_value(hints[f.name], rng, depth)
    return cls(**kwargs)


def _message_classes():
    out = [RpcHeader]
    for mod in (rm, mm):
        for name in sorted(dir(mod)):
            c = getattr(mod, name)
            if isinstance(c, type) and dataclasses.is_dataclass(c):
                out.append(c)
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_differential_all_messages(seed):
    rng = random.Random(seed)
    for cls in _message_classes():
        py_plan = codec._StructPlan(cls)
        c_plan = codec._fast_plan(cls, fc)
        for _ in range(4):
            obj = _rand_instance(cls, rng)
            out = bytearray()
            py_plan.encode(out, obj)
            py_bytes = bytes(out)
            c_bytes = c_plan.encode(obj)
            assert c_bytes == py_bytes, cls.__name__
            # both decoders accept both encodings and agree
            obj_py, off = py_plan.decode(c_bytes, 0)
            assert off == len(c_bytes)
            obj_c = c_plan.decode(py_bytes)
            assert obj_py == obj_c == obj, cls.__name__


def test_int_edges_exact():
    @dataclasses.dataclass
    class OneInt:
        v: int = 0

    py_plan = codec._StructPlan(OneInt)
    c_plan = codec._fast_plan(OneInt, fc)
    for v in INT_EDGES:
        obj = OneInt(v)
        out = bytearray()
        py_plan.encode(out, obj)
        assert c_plan.encode(obj) == bytes(out), v
        assert c_plan.decode(bytes(out)).v == v


def test_errors_match_python_semantics():
    fc.register_error(codec.CodecError)
    c_plan = codec._fast_plan(RpcHeader, fc)
    good = c_plan.encode(RpcHeader(seq=7, code="RPC_X"))
    with pytest.raises(codec.CodecError):
        c_plan.decode(good + b"\x00")  # trailing bytes
    with pytest.raises(codec.CodecError):
        c_plan.decode(b"\x7f" + good[1:])  # 127 fields > plan's
    with pytest.raises(codec.CodecError):
        c_plan.decode(good[:-2])  # truncated


def test_public_api_uses_fast_path_and_roundtrips():
    # the public encode/decode must be byte-compatible with the closures
    req = rm.MultiGetRequest(hash_key=b"h", sort_keys=[b"a", b"b"],
                             max_kv_count=10)
    data = codec.encode(req)
    back = codec.decode(rm.MultiGetRequest, data)
    assert back == req
    py = bytearray()
    codec._StructPlan(rm.MultiGetRequest).encode(py, req)
    assert data == bytes(py)


def test_concurrent_first_use_thread_safe():
    """r5 review: lru_cache does not serialize concurrent misses — a
    racing thread must never observe a created-but-uninitialized C plan."""
    import threading

    classes = []
    for i in range(8):
        ns = {"__annotations__": {"a": int, "b": bytes, "c": str}, "a": 0,
              "b": b"", "c": ""}
        classes.append(dataclasses.dataclass(
            type(f"Conc{i}", (), dict(ns))))
    errors = []

    def hammer(tid):
        try:
            for cls in classes:
                obj = cls(a=tid, b=b"x" * tid, c=str(tid))
                assert codec.decode(cls, codec.encode(obj)) == obj
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_failed_plan_rolls_back_siblings():
    """r5 review: when a recursive plan graph fails mid-build, every plan
    created by that top-level call must be discarded — an initialized
    sibling holding the in-flight shell would silently encode it as an
    empty struct."""

    @dataclasses.dataclass
    class Good:
        v: int = 0

    @dataclasses.dataclass
    class Bad:
        g: Good = None
        x: tuple = ()  # direct unsupported type: C plan build fails

    with pytest.raises(Exception):
        codec._fast_plan(Bad, fc)
    assert Bad not in codec._fast_plans
    assert Good not in codec._fast_plans  # sibling rolled back too
    # Good still works standalone afterwards (fresh, initialized plan)
    plan = codec._fast_plan(Good, fc)
    assert plan.decode(plan.encode(Good(7))).v == 7


def test_list_of_c_unsupported_dataclass_matches_python():
    """r5 review: List[dataclass the C side can't plan] must not narrow to
    empty-only if the Python codec supports the same shape — both paths
    must agree (here: both defer the failure to first real use)."""

    @dataclasses.dataclass
    class BadItem:
        x: tuple = ()

    @dataclasses.dataclass
    class Holder:
        items: typing.List[BadItem] = dataclasses.field(default_factory=list)

    empty = Holder()
    data = codec.encode(empty)  # empty lists round-trip on every path
    assert codec.decode(Holder, data) == empty
    with pytest.raises(codec.CodecError):
        codec.encode(Holder(items=[BadItem()]))  # non-empty: both raise


def test_overlong_varint_rejected_by_both_decoders():
    """ADVICE r5: rd_varint must bound varints at 10 bytes like the Python
    decoder — a corrupt frame raises on BOTH paths instead of the C side
    shifting continuation bits into a silently-wrong value."""

    @dataclasses.dataclass
    class OneInt:
        v: int = 0

    py_plan = codec._StructPlan(OneInt)
    c_plan = codec._fast_plan(OneInt, fc)
    # the longest legal varint: -2**63 zigzags to 2**64-1 (10 bytes)
    legal = bytes(c_plan.encode(OneInt(-2**63)))
    assert len(legal) == 11  # 1 field-count byte + 10 varint bytes
    assert py_plan.decode(legal, 0)[0] == OneInt(-2**63)
    assert c_plan.decode(legal) == OneInt(-2**63)
    # corrupt: every byte keeps the continuation bit past the 10-byte cap
    bad = bytes([1]) + b"\xff" * 11 + b"\x01"
    with pytest.raises(codec.CodecError):
        py_plan.decode(bad, 0)
    with pytest.raises((codec.CodecError, ValueError)):
        c_plan.decode(bad)
