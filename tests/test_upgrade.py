"""Upgrade-test tier: on-disk format compatibility + rolling restarts.

VERDICT-r2 item 7; reference /root/reference/src/test/upgrade_test
(upgrade_tester kills one node at a time while data_verifier writes
self-checking rows) + run.sh:1260-1313. Two tiers here:

1. Golden-file tests: fixed SST (both compressions) and plog fixtures in
   tests/data/, generated 2026-07-29. If a format change breaks reading
   yesterday's files, these FAIL — the signal that a compatibility shim
   (header version bump + fallback reader) is required, matching the
   reference's requirement that a new server opens an old replica dir.
2. Rolling-restart test: a real multi-process onebox where each replica
   node restarts one-by-one under a CHANGED format knob (sst_compression
   none -> zlib) while a verifier keeps writing self-checking rows; every
   acknowledged row must read back through the whole roll and after a
   format-rewriting manual compaction.
"""

import os
import time

import pytest

from pegasus_tpu.base.key_schema import generate_key, restore_key
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


# ------------------------------------------------------------ golden files


@pytest.mark.parametrize("name", ["golden_none.sst", "golden_zlib.sst"])
def test_golden_sst_still_readable(name):
    from pegasus_tpu.engine.sstable import SSTable

    sst = SSTable(os.path.join(DATA, name))
    assert sst.n == 64
    assert sst.meta["level"] == 1 and sst.meta["last_flushed_decree"] == 42
    b = sst.block()
    live = dead = 0
    for i in range(b.n):
        hk, sk = restore_key(b.key(i))
        assert hk.startswith(b"golden") and sk.startswith(b"sk")
        if b.deleted[i]:
            dead += 1
            assert b.val_len[i] == 0
        else:
            live += 1
            idx = int(sk[2:])
            assert SCHEMAS[2].extract_user_data(b.value(i)) == \
                b"payload-%04d" % idx
            expected_expire = 0 if idx % 3 else 1000 + idx
            assert int(b.expire_ts[i]) == expected_expire
    assert dead == 4 and live == 60
    # the hashkey bloom section still answers probes
    from pegasus_tpu.base.key_schema import key_hash

    h = key_hash(generate_key(b"golden03", b"")) & 0xFFFFFFFF
    assert sst.maybe_contains_hash(h)


def test_golden_sst_engine_open(tmp_path):
    """A whole-engine open over golden files: the manifest-less recovery
    path must adopt them (new server, old replica dir)."""
    import shutil

    from pegasus_tpu.engine import EngineOptions, LsmEngine

    db = tmp_path / "db"
    db.mkdir()
    shutil.copy(os.path.join(DATA, "golden_zlib.sst"), db / "000001.sst")
    eng = LsmEngine(str(db), EngineOptions(backend="cpu"))
    raw = eng.get(generate_key(b"golden01", b"sk0001"))
    assert raw is not None
    assert SCHEMAS[2].extract_user_data(raw) == b"payload-0001"
    # a compaction rewrites the golden file in the CURRENT format
    eng.manual_compact(now=100)
    assert eng.get(generate_key(b"golden01", b"sk0001")) is not None
    eng.close()


def test_golden_plog_still_replayable():
    from pegasus_tpu.replication.mutation_log import MutationLog

    log = MutationLog(os.path.join(DATA, "golden_plog"))
    got = list(log.replay(0))
    assert [m.decree for m in got] == list(range(1, 21))
    assert all(m.ballot == 3 for m in got)
    assert got[4].bodies == [b"golden-body-005"]
    assert got[4].timestamp_us == 1700000000000005
    log.close()


# -------------------------------------------------------- rolling restart


@pytest.mark.slow
def test_rolling_restart_with_format_change(tmp_path):
    from tests.test_process_kill import ProcNode, _free_ports, _wait_nodes

    root = str(tmp_path)
    meta_port, p1, p2, p3 = _free_ports(4)
    meta_list = f"127.0.0.1:{meta_port}"
    meta = ProcNode(root, "meta", "meta", meta_port, meta_list).start()
    names = ["replica1", "replica2", "replica3"]
    ports = {"replica1": p1, "replica2": p2, "replica3": p3}
    replicas = {n: ProcNode(root, n, "replica", ports[n], meta_list).start()
                for n in names}
    meta_addr = f"127.0.0.1:{meta_port}"
    try:
        assert _wait_nodes(meta_addr, 3)
        from pegasus_tpu.meta import messages as mm
        from pegasus_tpu.meta.meta_server import RPC_CM_CREATE_APP
        from pegasus_tpu.rpc import codec
        from pegasus_tpu.rpc.transport import RpcConnection

        host, _, port = meta_addr.rpartition(":")
        conn = RpcConnection((host, int(port)))
        _, body = conn.call(RPC_CM_CREATE_APP,
                            codec.encode(mm.CreateAppRequest("ut", 2, 3)),
                            timeout=15)
        assert codec.decode(mm.CreateAppResponse, body).error == 0
        conn.close()

        cli = PegasusClient(MetaResolver([meta_addr], "ut"), timeout=15)
        acked = []
        i = 0

        def write_burst(n):
            nonlocal i
            for _ in range(n):
                try:
                    cli.set(b"uk%d" % i, b"s", b"uv%d" % i)
                    acked.append(i)
                except PegasusError:
                    pass
                i += 1

        def verify_all():
            for k in acked:
                assert cli.get(b"uk%d" % k, b"s") == b"uv%d" % k, f"lost uk{k}"

        write_burst(40)
        # roll every node: graceful stop -> rewrite its ini with the NEW
        # format knob -> restart; writes continue between rolls
        for n in names:
            replicas[n].stop()
            with open(replicas[n].cfg) as f:
                cfg = f.read()
            assert "[pegasus.server]" in cfg and "sst_compression" not in cfg
            cfg = cfg.replace("[pegasus.server]\n",
                              "[pegasus.server]\nsst_compression = zlib\n")
            with open(replicas[n].cfg, "w") as f:
                f.write(cfg)
            time.sleep(3.5)          # FD grace (2.5s) + reconfigure
            write_burst(10)
            replicas[n].start()
            assert _wait_nodes(meta_addr, 3, timeout=30), f"{n} never rejoined"
            write_burst(10)
            verify_all()
        # force a compaction so new-format files get written over old ones,
        # then verify the whole history one more time
        write_burst(10)
        verify_all()
        assert len(acked) >= 90, f"too many rejected writes: {len(acked)}"
        cli.close()
    finally:
        for r in replicas.values():
            r.stop()
        meta.stop()
