"""Key codec tests (reference behavior: src/base/pegasus_key_schema.h,
reference tests: src/base/test)."""

import numpy as np
import pytest

from pegasus_tpu.base import (
    crc64,
    generate_key,
    generate_next_bytes,
    restore_key,
    key_hash,
    hash_key_hash,
    check_key_hash,
)
from pegasus_tpu.base.crc64 import crc64_batch


def test_generate_restore_roundtrip():
    for hk, sk in [
        (b"hash", b"sort"),
        (b"", b"sort"),
        (b"hash", b""),
        (b"", b""),
        (b"\x00\xff", b"\xff\x00"),
        (b"h" * 1000, b"s" * 1000),
    ]:
        key = generate_key(hk, sk)
        assert key[:2] == len(hk).to_bytes(2, "big")
        rhk, rsk = restore_key(key)
        assert (rhk, rsk) == (hk, sk)


def test_generate_key_layout():
    # [u16 BE len][hash_key][sort_key]
    assert generate_key(b"ab", b"cd") == b"\x00\x02abcd"
    assert generate_key(b"", b"xy") == b"\x00\x00xy"


def test_key_too_long():
    with pytest.raises(ValueError):
        generate_key(b"x" * 0xFFFF, b"")


def test_next_bytes_is_adjacent_successor():
    # plain increment of last byte
    assert generate_next_bytes(b"ab") == b"\x00\x02ac"
    # trailing 0xFF bytes are stripped before increment
    assert generate_next_bytes(b"a\xff") == b"\x00\x02b"
    assert generate_next_bytes(b"ab", b"c\xff\xff") == b"\x00\x02abd"


def test_next_bytes_orders_all_keys_of_hashkey():
    hk = b"hashkey"
    stop = generate_next_bytes(hk)
    for sk in [b"", b"a", b"\xff" * 8, b"zzz"]:
        assert generate_key(hk, sk) < stop
    # and keys of the next hash_key of same length sort >= stop
    assert generate_key(b"hashkez", b"") >= stop


def test_key_hash_uses_hashkey_or_sortkey():
    k1 = generate_key(b"h", b"s1")
    k2 = generate_key(b"h", b"s2")
    assert key_hash(k1) == key_hash(k2) == hash_key_hash(b"h")
    # empty hash_key: hash over sort_key instead
    k3 = generate_key(b"", b"s1")
    k4 = generate_key(b"", b"s2")
    assert key_hash(k3) != key_hash(k4)
    assert key_hash(k3) == crc64(b"s1")


def test_check_key_hash_partition_mask():
    key = generate_key(b"pk", b"sk")
    mask = 7  # 8 partitions
    pidx = key_hash(key) & mask
    assert check_key_hash(key, pidx, mask)
    assert not check_key_hash(key, (pidx + 1) % 8, mask)


def test_crc64_known_properties():
    assert crc64(b"") == 0
    a, b = crc64(b"hello"), crc64(b"hello!")
    assert a != b
    assert crc64(b"hello") == a  # deterministic


def test_crc64_batch_matches_scalar():
    rng = np.random.default_rng(0)
    keys = [rng.integers(0, 256, size=rng.integers(1, 40), dtype=np.uint8).tobytes() for _ in range(50)]
    keys.append(b"")
    arena = np.frombuffer(b"".join(keys), dtype=np.uint8)
    lengths = np.array([len(k) for k in keys])
    offsets = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    got = crc64_batch(arena, offsets, lengths)
    want = np.array([crc64(k) for k in keys], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)
