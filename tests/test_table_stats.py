"""Tenant plane (ISSUE 18): per-table ledgers, SLO burn verdicts, top-k.

  - ledger units: every charge_* lands on the right `table.<name>.*`
    counter, snapshot() exports monotone totals, fold_snapshots sums
    across process fragments with per-quantile MAX on latencies, top_k
    ranks the capacity axes;
  - gpid/app registration: partition- and transport-scoped signals
    (charge_app_error, attribute_jobs) resolve to the tenant key, and
    an ambiguous bare-pidx job is skipped rather than mis-charged;
  - DebtThrottle regression: the global engine.throttle.debt_delay_ms_total
    rate equals the SUM of the per-table throttle_delay_ms attributions
    (the throttle charges the ledger itself, so the identity is
    structural, not sampled);
  - SLO config: the [slo] ini section overrides the env defaults per
    table;
  - grouped-onebox e2e (the acceptance shape): two tables served by a
    2-group node, per-table series flowing through the parent router's
    pid-keyed structural merge and the meta's beacon fold, a planted
    count-bounded serve.dispatch raise driving exactly ONE table to a
    burning verdict that the doctor names and the flight recorder
    embeds, while the other table stays ok.
"""

import json
import time

import pytest

from pegasus_tpu.engine.throttling import DebtThrottle
from pegasus_tpu.runtime.perf_counters import counters
from pegasus_tpu.runtime.table_stats import (TABLE_STATS, fold_snapshots,
                                             top_k)

# ------------------------------------------------------------ ledger units


def test_ledger_charges_snapshot_and_registry_counters():
    TABLE_STATS.reset()
    try:
        led = TABLE_STATS.ledger("unit_a")
        led.charge_read(100, 10)
        led.charge_write(200, 20)
        led.charge_scan(50, 5)
        led.charge_error()
        led.charge_throttle_delay(1.5)
        led.charge_device_read(3)
        led.set_hbm_resident(1024)
        led.set_device_attribution(2.5, 77)
        snap = TABLE_STATS.snapshot()["unit_a"]
        assert snap["read_qps"] == 1 and snap["write_qps"] == 1
        assert snap["scan_qps"] == 1
        assert snap["bytes_in"] == 20 and snap["bytes_out"] == 15
        assert snap["errors"] == 1
        assert snap["throttle_delay_ms"] == pytest.approx(1.5)
        assert snap["device_read_count"] == 3
        assert snap["hbm_resident_bytes"] == 1024
        assert snap["device_seconds"] == pytest.approx(2.5)
        assert snap["offload_bytes"] == 77
        assert snap["read_latency_us"]["p99"] == 100
        assert snap["write_latency_us"]["p99"] == 200
        # the ledger writes through to the shared registry (the beacon
        # fragment and metric history read the same names)
        assert counters.rate("table.unit_a.read_qps").total() == 1
        assert counters.rate("table.unit_a.error_count").total() == 1
        # snapshots are JSON-able (they ride beacons + remote commands)
        json.dumps(TABLE_STATS.snapshot())
    finally:
        TABLE_STATS.reset()
    assert TABLE_STATS.tables() == [], "reset drops the ledgers"


def test_fold_sums_totals_and_maxes_percentiles():
    a = {"t1": {"read_qps": 10, "bytes_out": 100, "errors": 1,
                "read_latency_us": {"p50": 10, "p99": 50}}}
    b = {"t1": {"read_qps": 5, "bytes_out": 30, "errors": 0,
                "read_latency_us": {"p50": 20, "p99": 40}},
         "t2": {"write_qps": 99, "bytes_in": 7}}
    folded = fold_snapshots([a, b, "not-a-dict", {"t1": 3}])
    assert folded["t1"]["read_qps"] == 15
    assert folded["t1"]["bytes_out"] == 130
    assert folded["t1"]["errors"] == 1
    assert folded["t1"]["read_latency_us"] == {"p50": 20, "p99": 50}, \
        "latency folds by per-quantile MAX (worst host), never sums"
    assert folded["t2"]["write_qps"] == 99

    top = top_k(folded, k=5)
    assert [e["table"] for e in top["ops"]] == ["t2", "t1"]
    assert top["ops"][0]["value"] == 99
    assert [e["table"] for e in top["bytes"]] == ["t1", "t2"]
    assert top["device_seconds"] == [], "zero-valued axes rank nobody"
    assert [e["table"] for e in top_k(folded, k=1)["ops"]] == ["t2"]


def test_gpid_registration_routes_app_errors_and_jobs():
    TABLE_STATS.reset()
    try:
        TABLE_STATS.register_gpid(7, 0, "unit_g")
        assert TABLE_STATS.table_for_app(7) == "unit_g"
        assert TABLE_STATS.table_for_gpid("7.0") == "unit_g"
        TABLE_STATS.charge_app_error(7)
        TABLE_STATS.charge_app_error(999)  # unmapped: must no-op
        assert TABLE_STATS.snapshot()["unit_g"]["errors"] == 1

        jobs = [
            # gpid-tagged compact job: 2 s of device time, one offload hop
            {"kind": "compact", "status": "ok", "duration_us": 2_000_000,
             "attrs": {"gpid": "7.0"},
             "hops": [{"name": "offload.ship", "nbytes": 10},
                      {"name": "learn.fetch", "nbytes": 99}]},
            # bare-pidx job resolved via the unique gpid suffix match
            {"kind": "compact", "status": "ok", "duration_us": 500_000,
             "attrs": {"pidx": 0}, "hops": []},
            # still-active job (no status): not attributable yet
            {"kind": "compact", "duration_us": 9_999_999,
             "attrs": {"gpid": "7.0"}, "hops": []},
        ]
        TABLE_STATS.attribute_jobs(jobs)
        snap = TABLE_STATS.snapshot()["unit_g"]
        assert snap["device_seconds"] == pytest.approx(2.5)
        assert snap["offload_bytes"] == 10, "only offload.* hop bytes count"

        # a second table sharing pidx 0 makes the bare-pidx job ambiguous:
        # it must be SKIPPED, not split or mis-charged
        TABLE_STATS.register_gpid(8, 0, "unit_h")
        TABLE_STATS.attribute_jobs(jobs)
        snap = TABLE_STATS.snapshot()
        assert snap["unit_g"]["device_seconds"] == pytest.approx(2.0)
        assert snap["unit_h"]["device_seconds"] == 0
    finally:
        TABLE_STATS.reset()


# ------------------------------------------- throttle attribution == global


class _RatioEngine:
    def __init__(self, ratio, policy="normal"):
        self.ratio = ratio
        self.policy = policy

    def compact_debt_ratio(self):
        return self.ratio

    def compact_policy_fast(self):
        return self.policy


def test_debt_throttle_global_equals_per_table_sum(monkeypatch):
    """Regression (ISSUE 18 satellite): the throttle charges its OWN
    ledger at the moment it accumulates the global total, so the global
    engine.throttle.debt_delay_ms_total rate must equal the sum of the
    per-table throttle_delay_ms attributions — exactly, not modulo
    sampling."""
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE", "1")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_SOFT", "0.25")
    monkeypatch.setenv("PEGASUS_SCHED_THROTTLE_MAX_MS", "1")
    TABLE_STATS.reset()
    g = counters.rate("engine.throttle.debt_delay_ms_total")
    g0 = g.total()
    try:
        th_a = DebtThrottle(_RatioEngine(0.75))
        th_a.ledger = TABLE_STATS.ledger("thr_a")
        th_b = DebtThrottle(_RatioEngine(0.95))
        th_b.ledger = TABLE_STATS.ledger("thr_b")
        for _ in range(5):
            assert th_a.consume() > 0, "past soft: every write delays"
            th_b.consume()
        th_b.consume()  # asymmetric op counts: the sum is not a 50/50 split
        delta_global = g.total() - g0
        per_table = TABLE_STATS.total_throttle_delay_ms()
        assert delta_global > 0
        assert per_table == pytest.approx(delta_global), \
            "global delay-ms total must equal the sum of table attributions"
        assert th_a.ledger.throttle_delay_ms_total() == pytest.approx(
            th_a.delay_ms_total)
        # below the soft ratio: free, and nothing charged anywhere
        th_a.engine.ratio = 0.1
        assert th_a.consume() == 0.0
        assert TABLE_STATS.total_throttle_delay_ms() == pytest.approx(
            delta_global)
    finally:
        TABLE_STATS.reset()


# ----------------------------------------------------------- slo config


def test_slo_config_ini_overrides_env_defaults(tmp_path, monkeypatch):
    from pegasus_tpu.collector.info_collector import _slo_config

    monkeypatch.setenv("PEGASUS_SLO_AVAIL", "0.99")
    monkeypatch.setenv("PEGASUS_SLO_P99_US", "0")
    cfg = tmp_path / "slo.ini"
    cfg.write_text("[slo]\n"
                   "table.gold.availability = 0.9999\n"
                   "table.gold.p99_us = 5000\n"
                   "table.my.dotted.name.availability = 0.5\n"
                   "table.gold.bogus_field = 1\n"
                   "notatable.x.availability = 0.1\n")
    monkeypatch.setenv("PEGASUS_SLO_CONFIG", str(cfg))
    per = _slo_config(["gold", "brass", "my.dotted.name"])
    assert per["gold"] == {"availability": 0.9999, "p99_us": 5000.0}
    assert per["brass"] == {"availability": 0.99, "p99_us": 0.0}, \
        "tables without ini rows keep the env defaults"
    assert per["my.dotted.name"]["availability"] == 0.5, \
        "dotted table names resolve (field = last segment)"


# ------------------------------------------------- grouped onebox e2e


def _node_cmd(conn, name, args):
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                    RemoteCommandResponse)

    _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
        RemoteCommandRequest(name, list(args))), timeout=30.0)
    return codec.decode(RemoteCommandResponse, body).output


def test_grouped_two_tables_burning_verdict_names_the_table(
        tmp_path, monkeypatch):
    """The ISSUE 18 acceptance run: two tables on a grouped onebox;
    per-table series survive the worker->router pid-keyed merge and the
    beacon fold on the meta's /tables; a count-bounded serve.dispatch
    raise fed ONLY gold traffic drives gold to burning — named in the
    slo verdicts, the doctor's causes and a captured incident — while
    brass stays ok."""
    from pegasus_tpu.collector.cluster_doctor import run_cluster_doctor
    from pegasus_tpu.collector.flight_recorder import RECORDER
    from pegasus_tpu.collector.info_collector import (InfoCollector,
                                                      latest_slo, reset_slo)
    from pegasus_tpu.rpc.transport import RpcConnection
    from pegasus_tpu.runtime.service_app import (_slo_route,
                                                 _tables_meta_route)

    from tests.test_satellites import MiniCluster

    monkeypatch.setenv("PEGASUS_INCIDENT_DIR", str(tmp_path / "inc"))
    monkeypatch.setenv("PEGASUS_SLO_FAST_S", "60")
    monkeypatch.setenv("PEGASUS_SLO_SLOW_S", "120")
    monkeypatch.setenv("PEGASUS_SLO_AVAIL", "0.999")
    cluster = MiniCluster(tmp_path / "c", n_nodes=2, serve_groups=2)
    col = None
    RECORDER.reset()
    reset_slo()
    try:
        gold = cluster.create("gold", partitions=2, replicas=2)
        brass = cluster.create("brass", partitions=2, replicas=2)
        for i in range(60):
            gold.set(b"g%04d" % i, b"s", b"v%d" % i)
        for i in range(20):
            brass.set(b"b%04d" % i, b"s", b"v%d" % i)
            brass.get(b"b%04d" % i, b"s")
        for i in range(30):
            gold.get(b"g%04d" % i, b"s")
        time.sleep(0.7)  # ledger fragments ride the next beacons

        # -- per-table series through the router's structural merge: the
        # node answers table-stats with BOTH workers' pid-keyed fragments
        node = cluster.stubs[0]
        host, _, port = node.address.rpartition(":")
        conn = RpcConnection((host, int(port)))
        try:
            reply = json.loads(_node_cmd(conn, "table-stats", []))
            pids = sorted(k for k in reply if k.startswith("pid:"))
            assert len(pids) == 2, f"one fragment per worker: {reply.keys()}"
            seen = set()
            for pid in pids:
                seen.update(reply[pid])
            assert {"gold", "brass"} <= seen, seen

            # -- meta /tables: the beacon fold serves the cluster view
            out = _tables_meta_route(cluster.meta)("/tables")
            assert {"gold", "brass"} <= set(out["tables"]), out["tables"]
            assert out["tables"]["gold"]["read_qps"] > 0
            assert out["tables"]["gold"]["write_qps"] > 0
            ops_rank = [e["table"] for e in out["top"]["ops"]]
            assert ops_rank[0] == "gold", \
                f"gold took the skewed share of ops: {out['top']}"

            # -- baseline SLO round: both tables ok
            col = InfoCollector([cluster.meta_addr])
            col.collect_once()
            verdicts = latest_slo()
            assert verdicts["gold"]["verdict"] == "ok", verdicts
            assert verdicts["brass"]["verdict"] == "ok", verdicts

            # -- breach: count-bounded dispatch raise (bounded blast
            # radius), fed ONLY gold traffic while armed
            conns = [conn]
            for stub in cluster.stubs[1:]:
                h2, _, p2 = stub.address.rpartition(":")
                conns.append(RpcConnection((h2, int(p2))))
            for c in conns:
                _node_cmd(c, "set-fail-point",
                          ["serve.dispatch", "40*raise(slo breach drill)"])
            errs = 0
            for i in range(300):
                try:
                    gold.set(b"g%04d" % (i % 60), b"s", b"x")
                except Exception:  # noqa: BLE001 - the drill's rejects
                    errs += 1
                if errs >= 12:
                    break
            assert errs >= 12, "the armed raise must reject gold traffic"
            # drain + disarm every worker before scraping: each fan-out
            # attempt consumes one remaining count in EVERY still-armed
            # worker, so >40 attempts guarantee the scrape path is clean
            for c in conns:
                for _ in range(50):
                    try:
                        _node_cmd(c, "set-fail-point",
                                  ["serve.dispatch", "off()"])
                    except Exception:  # noqa: BLE001 - still armed: retry
                        continue
                _node_cmd(c, "help", [])  # clean: answers without a raise
            for c in conns[1:]:
                c.close()

            time.sleep(0.7)  # error totals ride the next beacons
            col.collect_once()
            verdicts = latest_slo()
            assert verdicts["gold"]["verdict"] == "burning", verdicts
            assert verdicts["gold"]["errors_fast"] >= 10
            assert verdicts["brass"]["verdict"] == "ok", \
                f"only the victim table may burn: {verdicts}"
            assert _slo_route("/slo")["slo"] is verdicts

            # -- the doctor names the burning table as a degraded cause
            report = run_cluster_doctor([cluster.meta_addr])
            slo_causes = [c for c in report["causes"]
                          if "table gold SLO burning" in c["cause"]]
            assert slo_causes, report["causes"]
            assert not any("table brass SLO burning" in c["cause"]
                           for c in report["causes"])

            # -- the incident embeds the burning table's in-window series
            inc = RECORDER.capture([cluster.meta_addr],
                                   reason="slo drill", trigger="test")
            assert "gold" in inc.get("slo_tables", {}), inc.get("errors")
            assert inc["slo_tables"]["gold"]["verdict"]["verdict"] \
                == "burning"
            assert "brass" not in inc["slo_tables"]
        finally:
            conn.close()
        gold.close()
        brass.close()
    finally:
        if col is not None:
            col.stop()
        cluster.stop()
        RECORDER.reset()
        reset_slo()
        TABLE_STATS.reset()
