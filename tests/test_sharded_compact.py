"""Multi-chip sharded compaction on the virtual 8-device CPU mesh:
equivalence with the single-chip path + shard invariants."""

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import key_hash
from pegasus_tpu.ops import CompactOptions, compact_blocks
from pegasus_tpu.parallel import make_mesh, sharded_compact
from tests.test_compact_ops import _adversarial_records, make_block


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _records_set(block):
    return {(block.key(i), block.value(i), int(block.expire_ts[i]), bool(block.deleted[i]))
            for i in range(block.n)}


@pytest.mark.parametrize("seed,bottommost", [(0, True), (1, False)])
def test_sharded_equals_single_chip(mesh, seed, bottommost):
    rng = np.random.default_rng(seed)
    runs = [make_block(_adversarial_records(rng, 300)) for _ in range(3)]
    opts = CompactOptions(backend="cpu", now=100, pidx=1, partition_mask=1,
                          bottommost=bottommost, default_ttl=25)
    single = compact_blocks(runs, opts)
    shards, stats = sharded_compact(runs, mesh, opts)
    assert len(shards) == 8
    union = set()
    for s, shard in enumerate(shards):
        ks = list(shard.keys())
        assert ks == sorted(ks)  # each shard is a sorted run
        for k in ks:
            assert key_hash(k) % 8 == s  # shard owns its hash class
        union |= _records_set(shard)
    assert union == _records_set(single.block)
    assert stats["output_records"] == single.block.n


def test_overflow_retry_with_skewed_hashes(mesh):
    # all records share one hash_key -> one hash class -> every row routes to
    # a single shard, guaranteeing per-pair capacity overflow at factor 2/8
    recs = [(b"hot", b"sk%04d" % i, b"v", 0, False) for i in range(512)]
    runs = [make_block(recs)]
    opts = CompactOptions(backend="cpu", now=1)
    shards, stats = sharded_compact(runs, mesh, opts, capacity_factor=0.25)
    sizes = [s.n for s in shards]
    assert sum(sizes) == 512
    assert sorted(sizes)[-1] == 512  # all on the owning shard
    single = compact_blocks(runs, opts)
    assert _records_set(shards[np.argmax(sizes)]) == _records_set(single.block)


def test_empty_input(mesh):
    shards, stats = sharded_compact([], mesh, CompactOptions(backend="cpu", now=1))
    assert all(s.n == 0 for s in shards)
    assert stats["output_records"] == 0


def _digest(block) -> bytes:
    import hashlib

    h = hashlib.sha256()
    for arr in (block.key_arena, block.key_off, block.key_len,
                block.val_arena, block.val_off, block.val_len,
                block.expire_ts, block.hash32, block.deleted):
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.digest()


def test_sharded_block_byte_equal(mesh):
    """sharded_compact_block (the engine seam) reassembles the exact
    single-chip output block, byte for byte."""
    from pegasus_tpu.parallel import sharded_compact_block

    rng = np.random.default_rng(7)
    runs = [make_block(_adversarial_records(rng, 400)) for _ in range(4)]
    opts = CompactOptions(backend="cpu", now=100, pidx=1, partition_mask=1,
                          bottommost=True, default_ttl=25, runs_sorted=None)
    single = compact_blocks(runs, opts)
    sharded = sharded_compact_block(runs, mesh, opts)
    assert _digest(sharded.block) == _digest(single.block)
    assert sharded.stats["output_records"] == single.block.n


def test_engine_manual_compact_sharded_byte_equal(mesh, tmp_path):
    """VERDICT-r3 item 7: manual_compact through the REAL engine routes to
    the multi-chip kernel when a >1-device mesh is injected, and the
    on-disk result is byte-equal to the single-chip engine's."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine
    from pegasus_tpu.runtime.perf_counters import counters

    def fill(eng):
        rng = np.random.default_rng(3)
        for i in range(600):
            hk, sk = b"h%03d" % int(rng.integers(0, 80)), b"s%03d" % i
            k = generate_key(hk, sk)
            if rng.random() < 0.15:
                eng.delete(k)
            else:
                expire = int(rng.integers(0, 3)) * 50
                eng.put(k, SCHEMAS[2].generate_value(expire, 0, b"v%d" % i))
            if i % 150 == 149:
                eng.flush()

    eng_s = LsmEngine(str(tmp_path / "sharded"),
                      EngineOptions(backend="tpu", compaction_mesh=mesh))
    eng_1 = LsmEngine(str(tmp_path / "single"),
                      EngineOptions(backend="cpu"))
    fill(eng_s)
    fill(eng_1)
    before = counters.rate("engine.sharded_compaction_count").value()
    st_s = eng_s.manual_compact(now=60)
    st_1 = eng_1.manual_compact(now=60)
    assert counters.rate("engine.sharded_compaction_count").value() > before
    assert st_s["output_records"] == st_1["output_records"]
    bot_s = [s for s in eng_s._levels[eng_s.opts.max_levels]]
    bot_1 = [s for s in eng_1._levels[eng_1.opts.max_levels]]
    assert len(bot_s) == len(bot_1)
    for a, b in zip(bot_s, bot_1):
        assert _digest(a.block()) == _digest(b.block())
    eng_s.close()
    eng_1.close()


def test_sharded_block_byte_equal_with_user_rules(mesh):
    """Post filters (user compaction rules then default-TTL rewrite) must
    run in compact_blocks' exact order after shard reassembly — a clock or
    ordering skew between the kernel and the post pass would break the
    byte-equality contract."""
    from pegasus_tpu.engine.compaction_rules import \
        parse_user_specified_compaction
    from pegasus_tpu.parallel import sharded_compact_block

    ops = tuple(parse_user_specified_compaction(
        '{"ops": [{"type": "COT_DELETE", "params": "{}", "rules": '
        '[{"type": "FRT_SORTKEY_PATTERN", "params": '
        '"{\\"pattern\\": \\"s1\\", \\"match_type\\": '
        '\\"SMT_MATCH_PREFIX\\"}"}]}]}'))
    assert ops
    rng = np.random.default_rng(11)
    runs = [make_block(_adversarial_records(rng, 350)) for _ in range(3)]
    opts = CompactOptions(backend="cpu", now=60, bottommost=True,
                          user_ops=ops, default_ttl=500, runs_sorted=None)
    single = compact_blocks(runs, opts)
    sharded = sharded_compact_block(runs, mesh, opts)
    assert _digest(sharded.block) == _digest(single.block)


def test_init_multihost_reads_jax_env(monkeypatch):
    """ADVICE r5: the docstring promised JAX_NUM_PROCESSES/JAX_PROCESS_ID
    defaults but the code only read PEGASUS_COORDINATOR; all three env
    vars must reach jax.distributed.initialize, once (idempotent)."""
    import pegasus_tpu.parallel.mesh as mesh_mod

    calls = []
    monkeypatch.setattr(mesh_mod, "_joined", False)
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda coordinator_address=None, num_processes=None,
        process_id=None: calls.append(
            (coordinator_address, num_processes, process_id)))
    monkeypatch.delenv("PEGASUS_COORDINATOR", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    # no env, no args: single host, never touches jax.distributed
    assert mesh_mod.init_multihost() is False
    assert calls == []
    monkeypatch.setenv("PEGASUS_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert mesh_mod.init_multihost() is True
    assert calls == [("10.0.0.1:8476", 4, 2)]
    # idempotent: a second join is a no-op success
    assert mesh_mod.init_multihost() is True
    assert calls == [("10.0.0.1:8476", 4, 2)]


def test_service_startup_invokes_multihost_join(monkeypatch):
    """The hook existed but nothing called it (ADVICE r5): container
    start() must join when the env is present and skip when absent."""
    import pegasus_tpu.parallel.mesh as mesh_mod
    from pegasus_tpu.runtime.service_app import _maybe_join_multihost

    calls = []
    monkeypatch.setattr(mesh_mod, "_joined", False)
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: calls.append(kw))
    monkeypatch.delenv("PEGASUS_COORDINATOR", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    assert _maybe_join_multihost() is False
    assert calls == []
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    assert _maybe_join_multihost() is True
    assert len(calls) == 1
