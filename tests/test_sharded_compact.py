"""Multi-chip sharded compaction on the virtual 8-device CPU mesh:
equivalence with the single-chip path + shard invariants."""

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import key_hash
from pegasus_tpu.ops import CompactOptions, compact_blocks
from pegasus_tpu.parallel import make_mesh, sharded_compact
from tests.test_compact_ops import _adversarial_records, make_block


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _records_set(block):
    return {(block.key(i), block.value(i), int(block.expire_ts[i]), bool(block.deleted[i]))
            for i in range(block.n)}


@pytest.mark.parametrize("seed,bottommost", [(0, True), (1, False)])
def test_sharded_equals_single_chip(mesh, seed, bottommost):
    rng = np.random.default_rng(seed)
    runs = [make_block(_adversarial_records(rng, 300)) for _ in range(3)]
    opts = CompactOptions(backend="cpu", now=100, pidx=1, partition_mask=1,
                          bottommost=bottommost, default_ttl=25)
    single = compact_blocks(runs, opts)
    shards, stats = sharded_compact(runs, mesh, opts)
    assert len(shards) == 8
    union = set()
    for s, shard in enumerate(shards):
        ks = list(shard.keys())
        assert ks == sorted(ks)  # each shard is a sorted run
        for k in ks:
            assert key_hash(k) % 8 == s  # shard owns its hash class
        union |= _records_set(shard)
    assert union == _records_set(single.block)
    assert stats["output_records"] == single.block.n


def test_overflow_retry_with_skewed_hashes(mesh):
    # all records share one hash_key -> one hash class -> every row routes to
    # a single shard, guaranteeing per-pair capacity overflow at factor 2/8
    recs = [(b"hot", b"sk%04d" % i, b"v", 0, False) for i in range(512)]
    runs = [make_block(recs)]
    opts = CompactOptions(backend="cpu", now=1)
    shards, stats = sharded_compact(runs, mesh, opts, capacity_factor=0.25)
    sizes = [s.n for s in shards]
    assert sum(sizes) == 512
    assert sorted(sizes)[-1] == 512  # all on the owning shard
    single = compact_blocks(runs, opts)
    assert _records_set(shards[np.argmax(sizes)]) == _records_set(single.block)


def test_empty_input(mesh):
    shards, stats = sharded_compact([], mesh, CompactOptions(backend="cpu", now=1))
    assert all(s.n == 0 for s in shards)
    assert stats["output_records"] == 0
