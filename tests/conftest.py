"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import anywhere in the test session, hence env vars
set at conftest import time. Mirrors the reference's approach of testing
multi-node behavior on one machine (onebox, run.sh:480).
"""

import os

# arm the lock-order deadlock detector for the WHOLE suite (ISSUE 9):
# every named lock records its acquisition graph, a cycle = a deadlock
# waiting for the right interleaving, and pytest_sessionfinish below
# fails the run on any recorded violation — so every onebox /
# group-worker / chaos test doubles as a lock-order regression test.
# Must happen before any pegasus_tpu import (locks are created at class
# init with the env read per factory call); subprocesses (group workers,
# killed-node oneboxes, bench children) inherit both knobs and report
# violations into the shared file.
os.environ.setdefault("PEGASUS_LOCKRANK", "1")
_LOCKRANK_FILE_PRESET = "PEGASUS_LOCKRANK_FILE" in os.environ
_LOCKRANK_FILE = os.environ.setdefault(
    "PEGASUS_LOCKRANK_FILE", f"/tmp/pegasus_lockrank_{os.getpid()}.jsonl")
if not _LOCKRANK_FILE_PRESET:
    # OUR file (pid-named): drop any leftover from a crashed prior run
    # with a recycled pid so stale violations can't fail a green session
    try:
        os.unlink(_LOCKRANK_FILE)
    except OSError:
        pass
# an externally-owned file is never deleted and only NEW lines count:
# remember how many were already there when the session began
try:
    with open(_LOCKRANK_FILE) as _f:
        _LOCKRANK_BASELINE_LINES = sum(1 for line in _f if line.strip())
except OSError:
    _LOCKRANK_BASELINE_LINES = 0

# the image pre-sets JAX_PLATFORMS=axon (the real TPU tunnel); tests always
# run on the virtual CPU mesh unless explicitly opted onto hardware
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("PEGASUS_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # something in the image re-asserts the axon platform over the env var;
    # the config API wins over both
    import jax

    jax.config.update("jax_platforms", "cpu")

# rebuild any stale native artifact BEFORE the first pegasus_tpu import
# caches a loaded .so (ISSUE 20): tier-1 must never silently exercise a
# binary older than its C source. Failures degrade loudly to the
# pure-Python twins and never fail collection.
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
try:
    from tools import build_native  # noqa: E402

    build_native.ensure()
except Exception as _e:  # noqa: BLE001 - the gate is best-effort
    print(f"[conftest] build_native: {_e!r}")

# persistent compile cache: the suite jit-compiles many static shapes; cold
# runs took 7 minutes in round 1 (VERDICT weak #9)
from pegasus_tpu.base.utils import enable_compile_cache  # noqa: E402

enable_compile_cache()


def _reap_group_workers():
    """Kill any partition-group executor the suite (or a crashed test)
    left behind: workers are separate OS processes (`-m pegasus_tpu.server
    --group-worker`), and a leaked one would hold its engine dirs and
    sockets past the run. Normal teardown (GroupedReplicaNode.stop or
    control-channel EOF) exits them; this is the backstop that keeps
    tier-1 leak-free no matter how a test died."""
    import signal

    me = os.getpid()
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(errors="replace")
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        # scope the kill: only THIS session's children and true orphans
        # (ppid 1 = a worker whose parent already died) — never another
        # concurrent run's live workers
        if "--group-worker" in cmd and ppid in (me, 1):
            print(f"[conftest] reaping leaked group worker pid={pid}")
            try:
                os.kill(int(pid), signal.SIGKILL)
            except OSError:
                pass


def pytest_sessionfinish(session, exitstatus):
    """Join the process-wide daemon executors BEFORE interpreter exit.

    The long-standing "rc=134/139 after 'N passed'" shutdown crash
    (CHANGES PR 3/4): CPython finalization kills daemon threads at an
    arbitrary bytecode boundary, and the suite leaves three kinds of them
    alive — the compact pipeline/install pool workers and the
    device-watchdog probe loop — all of which may be INSIDE an XLA
    dispatch (watchdog probes jit a kernel on a cadence; pool workers run
    deferred installs/primes). A worker killed mid-dispatch dies holding
    TSL/XLA resources, and the C++ static teardown then aborts
    ("terminate called without an active exception") AFTER pytest printed
    its summary — so the tier-1 command's rc lied about a green run.
    Stopping the watchdog and joining the pools (bounded: ThreadPool.stop
    joins with a 5 s timeout per worker) drains the process of
    XLA-touching daemons before Py_Finalize runs."""
    try:
        from pegasus_tpu.ops import pipeline
        from pegasus_tpu.ops.device_watchdog import WATCHDOG

        WATCHDOG.stop()
        t = getattr(WATCHDOG, "_loop_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=5)
        with pipeline._POOL_LOCK:
            pools = [p for p in (pipeline._POOL, pipeline._IO_POOL)
                     if p is not None]
        for p in pools:
            p.stop()
        # the tracked-spawn registry is the GENERAL backstop for the
        # same bug class: shut down every tracked executor and join
        # every tracked daemon (bounded) so no thread the registry knows
        # about can die inside an XLA dispatch during Py_Finalize
        from pegasus_tpu.runtime.tasking import TRACKED

        leftover = TRACKED.join_all(timeout_s=5.0)
        if leftover:
            print(f"[conftest] {len(leftover)} tracked thread(s) still "
                  f"alive at teardown: "
                  f"{sorted(t.name for t in leftover)[:10]}")
    except Exception as e:  # teardown must never mask the run's outcome
        print(f"[conftest] executor teardown: {e!r}")
    try:
        _reap_group_workers()
    except Exception as e:  # the reaper is best-effort
        print(f"[conftest] group-worker reap: {e!r}")
    try:
        _check_lockrank(session)
    except Exception as e:  # the gate must never mask the run's outcome
        print(f"[conftest] lockrank gate: {e!r}")


def _check_lockrank(session):
    """Fail the session on any lock-order cycle recorded this run — in
    THIS process (GRAPH.violations) or by any subprocess (group workers,
    chaos-killed oneboxes) that appended to the shared violation file."""
    from pegasus_tpu.runtime import lockrank

    import json

    violations = list(lockrank.GRAPH.violations)
    try:
        with open(_LOCKRANK_FILE) as f:
            file_lines = [line.strip() for line in f if line.strip()]
    except OSError:
        file_lines = []
    # only lines THIS session appended count (an externally-owned file
    # may carry history)...
    file_lines = file_lines[_LOCKRANK_BASELINE_LINES:]

    # ...and in-process violations land in BOTH the graph and the file;
    # count the file only for other pids (subprocess reports)
    def _other_pid(line):
        try:
            return json.loads(line).get("pid") != os.getpid()
        except ValueError:
            return True
    file_lines = [line for line in file_lines if _other_pid(line)]
    if not _LOCKRANK_FILE_PRESET:
        # our pid-named file; an externally-owned one stays for its owner
        try:
            os.unlink(_LOCKRANK_FILE)
        except OSError:
            pass
    n = len(violations) + len(file_lines)
    if not n:
        return
    print(f"\n[conftest] LOCKRANK: {n} lock-order violation(s) recorded "
          f"this session — each is a deadlock waiting for the right "
          f"interleaving:")
    for v in violations:
        print(f"  in-process: {' -> '.join(v['cycle'])} "
              f"({v['held_site']} vs {v['acquire_site']})")
    for line in file_lines:
        print(f"  subprocess: {line}")
    if session.exitstatus == 0:
        session.exitstatus = 1
