"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import anywhere in the test session, hence env vars
set at conftest import time. Mirrors the reference's approach of testing
multi-node behavior on one machine (onebox, run.sh:480).
"""

import os

# the image pre-sets JAX_PLATFORMS=axon (the real TPU tunnel); tests always
# run on the virtual CPU mesh unless explicitly opted onto hardware
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("PEGASUS_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # something in the image re-asserts the axon platform over the env var;
    # the config API wins over both
    import jax

    jax.config.update("jax_platforms", "cpu")

# persistent compile cache: the suite jit-compiles many static shapes; cold
# runs took 7 minutes in round 1 (VERDICT weak #9)
from pegasus_tpu.base.utils import enable_compile_cache  # noqa: E402

enable_compile_cache()
