"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import anywhere in the test session, hence env vars
set at conftest import time. Mirrors the reference's approach of testing
multi-node behavior on one machine (onebox, run.sh:480).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
