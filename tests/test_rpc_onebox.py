"""Onebox RPC test: multi-partition table through real sockets.

The VERDICT-r1 'minimum viable server' milestone: every data op driven
through the codec + TCP transport + replica serverlet + client, partitions
spread over two server processes' worth of RpcServers in one process
(the reference's onebox pattern, run.sh:480).
"""

import numpy as np
import pytest

from pegasus_tpu.client import PegasusClient, PegasusError, StaticResolver
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.replica_service import ReplicaService
from pegasus_tpu.engine.server_impl import PegasusServer
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.messages import CasCheckType, Status
from pegasus_tpu.rpc.transport import RpcServer

N_PARTITIONS = 4
APP_ID = 7


@pytest.fixture(scope="module")
def onebox(tmp_path_factory):
    """Two RpcServers ("nodes"), 4 partitions split across them."""
    root = tmp_path_factory.mktemp("onebox")
    servers, services = [], []
    addr_by_pidx = {}
    for node in range(2):
        svc = ReplicaService()
        rpc = RpcServer().start()
        for pidx in range(N_PARTITIONS):
            if pidx % 2 == node:
                ps = PegasusServer(str(root / f"p{pidx}"), app_id=APP_ID,
                                   pidx=pidx, options=EngineOptions(backend="cpu"),
                                   server=f"node{node}")
                svc.add_replica(ps, N_PARTITIONS)
                addr_by_pidx[pidx] = rpc.address
        rpc.register_serverlet(svc)
        servers.append(rpc)
        services.append(svc)
    resolver = StaticResolver(APP_ID, [addr_by_pidx[p] for p in range(N_PARTITIONS)])
    client = PegasusClient(resolver)
    yield client
    client.close()
    for s in servers:
        s.stop()


def test_set_get_del_exist_ttl(onebox):
    c = onebox
    c.set(b"user1", b"k1", b"v1")
    c.set(b"user2", b"k1", b"v2", ttl_seconds=1000)
    assert c.get(b"user1", b"k1") == b"v1"
    assert c.get(b"user2", b"k1") == b"v2"
    assert c.get(b"user1", b"missing") is None
    assert c.exist(b"user1", b"k1")
    assert not c.exist(b"nope", b"k1")
    assert c.ttl(b"user1", b"k1") == -1
    ttl = c.ttl(b"user2", b"k1")
    assert 990 < ttl <= 1000
    assert c.ttl(b"gone", b"x") is None
    c.delete(b"user1", b"k1")
    assert c.get(b"user1", b"k1") is None


def test_routing_covers_all_partitions(onebox):
    """Write enough hash keys that every partition serves some of them."""
    from pegasus_tpu.base import key_schema

    seen = set()
    for i in range(64):
        hk = b"route%d" % i
        onebox.set(hk, b"s", b"v%d" % i)
        key = key_schema.generate_key(hk, b"s")
        seen.add(key_schema.key_hash(key) % N_PARTITIONS)
    assert seen == set(range(N_PARTITIONS))
    for i in range(64):
        assert onebox.get(b"route%d" % i, b"s") == b"v%d" % i


def test_multi_ops(onebox):
    c = onebox
    c.multi_set(b"mh", {b"a": b"1", b"b": b"2", b"c": b"3"})
    complete, kvs = c.multi_get(b"mh")
    assert complete and kvs == {b"a": b"1", b"b": b"2", b"c": b"3"}
    _, kvs = c.multi_get(b"mh", sort_keys=[b"a", b"c", b"zz"])
    assert kvs == {b"a": b"1", b"c": b"3"}
    assert c.sortkey_count(b"mh") == 3
    assert c.multi_del(b"mh", [b"a", b"b"]) == 2
    _, kvs = c.multi_get(b"mh")
    assert kvs == {b"c": b"3"}


def test_multi_get_reverse_window(onebox):
    c = onebox
    c.multi_set(b"rev", {b"k%02d" % i: b"v%02d" % i for i in range(10)})
    complete, kvs = c.multi_get(b"rev", max_kv_count=3, reverse=True)
    # reverse keeps the LAST 3 of the ascending range
    assert not complete
    assert set(kvs) == {b"k07", b"k08", b"k09"}


def test_incr(onebox):
    c = onebox
    assert c.incr(b"cnt", b"x", 5) == 5
    assert c.incr(b"cnt", b"x", -2) == 3
    assert c.get(b"cnt", b"x") == b"3"
    # non-numeric value -> INVALID_ARGUMENT surfaced as PegasusError
    c.set(b"cnt", b"bad", b"notanumber")
    with pytest.raises(PegasusError) as ei:
        c.incr(b"cnt", b"bad", 1)
    assert ei.value.status == Status.INVALID_ARGUMENT


def test_check_and_set(onebox):
    c = onebox
    r = c.check_and_set(b"cas", b"ck", CasCheckType.VALUE_NOT_EXIST, b"",
                        b"ck", b"first")
    assert r.error == Status.OK
    r = c.check_and_set(b"cas", b"ck", CasCheckType.VALUE_NOT_EXIST, b"",
                        b"ck", b"second")
    assert r.error == Status.TRY_AGAIN  # check failed
    assert c.get(b"cas", b"ck") == b"first"
    r = c.check_and_set(b"cas", b"ck", CasCheckType.VALUE_BYTES_EQUAL, b"first",
                        b"other", b"written", return_check_value=True)
    assert r.error == Status.OK
    assert r.check_value_returned and r.check_value == b"first"
    assert c.get(b"cas", b"other") == b"written"


def test_check_and_mutate(onebox):
    c = onebox
    c.set(b"cam", b"guard", b"go")
    r = c.check_and_mutate(b"cam", b"guard", CasCheckType.VALUE_BYTES_EQUAL,
                           b"go", [("set", b"m1", b"v1", 0), ("del", b"guard")])
    assert r.error == Status.OK
    assert c.get(b"cam", b"m1") == b"v1"
    assert c.get(b"cam", b"guard") is None


def test_scanner_full_and_hash(onebox):
    c = onebox
    rows = {b"s%02d" % i: b"val%d" % i for i in range(25)}
    c.multi_set(b"scanhk", rows)
    got = {sk: v for hk, sk, v in c.get_scanner(b"scanhk", batch_size=7)}
    assert got == rows
    # full-table scan across all partitions finds every row written above
    total = {}
    for sc in c.get_unordered_scanners():
        for hk, sk, v in sc:
            total.setdefault(hk, {})[sk] = v
    assert total[b"scanhk"] == rows
    assert b"cas" in total


def test_scan_session_keeps_one_context_id(onebox):
    """VERDICT r1 weak #7: one context id per scan session."""
    c = onebox
    c.multi_set(b"ctxhk", {b"s%02d" % i: b"v" for i in range(30)})
    from pegasus_tpu.base import key_schema
    from pegasus_tpu.engine import replica_service as codes

    start = key_schema.generate_key(b"ctxhk", b"")
    stop = key_schema.generate_next_bytes(b"ctxhk")
    pidx, h = c._route(start)
    req = msg.GetScannerRequest(start_key=start, stop_key=stop, batch_size=5,
                                validate_partition_hash=False)
    r1 = c._call(codes.RPC_GET_SCANNER, pidx, h, req, msg.ScanResponse)
    assert r1.error == Status.OK and len(r1.kvs) == 5
    cid = r1.context_id
    assert cid >= 0
    r2 = c._call(codes.RPC_SCAN, pidx, h, msg.ScanRequest(cid), msg.ScanResponse)
    assert r2.error == Status.OK
    assert r2.context_id == cid  # same session id across batches
    c._call(codes.RPC_CLEAR_SCANNER, pidx, h, msg.ScanRequest(cid), None)
    r3 = c._call(codes.RPC_SCAN, pidx, h, msg.ScanRequest(cid), msg.ScanResponse)
    assert r3.error == Status.NOT_FOUND


def test_wrong_partition_rejected_then_rerouted(onebox):
    """Partition-hash sanity check (pegasus_server_write.cpp): the server
    rejects a misrouted request; the client layer re-routes it."""
    from pegasus_tpu.base import key_schema
    from pegasus_tpu.engine import replica_service as codes
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import ERR_INVALID_STATE, RpcError

    c = onebox
    c.set(b"misroute", b"s", b"v")
    key = key_schema.generate_key(b"misroute", b"s")
    h = key_schema.key_hash(key)
    wrong = (h % N_PARTITIONS + 1) % N_PARTITIONS
    # raw call straight at the wrong partition: rejected server-side
    conn = c.pool.get(c.resolver.resolve(wrong))
    with pytest.raises(RpcError) as ei:
        conn.call(codes.RPC_GET, codec.encode(msg.KeyRequest(key)),
                  app_id=c.resolver.app_id, partition_index=wrong,
                  partition_hash=h, timeout=5)
    assert ei.value.err == ERR_INVALID_STATE
    # the client layer turns the rejection into a transparent re-route
    r = c._call(codes.RPC_GET, wrong, h, msg.KeyRequest(key), msg.ReadResponse)
    assert r.error == Status.OK and r.value == b"v"


def test_codec_roundtrip_all_messages():
    rng = np.random.default_rng(0)
    samples = [
        msg.UpdateRequest(b"k", b"v", 77),
        msg.MultiGetRequest(b"h", [b"a", b"b"], 10, 20, True, b"s", b"t",
                            False, True, msg.FilterType.MATCH_PREFIX, b"p", True),
        msg.MultiGetResponse(0, [msg.KeyValue(b"k", b"v", 5),
                                 msg.KeyValue(b"x", b"", None)], 1, 2, "srv"),
        msg.CheckAndMutateRequest(b"h", b"cs", 3, b"op",
                                  [msg.Mutate(1, b"sk", b"v", 9)], True),
        msg.ScanResponse(0, [], -1, 3, 1, "s"),
        msg.IncrRequest(b"k", -(1 << 40), -1),
    ]
    for obj in samples:
        enc = codec.encode(obj)
        dec = codec.decode(type(obj), enc)
        assert dec == obj, obj


def test_call_many_coalesced_pipeline():
    """RpcConnection.call_many: k requests leave in ONE coalesced socket
    send and the responses come back in issue order — the replication
    catch-up path's writev-style transport batching."""
    from pegasus_tpu.rpc.transport import RpcConnection

    served = []
    srv = RpcServer()
    srv.register("ECHO", lambda h, b: b + b"!")
    srv.register("COUNT", lambda h, b: (served.append(b), b)[1])
    srv.start()
    try:
        conn = RpcConnection(srv.address)
        try:
            calls = [("ECHO", b"m%d" % i) for i in range(16)]
            out = conn.call_many(calls, timeout=10.0)
            assert [body for _, body in out] == \
                [b"m%d!" % i for i in range(16)]
            # interleaves safely with single calls on the same connection
            _, single = conn.call("ECHO", b"solo", timeout=10.0)
            assert single == b"solo!"
            assert conn.call_many([], timeout=1.0) == []
        finally:
            conn.close()
    finally:
        srv.stop()
