"""Cluster onebox: meta server + 3 replica nodes + meta-resolved client.

The VERDICT-r1 item 8 'Done' criterion: table DDL, beacon FD, and a client
that survives a replica-node kill with automatic re-route — all over real
sockets in one process (the reference's onebox, run.sh:480).
"""

import threading
import time

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError
from pegasus_tpu.rpc.messages import Status
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.meta import MetaServer
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.meta.meta_server import (RPC_CM_CREATE_APP, RPC_CM_LIST_NODES,
                                          RPC_CM_SET_APP_ENVS)
from pegasus_tpu.replication.replica_stub import ReplicaStub
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc.transport import RpcServer


class Cluster:
    def __init__(self, root, n_nodes=3, fd_grace=60.0, remote_clusters=None,
                 cluster_id=1):
        self.meta = MetaServer(str(root / "meta" / "state.json"),
                               fd_grace_seconds=fd_grace)
        self.meta_rpc = RpcServer().start()
        for code, fn in self.meta.rpc_handlers().items():
            self.meta_rpc.register(code, fn)
        self.meta_addr = f"{self.meta_rpc.address[0]}:{self.meta_rpc.address[1]}"
        self.nodes = {}
        for i in range(n_nodes):
            stub = ReplicaStub(str(root / f"node{i}"), [self.meta_addr],
                               options_factory=lambda: EngineOptions(backend="cpu"),
                               remote_clusters=remote_clusters,
                               cluster_id=cluster_id)
            stub.start(beacon_interval=0.2)
            self.nodes[stub.address] = stub

    def ddl(self, code, req, resp_cls):
        from pegasus_tpu.rpc.transport import RpcConnection, RpcError

        host, _, port = self.meta_addr.rpartition(":")
        # Bounded retry: under parallel-suite load the meta's accept
        # loop can lag past a single call's timeout, which used to flake
        # these tests with spurious meta-unreachable errors. Each
        # attempt uses a FRESH connection (a timed-out socket may have
        # a stale half-response buffered).
        last = None
        for attempt in range(4):
            conn = RpcConnection((host, int(port)))
            try:
                _, body = conn.call(code, codec.encode(req), timeout=10.0)
                return codec.decode(resp_cls, body)
            except (RpcError, OSError, TimeoutError) as e:
                last = e
                time.sleep(0.25 * (attempt + 1))
            finally:
                conn.close()
        raise last

    def kill_node(self, addr):
        stub = self.nodes.pop(addr)
        stub.stop()
        self.meta.mark_node_dead(addr)

    def stop(self):
        for s in self.nodes.values():
            s.stop()
        self.meta_rpc.stop()


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(tmp_path)
    yield c
    c.stop()


def make_client(cluster, app="t1", partitions=4):
    r = cluster.ddl(RPC_CM_CREATE_APP,
                    mm.CreateAppRequest(app_name=app, partition_count=partitions,
                                        replica_count=3),
                    mm.CreateAppResponse)
    assert r.error == 0 and r.app_id >= 1
    resolver = MetaResolver([cluster.meta_addr], app)
    return PegasusClient(resolver)


def test_create_app_and_data_ops(cluster):
    c = make_client(cluster)
    for i in range(32):
        c.set(b"hk%d" % i, b"sk", b"val%d" % i)
    for i in range(32):
        assert c.get(b"hk%d" % i, b"sk") == b"val%d" % i
    assert c.sortkey_count(b"hk3") == 1
    c.close()


def test_writes_replicate_across_nodes(cluster):
    c = make_client(cluster, app="t2")
    for i in range(16):
        c.set(b"k%d" % i, b"s", b"v%d" % i)
    # every partition has 3 members with matching prepared decrees
    resolver = c.resolver
    cfg = cluster.meta._parts[resolver.app_id]
    for pc in cfg:
        assert pc.primary and len(pc.secondaries) == 2
    c.close()


def test_client_survives_primary_node_kill(cluster):
    c = make_client(cluster, app="t3", partitions=4)
    for i in range(48):
        c.set(b"fk%d" % i, b"s", b"v%d" % i)
    # kill a node that is primary for at least one partition
    victim = cluster.meta._parts[c.resolver.app_id][0].primary
    cluster.kill_node(victim)
    # client re-resolves on routing failure and keeps working
    for i in range(48):
        assert c.get(b"fk%d" % i, b"s") == b"v%d" % i, f"lost fk{i}"
    for i in range(48, 64):
        c.set(b"fk%d" % i, b"s", b"v%d" % i)
        assert c.get(b"fk%d" % i, b"s") == b"v%d" % i
    # failed partitions were reconfigured with a promoted primary
    for pc in cluster.meta._parts[c.resolver.app_id]:
        assert pc.primary != victim
        assert victim not in pc.secondaries
    c.close()


def test_dead_node_replicas_rebuilt_on_survivor(cluster):
    c = make_client(cluster, app="t4", partitions=2)
    for i in range(20):
        c.set(b"rk%d" % i, b"s", b"v%d" % i)
    victim = cluster.meta._parts[c.resolver.app_id][0].primary
    cluster.kill_node(victim)
    # with 3 nodes and one dead, reconfiguration keeps 2 members (no spare
    # node); data still fully available
    for pc in cluster.meta._parts[c.resolver.app_id]:
        members = [pc.primary] + pc.secondaries
        assert victim not in members and len(members) >= 2
    for i in range(20):
        assert c.get(b"rk%d" % i, b"s") == b"v%d" % i
    c.close()


def test_rebuilt_learner_joins_primary_view_and_receives_writes(tmp_path):
    """After a node death rebuilds redundancy onto a spare node, the
    primary's live view must include the promoted learner so it receives
    subsequent prepares — not just meta's persisted table (ADVICE r2 med)."""
    c = Cluster(tmp_path, n_nodes=4)
    try:
        cl = make_client(c, app="t7", partitions=1)
        app_id = cl.resolver.app_id
        for i in range(10):
            cl.set(b"lk%d" % i, b"s", b"v%d" % i)
        pc = c.meta._parts[app_id][0]
        members = [pc.primary] + list(pc.secondaries)
        spare = next(a for a in c.nodes if a not in members)
        c.kill_node(pc.secondaries[0])
        assert spare in pc.secondaries
        prim_rep = c.nodes[pc.primary]._replicas[(app_id, 0)]
        assert spare in prim_rep.view.secondaries
        # new writes actually reach the new member
        for i in range(10, 20):
            cl.set(b"lk%d" % i, b"s", b"v%d" % i)
        spare_rep = c.nodes[spare]._replicas[(app_id, 0)]
        assert spare_rep.last_prepared >= prim_rep.last_committed
        cl.close()
    finally:
        c.stop()


def test_app_envs_propagate_to_replicas(cluster):
    c = make_client(cluster, app="t5", partitions=2)
    r = cluster.ddl(RPC_CM_SET_APP_ENVS,
                    mm.SetAppEnvsRequest(app_name="t5",
                                         envs_json='{"default_ttl": "120"}'),
                    mm.SetAppEnvsResponse)
    assert r.error == 0
    # every live replica of t5 picked the env up
    found = 0
    for stub in cluster.nodes.values():
        for (aid, pidx), rep in stub._replicas.items():
            if aid == c.resolver.app_id:
                assert rep.server.app_envs.get("default_ttl") == "120"
                found += 1
    assert found >= 2
    c.close()


def test_write_throttling_env(cluster):
    """replica.write_throttling: delay throttling slows the writer; the
    reject stage returns TRY_AGAIN (reference PERR_APP_BUSY) without the
    client transparently retrying."""
    c = make_client(cluster, app="thr", partitions=1)
    r = cluster.ddl(RPC_CM_SET_APP_ENVS,
                    mm.SetAppEnvsRequest(
                        app_name="thr",
                        envs_json='{"replica.write_throttling":'
                                  ' "5*delay*40,10*reject*5"}'),
                    mm.SetAppEnvsResponse)
    assert r.error == 0
    deadline = time.time() + 5
    armed = False
    while time.time() < deadline and not armed:
        for stub in cluster.nodes.values():
            for (aid, _), rep in stub._replicas.items():
                if (aid == c.resolver.app_id
                        and rep.server.write_qps_throttler.enabled):
                    armed = True
        time.sleep(0.1)
    assert armed, "throttling env never reached a replica"
    # burst past both thresholds; the controller's tumbling window can
    # roll over mid-burst on a loaded box, so retry the burst a few times
    rejected, slowed = 0, False
    for _ in range(4):
        t0 = time.perf_counter()
        for i in range(14):
            try:
                c.set(b"tk", b"s%d" % i, b"v")
            except PegasusError as e:
                assert e.status == Status.TRY_AGAIN
                rejected += 1
        slowed = slowed or (time.perf_counter() - t0) > 0.15
        if rejected and slowed:
            break
    assert rejected > 0, "reject threshold never fired"
    assert slowed, "delay throttling never slowed the burst"
    # disabling the env restores full service
    cluster.ddl(RPC_CM_SET_APP_ENVS,
                mm.SetAppEnvsRequest(app_name="thr",
                                     envs_json='{"replica.write_throttling":'
                                               ' ""}'),
                mm.SetAppEnvsResponse)
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            time.sleep(0.2)
            c.set(b"tk2", b"s", b"v")
            break
        except PegasusError:
            continue
    assert c.get(b"tk2", b"s") == b"v"
    c.close()


def test_read_throttling_env(cluster):
    c = make_client(cluster, app="rthr", partitions=1)
    c.set(b"rk", b"s", b"v")
    r = cluster.ddl(RPC_CM_SET_APP_ENVS,
                    mm.SetAppEnvsRequest(
                        app_name="rthr",
                        envs_json='{"replica.read_throttling": "5*reject*0"}'),
                    mm.SetAppEnvsResponse)
    assert r.error == 0
    deadline = time.time() + 5
    while time.time() < deadline:
        ok = any(rep.server.read_qps_throttler.enabled
                 for stub in cluster.nodes.values()
                 for (aid, _), rep in stub._replicas.items()
                 if aid == c.resolver.app_id)
        if ok:
            break
        time.sleep(0.1)
    assert ok
    rejected = 0
    for _ in range(10):
        try:
            c.get(b"rk", b"s")
        except PegasusError as e:
            assert e.status == Status.TRY_AGAIN
            rejected += 1
    assert rejected > 0
    c.close()


def test_list_nodes_fd_view(cluster):
    time.sleep(0.3)
    r = cluster.ddl(RPC_CM_LIST_NODES, mm.ListNodesRequest(), mm.ListNodesResponse)
    assert len(r.nodes) == 3
    assert all(n.alive for n in r.nodes)


def test_meta_state_survives_restart(tmp_path):
    c = Cluster(tmp_path)
    try:
        cl = make_client(c, app="t6", partitions=2)
        cl.set(b"h", b"s", b"v")
        cl.close()
        state_path = c.meta.state_path
        m2 = MetaServer(state_path)
        assert "t6" in m2._apps
        assert len(m2._parts[m2._apps["t6"].app_id]) == 2
    finally:
        c.stop()


def test_propose_and_balance(cluster):
    c = make_client(cluster, app="bal", partitions=8)
    for i in range(16):
        c.set(b"balk%d" % i, b"s", b"v%d" % i)
    app_id = c.resolver.app_id
    pc = cluster.meta._parts[app_id][0]
    target = pc.secondaries[0]
    old_primary = pc.primary
    r = cluster.ddl("RPC_CM_PROPOSE_BALANCER",
                    mm.ProposeRequest("bal", 0, target), mm.ProposeResponse)
    assert r.error == 0
    assert pc.primary == target and old_primary in pc.secondaries
    # data still fully served after the primary move
    for i in range(16):
        assert c.get(b"balk%d" % i, b"s") == b"v%d" % i
    # skew primaries onto one node, then balance
    node0 = cluster.meta._alive_nodes_locked()[0]
    for pc in cluster.meta._parts[app_id]:
        if pc.primary != node0 and node0 in pc.secondaries:
            cluster.ddl("RPC_CM_PROPOSE_BALANCER",
                        mm.ProposeRequest("bal", pc.pidx, node0),
                        mm.ProposeResponse)
    r = cluster.ddl("RPC_CM_START_BALANCE", mm.BalanceRequest(),
                    mm.BalanceResponse)
    counts = {}
    for pc in cluster.meta._parts[app_id]:
        counts[pc.primary] = counts.get(pc.primary, 0) + 1
    assert max(counts.values()) - min(counts.values()) <= 2
    for i in range(16):
        assert c.get(b"balk%d" % i, b"s") == b"v%d" % i
    c.close()


def test_balance_copy_secondary_to_new_node(tmp_path):
    """A node added to a loaded cluster starts empty; balance must migrate
    replicas onto it (greedy_load_balancer's copy_secondary stage), not
    just shuffle primaries among the old members."""
    c = Cluster(tmp_path)
    try:
        cl = make_client(c, app="cpbal", partitions=8)
        for i in range(32):
            cl.set(b"cp%d" % i, b"s", b"v%d" % i)
        new = ReplicaStub(str(tmp_path / "node_new"), [c.meta_addr],
                          options_factory=lambda: EngineOptions(backend="cpu"))
        new.start(beacon_interval=0.2)
        c.nodes[new.address] = new
        deadline = time.time() + 10
        while time.time() < deadline:
            if new.address in c.meta._alive_nodes_locked():
                break
            time.sleep(0.1)
        assert new.address in c.meta._alive_nodes_locked()
        r = c.ddl("RPC_CM_START_BALANCE", mm.BalanceRequest(),
                  mm.BalanceResponse)
        assert r.error == 0 and r.moved > 0
        with c.meta._lock:
            loads = {a: c.meta._node_load_locked(a)
                     for a in c.meta._alive_nodes_locked()}
        assert loads[new.address] > 0, "new node received no replicas"
        assert max(loads.values()) - min(loads.values()) < 2
        # membership stays 3-wide and disjoint per partition
        app_id = cl.resolver.app_id
        for pc in c.meta._parts[app_id]:
            members = [pc.primary] + pc.secondaries
            assert len(members) == 3 and len(set(members)) == 3
        # every record still served after the migrations
        for i in range(32):
            assert cl.get(b"cp%d" % i, b"s") == b"v%d" % i
        # writes still replicate (quorum intact through moved members)
        cl.set(b"cp_post", b"s", b"after")
        assert cl.get(b"cp_post", b"s") == b"after"
        cl.close()
    finally:
        c.stop()


def test_backup_request_reads_from_secondary(tmp_path):
    """backup_request serves reads from a secondary while the primary is
    down and the FD grace has NOT yet expired (no reconfiguration)."""
    c = Cluster(tmp_path, fd_grace=3600.0)  # meta will not fail over
    try:
        cli = make_client(c, app="bq", partitions=1)
        for i in range(10):
            cli.set(b"bq%d" % i, b"s", b"v%d" % i)
        # secondaries apply up to the commit point piggybacked on the NEXT
        # prepare; a sentinel write pushes bq0..bq9 below that point
        cli.set(b"sentinel", b"s", b"x")
        victim = c.meta._parts[cli.resolver.app_id][0].primary
        stub = c.nodes.pop(victim)
        stub.stop()
        # fresh clients (no pooled connections into the dead node): a plain
        # one cannot read — primary gone and no failover yet
        import pytest as _p

        plain = PegasusClient(MetaResolver([c.meta_addr], "bq"), timeout=1.5)
        with _p.raises(PegasusError):
            plain.get(b"bq1", b"s")
        plain.close()
        # backup-request client reads from a secondary
        bq = PegasusClient(MetaResolver([c.meta_addr], "bq"),
                           timeout=1.5, backup_request=True)
        for i in range(10):
            assert bq.get(b"bq%d" % i, b"s") == b"v%d" % i
        assert bq.sortkey_count(b"bq3") == 1
        bq.close()
        cli.close()
    finally:
        c.stop()


def test_async_client_api(cluster):
    """The reference client API is half async_* (client.h:283-320); here
    async_* returns a Future and also honors callback(error, result)."""
    import concurrent.futures

    c = make_client(cluster, app="async_t", partitions=4)
    # futures fan-out
    futs = [c.async_set(b"ak%d" % i, b"s", b"av%d" % i) for i in range(24)]
    concurrent.futures.wait(futs, timeout=30)
    assert all(f.exception() is None for f in futs)
    gets = [c.async_get(b"ak%d" % i, b"s") for i in range(24)]
    assert [g.result(timeout=10) for g in gets] == \
        [b"av%d" % i for i in range(24)]
    # callback idiom
    done = threading.Event()
    seen = {}

    def cb(err, value):
        seen["err"], seen["value"] = err, value
        done.set()

    c.async_get(b"ak3", b"s", callback=cb)
    assert done.wait(10) and seen == {"err": 0, "value": b"av3"}
    # multi ops + incr through the async surface
    assert c.async_multi_set(b"arow", {b"a": b"1", b"b": b"2"}).result(10) is None
    ok, kvs = c.async_multi_get(b"arow").result(10)
    assert ok and kvs == {b"a": b"1", b"b": b"2"}
    assert c.async_incr(b"acnt", b"c", 5).result(10) == 5
    assert c.async_sortkey_count(b"arow").result(10) == 2
    assert c.async_multi_del(b"arow", [b"a", b"b"]).result(10) == 2
    # failure surfaces through the callback error code, not an exception
    bad = {}
    done2 = threading.Event()
    c2 = PegasusClient(MetaResolver([cluster.meta_addr], "async_t"),
                       timeout=1.0)
    c2.async_incr(b"ak1", b"s", 1,
                  callback=lambda e, v: (bad.update(err=e), done2.set()))
    assert done2.wait(10) and bad["err"] != 0  # non-integer value
    c2.close()
    c.close()


def test_http_info_endpoints(tmp_path):
    """rDSN http_service analogues: /version + cluster/app/replica info
    over the meta's and a replica's HTTP ports (SURVEY §2.4 'HTTP
    service')."""
    import json as _json
    import urllib.request

    from pegasus_tpu.runtime.config import Config
    from pegasus_tpu.runtime.service_app import MetaApp, ReplicaApp

    ini = tmp_path / "app.ini"
    ini.write_text(f"""
[apps.meta]
type = meta
port = 0
state_dir = {tmp_path}/meta
http_port = 0

[apps.replica1]
type = replica
port = 0
data_dir = {tmp_path}/replica1
http_port = 0

[pegasus.server]
meta_servers = 127.0.0.1:0

[failure_detector]
beacon_interval_seconds = 0.2
""")
    cfg = Config(str(ini))
    meta_app = MetaApp("meta", cfg, "apps.meta")
    meta_app.start()
    try:
        # point the replica at the real (ephemeral) meta port
        cfg._parser.set("pegasus.server", "meta_servers", meta_app.address)
        rep_app = ReplicaApp("replica1", cfg, "apps.replica1").start()
        try:
            def fetch(reporter, path):
                host, port = reporter.address
                with urllib.request.urlopen(
                        f"http://{host}:{port}{path}", timeout=5) as r:
                    return _json.loads(r.read())

            v = fetch(meta_app.reporter, "/version")
            assert v["server_type"] == "meta" and "pegasus-tpu" in v["version"]
            # create a table so info endpoints have content
            from pegasus_tpu.meta import messages as mm
            from pegasus_tpu.meta.meta_server import RPC_CM_CREATE_APP
            from pegasus_tpu.rpc import codec
            from pegasus_tpu.rpc.transport import RpcConnection

            host, _, port = meta_app.address.rpartition(":")
            conn = RpcConnection((host, int(port)))
            conn.call(RPC_CM_CREATE_APP,
                      codec.encode(mm.CreateAppRequest("ht", 2, 1)),
                      timeout=10)
            conn.close()
            info = fetch(meta_app.reporter, "/meta/cluster_info")
            assert info["app_count"] == 1 and info["node_count"] == 1
            apps = fetch(meta_app.reporter, "/meta/apps")
            assert apps[0]["app_name"] == "ht"
            app = fetch(meta_app.reporter, "/meta/app?name=ht")
            assert len(app["partitions"]) == 2
            assert all(pc["primary"] for pc in app["partitions"])
            rv = fetch(rep_app.reporter, "/version")
            assert rv["server_type"] == "replica"
            rinfo = fetch(rep_app.reporter, "/replica/info")
            assert len(rinfo) == 2 and rinfo[0]["app_name"] == "ht"
        finally:
            rep_app.stop()
    finally:
        meta_app.stop()


def test_meta_level_blind_locks_down_ddl(cluster):
    """blind refuses every state-changing DDL but keeps queries, beacons,
    and the way back out (control_meta) working."""
    from pegasus_tpu.meta.meta_server import (RPC_CM_CONTROL_META,
                                              RPC_CM_CREATE_APP)

    c = make_client(cluster, app="blindtest", partitions=1)
    c.set(b"bk", b"s", b"v")
    r = cluster.ddl(RPC_CM_CONTROL_META,
                    mm.ControlMetaRequest(set_level="blind"),
                    mm.ControlMetaResponse)
    assert r.level == "blind"
    try:
        # DDL refused outright
        import pytest as _pytest

        from pegasus_tpu.rpc.transport import RpcError

        with _pytest.raises(RpcError):
            cluster.ddl(RPC_CM_CREATE_APP,
                        mm.CreateAppRequest(app_name="nope",
                                            partition_count=1,
                                            replica_count=3),
                        mm.CreateAppResponse)
        # queries + data path still served
        r = cluster.ddl(RPC_CM_LIST_NODES, mm.ListNodesRequest(),
                        mm.ListNodesResponse)
        assert any(n.alive for n in r.nodes)
        assert c.get(b"bk", b"s") == b"v"
    finally:
        r = cluster.ddl(RPC_CM_CONTROL_META,
                        mm.ControlMetaRequest(set_level="lively"),
                        mm.ControlMetaResponse)
        assert r.level == "lively"
    c.close()
