"""Multi-process partition-group serving (replication/serve_groups.py).

The onebox coverage the tentpole requires: >=2 group-executor processes
behind one node address, cross-group routing via both the sharded
fd-handoff fast path (PegasusClient) and the unsharded per-frame relay
(raw RpcConnection), the partition-hash sanity error propagating through
the router, node-level fan-out, and the chaos path — kill one group mid
traffic (clean bounded errors, sibling group unaffected), restart it and
re-serve reads AND writes. conftest's session reaper guarantees no worker
process outlives the suite.
"""

import time

import pytest

from pegasus_tpu.base import key_schema
from pegasus_tpu.client.client import PegasusError
from pegasus_tpu.replication.serve_groups import group_of
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc.transport import (ERR_BUSY, ERR_INVALID_STATE,
                                       ERR_NETWORK_FAILURE, RpcConnection,
                                       RpcError, RpcServer)
from tests.test_satellites import MiniCluster

PARTITIONS = 4
GROUPS = 2


@pytest.fixture(scope="module")
def gcluster(tmp_path_factory):
    c = MiniCluster(tmp_path_factory.mktemp("grp"), n_nodes=2,
                    serve_groups=GROUPS)
    c.cli = c.create("gt", partitions=PARTITIONS, replicas=2)
    yield c
    c.cli.close()
    c.stop()


def _pidx(hk: bytes, sk: bytes = b"sk") -> int:
    return key_schema.key_hash(key_schema.generate_key(hk, sk)) % PARTITIONS


def _keys_for_group(g: int, n: int):
    """n hash keys whose partitions belong to group g."""
    out, i = [], 0
    while len(out) < n:
        hk = b"gk%d" % i
        if group_of(1, _pidx(hk), GROUPS) == g:
            out.append(hk)
        i += 1
    return out


def test_cross_group_routing_sharded_client(gcluster):
    """Every partition (both groups) serves point ops and scans through
    the public node address, AND the sharded client connections really
    were handed off to the owning executors — if the SCM_RIGHTS fast
    path silently regressed to all-relay, this must fail, not pass
    through the fallback."""
    from pegasus_tpu.runtime.perf_counters import counters

    cli = gcluster.cli
    hit = set()
    for i in range(60):
        hk = b"hk%d" % i
        cli.set(hk, b"sk", b"v%d" % i)
        hit.add(group_of(1, _pidx(hk), GROUPS))
    assert hit == {0, 1}, "keys must land on BOTH groups"
    for i in range(60):
        assert cli.get(b"hk%d" % i, b"sk") == b"v%d" % i
    rows = {hk for hk, _, _ in cli.get_scanner()}
    assert {b"hk%d" % i for i in range(60)} <= rows
    # raw accumulator, not value(): the rate's rolling window could have
    # rolled to 0 between the traffic and this read
    assert counters.rate("serve.group.handoff_count")._value >= 1, \
        "sharded connections must be handed off, not relayed"
    snap = counters.snapshot(prefix="serve.group")
    assert snap.get("serve.group.active") == GROUPS


def test_partition_hash_sanity_error_via_relay(gcluster):
    """An unsharded raw connection stays on the parent's relay path; a
    deliberately misrouted partition_index must surface the worker's
    partition-hash sanity rejection, not hang or misserve."""
    from pegasus_tpu.rpc import messages as msg

    node = gcluster.stubs[0]
    host, _, port = node.address.rpartition(":")
    conn = RpcConnection((host, int(port)))
    try:
        key = key_schema.generate_key(b"sane", b"sk")
        h = key_schema.key_hash(key)
        right = h % PARTITIONS
        wrong = (right + 1) % PARTITIONS
        with pytest.raises(RpcError) as ei:
            conn.call("RPC_RRDB_RRDB_GET", codec.encode(msg.KeyRequest(key)),
                      app_id=1, partition_index=wrong, partition_hash=h,
                      timeout=10.0)
        assert ei.value.err in (ERR_INVALID_STATE,), ei.value
        assert "partition hash" in ei.value.text
    finally:
        conn.close()


def test_node_level_fanout_merges_groups(gcluster):
    """A node-level remote command has no partition route: the router
    fans it out to every group executor and joins the results."""
    from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                    RemoteCommandResponse)

    node = gcluster.stubs[0]
    host, _, port = node.address.rpartition(":")
    conn = RpcConnection((host, int(port)))
    try:
        _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
            RemoteCommandRequest("flush-log", [])), timeout=30.0)
        result = codec.decode(RemoteCommandResponse, body).output
        # one "flushed N logs" line per group executor
        assert len([l for l in result.splitlines() if "flushed" in l]) \
            == GROUPS, result
    finally:
        conn.close()


def test_batch_get_fanout(gcluster):
    """batch_get pipelines per-(node, partition) waves across both
    groups; order and NOT_FOUND semantics match per-key get."""
    cli = gcluster.cli
    items = [(b"bg%d" % i, b"sk") for i in range(20)]
    for hk, sk in items:
        cli.set(hk, sk, b"val-" + hk)
    vals = cli.batch_get(items + [(b"bg-missing", b"sk")])
    assert vals[:-1] == [b"val-" + hk for hk, _ in items]
    assert vals[-1] is None


def test_unordered_scanners_prefetch(gcluster):
    """get_unordered_scanners opens every partition's session as one
    fan-out wave; the union of scanners covers every written key."""
    cli = gcluster.cli
    want = set()
    for i in range(24):
        hk = b"sc%d" % i
        cli.set(hk, b"sk", b"x")
        want.add(hk)
    got = set()
    for sc in cli.get_unordered_scanners():
        for hk, _, _ in sc:
            got.add(hk)
    assert want <= got


def test_kill_group_clean_errors_then_restart_reserves(gcluster):
    """Kill group 0 on every node mid-traffic: its partitions fail FAST
    with clean errors (no hangs), group 1 keeps serving, and after
    restart_group the partitions re-serve reads AND writes (parent
    replays its cached open-replica state; decrees recover from plog)."""
    cli = gcluster.cli
    g0 = _keys_for_group(0, 6)
    g1 = _keys_for_group(1, 6)
    for hk in g0 + g1:
        cli.set(hk, b"sk", b"pre")
    for node in gcluster.stubs:
        node.kill_group(0)
    old_timeout, cli.timeout = cli.timeout, 5.0
    try:
        t0 = time.monotonic()
        for hk in g0[:3]:
            with pytest.raises(PegasusError):
                cli.get(hk, b"sk")
        assert time.monotonic() - t0 < 30, "dead-group errors must be fast"
        for hk in g1:     # the sibling group is unaffected
            assert cli.get(hk, b"sk") == b"pre"
        for node in gcluster.stubs:
            node.restart_group(0)
        for hk in g0:
            assert cli.get(hk, b"sk") == b"pre"   # state survived the kill
        cli.set(g0[0], b"sk", b"post")            # writes re-quorum too
        assert cli.get(g0[0], b"sk") == b"post"
    finally:
        cli.timeout = old_timeout
    from pegasus_tpu.runtime.perf_counters import counters

    # the monotone total, NOT the raw window accumulator: the metric-
    # history sampler (and any other scraper) rolls the rate window on a
    # cadence, zeroing _value at arbitrary points mid-test
    assert counters.rate("serve.group.restart_count").total() \
        >= len(gcluster.stubs), "every node must have restarted group 0"
    snap = counters.snapshot(prefix="serve.group")
    assert snap.get("serve.group.active") == GROUPS


def test_partition_split_crosses_groups(tmp_path):
    """Partition split on a grouped node: a child partition's owner group
    can differ from its parent's (child pidx = parent + old_count, and
    old_count % n_groups != 0 moves the group) — the stub must learn
    across sibling executors through the public router instead of
    silently skipping the seed. Partition counts are powers of two, so
    3 groups guarantees every child of a 4-partition app crosses."""
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_SPLIT_APP

    c = MiniCluster(tmp_path, n_nodes=2, serve_groups=3)
    cli = c.create("spl", partitions=4, replicas=2)
    try:
        before = cli.resolver.partition_count
        assert any(group_of(1, p, 3) != group_of(1, p + before, 3)
                   for p in range(before)), "fixture must cross groups"
        rows = {b"sp%d" % i: b"v%d" % i for i in range(40)}
        for hk, v in rows.items():
            cli.set(hk, b"sk", v)
        r = c.ddl(RPC_CM_SPLIT_APP, mm.SplitAppRequest("spl"),
                  mm.SplitAppResponse)
        assert r.error == 0, r.error_text
        cli.resolver.refresh()
        assert cli.resolver.partition_count == 2 * before
        for hk, v in rows.items():
            assert cli.get(hk, b"sk") == v, hk
    finally:
        cli.close()
        c.stop()


# --------------------------------------------------- dispatch chaos seam


def test_serve_dispatch_fail_point():
    """serve.dispatch is the wedged-group chaos seam: raise() rejects the
    request with ERR_BUSY (clean error, connection survives), sleep()
    stalls dispatch for its duration (the client's timeout is the
    bound)."""
    from pegasus_tpu.runtime import fail_points

    srv = RpcServer().start()
    srv.register("ECHO", lambda h, b: b)
    conn = RpcConnection(srv.address)
    fail_points.setup()
    try:
        fail_points.cfg("serve.dispatch", "raise(wedged group)")
        with pytest.raises(RpcError) as ei:
            conn.call("ECHO", b"x", timeout=5.0)
        assert ei.value.err == ERR_BUSY
        fail_points.cfg("serve.dispatch", "sleep(50)")
        t0 = time.monotonic()
        _, body = conn.call("ECHO", b"y", timeout=5.0)
        assert body == b"y" and time.monotonic() - t0 >= 0.05
        fail_points.cfg("serve.dispatch", "off()")
        _, body = conn.call("ECHO", b"z", timeout=5.0)
        assert body == b"z"
    finally:
        fail_points.teardown()
        conn.close()
        srv.stop()


def test_dispatch_queue_depth_gauge_exports():
    """Bounded dispatch: beyond-pool requests QUEUE (no raw thread per
    request) and the backlog is observable via
    rpc.server.dispatch_queue_depth."""
    import threading

    from pegasus_tpu.runtime.perf_counters import counters

    srv = RpcServer().start()
    gate = threading.Event()

    def slow(h, b):
        gate.wait(10.0)
        return b

    srv.register("SLOW", slow)
    conns = [RpcConnection(srv.address) for _ in range(4)]
    try:
        n = srv.POOL_WORKERS + 8
        pends = []
        for i in range(n):
            conn = conns[i % len(conns)]
            pends.append((conn, conn.call_many_send([("SLOW", b"x")])))
        # generous: late in a full tier-1 run this process carries many
        # hundreds of live threads, and GIL scheduling can take seconds
        # to drain 24 reads through 4 connection read loops
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            with srv._busy_lock:
                busy = srv._busy
            if busy == n:
                break
            time.sleep(0.02)
        # pool saturated (16 running) + 8 QUEUED — no raw overflow thread
        assert busy == n, f"expected {n} submitted-not-finished, saw {busy}"
        # the backlog is exported on /metrics (the gauge is process-global,
        # so other in-process servers may overwrite the value — presence +
        # final drain-to-zero are the stable assertions)
        assert "rpc.server.dispatch_queue_depth" in counters.snapshot()
        gate.set()
        for conn, pend in pends:
            conn.call_many_collect(pend, [("SLOW", b"x")], timeout=20.0)
        with srv._busy_lock:
            assert srv._busy == 0
        assert counters.number(
            "rpc.server.dispatch_queue_depth").value() >= 0
    finally:
        gate.set()
        for c in conns:
            c.close()
        srv.stop()
