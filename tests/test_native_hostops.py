"""Differential tests: native hostops (C++, ctypes) vs the numpy
fallbacks. crc64 is the PARTITION HASH — a native/numpy divergence would
route the same key to different partitions depending on whether a host
could compile the library, silently splitting a table's data."""

import numpy as np
import pytest

from pegasus_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native hostops unavailable")


def _arena(keys):
    arena = np.frombuffer(b"".join(keys), dtype=np.uint8).copy()
    lens = np.array([len(k) for k in keys], np.int64)
    offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64)
    return arena, offs, lens


@pytest.mark.parametrize("seed", [0, 1])
def test_crc64_native_matches_numpy(seed):
    from pegasus_tpu.base.crc64 import crc64_batch_numpy

    rng = np.random.default_rng(seed)
    keys = [rng.bytes(int(rng.integers(0, 60))) for _ in range(500)]
    keys += [b"", b"\x00", b"a" * 255]
    arena, offs, lens = _arena(keys)
    want = crc64_batch_numpy(arena, offs, lens)
    got = native.crc64_batch(arena, offs, lens)
    assert np.array_equal(got, want)


def test_pack_prefixes_native_matches_numpy():
    from pegasus_tpu.ops import packing

    rng = np.random.default_rng(3)
    keys = [rng.bytes(int(rng.integers(1, 50))) for _ in range(300)]
    arena, offs, lens = _arena(keys)
    lens32 = lens.astype(np.int32)
    for w in (1, 4, 8):
        got = native.pack_prefixes(arena, offs, lens32, w)
        # the numpy fallback lives inside pack_key_prefixes' else branch;
        # reproduce it directly
        pos = np.arange(w * 4, dtype=np.int64)
        idx = offs[:, None] + pos[None, :]
        valid = pos[None, :] < lens[:, None]
        b = np.where(valid, arena[np.minimum(idx, len(arena) - 1)],
                     0).astype(np.uint32)
        want = (
            (b[:, 0::4] << 24) | (b[:, 1::4] << 16)
            | (b[:, 2::4] << 8) | b[:, 3::4]
        ).astype(np.uint32)
        assert np.array_equal(np.asarray(got), want), w


def test_merge_counts_native_matches_searchsorted():
    rng = np.random.default_rng(5)
    for itemsize, na, nb in ((8, 400, 300), (16, 256, 256), (24, 100, 999)):
        a = np.sort(rng.integers(0, 1 << 62, size=na, dtype=np.int64)
                    .astype(f">u8").view(f"S8"))
        b = np.sort(rng.integers(0, 1 << 62, size=nb, dtype=np.int64)
                    .astype(f">u8").view(f"S8"))
        if itemsize != 8:
            reps = itemsize // 8
            a = np.sort(np.array([x * reps for x in a.tolist()],
                                 dtype=f"S{itemsize}"))
            b = np.sort(np.array([x * reps for x in b.tolist()],
                                 dtype=f"S{itemsize}"))
        for side in ("left", "right"):
            got = native.merge_counts(a, b, side)
            want = np.searchsorted(b, a, side=side)
            assert np.array_equal(got, want), (itemsize, side)


def test_gather_arena_native_matches_fancy_indexing():
    rng = np.random.default_rng(7)
    keys = [rng.bytes(int(rng.integers(0, 40))) for _ in range(200)]
    arena, offs, lens = _arena(keys)
    lens32 = lens.astype(np.int32)
    idx = rng.permutation(200)[:120].astype(np.int64)
    out, out_off = native.gather_arena(arena, offs, lens32, idx)
    want = b"".join(keys[i] for i in idx)
    assert out.tobytes() == want
    assert np.array_equal(out_off,
                          np.concatenate([[0], np.cumsum(lens32[idx][:-1])]))


def test_gather_block_uniform_native_matches_fancy_indexing():
    rng = np.random.default_rng(9)
    n, klen, vlen = 300, 12, 40
    key_arena = rng.integers(0, 256, size=n * klen, dtype=np.uint8)
    val_arena = rng.integers(0, 256, size=n * vlen, dtype=np.uint8)
    expire = rng.integers(0, 1000, size=n, dtype=np.uint32)
    hash32 = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
    deleted = rng.random(n) < 0.2
    idx = rng.permutation(n)[:150].astype(np.int32)
    m = len(idx)
    out_k = np.empty(m * klen, np.uint8)
    out_v = np.empty(m * vlen, np.uint8)
    out_e = np.empty(m, np.uint32)
    out_h = np.empty(m, np.uint32)
    out_d = np.empty(m, np.bool_)
    assert native.gather_block_uniform(key_arena, klen, val_arena, vlen,
                                       expire, hash32, deleted, idx,
                                       out_k, out_v, out_e, out_h, out_d)
    assert np.array_equal(out_k.reshape(m, klen),
                          key_arena.reshape(n, klen)[idx])
    assert np.array_equal(out_v.reshape(m, vlen),
                          val_arena.reshape(n, vlen)[idx])
    assert np.array_equal(out_e, expire[idx])
    assert np.array_equal(out_h, hash32[idx])
    assert np.array_equal(out_d, deleted[idx])
