"""ISSUE 8 acceptance + chaos: decree-anchored consistency audits, the
replication-lag plane, and the cluster doctor's one-verdict fold.

Onebox acceptance (pinned here):
  - under concurrent YCSB-A-style load, `trigger_audit` across all
    partitions reports ZERO mismatches, with identical digests at
    identical decrees on every replica;
  - with the `audit.digest` fail point armed on one secondary,
    `cluster_doctor` returns `critical` naming exactly that
    (app, pidx, node);
  - a mid-audit node kill degrades the audit to `inconclusive` — never a
    false mismatch.
"""

import json
import threading
import time

import pytest

from pegasus_tpu.collector.cluster_doctor import (ClusterCaller,
                                                  run_cluster_audit,
                                                  run_cluster_doctor)
from pegasus_tpu.collector.info_collector import rollup_slow_requests
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.meta.meta_server import RPC_CM_QUERY_CONFIG
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.perf_counters import counters

from tests.test_satellites import MiniCluster


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(tmp_path)
    yield c
    c.stop()


@pytest.fixture
def failpoints():
    fp.setup()
    yield fp
    fp.teardown()


def _quiet_breakers():
    """The counter registry is process-global: an earlier test's tripped
    lane breaker or queue-depth gauge must not leak into a healthy-verdict
    assertion here."""
    counters.number("compact.lane.breaker_open").set(0)
    counters.number("read.lane.breaker_open").set(0)
    counters.number("rpc.server.dispatch_queue_depth").set(0)


def _partition_members(cluster, app_name, pidx):
    cfg = cluster.ddl(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest(app_name),
                      mm.QueryConfigResponse)
    pc = cfg.partitions[pidx]
    return cfg.app.app_id, pc.primary, list(pc.secondaries)


class _Load:
    """Background YCSB-A-ish read/update mix against one table."""

    def __init__(self, cli, keys=64, threads=3):
        self.cli = cli
        self.stop = threading.Event()
        self.errors = []
        self.ops = 0

        def worker(tid):
            i = 0
            while not self.stop.is_set():
                k = b"user%05d" % ((i * 7 + tid * 13) % keys)
                try:
                    if i % 2:
                        self.cli.get(k, b"f0")
                    else:
                        self.cli.set(k, b"f0", b"v%d.%d" % (tid, i))
                    self.ops += 1
                except Exception as e:  # noqa: BLE001 - recorded, asserted
                    self.errors.append(repr(e))
                i += 1

        self.threads = [threading.Thread(target=worker, args=(t,))
                        for t in range(threads)]

    def __enter__(self):
        for t in self.threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=10)
        return False


# ------------------------------------------------------- onebox acceptance


def test_audit_under_load_zero_mismatches(cluster):
    """The acceptance shape: concurrent load, audit across every
    partition, identical digests at identical decrees on ALL replicas."""
    cli = cluster.create("ycsbish", partitions=4)
    for i in range(64):
        cli.set(b"user%05d" % i, b"f0", b"init%d" % i)
    with _Load(cli) as load:
        time.sleep(0.2)  # the audit must race real traffic
        report = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
    assert report["mismatches"] == []
    assert report["inconclusive"] == []
    assert sorted(report["ok"]) == sorted(report["digests"])
    assert report["partitions"] == 4 and len(report["ok"]) == 4
    for gpid, per_node in report["digests"].items():
        assert len(per_node) == 3, f"{gpid}: not every replica reported"
        decrees = {d["decree"] for d in per_node.values()}
        digests = {d["digest"] for d in per_node.values()}
        assert len(decrees) == 1, f"{gpid}: digests at different decrees"
        assert len(digests) == 1, f"{gpid}: digest mismatch {per_node}"
    assert load.ops > 0 and not load.errors
    cli.close()


def test_corrupt_secondary_flags_exactly_that_partition(cluster, failpoints):
    """audit.digest armed on ONE secondary of ONE partition: the audit
    names exactly (app, pidx, node); the doctor goes critical with the
    same naming; every other partition stays clean."""
    cli = cluster.create("audchaos", partitions=2)
    for i in range(40):
        cli.set(b"k%03d" % i, b"s", b"v%d" % i)
    app_id, primary, secondaries = _partition_members(cluster, "audchaos", 0)
    victim = secondaries[0]
    # clean baseline first: the doctor must call THIS cluster healthy
    clean = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
    assert clean["mismatches"] == [] and len(clean["ok"]) == 2
    time.sleep(0.5)  # beacons fold the audit states into the meta
    _quiet_breakers()
    verdict = run_cluster_doctor([cluster.meta_addr])
    assert verdict["verdict"] == "healthy", verdict["causes"]
    assert verdict["evidence"]["audit"]["mismatches"] == []

    failpoints.cfg("audit.digest", f"return({victim}@{app_id}.0)")
    report = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
    assert len(report["mismatches"]) == 1
    m = report["mismatches"][0]
    assert (m["app"], m["pidx"], m["node"]) == ("audchaos", 0, victim)
    assert m["digest"].startswith("deadbeef")
    assert m["digest"] != m["expected"]
    # the OTHER partition's replicas still agree
    assert f"{app_id}.1" in report["ok"]

    time.sleep(0.6)  # corrupted digest rides the next beacons
    verdict = run_cluster_doctor([cluster.meta_addr])
    assert verdict["verdict"] == "critical"
    crit = [c for c in verdict["causes"] if c["severity"] == "critical"]
    assert any(f"{app_id}.0" in c["cause"] and victim in c["cause"]
               for c in crit), crit
    mm_ = verdict["evidence"]["audit"]["mismatches"]
    assert any(e["gpid"] == f"{app_id}.0" and e["node"] == victim
               for e in mm_)
    cli.close()


def test_midaudit_node_kill_is_inconclusive_not_mismatch(cluster):
    """Kill a member mid-audit: the partition degrades to inconclusive
    (the dead node is named) and NEVER reports a false mismatch — the
    equal-decree comparison rule."""
    cli = cluster.create("audkill", partitions=2)
    for i in range(30):
        cli.set(b"k%03d" % i, b"s", b"v%d" % i)
    app_id, primary, secondaries = _partition_members(cluster, "audkill", 0)
    victim = secondaries[0]
    # trigger on the primary, then kill the secondary BEFORE collection —
    # a genuinely mid-audit death
    caller = ClusterCaller([cluster.meta_addr])
    out = json.loads(caller.remote_command(
        primary, "trigger-audit", [f"{app_id}.0"]))
    assert out["digest"] and out["decree"] > 0
    caller.close()
    for stub in list(cluster.stubs):
        if stub.address == victim:
            stub.stop()
            cluster.stubs.remove(stub)
    report = run_cluster_audit([cluster.meta_addr], wait_s=1.0)
    assert report["mismatches"] == [], \
        "a dead member must never fake a mismatch"
    assert any(e.get("node") == victim for e in report["inconclusive"]), \
        report["inconclusive"]
    # the doctor's audit evidence stays mismatch-free too (stale beacon
    # digests sit at an older decree: pending, not compared)
    verdict = run_cluster_doctor([cluster.meta_addr])
    assert verdict["evidence"]["audit"]["mismatches"] == []
    cli.close()


# ------------------------------------------------- replication-lag plane


def test_beacon_carries_committed_and_applied_distinctly(cluster):
    """Satellite: the beacon (and query_replica_info / replica-state)
    reports applied_decree distinct from committed_decree, so the lag
    gauges have a truthful source."""
    cli = cluster.create("lagt", partitions=1)
    for i in range(20):
        cli.set(b"k%d" % i, b"s", b"v")
    time.sleep(0.5)  # beacons land
    states = cluster.meta._node_states
    assert states, "beacons carried no replica_states"
    seen = 0
    for node, per_gpid in states.items():
        for gpid, st in per_gpid.items():
            assert "committed" in st and "applied" in st and "status" in st
            # healthy replicas: engine applied == replication committed
            assert st["applied"] == st["committed"]
            seen += 1
    assert seen >= 3  # every member of the 1-partition group reported
    # gauges exist per partition (process-global registry in the onebox)
    snap = counters.snapshot(prefix="replica.")
    assert any(k.endswith(".committed_decree") for k in snap)
    assert any(k.endswith(".applied_decree") for k in snap)
    assert any(k.endswith(".secondary_gap_max") for k in snap)
    # ReplicaStateResponse surfaces last_applied (append-only evolution)
    app_id, primary, _ = _partition_members(cluster, "lagt", 0)
    st = cluster.meta._query_replica_state(primary, app_id, 0)
    assert st is not None and st.last_applied == st.last_committed > 0
    cli.close()


def test_doctor_lag_fold_flags_commit_and_apply_distinctly(monkeypatch):
    """The lag fold names commit lag and apply lag as DISTINCT degraded
    causes (unit over the doctor's fold — deterministic, no beacon
    race)."""
    from pegasus_tpu.collector import cluster_doctor as cd

    monkeypatch.setenv("PEGASUS_DOCTOR_GAP_DEGRADED", "10")
    # lag is measured WITHIN each replica's own beacon snapshot (never
    # across nodes — beacons are asynchronous, cross-node compares would
    # flag healthy fast-writing clusters): commit lag = prepared-committed
    # (staged, commit point never arrived), apply lag = committed-applied
    state = {"replica_states": {
        "n1:1": {"1.0": {"gpid": "1.0", "status": "PRIMARY",
                         "prepared": 500, "committed": 500,
                         "applied": 500}},
        "n2:1": {"1.0": {"gpid": "1.0", "status": "SECONDARY",
                         "prepared": 500, "committed": 480,
                         "applied": 480}},   # commit lag
        "n3:1": {"1.0": {"gpid": "1.0", "status": "SECONDARY",
                         "prepared": 500, "committed": 500,
                         "applied": 420}},   # apply lag
    }}
    causes, evidence = [], {}
    cd._check_lag(state, causes, evidence)
    kinds = {(o["node"], o["kind"]) for o in evidence["lag"]["offenders"]}
    assert kinds == {("n2:1", "commit"), ("n3:1", "apply")}
    assert any("behind on COMMIT by 20" in c["cause"] and "n2:1" in c["cause"]
               for c in causes), causes
    assert any("behind on APPLY by 80" in c["cause"] and "n3:1" in c["cause"]
               for c in causes), causes
    assert evidence["lag"]["worst"] == {"commit_gap": 20, "apply_gap": 80}


# ------------------------------------------------ slow-request rollup


def test_slow_request_cluster_rollup_merges_worst_first():
    def fetch(node):
        base = {"n1": [{"trace_id": "a", "duration_us": 100, "op": "put"},
                       {"trace_id": "b", "duration_us": 900, "op": "get"}],
                "n2": [{"trace_id": "c", "duration_us": 500, "op": "put"}],
                "n3": "not json"}
        v = base[node]
        return v if isinstance(v, str) else json.dumps(v)

    merged = rollup_slow_requests(fetch, ["n1", "n2", "n3"], last=2)
    assert [t["trace_id"] for t in merged] == ["b", "c"]  # worst first
    assert merged[0]["node"] == "n1" and merged[1]["node"] == "n2"


def test_shell_slow_requests_cluster_and_doctor(cluster, monkeypatch):
    """`slow_requests --cluster` merges every node's ledger; the shell's
    cluster_doctor prints the one-verdict line."""
    import io

    from pegasus_tpu.runtime.tracing import REQUEST_TRACER
    from pegasus_tpu.shell.main import Shell

    cli = cluster.create("slowt", partitions=1)
    monkeypatch.setattr(REQUEST_TRACER, "slow_threshold_us", 1)
    cli.set(b"hk", b"s", b"v")  # every request is now "slow"
    _quiet_breakers()
    out = io.StringIO()
    sh = Shell([cluster.meta_addr], out=out)
    sh.run_line("slow_requests --cluster 5")
    merged = json.loads(out.getvalue())
    assert merged and all("node" in t and "spans" in t for t in merged)
    assert all(merged[i]["duration_us"] >= merged[i + 1]["duration_us"]
               for i in range(len(merged) - 1))
    out.truncate(0), out.seek(0)
    sh.run_line("cluster_doctor")
    text = out.getvalue()
    assert "cluster verdict: HEALTHY" in text
    cli.close()


# ------------------------------------------------------------ digest unit


def test_state_digest_layout_independent(tmp_path):
    """The digest is a function of logical contents only: flushing,
    compacting, or re-leveling must not change it; a data change must."""
    from pegasus_tpu.engine.db import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "e"), EngineOptions(backend="cpu"))
    d = 0
    for i in range(50):
        d += 1
        eng.put(b"k%03d" % i, b"v%d" % i, decree=d)
    now = 10_000
    base = eng.state_digest(now=now)
    assert base["records"] == 50
    eng.flush()
    assert eng.state_digest(now=now) == base, "flush changed the digest"
    eng.manual_compact(now=now)
    assert eng.state_digest(now=now) == base, "compaction changed the digest"
    # overwrite with the SAME bytes: still identical (newest-wins walk)
    d += 1
    eng.put(b"k000", b"v0", decree=d)
    assert eng.state_digest(now=now)["digest"] == base["digest"]
    # tombstone: digest changes, and compacting the tombstone away does
    # not change it back differently on this replica vs one that never
    # compacted (tombstones are excluded from the fold)
    d += 1
    eng.delete(b"k001", decree=d)
    after_del = eng.state_digest(now=now)
    assert after_del["digest"] != base["digest"]
    assert after_del["records"] == 49
    eng.flush()
    eng.manual_compact(now=now)
    assert eng.state_digest(now=now) == after_del
    eng.close()


def test_trigger_audit_is_a_noop_mutation(tmp_path):
    """trigger_audit advances the decree like any write but mutates no
    data; its digest matches an offline state_digest at the same clock."""
    from pegasus_tpu.engine.server_impl import PegasusServer
    from pegasus_tpu.rpc import messages as msg
    from pegasus_tpu.rpc.task_codes import RPC_TRIGGER_AUDIT

    srv = PegasusServer(str(tmp_path / "p"))
    srv.on_batched_write_requests(
        1, 0, [(RPC_TRIGGER_AUDIT,
                msg.TriggerAuditRequest(audit_id=7, now=5000))])
    assert srv.engine.last_committed_decree() == 1
    la = srv.last_audit
    assert la["audit_id"] == 7 and la["decree"] == 1 and la["records"] == 0
    assert la["digest"] == srv.engine.state_digest(now=5000)["digest"]
    srv.close()
