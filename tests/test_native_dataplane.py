"""Native read data plane (ISSUE 20): C dispatch waves, vectored reply
writes, zero-copy mmap SSTs — and the byte-identical Python twins.

Three pinned properties:

  * differential errors: adversarial frames (corrupt length words,
    truncated payloads, garbage headers) fail IDENTICALLY through the C
    FrameReader and the pure-Python reader — same exception class for
    the same poison;
  * byte identity: the same pipelined get/multi_get/scanner wave against
    a PEGASUS_NATIVE=0 server and a =1 server produces identical wire
    bytes per sequence number, including when the serve.native fail
    point forces the Python fallback MID-wave;
  * mmap lifetime: an SST loaded through the zero-copy path stays
    readable after the file is unlinked (compaction deletes its inputs
    while readers may still hold their blocks).
"""

import os
import socket
import struct

import numpy as np
import pytest

from pegasus_tpu import native
from pegasus_tpu.base import key_schema
from pegasus_tpu.client import PegasusClient, StaticResolver
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.replica_service import (RPC_GET, RPC_GET_SCANNER,
                                                RPC_MULTI_GET, RPC_SCAN,
                                                ReplicaService)
from pegasus_tpu.engine.server_impl import PegasusServer
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.transport import (RpcServer, RpcHeader, _FrameReader,
                                       make_frame_reader)
from pegasus_tpu.runtime import fail_points
from pegasus_tpu.runtime.perf_counters import counters

fc = native.fastcodec()
pytestmark = pytest.mark.skipif(
    fc is None, reason="fastcodec extension unavailable (no compiler?)")

APP_ID = 9
N_PARTITIONS = 2


def _frame(seq, code, body, pidx=0):
    h = codec.encode(RpcHeader(seq=seq, code=code, app_id=APP_ID,
                               partition_index=pidx))
    return struct.pack("<II", 4 + len(h) + len(body), len(h)) + h + body


def _c_reader(hot=()):
    fc.register_error(codec.CodecError)
    plan = codec._fast_plan(RpcHeader, fc)
    assert isinstance(plan, fc.Plan)
    return fc.FrameReader(plan, tuple(hot))


# ------------------------------------------------------------ wave parity


def test_wave_batched_binning_matches_python():
    """C read_wave_binned and the Python twin produce the same entry
    structure: hot codes coalesce at first arrival, others stay
    singleton, arrival order preserved."""
    frames = [
        _frame(1, RPC_GET, b"a"), _frame(2, "RPC_RRDB_RRDB_PUT", b"w"),
        _frame(3, RPC_GET, b"b"), _frame(4, RPC_SCAN, b"s"),
        _frame(5, RPC_GET, b"c"), _frame(6, RPC_SCAN, b"t"),
        _frame(7, "RPC_RRDB_RRDB_PUT", b"x"),
    ]
    blob = b"".join(frames)
    hot = (RPC_GET, RPC_SCAN)

    a, b = socket.socketpair()
    try:
        r = _c_reader(hot)
        a.sendall(blob)
        c_wave = r.read_wave_binned(b.fileno())
    finally:
        a.close()
        b.close()

    a2, b2 = socket.socketpair()
    try:
        py = _FrameReader(b2, hot=hot)
        a2.sendall(blob)
        py_wave = py.wave_batched()
    finally:
        a2.close()
        b2.close()

    def shape(wave):
        return [(code, [(h.seq, body) for h, body in fs])
                for code, fs in wave]

    assert shape(c_wave) == shape(py_wave) == [
        (RPC_GET, [(1, b"a"), (3, b"b"), (5, b"c")]),
        ("RPC_RRDB_RRDB_PUT", [(2, b"w")]),
        (RPC_SCAN, [(4, b"s"), (6, b"t")]),
        ("RPC_RRDB_RRDB_PUT", [(7, b"x")]),
    ]


def test_sendmsg_frames_matches_python_concat():
    """The vectored writer's bytes == the fallback bytearray's bytes."""
    h1 = codec.encode(RpcHeader(seq=3, code=RPC_GET, is_response=True))
    h2 = codec.encode(RpcHeader(seq=4, code=RPC_GET, is_response=True,
                                error=6, error_text="boom"))
    pairs = [(h1, b"value-one"), (h2, b""), (h1, os.urandom(4096))]
    expect = b"".join(
        struct.pack("<II", 4 + len(h) + len(b), len(h)) + h + b
        for h, b in pairs)
    a, b = socket.socketpair()
    try:
        sent = fc.sendmsg_frames(a.fileno(), pairs)
        assert sent == len(expect)
        got = bytearray()
        while len(got) < len(expect):
            got += b.recv(1 << 16)
        assert bytes(got) == expect
    finally:
        a.close()
        b.close()


def test_sendmsg_frames_peer_closed():
    a, b = socket.socketpair()
    b.close()
    try:
        h = codec.encode(RpcHeader(seq=1, code=RPC_GET, is_response=True))
        with pytest.raises((ConnectionError, OSError)):
            fc.sendmsg_frames(a.fileno(), [(h, b"x" * (1 << 20))] * 64)
    finally:
        a.close()


# ----------------------------------------------------- adversarial frames


def _c_poison(blob):
    a, b = socket.socketpair()
    try:
        r = _c_reader()
        a.sendall(blob)
        a.close()
        try:
            r.read_wave(b.fileno())
            return None
        except Exception as e:  # noqa: BLE001 - the class IS the assertion
            return type(e)
    finally:
        b.close()


def _py_poison(blob):
    a, b = socket.socketpair()
    try:
        a.sendall(blob)
        a.close()
        r = _FrameReader(b)
        try:
            r.wave()
            return None
        except Exception as e:  # noqa: BLE001 - the class IS the assertion
            return type(e)
    finally:
        b.close()


@pytest.mark.parametrize("name,blob", [
    # payload_len < 4: the frame cannot even hold its header-length word
    ("plen_too_small", struct.pack("<II", 2, 0) + b"xx"),
    # header_len exceeds payload_len - 4
    ("hlen_over_plen", struct.pack("<II", 10, 99) + b"x" * 6),
    # valid lengths, garbage header bytes (undecodable plan data)
    ("garbage_header", struct.pack("<II", 24, 20) + b"\xff" * 20),
    # truncated mid-payload then peer close
    ("truncated_frame", struct.pack("<II", 1000, 10) + b"x" * 20),
    # empty stream: peer closes immediately
    ("empty_close", b""),
])
def test_adversarial_frames_differential(name, blob):
    """Identical poison -> identical error class through C and Python."""
    c_exc, py_exc = _c_poison(blob), _py_poison(blob)
    assert c_exc is not None and py_exc is not None, name
    # corrupt framing surfaces as CodecError from both (the C reader
    # raises the registered class); a clean truncation is ConnectionError
    assert c_exc is py_exc, (name, c_exc, py_exc)


def test_trailing_bytes_after_header_differential():
    """A header shorter than header_len (trailing slack) errors in both
    readers — the C reader's explicit check vs the Python codec's."""
    h = codec.encode(RpcHeader(seq=1, code=RPC_GET))
    hl = len(h) + 4  # lie: claim 4 extra header bytes (eats body space)
    blob = struct.pack("<II", 4 + hl + 2, hl) + h + b"\x00" * 4 + b"ok"
    c_exc, py_exc = _c_poison(blob), _py_poison(blob)
    assert c_exc is not None and py_exc is not None
    assert issubclass(c_exc, codec.CodecError)
    assert issubclass(py_exc, codec.CodecError)


# --------------------------------------------------------- byte identity


def _run_leg(tmp_path, leg, request_frames):
    """Boot a fresh 1-node/2-partition replica server, load fixed data,
    fire `request_frames` as one pipelined wave over a raw socket, and
    return {seq: raw response frame bytes}."""
    root = tmp_path / leg
    svc = ReplicaService()
    rpc = RpcServer().start()
    try:
        for pidx in range(N_PARTITIONS):
            ps = PegasusServer(str(root / f"p{pidx}"), app_id=APP_ID,
                               pidx=pidx,
                               options=EngineOptions(backend="cpu"),
                               server="node0")
            svc.add_replica(ps, N_PARTITIONS)
        rpc.register_serverlet(svc)
        resolver = StaticResolver(APP_ID,
                                  [rpc.address] * N_PARTITIONS)
        client = PegasusClient(resolver)
        try:
            for i in range(8):
                client.set(b"hk%d" % i, b"sk", b"val-%d" % i)
            client.multi_set(b"multi", {b"a": b"1", b"b": b"2", b"c": b"3"})
        finally:
            client.close()

        s = socket.create_connection(rpc.address)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(b"".join(request_frames))
            got, buf = {}, bytearray()
            while len(got) < len(request_frames):
                chunk = s.recv(1 << 16)
                assert chunk, "server closed mid-response"
                buf += chunk
                while len(buf) >= 8:
                    plen, hlen = struct.unpack_from("<II", buf, 0)
                    if len(buf) < 4 + plen:
                        break
                    frame = bytes(buf[: 4 + plen])
                    header = codec.decode(RpcHeader, frame[8: 8 + hlen])
                    got[header.seq] = frame
                    del buf[: 4 + plen]
        finally:
            s.close()
        return got
    finally:
        rpc.stop()


def _identity_wave():
    """The pipelined request wave: point gets (hits, a miss, a bad
    partition), multi_gets, an exhausting scanner open (context id is
    the COMPLETED constant — deterministic) and a bogus-context scan."""
    frames, seq = [], 0

    def add(code, body, pidx=0):
        nonlocal seq
        seq += 1
        frames.append(_frame(seq, code, body, pidx=pidx))

    for i in range(8):
        key = key_schema.generate_key(b"hk%d" % i, b"sk")
        pidx = key_schema.key_hash(key) % N_PARTITIONS
        add(RPC_GET, codec.encode(msg.KeyRequest(key=key)), pidx=pidx)
    add(RPC_GET, codec.encode(msg.KeyRequest(
        key=key_schema.generate_key(b"nope", b"sk"))),
        pidx=key_schema.key_hash(
            key_schema.generate_key(b"nope", b"sk")) % N_PARTITIONS)
    add(RPC_GET, codec.encode(msg.KeyRequest(key=b"x")), pidx=7)  # no replica
    mkey = key_schema.generate_key(b"multi", b"")
    mpidx = key_schema.key_hash(mkey) % N_PARTITIONS
    add(RPC_MULTI_GET, codec.encode(msg.MultiGetRequest(hash_key=b"multi")),
        pidx=mpidx)
    add(RPC_MULTI_GET, codec.encode(msg.MultiGetRequest(
        hash_key=b"multi", sort_keys=[b"a", b"zz"])), pidx=mpidx)
    for pidx in range(N_PARTITIONS):
        add(RPC_GET_SCANNER, codec.encode(msg.GetScannerRequest(
            batch_size=10_000, validate_partition_hash=False)), pidx=pidx)
    add(RPC_SCAN, codec.encode(msg.ScanRequest(context_id=12345)), pidx=0)
    return frames


def test_byte_identity_native_vs_python(tmp_path, monkeypatch):
    wave = _identity_wave()
    monkeypatch.setenv("PEGASUS_NATIVE", "0")
    py_frames = _run_leg(tmp_path, "python", wave)
    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    nat_frames = _run_leg(tmp_path, "native", wave)
    assert set(py_frames) == set(nat_frames) == set(range(1, len(wave) + 1))
    for seq in py_frames:
        assert nat_frames[seq] == py_frames[seq], f"seq {seq} diverged"
    # the wave really exercised the batch plane: >= 8 gets coalesced
    assert len(wave) > 10


def test_byte_identity_midwave_fallback(tmp_path, monkeypatch):
    """serve.native armed to trigger a finite number of times: some
    batches/writes take the Python twin, later ones the native path —
    the wire must not be able to tell."""
    wave = _identity_wave()
    monkeypatch.setenv("PEGASUS_NATIVE", "0")
    py_frames = _run_leg(tmp_path, "python", wave)
    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    fail_points.setup()
    try:
        fail_points.cfg("serve.native", "3*return()")
        nat_frames = _run_leg(tmp_path, "native-fallback", wave)
    finally:
        fail_points.teardown()
    for seq in py_frames:
        assert nat_frames[seq] == py_frames[seq], f"seq {seq} diverged"


def test_batch_dispatch_counters(tmp_path, monkeypatch):
    """A pipelined get wave through the native plane moves the
    native.{wave_count,batch_frames,writev_count,writev_bytes} series."""
    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    names = ("native.wave_count", "native.batch_frames",
             "native.writev_count", "native.writev_bytes")
    base = {n: counters.rate(n).total() for n in names}
    _run_leg(tmp_path, "counters", _identity_wave())
    after = {n: counters.rate(n).total() for n in names}
    for n in names:
        assert after[n] > base[n], n


# ---------------------------------------------------------- mmap lifetime


def test_mmap_sst_survives_unlink(tmp_path, monkeypatch):
    """The zero-copy block stays readable after its file is deleted —
    the lifetime compaction relies on when it unlinks inputs while
    readers may still hold their blocks."""
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.engine import sstable

    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    rows = [(b"k%03d" % i, b"v%03d" % i, 0, False) for i in range(100)]
    block = KVBlock.from_records(rows)
    path = str(tmp_path / "x.sst")
    sstable.write_sst(path, block)
    loaded, header = sstable.read_sst(path)
    # zero-copy: the arena is a read-only VIEW over the mapping, not an
    # owning copy
    assert not loaded.key_arena.flags.writeable
    assert loaded.key_arena.base is not None
    os.unlink(path)
    assert not os.path.exists(path)
    for i in range(100):
        assert loaded.key(i) == b"k%03d" % i
        assert loaded.value(i) == b"v%03d" % i


def test_mmap_off_with_knob(tmp_path, monkeypatch):
    """PEGASUS_NATIVE=0 keeps the classic copying reader (writable,
    owning arrays) — and both paths materialize identical blocks."""
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.engine import sstable

    rows = [(b"a%02d" % i, os.urandom(64), 0, i % 7 == 0)
            for i in range(50)]
    block = KVBlock.from_records(rows)
    path = str(tmp_path / "y.sst")
    sstable.write_sst(path, block)
    monkeypatch.setenv("PEGASUS_NATIVE", "0")
    copied, _ = sstable.read_sst(path)
    assert copied.key_arena.flags.writeable
    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    mapped, _ = sstable.read_sst(path)
    for name in ("key_arena", "key_off", "key_len", "val_arena", "val_off",
                 "val_len", "expire_ts", "hash32", "deleted"):
        np.testing.assert_array_equal(getattr(copied, name),
                                      getattr(mapped, name))


def test_mmap_corruption_still_typed(tmp_path, monkeypatch):
    """The mmap reader keeps read_sst's typed-corruption contract."""
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.engine import sstable

    monkeypatch.setenv("PEGASUS_NATIVE", "1")
    block = KVBlock.from_records([(b"\x00\x01k", b"v", 0, False)])
    path = str(tmp_path / "z.sst")
    sstable.write_sst(path, block)
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF  # flip a section byte: crc must catch it
    with open(path, "wb") as f:
        f.write(data)
    with pytest.raises(sstable.CorruptionError):
        sstable.read_sst(path)
    with open(path, "wb") as f:
        f.write(data[:20])  # truncate into the header
    with pytest.raises(sstable.CorruptionError):
        sstable.read_sst(path)


# --------------------------------------------------------- reader gating


def test_make_frame_reader_respects_knob(monkeypatch):
    a, b = socket.socketpair()
    try:
        monkeypatch.setenv("PEGASUS_NATIVE", "0")
        assert isinstance(make_frame_reader(a), _FrameReader)
        monkeypatch.setenv("PEGASUS_NATIVE", "1")
        r = make_frame_reader(a, hot=(RPC_GET,))
        assert not isinstance(r, _FrameReader)
    finally:
        a.close()
        b.close()
