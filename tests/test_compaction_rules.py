"""User-specified compaction rule tests, mirroring
src/server/test compaction_filter_rule / compaction_operation tests and the
rule matrix of compaction_filter_rule.h:47-151 — on both backends with
identical bytes.
"""

import json

import numpy as np
import pytest

from pegasus_tpu.base import consts
from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.compaction_rules import (apply_operations,
                                                parse_user_specified_compaction)
from pegasus_tpu.engine.server_impl import PegasusServer
from pegasus_tpu.ops import CompactOptions, compact_blocks
from tests.test_compact_ops import make_block


def spec(*ops):
    return json.dumps({"ops": list(ops)})


def op(type_, params=None, rules=()):
    return {"type": type_, "params": json.dumps(params or {}),
            "rules": [{"type": t, "params": json.dumps(p)} for t, p in rules]}


def test_parse_skips_invalid_entries():
    assert parse_user_specified_compaction("not json") == []
    assert parse_user_specified_compaction(spec(
        op("COT_DELETE", rules=[]))) == []          # op without rules dropped
    ops = parse_user_specified_compaction(spec(
        op("COT_DELETE", rules=[("FRT_BOGUS", {})]),
        op("COT_DELETE",
           rules=[("FRT_HASHKEY_PATTERN",
                   {"pattern": "x", "match_type": "SMT_MATCH_PREFIX"})])))
    assert len(ops) == 1


@pytest.mark.parametrize("match_type,pattern,hk,expect", [
    ("SMT_MATCH_PREFIX", "user", b"user123", True),
    ("SMT_MATCH_PREFIX", "user", b"xuser", False),
    ("SMT_MATCH_POSTFIX", "123", b"user123", True),
    ("SMT_MATCH_POSTFIX", "123", b"123x", False),
    ("SMT_MATCH_ANYWHERE", "er1", b"user123", True),
    ("SMT_MATCH_ANYWHERE", "zzz", b"user123", False),
])
def test_hashkey_pattern_matrix(match_type, pattern, hk, expect):
    blk = make_block([(hk, b"s", b"v", 0, False)])
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE",
        rules=[("FRT_HASHKEY_PATTERN",
                {"pattern": pattern, "match_type": match_type})])))
    drop, _ = apply_operations(blk, ops, now=100)
    assert bool(drop[0]) is expect


def test_sortkey_pattern_rule():
    blk = make_block([(b"h", b"abc_keep", b"v", 0, False),
                      (b"h", b"drop_abc", b"v", 0, False)])
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE",
        rules=[("FRT_SORTKEY_PATTERN",
                {"pattern": "drop", "match_type": "SMT_MATCH_PREFIX"})])))
    drop, _ = apply_operations(blk, ops, now=100)
    assert list(drop) == [False, True]


def test_ttl_range_rule_matrix():
    now = 1000
    blk = make_block([
        (b"h", b"nottl", b"v", 0, False),
        (b"h", b"in", b"v", now + 50, False),     # remaining ttl 50
        (b"h", b"out", b"v", now + 500, False),   # remaining ttl 500
    ])
    # 0/0 matches records with NO ttl (reference :80-83)
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE", rules=[("FRT_TTL_RANGE", {"start_ttl": 0, "stop_ttl": 0})])))
    drop, _ = apply_operations(blk, ops, now=now)
    assert list(drop) == [True, False, False]
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE", rules=[("FRT_TTL_RANGE", {"start_ttl": 10, "stop_ttl": 100})])))
    drop, _ = apply_operations(blk, ops, now=now)
    assert list(drop) == [False, True, False]


def test_all_rules_must_match():
    blk = make_block([(b"user1", b"tmp_x", b"v", 0, False),
                      (b"user1", b"keep", b"v", 0, False),
                      (b"other", b"tmp_y", b"v", 0, False)])
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE",
        rules=[("FRT_HASHKEY_PATTERN",
                {"pattern": "user", "match_type": "SMT_MATCH_PREFIX"}),
               ("FRT_SORTKEY_PATTERN",
                {"pattern": "tmp_", "match_type": "SMT_MATCH_PREFIX"})])))
    drop, _ = apply_operations(blk, ops, now=100)
    assert list(drop) == [True, False, False]


def test_update_ttl_from_now_and_current_and_timestamp():
    from pegasus_tpu.base.utils import epoch_begin

    now = 1000
    blk = make_block([(b"h", b"a", b"v", 0, False),
                      (b"h", b"b", b"v", now + 100, False)])
    rules = [("FRT_HASHKEY_PATTERN",
              {"pattern": "h", "match_type": "SMT_MATCH_PREFIX"})]
    # FROM_NOW: everyone matched gets now+value
    ops = parse_user_specified_compaction(spec(op(
        "COT_UPDATE_TTL", {"type": "UTOT_FROM_NOW", "value": 77}, rules)))
    b2 = make_block([(b"h", b"a", b"v", 0, False),
                     (b"h", b"b", b"v", now + 100, False)])
    _, changed = apply_operations(b2, ops, now=now)
    assert changed and list(b2.expire_ts) == [now + 77, now + 77]
    # value bytes rewritten too (v2 header at offset 1)
    assert SCHEMAS[2].extract_expire_ts(b2.value(0)) == now + 77
    # FROM_CURRENT: only records WITH a ttl move
    ops = parse_user_specified_compaction(spec(op(
        "COT_UPDATE_TTL", {"type": "UTOT_FROM_CURRENT", "value": 5}, rules)))
    b3 = make_block([(b"h", b"a", b"v", 0, False),
                     (b"h", b"b", b"v", now + 100, False)])
    apply_operations(b3, ops, now=now)
    assert list(b3.expire_ts) == [0, now + 105]
    # TIMESTAMP: absolute unix ts converted to the 2016 epoch
    unix_ts = epoch_begin + 5000
    ops = parse_user_specified_compaction(spec(op(
        "COT_UPDATE_TTL", {"type": "UTOT_TIMESTAMP", "value": unix_ts}, rules)))
    b4 = make_block([(b"h", b"a", b"v", 0, False)])
    apply_operations(b4, ops, now=now)
    assert list(b4.expire_ts) == [5000]


def test_update_ttl_skips_tombstones_and_headerless():
    """A tombstone (zero-length value) sits before a live record in arena
    order; FRT_TTL_RANGE 0/0 matches expire==0 — which every tombstone has.
    The rewrite must not touch the tombstone's (absent) value bytes, or it
    clobbers the NEXT record's expire header / runs off the arena."""
    now = 500
    rules = [("FRT_TTL_RANGE", {"start_ttl": 0, "stop_ttl": 0})]
    ops = parse_user_specified_compaction(spec(op(
        "COT_UPDATE_TTL", {"type": "UTOT_FROM_NOW", "value": 9}, rules)))
    # tombstone first, then a live no-ttl record whose header must survive
    blk = make_block([(b"h", b"a_dead", b"", 0, True),
                      (b"h", b"b_live", b"payload", 0, False)])
    _, changed = apply_operations(blk, ops, now=now)
    assert changed
    # tombstone untouched entirely (filters never see deletion markers)
    assert blk.expire_ts[0] == 0 and blk.val_len[0] == 0
    # live record rewritten correctly — in column AND value bytes
    assert blk.expire_ts[1] == now + 9
    assert SCHEMAS[2].extract_expire_ts(blk.value(1)) == now + 9
    assert SCHEMAS[2].extract_user_data(blk.value(1)) == b"payload"
    # tombstone LAST in the arena: the unmasked write used to raise/overrun
    blk2 = make_block([(b"h", b"a_live", b"payload", 0, False),
                       (b"h", b"z_dead", b"", 0, True)])
    apply_operations(blk2, ops, now=now)
    assert blk2.expire_ts[1] == 0
    assert SCHEMAS[2].extract_user_data(blk2.value(0)) == b"payload"


def test_first_matching_op_wins():
    blk = make_block([(b"h", b"s", b"v", 0, False)])
    rules = [("FRT_HASHKEY_PATTERN",
              {"pattern": "h", "match_type": "SMT_MATCH_PREFIX"})]
    ops = parse_user_specified_compaction(spec(
        op("COT_UPDATE_TTL", {"type": "UTOT_FROM_NOW", "value": 9}, rules),
        op("COT_DELETE", rules=rules)))
    drop, changed = apply_operations(blk, ops, now=100)
    assert not drop[0] and changed          # first op handled it
    assert blk.expire_ts[0] == 109


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_rules_in_compaction_both_backends_identical(backend):
    rng = np.random.default_rng(3)
    recs = []
    for i in range(120):
        hk = (b"tmp_%d" if i % 3 == 0 else b"keep_%d") % i
        recs.append((hk, b"s%d" % i, b"v%d" % i, 0, False))
    runs = [make_block(recs[:60]), make_block(recs[60:])]
    ops = parse_user_specified_compaction(spec(op(
        "COT_DELETE",
        rules=[("FRT_HASHKEY_PATTERN",
                {"pattern": "tmp_", "match_type": "SMT_MATCH_PREFIX"})])))
    res = compact_blocks(runs, CompactOptions(
        backend=backend, now=100, user_ops=tuple(ops)))
    keys = [res.block.key(i) for i in range(res.block.n)]
    assert all(b"tmp_" not in k for k in keys)
    assert res.block.n == sum(1 for r in recs if r[0].startswith(b"keep_"))
    if backend == "tpu":
        cpu = compact_blocks(runs, CompactOptions(
            backend="cpu", now=100, user_ops=tuple(ops)))
        np.testing.assert_array_equal(cpu.block.key_arena, res.block.key_arena)
        np.testing.assert_array_equal(cpu.block.val_arena, res.block.val_arena)


def test_engine_env_wiring(tmp_path):
    srv = PegasusServer(str(tmp_path / "db"), options=EngineOptions(backend="cpu"))
    srv.update_app_envs({consts.USER_SPECIFIED_COMPACTION: spec(op(
        "COT_DELETE",
        rules=[("FRT_SORTKEY_PATTERN",
                {"pattern": "junk", "match_type": "SMT_MATCH_PREFIX"})]))})
    for i in range(10):
        srv.engine.put(generate_key(b"h", b"junk%d" % i),
                       SCHEMAS[2].generate_value(0, 0, b"x"))
        srv.engine.put(generate_key(b"h", b"good%d" % i),
                       SCHEMAS[2].generate_value(0, 0, b"x"))
    srv.engine.manual_compact(now=100)
    assert srv.engine.stats()["total_sst_records"] == 10
    srv.close()
