"""Manual-compact service tests with a mocked clock, mirroring
src/server/test manual_compact_service_test (PEGASUS_UNIT_TEST mock time)."""

import pytest

from pegasus_tpu.base import consts
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.manual_compact_service import GATE, ManualCompactService
from pegasus_tpu.engine.server_impl import PegasusServer


@pytest.fixture
def srv(tmp_path):
    s = PegasusServer(str(tmp_path / "db"), options=EngineOptions(backend="cpu"))
    yield s
    s.close()


def fill(srv, n=20):
    from pegasus_tpu.base import key_schema
    for i in range(n):
        srv.engine.put(key_schema.generate_key(b"h", b"s%03d" % i), b"\x82" + b"\0" * 12 + b"v")


def test_disabled_blocks_compaction(srv):
    svc = ManualCompactService(srv, mock_now=1000)
    envs = {consts.MANUAL_COMPACT_DISABLED_KEY: "true",
            consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "500"}
    assert not svc.start_manual_compact_if_needed(envs)


def test_once_trigger_fires_once(srv):
    fill(srv)
    svc = ManualCompactService(srv, mock_now=1000)
    envs = {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900"}
    assert svc.start_manual_compact_if_needed(envs)
    assert srv.engine.stats()["l0_files"] == 0
    # same trigger re-delivered: finish time newer -> no re-run
    svc.set_mock_now(2000)
    assert not svc.start_manual_compact_if_needed(envs)
    # a NEWER trigger fires again
    envs[consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY] = "1500"
    assert svc.start_manual_compact_if_needed(envs)


def test_once_trigger_in_future_does_not_fire(srv):
    svc = ManualCompactService(srv, mock_now=1000)
    envs = {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "5000"}
    assert not svc.start_manual_compact_if_needed(envs)


def test_periodic_trigger(srv):
    import time as _time

    fill(srv)
    svc = ManualCompactService(srv)
    # build a local timestamp at 04:30 today
    now = _time.time()
    lt = _time.localtime(now)
    midnight = int(now) - (lt.tm_hour * 3600 + lt.tm_min * 60 + lt.tm_sec)
    svc.set_mock_now(midnight + 4 * 3600 + 30 * 60)
    envs = {consts.MANUAL_COMPACT_PERIODIC_TRIGGER_TIME_KEY: "3:00,21:00"}
    assert svc.start_manual_compact_if_needed(envs)   # 3:00 already passed
    assert not svc.start_manual_compact_if_needed(envs)  # not 21:00 yet
    svc.set_mock_now(midnight + 21 * 3600 + 60)
    assert svc.start_manual_compact_if_needed(envs)   # 21:00 passed


def test_concurrency_cap(srv, tmp_path):
    svc = ManualCompactService(srv, mock_now=1000)
    envs = {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900",
            consts.MANUAL_COMPACT_MAX_CONCURRENT_RUNNING_COUNT_KEY: "1"}
    GATE.running = 1  # someone else is compacting cluster-wide
    try:
        assert not svc.start_manual_compact_if_needed(envs)
    finally:
        GATE.running = 0
    assert svc.start_manual_compact_if_needed(envs)


def test_bottommost_and_target_level_opts(srv):
    fill(srv)
    svc = ManualCompactService(srv, mock_now=1000)
    envs = {
        consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900",
        consts.MANUAL_COMPACT_ONCE_KEY_PREFIX
        + consts.MANUAL_COMPACT_TARGET_LEVEL_KEY: "1",
        consts.MANUAL_COMPACT_ONCE_KEY_PREFIX
        + consts.MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_KEY:
            consts.MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_FORCE,
    }
    assert svc.start_manual_compact_if_needed(envs)
    assert srv.engine.stats()["level_files"] == {1: 1}


def test_finish_time_persisted_and_state_string(srv):
    fill(srv)
    svc = ManualCompactService(srv, mock_now=1000)
    assert "never compacted" in svc.query_compact_state()
    svc.start_manual_compact_if_needed(
        {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900"})
    assert "idle; last finish" in svc.query_compact_state()
    assert srv.engine.meta_store[
        "pegasus_last_manual_compact_finish_time"] == 1000
    # a new service instance reads the persisted finish time
    svc2 = ManualCompactService(srv, mock_now=1000)
    assert svc2.last_finish_time_ms == 1000 * 1000


def test_app_env_update_path(srv):
    fill(srv)
    srv.manual_compact_service.set_mock_now(1000)
    srv.update_app_envs({consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900"})
    assert srv.engine.stats()["l0_files"] == 0
