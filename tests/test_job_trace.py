"""ISSUE 16: causal job tracing for the background planes — one job id
from scheduler decision to installed SST.

Pinned here:
  - JobTracer semantics: cluster-unique ids, idempotent begin-joins,
    bounded hop/active sets, nested jobs degrading to hops, adopt
    restoring the previous context, remote-view records, stitching;
  - an engine-local L0 trigger is ONE completed "compact" job holding
    the trigger, merge and (deferred) install hops;
  - a scheduler token's job id is adopted by the engine trigger it
    fires: decision and merge share ONE timeline, and the engine's
    finish closes the record the scheduler opened;
  - an offloaded merge stitches the service's ship/load/merge spans
    into the originating node's timeline, origin-tagged — one timeline
    spanning both sides of the wire;
  - partition-group mode: a job minted in a group worker is visible
    through BOTH router paths — the parent's per-frame relay (pid-keyed
    structural merge across workers) and an SCM_RIGHTS-handed-off
    sharded connection (the owning worker answers directly) — and both
    views show the same timeline;
  - the acceptance shape: a scheduler-urgent, offload-placed compaction
    through real RPC yields one timeline (decide, deliver, trigger,
    ship, stitched remote merge, fetch, install); a planted
    `compact.offload` fail point adds the lane-fallback hop to the same
    timeline; the flight-recorder incident artifact embeds the job.
"""

import json
import os
import time

import pytest

from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.db import LsmEngine
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.job_trace import JOB_TRACER, JobTracer


@pytest.fixture
def failpoints():
    fp.setup()
    yield fp
    fp.teardown()


# ------------------------------------------------------------ unit: tracer


def test_mint_ids_unique_across_tracers():
    a, b = JobTracer(), JobTracer()
    ids = {a.mint() for _ in range(200)}
    assert len(ids) == 200
    assert all(i.startswith("j") for i in ids)
    # distinct node seeds: two processes can never mint the same id
    assert not ids & {b.mint() for _ in range(200)}


def test_job_scope_records_hops_and_finishes():
    t = JobTracer()
    with t.job("compact", engine="/e", pidx=3) as jid:
        assert t.current() == jid
        with t.hop("engine.merge", level=1) as attrs:
            attrs["inputs"] = 4      # discovered mid-hop
        t.note("engine.trigger", trigger="ceiling")
    assert t.current() is None
    rec = t.find(jid)
    assert rec["status"] == "ok" and rec["duration_us"] >= 0
    assert rec["attrs"] == {"engine": "/e", "pidx": 3}
    assert [h["name"] for h in rec["hops"]] == ["engine.merge",
                                                "engine.trigger"]
    assert rec["hops"][0]["inputs"] == 4
    assert rec["hops"][0]["duration_us"] >= 0


def test_job_scope_error_status_propagates():
    t = JobTracer()
    with pytest.raises(RuntimeError):
        with t.job("learn") as jid:
            raise RuntimeError("boom")
    assert t.find(jid)["status"] == "error"
    assert t.current() is None


def test_begin_join_is_idempotent_and_engine_finish_closes_it():
    """The onebox acceptance shape in miniature: the scheduler begins a
    'sched' record, the engine joins it by id and finishes it — ONE
    record, the original kind and start, merged attrs."""
    t = JobTracer()
    jid = t.begin("sched", gpid="1.0")
    t.note("sched.decide", job_id=jid, policy="urgent")
    again = t.begin("compact", job_id=jid, engine="/e")
    assert again == jid
    rec = t.find(jid)
    assert rec["kind"] == "sched", "join must not re-key the record"
    assert rec["attrs"] == {"gpid": "1.0", "engine": "/e"}
    t.finish(jid, input_records=9)
    rec = t.find(jid)
    assert rec["status"] == "ok" and rec["attrs"]["input_records"] == 9
    t.finish(jid)               # double finish no-ops
    t.finish("jnope-1")         # unknown id no-ops
    assert len([j for j in t.jobs() if j["job_id"] == jid]) == 1


def test_nested_job_degrades_to_hop():
    t = JobTracer()
    with t.job("compact") as outer:
        with t.job("compact") as inner:
            assert inner == outer
    rec = t.find(outer)
    assert [h["name"] for h in rec["hops"]] == ["compact.nested"]


def test_hop_and_note_without_active_job_noop():
    t = JobTracer()
    with t.hop("engine.merge"):
        pass
    t.note("lane.fallback", lane="compact.lane")
    assert t.jobs() == []


def test_note_with_unseen_id_opens_remote_view():
    """A serving primary attributing learn pins to a learner's job id it
    never began: the note lands on a 'remote'-kind record."""
    t = JobTracer()
    t.note("learn.serve_prepare", job_id="jabc-1", blocks=7)
    rec = t.find("jabc-1")
    assert rec["kind"] == "remote"
    assert rec["hops"][0]["blocks"] == 7


def test_stitch_tags_origin_and_drops_malformed():
    t = JobTracer()
    jid = t.begin("compact")
    t.stitch(jid, [{"name": "offload.svc.merge", "duration_us": 5},
                   {"no_name": 1}, "junk", None], origin="svc:99")
    rec = t.find(jid)
    assert [h["name"] for h in rec["hops"]] == ["offload.svc.merge"]
    assert rec["hops"][0]["origin"] == "svc:99"
    t.stitch(jid, None)   # empty stitches no-op
    assert len(t.find(jid)["hops"]) == 1


def test_hop_cap_counts_drops():
    t = JobTracer()
    t.MAX_HOPS = 4
    jid = t.begin("duplicate")
    for i in range(7):
        t.note("dup.ship_window", job_id=jid, n=i)
    rec = t.find(jid)
    assert len(rec["hops"]) == 4 and rec["hops_dropped"] == 3


def test_active_set_bounded_oldest_evicted():
    t = JobTracer()
    t.MAX_ACTIVE = 8
    ids = [t.begin("sched") for _ in range(12)]
    assert t.find(ids[0]) is None, "oldest unfired decision must age out"
    assert t.find(ids[-1]) is not None
    t.finish(ids[0])   # finishing an evicted id no-ops, never raises


def test_adopt_restores_previous_context_and_none_noops():
    t = JobTracer()
    with t.job("compact") as outer:
        other = t.begin("sched")
        with t.adopt(other):
            assert t.current() == other
            with t.adopt(None):     # untraced caller: no-op
                assert t.current() == other
        assert t.current() == outer


def test_window_keeps_overlapping_timelines():
    t = JobTracer()
    with t.job("compact") as jid:
        pass
    assert any(j["job_id"] == jid for j in t.window(60))
    assert t.window(0.0) == [] or all(
        j["ts"] >= time.time() - 0.5 for j in t.window(0.0))


# --------------------------------------------------------- engine-level


def _engine(tmp_path, name="e", trigger=2):
    return LsmEngine(str(tmp_path / name),
                     EngineOptions(backend="cpu", memtable_bytes=1,
                                   l0_compaction_trigger=trigger))


def _key(i):
    from pegasus_tpu.base.key_schema import generate_key

    return generate_key(b"hk%04d" % i, b"s")


def test_engine_trigger_is_one_traced_job(tmp_path):
    eng = _engine(tmp_path, trigger=2)
    before = {j["job_id"] for j in JOB_TRACER.jobs(last=500)}
    for i in range(2):
        eng.put(_key(i), b"v" * 32)
        eng.flush()
    assert eng.stats()["l0_files"] <= 1, "the L0 trigger must have fired"
    mine = [j for j in JOB_TRACER.jobs(last=500)
            if j["job_id"] not in before and j["kind"] == "compact"
            and j["attrs"].get("engine") == eng.path]
    assert mine, "the trigger compaction must be a completed job"
    rec = mine[-1]
    assert rec["status"] == "ok"
    names = [h["name"] for h in rec["hops"]]
    assert "engine.trigger" in names and "engine.merge" in names
    trig = next(h for h in rec["hops"] if h["name"] == "engine.trigger")
    assert trig["trigger"] == "trigger" and trig["l0_files"] >= 2
    merge = next(h for h in rec["hops"] if h["name"] == "engine.merge")
    assert merge["where"] == "local"
    # the deferred install's disk work (pipeline pool thread) landed in
    # the SAME job before finish — compact() drains it synchronously
    assert "engine.install" in names
    assert rec["attrs"]["input_records"] >= 2
    eng.close()


def test_sched_token_job_adopted_by_engine_trigger(tmp_path):
    """The tentpole join: the id minted with the scheduler decision is
    the id the engine's compaction finishes — one timeline."""
    eng = _engine(tmp_path, trigger=4)    # urgent threshold = 2
    jid = JOB_TRACER.begin("sched", gpid="7.0")
    JOB_TRACER.note("sched.decide", job_id=jid, policy="urgent")
    eng.set_compact_policy("urgent", reasons=["l0_debt"], ttl_s=60, job=jid)
    for i in range(2):
        eng.put(_key(i), b"v" * 32)
        eng.flush()
    assert eng.stats()["l0_files"] <= 1, "urgent must fire at trigger//2"
    rec = JOB_TRACER.find(jid)
    assert rec["status"] == "ok", "the engine's finish closes the record"
    assert rec["kind"] == "sched", "the join keeps the decision's kind"
    names = [h["name"] for h in rec["hops"]]
    assert names.index("sched.decide") < names.index("engine.trigger")
    assert next(h for h in rec["hops"]
                if h["name"] == "engine.trigger")["trigger"] == "urgent"
    # the token id is consumed: the next compaction mints its own
    before = {j["job_id"] for j in JOB_TRACER.jobs(last=500)}
    for i in range(4):
        eng.put(_key(100 + i), b"v" * 32)
        eng.flush()
    later = [j for j in JOB_TRACER.jobs(last=500)
             if j["job_id"] not in before
             and j["attrs"].get("engine") == eng.path]
    assert later and all(j["job_id"] != jid for j in later)
    eng.close()


def test_manual_compact_is_its_own_traced_job(tmp_path):
    eng = _engine(tmp_path, trigger=64)   # no elective trigger in the way
    for i in range(3):
        eng.put(_key(i), b"v" * 32)
        eng.flush()
    before = {j["job_id"] for j in JOB_TRACER.jobs(last=500)}
    eng.manual_compact()
    mine = [j for j in JOB_TRACER.jobs(last=500)
            if j["job_id"] not in before
            and j["attrs"].get("engine") == eng.path
            and j["attrs"].get("trigger") == "manual"]
    assert mine and mine[-1]["status"] == "ok"
    assert any(h["name"] == "engine.merge" for h in mine[-1]["hops"])
    eng.close()


# ------------------------------------------------- offload: stitched spans


def test_offload_round_stitches_service_spans(tmp_path):
    from pegasus_tpu.ops.compact import CompactOptions
    from pegasus_tpu.replication.compact_offload import (
        CompactOffloadService, offload_compact_blocks)
    from tests.test_compact_offload import _runs

    svc = CompactOffloadService(str(tmp_path / "svc"),
                                backend="cpu").start()
    try:
        opts = CompactOptions(backend="cpu", now=100, runs_sorted=True,
                              bottommost=True)
        with JOB_TRACER.job("compact", tenant="t-trace") as jid:
            offload_compact_blocks(_runs(), opts, svc.address,
                                   tenant="t-trace")
        rec = JOB_TRACER.find(jid)
        names = [h["name"] for h in rec["hops"]]
        for want in ("offload.ship", "offload.merge", "offload.fetch",
                     "offload.svc.begin", "offload.svc.load",
                     "offload.svc.merge"):
            assert want in names, f"missing {want} in {names}"
        # the service's spans came home over the wire, origin-tagged —
        # one timeline spanning both sides
        for h in rec["hops"]:
            if h["name"].startswith("offload.svc."):
                assert h["origin"] == svc.address
        ship = next(h for h in rec["hops"] if h["name"] == "offload.ship")
        assert ship["nbytes"] > 0 and ship["service"] == svc.address
        svc_merge = next(h for h in rec["hops"]
                         if h["name"] == "offload.svc.merge")
        assert svc_merge["records_in"] > 0
        assert names.index("offload.ship") < names.index("offload.fetch")
    finally:
        svc.stop()


# --------------------------- partition groups: relay + SCM_RIGHTS handoff


def test_group_worker_job_survives_relay_and_handoff(tmp_path):
    """Satellite: a job minted inside a group-worker PROCESS is visible
    through both router paths — the parent's per-frame relay (whose
    structural merge keeps every worker's pid-keyed timelines) and a
    sharded connection handed to the owning worker via SCM_RIGHTS — and
    the two views agree on the same timeline."""
    from pegasus_tpu.base import key_schema
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc import messages as msg
    from pegasus_tpu.rpc.transport import RpcConnection
    from pegasus_tpu.runtime.perf_counters import counters
    from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                    RemoteCommandResponse)
    from tests.test_satellites import MiniCluster

    groups, partitions = 2, 4
    c = MiniCluster(tmp_path, n_nodes=2, serve_groups=groups)
    cli = c.create("jt", partitions=partitions, replicas=2)

    def cmd(conn, name, args):
        _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
            RemoteCommandRequest(name, list(args))), timeout=30.0)
        return codec.decode(RemoteCommandResponse, body).output

    try:
        for i in range(40):
            cli.set(b"jk%d" % i, b"sk", b"v%d" % i)
        node = c.stubs[0]
        host, _, port = node.address.rpartition(":")
        relay = RpcConnection((host, int(port)))   # unsharded: relay path
        try:
            # fans out to every worker; each mints its manual-compact jobs
            out = cmd(relay, "manual-compact", [])
            assert "compacted" in out
            merged = json.loads(cmd(relay, "job-trace", ["100"]))
            # pid-keyed structural merge: one key per worker process,
            # none of them this (parent) process
            assert len(merged) == groups, merged.keys()
            assert f"pid:{os.getpid()}" not in merged
            by_pid = {
                pid: [j for j in jobs if j["kind"] == "compact"
                      and j["attrs"].get("trigger") == "manual"]
                for pid, jobs in merged.items()}
            assert all(by_pid.values()), "every worker must hold its jobs"
            # cluster-unique minting: no id collides across workers
            all_ids = [j["job_id"] for jobs in merged.values() for j in jobs]
            assert len(all_ids) == len(set(all_ids))

            # SCM_RIGHTS leg: a sharded connection pinned to one
            # partition is handed to the owning worker wholesale
            hk = b"jk0"
            key = key_schema.generate_key(hk, b"sk")
            h = key_schema.key_hash(key)
            pidx = h % partitions
            h0 = counters.rate("serve.group.handoff_count").total()
            sharded = RpcConnection((host, int(port)), shard=pidx)
            try:
                _, body = sharded.call(
                    "RPC_RRDB_RRDB_GET", codec.encode(msg.KeyRequest(key)),
                    app_id=1, partition_index=pidx, partition_hash=h,
                    timeout=10.0)
                assert counters.rate(
                    "serve.group.handoff_count").total() > h0, \
                    "the sharded connection must have been handed off"
                # the handed-off socket reaches ONE worker: its pid only
                direct = json.loads(cmd(sharded, "job-trace", ["100"]))
                assert len(direct) == 1
                (wpid,) = direct.keys()
                assert wpid in merged, (wpid, list(merged))
                job = [j for j in direct[wpid]
                       if j["attrs"].get("trigger") == "manual"][-1]
                # same timeline through both paths: find the id over the
                # handed-off socket and match the relay view hop-for-hop
                found = json.loads(cmd(sharded, "job-trace",
                                       [job["job_id"]]))[wpid]
                assert found and found[0]["job_id"] == job["job_id"]
                relayed = [j for j in merged[wpid]
                           if j["job_id"] == job["job_id"]]
                assert relayed, "relay and handoff must see the same job"
                assert ([h_["name"] for h_ in relayed[0]["hops"]]
                        == [h_["name"] for h_ in found[0]["hops"]])
            finally:
                sharded.close()
        finally:
            relay.close()
    finally:
        cli.close()
        c.stop()


# -------------------------------------------------- acceptance: end to end


@pytest.fixture
def debt_cluster(tmp_path):
    """Three-stub cluster with tiny memtables and a low trigger so a
    modest write burst builds adoptable compaction debt."""
    from pegasus_tpu.meta import MetaServer
    from pegasus_tpu.replication.replica_stub import ReplicaStub
    from pegasus_tpu.rpc.transport import RpcConnection, RpcServer
    from tests.test_satellites import MiniCluster

    class _DebtCluster(MiniCluster):
        def __init__(self, root):
            self.meta = MetaServer(str(root / "meta.json"),
                                   fd_grace_seconds=60)
            self.rpc = RpcServer().start()
            for code, fn in self.meta.rpc_handlers().items():
                self.rpc.register(code, fn)
            self.meta_addr = f"{self.rpc.address[0]}:{self.rpc.address[1]}"
            self.stubs = [
                ReplicaStub(str(root / f"n{i}"), [self.meta_addr],
                            options_factory=lambda: EngineOptions(
                                backend="cpu", memtable_bytes=512,
                                l0_compaction_trigger=8)).start(0.2)
                for i in range(3)]
            self._conn = RpcConnection(self.rpc.address)

    c = _DebtCluster(tmp_path)
    yield c
    c.stop()


def _wait_beacon_debt(caller, min_l0, deadline_s=30.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        state = caller.meta_state()
        if state:
            by_gpid = {}
            for states in state.get("replica_states", {}).values():
                for gpid, st in states.items():
                    debt = st.get("compact") or {}
                    by_gpid[gpid] = max(by_gpid.get(gpid, 0),
                                        debt.get("l0_files", 0))
            if by_gpid and min(by_gpid.values()) >= min_l0:
                return by_gpid
        time.sleep(0.2)
    raise AssertionError("beacons never carried the compaction debt")


def _full_records(jid):
    """Every retained record for a propagated id (several replicas can
    re-open a consumed id; the FIRST fire holds the scheduler hops)."""
    rec = JOB_TRACER.find(jid)
    out = [j for j in JOB_TRACER.jobs(last=1000) if j["job_id"] == jid]
    if rec and all(r is not rec for r in out):
        out.append(rec)
    return out


def test_e2e_sched_urgent_offload_one_timeline(debt_cluster, monkeypatch,
                                               tmp_path, failpoints):
    """The acceptance shape: scheduler-urgent, offload-placed compaction
    driven over real RPC yields ONE timeline carrying the decision, the
    delivery, the engine trigger, the ship, the stitched remote merge,
    the fetch and the install; a planted `compact.offload` fail point
    puts the offload lane's fallback hop in the same timeline; the
    flight-recorder artifact embeds the in-window job timelines."""
    from pegasus_tpu.collector.cluster_doctor import ClusterCaller
    from pegasus_tpu.collector.compact_scheduler import run_scheduler_tick
    from pegasus_tpu.collector.flight_recorder import FlightRecorder
    from pegasus_tpu.replication.compact_offload import (
        OFFLOAD_LANE_GUARD, CompactOffloadService)

    svc = CompactOffloadService(str(tmp_path / "svc"), backend="cpu").start()
    OFFLOAD_LANE_GUARD.reset()
    cli = debt_cluster.create("traced", partitions=2)
    caller = ClusterCaller([debt_cluster.meta_addr])
    knobs = {"urgent_l0": 2, "max_urgent_per_node": 8, "ttl_s": 30.0,
             "max_device": 0}

    def burst(base, n=120):
        for i in range(n):
            cli.set(b"user%05d" % (base + i), b"f0", b"v" * 64)

    feed = [2000]

    def wait_timeline(jid, wanted, deadline_s=60.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            for rec in _full_records(jid):
                names = [h["name"] for h in rec["hops"]]
                if all(w in names for w in wanted):
                    return rec, names
            # keep flushing so the urgent trigger (trigger//2 L0 files)
            # fires while the delivered lease is still live
            burst(feed[0], n=16)
            feed[0] += 16
            time.sleep(0.2)
        raise AssertionError(
            f"no record of {jid} grew hops {wanted}; have "
            f"{[[h['name'] for h in r['hops']] for r in _full_records(jid)]}")

    try:
        burst(0)
        _wait_beacon_debt(caller, min_l0=2)
        monkeypatch.setenv("PEGASUS_OFFLOAD_SERVICES", svc.address)
        report = run_scheduler_tick([debt_cluster.meta_addr], caller=caller,
                                    knobs=knobs)
        assert not report["errors"], report["errors"]
        targets = [g for g, d in report["decisions"].items()
                   if d["policy"] == "urgent" and d["where"] == svc.address]
        assert targets, f"need an urgent+placed gpid: {report['decisions']}"
        jid = report["decisions"][targets[0]]["job"]
        assert jid.startswith("j")
        # the token is live on the engines; more writes fire the urgent
        # trigger, which adopts the delivered id — then ships, stitches
        # and installs, all in the one timeline the decision opened
        burst(1000)
        rec, names = wait_timeline(jid, (
            "sched.decide", "sched.deliver", "engine.trigger",
            "engine.merge", "offload.ship", "offload.svc.merge",
            "offload.fetch", "engine.install"))
        assert names.index("sched.decide") < names.index("sched.deliver") \
            < names.index("engine.trigger")
        trig = next(h for h in rec["hops"] if h["name"] == "engine.trigger")
        assert trig["trigger"] == "urgent"
        merge = next(h for h in rec["hops"] if h["name"] == "engine.merge")
        assert merge["where"] == "offload"
        svc_hops = [h for h in rec["hops"]
                    if h["name"].startswith("offload.svc.")]
        assert svc_hops and all(h["origin"] == svc.address
                                for h in svc_hops), \
            "the service's spans must come home origin-tagged"

        # ---- fallback leg: wedge the offload wire, next placed urgent
        # compaction records the lane fallback INSIDE its timeline
        failpoints.cfg("compact.offload", "raise(job-trace-chaos)")
        # the first leg's compactions drained the L0 debt the tick folds,
        # so re-build it and re-tick until a partition reads urgent again
        # (local fallback merges keep draining it in the background —
        # one snapshot is not guaranteed to catch l0 >= urgent_l0)
        deadline = time.monotonic() + 90.0
        targets2, report2 = [], {"decisions": {}}
        while not targets2:
            assert time.monotonic() < deadline, \
                f"no urgent+placed decision: {report2['decisions']}"
            burst(feed[0], n=48)
            feed[0] += 48
            time.sleep(0.3)  # let a beacon carry the fresh debt
            report2 = run_scheduler_tick([debt_cluster.meta_addr],
                                         caller=caller, knobs=knobs)
            targets2 = [g for g, d in report2["decisions"].items()
                        if d["policy"] == "urgent"
                        and d["where"] == svc.address]
        jid2 = report2["decisions"][targets2[0]]["job"]
        burst(4000)
        rec2, names2 = wait_timeline(jid2, (
            "engine.trigger", "lane.fallback", "engine.install"))
        fb = next(h for h in rec2["hops"] if h["name"] == "lane.fallback")
        assert fb["lane"] == "offload.lane"
        failpoints.cfg("compact.offload", "off()")

        # ---- the incident artifact embeds the in-window job timelines
        monkeypatch.setenv("PEGASUS_INCIDENT_DIR",
                           str(tmp_path / "incidents"))
        inc = FlightRecorder().capture([debt_cluster.meta_addr],
                                       reason="job-trace acceptance",
                                       trigger="manual", caller=caller)
        embedded = {j["job_id"] for j in inc["jobs"]}
        assert {jid, jid2} <= embedded, \
            "the artifact must embed the traced jobs"
        # and the per-node scrape carried pid-keyed timelines too
        assert any("jobs" in d for d in inc["nodes"].values())
        with open(inc["path"]) as f:
            assert json.load(f)["id"] == inc["id"]
    finally:
        caller.close()
        cli.close()
        svc.stop()
        OFFLOAD_LANE_GUARD.reset()


def test_jobs_http_route_and_remote_command(tmp_path):
    """The /jobs route and the job-trace command agree on the tracer's
    retained timelines (pid-keyed for the router's structural merge)."""
    from pegasus_tpu.runtime.remote_command import RemoteCommandService

    with JOB_TRACER.job("compact", surface="test") as jid:
        JOB_TRACER.note("engine.trigger", trigger="manual")
    svc = RemoteCommandService()
    svc.register_defaults("test")
    out = json.loads(svc.invoke("job-trace", [jid]))
    key = f"pid:{os.getpid()}"
    assert out[key] and out[key][0]["job_id"] == jid
    listed = json.loads(svc.invoke("job-trace", ["50"]))
    assert any(j["job_id"] == jid for j in listed[key])
