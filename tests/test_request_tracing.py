"""End-to-end request tracing tests: one client write through a real
onebox (meta + replicas over TCP, PacificA 2PC) must yield ONE trace
whose spans cover client, rpc, replication prepare/commit, the
private-log append and the engine apply — retrievable via
GET /requests/trace and the slow-requests remote command — plus the
RequestTracer unit surface and the new replication-path counters.
"""

import io
import json
import threading
import time
import urllib.request

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.perf_counters import counters
from pegasus_tpu.runtime.service_app import ServiceAppContainer
from pegasus_tpu.runtime.tracing import REQUEST_TRACER, RequestTracer, TraceContext
from pegasus_tpu.rpc.task_codes import RPC_PUT
from pegasus_tpu.shell.main import Shell

ONEBOX_INI = """
[apps.meta]
type = meta
run = true
port = 0
state_dir = %{root}/meta

[apps.replica1]
type = replica
run = true
port = 0
http_port = 0
data_dir = %{root}/replica1

[apps.replica2]
type = replica
run = true
port = 0
data_dir = %{root}/replica2

[apps.replica3]
type = replica
run = true
port = 0
data_dir = %{root}/replica3

[pegasus.server]
meta_servers = %{meta}

[failure_detector]
beacon_interval_seconds = 0.2
grace_seconds = 60
check_interval_seconds = 3600
"""


@pytest.fixture(scope="module")
def onebox(tmp_path_factory):
    root = tmp_path_factory.mktemp("tracebox")
    c1 = ServiceAppContainer(Config(
        text=ONEBOX_INI, variables={"root": str(root), "meta": "x"}))
    c1.start(only=["meta"])
    meta_addr = c1.apps["meta"].address
    c2 = ServiceAppContainer(Config(
        text=ONEBOX_INI, variables={"root": str(root), "meta": meta_addr}))
    c2.start(only=["replica1", "replica2", "replica3"])
    time.sleep(0.3)  # beacons land
    sh = Shell([meta_addr], out=io.StringIO())
    sh.run_line("create tracetest -p 2 -r 3")
    client = PegasusClient(MetaResolver([meta_addr], "tracetest"))
    yield meta_addr, c2.apps["replica1"], client
    client.close()
    c2.stop()
    c1.stop()


def _put_traces(traces):
    """Completed traces of replicated client puts (prepare span seen)."""
    return [t for t in traces
            if t["op"] == RPC_PUT
            and any(s["name"] == "replica.prepare" for s in t["spans"])]


def test_one_put_yields_one_trace_with_full_stage_timeline(onebox):
    """Acceptance: a single traced client write produces a single trace
    (one trace_id) holding >= 5 stage spans across client, rpc,
    replication (prepare/commit), mutation-log append and engine apply."""
    _, _, client = onebox
    before = {t["trace_id"] for t in _put_traces(REQUEST_TRACER.trace(500))}
    client.set(b"tk", b"sk", b"payload")
    new = [t for t in _put_traces(REQUEST_TRACER.trace(500))
           if t["trace_id"] not in before]
    assert len(new) == 1, "one client put must yield exactly one trace"
    trace = new[0]
    names = [s["name"] for s in trace["spans"]]
    assert len(names) >= 5
    assert any(n.startswith("client.") for n in names)
    assert any(n.startswith("rpc.") for n in names)
    assert "replica.prepare" in names
    assert "replica.commit" in names
    assert "plog.append" in names
    assert "engine.apply" in names
    # span durations nest sanely: the client span covers the whole trace
    client_span = next(s for s in trace["spans"]
                       if s["name"].startswith("client."))
    assert client_span["duration_us"] <= trace["duration_us"]
    assert all(s["duration_us"] >= 0 for s in trace["spans"])


def test_requests_trace_http_route_serves_the_trace(onebox):
    _, rep_app, client = onebox
    client.set(b"hk", b"sk", b"http-surface")
    host, port = rep_app.reporter.address
    body = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/requests/trace?last=500", timeout=5).read())
    puts = _put_traces(body["traces"])
    assert puts, "PUT trace must be retrievable via GET /requests/trace"
    # ?id= fetches one trace by hex id
    tid = puts[-1]["trace_id"]
    one = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/requests/trace?id={tid}", timeout=5).read())
    assert one["trace"] is not None and one["trace"]["trace_id"] == tid


def test_slow_request_ledger_and_remote_command(onebox):
    """Any request over the threshold keeps its full stage timeline in
    the ledger regardless of sampling, served by `slow-requests`."""
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection
    from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                    RemoteCommandResponse)

    meta_addr, rep_app, client = onebox
    old = REQUEST_TRACER.slow_threshold_us
    REQUEST_TRACER.slow_threshold_us = 0  # everything is "slow"
    try:
        client.set(b"slowk", b"sk", b"ledger-me")
    finally:
        REQUEST_TRACER.slow_threshold_us = old
    ledger = REQUEST_TRACER.slow_requests(500)
    slow_puts = _put_traces(ledger)
    assert slow_puts, "the put must land in the slow-request ledger"
    assert any(s["name"] == "plog.append" for s in slow_puts[-1]["spans"])

    host, _, port = rep_app.address.rpartition(":")
    conn = RpcConnection((host, int(port)))
    try:
        _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
            RemoteCommandRequest("slow-requests", ["500"])), timeout=10)
        out = json.loads(codec.decode(RemoteCommandResponse, body).output)
    finally:
        conn.close()
    assert any(t["trace_id"] == slow_puts[-1]["trace_id"] for t in out)
    # the http twin of the ledger
    hhost, hport = rep_app.reporter.address
    body = json.loads(urllib.request.urlopen(
        f"http://{hhost}:{hport}/requests/trace?slow=1&last=500",
        timeout=5).read())
    assert _put_traces(body["slow_requests"])


def test_metrics_route_serves_replication_counters(onebox):
    """Acceptance: /metrics covers the write path — replica.* and plog.*
    counters appear after a replicated write (percentiles flattened to
    _p50.._p999 series)."""
    _, rep_app, client = onebox
    client.set(b"mk", b"sk", b"metrics")
    host, port = rep_app.reporter.address
    body = urllib.request.urlopen(
        f"http://{host}:{port}/metrics", timeout=5).read().decode()
    assert "replica_prepare_latency_us_p99" in body
    assert "replica_commit_latency_us_p50" in body
    assert "plog_append_count" in body
    assert "plog_append_duration_us_p999" in body
    assert "rpc_server_latency_us_p99" in body
    # write-path latency parity: puts now have a percentile counter
    assert "put_latency_us_p99" in body


def test_write_latency_parity_counters(onebox):
    _, _, client = onebox
    client.multi_set(b"wl", {b"a": b"1", b"b": b"2"})
    client.incr(b"wl", b"n", 3)
    client.delete(b"wl", b"a")
    snap = counters.snapshot(substr="_latency_us")
    for op in ("multi_put", "incr", "remove"):
        keys = [k for k in snap if k.endswith(f".{op}_latency_us")]
        assert keys, f"missing {op}_latency_us percentile counter"
        assert all(isinstance(snap[k], dict)
                   and set(snap[k]) == {"p50", "p90", "p95", "p99", "p999"}
                   for k in keys)


def test_per_partition_write_gauges(onebox):
    _, _, client = onebox
    client.set(b"gk", b"sk", b"gauge")
    snap = counters.snapshot(prefix="replica.")
    assert any(k.endswith(".inflight") for k in snap)
    assert any(k.endswith(".backlog") for k in snap)
    # the write committed: its partition's backlog drained back to 0
    hot = [k for k in snap if k.endswith(".backlog")]
    assert all(snap[k] == 0 for k in hot)


# ------------------------------------------------------- tracer unit tests


def test_request_tracer_root_and_span_nesting():
    tr = RequestTracer()
    tr.slow_threshold_us = 1 << 60
    with tr.root("OP") as ctx:
        assert tr.current() is ctx
        with tr.span("stage.a", records=3):
            with tr.span("stage.b"):
                pass
    assert tr.current() is None
    (trace,) = tr.trace(1)
    assert trace["op"] == "OP"
    names = [(s["name"], s["depth"]) for s in trace["spans"]]
    # close order: children before parents; client.<op> is the root span
    assert names == [("stage.b", 2), ("stage.a", 1), ("client.OP", 0)]
    assert trace["spans"][1]["records"] == 3


def test_request_tracer_spans_without_context_are_noops():
    tr = RequestTracer()
    with tr.span("orphan"):
        pass
    assert tr.trace() == []
    assert tr.slow_requests() == []


def test_request_tracer_serve_finalizes_remote_view():
    """A wire-propagated context with no local root finalizes once the
    last open handler returns (the peer node's partial trace view)."""
    tr = RequestTracer()
    tr.slow_threshold_us = 1 << 60
    ctx = TraceContext(0xABC, sampled=True, remote=True)
    with tr.serve(ctx, "RPC_X"):
        with tr.span("replica.on_prepare"):
            pass
    (trace,) = tr.trace(1)
    assert trace["trace_id"] == format(0xABC, "016x")
    assert [s["name"] for s in trace["spans"]] == \
        ["replica.on_prepare", "rpc.server.RPC_X"]


def test_request_tracer_sampling_and_ledger_are_independent():
    tr = RequestTracer()
    tr.sample_every = 1 << 30   # effectively never sampled
    tr.slow_threshold_us = 0    # everything is slow
    with tr.root("OP"):
        pass
    assert tr.trace() == []                 # not sampled
    assert len(tr.slow_requests()) == 1     # but ledgered
    assert tr.find(tr.slow_requests()[0]["trace_id"]) is not None


def test_parallel_prepare_keeps_spans_in_the_trace(tmp_path, monkeypatch):
    """PEGASUS_PARALLEL_PREPARE=1 fans prepares out on a worker pool; the
    thread-local trace context must survive the hop or the secondaries'
    spans (and the trace_id on the wire) silently vanish."""
    from pegasus_tpu.base import key_schema
    from pegasus_tpu.replication import ReplicaGroup
    from pegasus_tpu.rpc import messages as msg

    monkeypatch.setenv("PEGASUS_PARALLEL_PREPARE", "1")
    g = ReplicaGroup(str(tmp_path), n=3)
    try:
        tr = RequestTracer()
        tr.slow_threshold_us = 1 << 60
        key = key_schema.generate_key(b"ph", b"ps")
        # patch the process tracer the replication layer uses
        import pegasus_tpu.replication.mutation_log as ml
        import pegasus_tpu.replication.replica as rp

        monkeypatch.setattr(rp, "REQUEST_TRACER", tr)
        monkeypatch.setattr(ml, "REQUEST_TRACER", tr)
        with tr.root("PUT"):
            g.write(RPC_PUT, msg.UpdateRequest(key, b"v", 0))
        (trace,) = tr.trace(1)
        names = [s["name"] for s in trace["spans"]]
        # primary append + BOTH secondaries' pool-thread appends join it
        assert names.count("plog.append") == 3, names
        assert names.count("replica.on_prepare") == 2, names
    finally:
        g.close()


def test_request_tracer_cross_thread_spans_join_the_trace():
    """Spans closed by another thread holding the same context land in
    the same trace (the onebox server-side shape)."""
    tr = RequestTracer()
    tr.slow_threshold_us = 1 << 60
    done = threading.Event()

    with tr.root("OP") as ctx:
        def server():
            with tr.serve(TraceContext(ctx.trace_id, True, remote=True),
                          "RPC_X"):
                with tr.span("plog.append"):
                    pass
            done.set()

        t = threading.Thread(target=server)
        t.start()
        assert done.wait(5)
        t.join()
    (trace,) = tr.trace(1)
    names = {s["name"] for s in trace["spans"]}
    assert {"client.OP", "rpc.server.RPC_X", "plog.append"} <= names
