"""Flight recorder (ISSUE 12): the recorded-past plane.

  - event-ring units: ordering, wraparound + drop accounting, filters;
  - metric-history units: per-kind sampling semantics (gauge level,
    percentile -> p99), ring wraparound, window queries with the
    pre-window-anchored delta derivation;
  - incident units: offline capture (an unreachable cluster still
    retains an artifact), retention pruning, and the doctor-transition /
    cooldown semantics of observe_verdict;
  - grouped-onebox `events-dump`: every worker pid's ring stays visible
    through the router's structural fan-out merge;
  - collector scrape robustness: a node dying mid-collect_once COUNTS
    (`collector.scrape.error_count` + a `collector.scrape_failed`
    event) instead of being silently skipped;
  - e2e acceptance: `audit.digest` corruption planted under load — the
    doctor's healthy→critical transition auto-captures ONE retained
    incident whose first cause names the fault's arm event, with the
    audit/doctor events ordered on one timeline.
"""

import io
import json
import os
import time

import pytest

from pegasus_tpu.collector.cluster_doctor import (run_cluster_audit,
                                                  run_cluster_doctor)
from pegasus_tpu.collector.flight_recorder import RECORDER, FlightRecorder
from pegasus_tpu.collector.info_collector import InfoCollector
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc.transport import RpcConnection
from pegasus_tpu.runtime import events
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.events import EventBus
from pegasus_tpu.runtime.metric_history import MetricHistory
from pegasus_tpu.runtime.perf_counters import counters
from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                RemoteCommandResponse)

from tests.test_cluster_doctor import (_Load, _partition_members,
                                       _quiet_breakers)
from tests.test_satellites import MiniCluster

# meta addr nobody listens on: capture must degrade, never raise
UNREACHABLE = ["127.0.0.1:1"]


class _Cnt:
    """Counter stand-in: records increments without the process-global
    registry (whose rate windows other tests / the live sampler roll)."""

    def __init__(self):
        self.n = 0

    def increment(self, by=1):
        self.n += by


# ------------------------------------------------------------- event ring


def test_event_ring_wraparound_keeps_newest():
    bus = EventBus(capacity=8)
    for i in range(20):
        bus.emit("unit.test", i=i)
    evs = bus.snapshot()
    assert len(evs) == 8, "ring must stay bounded at capacity"
    assert [e["seq"] for e in evs] == list(range(12, 20)), \
        "oldest first, newest retained"
    assert [e["attrs"]["i"] for e in evs] == list(range(12, 20))
    assert bus.emitted_total() == 20


def test_event_ring_drop_accounting():
    bus = EventBus(capacity=4)
    bus._c_drop = drops = _Cnt()
    for _ in range(4):
        bus.emit("unit.test")
    assert drops.n == 0, "filling an empty ring drops nothing"
    for _ in range(3):
        bus.emit("unit.test")
    assert drops.n == 3, "every wrapped slot counts"


def test_event_snapshot_filters():
    bus = EventBus(capacity=16)
    t0 = time.time()
    bus.emit("a.one")
    bus.emit("a.two", severity="warn")
    bus.emit("b.three")
    assert [e["name"] for e in bus.snapshot(prefix="a.")] == \
        ["a.one", "a.two"]
    assert bus.snapshot(prefix="a.")[1]["sev"] == "warn"
    assert [e["name"] for e in bus.snapshot(last=1)] == ["b.three"]
    assert bus.snapshot(since=time.time() + 10) == []
    assert len(bus.snapshot(since=t0 - 1)) == 3
    # `last` applies AFTER the filters: the newest MATCHING event
    assert [e["name"] for e in bus.snapshot(last=1, prefix="a.")] == \
        ["a.two"]


# --------------------------------------------------------- metric history


def test_history_sampling_kinds_and_wraparound():
    g = counters.number("frtest.gauge")
    p = counters.percentile("frtest.lat_ms")
    try:
        h = MetricHistory(interval_s=5, capacity=4, prefixes=("frtest.",))
        for i, t in enumerate([0, 10, 20, 30, 40, 50]):
            g.set(i * 10)
            p.set(100 + i)
            h.sample_once(now=t)
        w = h.window()
        assert w["interval_s"] == 5 and w["capacity"] == 4
        assert [s["ts"] for s in w["samples"]] == [20, 30, 40, 50], \
            "ring wrapped: oldest two samples gone, order preserved"
        assert [s["values"]["frtest.gauge"] for s in w["samples"]] == \
            [20.0, 30.0, 40.0, 50.0]
        # percentile counters flatten to their p99 series
        assert all("frtest.lat_ms.p99" in s["values"] for s in w["samples"])
        assert all("frtest.lat_ms" not in s["values"] for s in w["samples"])
    finally:
        counters.remove("frtest.gauge")
        counters.remove("frtest.lat_ms")


def test_history_window_query_and_deltas():
    g = counters.number("frtest.level")
    try:
        h = MetricHistory(interval_s=5, capacity=8, prefixes=("frtest.",))
        for t, v in [(0, 5), (10, 7), (20, 12), (30, 40)]:
            g.set(v)
            h.sample_once(now=t)
        # full tail, convenience series view
        assert h.series("frtest.level") == \
            [(0, 5.0), (10, 7.0), (20, 12.0), (30, 40.0)]
        # window cut at now-25: the ts=0 sample is outside but still
        # anchors the first in-window delta (level -> rate view)
        w = h.window(seconds=25, now=35, deltas=True)
        assert [s["ts"] for s in w["samples"]] == [10, 20, 30]
        assert [s["deltas"]["frtest.level"] for s in w["samples"]] == \
            [2.0, 5.0, 28.0]
        # names filter keeps only the asked-for series
        w = h.window(names=["frtest.level"])
        assert all(set(s["values"]) == {"frtest.level"}
                   for s in w["samples"])
    finally:
        counters.remove("frtest.level")


def test_history_sampler_refcounted_start_stop():
    h = MetricHistory(interval_s=60, capacity=4, prefixes=("frtest.",))
    h.start()
    h.start()               # second role in the same process
    h.stop()                # first stop: a ref remains, sampler lives
    assert h._stop_evt is not None
    h.stop()                # last stop: sampler told to exit
    assert h._stop_evt is None


# -------------------------------------------------------- incident units


@pytest.fixture
def incident_dir(tmp_path, monkeypatch):
    d = tmp_path / "incidents"
    monkeypatch.setenv("PEGASUS_INCIDENT_DIR", str(d))
    return d


def test_capture_offline_still_retains_local_ring(incident_dir):
    """A flight recorder that needs a healthy cluster to record records
    nothing useful: with NO meta reachable the capture degrades to the
    capturing process's own ring and still retains the artifact."""
    events.EVENTS.reset()
    events.emit("learn.start", gpid="1.0")              # not a cause class
    events.emit("lane.breaker_trip", lane="compact.lane", op="merge")
    events.emit("failpoint.arm", point="unit.fault", action="return()")
    fr = FlightRecorder()
    inc = fr.capture(UNREACHABLE, reason="unit", trigger="manual")
    assert inc["errors"], "the unreachable meta must be NAMED, not hidden"
    # earliest event of the cascade-starting classes wins — the breaker
    # trip beat the arm, and learn.start is not a candidate at all
    assert inc["first_cause"]["name"] == "lane.breaker_trip"
    assert [e["name"] for e in inc["timeline"]] == \
        ["learn.start", "lane.breaker_trip", "failpoint.arm"]
    assert all("t_rel" in e for e in inc["timeline"])
    assert os.path.exists(inc["path"])
    assert fr.load(inc["id"])["id"] == inc["id"]
    listing = fr.list_incidents()
    assert listing[0]["id"] == inc["id"]
    assert listing[0]["first_cause"] == "lane.breaker_trip"


def test_incident_retention_prunes_to_keep(incident_dir, monkeypatch):
    monkeypatch.setenv("PEGASUS_INCIDENT_KEEP", "2")
    events.EVENTS.reset()
    fr = FlightRecorder()
    ids = [fr.capture(UNREACHABLE, reason=f"r{i}", trigger="manual")["id"]
           for i in range(4)]
    kept = {i["id"] for i in fr.list_incidents()}
    assert kept == set(ids[-2:]), "oldest artifacts pruned past the cap"


def test_observe_verdict_transition_and_cooldown(incident_dir, monkeypatch):
    monkeypatch.setenv("PEGASUS_INCIDENT_COOLDOWN_S", "3600")
    events.EVENTS.reset()
    fr = FlightRecorder()
    ok = {"verdict": "healthy", "causes": []}
    bad = {"verdict": "critical",
           "causes": [{"cause": "x", "severity": "critical"}]}
    assert fr.observe_verdict(ok, UNREACHABLE) is None
    i1 = fr.observe_verdict(bad, UNREACHABLE)
    assert i1, "healthy->critical must capture"
    # STAYING unhealthy: the same id keeps riding the verdict — a second
    # doctor run minutes into one incident points at one artifact
    assert fr.observe_verdict({"verdict": "degraded", "causes": []},
                              UNREACHABLE) == i1
    # recover, then degrade again INSIDE the cooldown: no spam capture —
    # and no stale id either (the retained artifact documents a DIFFERENT
    # excursion; attaching it to a fresh transition would mislabel it)
    assert fr.observe_verdict(ok, UNREACHABLE) is None
    assert fr.observe_verdict(bad, UNREACHABLE) is None
    assert len(fr.list_incidents()) == 1
    # cooldown cleared: a fresh transition captures a fresh artifact
    fr.reset()
    i2 = fr.observe_verdict(bad, UNREACHABLE)
    assert i2 and i2 != i1
    assert len(fr.list_incidents()) == 2


# --------------------------------------- grouped onebox structural merge


@pytest.fixture(scope="module")
def gcluster(tmp_path_factory):
    c = MiniCluster(tmp_path_factory.mktemp("fr-grp"), n_nodes=1,
                    serve_groups=2)
    c.cli = c.create("frg", partitions=4, replicas=1)
    yield c
    c.cli.close()
    c.stop()


def _node_cmd(conn, name, args):
    _, body = conn.call("RPC_CLI_CLI_CALL", codec.encode(
        RemoteCommandRequest(name, list(args))), timeout=30.0)
    return codec.decode(RemoteCommandResponse, body).output


def test_grouped_events_dump_merges_every_worker(gcluster):
    """Node-level `events-dump` through the group router: the pid-keyed
    replies merge structurally, so EVERY worker process's ring stays
    visible side by side — nothing averages or overwrites."""
    node = gcluster.stubs[0]
    host, _, port = node.address.rpartition(":")
    conn = RpcConnection((host, int(port)))
    try:
        # arm+heal a fail point node-wide: the fan-out plants one
        # arm/disarm pair in EACH worker's ring
        _node_cmd(conn, "set-fail-point", ["frg.unit.fault", "return()"])
        _node_cmd(conn, "set-fail-point", ["frg.unit.fault", "off()"])
        merged = json.loads(_node_cmd(conn, "events-dump", []))
        pids = sorted(k for k in merged if k.startswith("pid:"))
        assert len(pids) == 2, f"one ring per worker process: {merged.keys()}"
        for pid in pids:
            names = [e["name"] for e in merged[pid]]
            assert "failpoint.arm" in names and "failpoint.disarm" in names
            arm = next(e for e in merged[pid]
                       if e["name"] == "failpoint.arm")
            assert arm["attrs"]["point"] == "frg.unit.fault"
            assert {"seq", "ts", "name", "sev"} <= set(arm)
        # the history rings ride the same pid-keyed merge
        hist = json.loads(_node_cmd(conn, "metrics-history", []))
        assert sorted(k for k in hist if k.startswith("pid:")) == pids
        assert all("samples" in hist[pid] for pid in pids)
    finally:
        conn.close()


# ----------------------------------------------------- http route units


def test_http_route_functions_parse_queries(incident_dir):
    """GET /events, /metrics/history and /incidents share the remote
    commands' data paths; what is route-specific is the query parsing —
    filters applied, malformed numbers degrade to unfiltered."""
    from pegasus_tpu.runtime.service_app import (_events_route,
                                                 _incidents_route,
                                                 _metrics_history_route)

    events.EVENTS.reset()
    events.emit("failpoint.arm", point="u", action="return()")
    events.emit("learn.start", gpid="1.0")
    out = _events_route("/events?prefix=failpoint.&last=5")
    assert [e["name"] for e in out["events"]] == ["failpoint.arm"]
    assert len(_events_route("/events?last=oops")["events"]) == 2
    assert _events_route("/events?since=%f" % (time.time() + 5)) \
        == {"events": []}
    hist = _metrics_history_route("/metrics/history?seconds=60")
    assert "samples" in hist and hist["interval_s"] > 0
    # empty incident dir: empty listing, unknown id -> None
    assert _incidents_route("/incidents") == {"incidents": []}
    assert _incidents_route("/incidents?id=nope") == {"incident": None}


# ------------------------------------------- collector scrape robustness


def test_collector_scrape_failure_counts_not_skips(tmp_path):
    """Regression (ISSUE 12 satellite): a node that dies mid-
    collect_once must COUNT — error counter + collector.scrape_failed
    event naming the node — and the round must still conclude."""
    cluster = MiniCluster(tmp_path)
    col = None
    try:
        cli = cluster.create("scr", partitions=2)
        for i in range(10):
            cli.set(b"k%03d" % i, b"s", b"v%d" % i)
        col = InfoCollector([cluster.meta_addr])  # driven by hand: no loop
        col._c_scrape_err = errs = _Cnt()
        # kill a node the round WILL scrape (a primary), while the meta
        # still lists it (failure-detector grace)
        _, victim, _ = _partition_members(cluster, "scr", 0)
        for stub in list(cluster.stubs):
            if stub.address == victim:
                stub.stop()
                cluster.stubs.remove(stub)
        events.EVENTS.reset()
        summary = col.collect_once()
        assert "scr" in summary, "the round must conclude despite the death"
        assert errs.n > 0, "a dead node must count, not silently vanish"
        failed = events.EVENTS.snapshot(prefix="collector.scrape_failed")
        assert any(e["attrs"]["node"] == victim for e in failed), failed
        cli.close()
    finally:
        if col is not None:
            col.stop()
        cluster.stop()


# ------------------------------------------------------- e2e acceptance


def test_incident_autocapture_names_planted_fault(tmp_path, monkeypatch):
    """The acceptance shape, in-suite: `audit.digest` corruption planted
    under concurrent load — the doctor's healthy→critical transition
    auto-captures ONE retained incident whose first-cause entry names
    the fault's arm event, with the arm/audit/doctor events ordered on
    one wall-clock timeline, and the id riding every doctor verdict for
    the duration of the incident."""
    monkeypatch.setenv("PEGASUS_INCIDENT_DIR", str(tmp_path / "inc"))
    cluster = MiniCluster(tmp_path)
    fp.setup()
    RECORDER.reset()
    try:
        cli = cluster.create("frinc", partitions=2)
        for i in range(40):
            cli.set(b"k%03d" % i, b"s", b"v%d" % i)
        _quiet_breakers()
        time.sleep(0.5)  # beacons land
        assert run_cluster_doctor([cluster.meta_addr])["verdict"] \
            == "healthy"
        app_id, _, secondaries = _partition_members(cluster, "frinc", 0)
        victim = secondaries[0]
        # clean slate: the planted arm below must be the EARLIEST
        # cascade-class event in the ring, as in a real incident window
        events.EVENTS.reset()
        fp.cfg("audit.digest", f"return({victim}@{app_id}.0)")
        with _Load(cli):
            report = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
        assert report["mismatches"], "the planted fault must be caught"
        time.sleep(0.6)  # corrupted digest rides the next beacons
        verdict = run_cluster_doctor([cluster.meta_addr])
        assert verdict["verdict"] == "critical"
        inc_id = verdict.get("incident")
        assert inc_id, "the transition must auto-capture an incident"

        inc = RECORDER.load(inc_id)
        assert inc is not None, "the artifact must be retained on disk"
        fc = inc["first_cause"]
        assert fc["name"] == "failpoint.arm", fc
        assert fc["attrs"]["point"] == "audit.digest"
        tl = inc["timeline"]
        assert all(tl[i]["ts"] <= tl[i + 1]["ts"]
                   for i in range(len(tl) - 1)), "one aligned timeline"
        names = {e["name"] for e in tl}
        assert {"failpoint.arm", "audit.mismatch", "doctor.verdict"} \
            <= names, names
        # cause precedes symptom on the aligned axis
        assert fc["ts"] <= min(e["ts"] for e in tl
                               if e["name"] == "audit.mismatch")

        # a doctor run INSIDE the cooldown, still critical: same id
        assert run_cluster_doctor([cluster.meta_addr]).get("incident") \
            == inc_id
        assert any(i["id"] == inc_id and i["first_cause"] == "failpoint.arm"
                   for i in RECORDER.list_incidents())

        # the shell surfaces list it
        out = io.StringIO()
        from pegasus_tpu.shell.main import Shell

        Shell([cluster.meta_addr], out=out).run_line("flight_recorder")
        assert inc_id in out.getvalue()
        cli.close()
    finally:
        fp.teardown()
        cluster.stop()
        RECORDER.reset()
