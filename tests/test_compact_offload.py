"""ISSUE 14: rack-scale compaction offload — one device-owning
compaction service serving many CPU-only replica nodes.

Pinned here:
  - the run wire codec round-trips a KVBlock exactly;
  - a merge through the remote service (real sockets) is byte-identical
    to the local cpu merge, including with user compaction rules and a
    table default-TTL (the tenant-side post-filter pattern);
  - an interrupted ship RESUMES: a retry ships only the runs that never
    landed (content-addressed staging), and a fail-point-aborted round
    is retried by the offload lane without a local fallback;
  - a dead service means the node's byte-identical LOCAL cpu fallback,
    bounded, never a stall; the admission gate refuses (not queues) over
    cap and the refused tenant degrades the same way;
  - engine-level byte identity: a partition compacted through a
    placement lease produces SSTs byte-identical to local compaction
    (elective trigger + manual compact), and the lease expires back to
    local like every other scheduler token;
  - the scheduler fold emits (when, where) pairs against the services'
    free budget, localize passes placement through, and the feedback
    tuner rescales the urgency thresholds from measured stage costs;
  - chaos: killing the offload service mid-run engages the lane
    fallback with zero lost acked writes and identical post-run digests.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.engine.block import KVBlock
from pegasus_tpu.engine.db import EngineOptions, LsmEngine, WriteBatch
from pegasus_tpu.ops.compact import CompactOptions, compact_blocks
from pegasus_tpu.ops.packing import pack_run_bytes, unpack_run_bytes
from pegasus_tpu.replication.compact_offload import (
    OFFLOAD_LANE_GUARD, CompactOffloadService, offload_compact_blocks)
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.lane_guard import LaneGuard, LaneGuardConfig
from pegasus_tpu.runtime.perf_counters import counters


@pytest.fixture
def failpoints():
    fp.setup()
    yield fp
    fp.teardown()


@pytest.fixture(autouse=True)
def _clean_lane():
    OFFLOAD_LANE_GUARD.reset()
    yield
    OFFLOAD_LANE_GUARD.reset()


@pytest.fixture
def svc(tmp_path):
    s = CompactOffloadService(str(tmp_path / "offload_svc"),
                              backend="cpu").start()
    yield s
    s.stop()


def _mk_run(seed, n=400, keyspace=200, deleted_every=0):
    recs = {}
    for i in range(n):
        k = generate_key(b"h%03d" % ((seed * 31 + i) % 17),
                         b"s%05d" % ((seed * 7 + i) % keyspace))
        recs[k] = (k, b"val%04d.%d" % (i, seed), 0,
                   bool(deleted_every and i % deleted_every == 0))
    return KVBlock.from_records(
        sorted(recs.values(), key=lambda r: r[0]))


def _runs(k=3):
    return [_mk_run(s, deleted_every=(7 if s == 0 else 0)) for s in range(k)]


def _blk_equal(a, b):
    return all(np.array_equal(getattr(a, c), getattr(b, c))
               for c in ("key_arena", "key_off", "key_len", "val_arena",
                         "val_off", "val_len", "expire_ts", "hash32",
                         "deleted"))


# --------------------------------------------------------------- run wire


def test_run_wire_round_trip():
    b = _mk_run(1, deleted_every=5)
    rt = unpack_run_bytes(pack_run_bytes(b))
    assert _blk_equal(b, rt)
    # deterministic: same block, same bytes (the content address)
    assert pack_run_bytes(b) == pack_run_bytes(rt)
    empty = unpack_run_bytes(pack_run_bytes(KVBlock.empty()))
    assert empty.n == 0


def test_run_wire_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_run_bytes(b"not a run at all" * 4)


# ------------------------------------------------------ block-level merge


def test_offloaded_merge_byte_identical(svc):
    runs = _runs()
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True,
                          bottommost=True)
    local = compact_blocks(runs, opts)
    remote = offload_compact_blocks(runs, opts, svc.address, tenant="t1")
    assert _blk_equal(local.block, remote.block)
    assert remote.stats["offloaded"] is True
    assert remote.stats["service"] == svc.address
    assert remote.stats["shipped_runs"] == 3
    assert OFFLOAD_LANE_GUARD.state()["fallbacks"] == 0


def test_offloaded_merge_with_post_filters_byte_identical(svc):
    """default_ttl (and the user-rule slot) run tenant-side after the
    fetch — the service never sees them, the bytes still match."""
    runs = _runs()
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True,
                          bottommost=True, default_ttl=3600)
    local = compact_blocks(runs, opts)
    remote = offload_compact_blocks(runs, opts, svc.address, tenant="t1")
    assert _blk_equal(local.block, remote.block)
    assert int(remote.block.expire_ts.max()) == 100 + 3600


def test_interrupted_ship_resumes_content_addressed(svc):
    """A second round over the same runs ships ZERO bytes: the staging
    is content-addressed, so whatever landed (even under a different
    job) is reused — the mid-ship-kill resume story."""
    runs = _runs()
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True)
    local = compact_blocks(runs, opts)
    r1 = offload_compact_blocks(runs, opts, svc.address, tenant="t1")
    assert r1.stats["shipped_runs"] == 3
    r2 = offload_compact_blocks(runs, opts, svc.address, tenant="t1")
    assert r2.stats["shipped_runs"] == 0
    assert r2.stats["skipped_runs"] == 3
    assert r2.stats["shipped_bytes"] == 0
    assert _blk_equal(local.block, r1.block)
    assert _blk_equal(local.block, r2.block)


def test_mid_ship_abort_retries_without_fallback(svc, failpoints):
    """A fail-point abort mid-round is a transient: the offload lane
    RETRIES (resuming staged runs) and still returns the remote merge —
    no local fallback, byte-identical output."""
    runs = _runs()
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True)
    local = compact_blocks(runs, opts)
    failpoints.cfg("compact.offload", "2*raise(chaos mid-ship)")
    remote = offload_compact_blocks(runs, opts, svc.address, tenant="t1")
    assert _blk_equal(local.block, remote.block)
    lane = OFFLOAD_LANE_GUARD.state()
    assert lane["fallbacks"] == 0
    assert lane["retries"] >= 1


def test_dead_service_falls_back_bounded():
    """No service listening: the guard degrades to the LOCAL cpu merge
    — byte-identical, and bounded (no stall)."""
    runs = _runs()
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True)
    local = compact_blocks(runs, opts)
    guard = LaneGuard(LaneGuardConfig(deadline_s=30.0, max_retries=0),
                      metric_prefix="offload.lane")
    t0 = time.monotonic()
    remote = offload_compact_blocks(runs, opts, "127.0.0.1:1",
                                    tenant="t1", guard=guard)
    assert time.monotonic() - t0 < 20.0
    assert _blk_equal(local.block, remote.block)
    assert guard.state()["fallbacks"] == 1


def test_admission_gate_refuses_over_cap(tmp_path, monkeypatch):
    """Merges over the service cap are REFUSED, not queued; the refused
    tenant's lane falls back to local cpu — same bytes either way."""
    import importlib

    import pegasus_tpu.parallel as par

    # the package re-exports the sharded_compact FUNCTION under the
    # submodule's name, so fetch the module itself for patching
    shc = importlib.import_module("pegasus_tpu.parallel.sharded_compact")
    svc = CompactOffloadService(str(tmp_path / "svc1"), backend="cpu",
                                max_concurrent=1).start()
    release = threading.Event()
    real = shc.compact_blocks_meshed

    def slow(blocks, opts, mesh=None):
        release.wait(20.0)
        return real(blocks, opts, mesh)

    monkeypatch.setattr(shc, "compact_blocks_meshed", slow)
    monkeypatch.setattr(par, "compact_blocks_meshed", slow)
    runs_a, runs_b = _runs(), [_mk_run(s + 10) for s in range(2)]
    opts = CompactOptions(backend="cpu", now=100, runs_sorted=True)
    local_b = compact_blocks(runs_b, opts)
    guard = LaneGuard(LaneGuardConfig(deadline_s=60.0, max_retries=0),
                      metric_prefix="offload.lane")
    box = {}

    def first():
        box["a"] = offload_compact_blocks(runs_a, opts, svc.address,
                                          tenant="slow", guard=guard)

    t = threading.Thread(target=first, daemon=True)
    t.start()
    # wait until the slow merge actually occupies the one slot
    deadline = time.monotonic() + 10.0
    while svc.status()["running_merges"] < 1:
        assert time.monotonic() < deadline, "merge never started"
        time.sleep(0.02)
    try:
        r_b = offload_compact_blocks(runs_b, opts, svc.address,
                                     tenant="refused", guard=guard)
        assert _blk_equal(local_b.block, r_b.block)
        assert guard.state()["fallbacks"] == 1  # refused -> local cpu
        assert counters.rate(
            "offload.service.reject_count").total() >= 1
    finally:
        release.set()
        t.join(timeout=30.0)
        svc.stop()
    assert "a" in box  # the slow tenant's merge still completed


# ------------------------------------------------------------ engine level


def _engine_load(eng, n=1200, flush_every=300):
    d = 0
    for i in range(n):
        d += 1
        k = generate_key(b"h%03d" % (i % 40), b"s%05d" % (i % 400))
        eng.write(WriteBatch().put(k, b"v%06d" % i), d)
        if i % flush_every == flush_every - 1:
            eng.flush()
    eng.flush()


def _sst_files(path):
    out = {}
    for n in sorted(os.listdir(path)):
        if n.endswith(".sst"):
            with open(os.path.join(path, n), "rb") as f:
                out[n] = f.read()
    return out


def _eopts():
    return EngineOptions(backend="cpu", l0_compaction_trigger=2,
                         memtable_bytes=1 << 20)


def test_engine_offloaded_ssts_byte_identical(tmp_path, svc):
    """The acceptance bar: elective (trigger) and manual merges routed
    through the placement lease produce SST files byte-identical to
    local compaction — names, headers, columns, blooms."""
    a = LsmEngine(str(tmp_path / "local"), _eopts())
    b = LsmEngine(str(tmp_path / "offl"), _eopts())
    b.set_offload_target(svc.address, ttl_s=600)
    try:
        _engine_load(a)
        _engine_load(b)
        a.manual_compact(now=100)
        b.manual_compact(now=100)
    finally:
        a.close()
        b.close()
    assert _sst_files(a.path) == _sst_files(b.path)
    assert counters.rate("engine.compact.offload_count").total() > 0
    assert OFFLOAD_LANE_GUARD.state()["fallbacks"] == 0
    assert b.stats()["compact_offload"] == svc.address


def test_engine_dead_service_byte_identical_fallback(tmp_path):
    """A placement lease pointing at a DEAD service: every merge rides
    the lane fallback — same SST bytes as a local engine, no stall."""
    a = LsmEngine(str(tmp_path / "local"), _eopts())
    b = LsmEngine(str(tmp_path / "offl"), _eopts())
    b.set_offload_target("127.0.0.1:1", ttl_s=600)
    try:
        _engine_load(b, n=600)
        _engine_load(a, n=600)
        a.manual_compact(now=100)
        b.manual_compact(now=100)
    finally:
        a.close()
        b.close()
    assert _sst_files(a.path) == _sst_files(b.path)
    assert OFFLOAD_LANE_GUARD.state()["fallbacks"] > 0


def test_placement_lease_expires_to_local(tmp_path):
    eng = LsmEngine(str(tmp_path / "e"), _eopts())
    try:
        eng.set_offload_target("127.0.0.1:9", ttl_s=0.05)
        assert eng.offload_target() == "127.0.0.1:9"
        time.sleep(0.1)
        assert eng.offload_target() is None  # lease lapsed -> local
        eng.set_offload_target("127.0.0.1:9", ttl_s=30)
        eng.set_offload_target("", ttl_s=30)  # explicit clear
        assert eng.offload_target() is None
    finally:
        eng.close()


# ---------------------------------------------------- scheduler placement


def _part(node="n1:1", l0=0, debt=0, gap=0, ceiling=12):
    return {"node": node, "l0_files": l0, "debt_bytes": debt,
            "apply_gap": gap, "ceiling_files": ceiling,
            "pending_installs": 0}


KNOBS = {"urgent_l0": 4, "backlog_urgent": 64, "max_urgent_per_node": 2,
         "max_device": 0, "ttl_s": 30.0}


def test_fold_emits_when_where_pairs():
    from pegasus_tpu.collector.compact_scheduler import fold_decisions

    parts = {
        "1.0": _part(l0=5, debt=500),     # debtiest -> placed
        "1.1": _part(l0=3, debt=300),     # placed second
        "1.2": _part(l0=1, debt=100),     # budget exhausted -> local
        "1.3": _part(l0=0, debt=0),       # nothing to do -> local
        "1.4": _part(l0=6, debt=900),     # hot -> defer, never placed
    }
    out = fold_decisions(parts, hot={"1.4"}, knobs=KNOBS,
                         places={"svc:1": 2})
    assert out["1.0"]["where"] == "svc:1"
    assert "offload_budget" in out["1.0"]["reasons"]
    assert out["1.1"]["where"] == "svc:1"
    assert out["1.2"]["where"] == ""
    assert out["1.3"]["where"] == ""
    assert out["1.4"]["policy"] == "defer" and out["1.4"]["where"] == ""


def test_fold_placement_balances_services():
    from pegasus_tpu.collector.compact_scheduler import fold_decisions

    parts = {f"1.{i}": _part(l0=2 + i, debt=100 * (i + 1))
             for i in range(4)}
    out = fold_decisions(parts, knobs=KNOBS,
                         places={"svcA:1": 1, "svcB:1": 1})
    placed = [d["where"] for d in out.values() if d["where"]]
    assert sorted(placed) == ["svcA:1", "svcB:1"]  # one each, balanced


def test_localize_passes_where_through():
    from pegasus_tpu.collector.compact_scheduler import (fold_decisions,
                                                         localize_decisions)

    parts = {"1.0": _part(node="n1:1", l0=5, debt=500)}
    dec = fold_decisions(parts, knobs=KNOBS, places={"svc:1": 4})
    mine = localize_decisions(dec, {"1.0": ["n1:1", "n2:1"]}, "n2:1")
    assert mine["1.0"]["where"] == "svc:1"


def test_tune_knobs_from_stage_cost():
    from pegasus_tpu.collector.compact_scheduler import (stage_cost_us,
                                                         tune_knobs)

    k = dict(KNOBS, tune_slow_us=2e6, tune_fast_us=25e4)
    slow, rep = tune_knobs(5e6, k)
    assert slow["urgent_l0"] == 8 and rep["mode"] == "slow_merges"
    fast, rep = tune_knobs(1e5, k)
    assert fast["urgent_l0"] == 2 and rep["mode"] == "fast_merges"
    base, rep = tune_knobs(1e6, k)
    assert base["urgent_l0"] == 4 and rep["mode"] == "base"
    window = {"samples": [
        {"ts": 1, "values": {"compact.stage.pack.duration_us.p99": 100.0,
                             "compact.stage.device.duration_us.p99": 900.0}},
        {"ts": 2, "values": {"compact.stage.pack.duration_us.p99": 50.0}},
    ]}
    assert stage_cost_us(window) == 1000.0
    assert stage_cost_us({"samples": []}) == 0.0


def test_scheduler_tick_scrapes_service_budget(tmp_path, svc, monkeypatch):
    """run_scheduler_tick folds the service's offload-status into the
    report even with no cluster behind it (no meta = early exit, but the
    service scrape shape is covered by the fold test; here we pin the
    END-TO-END remote-command surface the scrape uses)."""
    from pegasus_tpu.collector.cluster_doctor import ClusterCaller

    caller = ClusterCaller([])
    try:
        out = json.loads(caller.remote_command(svc.address,
                                               "offload-status", []))
    finally:
        caller.close()
    assert out["free_slots"] == svc.max_concurrent
    assert out["address"] == svc.address
    assert out["backend"] == "cpu"


# ------------------------------------------------------------------ chaos


class _SvcCtl:
    def __init__(self, tmp_path):
        self.root = str(tmp_path / "chaos_svc")
        self.svc = CompactOffloadService(self.root, backend="cpu").start()
        self.address = self.svc.address

    def stop(self):
        self.svc.stop()

    def restart(self):
        host, _, port = self.address.rpartition(":")
        self.svc = CompactOffloadService(self.root, host=host,
                                         port=int(port),
                                         backend="cpu").start()


def test_offload_service_kill_mid_run_chaos(tmp_path):
    """The ISSUE 14 chaos scenario actor: hard-kill the offload service
    mid-run under write load. Asserts: the lane fallback engages, ZERO
    lost acked writes (per-key payload verification on the offloaded
    engine), post-run digests identical to an un-offloaded control, and
    the actor reports recovered once the service is back."""
    from pegasus_tpu.chaos.actors import OffloadServiceKill
    from pegasus_tpu.chaos.journal import EventJournal
    from pegasus_tpu.chaos.scenario import FaultAction, Scenario, \
        ScenarioRunner

    ctl = _SvcCtl(tmp_path)
    control = LsmEngine(str(tmp_path / "control"),
                        EngineOptions(backend="cpu", l0_compaction_trigger=1,
                                      memtable_bytes=1 << 20))
    victim = LsmEngine(str(tmp_path / "victim"),
                       EngineOptions(backend="cpu", l0_compaction_trigger=1,
                                     memtable_bytes=1 << 20))
    victim.set_offload_target(ctl.address, ttl_s=600)
    journal = EventJournal()
    scenario = Scenario("offload-kill", [
        FaultAction("kill-offload", "offload_kill", at_s=0.3,
                    duration_s=1.0, recovery_deadline_s=15.0,
                    settle_s=0.1),
    ])
    runner = ScenarioRunner(scenario,
                            {"offload_kill": OffloadServiceKill(ctl)},
                            journal)
    runner.start(run_s=2.0)
    acked = {}
    d = 0
    t_end = time.monotonic() + 2.2
    i = 0
    try:
        while time.monotonic() < t_end:
            d += 1
            k = generate_key(b"h%03d" % (i % 20), b"s%05d" % i)
            v = b"payload%08d" % i
            for eng in (control, victim):
                eng.write(WriteBatch().put(k, v), d)
            acked[k] = v
            i += 1
            if i % 40 == 0:
                control.flush()
                victim.flush()  # trigger=1: every flush drives a merge
        runner.join(timeout=30.0)
        assert not journal.failures, journal.failures
        assert OFFLOAD_LANE_GUARD.state()["fallbacks"] > 0, \
            "the kill window never forced a fallback"
        now = 100
        dv = victim.state_digest(now=now)
        dc = control.state_digest(now=now)
        assert dv == dc  # identical post-run state, record for record
        # zero lost acked writes, verified key by key
        keys = sorted(acked)
        got = victim.get_batch(keys, now=now)
        assert got == [acked[k] for k in keys]
    finally:
        control.close()
        victim.close()
        ctl.stop()


def test_fold_placement_weighted_by_replica_count():
    """A placement reaches every replica of the partition (each
    compacts independently), so it charges min(replicas, remaining)
    slots — the scraped budget is not oversubscribed by the
    replication factor."""
    from pegasus_tpu.collector.compact_scheduler import fold_decisions

    parts = {f"1.{i}": _part(l0=2 + i, debt=100 * (i + 1))
             for i in range(3)}
    out = fold_decisions(parts, knobs=KNOBS, places={"svc:1": 4},
                         weights={g: 3 for g in parts})
    placed = [g for g, d in out.items() if d["where"]]
    # debtiest charges 3 of 4 slots, second charges the remaining 1,
    # third finds no budget left
    assert placed == ["1.1", "1.2"]
    assert out["1.0"]["where"] == ""
