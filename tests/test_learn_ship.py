"""Block-shipped learning (ISSUE 13): byte-identity across learn paths,
block-granular resume after a mid-ship kill, GC/unlink holds while a
checkpoint is pinned, the digest proof failing loudly, and the streamed
re-seed over real sockets with bounded chunks.

Every learn here ends with the PR 8 decree-anchored digest compared
against the primary at equal decrees — a transfer that loses bytes must
fail these tests, not pass as a faster learn.
"""

import json
import os
import time

import pytest

from pegasus_tpu.base.utils import epoch_now
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.server_impl import RPC_MULTI_PUT
from pegasus_tpu.replication.replica import (GroupView, PrepareRejected,
                                             Replica, ReplicaError)
from pegasus_tpu.replication.mutation_log import LogMutation
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.runtime.perf_counters import counters


def _opts(**kw):
    """Many small SSTs (no L0 merge) so the block manifest has real
    granularity for delta/resume assertions."""
    kw.setdefault("backend", "cpu")
    kw.setdefault("memtable_bytes", 32 << 10)
    kw.setdefault("l0_compaction_trigger", 100)
    return EngineOptions(**kw)


def _mk_primary(root, n=1500, **okw):
    prim = Replica("prim", str(root / "prim"), options=_opts(**okw),
                   quorum=1)
    prim.assume_view(GroupView(1, "prim", []))
    _load(prim, 0, n)
    prim.server.engine.flush()
    return prim


def _load(prim, lo, hi):
    for base in range(lo, hi, 50):
        kvs = [msg.KeyValue(b"s%06d" % i, b"v%04d" % (i % 7919) + b"x" * 30)
               for i in range(base, min(base + 50, hi))]
        prim.client_write(RPC_MULTI_PUT, msg.MultiPutRequest(
            hash_key=b"h%03d" % (base % 31), kvs=kvs))


def _learner(root, name, **okw):
    return Replica(name, str(root / name), options=_opts(**okw), quorum=1)


def _totals():
    return {k: counters.rate("learn.ship." + k).total()
            for k in ("blocks", "bytes", "delta_skipped_blocks")}


def _delta(before, after):
    return {k: after[k] - before[k] for k in before}


def _assert_identical(prim, learner, now):
    assert learner.last_committed == prim.last_committed
    a = prim.server.engine.state_digest(now=now)
    b = learner.server.engine.state_digest(now=now)
    assert a["digest"] == b["digest"], "post-learn digest diverged"
    assert a["records"] == b["records"] > 0


class _MonolithicPeer:
    """Only the legacy surface: forces learn_from down the monolithic
    whole-state path against the same primary."""

    def __init__(self, prim):
        self.prim = prim

    def fetch_learn_state(self):
        return self.prim.fetch_learn_state()


# ------------------------------------------------------ byte identity


def test_full_delta_and_monolithic_learns_are_byte_identical(tmp_path):
    """The three learn paths produce identical engine digests at equal
    decrees, and the delta re-learn moves >=5x fewer bytes than either
    full path (the acceptance ratio) while skipping the blocks the
    learner already held."""
    prim = _mk_primary(tmp_path, n=1500)
    now = epoch_now()
    try:
        t0 = _totals()
        mono = _learner(tmp_path, "mono")
        mono.learn_from(_MonolithicPeer(prim))
        t1 = _totals()
        _assert_identical(prim, mono, now)
        mono_bytes = _delta(t0, t1)["bytes"]
        assert mono_bytes > 0

        full = _learner(tmp_path, "full")
        full.learn_from(prim)
        t2 = _totals()
        _assert_identical(prim, full, now)
        d_full = _delta(t1, t2)
        assert d_full["bytes"] > 0 and d_full["blocks"] > 1
        # fresh learner: nothing to delta-skip
        assert d_full["delta_skipped_blocks"] == 0

        # small burst, then RE-learn the same learner: it already holds
        # every old SST, so only the new blocks (+ manifest) ship
        _load(prim, 1500, 1700)
        prim.server.engine.flush()
        now2 = epoch_now()
        full.learn_from(prim)
        t3 = _totals()
        _assert_identical(prim, full, now2)
        d_delta = _delta(t2, t3)
        assert d_delta["delta_skipped_blocks"] > 0, \
            "delta learn re-shipped blocks the learner already held"
        assert d_delta["bytes"] * 5 <= mono_bytes, (
            f"delta learn moved {d_delta['bytes']}B, monolithic "
            f"{mono_bytes}B — not the >=5x win")
        assert d_delta["bytes"] * 5 <= d_full["bytes"]
    finally:
        for r in (prim, mono, full):
            r.close()


def test_delta_kill_switch_ships_everything(tmp_path, monkeypatch):
    """PEGASUS_LEARN_DELTA=0 must disable the delta for REAL: a
    re-learn of a learner that already holds every block still
    re-fetches all of them (counter-asserted, not just the advisory
    missing list), and the handshake's missing diff reflects the
    switch both ways."""
    from pegasus_tpu.replication.learn import dir_manifest

    prim = _mk_primary(tmp_path, n=500)
    lrn = _learner(tmp_path, "lrn")
    try:
        lrn.learn_from(prim)  # learner now holds every block
        t0 = _totals()
        monkeypatch.setenv("PEGASUS_LEARN_DELTA", "0")
        lrn.learn_from(prim)  # kill switch: nothing reused, all fetched
        d = _delta(t0, _totals())
        assert d["delta_skipped_blocks"] == 0, \
            "kill switch left the delta reuse path active"
        assert d["blocks"] > 1 and d["bytes"] > 0
        _assert_identical(prim, lrn, epoch_now())
        monkeypatch.delenv("PEGASUS_LEARN_DELTA")
        # handshake diff: delta=False ignores the have-set entirely
        prim.server.engine.sync_checkpoint()
        have = dir_manifest(prim.server.engine.get_checkpoint_dir())
        st = prim.prepare_learn_state(have=have, delta=False)
        try:
            assert st["missing"] == [e["name"] for e in st["blocks"]]
        finally:
            prim.finish_learn(st["learn_id"])
        st2 = prim.prepare_learn_state(have=have, delta=True)
        try:
            assert st2["missing"] == []  # everything digest-matched
        finally:
            prim.finish_learn(st2["learn_id"])
    finally:
        prim.close()
        lrn.close()


# ------------------------------------------------- mid-ship kill + resume


class _FlakyPeer:
    """Drops the connection after N block waves on the FIRST attempt —
    the mid-ship learner-kill stand-in."""

    def __init__(self, prim, fail_after_blocks):
        self.prim = prim
        self.fail_after = fail_after_blocks
        self.calls = 0
        self.armed = True

    def prepare_learn_state(self, have=None, delta=None):
        return self.prim.prepare_learn_state(have=have, delta=delta)

    def fetch_learn_chunks(self, learn_id, reqs):
        self.calls += 1
        if self.armed and self.calls > self.fail_after:
            raise ConnectionError("mid-ship drop")
        return self.prim.fetch_learn_chunks(learn_id, reqs)

    def fetch_learn_tail(self, learn_id):
        return self.prim.fetch_learn_tail(learn_id)

    def finish_learn(self, learn_id):
        self.prim.finish_learn(learn_id)


def test_mid_ship_kill_resumes_at_block_granularity(tmp_path):
    """A learn dropped mid-ship leaves the partition re-learnable, and
    the retry fetches ONLY the blocks the first attempt did not land —
    counter-asserted."""
    prim = _mk_primary(tmp_path, n=1200)
    now = epoch_now()
    lrn = _learner(tmp_path, "lrn")
    try:
        flaky = _FlakyPeer(prim, fail_after_blocks=3)
        t0 = _totals()
        with pytest.raises(ConnectionError):
            lrn.learn_from(flaky)
        t1 = _totals()
        first = _delta(t0, t1)
        assert first["blocks"] == 3  # three blocks landed before the drop
        assert not prim.learn_pins(), "failed learn leaked its pin"
        # partition is re-learnable; the resume skips the landed blocks
        flaky.armed = False
        lrn.learn_from(flaky)
        t2 = _totals()
        second = _delta(t1, t2)
        _assert_identical(prim, lrn, now)
        assert second["delta_skipped_blocks"] >= 3, \
            "resume re-fetched blocks the first attempt already landed"
        total_blocks = len(os.listdir(os.path.join(prim.path, "data"))) \
            - len([n for n in os.listdir(os.path.join(prim.path, "data"))
                   if not n.endswith(".sst") and n != "MANIFEST"])
        assert second["blocks"] + first["blocks"] \
            + second["delta_skipped_blocks"] >= total_blocks
    finally:
        prim.close()
        lrn.close()


def test_mid_ship_fail_point_aborts_then_resumes(tmp_path):
    """The chaos seam: `learn.ship` armed with raise() aborts a learn
    mid-ship; healing it lets the SAME learner finish via resume."""
    from pegasus_tpu.runtime import fail_points as fp

    prim = _mk_primary(tmp_path, n=800)
    now = epoch_now()
    lrn = _learner(tmp_path, "lrn")
    fp.setup()
    try:
        # the learner-side hook fires once per block: let 2 pass, then
        # kill every later fetch for the first attempt
        fp.cfg("learn.ship", "2*off()")
        fp.cfg("learn.ship", "off()")
        fp.cfg("learn.ship", "100%raise(chaos)")
        with pytest.raises((ConnectionError, ReplicaError, Exception)):
            lrn.learn_from(prim)
        fp.cfg("learn.ship", "off()")
        lrn.learn_from(prim)
        _assert_identical(prim, lrn, now)
    finally:
        fp.teardown()
        prim.close()
        lrn.close()


# ------------------------------------------------------- pin semantics


def test_gc_and_log_held_while_checkpoint_pinned(tmp_path):
    """While a learn pin is live: checkpoint GC must not drop the pinned
    dir (no dangling block fetch) and plog GC must not drop segments
    above the pinned decree (the tail fetch must stay replayable).
    Releasing the pin restores both."""
    prim = _mk_primary(tmp_path, n=600,
                       checkpoint_reserve_min_count=1)
    prim.plog.segment_bytes = 2048  # roll segments fast so GC has prey
    try:
        st = prim.prepare_learn_state(have=())
        lid, pinned_decree = st["learn_id"], st["ckpt_decree"]
        eng = prim.server.engine
        pinned_dir = eng.get_checkpoint_dir(pinned_decree)
        # advance the world: more writes, newer checkpoints, GC rounds
        _load(prim, 600, 1200)
        prim.server.engine.flush()
        eng.sync_checkpoint()
        assert pinned_decree in eng.pinned_checkpoints()
        assert os.path.isdir(pinned_dir), \
            "checkpoint GC dropped a pinned checkpoint"
        prim.gc_log(flush=True)
        tail = [m.decree for m in prim.plog.replay(pinned_decree)]
        assert tail and tail[0] == pinned_decree + 1, \
            "plog GC opened a gap above the pinned checkpoint decree"
        # fetches still serve from the pinned dir
        entry = next(e for e in st["blocks"] if e["name"] != "MANIFEST")
        ch = prim.fetch_learn_block(lid, entry["name"], 0, entry["size"])
        assert len(ch["data"]) == entry["size"]
        # release: GC reclaims on the next rounds
        prim.finish_learn(lid)
        assert pinned_decree not in eng.pinned_checkpoints()
        eng.sync_checkpoint()  # runs gc_checkpoints with the pin gone
        assert not os.path.isdir(pinned_dir)
        with pytest.raises(ReplicaError):
            prim.fetch_learn_block(lid, entry["name"], 0, 16)
    finally:
        prim.close()


def test_expired_pin_is_reaped_and_fetch_fails_loudly(tmp_path, monkeypatch):
    monkeypatch.setenv("PEGASUS_LEARN_PIN_TTL_S", "0.05")
    prim = _mk_primary(tmp_path, n=300)
    try:
        st = prim.prepare_learn_state(have=())
        time.sleep(0.1)
        with pytest.raises(ReplicaError):
            prim.fetch_learn_block(st["learn_id"], st["blocks"][0]["name"],
                                   0, 16)
        prim.gc_log()  # reaps the expired pin
        assert not prim.learn_pins()
        assert not prim.server.engine.pinned_checkpoints()
    finally:
        prim.close()


# --------------------------------------------------- digest proof + locks


class _TamperingPeer:
    """Corrupts the handshake digest: the learn must fail loudly, never
    silently serve."""

    def __init__(self, prim):
        self.prim = prim

    def prepare_learn_state(self, have=None, delta=None):
        st = self.prim.prepare_learn_state(have=have, delta=delta)
        st["digest"] = "0" * 32
        return st

    def fetch_learn_chunks(self, learn_id, reqs):
        return self.prim.fetch_learn_chunks(learn_id, reqs)

    def fetch_learn_tail(self, learn_id):
        return self.prim.fetch_learn_tail(learn_id)

    def finish_learn(self, learn_id):
        self.prim.finish_learn(learn_id)


def test_digest_mismatch_fails_learn_loudly(tmp_path):
    prim = _mk_primary(tmp_path, n=400)
    lrn = _learner(tmp_path, "lrn")
    try:
        with pytest.raises(ReplicaError, match="digest mismatch"):
            lrn.learn_from(_TamperingPeer(prim))
        assert lrn.status != "SECONDARY"  # never silently serves
        lrn.learn_from(prim)  # honest retry succeeds
        _assert_identical(prim, lrn, epoch_now())
    finally:
        prim.close()
        lrn.close()


def test_learning_replica_rejects_prepares(tmp_path):
    """Mid-learn (lock RELEASED while staging), prepares are rejected
    instead of interleaving with the state about to be swapped in."""
    rep = _learner(tmp_path, "rep")
    try:
        with rep._lock:
            rep._learning = True
        m = LogMutation(decree=1, ballot=1, codes=["RPC_RRDB_RRDB_PUT"],
                        bodies=[b"x"])
        with pytest.raises(PrepareRejected) as ei:
            rep.on_prepare_batch(1, [m], 0)
        assert ei.value.reason == "learning"
        with rep._lock:
            rep._learning = False
        assert rep.on_prepare_batch(1, [m], 0) == 1
    finally:
        rep.close()


def test_fetch_learn_state_reads_outside_replica_lock(tmp_path):
    """Satellite 1 regression: the legacy monolithic state fetch must
    not hold the replica lock across its file reads — a concurrent
    lock acquisition must succeed while the fetch is mid-read."""
    import threading

    prim = _mk_primary(tmp_path, n=1000)
    try:
        locked_during_fetch = []
        stop = threading.Event()

        def prober():
            while not stop.is_set():
                got = prim._lock.acquire(timeout=0.02)
                if got:
                    prim._lock.release()
                locked_during_fetch.append(got)
                time.sleep(0.002)

        t = threading.Thread(target=prober)
        t.start()
        try:
            for _ in range(3):
                state = prim.fetch_learn_state()
                assert state["files"]
        finally:
            stop.set()
            t.join()
        # the lock was acquirable essentially throughout (the watermark
        # snapshot is the only locked moment)
        assert locked_during_fetch and \
            sum(locked_during_fetch) >= len(locked_during_fetch) * 0.8
    finally:
        prim.close()


# ----------------------------------------------------- over real sockets


def test_rpc_reseed_uses_block_ship_and_learn_status(tmp_path, monkeypatch):
    """A replacement node re-seeds over real sockets with bounded chunks
    (4 KiB: every block is a multi-chunk call_many wave), the learn-status
    remote command reports the ship totals the chaos harness asserts on,
    and the rebuilt replica digests identically to its primary."""
    monkeypatch.setenv("PEGASUS_LEARN_CHUNK_BYTES", "4096")
    from pegasus_tpu.collector.cluster_doctor import ClusterCaller
    from pegasus_tpu.replication.replica_stub import ReplicaStub
    from tests.test_cluster import Cluster, make_client

    c = Cluster(tmp_path, n_nodes=3)
    caller = None
    try:
        cli = make_client(c, app="ls", partitions=2)
        for i in range(250):
            cli.set(b"k%04d" % i, b"s", b"v%d" % i)
        for stub in c.nodes.values():
            for rep in list(stub._replicas.values()):
                rep.server.engine.flush()
        victim = sorted(c.nodes)[0]
        c.kill_node(victim)
        for i in range(250, 320):
            cli.set(b"k%04d" % i, b"s", b"v%d" % i)
        fresh = ReplicaStub(
            str(tmp_path / "node_new"), [c.meta_addr],
            options_factory=lambda: EngineOptions(backend="cpu"),
            cluster_id=1).start(beacon_interval=0.2)
        c.nodes[fresh.address] = fresh
        time.sleep(0.3)  # a beacon must land before repair sees the node
        assert c.meta.repair_under_replication() > 0
        assert fresh._replicas, "repair seeded no replica on the new node"
        caller = ClusterCaller([c.meta_addr])
        out = json.loads(caller.remote_command(fresh.address,
                                               "learn-status", []))
        assert out["ship.blocks"] + out["ship.delta_skipped_blocks"] > 0
        assert out["ship.bytes"] > 0
        for key, ent in out.items():
            if key.startswith("replica."):
                assert ent["learning"] is False
        # the rebuilt replicas are byte-consistent with their primaries
        now = epoch_now()
        for (a, p), rep in fresh._replicas.items():
            src = next(r for stub in c.nodes.values()
                       for (a2, p2), r in stub._replicas.items()
                       if (a2, p2) == (a, p) and r.status == "PRIMARY")
            src.broadcast_commit_point()

            def caught_up(rep=rep, src=src):
                return rep.last_committed == src.last_committed
            deadline = time.time() + 10
            while not caught_up() and time.time() < deadline:
                time.sleep(0.05)
            assert caught_up()
            assert rep.server.engine.state_digest(now=now)["digest"] \
                == src.server.engine.state_digest(now=now)["digest"]
        cli.close()
    finally:
        if caller is not None:
            caller.close()
        c.stop()


# ------------------------------------- incremental arrival proof (ISSUE 14)


def _verify_totals():
    return {k: counters.rate("learn.verify." + k).total()
            for k in ("incremental_count", "rescan_count")}


def test_delta_learn_arrival_proof_is_incremental(tmp_path):
    """Learn follow-on (c): the first (fresh) learn pays the full
    decree-anchored RESCAN — the trust anchor — but a DELTA re-learn
    proves arrival through the incremental per-block digest fold: the
    counter-assert that the staged-state rescan no longer happens per
    learn."""
    prim = _mk_primary(tmp_path, n=1200)
    lrn = _learner(tmp_path, "lrn")
    try:
        v0 = _verify_totals()
        lrn.learn_from(prim)                  # fresh seed: full rescan
        v1 = _verify_totals()
        assert v1["rescan_count"] - v0["rescan_count"] == 1
        assert v1["incremental_count"] == v0["incremental_count"]

        _load(prim, 1200, 1300)
        prim.server.engine.flush()
        lrn.learn_from(prim)                  # delta re-learn
        v2 = _verify_totals()
        assert v2["rescan_count"] == v1["rescan_count"], \
            "the delta learn re-scanned the staged state"
        assert v2["incremental_count"] - v1["incremental_count"] == 1
        _assert_identical(prim, lrn, epoch_now())
    finally:
        prim.close()
        lrn.close()


def test_incremental_proof_kill_switch_rescans(tmp_path, monkeypatch):
    """PEGASUS_LEARN_INCREMENTAL_DIGEST=0: every learn (delta or not)
    goes back to the full rescan proof."""
    monkeypatch.setenv("PEGASUS_LEARN_INCREMENTAL_DIGEST", "0")
    prim = _mk_primary(tmp_path, n=600)
    lrn = _learner(tmp_path, "lrn")
    try:
        lrn.learn_from(prim)
        _load(prim, 600, 700)
        prim.server.engine.flush()
        v1 = _verify_totals()
        lrn.learn_from(prim)
        v2 = _verify_totals()
        assert v2["rescan_count"] - v1["rescan_count"] == 1
        assert v2["incremental_count"] == v1["incremental_count"]
        _assert_identical(prim, lrn, epoch_now())
    finally:
        prim.close()
        lrn.close()


def test_manifest_fold_order_independent_and_sensitive():
    from pegasus_tpu.replication import learn as learn_mod

    a = [{"name": "1.sst", "digest": "aa"}, {"name": "2.sst",
                                             "digest": "bb"}]
    assert learn_mod.manifest_fold(a) == learn_mod.manifest_fold(a[::-1])
    tampered = [{"name": "1.sst", "digest": "aa"},
                {"name": "2.sst", "digest": "cc"}]
    assert learn_mod.manifest_fold(a) != learn_mod.manifest_fold(tampered)
    assert learn_mod.manifest_fold([]) == f"{0:016x}{0:016x}"


def test_sidecar_resume_skips_rehash(tmp_path, monkeypatch):
    """The O(delta) resume: after a mid-ship abort, the retry trusts
    the sidecar's stat identity for every block the aborted stage
    already VERIFIED — file_digest does not run again for them under
    learn_ckpt/ — and hardlink reuse from the live dir never re-hashes
    (inode trust). Only genuinely new bytes get hashed."""
    from pegasus_tpu.replication import learn as learn_mod

    prim = _mk_primary(tmp_path, n=900)
    lrn = _learner(tmp_path, "lrn")
    try:
        # interrupted first learn: let a few blocks land, then abort
        st = prim.prepare_learn_state(have=[], delta=True)
        ckpt_dir = os.path.join(lrn.path, "learn_ckpt")

        class _Abort(Exception):
            pass

        fetched = []
        real_fetch = learn_mod._fetch_block

        def flaky(source, learn_id, entry, dest_dir):
            if len(fetched) >= 1:
                raise _Abort()
            fetched.append(entry["name"])
            return real_fetch(source, learn_id, entry, dest_dir)

        monkeypatch.setattr(learn_mod, "_fetch_block", flaky)
        with pytest.raises(_Abort):
            learn_mod.stage_blocks(prim, st, ckpt_dir)
        monkeypatch.setattr(learn_mod, "_fetch_block", real_fetch)
        assert len(fetched) == 1

        hashed_ckpt = []
        real_digest = learn_mod.file_digest

        def spy(path):
            if "learn_ckpt" in path:
                hashed_ckpt.append(os.path.basename(path))
            return real_digest(path)

        monkeypatch.setattr(learn_mod, "file_digest", spy)
        stats = learn_mod.stage_blocks(prim, st, ckpt_dir)
        prim.finish_learn(st["learn_id"])
        assert stats["resumed"] == 1  # the aborted stage's block
        # the resumed block was not re-hashed: the sidecar's
        # stat identity carried their proof (fetched blocks hash once
        # inside _fetch_block, which spy counts under learn_ckpt too —
        # so the resumed names must be absent)
        assert not (set(fetched) & set(hashed_ckpt)), (fetched, hashed_ckpt)
        assert stats["fold"] == learn_mod.manifest_fold(st["blocks"])
    finally:
        prim.close()
        lrn.close()
