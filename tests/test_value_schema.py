"""Value schema tests (reference formats: src/base/pegasus_value_schema.h,
src/base/value_schema_v2.cpp; reference tests: src/base/test)."""

import struct

import pytest

from pegasus_tpu.base.value_schema import (
    SCHEMAS,
    ValueSchemaManager,
    check_if_ts_expired,
    extract_cluster_id_from_timetag,
    extract_deleted_from_timetag,
    extract_timestamp_from_timetag,
    generate_timetag,
)


def test_v0_layout():
    v = SCHEMAS[0].generate_value(0x01020304, 0, b"data")
    assert v == b"\x01\x02\x03\x04data"
    assert SCHEMAS[0].extract_expire_ts(v) == 0x01020304
    assert SCHEMAS[0].extract_user_data(v) == b"data"


def test_v1_layout():
    tag = generate_timetag(123456789, 5, True)
    v = SCHEMAS[1].generate_value(42, tag, b"payload")
    assert v[:4] == struct.pack(">I", 42)
    assert v[4:12] == struct.pack(">Q", tag)
    assert SCHEMAS[1].extract_user_data(v) == b"payload"
    assert SCHEMAS[1].extract_timetag(v) == tag


def test_v2_layout_self_describing():
    tag = generate_timetag(1, 2, False)
    v = SCHEMAS[2].generate_value(7, tag, b"u")
    assert v[0] == 0x82  # 0x80 | version 2
    assert SCHEMAS[2].extract_expire_ts(v) == 7
    assert SCHEMAS[2].extract_timetag(v) == tag
    assert SCHEMAS[2].extract_user_data(v) == b"u"


def test_timetag_bit_packing():
    # (timestamp_us << 8) | (cluster_id << 1) | deleted
    tag = generate_timetag(0xABCDEF, 0x7F, True)
    assert extract_timestamp_from_timetag(tag) == 0xABCDEF
    assert extract_cluster_id_from_timetag(tag) == 0x7F
    assert extract_deleted_from_timetag(tag) is True
    # 56-bit timestamp truncation
    assert extract_timestamp_from_timetag(generate_timetag(1 << 60, 0, False)) == (1 << 60) & (
        (1 << 56) - 1
    )


def test_update_expire_ts_in_place():
    for ver in (0, 1, 2):
        tag = 99 if ver else 0
        v = SCHEMAS[ver].generate_value(10, tag, b"keepme")
        v2 = SCHEMAS[ver].update_expire_ts(v, 77)
        assert SCHEMAS[ver].extract_expire_ts(v2) == 77
        assert SCHEMAS[ver].extract_user_data(v2) == b"keepme"
        assert SCHEMAS[ver].extract_timetag(v2) == tag


def test_manager_dispatch():
    mgr = ValueSchemaManager()
    v0 = SCHEMAS[0].generate_value(1, 0, b"x")
    v1 = SCHEMAS[1].generate_value(1, 2, b"x")
    v2 = SCHEMAS[2].generate_value(1, 2, b"x")
    # table-level version decides when first bit unset
    assert mgr.get_value_schema(0, v0).VERSION == 0
    assert mgr.get_value_schema(1, v1).VERSION == 1
    # per-record version wins when first bit set, regardless of meta cf version
    assert mgr.get_value_schema(0, v2).VERSION == 2
    assert mgr.get_value_schema(1, v2).VERSION == 2
    # unknown future per-record version falls back to latest
    fake_future = bytes([0x80 | 0x55]) + v2[1:]
    assert mgr.get_value_schema(0, fake_future).VERSION == 2
    with pytest.raises(ValueError):
        mgr.get_value_schema(9, v0)


def test_expiry_semantics():
    assert not check_if_ts_expired(100, 0)  # 0 = no ttl
    assert check_if_ts_expired(100, 100)
    assert check_if_ts_expired(100, 99)
    assert not check_if_ts_expired(100, 101)
