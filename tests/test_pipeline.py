"""Double-buffered compaction pipeline tests (ops/pipeline.py).

Three tiers:
  1. executor semantics — ordering, bounded depth, stall/overlap
     accounting, drain-on-error;
  2. byte equality — pipelined blockwise/engine compaction must be
     byte-identical to serial on every backend, under adversarial inputs
     (duplicate keys straddling range boundaries, TTL/tombstones at
     range edges, degenerate single-repeated-key distributions);
  3. the acceptance demonstration — with fail-point-delayed stages, the
     pipelined wall time undercuts the sum of its own serial stage
     times, and the `compact.pipeline.*` counters land in /metrics.
"""

import os
import time

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.ops.compact import CompactOptions, compact_blocks, sort_block
from pegasus_tpu.ops.pipeline import CompactPipeline, pipeline_depth, submit
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.perf_counters import counters
from pegasus_tpu.runtime.tracing import COMPACT_TRACER
from tests.test_compact_ops import _adversarial_records, make_block


def _assert_blocks_byte_equal(a, b):
    assert a.n == b.n
    np.testing.assert_array_equal(a.key_arena, b.key_arena)
    np.testing.assert_array_equal(a.val_arena, b.val_arena)
    np.testing.assert_array_equal(a.expire_ts, b.expire_ts)
    np.testing.assert_array_equal(a.deleted, b.deleted)


# ------------------------------------------------------------ executor


def test_depth_env_knob(monkeypatch):
    monkeypatch.delenv("PEGASUS_COMPACT_PIPELINE_DEPTH", raising=False)
    assert pipeline_depth() == 2
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "4")
    assert pipeline_depth() == 4
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "0")
    assert pipeline_depth() == 1  # floored: 0/negative = serial
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "junk")
    assert pipeline_depth() == 2


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_map_preserves_item_order(depth):
    items = list(range(7))
    log = []

    def prefetch(x):
        return x * 10

    def dispatch(i, p):
        log.append(i)
        return p + i

    def finish(i, d):
        return d + 1

    out = CompactPipeline(depth=depth).map(items, prefetch, dispatch, finish)
    assert out == [x * 10 + i + 1 for i, x in enumerate(items)]
    assert log == list(range(7))  # dispatch strictly in item order


def test_map_without_finish_returns_dispatch_results():
    out = CompactPipeline(depth=2).map([3, 4], lambda x: x, lambda i, p: p * p)
    assert out == [9, 16]


def test_dispatch_error_drains_and_raises():
    def dispatch(i, p):
        if i == 1:
            raise RuntimeError("device died")
        return p

    pipe = CompactPipeline(depth=2)
    with pytest.raises(RuntimeError, match="device died"):
        pipe.map(list(range(4)), lambda x: x, dispatch, lambda i, d: d)
    assert pipe.drains == 1


def test_prefetch_error_surfaces_on_its_item():
    def prefetch(x):
        if x == 2:
            raise ValueError("bad pack")
        return x

    with pytest.raises(ValueError, match="bad pack"):
        CompactPipeline(depth=2).map(list(range(4)), prefetch,
                                     lambda i, p: p)


def test_overlap_and_stall_accounting():
    """Sleeping stages on disjoint resources: the pipeline's wall time
    must undercut the serial stage sum, and the overlap/stall numbers
    must reflect it."""
    n = 4

    def prefetch(x):
        time.sleep(0.05)
        return x

    def dispatch(i, p):
        time.sleep(0.05)
        return p

    pipe = CompactPipeline(depth=2)
    t0 = time.perf_counter()
    pipe.map(list(range(n)), prefetch, dispatch)
    wall = time.perf_counter() - t0
    serial_sum = n * 0.1
    assert wall < serial_sum * 0.9, (wall, serial_sum)
    assert pipe.overlap_s > 0.0
    # the first prefetch is always a stall (nothing to overlap it with)
    assert pipe.stall_s >= 0.04


def test_prefetch_timeout_dispatches_marker_not_hang():
    """A guard-less caller (batched compaction) bounds prefetch pickup:
    a wedged worker is abandoned at the timeout and dispatch receives a
    TimeoutError marker so it can redo the work inline."""
    release = __import__("threading").Event()
    seen = []

    def prefetch(x):
        if x == 1:
            release.wait(10)  # wedged worker
        return x

    def dispatch(i, p):
        seen.append(type(p).__name__)
        return p

    try:
        t0 = time.perf_counter()
        out = CompactPipeline(depth=2, prefetch_timeout_s=0.2).map(
            [0, 1, 2], prefetch, dispatch)
        assert time.perf_counter() - t0 < 5.0
        assert seen == ["int", "TimeoutError", "int"]
        assert isinstance(out[1], TimeoutError)
    finally:
        release.set()


def test_submit_adopts_and_restores_trace_sessions():
    """A pool worker must aggregate its spans into the SUBMITTER's
    sessions for the task, then restore — reused workers must not keep
    feeding a closed session."""
    with COMPACT_TRACER.session() as sess:
        fut = submit(lambda: COMPACT_TRACER.event("t_submit_probe", 0.001))
        fut.result()
    assert "t_submit_probe" in sess.stages
    with COMPACT_TRACER.session() as sess2:
        submit(lambda: None).result()  # same worker, new task, no adoption
    assert "t_submit_probe" not in sess2.stages


# --------------------------------------------- blockwise byte equality


def _boundary_straddle_runs(rng, n_runs=3, n=500):
    """Adversarial blockwise inputs: heavy duplicate keys shared across
    runs (so every range boundary straddles versions of the same key),
    TTL-expired and tombstoned records clustered at the key-space edges,
    plus the generic adversarial key shapes."""
    runs = []
    for r in range(n_runs):
        recs = []
        for i in range(n):
            bucket = int(rng.integers(0, 40))  # few hashkeys => many dups
            hk = b"dup%04d" % bucket
            sk = b"s%02d" % int(rng.integers(0, 6))
            expire = int(rng.integers(0, 200)) if bucket % 3 == 0 else 0
            deleted = bucket in (0, 39) and bool(rng.random() < 0.5)
            recs.append((hk, sk, b"" if deleted else b"r%dv%d" % (r, i),
                         expire, deleted))
        recs += _adversarial_records(rng, 60)
        runs.append(sort_block(make_block(recs),
                               CompactOptions(backend="cpu")))
    return runs


@pytest.mark.parametrize("seed", [0, 1])
def test_pipelined_blockwise_byte_equal_serial_and_cpu(seed, monkeypatch):
    """Acceptance: pipelined blockwise output is byte-equal both to the
    serial (depth=1) blockwise run and to the whole-merge cpu result, on
    boundary-straddling duplicates and TTL/tombstone edge records."""
    from dataclasses import replace

    rng = np.random.default_rng(seed)
    runs = _boundary_straddle_runs(rng)
    base = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    cpu_whole = compact_blocks(runs, replace(base, backend="cpu"))
    for budget in (300, 700):
        split = replace(base, max_device_records=budget)
        monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "1")
        serial = compact_blocks(runs, split)
        monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
        pipelined = compact_blocks(runs, split)
        _assert_blocks_byte_equal(serial.block, pipelined.block)
        _assert_blocks_byte_equal(cpu_whole.block, pipelined.block)
        assert pipelined.stats == serial.stats


def test_degenerate_repeated_keys_terminate_under_pipeline(monkeypatch):
    """Non-shrinking-range guard under the pipeline: ranges dominated by
    a single repeated key (cannot shrink below the budget) route through
    the direct path and terminate, byte-equal to cpu."""
    from dataclasses import replace

    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
    # two hot keys, each repeated far beyond the budget -> every range is
    # degenerate; plus a cold tail so multiple ranges exist at all
    hot = [(b"hotA", b"s", b"v%d" % i, 0, False) for i in range(120)] \
        + [(b"hotB", b"s", b"w%d" % i, 0, False) for i in range(120)]
    cold = [(b"z%03d" % i, b"s", b"c%d" % i, 0, False) for i in range(40)]
    one = sort_block(make_block(hot + cold), CompactOptions(backend="cpu"))
    runs = [one, one]
    base = CompactOptions(backend="tpu", now=50, runs_sorted=True)
    want = compact_blocks(runs, replace(base, backend="cpu"))
    got = compact_blocks(runs, replace(base, max_device_records=50))
    _assert_blocks_byte_equal(want.block, got.block)


def test_single_repeated_key_still_terminates(monkeypatch):
    """The pure degenerate distribution (ranges can never shrink at all)
    must terminate and dedup to one survivor, as before the pipeline."""
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
    one = sort_block(make_block([(b"k", b"s", b"v%d" % i, 0, False)
                                 for i in range(50)]),
                     CompactOptions(backend="cpu"))
    res = compact_blocks([one, one], CompactOptions(
        backend="tpu", now=50, runs_sorted=True, max_device_records=10))
    assert res.block.n == 1


# ------------------------------------------------ overlap demonstration


def test_failpoint_delayed_stages_demonstrate_overlap(monkeypatch):
    """Acceptance: with deterministic fail-point delays on the pack and
    device stages, the pipelined wall time of a multi-range compaction is
    LESS than the sum of its own serial stage times — and the per-range
    overlap surfaces in the trace session, the ring buffer
    (/compact/trace's source) and the counter registry."""
    from dataclasses import replace

    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
    rng = np.random.default_rng(7)
    runs = _boundary_straddle_runs(rng, n_runs=2, n=120)
    total = sum(b.n for b in runs)
    opts = CompactOptions(backend="tpu", now=100, runs_sorted=True,
                          max_device_records=max(64, total // 3))
    want = compact_blocks(runs, replace(opts, backend="cpu",
                                        max_device_records=1 << 40))
    # warmup: identical shapes once, so jit compiles are cached and the
    # measured run's stage times are dominated by the injected delays
    compact_blocks(runs, opts)
    fp.setup()
    try:
        fp.cfg("compact.pack", "sleep(150)")
        fp.cfg("compact.device", "sleep(150)")
        with COMPACT_TRACER.session() as sess:
            t0 = time.perf_counter()
            got = compact_blocks(runs, opts)
            wall = time.perf_counter() - t0
    finally:
        fp.teardown()
    _assert_blocks_byte_equal(want.block, got.block)
    # serial sum: every stage second this compaction actually spent
    # (pack+h2d on workers, device in the lane thread, gather on workers)
    stage_sum = sum(v["s"] for k, v in sess.stages.items()
                    if k in ("pack", "h2d", "device", "gather"))
    assert wall < stage_sum * 0.9, (wall, sess.summary())
    assert "pipeline.overlap" in sess.stages
    assert sess.stages["pipeline.overlap"]["s"] > 0.05
    assert counters.percentile(
        "compact.pipeline.overlap_us").percentile(0.99) > 50_000
    ring_stages = {r["stage"] for r in COMPACT_TRACER.trace(200)}
    assert "pipeline.overlap" in ring_stages


def test_pipeline_counters_reach_metrics_surface():
    """compact.pipeline.* appears on the Prometheus /metrics rendering
    after any pipelined run (the counters live in the one process-wide
    registry every surface reads)."""
    CompactPipeline(depth=2).map([1, 2, 3], lambda x: x, lambda i, p: p,
                                 lambda i, d: d)
    from pegasus_tpu.collector.reporter import prometheus_text

    text = prometheus_text()
    for name in ("compact_pipeline_depth", "compact_pipeline_prefetch_count",
                 "compact_pipeline_overlap_us", "compact_pipeline_stall_us"):
        assert name in text, name


# ------------------------------------------------- engine byte equality


def _filled_engine(path, backend):
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    eng = LsmEngine(path, EngineOptions(
        backend=backend, memtable_bytes=16 << 10, l0_compaction_trigger=2,
        target_file_size_bytes=24 << 10, level_base_bytes=48 << 10,
        level_size_ratio=4, max_levels=3))
    rng = np.random.default_rng(3)
    for i in range(2500):
        eng.put(generate_key(b"hk%04d" % rng.integers(0, 500), b"s%d" % i),
                SCHEMAS[2].generate_value(
                    int(rng.integers(0, 60)) if i % 9 == 0 else 0, 0,
                    b"v%d" % i))
        if i % 23 == 0:
            eng.delete(generate_key(b"hk%04d" % rng.integers(0, 500), b"sX"))
    eng.flush()
    eng.compact(now=100)
    return eng


def _engine_digest(eng):
    import hashlib

    h = hashlib.sha256()
    for k, v, e in eng.scan(now=100):
        h.update(k)
        h.update(v)
        h.update(str(e).encode())
    return h.hexdigest()


def test_engine_pipelined_installs_byte_equal_serial(tmp_path, monkeypatch):
    """Acceptance: deferred (pipelined) engine installs serve and persist
    the same data as serial installs, on both backends — including after
    a reopen from disk (manifest settled by the drain)."""
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    digests = {}
    for backend in ("cpu", "tpu"):
        for depth in ("1", "2"):
            monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", depth)
            eng = _filled_engine(str(tmp_path / f"{backend}{depth}"), backend)
            digests[(backend, depth)] = _engine_digest(eng)
            # on-disk state is settled: every level file exists
            for s in eng._all_ssts_locked():
                assert os.path.exists(s.path), s.path
                assert s._on_disk
            eng.close()
    assert len(set(digests.values())) == 1, digests
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
    reopened = LsmEngine(str(tmp_path / "tpu2"), EngineOptions(backend="cpu"))
    assert _engine_digest(reopened) == digests[("tpu", "2")]
    reopened.close()


def test_device_budget_accounting_balanced(tmp_path):
    """The HBM budget must never under- or over-count across the
    async-prime/release races: releasing an unbudgeted run subtracts
    nothing, a retired file never primes, and prime->release round-trips
    return the budget exactly to its starting point."""
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(
        backend="tpu", memtable_bytes=1 << 20))
    for i in range(100):
        eng.put(generate_key(b"h%02d" % (i % 7), b"s%03d" % i),
                SCHEMAS[2].generate_value(0, 0, b"v%d" % i))
    eng.flush()
    sst = eng._l0[0]
    # settle any in-flight async prime, then measure from a known state
    deadline = time.time() + 10
    while True:
        with eng._lock:
            if not sst._prime_inflight:
                break
        assert time.time() < deadline
        time.sleep(0.01)
    eng._release_device_run(sst)
    base = eng._device_cache_used
    # releasing again (unbudgeted, already retired) subtracts nothing
    eng._release_device_run(sst)
    assert eng._device_cache_used == base
    # a retired file never primes (late async prime loses the race)
    assert eng._device_run_budgeted(sst) is None
    assert eng._device_cache_used == base
    # a fresh file's prime -> release round-trips the budget exactly
    sst2 = None
    for s in eng._l0:
        if not s._device_retired:
            sst2 = s
            break
    if sst2 is not None:
        dr = eng._device_run_budgeted(sst2)
        if dr is not None:
            assert eng._device_cache_used == base + dr.nbytes()
        eng._release_device_run(sst2)
        assert eng._device_cache_used == base
    eng.close()


def test_deferred_install_failure_recovers_pre_merge_state(tmp_path,
                                                          monkeypatch):
    """A deferred install whose write_sst dies must keep the durability
    invariant: the old manifest + input files stay on disk until the
    drain's repair pass lands the outputs; the engine keeps serving the
    merged view from memory throughout."""
    monkeypatch.setenv("PEGASUS_COMPACT_PIPELINE_DEPTH", "2")
    fp.setup()
    try:
        eng = _filled_engine(str(tmp_path / "db"), "cpu")
        # a one-shot failure in the next pool-side install job (the
        # compact.pipeline point fires in every pipeline-pool task): the
        # worker dies before writing, the drain repairs synchronously
        fp.cfg("compact.pipeline", "1*raise(injected install failure)")
        rng = np.random.default_rng(9)
        for i in range(2500):
            eng.put(generate_key(b"qk%04d" % rng.integers(0, 300),
                                 b"s%d" % i),
                    SCHEMAS[2].generate_value(0, 0, b"w%d" % i))
        eng.flush()
        eng.compact(now=100)
        for s in eng._all_ssts_locked():
            assert os.path.exists(s.path) and s._on_disk
        digest = _engine_digest(eng)
        eng.close()
        from pegasus_tpu.engine import EngineOptions, LsmEngine

        reopened = LsmEngine(str(tmp_path / "db"),
                             EngineOptions(backend="cpu"))
        assert _engine_digest(reopened) == digest
        reopened.close()
    finally:
        fp.teardown()
