"""Concurrency lint plane (ISSUE 9): tools/analyze + runtime/lockrank.

Every checker is proven against a SEEDED defect (a synthetic module it
must flag) and a clean twin it must pass — a lint that cannot catch its
own bug class is decoration. Plus: the repo-clean gate that wires the
whole plane into tier-1, the AB/BA lock-order detection (no unlucky
interleaving needed: the graph persists across threads), and the
grouped-onebox write workload under PEGASUS_LOCKRANK=1 proving the real
serving stack is cycle-free.
"""

import json
import os
import textwrap
import threading

import pytest

from tools.analyze import Repo, load_baseline, run_all, run_pass


# ---------------------------------------------------------------- helpers

def make_repo(tmp_path, modules: dict, readme: str = "") -> Repo:
    """A throwaway repo shaped like this one: modules land under
    pegasus_tpu/, README.md beside them."""
    (tmp_path / "pegasus_tpu").mkdir(exist_ok=True)
    for rel, src in modules.items():
        p = tmp_path / "pegasus_tpu" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return Repo(tmp_path)


# ------------------------------------------------------- lock_discipline

GUARDED_BAD = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._files = []  #: guarded_by self._lock

        def good(self):
            with self._lock:
                self._files.append(1)

        def bad(self):
            self._files.append(2)
"""


def test_lock_discipline_flags_guarded_write_outside_lock(tmp_path):
    repo = make_repo(tmp_path, {"m.py": GUARDED_BAD})
    findings = run_pass("lock_discipline", repo)
    assert len(findings) == 1
    f = findings[0]
    assert "Engine.bad" in f.message and "self._files" in f.message
    assert f.file == "pegasus_tpu/m.py"
    # the clean method produced nothing, and the key is line-stable
    assert "bad" in f.key and str(f.line) not in f.key


def test_lock_discipline_requires_and_escapes(tmp_path):
    repo = make_repo(tmp_path, {"m.py": """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self._n = 0  #: guarded_by self._lock

        def locked_helper(self):  #: requires self._lock
            self._n += 1

        def via_condition(self):
            with self._cv:
                self._n += 1

        def reasoned_escape(self):
            return self._n  #: unguarded_ok racy gauge read

        def reasonless_escape(self):
            return self._n  #: unguarded_ok

        def closure_leak(self):
            with self._lock:
                def later():
                    self._n += 1
                return later
    """})
    findings = run_pass("lock_discipline", repo)
    msgs = [f.message for f in findings]
    # requires + condition alias + reasoned escape are all clean
    assert not any("locked_helper" in m or "via_condition" in m
                   or "reasoned_escape" in m for m in msgs)
    # an EMPTY unguarded_ok reason does not suppress
    assert any("reasonless_escape" in m for m in msgs)
    # a closure born under the lock runs AFTER it: inherits nothing
    assert any("closure_leak" in m for m in msgs)


def test_lock_discipline_module_level_guard(tmp_path):
    repo = make_repo(tmp_path, {"m.py": """
    import threading

    _POOL_LOCK = threading.Lock()
    _POOL = None  #: guarded_by _POOL_LOCK

    def good():
        global _POOL
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = object()
            return _POOL

    def bad():
        return _POOL
    """})
    findings = run_pass("lock_discipline", repo)
    assert len(findings) == 1 and "bad" in findings[0].message


# ------------------------------------------------------ thread_lifecycle

def test_thread_lifecycle_flags_raw_spawn(tmp_path):
    repo = make_repo(tmp_path, {"m.py": """
    import threading
    from concurrent.futures import ThreadPoolExecutor

    def raw():
        threading.Thread(target=print, daemon=True).start()
        return ThreadPoolExecutor(2)

    def escaped():
        return threading.Thread(target=print)  #: untracked_ok test fixture thread joined by its caller

    class Factory(threading.Thread):
        pass
    """})
    findings = run_pass("thread_lifecycle", repo)
    msgs = [f.message for f in findings]
    assert sum("raw" in m for m in msgs) == 2  # Thread + executor
    assert not any("escaped" in m for m in msgs)
    assert any("Factory" in m and "subclasses" in m for m in msgs)


def test_spawn_helpers_register_in_tracked_registry():
    from pegasus_tpu.runtime.tasking import (TRACKED, spawn_thread,
                                             tracked_executor)

    ev = threading.Event()
    t = spawn_thread(ev.wait, 5.0, name="tracked-test")
    ex = tracked_executor(1, thread_name_prefix="tracked-test")
    try:
        assert t in TRACKED.live_threads()
        assert ex in TRACKED.live_executors()
    finally:
        ev.set()
        t.join(5)
        ex.shutdown(wait=False)


# ------------------------------------------------------------- env_knobs

KNOB_README = """
    ### Configuration-knob table

    | Knob | Default | Effect |
    |---|---|---|
    | `PEGASUS_DOCUMENTED` | 1 | a knob both read and documented |
    | `PEGASUS_GHOST` | 0 | a knob nothing reads any more |
"""


def test_env_knobs_both_directions(tmp_path):
    repo = make_repo(tmp_path, {"m.py": """
    import os

    def knobs():
        a = os.environ.get("PEGASUS_DOCUMENTED", "1")
        b = os.environ.get("PEGASUS_UNREGISTERED", "0")
        return a, b
    """}, readme=KNOB_README)
    keys = {f.key for f in run_pass("env_knobs", repo)}
    assert keys == {"undoc:PEGASUS_UNREGISTERED", "stale-row:PEGASUS_GHOST"}


def test_env_knobs_expands_prefix_families(tmp_path):
    repo = make_repo(tmp_path, {"m.py": """
    import os

    def _env_float(name, default):
        return float(os.environ.get(name, default))

    class Cfg:
        @classmethod
        def from_env(cls, env_prefix="PEGASUS_ALPHA"):
            return _env_float(f"{env_prefix}_TIMEOUT_S", 1.0)

    CFG_B = Cfg.from_env("PEGASUS_BETA")
    """}, readme=KNOB_README)
    from tools.analyze.env_knobs import source_knobs

    knobs = source_knobs(repo)
    assert {"PEGASUS_ALPHA_TIMEOUT_S", "PEGASUS_BETA_TIMEOUT_S"} <= knobs


def test_env_knobs_ignores_docstring_mentions(tmp_path):
    repo = make_repo(tmp_path, {"m.py": '''
    """Docs may mention PEGASUS_FANTASY freely — docs are not reads."""

    def nothing():
        return 0
    '''}, readme=KNOB_README)
    keys = {f.key for f in run_pass("env_knobs", repo)}
    assert "undoc:PEGASUS_FANTASY" not in keys
    # both table rows are now stale (nothing reads them)
    assert "stale-row:PEGASUS_DOCUMENTED" in keys


# ---------------------------------------------------------------- events

EVENTS_OK = """
    from pegasus_tpu.runtime import events

    def trip():
        events.emit("lane.breaker_trip", severity="error", lane="compact")
"""

EVENT_README = """
    ### Event table

    | event | severity | transition it records |
    |---|---|---|
    | `lane.breaker_trip` | error | a breaker opened |
"""


def test_events_pass_clean_twin(tmp_path):
    repo = make_repo(tmp_path, {"m.py": EVENTS_OK}, readme=EVENT_README)
    assert run_pass("events", repo) == []


def test_events_pass_both_directions(tmp_path):
    # an emit with no table row, and a table row with no emit
    repo = make_repo(tmp_path, {"m.py": EVENTS_OK + """
    def ghost():
        events.emit("ghost.event", why="undocumented")
    """}, readme=EVENT_README + """
    | `stale.event` | info | deleted emitter, row kept |
    """)
    keys = {f.key for f in run_pass("events", repo)}
    assert "undoc:ghost.event" in keys
    assert "stale-row:stale.event" in keys
    assert not any(k.startswith(("undoc:lane.", "stale-row:lane."))
                   for k in keys)


def test_events_pass_requires_table(tmp_path):
    repo = make_repo(tmp_path, {"m.py": EVENTS_OK}, readme="# nothing")
    assert [f.key for f in run_pass("events", repo)] == ["no-table"]


def test_events_pass_flags_nonliteral_names(tmp_path):
    """A dynamic event name is invisible to the lint and to anyone
    grepping an incident artifact — flagged even if it happens to land
    on a documented name at runtime."""
    repo = make_repo(tmp_path, {"m.py": EVENTS_OK + """
    def dynamic(kind):
        events.emit(f"lane.{kind}", lane="compact")

    def indirect(name):
        events.emit(name, lane="compact")
    """}, readme=EVENT_README)
    nonlit = [f for f in run_pass("events", repo)
              if f.key.startswith("nonliteral:")]
    assert len(nonlit) == 2
    assert all("plain string literal" in f.message for f in nonlit)


# ------------------------------------------------------------ span_names

SPANS_OK = """
    from pegasus_tpu.runtime.job_trace import JOB_TRACER
    from pegasus_tpu.runtime.tracing import COMPACT_TRACER

    def work(job):
        with COMPACT_TRACER.span("pack", records=1):
            pass
        with JOB_TRACER.hop("engine.merge", where="local"):
            JOB_TRACER.note("sched.decide", gpid="1.0")
        self._trace(job, "offload.svc.merge", ms=3)
"""

SPAN_README = """
    ### Span-name table

    | span / hop | tracer | what it times |
    |---|---|---|
    | `pack` | stage | columnarization |
    | `engine.merge` / `sched.decide` | job | merge hop; the minting decision |
    | `offload.svc.merge` | job (service-side) | the remote merge |
"""


def test_span_names_pass_clean_twin(tmp_path):
    repo = make_repo(tmp_path, {"m.py": SPANS_OK}, readme=SPAN_README)
    assert run_pass("span_names", repo) == []


def test_span_names_pass_both_directions(tmp_path):
    repo = make_repo(tmp_path, {"m.py": SPANS_OK + """
    def ghost():
        with JOB_TRACER.hop("ghost.hop"):
            pass
    """}, readme=SPAN_README + """
    | `stale.span` | stage | deleted call site, row kept |
    """)
    keys = {f.key for f in run_pass("span_names", repo)}
    assert "undoc:ghost.hop" in keys
    assert "stale-row:stale.span" in keys
    assert not any(k.endswith((":pack", ":engine.merge", ":sched.decide",
                               ":offload.svc.merge")) for k in keys)


def test_span_names_pass_requires_table(tmp_path):
    repo = make_repo(tmp_path, {"m.py": SPANS_OK}, readme="# nothing")
    assert [f.key for f in run_pass("span_names", repo)] == ["no-table"]


def test_span_names_pass_exempts_dynamic_names(tmp_path):
    """Unlike event names, span names are legitimately parameterized
    (client.<op>, rpc.<code>, the <kind>.nested degradation hop) —
    dynamic call sites are exempt, never flagged."""
    repo = make_repo(tmp_path, {"m.py": SPANS_OK + """
    def dynamic(op, kind):
        with COMPACT_TRACER.span(f"client.{op}"):
            pass
        with JOB_TRACER.hop(f"{kind}.nested"):
            pass
    """}, readme=SPAN_README)
    assert run_pass("span_names", repo) == []


# -------------------------------------------------------------- lockrank

def _graph():
    from pegasus_tpu.runtime import lockrank

    return lockrank._Graph()


def test_lockrank_detects_ab_ba_cycle(monkeypatch):
    """The classic inversion, WITHOUT needing the unlucky interleaving:
    the graph is process-wide and persists, so sequential A->B then
    B->A (even on one thread) is caught and names both sites."""
    monkeypatch.setenv("PEGASUS_LOCKRANK", "1")
    from pegasus_tpu.runtime import lockrank

    g = _graph()
    a = lockrank.named_lock("t.A", _graph=g)
    b = lockrank.named_rlock("t.B", _graph=g)
    with a:
        with b:
            pass
    assert g.snapshot()["violations"] == []
    with b:
        with a:
            pass
    (v,) = g.snapshot()["violations"]
    assert v["cycle"] == ["t.A", "t.B", "t.A"]
    assert "test_analyze.py" in v["acquire_site"]
    assert "test_analyze.py" in v["reverse_edge"]["acquire_site"]
    # reported once per edge pair, not per occurrence
    with b:
        with a:
            pass
    assert len(g.snapshot()["violations"]) == 1


def test_lockrank_longer_cycle_and_condition_wait(monkeypatch):
    monkeypatch.setenv("PEGASUS_LOCKRANK", "1")
    from pegasus_tpu.runtime import lockrank

    g = _graph()
    a = lockrank.named_lock("c.a", _graph=g)
    b = lockrank.named_lock("c.b", _graph=g)
    c = lockrank.named_lock("c.c", _graph=g)
    for first, second in ((a, b), (b, c), (c, a)):
        with first:
            with second:
                pass
    (v,) = g.snapshot()["violations"]
    assert v["cycle"] == ["c.a", "c.b", "c.c", "c.a"]

    # Condition.wait releases the lock: a waiter holding the condition
    # must NOT generate held-while-acquiring edges for locks the waker
    # takes, and the held-stack drains clean
    g2 = _graph()
    cv = lockrank.named_condition("c.cv", _graph=g2)
    other = lockrank.named_lock("c.other", _graph=g2)
    woke = threading.Event()

    def waiter():
        with cv:
            cv.wait(5.0)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    with other:
        with cv:
            cv.notify_all()
    t.join(5)
    assert woke.is_set()
    assert g2.snapshot()["violations"] == []
    assert lockrank._held() == []


def test_lockrank_disabled_returns_raw_primitives(monkeypatch):
    monkeypatch.setenv("PEGASUS_LOCKRANK", "0")
    from pegasus_tpu.runtime import lockrank

    assert type(lockrank.named_lock("x")) is type(threading.Lock())
    cv = lockrank.named_condition("x")
    assert isinstance(cv, threading.Condition)


def test_lockrank_raise_mode(monkeypatch):
    monkeypatch.setenv("PEGASUS_LOCKRANK", "raise")
    from pegasus_tpu.runtime import lockrank

    g = _graph()
    a = lockrank.named_lock("r.a", _graph=g)
    b = lockrank.named_lock("r.b", _graph=g)
    with a:
        with b:
            pass
    with pytest.raises(lockrank.LockOrderError):
        with b:
            with a:
                pass
    # the failed acquire still HOLDS b+a; reset this thread's stack so
    # the shared per-thread state can't leak into later tests
    lockrank._held().clear()


def test_lockrank_grouped_onebox_write_workload(tmp_path):
    """Acceptance: a grouped-onebox write workload (parent router +
    group-worker subprocesses, all under the session's
    PEGASUS_LOCKRANK=1) records ZERO lock-order cycles — in this
    process' graph and in the shared violation file the workers
    inherit."""
    from pegasus_tpu.runtime import lockrank
    from tests.test_satellites import MiniCluster

    assert lockrank.enabled(), "conftest must arm PEGASUS_LOCKRANK"
    sink = os.environ["PEGASUS_LOCKRANK_FILE"]

    def sink_lines():
        try:
            with open(sink) as f:
                return [line for line in f if line.strip()]
        except OSError:
            return []

    before_g = len(lockrank.GRAPH.violations)
    before_f = len(sink_lines())
    c = MiniCluster(tmp_path, n_nodes=2, serve_groups=2)
    try:
        cli = c.create("lockrank_t", partitions=4, replicas=2)
        try:
            for i in range(120):
                cli.set(b"lk%d" % i, b"s", b"v%d" % i)
            for i in range(0, 120, 3):
                cli.delete(b"lk%d" % i, b"s")
            for i in range(1, 120, 3):
                assert cli.get(b"lk%d" % i, b"s") == b"v%d" % i
        finally:
            cli.close()
    finally:
        c.stop()
    assert len(lockrank.GRAPH.violations) == before_g, \
        lockrank.GRAPH.violations[before_g:]
    assert len(sink_lines()) == before_f, sink_lines()[before_f:]


# ------------------------------------------- lock-discipline fix regress

def test_set_read_residency_holds_engine_lock(tmp_path):
    """Regression for the unlocked _read_hot flip the lock-discipline
    pass caught (now written under the engine lock), AND for the review
    bug the fix briefly introduced: a duplicated nested prime loop that
    submitted N + N*N prime jobs for N SSTs. With a tpu backend the pin
    must submit EXACTLY one prime per current SST."""
    from pegasus_tpu.engine.db import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "e"), EngineOptions(backend="cpu"))
    try:
        eng.set_read_residency(True)
        assert eng.stats()["read_hot"] is True
        eng.set_read_residency(False)
        assert eng.stats()["read_hot"] is False
    finally:
        eng.close()

    eng = LsmEngine(str(tmp_path / "t"),
                    EngineOptions(backend="tpu", memtable_bytes=1))
    try:
        for i in range(3):
            eng.put(b"k%d" % i, b"v")
            eng.flush()
        n_ssts = eng.stats()["l0_files"] + sum(
            eng.stats()["level_files"].values())
        assert n_ssts >= 2
        primed = []
        eng._prime_async = primed.append
        eng.set_read_residency(True)
        assert len(primed) == n_ssts, "one prime submission per SST"
    finally:
        eng._prime_async = lambda sst: None  # close() must not re-prime
        eng.close()


def test_flush_trigger_compacts_outside_flush_lock(tmp_path):
    """Regression for the lock-order cycle lockrank caught on the LIVE
    suite: the L0 compaction trigger used to run under the flush lock
    (flush->compaction), while batched_manual_compact flushes engine
    i+1 holding engine i's compaction lock (compaction->flush) — a
    deadlock waiting for the interleaving. The trigger now fires after
    the flush lock is released: exercising the exact path must leave NO
    flush->compaction edge in the process-wide graph."""
    from pegasus_tpu.engine.db import EngineOptions, LsmEngine
    from pegasus_tpu.runtime import lockrank

    assert lockrank.enabled()
    eng = LsmEngine(str(tmp_path / "e"),
                    EngineOptions(backend="cpu", l0_compaction_trigger=1,
                                  memtable_bytes=1))
    try:
        for i in range(3):
            eng.put(b"k%d" % i, b"v")  # rotate -> drain -> trigger
        eng.flush()
    finally:
        eng.close()
    with lockrank.GRAPH._mu:
        assert "engine.compaction" not in \
            lockrank.GRAPH.edges.get("engine.flush", {})


def test_manual_compact_finish_time_written_under_lock(tmp_path):
    """Regression for the unlocked _meta write in manual_compact: the
    finish timestamp still lands (and the manifest persists it) with the
    write now inside the engine lock."""
    from pegasus_tpu.engine.db import (META_LAST_MANUAL_COMPACT_FINISH_TIME,
                                       EngineOptions, LsmEngine)

    eng = LsmEngine(str(tmp_path / "e"), EngineOptions(backend="cpu"))
    try:
        eng.put(b"k1", b"v1")
        eng.manual_compact()
        ts = int(eng.meta_store[META_LAST_MANUAL_COMPACT_FINISH_TIME])
        assert ts > 0
    finally:
        eng.close()


# ------------------------------------------------------------ the runner

def test_runner_baseline_semantics(tmp_path):
    repo = make_repo(tmp_path, {"m.py": GUARDED_BAD})
    # no baseline: the seeded finding fails the run
    r = run_all(repo, passes=["lock_discipline"], baseline={})
    assert not r.clean and len(r.findings) == 1
    key = r.findings[0].key
    # baselined: tracked as grandfathered, run is clean
    r = run_all(repo, passes=["lock_discipline"],
                baseline={"lock_discipline": {key}})
    assert r.clean and len(r.grandfathered) == 1 and not r.findings
    # stale entry (finding gone, entry kept) fails — debt must shrink
    r = run_all(repo, passes=["lock_discipline"],
                baseline={"lock_discipline": {key, "ghost:key"}})
    assert not r.clean
    assert ("lock_discipline", "ghost:key") in r.stale_baseline


def test_analyze_cli_json():
    import subprocess
    import sys
    from pathlib import Path

    repo_root = Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--json",
         "--pass", "lock_discipline", "--pass", "thread_lifecycle"],
        capture_output=True, text=True, timeout=120, cwd=repo_root)
    doc = json.loads(proc.stdout)
    assert doc["clean"] is True and proc.returncode == 0
    assert set(doc["passes"]) == {"lock_discipline", "thread_lifecycle"}


def test_repo_clean():
    """THE tier-1 gate: every pass of the static-analysis plane is clean
    against this repository, modulo the committed baseline (which must
    itself be exact — stale entries fail). A new unguarded access, raw
    thread spawn, undocumented knob/counter/command/fail-point, or
    deleted-but-still-documented surface fails tier-1 here."""
    report = run_all(Repo(), baseline=load_baseline())
    lines = [f.render() for f in report.findings] + [
        f"STALE baseline: {p}:{k}" for p, k in report.stale_baseline]
    assert report.clean, "\n".join(lines)
    assert set(report.ran) == {"env_knobs", "events", "fail_points",
                               "lock_discipline", "metric_names",
                               "remote_commands", "span_names",
                               "thread_lifecycle"}
