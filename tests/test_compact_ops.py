"""Compaction kernel tests: semantics + cpu/tpu differential (bit-stability).

The tpu backend runs on the test harness's virtual CPU devices; semantics and
output bytes must match the numpy cpu backend exactly (SURVEY.md §7d).
"""

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.engine.block import KVBlock
from pegasus_tpu.ops import CompactOptions, compact_blocks, sort_block
from pegasus_tpu.ops.packing import compute_suffix_ranks, pack_key_prefixes


def make_block(records):
    """records: (hash_key, sort_key, payload, expire, deleted)"""
    rows = []
    for hk, sk, payload, expire, deleted in records:
        key = generate_key(hk, sk)
        val = b"" if deleted else SCHEMAS[2].generate_value(expire, 0, payload)
        rows.append((key, val, expire, deleted))
    return KVBlock.from_records(rows)


def keys_of(block):
    return list(block.keys())


def test_sort_block_orders_by_key_bytes():
    recs = [(f"hk{i%7}".encode(), f"sk{i:03d}".encode(), b"v", 0, False) for i in range(50)]
    np.random.default_rng(1).shuffle(recs)
    out = sort_block(make_block(recs), CompactOptions(backend="cpu"))
    ks = keys_of(out)
    assert ks == sorted(ks)
    assert out.n == 50


def test_dedup_newest_run_wins():
    newest = make_block([(b"h", b"s", b"NEW", 0, False)])
    oldest = make_block([(b"h", b"s", b"OLD", 0, False), (b"h", b"t", b"KEEP", 0, False)])
    res = compact_blocks([newest, oldest], CompactOptions(backend="cpu", now=100))
    assert res.block.n == 2
    vals = [res.block.value(i) for i in range(2)]
    assert SCHEMAS[2].extract_user_data(vals[0]) == b"NEW"
    assert SCHEMAS[2].extract_user_data(vals[1]) == b"KEEP"


def test_ttl_expiry_dropped_only_when_filtering():
    blk = make_block([
        (b"h", b"alive", b"v", 1000, False),
        (b"h", b"dead", b"v", 50, False),
        (b"h", b"nottl", b"v", 0, False),
    ])
    res = compact_blocks([blk], CompactOptions(backend="cpu", now=100))
    assert {k for k in (generate_key(b"h", s) for s in (b"alive", b"nottl"))} == set(keys_of(res.block))
    # flush path keeps expired records
    out = sort_block(blk, CompactOptions(backend="cpu", now=100))
    assert out.n == 3


def test_tombstones_dropped_only_at_bottommost():
    newest = make_block([(b"h", b"s", b"", 0, True)])  # delete marker
    oldest = make_block([(b"h", b"s", b"OLD", 0, False)])
    bottom = compact_blocks([newest, oldest], CompactOptions(backend="cpu", now=1, bottommost=True))
    assert bottom.block.n == 0  # tombstone consumed the old version and itself
    mid = compact_blocks([newest, oldest], CompactOptions(backend="cpu", now=1, bottommost=False))
    assert mid.block.n == 1  # tombstone survives to keep masking lower levels
    assert mid.block.deleted[0]


def test_split_stale_keys_gc():
    recs = [(f"k{i}".encode(), b"", b"v", 0, False) for i in range(64)]
    blk = make_block(recs)
    mask, pidx = 3, 2
    res = compact_blocks([blk], CompactOptions(backend="cpu", now=1, pidx=pidx, partition_mask=mask))
    for k in keys_of(res.block):
        assert (key_hash(k) & mask) == pidx
    expect = sum(1 for hk, _, _, _, _ in recs if key_hash(generate_key(hk, b"")) & mask == pidx)
    assert res.block.n == expect > 0


def test_default_ttl_rewrite():
    blk = make_block([(b"h", b"a", b"v", 0, False), (b"h", b"b", b"v", 500, False)])
    res = compact_blocks([blk], CompactOptions(backend="cpu", now=100, default_ttl=50))
    by_key = {res.block.key(i): i for i in range(res.block.n)}
    ia = by_key[generate_key(b"h", b"a")]
    assert res.block.expire_ts[ia] == 150  # now + default_ttl
    # value header rewritten too (v2: expire at offset 1)
    assert SCHEMAS[2].extract_expire_ts(res.block.value(ia)) == 150
    ib = by_key[generate_key(b"h", b"b")]
    assert res.block.expire_ts[ib] == 500


def test_default_ttl_short_value_guarded():
    """Regression: the 4-byte BE TTL rewrite must SKIP records whose value
    is shorter than the expire field itself (has_hdr only guarded the
    READ) — rewriting them scribbled into the neighboring record's arena
    bytes, or past the arena end for the last record."""
    from pegasus_tpu.ops.compact import _apply_default_ttl

    good_val = SCHEMAS[2].generate_value(0, 0, b"payload")
    blk = KVBlock.from_records([
        (b"\x00\x01a", b"\x01\x02", 0, False),   # 2B value: can't hold a TTL
        (b"\x00\x01b", good_val, 0, False),
    ])
    neighbor_before = bytes(blk.val_arena[blk.val_off[1]:
                                          blk.val_off[1] + blk.val_len[1]])
    _apply_default_ttl(blk, 777)
    # the short record was skipped entirely: bytes AND column untouched
    assert bytes(blk.val_arena[blk.val_off[0]:
                               blk.val_off[0] + blk.val_len[0]]) == b"\x01\x02"
    assert blk.expire_ts[0] == 0
    # the neighbor got its own rewrite, not the short record's overflow
    assert blk.expire_ts[1] == 777
    assert SCHEMAS[2].extract_expire_ts(
        bytes(blk.val_arena[blk.val_off[1]:
                            blk.val_off[1] + blk.val_len[1]])) == 777
    assert neighbor_before != bytes(
        blk.val_arena[blk.val_off[1]:blk.val_off[1] + blk.val_len[1]])
    # last-record overflow: a lone short value must not crash or write
    # past the arena end
    solo = KVBlock.from_records([(b"\x00\x01c", b"\x01", 0, False)])
    _apply_default_ttl(solo, 777)
    assert solo.expire_ts[0] == 0 and bytes(solo.val_arena[
        solo.val_off[0]:solo.val_off[0] + solo.val_len[0]]) == b"\x01"


def _adversarial_records(rng, n):
    """Keys engineered to stress prefix windows: shared 32+ byte prefixes,
    trailing zeros, strict-prefix pairs, empty hash/sort keys."""
    recs = []
    long_prefix = b"P" * 40
    for i in range(n):
        mode = i % 6
        if mode == 0:
            hk, sk = rng.bytes(4), rng.bytes(rng.integers(0, 6))
        elif mode == 1:  # long keys sharing a 40-byte prefix
            hk, sk = long_prefix, rng.bytes(rng.integers(0, 8))
        elif mode == 2:  # trailing zero bytes
            hk, sk = b"z", b"\x00" * rng.integers(0, 5)
        elif mode == 3:  # strict prefix pairs
            hk, sk = b"pre", b"fix"[: rng.integers(0, 4)]
        elif mode == 4:  # empty hash key
            hk, sk = b"", rng.bytes(3)
        else:
            hk, sk = rng.bytes(30), rng.bytes(30)
        expire = int(rng.integers(0, 200))
        deleted = bool(rng.random() < 0.15)
        recs.append((hk, sk, b"payload%d" % i, expire, deleted))
    return recs


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_cpu_tpu_differential_bitstable(seed):
    rng = np.random.default_rng(seed)
    runs = [make_block(_adversarial_records(rng, 200)) for _ in range(3)]
    opts = dict(now=100, pidx=1, partition_mask=1, bottommost=(seed % 2 == 0), default_ttl=30)
    r_cpu = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    r_tpu = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    assert r_cpu.block.n == r_tpu.block.n
    np.testing.assert_array_equal(r_cpu.block.key_arena, r_tpu.block.key_arena)
    np.testing.assert_array_equal(r_cpu.block.val_arena, r_tpu.block.val_arena)
    np.testing.assert_array_equal(r_cpu.block.expire_ts, r_tpu.block.expire_ts)
    np.testing.assert_array_equal(r_cpu.block.deleted, r_tpu.block.deleted)
    # output is sorted, unique, and semantically correct
    ks = keys_of(r_cpu.block)
    assert ks == sorted(ks) and len(ks) == len(set(ks))


def test_cpu_output_matches_python_reference_model():
    """Model-based check: brute-force dict semantics == kernel output."""
    rng = np.random.default_rng(7)
    runs = [make_block(_adversarial_records(rng, 150)) for _ in range(4)]
    now, pidx, pmask = 100, 0, 1
    res = compact_blocks(runs, CompactOptions(backend="cpu", now=now, pidx=pidx,
                                              partition_mask=pmask, bottommost=True))
    # brute force: newest run wins per key; then filter
    model = {}
    for b in runs:  # newest first; first writer wins
        for i in range(b.n):
            model.setdefault(b.key(i), (b.value(i), int(b.expire_ts[i]), bool(b.deleted[i])))
    expect = []
    for k, (v, exp, dead) in model.items():
        if dead or (0 < exp <= now):
            continue
        if (key_hash(k) & pmask) != pidx:
            continue
        expect.append(k)
    assert sorted(expect) == keys_of(res.block)


def test_prefix_collision_suffix_ranks():
    base = b"C" * 36
    recs = [(base, bytes([b]), b"v", 0, False) for b in [3, 1, 2, 0xFF, 0]]
    recs.append((base, b"", b"v", 0, False))  # strict prefix of the others
    blk = make_block(recs)
    ranks = compute_suffix_ranks(blk)
    out = sort_block(blk, CompactOptions(backend="cpu"))
    ks = keys_of(out)
    assert ks == sorted(ks)
    assert out.n == 6


@pytest.mark.parametrize("n,ncols", [(64, 1), (1024, 3), (4096, 9)])
def test_sort_network_matches_lexsort(n, ncols):
    import jax
    import jax.numpy as jnp

    from pegasus_tpu.ops.device_sort import sort_network

    rng = np.random.default_rng(n + ncols)
    # small value range to force cross-column ties
    cols = [rng.integers(0, 7, size=n, dtype=np.uint32) for _ in range(ncols)]
    out = jax.jit(lambda c: sort_network(c, nk=ncols))(
        [jnp.asarray(c) for c in cols] + [jnp.arange(n, dtype=jnp.int32)]
    )
    want = np.lexsort(tuple(reversed(cols)))
    for c, g in zip(cols, out[:ncols]):
        np.testing.assert_array_equal(np.asarray(g), c[want])
    # permutation is a valid reordering producing the sorted columns
    perm = np.asarray(out[-1])
    assert sorted(perm) == list(range(n))
    for c, g in zip(cols, out[:ncols]):
        np.testing.assert_array_equal(c[perm], np.asarray(g))


@pytest.mark.parametrize("la,lb", [(100, 100), (1, 37), (500, 12), (1024, 1024)])
def test_merge_two_sorted_runs(la, lb):
    import jax
    import jax.numpy as jnp

    from pegasus_tpu.ops.device_sort import merge_two_sorted

    rng = np.random.default_rng(la * 1000 + lb)
    ncols = 3

    def mk(n):
        prim = np.sort(rng.integers(0, 50, size=n, dtype=np.uint32))
        rest = [rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
                for _ in range(ncols - 1)]
        # make rows unique & sorted via lexsort on all cols
        order = np.lexsort(tuple(reversed([prim] + rest)))
        return [c[order] for c in [prim] + rest]

    A, B = mk(la), mk(lb)
    pad_fill = tuple([np.uint32(0xFFFFFFFF)] * ncols + [np.int32(-1)])
    a_ops = [jnp.asarray(c) for c in A] + [jnp.arange(la, dtype=jnp.int32)]
    b_ops = [jnp.asarray(c) for c in B] + [jnp.arange(la, la + lb, dtype=jnp.int32)]
    out = jax.jit(lambda a, b: merge_two_sorted(a, b, ncols, pad_fill))(a_ops, b_ops)
    merged = [np.asarray(c)[: la + lb] for c in out]
    want_cols = [np.concatenate([a, b]) for a, b in zip(A, B)]
    want = np.lexsort(tuple(reversed(want_cols)))
    for wc, g in zip(want_cols, merged[:ncols]):
        np.testing.assert_array_equal(g, wc[want])
    assert sorted(np.asarray(merged[-1])) == list(range(la + lb))


def test_pack_prefix_bigendian_order():
    blk = make_block([(b"ab", b"", b"v", 0, False), (b"ac", b"", b"v", 0, False)])
    p = pack_key_prefixes(blk.key_arena, blk.key_off, blk.key_len, 2)
    # big-endian packing preserves byte order in u32 comparison
    assert p[0, 0] < p[1, 0]
    # key bytes \x00\x02ab -> 0x000261 62
    assert p[0, 0] == 0x00026162
    assert p[0, 1] == 0  # zero padding


def test_wide_merge_over_255_runs_chunks_correctly():
    """Run priority travels in 8 bits; >255 runs pre-combine (newest-first)
    without filtering so the final semantics are unchanged."""
    runs = []
    for i in range(300):
        runs.append(make_block([(b"shared", b"", b"run%d" % i, 0, False),
                                (b"only%d" % i, b"", b"v", 0, False)]))
    res = compact_blocks(runs, CompactOptions(backend="cpu", now=1))
    assert res.block.n == 301
    by_key = {res.block.key(i): res.block.value(i) for i in range(res.block.n)}
    from pegasus_tpu.base.value_schema import SCHEMAS
    assert SCHEMAS[2].extract_user_data(by_key[generate_key(b"shared", b"")]) == b"run0"


def test_pow2_bucketing_bounds_recompiles():
    """VERDICT-r2 weak 9: a pathological flush pattern (many distinct run
    sizes) must not mean one tunnel compile per size — pow2 bucket padding
    maps nearby lengths onto the same jitted pipeline."""
    from pegasus_tpu.ops.compact import (CompactOptions, _compiled_pipeline,
                                         compact_blocks)

    _compiled_pipeline.cache_clear()
    rng = np.random.default_rng(11)
    for n in (300, 311, 342, 401, 477, 509):  # all in the (256, 512] bucket
        recs = [(b"h%d" % i, b"s%d" % (rng.integers(0, 1000)), b"v", 0, False)
                for i in range(n)]
        runs = [make_block(recs[: n // 2]), make_block(recs[n // 2:])]
        compact_blocks(runs, CompactOptions(backend="tpu", now=100))
    info = _compiled_pipeline.cache_info()
    # every distinct-size merge after the first reused the compiled program
    assert info.misses <= 2, f"recompiled per size: {info}"
    assert info.hits >= 4, f"no cache reuse: {info}"


def test_device_run_cache_matches_host_pack_path():
    """VERDICT-r2 item 4: compaction over cached DeviceRuns (the engine's
    HBM-resident path — no host pack, no re-upload) must be byte-identical
    to the host-packed tpu path AND the cpu lane."""
    from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,
                                         pack_run_device)

    rng = np.random.default_rng(29)
    recs = []
    for i in range(900):
        hk = b"u%05d" % rng.integers(0, 400)
        deleted = bool(rng.random() < 0.1)
        expire = int(rng.integers(0, 3)) * 60
        recs.append((hk, b"s%02d" % (i % 7), b"" if deleted else b"val%d" % i,
                     expire, deleted))
    # three sorted non-overlapping-free runs (dups across runs)
    from tests.test_compact_ops import make_block

    runs = []
    for part in (recs[:300], recs[300:600], recs[600:]):
        blk = make_block(sorted(set(part), key=lambda r: (len(r[0]), r[0], r[1])))
        # make_block inputs must be sorted by encoded key: easier to sort
        # the block through the flush path
        from pegasus_tpu.ops.compact import sort_block

        runs.append(sort_block(blk, CompactOptions(backend="cpu")))
    opts = dict(now=100, bottommost=True, runs_sorted=True)
    cpu = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    host = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    device_runs = [pack_run_device(b) for b in runs]
    assert all(d is not None for d in device_runs)
    cached = compact_blocks(runs, CompactOptions(backend="tpu", **opts),
                            device_runs=device_runs)
    for other in (host, cached):
        assert other.block.n == cpu.block.n
        np.testing.assert_array_equal(cpu.block.key_arena, other.block.key_arena)
        np.testing.assert_array_equal(cpu.block.val_arena, other.block.val_arena)
        np.testing.assert_array_equal(cpu.block.expire_ts, other.block.expire_ts)


def test_engine_tpu_backend_uses_device_cache(tmp_path):
    """An engine on backend=tpu serves identical data to a cpu engine, and
    its SSTs hold primed device runs after flush."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    engines = {}
    for backend in ("cpu", "tpu"):
        eng = LsmEngine(str(tmp_path / backend), EngineOptions(
            backend=backend, memtable_bytes=8 << 10,
            l0_compaction_trigger=3))
        for i in range(400):
            key = generate_key(b"h%d" % (i % 37), b"s%05d" % i)
            eng.put(key, SCHEMAS[2].generate_value(0, 0, b"v%d" % i))
            if i % 90 == 89:
                eng.delete(generate_key(b"h%d" % (i % 37), b"s%05d" % i))
        eng.manual_compact(now=100)
        engines[backend] = eng
    tpu = engines["tpu"]
    # flush/compaction outputs were primed into the device cache
    primed = [s for s in tpu._l0 + sum(tpu._levels.values(), [])
              if s._device_run is not None]
    assert primed, "no SST holds a device-resident run"
    for i in range(400):
        key = generate_key(b"h%d" % (i % 37), b"s%05d" % i)
        assert engines["cpu"].get(key) == tpu.get(key), f"diverged at {i}"
    for eng in engines.values():
        eng.close()


def test_device_cache_pipeline_shares_programs_across_sizes():
    """The cached-run pipeline must be keyed on pow2 buckets, not exact run
    lengths: distinct sizes in one bucket share one compiled program."""
    from pegasus_tpu.ops.compact import (CompactOptions,
                                         _compiled_pipeline_cached,
                                         compact_blocks, pack_run_device,
                                         sort_block)

    _compiled_pipeline_cached.cache_clear()
    rng = np.random.default_rng(31)
    outs = []
    for n in (300, 333, 410, 489):  # all in the (256, 512] bucket
        recs = [(b"h%03d" % rng.integers(0, 200), b"s%d" % i, b"v%d" % i,
                 0, False) for i in range(n)]
        runs = [sort_block(make_block(recs[: n // 2]),
                           CompactOptions(backend="cpu")),
                sort_block(make_block(recs[n // 2:]),
                           CompactOptions(backend="cpu"))]
        device_runs = [pack_run_device(b) for b in runs]
        opts = CompactOptions(backend="tpu", now=100, runs_sorted=True)
        got = compact_blocks(runs, opts, device_runs=device_runs)
        want = compact_blocks(runs, CompactOptions(backend="cpu", now=100,
                                                   runs_sorted=True))
        np.testing.assert_array_equal(want.block.key_arena, got.block.key_arena)
        np.testing.assert_array_equal(want.block.val_arena, got.block.val_arena)
        outs.append(got.block.n)
    info = _compiled_pipeline_cached.cache_info()
    assert info.misses == 1, f"recompiled per size: {info}"
    assert info.hits == 3, f"no reuse: {info}"


def test_blockwise_merge_matches_whole_merge():
    """SURVEY §5.7 long-context analogue: a merge bigger than the device
    budget decomposes into disjoint key ranges whose outputs concatenate
    byte-equal to the whole-merge result — the bigger-than-HBM path."""
    from dataclasses import replace

    from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,
                                         sort_block)

    rng = np.random.default_rng(41)
    recs = []
    for i in range(4000):
        hk = b"u%06d" % rng.integers(0, 1500)
        deleted = bool(rng.random() < 0.08)
        expire = int(rng.integers(0, 3)) * 50
        recs.append((hk, b"s%d" % (i % 5), b"" if deleted else b"w%d" % i,
                     expire, deleted))
    runs = [sort_block(make_block(part), CompactOptions(backend="cpu"))
            for part in (recs[:1500], recs[1500:2600], recs[2600:])]
    base = CompactOptions(backend="tpu", now=60, runs_sorted=True)
    whole = compact_blocks(runs, base)
    for budget in (500, 1000, 2500):
        split = compact_blocks(runs, replace(base,
                                             max_device_records=budget))
        assert split.block.n == whole.block.n
        np.testing.assert_array_equal(whole.block.key_arena,
                                      split.block.key_arena)
        np.testing.assert_array_equal(whole.block.val_arena,
                                      split.block.val_arena)
        np.testing.assert_array_equal(whole.block.expire_ts,
                                      split.block.expire_ts)
    # degenerate distribution: every record shares one key — must not
    # recurse forever, and still dedups to a single survivor
    one = sort_block(make_block([(b"k", b"s", b"v%d" % i, 0, False)
                                 for i in range(50)]),
                     CompactOptions(backend="cpu"))
    same = [one, one]
    res = compact_blocks(same, replace(base, max_device_records=10))
    assert res.block.n == 1


def test_blockwise_merge_long_keys_rank_path():
    """Blockwise decomposition with keys beyond the prefix window (the
    suffix-rank pack path) must stay byte-equal — and compacts its range
    slices so the rank concat doesn't drag whole arenas per range."""
    from dataclasses import replace

    from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,
                                         sort_block)

    rng = np.random.default_rng(43)
    recs = []
    for i in range(1200):
        # 60+B hashkeys: longer than 4*prefix_u32(8)=32 bytes
        hk = b"verylonghashkeyprefix-%038d" % rng.integers(0, 400)
        recs.append((hk, b"s%d" % (i % 3), b"v%d" % i, 0, False))
    runs = [sort_block(make_block(part), CompactOptions(backend="cpu"))
            for part in (recs[:600], recs[600:])]
    base = CompactOptions(backend="tpu", now=60, runs_sorted=True)
    whole = compact_blocks(runs, base)
    split = compact_blocks(runs, replace(base, max_device_records=400))
    assert split.block.n == whole.block.n
    np.testing.assert_array_equal(whole.block.key_arena, split.block.key_arena)
    np.testing.assert_array_equal(whole.block.val_arena, split.block.val_arena)


def _uniform_runs(rng, n_runs=3, n=400):
    """Fixed-width records (the bench/engine fast layout): 8B hash keys,
    8B sort keys, width-10 payloads -> uniform_layout() is non-None."""
    runs = []
    for r in range(n_runs):
        recs = [(b"h%07d" % rng.integers(0, 120), b"s%07d" % rng.integers(0, 40),
                 b"p%09d" % rng.integers(0, 10**9), int(rng.integers(0, 150)),
                 bool(rng.random() < 0.2)) for _ in range(n)]
        # tombstones must keep the uniform value width (empty values would
        # break the fixed layout, as in the bench fill where tombstones
        # still carry a full-width value row)
        rows = []
        from pegasus_tpu.base.key_schema import generate_key
        from pegasus_tpu.base.value_schema import SCHEMAS

        for hk, sk, payload, expire, deleted in recs:
            rows.append((generate_key(hk, sk),
                         SCHEMAS[2].generate_value(expire, 0, payload),
                         expire, deleted))
        runs.append(sort_block(KVBlock.from_records(rows)))
    return runs


def test_materialize_device_survivors_matches_host_gather():
    """Value-residency materialization (device value gather + host key
    gather, overlapped) is byte-identical to the host fused gather."""
    from pegasus_tpu.ops.compact import (TpuBackend, gather_device_survivors,
                                         materialize_device_survivors,
                                         pack_runs, prepare_values)

    rng = np.random.default_rng(3)
    runs = _uniform_runs(rng)
    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    packed = pack_runs(runs, opts, need_sbytes=False)
    backend = TpuBackend()
    prep = backend.prepare(packed)
    dev_idx, cnt = backend.survivors_device(prep, 100, 0, 0, True, True)
    assert cnt > 0
    concat = KVBlock.concat(runs)
    base = gather_device_survivors(concat, dev_idx, cnt)
    dev_vals = prepare_values(concat)
    assert dev_vals is not None
    out = materialize_device_survivors(concat, dev_vals, dev_idx, cnt)
    assert out.n == base.n == cnt
    np.testing.assert_array_equal(base.key_arena, out.key_arena)
    np.testing.assert_array_equal(base.val_arena, out.val_arena)
    np.testing.assert_array_equal(base.expire_ts, out.expire_ts)
    np.testing.assert_array_equal(base.hash32, out.hash32)
    np.testing.assert_array_equal(base.deleted, out.deleted)
    np.testing.assert_array_equal(base.key_off, out.key_off)
    np.testing.assert_array_equal(base.val_off, out.val_off)


def test_materialize_device_survivors_nonuniform_falls_back():
    """Variable-width values: prepare_values declines, and the entry point
    degrades to the host gather instead of corrupting rows."""
    from pegasus_tpu.ops.compact import (TpuBackend, materialize_device_survivors,
                                         pack_runs, prepare_values)

    rng = np.random.default_rng(5)
    runs = [sort_block(make_block(_adversarial_records(rng, 150)))
            for _ in range(2)]
    concat = KVBlock.concat(runs)
    assert prepare_values(concat) is None
    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    packed = pack_runs(runs, opts, need_sbytes=False)
    backend = TpuBackend()
    dev_idx, cnt = backend.survivors_device(packed, 100, 0, 0, True, True)
    out = materialize_device_survivors(concat, None, dev_idx, cnt)
    r_cpu = compact_blocks(runs, CompactOptions(backend="cpu", now=100,
                                                bottommost=True,
                                                runs_sorted=True))
    np.testing.assert_array_equal(r_cpu.block.key_arena, out.key_arena)
    np.testing.assert_array_equal(r_cpu.block.val_arena, out.val_arena)


def test_cached_value_residency_matches_cpu():
    """Cached runs with pinned value rows (pack_run_device with_values):
    compact_blocks takes the device-materialization branch and stays
    byte-identical to the cpu lane; mixed caches (one run without values)
    fall back to the host gather, same bytes."""
    from pegasus_tpu.ops.compact import pack_run_device

    rng = np.random.default_rng(31)
    runs = _uniform_runs(rng, n_runs=3, n=350)
    opts = dict(now=100, bottommost=True, runs_sorted=True)
    cpu = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    drs_v = [pack_run_device(b, with_values=True) for b in runs]
    assert all(d is not None and d.val2d is not None for d in drs_v)
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts),
                         device_runs=drs_v)
    # mixed: one run lacks values -> host-gather fallback branch
    drs_mixed = [pack_run_device(runs[0])] + drs_v[1:]
    mixed = compact_blocks(runs, CompactOptions(backend="tpu", **opts),
                           device_runs=drs_mixed)
    for other in (got, mixed):
        assert other.block.n == cpu.block.n
        np.testing.assert_array_equal(cpu.block.key_arena, other.block.key_arena)
        np.testing.assert_array_equal(cpu.block.val_arena, other.block.val_arena)
        np.testing.assert_array_equal(cpu.block.expire_ts, other.block.expire_ts)
        np.testing.assert_array_equal(cpu.block.deleted, other.block.deleted)


def test_engine_device_values_end_to_end(tmp_path):
    """EngineOptions.device_values=True: uniform-width tables compact
    through the value-residency branch and serve identical data to cpu."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine

    engines = {}
    for backend, dv in (("cpu", False), ("tpu", True)):
        eng = LsmEngine(str(tmp_path / backend), EngineOptions(
            backend=backend, memtable_bytes=8 << 10,
            l0_compaction_trigger=3, device_values=dv))
        for i in range(500):
            key = generate_key(b"h%03d" % (i % 41), b"s%05d" % i)
            eng.put(key, SCHEMAS[2].generate_value(0, 0, b"pay%07d" % i))
        eng.manual_compact(now=100)
        engines[backend] = eng
    tpu = engines["tpu"]
    primed = [s for s in tpu._l0 + sum(tpu._levels.values(), [])
              if s._device_run is not None and s._device_run.val2d is not None]
    assert primed, "no SST holds resident value rows"
    for i in range(500):
        key = generate_key(b"h%03d" % (i % 41), b"s%05d" % i)
        assert engines["cpu"].get(key) == tpu.get(key), f"diverged at {i}"
    for eng in engines.values():
        eng.close()


def test_intra_run_duplicate_keys_byte_equal_and_correct():
    """r5 regression (seed 11): runs with DUPLICATE keys inside one run —
    legal for raw external sets, never produced by the engine — must
    compact byte-equal across backends and match the model (newest run
    wins; within a run the FIRST occurrence wins). The device merge
    networks are not stable, so pack_runs now first-wins-dedups any run
    it host-sorts, and merge_body keys the sort on original position."""
    rng = np.random.default_rng(11)
    runs = [make_block(_adversarial_records(rng, 350)) for _ in range(3)]

    merged = {}
    for b in runs:  # newest first
        seen = set()
        for i in range(b.n):
            k = b.key(i)
            if k in seen:
                continue
            seen.add(k)
            if k not in merged:
                merged[k] = (b.value(i), int(b.expire_ts[i]),
                             bool(b.deleted[i]))
    now = 60
    want = {(k, v) for k, (v, e, d) in merged.items()
            if not d and not (0 < e <= now)}

    cpu = compact_blocks(runs, CompactOptions(backend="cpu", now=now,
                                              bottommost=True,
                                              runs_sorted=None))
    tpu = compact_blocks(runs, CompactOptions(backend="tpu", now=now,
                                              bottommost=True,
                                              runs_sorted=None))
    got_cpu = {(cpu.block.key(i), cpu.block.value(i))
               for i in range(cpu.block.n)}
    assert got_cpu == want
    assert bytes(cpu.block.key_arena) == bytes(tpu.block.key_arena)
    assert bytes(cpu.block.val_arena) == bytes(tpu.block.val_arena)


def test_sorted_dup_runs_backend_parity_and_stats():
    """r5 review findings: (1) a PRE-SORTED run carrying duplicate keys
    (runs_sorted=True skips only the sort check, not uniqueness) must
    dedup identically on both backends; (2) stats count RAW input rows on
    every path, not post-dedup pack lengths."""
    recs = []
    for i in range(50):
        recs.append((b"hk%02d" % (i % 10), b"s%03d" % i, b"v%d" % i, 0, False))
        if i % 5 == 0:  # duplicate key, older value — must be shadowed
            recs.append((b"hk%02d" % (i % 10), b"s%03d" % i, b"OLD", 0, False))
    blocks = [make_block(sorted(recs, key=lambda r: (len(r[0]), r[0], r[1])))]
    # make_block sorts? ensure sortedness by building then asserting
    b = blocks[0]
    keys = [b.key(i) for i in range(b.n)]
    assert keys == sorted(keys)
    raw_n = b.n
    cpu = compact_blocks([b], CompactOptions(backend="cpu", now=5,
                                             runs_sorted=True))
    tpu = compact_blocks([b], CompactOptions(backend="tpu", now=5,
                                             runs_sorted=True))
    assert bytes(cpu.block.key_arena) == bytes(tpu.block.key_arena)
    assert bytes(cpu.block.val_arena) == bytes(tpu.block.val_arena)
    assert b"OLD" not in bytes(cpu.block.val_arena)  # first-wins kept new
    assert cpu.stats["input_records"] == tpu.stats["input_records"] == raw_n
