"""bench.py harness bounds: the driver artifact is (rc, one JSON line),
and three rounds of red artifacts (BENCH_r01 rc=1, r02 rc=1, r03 rc=124)
all came from unbounded failure modes the happy-path tests never walked.
These tests run bench.py exactly as the driver does — a subprocess whose
stdout must yield a parseable JSON line, rc=0, within a wall-clock bound —
under every wedge mode the tunnel has actually produced:

  - lane child hangs after a healthy start (r3's failure: post-probe
    wedge) -> PEGASUS_BENCH_FAKE_LANE=sleep
  - lane child dies in backend init (r2's failure) -> FAKE_LANE=crash
  - everything hangs and only the watchdog is left -> tiny TIMEOUT_S

The happy path (real child lane on the CPU platform) is covered too, so
the digest-equality handshake between parent and child stays exercised.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def run_bench(env_extra, timeout_s, n=30_000):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PEGASUS_BENCH_N": str(n),
        "PEGASUS_BENCH_REPS": "1",
    })
    env.update(env_extra)
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=timeout_s, env=env, cwd=REPO)
    elapsed = time.monotonic() - t0
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line. rc={proc.returncode} err={proc.stderr[-800:]}"
    return proc.returncode, json.loads(lines[-1]), elapsed


def test_lane_wedge_after_start_bounded():
    """r3's exact failure mode: the TPU lane wedges after a healthy start.
    The parent must SIGTERM the child and emit the degraded line WITH the
    cpu numbers, rc=0, within the lane budget + slack — never rc=124."""
    rc, line, elapsed = run_bench(
        {"PEGASUS_BENCH_FAKE_LANE": "sleep", "PEGASUS_BENCH_LANE_S": "4"},
        timeout_s=120)
    assert rc == 0
    assert line["value"] is None
    d = line["detail"]
    assert d["tpu_unavailable"] is True
    assert "exceeded 4s" in d["reason"]
    # the degraded line carries the measured CPU lane (VERDICT-r3 item 1)
    assert d["cpu_compact_s"] > 0
    assert d["input_records"] == 30_000
    assert elapsed < 90


def test_lane_crash_reports_degraded():
    rc, line, _ = run_bench({"PEGASUS_BENCH_FAKE_LANE": "crash"},
                            timeout_s=120)
    assert rc == 0
    assert line["value"] is None
    assert "rc=7" in line["detail"]["reason"]
    assert line["detail"]["cpu_compact_s"] > 0


def test_watchdog_backstop_emits_parseable_line():
    """If everything else fails, the watchdog itself must produce the
    artifact: parseable line, rc=0, no stray second JSON line."""
    env = {"PEGASUS_BENCH_FAKE_LANE": "sleep", "PEGASUS_BENCH_LANE_S": "3600",
           "PEGASUS_BENCH_TIMEOUT_S": "8"}
    rc, line, elapsed = run_bench(env, timeout_s=120)
    assert rc == 0
    assert line["value"] is None
    assert "watchdog fired" in line["detail"]["reason"]
    # the backstop still carries the measured CPU lane numbers
    assert line["detail"]["cpu_compact_s"] > 0
    assert elapsed < 60


@pytest.mark.slow
def test_happy_path_child_lane_byte_equal():
    """Real child lane on the CPU platform: digest handshake across the
    process boundary, speedup value present (its magnitude is meaningless
    on CPU jax — only byte_equal and shape of the line matter here)."""
    rc, line, _ = run_bench({}, timeout_s=600, n=6_000)
    assert rc == 0
    assert line["value"] is not None
    assert line["detail"]["byte_equal"] is True
    assert line["unit"] == "x"


SCALE = os.path.join(REPO, "tools", "scale_bench.py")


def run_scale(env_extra, timeout_s, n=50_000, maxdev=8192):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PEGASUS_SCALE_N": str(n),
        "PEGASUS_SCALE_MAXDEV": str(maxdev),
    })
    env.update(env_extra)
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, SCALE], capture_output=True,
                          text=True, timeout=timeout_s, env=env, cwd=REPO)
    elapsed = time.monotonic() - t0
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line. rc={proc.returncode} err={proc.stderr[-800:]}"
    return proc.returncode, json.loads(lines[-1]), elapsed


def test_scale_bench_wedge_bounded():
    """tools/scale_bench.py under a wedged device lane must emit a
    degraded-but-parseable line within its watchdog budget, rc=0 (the
    worst-case-runtime guarantee every tool needs, VERDICT-r3 item 8)."""
    rc, line, elapsed = run_scale({"PEGASUS_SCALE_FAKE": "sleep",
                                   "PEGASUS_SCALE_TIMEOUT_S": "12"},
                                  timeout_s=120)
    assert rc == 0
    assert line["value"] is None
    assert line["detail"]["degraded"] is True
    assert "watchdog" in line["detail"]["reason"]
    # the cpu lane's numbers still made it into the degraded line
    assert line["detail"]["cpu_compact_s"] > 0
    assert elapsed < 60


def test_scale_bench_happy_blockwise():
    """Happy path on the CPU platform: the device lane takes the blockwise
    range-decomposition (n > max_device_records) and the output is
    byte-equal to the native CPU lane."""
    rc, line, elapsed = run_scale({"PEGASUS_SCALE_TIMEOUT_S": "300"},
                                  timeout_s=360)
    assert rc == 0
    assert line["detail"]["byte_equal"] is True
    assert line["detail"]["blocks"] >= 2
    assert line["value"] is not None


EBENCH = os.path.join(REPO, "tools", "engine_bench.py")


def test_engine_bench_wedge_bounded():
    """tools/engine_bench.py with a wedged backend init must emit a
    degraded JSON line within its watchdog budget, rc=0 — the engine lane
    is driven in-process by tpu_oneshot, but driven standalone it needs
    its own worst-case bound (VERDICT-r3 item 8)."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PEGASUS_EBENCH_N": "20000",
                "PEGASUS_EBENCH_FAKE": "sleep",
                "PEGASUS_EBENCH_TIMEOUT_S": "8"})
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, EBENCH], capture_output=True,
                          text=True, timeout=120, env=env, cwd=REPO)
    elapsed = time.monotonic() - t0
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line. rc={proc.returncode} err={proc.stderr[-500:]}"
    line = json.loads(lines[-1])
    assert proc.returncode == 0
    assert line["degraded"] is True and "watchdog" in line["reason"]
    assert elapsed < 60


def test_engine_bench_happy_cpu_only():
    """Happy path: cpu-only lane completes well under the watchdog and
    prints its lane line."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PEGASUS_EBENCH_N": "20000",
                "PEGASUS_EBENCH_REPS": "1",
                "PEGASUS_EBENCH_BACKENDS": "cpu",
                "PEGASUS_EBENCH_DIR": "/tmp/pegasus_ebench_test",
                "PEGASUS_EBENCH_TIMEOUT_S": "300"})
    proc = subprocess.run([sys.executable, EBENCH], capture_output=True,
                          text=True, timeout=320, env=env, cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert proc.returncode == 0 and lines
    lane = json.loads(lines[0])
    assert lane["backend"] == "cpu" and lane["manual_compact_s"] > 0


def test_lane_wedge_reports_stage_attribution():
    """A wedged lane whose watchdog heartbeated before dying must be
    attributed: the degraded reason names the stage (the BENCH_r05 gap —
    no more bare '360s exceeded'), the watchdog heartbeat rides in the
    detail, and the cpu lane's per-stage trace is present regardless."""
    rc, line, _ = run_bench(
        {"PEGASUS_BENCH_FAKE_LANE": "wedge", "PEGASUS_BENCH_LANE_S": "4"},
        timeout_s=120)
    assert rc == 0
    assert line["value"] is None
    d = line["detail"]
    assert "wedged at stage: device" in d["reason"]
    assert d["watchdog"]["wedged_at_stage"] == "device"
    # acceptance: the cpu lane's trace breakdown is in the detail
    for stage in ("pack", "device", "gather"):
        assert stage in d["trace"], d["trace"]
    assert d["trace"]["pack"]["records"] == 30_000


def _python_procs():
    out = subprocess.run(["ps", "-eo", "args"], capture_output=True,
                         text=True).stdout.splitlines()
    return [l for l in out if "bench.py" in l or "tpu-lane" in l]


def test_ycsb_mode_smoke():
    """PEGASUS_BENCH_MODE=ycsb at tiny N: one parseable JSON line with
    ops/sec > 0, per-op-class latency percentiles, the plog group-size
    histogram + prepare-latency attribution, and a host block; the
    in-process onebox leaves no processes behind; the default mode's
    schema is untouched (covered by the other tests in this file)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PEGASUS_BENCH_MODE": "ycsb",
        "PEGASUS_BENCH_YCSB_RECORDS": "300",
        "PEGASUS_BENCH_YCSB_OPS": "600",
        "PEGASUS_BENCH_YCSB_THREADS": "4",
        "PEGASUS_BENCH_YCSB_PARTITIONS": "4",
        "PEGASUS_BENCH_TIMEOUT_S": "150",
    })
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=170, env=env, cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert proc.returncode == 0 and len(lines) == 1, \
        f"rc={proc.returncode} out={proc.stdout[-300:]} err={proc.stderr[-500:]}"
    line = json.loads(lines[0])
    assert line["unit"] == "ops/s"
    assert line["value"] and line["value"] > 0
    assert line["metric"].startswith("YCSB-A")
    d = line["detail"]
    assert d["errors"] == 0
    assert d["partitions"] == 4 and d["records"] == 300
    for cls in ("read", "update"):
        assert d["client_latency_us"][cls]["p99"] > 0
    # the batching win is attributable: group histogram + prepare latency
    assert set(d["plog"]["group_size"]) == {"p50", "p90", "p95", "p99", "p999"}
    assert d["plog"]["append_count"] > 0 and d["plog"]["flush_count"] > 0
    assert d["prepare_latency_us"]["p99"] > 0
    # host-contention attribution rides the line like the compaction bench
    assert "loadavg" in d["host"]["start"] and "cpu_count" in d["host"]["end"]
    # the self-booted onebox is in-process: nothing may outlive the bench
    assert not _python_procs(), "ycsb mode left processes behind"


def test_ycsb_read_heavy_mix_smoke():
    """PEGASUS_BENCH_YCSB_MIX=c: the read-heavy device-read A/B variant
    (ISSUE 7) — the metric names the mix, and detail.reads carries the
    device probe totals, the read-lane state, and the fallback-free
    verdict (device_numbers_degraded) so a degraded read lane can never
    pass its numbers off as clean device throughput."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PEGASUS_BENCH_MODE": "ycsb",
        "PEGASUS_BENCH_YCSB_MIX": "c",
        "PEGASUS_BENCH_YCSB_RECORDS": "200",
        "PEGASUS_BENCH_YCSB_OPS": "400",
        "PEGASUS_BENCH_YCSB_THREADS": "4",
        "PEGASUS_BENCH_YCSB_PARTITIONS": "4",
        "PEGASUS_BENCH_TIMEOUT_S": "150",
    })
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=170, env=env, cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert proc.returncode == 0 and len(lines) == 1, \
        f"rc={proc.returncode} out={proc.stdout[-300:]} err={proc.stderr[-500:]}"
    line = json.loads(lines[0])
    assert line["metric"].startswith("YCSB-C 100/0")
    assert line["value"] and line["value"] > 0
    reads = line["detail"]["reads"]
    assert reads["mix"] == "c" and reads["read_fraction"] == 1.0
    assert set(reads["device"]) == {"lookup_count", "keys", "hits"}
    assert "fallbacks" in reads["lane"]
    # cpu-backend onebox: the read lane never engaged, so the device
    # numbers are clean (zero) — NOT degraded
    assert reads["device_numbers_degraded"] is False


@pytest.mark.slow
def test_ycsb_group_sweep_scaling():
    """The partition-group scaling artifact (BENCH_r06-ready): the sweep
    mode runs the same YCSB-A workload with the replica nodes split into
    1 vs 4 shared-nothing group executors. On a >=4-core host groups=4
    must clear 1.5x the ops/s of groups=1 (the single-GIL ceiling); on
    smaller hosts only the sweep mechanics are asserted — the scaling
    claim needs cores for the executors to land on."""
    cores = os.cpu_count() or 1
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PEGASUS_BENCH_MODE": "ycsb",
        "PEGASUS_BENCH_YCSB_GROUPS": "1,4",
        "PEGASUS_BENCH_YCSB_RECORDS": "2000",
        "PEGASUS_BENCH_YCSB_OPS": "16000",
        "PEGASUS_BENCH_YCSB_THREADS": "8",
        "PEGASUS_BENCH_YCSB_PARTITIONS": "8",
        "PEGASUS_BENCH_TIMEOUT_S": "560",
    })
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=580, env=env, cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert proc.returncode == 0 and len(lines) == 1, \
        f"rc={proc.returncode} out={proc.stdout[-300:]} err={proc.stderr[-500:]}"
    line = json.loads(lines[0])
    assert line["unit"] == "ops/s"
    assert "serve-group sweep" in line["metric"]
    sweep = line["detail"]["sweep"]
    assert [e["groups"] for e in sweep] == [1, 4]
    assert all(e["errors"] == 0 for e in sweep), sweep
    assert all(e["ops_s"] > 0 for e in sweep)
    # host-contention detail rides every sweep entry
    assert all("loadavg" in e["host"]["start"] for e in sweep)
    # no leaked group-executor processes after the bench exits
    assert not _python_procs(), "sweep left processes behind"
    if cores >= 4:
        scaling = sweep[1]["ops_s"] / sweep[0]["ops_s"]
        assert scaling >= 1.5, (
            f"groups=4 must clear 1.5x groups=1 on a {cores}-core host, "
            f"got {scaling:.2f}x ({sweep[0]['ops_s']} -> "
            f"{sweep[1]['ops_s']} ops/s)")
