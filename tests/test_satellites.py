"""Satellite subsystem tests: bulk load, duplication, partition split,
cold backup/restore — over the real socket cluster (reference function-test
equivalents: bulk_load, test_split, backup_and_restore, dup tests)."""

import json
import time

import pytest

from pegasus_tpu.base import key_schema
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.engine import bulk_load as bl
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.meta import MetaServer
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.meta.meta_server import (RPC_CM_BACKUP_APP, RPC_CM_CREATE_APP,
                                          RPC_CM_QUERY_CONFIG,
                                          RPC_CM_RESTORE_APP, RPC_CM_SPLIT_APP,
                                          RPC_CM_START_BULK_LOAD)
from pegasus_tpu.replication.duplicator import MutationDuplicator
from pegasus_tpu.replication.replica_stub import ReplicaStub
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc.transport import RpcConnection, RpcServer


class MiniCluster:
    def __init__(self, root, n_nodes=3, serve_groups=0, remote_clusters=None,
                 cluster_id=1, fd_grace_seconds=60):
        self.meta = MetaServer(str(root / "meta.json"),
                               fd_grace_seconds=fd_grace_seconds)
        self.rpc = RpcServer().start()
        for code, fn in self.meta.rpc_handlers().items():
            self.rpc.register(code, fn)
        self.meta_addr = f"{self.rpc.address[0]}:{self.rpc.address[1]}"
        if serve_groups and serve_groups >= 1:
            # shared-nothing partition-group serving: each node forks
            # serve_groups worker processes behind one public router
            from pegasus_tpu.replication.serve_groups import GroupedReplicaNode

            self.stubs = [GroupedReplicaNode(str(root / f"n{i}"),
                                             [self.meta_addr],
                                             groups=serve_groups,
                                             remote_clusters=remote_clusters,
                                             cluster_id=cluster_id).start(0.2)
                          for i in range(n_nodes)]
        else:
            self.stubs = [ReplicaStub(str(root / f"n{i}"),
                                      [self.meta_addr],
                                      remote_clusters=remote_clusters,
                                      cluster_id=cluster_id).start(0.2)
                          for i in range(n_nodes)]
        self._conn = RpcConnection(self.rpc.address)

    def ddl(self, code, req, resp_cls, timeout=30.0):
        _, body = self._conn.call(code, codec.encode(req), timeout=timeout)
        return codec.decode(resp_cls, body)

    def create(self, name, partitions=2, replicas=3):
        r = self.ddl(RPC_CM_CREATE_APP,
                     mm.CreateAppRequest(name, partitions, replicas),
                     mm.CreateAppResponse)
        assert r.error == 0
        return PegasusClient(MetaResolver([self.meta_addr], name))

    def stop(self):
        self._conn.close()
        for s in self.stubs:
            s.stop()
        self.rpc.stop()


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(tmp_path)
    yield c
    c.stop()


# ------------------------------------------------------------- bulk load

def test_raw_set_roundtrip(tmp_path):
    p = str(tmp_path / "set.raw")
    rows = [(b"hk%d" % i, b"sk", b"v%d" % i, 0) for i in range(20)]
    assert bl.write_raw_set(p, rows) == 20
    assert list(bl.read_raw_set(p)) == rows


def test_bulk_load_end_to_end(cluster, tmp_path):
    cli = cluster.create("blt", partitions=2)
    provider = tmp_path / "provider"
    n_total = 60
    # offline producer: records partitioned by hash, like the Spark job
    per_part = {0: [], 1: []}
    for i in range(n_total):
        hk, sk, v = b"bl%d" % i, b"s", b"val%d" % i
        h = key_schema.key_hash(key_schema.generate_key(hk, sk))
        per_part[h % 2].append((hk, sk, v, 0))
    for pidx, rows in per_part.items():
        pdir = provider / "blt" / "2" / str(pidx)
        pdir.mkdir(parents=True)
        bl.write_raw_set(str(pdir / "part0.raw"), rows[: len(rows) // 2])
        bl.write_raw_set(str(pdir / "part1.raw"), rows[len(rows) // 2:])
    bl.write_metadata(str(provider), "blt", 2)
    r = cluster.ddl(RPC_CM_START_BULK_LOAD,
                    mm.StartBulkLoadRequest("blt", str(provider)),
                    mm.StartBulkLoadResponse)
    assert r.error == 0, r.error_text
    assert r.ingested_records == n_total
    for i in range(n_total):
        assert cli.get(b"bl%d" % i, b"s") == b"val%d" % i
    cli.close()


def test_bulk_load_async_session_controls(cluster, tmp_path):
    """Async bulk load is a controllable session: pause holds the partition
    walk, restart resumes it, query reports progress (reference bulk-load
    state machine, shell bulk_load.cpp control verbs)."""
    import time as _time

    from pegasus_tpu.meta.meta_server import (RPC_CM_CONTROL_BULK_LOAD,
                                              RPC_CM_QUERY_BULK_LOAD)

    cli = cluster.create("blas", partitions=2)
    provider = tmp_path / "prov_async"
    per_part = {0: [], 1: []}
    n_total = 40
    for i in range(n_total):
        hk, sk, v = b"as%d" % i, b"s", b"av%d" % i
        h = key_schema.key_hash(key_schema.generate_key(hk, sk))
        per_part[h % 2].append((hk, sk, v, 0))
    for pidx, rows in per_part.items():
        pdir = provider / "blas" / "2" / str(pidx)
        pdir.mkdir(parents=True)
        bl.write_raw_set(str(pdir / "set.raw"), rows)
    bl.write_metadata(str(provider), "blas", 2)
    # pause before starting the session: the worker must hold at 0 done
    app_id = cli.resolver.app_id
    r = cluster.ddl(RPC_CM_START_BULK_LOAD,
                    mm.StartBulkLoadRequest("blas", str(provider),
                                            async_start=True),
                    mm.StartBulkLoadResponse)
    assert r.error == 0, r.error_text
    r = cluster.ddl(RPC_CM_CONTROL_BULK_LOAD,
                    mm.ControlBulkLoadRequest("blas", "pause"),
                    mm.ControlBulkLoadResponse)
    # the session may legitimately finish before the pause lands on a fast
    # box; only assert the control surface behaves for whichever state
    q = cluster.ddl(RPC_CM_QUERY_BULK_LOAD, mm.QueryBulkLoadRequest("blas"),
                    mm.QueryBulkLoadResponse)
    assert q.status in ("paused", "ingesting", "succeed")
    if q.status == "paused":
        held = cluster.ddl(RPC_CM_QUERY_BULK_LOAD,
                           mm.QueryBulkLoadRequest("blas"),
                           mm.QueryBulkLoadResponse)
        r = cluster.ddl(RPC_CM_CONTROL_BULK_LOAD,
                        mm.ControlBulkLoadRequest("blas", "restart"),
                        mm.ControlBulkLoadResponse)
        assert r.error == 0
    deadline = _time.time() + 15
    while _time.time() < deadline:
        q = cluster.ddl(RPC_CM_QUERY_BULK_LOAD,
                        mm.QueryBulkLoadRequest("blas"),
                        mm.QueryBulkLoadResponse)
        if q.status == "succeed":
            break
        _time.sleep(0.2)
    assert q.status == "succeed", q.status
    assert q.ingested_records == n_total
    assert q.done_partitions == q.total_partitions == 2
    for i in range(n_total):
        assert cli.get(b"as%d" % i, b"s") == b"av%d" % i
    # double-start while a finished session exists is allowed again
    q = cluster.ddl(RPC_CM_CONTROL_BULK_LOAD,
                    mm.ControlBulkLoadRequest("blas", "pause"),
                    mm.ControlBulkLoadResponse)
    assert q.error == 1  # cannot pause a finished session
    cli.close()


def test_bulk_load_drops_misrouted_rows(tmp_path):
    """Rows that hash to another partition are filtered at ingest."""
    from pegasus_tpu.engine.db import LsmEngine

    eng = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    provider = tmp_path / "prov"
    pdir = provider / "t" / "4" / "1"
    pdir.mkdir(parents=True)
    rows = [(b"k%d" % i, b"s", b"v", 0) for i in range(40)]
    bl.write_raw_set(str(pdir / "all.raw"), rows)
    stats = bl.ingest_partition(eng, str(provider), "t", 4, 1, SCHEMAS[2])
    expect = sum(1 for hk, sk, _, _ in rows
                 if key_schema.key_hash(key_schema.generate_key(hk, sk)) % 4 == 1)
    assert stats["records"] == expect > 0
    eng.close()


# ------------------------------------------------------------ duplication

def test_duplication_ships_writes_to_remote_cluster(tmp_path):
    src = MiniCluster(tmp_path / "src", n_nodes=3)
    dst = MiniCluster(tmp_path / "dst", n_nodes=3)
    try:
        src_cli = src.create("dup", partitions=2)
        dst.create("dup", partitions=2).close()
        # attach a duplicator to every source replica (the dup framework's
        # per-replica mutation_duplicator)
        dups = []
        for stub in src.stubs:
            for rep in stub._replicas.values():
                d = MutationDuplicator(
                    MetaResolver([dst.meta_addr], "dup"), cluster_id=1)
                rep.commit_hooks.append(d.on_commit)
                dups.append(d)
        for i in range(20):
            src_cli.set(b"d%d" % i, b"s", b"dv%d" % i)
        src_cli.delete(b"d0", b"s")
        for d in dups:
            assert d.flush(timeout=15)
        dst_cli = PegasusClient(MetaResolver([dst.meta_addr], "dup"))
        for i in range(1, 20):
            assert dst_cli.get(b"d%d" % i, b"s") == b"dv%d" % i, i
        assert dst_cli.get(b"d0", b"s") is None  # the delete shipped too
        for d in dups:
            d.stop()
        src_cli.close()
        dst_cli.close()
    finally:
        src.stop()
        dst.stop()


def test_duplicate_verify_timetag_lww(tmp_path):
    """A stale duplicate must not clobber a newer local write."""
    from pegasus_tpu.engine.server_impl import PegasusServer
    from pegasus_tpu.rpc import messages as msg, task_codes

    srv = PegasusServer(str(tmp_path / "db"), options=EngineOptions(backend="cpu"))
    key = key_schema.generate_key(b"h", b"s")
    now_us = int(time.time() * 1e6)
    d = srv.engine.last_committed_decree() + 1
    srv.on_batched_write_requests(
        d, now_us, [(task_codes.RPC_PUT, msg.UpdateRequest(key, b"local", 0))])
    stale = msg.DuplicateRequest(
        timestamp=now_us - 10_000_000, task_code=task_codes.RPC_PUT,
        raw_message=codec.encode(msg.UpdateRequest(key, b"stale", 0)),
        cluster_id=2, verify_timetag=True)
    r = srv.on_batched_write_requests(
        d + 1, now_us, [(task_codes.RPC_DUPLICATE, stale)])[0]
    assert r.error == 0 and "ignored" in r.error_hint
    assert srv.on_get(key).value == b"local"
    # a NEWER duplicate wins
    fresh = msg.DuplicateRequest(
        timestamp=now_us + 10_000_000, task_code=task_codes.RPC_PUT,
        raw_message=codec.encode(msg.UpdateRequest(key, b"fresh", 0)),
        cluster_id=2, verify_timetag=True)
    srv.on_batched_write_requests(
        d + 2, now_us, [(task_codes.RPC_DUPLICATE, fresh)])
    assert srv.on_get(key).value == b"fresh"
    srv.close()


# --------------------------------------------------------------- split

def test_partition_split_doubles_and_rebalances_keys(cluster):
    cli = cluster.create("sp", partitions=2)
    rows = {b"sp%d" % i: b"v%d" % i for i in range(40)}
    for hk, v in rows.items():
        cli.set(hk, b"s", v)
    r = cluster.ddl(RPC_CM_SPLIT_APP, mm.SplitAppRequest("sp"),
                    mm.SplitAppResponse)
    assert r.error == 0 and r.new_partition_count == 4
    # a fresh client sees 4 partitions and every key
    cli2 = PegasusClient(MetaResolver([cluster.meta_addr], "sp"))
    assert cli2.resolver.partition_count == 4
    for hk, v in rows.items():
        assert cli2.get(hk, b"s") == v, hk
    # new writes land on the doubled space
    for i in range(40, 60):
        cli2.set(b"sp%d" % i, b"s", b"v%d" % i)
        assert cli2.get(b"sp%d" % i, b"s") == b"v%d" % i
    # stale client re-routes transparently (partition-hash rejection path)
    for hk, v in rows.items():
        assert cli.get(hk, b"s") == v
    cli.close()
    cli2.close()


def test_split_stale_keys_gc_after_compact(cluster):
    cli = cluster.create("spgc", partitions=1)
    for i in range(30):
        cli.set(b"g%d" % i, b"s", b"v")
    cluster.ddl(RPC_CM_SPLIT_APP, mm.SplitAppRequest("spgc"), mm.SplitAppResponse)
    # manual compact every replica: stale halves disappear from storage
    total = 0
    app_id = None
    for stub in cluster.stubs:
        for (aid, pidx), rep in list(stub._replicas.items()):
            if rep.server.engine.opts.partition_mask:
                rep.server.engine.manual_compact()
    # count rows remaining per partition primary: each key exactly once
    cfg = cluster.ddl(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest("spgc"),
                      mm.QueryConfigResponse)
    seen = {}
    for stub in cluster.stubs:
        for (aid, pidx), rep in stub._replicas.items():
            if aid != cfg.app.app_id:
                continue
            if cfg.partitions[pidx].primary != stub.address:
                continue
            for k, _, _ in rep.server.engine.scan(b"", None, now=1):
                assert key_schema.key_hash(k) % 2 == pidx % 2
                seen[k] = seen.get(k, 0) + 1
    assert len(seen) == 30 and all(c == 1 for c in seen.values())
    cli.close()


# ------------------------------------------------------- backup/restore

def test_cold_backup_and_restore(cluster, tmp_path):
    cli = cluster.create("bk", partitions=2)
    for i in range(25):
        cli.set(b"bk%d" % i, b"s", b"bv%d" % i)
    backup_root = str(tmp_path / "backups")
    r = cluster.ddl(RPC_CM_BACKUP_APP,
                    mm.BackupAppRequest("bk", backup_root),
                    mm.BackupAppResponse)
    assert r.error == 0 and r.backup_id > 0
    # mutate after the backup; restore must show the backup-time view
    for i in range(25):
        cli.set(b"bk%d" % i, b"s", b"MUTATED")
    rr = cluster.ddl(RPC_CM_RESTORE_APP,
                     mm.RestoreAppRequest(backup_root, r.backup_id, "bk",
                                          "bk_restored"),
                     mm.RestoreAppResponse)
    assert rr.error == 0, rr.error_text
    rcli = PegasusClient(MetaResolver([cluster.meta_addr], "bk_restored"))
    for i in range(25):
        assert rcli.get(b"bk%d" % i, b"s") == b"bv%d" % i
    # original table unaffected
    assert cli.get(b"bk3", b"s") == b"MUTATED"
    cli.close()
    rcli.close()


def test_bulk_load_survives_primary_failover(cluster, tmp_path):
    """code-review r2: ingestion must replicate (same decree on every
    replica), not land only on the primary."""
    cli = cluster.create("blf", partitions=1)
    provider = tmp_path / "prov2"
    pdir = provider / "blf" / "1" / "0"
    pdir.mkdir(parents=True)
    bl.write_raw_set(str(pdir / "set.raw"),
                     [(b"fk%d" % i, b"s", b"fv%d" % i, 0) for i in range(15)])
    bl.write_metadata(str(provider), "blf", 1)
    r = cluster.ddl(RPC_CM_START_BULK_LOAD,
                    mm.StartBulkLoadRequest("blf", str(provider)),
                    mm.StartBulkLoadResponse)
    assert r.error == 0 and r.ingested_records == 15
    # kill the partition's primary node; data must survive on the promoted
    # secondary because ingestion committed through PacificA
    cfg = cluster.ddl(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest("blf"),
                      mm.QueryConfigResponse)
    victim = cfg.partitions[0].primary
    for stub in list(cluster.stubs):
        if stub.address == victim:
            stub.stop()
            cluster.stubs.remove(stub)
    cluster.meta.mark_node_dead(victim)
    for i in range(15):
        assert cli.get(b"fk%d" % i, b"s") == b"fv%d" % i, f"lost fk{i}"
    cli.close()


def test_geo_nul_bytes_in_keys(cluster):
    """code-review r2: geo index keys containing NUL parse exactly."""
    from pegasus_tpu.geo import GeoClient

    common = cluster.create("geo_nul_d", partitions=1)
    index = cluster.create("geo_nul_i", partitions=1)
    g = GeoClient(common, index)
    v = b"|".join([b"x", b"", b"", b"", b"121.4737", b"31.2304"])
    g.set(b"a\x00b", b"s\x00k", v)
    hits = g.search_radial(31.2304, 121.4737, 100)
    assert len(hits) == 1
    _, hk, sk, _ = hits[0]
    assert hk == b"a\x00b" and sk == b"s\x00k"
    common.close()
    index.close()


def test_covering_cells_large_radius_no_gaps():
    from pegasus_tpu.geo import cells as C

    # 50km radius at level 12 (~5km cells): every cell within the bbox of
    # the circle must be covered — check a ring of probe points
    got = set(C.covering_cells(31.0, 121.0, 50_000, 12))
    import math
    for ang in range(0, 360, 15):
        la = 31.0 + math.degrees(40_000 / C.EARTH_RADIUS_M) * math.sin(math.radians(ang))
        ln = 121.0 + math.degrees(40_000 / (C.EARTH_RADIUS_M * math.cos(math.radians(31)))) * math.cos(math.radians(ang))
        assert C.cell_id(la, ln, 12) in got, ang
