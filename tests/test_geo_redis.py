"""Geo client + redis proxy tests (reference: src/geo tests, redis_proxy_ut)."""

import socket

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.geo import GeoClient, LatlngCodec, cells
from pegasus_tpu.redis_proxy import RedisProxy
from tests.test_satellites import MiniCluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniCluster(tmp_path_factory.mktemp("georedis"), n_nodes=3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def geo(cluster):
    common = cluster.create("geo_data", partitions=2)
    index = cluster.create("geo_index", partitions=2)
    g = GeoClient(common, index, min_level=12)
    yield g
    common.close()
    index.close()


def val(lat, lng, name=b"x"):
    # '|'-separated value; codec defaults: lng at 4, lat at 5
    return b"|".join([name, b"", b"", b"", repr(lng).encode(), repr(lat).encode()])


def test_latlng_codec_roundtrip():
    c = LatlngCodec()
    v = c.encode(b"a|b", 31.23, 121.47)
    assert c.decode(v) == (31.23, 121.47)
    assert c.decode(b"no fields") is None
    assert c.decode(val(99.0, 0.0)) is None  # out of range


def test_morton_cells_share_prefixes():
    # nearby points share their level-12 cell far more often than distant ones
    a = cells.cell_id(31.2304, 121.4737, 12)
    b = cells.cell_id(31.2305, 121.4738, 12)
    c = cells.cell_id(-33.8688, 151.2093, 12)
    assert a == b != c
    assert cells.haversine_m(31.2304, 121.4737, 31.2305, 121.4738) < 20


def test_geo_set_get_search(geo):
    # a cluster of points in Shanghai + one far away
    pts = {
        b"p1": (31.2304, 121.4737),
        b"p2": (31.2310, 121.4745),
        b"p3": (31.2400, 121.4900),
        b"far": (39.9042, 116.4074),  # Beijing
    }
    for name, (lat, lng) in pts.items():
        geo.set(b"city", name, val(lat, lng, name))
    assert geo.get(b"city", b"p1") == val(*pts[b"p1"], name=b"p1")
    hits = geo.search_radial(31.2304, 121.4737, 2500)
    names = [sk for _, hk, sk, _ in hits]
    assert names[0] == b"p1"            # sorted by distance
    assert set(names) == {b"p1", b"p2", b"p3"}
    near = geo.search_radial(31.2304, 121.4737, 200)
    assert {sk for _, _, sk, _ in near} == {b"p1", b"p2"}
    # by-member + distance + count limit
    bym = geo.search_radial_by_key(b"city", b"p1", 2500, count=2)
    assert len(bym) == 2
    d = geo.distance(b"city", b"p1", b"city", b"far")
    assert 1000_000 < d < 1200_000      # Shanghai-Beijing ~1070km
    # delete removes from the index
    geo.delete(b"city", b"p2")
    after = geo.search_radial(31.2304, 121.4737, 200)
    assert {sk for _, _, sk, _ in after} == {b"p1"}


def test_covering_ranges_properties():
    lat, lng, radius = 31.2304, 121.4737, 800.0
    ranges = cells.covering_ranges(lat, lng, radius, 12, 16)
    # every range lies inside its ancestor cell, sorted and non-overlapping
    for anc, spans in ranges.items():
        if spans is None:
            continue
        lo = anc << (2 * (30 - 12))
        hi = (anc + 1) << (2 * (30 - 12))
        prev = lo
        for start, stop in spans:
            assert lo <= start < stop <= hi
            assert start >= prev
            prev = stop
    # the narrowed covering keeps every in-circle point reachable
    import random

    rnd = random.Random(7)
    for _ in range(300):
        # points across the circle incl. near-boundary
        ang = rnd.random() * 6.283185
        r = radius * rnd.random() ** 0.5
        import math

        pla = lat + math.degrees(r * math.cos(ang) / cells.EARTH_RADIUS_M)
        pln = lng + math.degrees(
            r * math.sin(ang) / (cells.EARTH_RADIUS_M
                                 * math.cos(math.radians(lat))))
        if cells.haversine_m(lat, lng, pla, pln) > radius:
            continue
        m = cells.morton(pla, pln)
        anc = m >> (2 * (30 - 12))
        spans = ranges.get(anc, "missing")
        assert spans != "missing"
        assert spans is None or any(s <= m < e for s, e in spans)
    # narrowing reads strictly less than whole-cell scans would
    spanned = sum(e - s for spans in ranges.values() if spans
                  for s, e in spans)
    whole = sum(1 << (2 * (30 - 12)) for spans in ranges.values()
                if spans is not None)
    assert spanned < whole or whole == 0


def test_covering_ranges_large_radius_complete():
    # radius big enough that the max_level covering hits MAX_COVERING_CELLS:
    # the whole-cell fallback must fire (the cap check runs BEFORE the
    # circle filter — checking after dropped ~32% of a 15km circle)
    import math
    import random

    lat, lng, radius = 40.06, 116.4, 15000.0
    ranges = cells.covering_ranges(lat, lng, radius, 12, 16)
    rnd = random.Random(3)
    for _ in range(400):
        ang = rnd.random() * 6.283185
        r = radius * rnd.random() ** 0.5
        pla = lat + math.degrees(r * math.cos(ang) / cells.EARTH_RADIUS_M)
        pln = lng + math.degrees(
            r * math.sin(ang) / (cells.EARTH_RADIUS_M
                                 * math.cos(math.radians(lat))))
        if cells.haversine_m(lat, lng, pla, pln) > radius:
            continue
        m = cells.morton(pla, pln)
        spans = ranges.get(m >> (2 * (30 - 12)))
        assert spans is None or any(s <= m < e for s, e in spans), \
            "in-circle point unreachable at 15km radius"


def test_search_radial_narrowed_matches_bruteforce(geo):
    import random

    rnd = random.Random(11)
    pts = {}
    for i in range(60):
        name = b"n%03d" % i
        pla = 30.0 + rnd.random() * 0.02     # ~2.2km box
        pln = 120.0 + rnd.random() * 0.02
        pts[name] = (pla, pln)
        geo.set(b"grid", name, val(pla, pln, name))
    center, radius = (30.01, 120.01), 600.0
    want = {n for n, (a, b) in pts.items()
            if cells.haversine_m(center[0], center[1], a, b) <= radius}
    hits = geo.search_radial(center[0], center[1], radius)
    got = {sk for _, hk, sk, _ in hits if hk == b"grid"}
    assert got == want
    # serial path returns the same thing as the threaded one
    geo.scan_threads, saved = 1, geo.scan_threads
    try:
        hits2 = geo.search_radial(center[0], center[1], radius)
    finally:
        geo.scan_threads = saved
    assert [h[2] for h in hits2 if h[1] == b"grid"] == \
           [h[2] for h in hits if h[1] == b"grid"]


@pytest.fixture(scope="module")
def redis_sock(cluster, geo):
    cli = cluster.create("redis_kv", partitions=2)
    proxy = RedisProxy(cli, geo=geo).start()
    sock = socket.create_connection(proxy.address, timeout=10)
    f = sock.makefile("rwb")
    yield f
    sock.close()
    proxy.stop()
    cli.close()


def resp(f, *args):
    out = b"*%d\r\n" % len(args)
    for a in args:
        a = a if isinstance(a, bytes) else str(a).encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    f.write(out)
    f.flush()
    return read_reply(f)


def read_reply(f):
    line = f.readline().rstrip(b"\r\n")
    t, rest = line[:1], line[1:]
    if t in (b"+", b"-"):
        return rest
    if t == b":":
        return int(rest)
    if t == b"$":
        n = int(rest)
        if n < 0:
            return None
        data = f.read(n + 2)[:-2]
        return data
    if t == b"*":
        n = int(rest)
        if n < 0:
            return None
        return [read_reply(f) for _ in range(n)]
    raise ValueError(line)


def test_redis_kv_commands(redis_sock):
    f = redis_sock
    assert resp(f, "PING") == b"PONG"
    assert resp(f, "SET", "rk1", "hello") == b"OK"
    assert resp(f, "GET", "rk1") == b"hello"
    assert resp(f, "GET", "missing") is None
    assert resp(f, "EXISTS", "rk1", "missing") == 1
    assert resp(f, "SETEX", "rk2", 500, "temp") == b"OK"
    ttl = resp(f, "TTL", "rk2")
    assert 490 < ttl <= 500
    # TTL and PTTL read the clock at different instants: a second boundary
    # between the two calls legitimately shaves one second off
    assert (ttl - 1) * 1000 <= resp(f, "PTTL", "rk2") <= ttl * 1000
    assert resp(f, "TTL", "rk1") == -1
    assert resp(f, "TTL", "missing") == -2
    assert resp(f, "INCR", "cnt") == 1
    assert resp(f, "INCRBY", "cnt", 10) == 11
    assert resp(f, "DECR", "cnt") == 10
    assert resp(f, "DECRBY", "cnt", 4) == 6
    assert resp(f, "DEL", "rk1", "missing") == 1
    assert resp(f, "GET", "rk1") is None
    assert b"unknown command" in resp(f, "FLUSHALL")


def test_redis_geo_commands(redis_sock):
    f = redis_sock
    assert resp(f, "GEOADD", "fleet", "121.4737", "31.2304", "car1",
                "121.4745", "31.2310", "car2") == 2
    pos = resp(f, "GEOPOS", "fleet", "car1", "nope")
    assert float(pos[0][0]) == pytest.approx(121.4737, abs=1e-4)
    assert float(pos[0][1]) == pytest.approx(31.2304, abs=1e-4)
    assert pos[1] is None
    dist = float(resp(f, "GEODIST", "fleet", "car1", "car2"))
    assert 50 < dist < 200
    members = resp(f, "GEORADIUS", "fleet", "121.4737", "31.2304", "500", "m")
    assert set(members) == {b"car1", b"car2"}
    members = resp(f, "GEORADIUSBYMEMBER", "fleet", "car1", "10", "m")
    assert members == [b"car1"]
