"""Batched multi-partition compaction: one dispatch, many partitions.

Differential contract: compact_partition_batch must be byte-equal to
per-partition compact_blocks over cached device runs, for mixed shapes
(grouped dispatches), per-partition split GC masks, and when the batch
axis shards across a multi-device mesh (the dp-over-partitions story).
"""

import numpy as np
import pytest

from pegasus_tpu.ops.batched_compact import (_compiled_batched_pipeline,
                                             compact_partition_batch)
from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,
                                     pack_run_device, sort_block)
from tests.test_compact_ops import make_block


def make_partition(seed, n, hk_space=120, k_runs=2):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        hk = b"p%05d" % rng.integers(0, hk_space)
        deleted = bool(rng.random() < 0.1)
        expire = int(rng.integers(0, 3)) * 40
        recs.append((hk, b"s%d" % (i % 4), b"" if deleted else b"v%d" % i,
                     expire, deleted))
    per = n // k_runs
    runs = [sort_block(make_block(recs[i * per:(i + 1) * per]),
                       CompactOptions(backend="cpu"))
            for i in range(k_runs)]
    device_runs = [pack_run_device(b) for b in runs]
    assert all(d is not None for d in device_runs)
    return runs, device_runs


@pytest.mark.parametrize("mesh_dp", [False, True])
def test_batched_matches_per_partition(mesh_dp):
    opts = CompactOptions(backend="tpu", now=60, bottommost=True,
                          runs_sorted=True)
    # 8 partitions: 6 share one shape signature, 2 are a different size
    jobs = []
    for pidx in range(6):
        runs, drs = make_partition(100 + pidx, 400)
        jobs.append((runs, drs, pidx))
    for pidx in (6, 7):
        runs, drs = make_partition(100 + pidx, 700)
        jobs.append((runs, drs, pidx))
    mesh = None
    if mesh_dp:
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:2])
        # the suite's conftest forces an 8-virtual-device CPU platform;
        # fail LOUDLY if that regresses — a size-1 mesh would make this
        # parametrization silently identical to mesh_dp=False
        assert len(devs) == 2, "need >=2 devices for the dp sharding test"
        mesh = Mesh(devs, ("dp",))
    outs = compact_partition_batch(jobs, opts, mesh=mesh)
    for (runs, drs, pidx), got in zip(jobs, outs):
        from dataclasses import replace

        want = compact_blocks(runs, replace(opts, pidx=pidx),
                              device_runs=drs)
        assert got.n == want.block.n
        np.testing.assert_array_equal(want.block.key_arena, got.key_arena)
        np.testing.assert_array_equal(want.block.val_arena, got.val_arena)
        np.testing.assert_array_equal(want.block.expire_ts, got.expire_ts)


def test_batched_per_partition_split_gc_mask():
    """pidx is a BATCHED argument: with a partition mask set, each row
    must drop exactly the keys its own partition no longer owns."""
    opts = CompactOptions(backend="tpu", now=60, bottommost=True,
                          runs_sorted=True, partition_mask=1)
    jobs = []
    for pidx in (0, 1):
        runs, drs = make_partition(7, 400)  # same seed: identical data
        jobs.append((runs, drs, pidx))
    outs = compact_partition_batch(jobs, opts)
    from dataclasses import replace

    for (runs, drs, pidx), got in zip(jobs, outs):
        want = compact_blocks(runs, replace(opts, pidx=pidx),
                              device_runs=drs)
        assert got.n == want.block.n
        np.testing.assert_array_equal(want.block.key_arena, got.key_arena)
    # the two partitions kept complementary halves
    assert outs[0].n + outs[1].n > 0
    h0 = set(outs[0].hash32.tolist())
    h1 = set(outs[1].hash32.tolist())
    assert all(h & 1 == 0 for h in h0)
    assert all(h & 1 == 1 for h in h1)


def test_batched_groups_share_compiled_programs():
    """Same shape signature across calls -> one compile, reused."""
    _compiled_batched_pipeline.cache_clear()
    opts = CompactOptions(backend="tpu", now=60, runs_sorted=True)
    for seed in (1, 2, 3):
        jobs = []
        for pidx in range(3):
            # varying real sizes within one pow2 bucket
            runs, drs = make_partition(seed * 10 + pidx, 300 + 40 * pidx)
            jobs.append((runs, drs, pidx))
        compact_partition_batch(jobs, opts)
    info = _compiled_batched_pipeline.cache_info()
    assert info.misses == 1 and info.hits == 2, info


def test_batched_applies_user_rules_and_default_ttl():
    """The batched path must run the same post passes as compact_blocks
    (user compaction rules, table default_ttl) — byte-equal outputs."""
    from dataclasses import replace

    from pegasus_tpu.engine.compaction_rules import \
        parse_user_specified_compaction

    ops = tuple(parse_user_specified_compaction(
        '{"ops": [{"type": "COT_DELETE", "params": "{}", "rules": '
        '[{"type": "FRT_SORTKEY_PATTERN", "params": '
        '"{\\"pattern\\": \\"s1\\", \\"match_type\\": '
        '\\"SMT_MATCH_PREFIX\\"}"}]}]}'))
    assert ops
    opts = CompactOptions(backend="tpu", now=60, runs_sorted=True,
                          user_ops=ops, default_ttl=500)
    jobs = []
    for pidx in range(3):
        runs, drs = make_partition(60 + pidx, 300)
        jobs.append((runs, drs, pidx))
    outs = compact_partition_batch(jobs, opts)
    for (runs, drs, pidx), got in zip(jobs, outs):
        want = compact_blocks(runs, replace(opts, pidx=pidx),
                              device_runs=drs)
        assert got.n == want.block.n
        np.testing.assert_array_equal(want.block.key_arena, got.key_arena)
        np.testing.assert_array_equal(want.block.val_arena, got.val_arena)
        # the rules dropped the s1 sortkeys and default_ttl stamped expire
        from pegasus_tpu.base.key_schema import restore_key

        for i in range(got.n):
            assert not restore_key(got.key(i))[1].startswith(b"s1")
        assert (got.expire_ts[~got.deleted] > 0).all()


def test_batched_chunks_oversized_groups():
    """A group bigger than max_device_records splits into several
    dispatches instead of one giant stacked allocation."""
    from dataclasses import replace

    opts = CompactOptions(backend="tpu", now=60, runs_sorted=True,
                          max_device_records=1500)
    jobs = []
    for pidx in range(6):  # same signature; padded total/job = 1024
        runs, drs = make_partition(80 + pidx, 400)
        jobs.append((runs, drs, pidx))
    outs = compact_partition_batch(jobs, opts)
    for (runs, drs, pidx), got in zip(jobs, outs):
        want = compact_blocks(runs, replace(opts, pidx=pidx),
                              device_runs=drs)
        assert got.n == want.block.n
        np.testing.assert_array_equal(want.block.key_arena, got.key_arena)


def test_stub_batched_manual_compact(tmp_path):
    """Node-level batched manual compaction: a stub's tpu replicas compact
    in batched dispatches with the same results as per-replica
    manual_compact (digest-equal), updating the finish-time meta."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions
    from pegasus_tpu.engine.db import META_LAST_MANUAL_COMPACT_FINISH_TIME
    from pegasus_tpu.replication.replica import Replica

    def fill(rep, pidx):
        for i in range(300):
            rep.server.engine.put(
                generate_key(b"bm%d" % (i % 41), b"s%05d" % i),
                SCHEMAS[2].generate_value(0, 0, b"v%d.%d" % (pidx, i)))
            if i % 80 == 79:
                rep.server.engine.flush()

    import hashlib

    def digest(eng):
        h = hashlib.sha256()
        with eng._lock:
            files = list(eng._l0) + [f for lv in sorted(eng._levels)
                                     for f in eng._levels[lv]]
        for sst in files:
            b = sst.block()
            h.update(b.key_arena.tobytes())
            h.update(b.val_arena.tobytes())
        return h.hexdigest()

    # lane A: batched through a stub-shaped object
    class FakeStub:
        _lock = __import__("threading").RLock()

    from pegasus_tpu.replication.replica_stub import ReplicaStub

    stub = FakeStub()
    stub._replicas = {}
    reps = {}
    for pidx in range(4):
        rep = Replica(f"n0", str(tmp_path / f"b{pidx}"), app_id=1,
                      pidx=pidx, options=EngineOptions(backend="tpu"))
        fill(rep, pidx)
        stub._replicas[(1, pidx)] = rep
        reps[pidx] = rep
    stats = ReplicaStub.batched_manual_compact(stub, now=100)
    assert stats["batched"] == 4 and stats["fallback"] == 0
    assert stats["output_records"] > 0
    digests_batched = {p: digest(reps[p].server.engine) for p in reps}
    for rep in reps.values():
        assert META_LAST_MANUAL_COMPACT_FINISH_TIME in \
            rep.server.engine.meta_store
        rep.close()
    # lane B: plain per-replica manual_compact on identical data
    for pidx in range(4):
        rep = Replica(f"n1", str(tmp_path / f"s{pidx}"), app_id=1,
                      pidx=pidx, options=EngineOptions(backend="tpu"))
        fill(rep, pidx)
        rep.server.engine.manual_compact(now=100)
        assert digest(rep.server.engine) == digests_batched[pidx], pidx
        rep.close()
