"""The collector as a real third app role in a multi-process onebox.

VERDICT-r2 item 9; reference src/server/pegasus_service_app.h:31-102 runs
info_collector as its own service app. Here the collector boots as a
separate PROCESS beside meta + replicas, publishes canary availability +
hotspot analysis over its own RPC port, survives SIGKILL + restart, and
auto-creates its probe table.
"""

import json
import os
import time

import pytest

from pegasus_tpu.client import MetaResolver, PegasusClient
from pegasus_tpu.rpc import codec
from pegasus_tpu.rpc.transport import RpcConnection, RpcError
from pegasus_tpu.runtime.remote_command import (RemoteCommandRequest,
                                                RemoteCommandResponse)
from tests.test_process_kill import ProcNode, _free_ports, _wait_nodes


def collector_command(port, command, args=(), timeout=5.0):
    conn = RpcConnection(("127.0.0.1", port))
    try:
        _, body = conn.call("RPC_CLI_CLI_CALL",
                            codec.encode(RemoteCommandRequest(command,
                                                              list(args))),
                            timeout=timeout)
        return codec.decode(RemoteCommandResponse, body).output
    finally:
        conn.close()


def wait_for(fn, timeout=30.0, interval=0.3):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = fn()
            if last:
                return last
        except (RpcError, OSError, ValueError):
            pass
        time.sleep(interval)
    return last


@pytest.mark.slow
def test_collector_app_role_canary_and_hotspot(tmp_path):
    root = str(tmp_path)
    meta_port, p1, p2, p3, cport = _free_ports(5)
    meta_list = f"127.0.0.1:{meta_port}"
    meta = ProcNode(root, "meta", "meta", meta_port, meta_list).start()
    replicas = [ProcNode(root, f"replica{i}", "replica", p, meta_list).start()
                for i, p in enumerate((p1, p2, p3), 1)]
    coll = ProcNode(root, "collector", "collector", cport, meta_list)
    # collector-specific knobs must land in ITS app section
    with open(coll.cfg) as f:
        cfg = f.read()
    cfg = cfg.replace("[apps.collector]\n",
                      "[apps.collector]\n"
                      "interval_seconds = 1.0\n"
                      "detect_interval_seconds = 0.4\n")
    with open(coll.cfg, "w") as f:
        f.write(cfg)
    coll.start()
    meta_addr = f"127.0.0.1:{meta_port}"
    try:
        assert _wait_nodes(meta_addr, 3)

        # --- the collector responds on its own RPC port as a server role
        info = wait_for(lambda: collector_command(cport, "server-info"))
        assert "collector" in info

        # --- canary: probe table auto-created, availability published.
        # Requires real SAMPLES: an empty window reads 1.0 and must not
        # count as proof of life (a dead canary looked "up" that way)
        def canary_up():
            out = json.loads(collector_command(cport, "collector-info"))
            av = out["availability"]
            ok = av.get("samples", 0) >= 3 and av["minute"] > 0.9
            return out if ok else None

        # pre-creation probe failures weigh the minute window down; give
        # the ratio time to recover past 0.9 (0.4s probes on a loaded box)
        out = wait_for(canary_up, timeout=60)
        assert out, f"canary never published: {out}"
        # the canary actually WRITES the probe table (result_writer role);
        # the table creation retry loop may lag the first canary rounds
        cli = wait_for(lambda: PegasusClient(
            MetaResolver([meta_addr], "test"), timeout=10))
        assert cli
        assert wait_for(
            lambda: cli.get(b"detect_available_result", b"last") is not None)

        # --- hotspot analysis: hammer one hashkey so its partition's qps
        # dwarfs the others across a collector scrape round
        hot = PegasusClient(MetaResolver([meta_addr], "test"), timeout=10)

        def hotspot_seen():
            for _ in range(400):
                hot.set(b"hotkey", b"s", b"v")
            out = json.loads(collector_command(cport, "collector-info"))
            return out["hotspots"].get("test") or None

        spots = wait_for(hotspot_seen, timeout=25)
        assert spots, "hotspot partitions never flagged"
        hot.close()

        # --- SIGKILL the collector: the serving cluster is unaffected,
        # and a restarted collector publishes again
        coll.kill9()
        cli.set(b"after_kill", b"s", b"x")
        assert cli.get(b"after_kill", b"s") == b"x"
        coll.start()
        out = wait_for(canary_up)
        assert out, "restarted collector never re-published"
        cli.close()
    finally:
        coll.stop()
        for r in replicas:
            r.stop()
        meta.stop()
