"""Chaos suite for the compaction lane guard (runtime/lane_guard.py).

Every fail point threaded through the pipeline is driven here with the
sleep()/raise() verbs: an injected device hang must be abandoned at the
deadline and fall back to the cpu backend with BYTE-EQUAL output; injected
transient errors must retry, then fall back; N consecutive failures must
open the circuit breaker, which re-probes via the watchdog before closing.
Everything is seeded-RNG deterministic and runs in tier-1 (not slow).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pegasus_tpu.base import consts
from pegasus_tpu.ops.compact import CompactOptions, compact_blocks
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.lane_guard import (LANE_GUARD, LaneDeadlineExceeded,
                                            LaneGuardConfig)
from pegasus_tpu.runtime.perf_counters import counters
from tests.test_compact_ops import _adversarial_records, make_block

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def guard():
    """Deterministic small-knob config; fail points armed; everything
    restored afterwards (LANE_GUARD is process-wide)."""
    saved_cfg, saved_probe = LANE_GUARD.config, LANE_GUARD.probe_fn
    LANE_GUARD.config = LaneGuardConfig(
        deadline_s=60.0, max_retries=1, backoff_base_s=0.001,
        backoff_max_s=0.002, breaker_threshold=2, breaker_cooldown_s=60.0)
    LANE_GUARD.probe_fn = lambda: True
    LANE_GUARD.reset()
    fp.setup()
    yield LANE_GUARD
    fp.teardown()
    LANE_GUARD.config, LANE_GUARD.probe_fn = saved_cfg, saved_probe
    LANE_GUARD.reset()


def _runs(seed=3, n=220, k=2):
    rng = np.random.default_rng(seed)
    return [make_block(_adversarial_records(rng, n)) for _ in range(k)]


def _assert_byte_equal(a, b):
    assert a.n == b.n
    np.testing.assert_array_equal(a.key_arena, b.key_arena)
    np.testing.assert_array_equal(a.val_arena, b.val_arena)
    np.testing.assert_array_equal(a.expire_ts, b.expire_ts)
    np.testing.assert_array_equal(a.deleted, b.deleted)


# ------------------------------------------------------- fail-point verbs


def test_sleep_and_raise_verbs():
    import time

    fp.setup()
    try:
        fp.cfg("chaos.sleep", "sleep(40)")
        t0 = time.perf_counter()
        assert fp.fail_point("chaos.sleep") is None  # sleeps, then continues
        assert time.perf_counter() - t0 >= 0.035
        fp.cfg("chaos.raise", "raise(boom)")
        with pytest.raises(fp.FailPointError, match="boom"):
            fp.fail_point("chaos.raise")
        # count modifier applies to the new verbs too
        fp.cfg("chaos.once", "1*raise(once)")
        with pytest.raises(fp.FailPointError):
            fp.fail_point("chaos.once")
        assert fp.fail_point("chaos.once") is None
    finally:
        fp.teardown()


# --------------------------------------------------- deadline + fallback


def test_injected_hang_deadline_abandons_and_falls_back(guard):
    """Acceptance: a fail-point-injected device hang completes via cpu
    fallback within deadline + backoff (no external kill), byte-identical
    to a clean cpu compaction, and the incident is visible in /metrics."""
    guard.config.deadline_s = 0.25
    runs = _runs(seed=5)
    opts = dict(now=100, bottommost=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    fp.cfg("compact.device", "1*sleep(1500)")
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    _assert_byte_equal(want.block, got.block)
    st = guard.state()
    assert st["deadline_abandons"] == 1
    assert st["fallbacks"] == 1
    assert st["retries"] == 0  # a wedge must NOT retry
    assert "device" in st["last_failure"]["error"]  # stage attribution
    # the incident is scrape-visible on /metrics
    from pegasus_tpu.collector.reporter import prometheus_text

    text = prometheus_text()
    assert "compact_lane_fallback_count" in text
    assert "compact_lane_deadline_abandon_count" in text


def test_transient_raise_retries_then_succeeds(guard):
    """One transient device error: bounded retry recovers ON DEVICE (no
    fallback), and the breaker's consecutive count resets."""
    runs = _runs(seed=7)
    opts = dict(now=100, bottommost=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    fp.cfg("compact.device", "1*raise(transient h2d glitch)")
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    _assert_byte_equal(want.block, got.block)
    st = guard.state()
    assert st["retries"] == 1
    assert st["fallbacks"] == 0
    assert st["breaker_consecutive_failures"] == 0  # success reset it


def test_raise_exhausts_retries_then_falls_back(guard):
    guard.config.breaker_threshold = 99  # isolate the retry/fallback path
    runs = _runs(seed=9)
    opts = dict(now=100, bottommost=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    fp.cfg("compact.device", "raise(device dead)")
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    _assert_byte_equal(want.block, got.block)
    st = guard.state()
    assert st["retries"] == 1  # max_retries=1 -> two attempts
    assert st["fallbacks"] == 1
    assert st["device_failures"] == 2


@pytest.mark.parametrize("point", ["compact.pack", "compact.h2d",
                                   "compact.gather"])
def test_every_stage_fail_point_falls_back_byte_equal(guard, point):
    """Chaos at every instrumented stage boundary: the guard's fallback
    contract holds no matter WHERE the device lane dies. Count-limited
    arming (2*) means both device attempts die and the cpu rerun is clean
    even for stages shared with the cpu lane (pack)."""
    runs = _runs(seed=11)
    opts = dict(now=100, bottommost=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    fp.cfg(point, "2*raise(chaos)")
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    _assert_byte_equal(want.block, got.block)
    assert guard.state()["fallbacks"] == 1


# ------------------------------------------------------- circuit breaker


def test_breaker_opens_cooldown_and_reprobes_before_closing(guard):
    probes = []

    def probe():
        probes.append(1)
        return probe_result[0]

    probe_result = [False]
    guard.probe_fn = probe
    runs = _runs(seed=13)
    opts = dict(now=100, bottommost=True)
    fp.cfg("compact.device", "raise(hard down)")
    # one guarded compaction = 2 attempts = 2 consecutive failures ->
    # threshold 2 trips the breaker
    compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    st = guard.state()
    assert st["breaker_open"] and st["breaker_trips"] == 1
    assert counters.number("compact.lane.breaker_open").value() == 1
    # cooldown active: routed straight to cpu, device NOT attempted
    failures_before = st["device_failures"]
    got = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    assert guard.state()["device_failures"] == failures_before
    assert guard.state()["fallbacks"] == 2
    assert not probes  # no re-probe while the cooldown is running
    want = compact_blocks(runs, CompactOptions(backend="cpu", **opts))
    _assert_byte_equal(want.block, got.block)
    # cooldown lapses -> half-open: a FAILING probe keeps it open
    guard._breaker_open_until = 0.0
    assert guard.breaker_open() is True
    assert len(probes) == 1
    assert guard.state()["breaker_cooldown_remaining_s"] > 0  # re-armed
    # a PASSING probe closes it and the device lane runs again
    guard._breaker_open_until = 0.0
    probe_result[0] = True
    assert guard.breaker_open() is False
    assert counters.number("compact.lane.breaker_open").value() == 0
    fp.cfg("compact.device", "off()")
    got2 = compact_blocks(runs, CompactOptions(backend="tpu", **opts))
    _assert_byte_equal(want.block, got2.block)
    assert guard.state()["breaker_consecutive_failures"] == 0


def test_nested_fallback_does_not_reset_breaker(guard):
    """A device_fn that 'succeeds' only because a NESTED guarded call fell
    back to cpu (sharded reassembly sorts re-enter compact_blocks) must
    not be credited as device health — the breaker still accumulates."""
    guard.config.breaker_threshold = 3

    def device_with_nested_degrade():
        guard.record_device_failure("nested", "inner lane died")
        return "ok"

    for _ in range(3):
        assert guard.run(device_with_nested_degrade, lambda: "cpu") == "ok"
    st = guard.state()
    assert st["breaker_open"] and st["breaker_trips"] == 1


def test_passive_breaker_check_never_probes(guard):
    """breaker_open(probe=False) — the engine write path's check — must
    stay open without running a half-open device probe, even after the
    cooldown lapsed; only a probing caller may close the breaker."""
    probes = []
    guard.probe_fn = lambda: probes.append(1) or True
    guard.record_device_failure("compact", "down")
    guard.record_device_failure("compact", "down")  # threshold 2: open
    guard._breaker_open_until = 0.0  # cooldown already lapsed
    assert guard.breaker_open(probe=False) is True
    assert not probes
    assert guard.breaker_open() is False  # the probing caller closes it
    assert len(probes) == 1


def test_capacity_local_failures_do_not_advance_breaker(guard):
    """Per-sst HBM prime OOMs are capacity-local, not device death: they
    are recorded but must never flap the breaker open."""
    for _ in range(5):
        guard.record_device_failure("device_run_prime", "RESOURCE_EXHAUSTED",
                                    breaker=False)
    st = guard.state()
    assert not st["breaker_open"]
    assert st["breaker_consecutive_failures"] == 0
    assert st["device_failures"] == 5


# --------------------------------------------------- pipelined blockwise


def _blockwise_runs(seed=5, n=400, k=2):
    from pegasus_tpu.ops.compact import sort_block

    rng = np.random.default_rng(seed)
    return [sort_block(make_block(_adversarial_records(rng, n)),
                       CompactOptions(backend="cpu")) for _ in range(k)]


def test_wedged_pipeline_prefetch_abandoned_cpu_rerun_byte_equal(guard):
    """Satellite (ISSUE 4): a wedged PREFETCH worker (armed at the
    compact.pipeline stage) stalls the pipelined blockwise lane; the lane
    guard's deadline abandons it WITHOUT deadlocking the drain — the
    serial cpu rerun completes promptly and byte-identical."""
    import time

    guard.config.deadline_s = 0.3
    runs = _blockwise_runs()
    base = dict(now=100, bottommost=True, runs_sorted=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **base))
    fp.cfg("compact.pipeline", "sleep(1500)")
    t0 = time.perf_counter()
    got = compact_blocks(runs, CompactOptions(
        backend="tpu", max_device_records=200, **base))
    elapsed = time.perf_counter() - t0
    _assert_byte_equal(want.block, got.block)
    st = guard.state()
    assert st["deadline_abandons"] == 1
    assert st["fallbacks"] == 1
    assert st["retries"] == 0  # a wedge must NOT retry
    # the cpu rerun did not wait out the 1.5s wedge: abandon + rerun
    # only (waiting it out would be >= 1.5 + rerun; the 0.9s scaled
    # deadline + rerun can brush 1.3 on a loaded 1-core box)
    assert elapsed < 1.45, elapsed
    # the stall was attributable (open pipeline.stall span in the
    # abandoned lane thread)
    assert "pipeline.stall" in st["last_failure"]["error"]


def test_pipeline_device_raise_drains_then_falls_back_byte_equal(guard):
    """A raising device stage inside the pipelined blockwise lane drains
    the in-flight prefetch workers (no deadlock), retries, then falls
    back to the serial cpu rerun byte-identically."""
    runs = _blockwise_runs(seed=21)
    base = dict(now=100, bottommost=True, runs_sorted=True)
    want = compact_blocks(runs, CompactOptions(backend="cpu", **base))
    fp.cfg("compact.device", "raise(pipelined lane down)")
    drains_before = counters.rate("compact.pipeline.drain_count")._value
    got = compact_blocks(runs, CompactOptions(
        backend="tpu", max_device_records=200, **base))
    _assert_byte_equal(want.block, got.block)
    st = guard.state()
    assert st["fallbacks"] == 1
    assert st["retries"] == 1  # transient-looking: the guard retried
    # both guarded attempts drained the pipeline before giving it back
    drained = counters.rate("compact.pipeline.drain_count")._value \
        - drains_before
    assert drained == 2, drained


# ------------------------------------------- batched + sharded call sites


def test_batched_wedged_prefetch_restacks_inline_no_hang(guard):
    """A wedged stacking prefetch in the batched path (which runs OUTSIDE
    any lane guard) must not hang compact_partition_batch: the bounded
    prefetch pickup abandons the worker at the lane deadline and the
    chunk re-stacks inline under its own guard, byte-equal."""
    import time

    from dataclasses import replace

    from pegasus_tpu.ops.batched_compact import compact_partition_batch
    from tests.test_batched_compact import make_partition

    guard.config.deadline_s = 0.3
    # max_device_records below 2x the per-job padded rows forces ONE job
    # per chunk -> 2 chunks -> the map actually pipelines (n > 1) and the
    # prefetch really rides a pool worker where compact.pipeline fires
    opts = CompactOptions(backend="tpu", now=60, bottommost=True,
                          runs_sorted=True, max_device_records=600)
    jobs = []
    for pidx in range(2):
        runs, drs = make_partition(70 + pidx, 250)
        assert sum(d.padded_len for d in drs) <= 600
        jobs.append((runs, drs, pidx))
    fp.cfg("compact.pipeline", "sleep(2000)")
    t0 = time.perf_counter()
    outs = compact_partition_batch(jobs, opts)
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.8, elapsed  # bounded by the deadline, not the wedge
    fp.cfg("compact.pipeline", "off()")
    for (runs, _, pidx), got in zip(jobs, outs):
        want = compact_blocks(runs, replace(opts, pidx=pidx, backend="cpu"))
        _assert_byte_equal(want.block, got)


def test_batched_compact_falls_back_byte_equal(guard):
    from dataclasses import replace

    from pegasus_tpu.ops.batched_compact import compact_partition_batch
    from tests.test_batched_compact import make_partition

    opts = CompactOptions(backend="tpu", now=60, bottommost=True,
                          runs_sorted=True)
    jobs = []
    for pidx in range(3):
        runs, drs = make_partition(50 + pidx, 300)
        jobs.append((runs, drs, pidx))
    fp.cfg("compact.device", "raise(vmap lane down)")
    outs = compact_partition_batch(jobs, opts)
    assert guard.state()["fallbacks"] >= 1
    fp.cfg("compact.device", "off()")
    for (runs, _, pidx), got in zip(jobs, outs):
        want = compact_blocks(runs, replace(opts, pidx=pidx, backend="cpu"))
        _assert_byte_equal(want.block, got)


def test_sharded_compact_block_falls_back_byte_equal(guard):
    from dataclasses import replace

    from pegasus_tpu.parallel import make_mesh, sharded_compact_block

    mesh = make_mesh(8)
    rng = np.random.default_rng(17)
    blocks = [make_block(_adversarial_records(rng, 250)) for _ in range(2)]
    opts = CompactOptions(backend="tpu", now=100, bottommost=True)
    fp.cfg("compact.device", "raise(collective wedged)")
    got = sharded_compact_block(blocks, mesh, opts)
    assert guard.state()["fallbacks"] >= 1
    fp.cfg("compact.device", "off()")
    want = compact_blocks(blocks, replace(opts, backend="cpu"))
    _assert_byte_equal(want.block, got.block)


# --------------------------------------------------- engine/service level


@pytest.fixture
def srv(tmp_path):
    from pegasus_tpu.engine import EngineOptions
    from pegasus_tpu.engine.server_impl import PegasusServer

    s = PegasusServer(str(tmp_path / "db"),
                      options=EngineOptions(backend="tpu"))
    yield s
    s.close()


def _fill(srv, n=40):
    from pegasus_tpu.base import key_schema

    for i in range(n):
        srv.engine.put(key_schema.generate_key(b"h", b"s%03d" % i),
                       b"\x82" + b"\0" * 12 + b"v%d" % i)


def test_manual_compact_survives_device_hang_and_reports(guard, srv):
    """Acceptance end-to-end: a device hang during manual compaction is
    abandoned at the deadline, the compaction completes via cpu fallback,
    and the incident is visible in query_compact_state, device-health,
    and /metrics."""
    from pegasus_tpu.engine.manual_compact_service import ManualCompactService
    from pegasus_tpu.ops.device_watchdog import WATCHDOG

    guard.config.deadline_s = 0.25
    guard.config.breaker_threshold = 99
    _fill(srv)
    svc = ManualCompactService(srv, mock_now=1000)
    fp.cfg("compact.device", "sleep(1200)")
    assert svc.start_manual_compact_if_needed(
        {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900"})
    # the data survived, served identically
    from pegasus_tpu.base import key_schema

    assert srv.engine.get(key_schema.generate_key(b"h", b"s000"),
                          now=50) is not None
    fp.cfg("compact.device", "off()")
    state = svc.query_compact_state()
    assert "idle; last finish" in state
    assert "cpu fallbacks:" in state
    assert guard.state()["deadline_abandons"] >= 1
    # device-health surfaces the lane guard state
    health = WATCHDOG.state()
    assert health["lane"]["fallbacks"] >= 1
    # the trace session survived the guard's worker-thread hop: the run
    # still records a per-stage breakdown
    assert svc.last_trace and "sst_write" in svc.last_trace


def test_failed_manual_compact_is_not_deduped_as_finished(guard, tmp_path):
    """Satellite: a raising compaction must NOT persist finish state (the
    once-trigger would be deduped as 'finished' and never retried); the
    failure surfaces in query_compact_state, and re-delivering the same
    trigger retries."""
    from pegasus_tpu.engine import EngineOptions
    from pegasus_tpu.engine.manual_compact_service import ManualCompactService
    from pegasus_tpu.engine.server_impl import PegasusServer

    s = PegasusServer(str(tmp_path / "db"),
                      options=EngineOptions(backend="cpu"))
    try:
        _fill(s)
        svc = ManualCompactService(s, mock_now=1000)
        envs = {consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY: "900"}
        fp.cfg("engine.sst_write", "1*raise(injected disk failure)")
        with pytest.raises(fp.FailPointError):
            svc.start_manual_compact_if_needed(envs)
        # finish state NOT recorded
        assert "pegasus_last_manual_compact_finish_time" \
            not in s.engine.meta_store
        assert svc.last_finish_time_ms == 0
        state = svc.query_compact_state()
        assert "FAILED" in state and "disk failure" in state
        # the SAME trigger retries now that the fault cleared
        svc.set_mock_now(1100)
        assert svc.start_manual_compact_if_needed(envs)
        assert s.engine.meta_store[
            "pegasus_last_manual_compact_finish_time"] == 1100
        assert "FAILED" not in svc.query_compact_state()
    finally:
        s.close()


# ------------------------------------------------------- the read lane


@pytest.fixture
def read_guard():
    from pegasus_tpu.runtime.lane_guard import READ_LANE_GUARD

    saved = READ_LANE_GUARD.config
    READ_LANE_GUARD.config = LaneGuardConfig(
        deadline_s=30.0, max_retries=1, backoff_base_s=0.001,
        backoff_max_s=0.002, breaker_threshold=2, breaker_cooldown_s=60.0)
    READ_LANE_GUARD.probe_fn = lambda: True
    READ_LANE_GUARD.reset()
    fp.setup()
    yield READ_LANE_GUARD
    fp.teardown()
    READ_LANE_GUARD.config = saved
    READ_LANE_GUARD.probe_fn = None
    READ_LANE_GUARD.reset()


def _read_engine(tmp_path):
    from pegasus_tpu.base import key_schema
    from pegasus_tpu.engine.db import EngineOptions, LsmEngine

    eng = LsmEngine(str(tmp_path / "rdb"), EngineOptions(
        backend="tpu", device_reads=True, device_read_min_batch=1,
        l0_compaction_trigger=100))
    for i in range(30):
        eng.put(key_schema.generate_key(b"h", b"s%03d" % i),
                b"\x82" + b"\0" * 12 + b"v%d" % i)
    eng.flush()
    with eng._lock:
        ssts = eng._all_ssts_locked()
    for s in ssts:
        eng._device_run_budgeted(s)
    keys = [key_schema.generate_key(b"h", b"s%03d" % i) for i in range(32)]
    return eng, keys


def test_wedged_device_read_abandons_and_serves_host_byte_equal(
        guard, read_guard, tmp_path):
    """Satellite chaos: a wedged device read is deadline-abandoned and
    the host fallback serves the identical answers — within the read
    deadline, not the wedge's duration."""
    import time

    read_guard.config.deadline_s = 0.25
    eng, keys = _read_engine(tmp_path)
    try:
        want = [eng.get(k, now=100) for k in keys]
        fp.cfg("read.device", "1*sleep(1500)")
        t0 = time.perf_counter()
        got = eng.get_batch(keys, now=100)
        elapsed = time.perf_counter() - t0
        assert got == want
        st = read_guard.state()
        assert st["deadline_abandons"] == 1
        assert st["fallbacks"] == 1
        assert st["retries"] == 0  # a wedge must NOT retry
        assert elapsed < 1.2, elapsed
    finally:
        eng.close()


def test_read_breaker_trips_without_opening_compact_lane(
        guard, read_guard, tmp_path):
    """Satellite: the read lane's breaker is ITS OWN — tripping it routes
    reads to the host walk while the compact lane stays closed and
    device compaction keeps running (and its counters stay untouched)."""
    eng, keys = _read_engine(tmp_path)
    try:
        fp.cfg("read.device", "raise(probe hard down)")
        # one guarded read batch = 2 attempts = threshold 2: breaker trips
        want = [eng.get(k, now=100) for k in keys]
        assert eng.get_batch(keys, now=100) == want
        st = read_guard.state()
        assert st["breaker_open"] and st["breaker_trips"] == 1
        assert counters.number("read.lane.breaker_open").value() == 1
        # breaker open: reads route straight to host, device NOT probed
        failures = st["device_failures"]
        assert eng.get_batch(keys, now=100) == want
        assert read_guard.state()["device_failures"] == failures
        # the COMPACT lane is untouched: breaker closed, no fallbacks,
        # and a device compaction still runs clean
        cst = guard.state()
        assert not cst["breaker_open"]
        assert cst["fallbacks"] == 0 and cst["device_failures"] == 0
        runs = _runs(seed=23)
        got = compact_blocks(runs, CompactOptions(
            backend="tpu", now=100, bottommost=True))
        want_c = compact_blocks(runs, CompactOptions(
            backend="cpu", now=100, bottommost=True))
        _assert_byte_equal(want_c.block, got.block)
        assert guard.state()["fallbacks"] == 0
    finally:
        eng.close()


def test_compact_breaker_does_not_block_device_reads(
        guard, read_guard, tmp_path):
    """The mirror isolation: a tripped COMPACT breaker must not push
    reads off already-resident runs (the read lane judges the device
    independently). Primes ride the compact lane's breaker, so residency
    is established BEFORE the trip — exactly the production shape: the
    data is on the chip, compactions degrade, reads keep serving."""
    eng, keys = _read_engine(tmp_path)
    guard.record_device_failure("compact", "down")
    guard.record_device_failure("compact", "down")  # threshold 2: open
    assert guard.state()["breaker_open"]
    try:
        before = counters.number("read.device.lookup_count").value()
        want = [eng.get(k, now=100) for k in keys]
        assert eng.get_batch(keys, now=100) == want
        assert counters.number("read.device.lookup_count").value() > before
        assert read_guard.state()["fallbacks"] == 0
    finally:
        eng.close()


# ------------------------------------------------------------- CI wiring


def test_fail_point_lint_clean():
    """tools/check_fail_points.py wired into the test run: every
    test-armed fail point exists in source, every source point is
    documented in README."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_fail_points.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr


def test_bench_degraded_line_carries_lane_state():
    """bench.py JSON: the degraded line's watchdog heartbeat includes the
    lane guard state, so BENCH_r06+ can't report a cpu-fallback run as a
    tpu number without the counters showing it."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PEGASUS_BENCH_N": "20000",
                "PEGASUS_BENCH_REPS": "1",
                "PEGASUS_BENCH_FAKE_LANE": "wedge",
                "PEGASUS_BENCH_LANE_S": "4"})
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=120,
                          env=env, cwd=REPO)
    lines = [l for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    assert proc.returncode == 0 and lines, proc.stderr[-500:]
    line = json.loads(lines[-1])
    assert line["value"] is None
    assert line["detail"]["watchdog"]["wedged_at_stage"] == "device"
