"""Server-layer unit tests: write service matrices + read handler semantics.

The fake-replica pattern (SURVEY.md §4.1): PegasusServer runs in-process
over a temp-dir engine, mutations fabricated as committed batches. Ports the
reference coverage of src/test/function_test/test_basic.cpp (CAS matrices),
pegasus_write_service_impl.h:179-258 (incr), :570-663 (cas check types).
"""

import pytest

from pegasus_tpu.base import consts, key_schema
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.engine import EngineOptions
from pegasus_tpu.engine.server_impl import (PegasusServer, RPC_CHECK_AND_MUTATE,
                                            RPC_CHECK_AND_SET, RPC_INCR,
                                            RPC_MULTI_PUT, RPC_MULTI_REMOVE,
                                            RPC_PUT, RPC_REMOVE)
from pegasus_tpu.rpc import messages as msg
from pegasus_tpu.rpc.messages import CasCheckType, FilterType, Status


@pytest.fixture
def srv(tmp_path):
    s = PegasusServer(str(tmp_path / "db"), app_id=1, pidx=0,
                      options=EngineOptions(backend="cpu"))
    yield s
    s.close()


def write(srv, code, req, now=None):
    d = srv.engine.last_committed_decree() + 1
    return srv.on_batched_write_requests(d, 1000, [(code, req)], now=now)[0]


def put(srv, hk, sk, value, expire=0):
    key = key_schema.generate_key(hk, sk)
    return write(srv, RPC_PUT, msg.UpdateRequest(key, value, expire))


def get(srv, hk, sk, now=None):
    r = srv.on_get(key_schema.generate_key(hk, sk), now=now)
    return None if r.error == Status.NOT_FOUND else r.value


# ------------------------------------------------------------------ batching

def test_batched_puts_and_removes_one_decree(srv):
    d = srv.engine.last_committed_decree() + 1
    reqs = [
        (RPC_PUT, msg.UpdateRequest(key_schema.generate_key(b"h", b"a"), b"1", 0)),
        (RPC_PUT, msg.UpdateRequest(key_schema.generate_key(b"h", b"b"), b"2", 0)),
        (RPC_REMOVE, msg.KeyRequest(key_schema.generate_key(b"h", b"a"))),
    ]
    resps = srv.on_batched_write_requests(d, 1000, reqs)
    assert len(resps) == 3 and all(r.error == Status.OK for r in resps)
    assert srv.engine.last_committed_decree() == d
    assert get(srv, b"h", b"a") is None
    assert get(srv, b"h", b"b") == b"2"


def test_empty_batch_advances_decree(srv):
    d = srv.engine.last_committed_decree() + 1
    assert srv.on_batched_write_requests(d, 0, []) == []
    assert srv.engine.last_committed_decree() == d


# -------------------------------------------------------------------- incr

def test_incr_semantics(srv):
    key = key_schema.generate_key(b"i", b"k")

    def incr(by, expire=0):
        return write(srv, RPC_INCR, msg.IncrRequest(key, by, expire))

    r = incr(10)
    assert (r.error, r.new_value) == (Status.OK, 10)
    r = incr(-4)
    assert r.new_value == 6
    # incr by 0 reads without writing
    r = incr(0)
    assert (r.error, r.new_value) == (Status.OK, 6)
    # non-numeric existing value
    put(srv, b"i", b"bad", b"xyz")
    r = write(srv, RPC_INCR, msg.IncrRequest(key_schema.generate_key(b"i", b"bad"), 1))
    assert r.error == Status.INVALID_ARGUMENT
    # overflow detection (reference :137-143)
    put(srv, b"i", b"max", str(2**63 - 1).encode())
    r = write(srv, RPC_INCR,
              msg.IncrRequest(key_schema.generate_key(b"i", b"max"), 1))
    assert r.error == Status.INVALID_ARGUMENT
    assert get(srv, b"i", b"max") == str(2**63 - 1).encode()


def test_incr_ttl_interaction(srv):
    key = key_schema.generate_key(b"i", b"ttl")
    now = 1000
    # create with ttl via expire>0
    r = write(srv, RPC_INCR, msg.IncrRequest(key, 1, now + 50), now=now)
    assert r.error == Status.OK
    assert srv.on_ttl(key, now=now).ttl_seconds == 50
    # expire=0 keeps existing ttl
    write(srv, RPC_INCR, msg.IncrRequest(key, 1, 0), now=now)
    assert srv.on_ttl(key, now=now).ttl_seconds == 50
    # expire<0 clears ttl
    write(srv, RPC_INCR, msg.IncrRequest(key, 1, -1), now=now)
    assert srv.on_ttl(key, now=now).ttl_seconds == -1


# ------------------------------------------------------------- CAS matrix

CAS_CASES = [
    # (check_type, existing value or None, operand, expect_pass)
    (CasCheckType.NO_CHECK, None, b"", True),
    (CasCheckType.VALUE_NOT_EXIST, None, b"", True),
    (CasCheckType.VALUE_NOT_EXIST, b"v", b"", False),
    (CasCheckType.VALUE_NOT_EXIST_OR_EMPTY, b"", b"", True),
    (CasCheckType.VALUE_NOT_EXIST_OR_EMPTY, b"v", b"", False),
    (CasCheckType.VALUE_EXIST, None, b"", False),
    (CasCheckType.VALUE_EXIST, b"", b"", True),
    (CasCheckType.VALUE_NOT_EMPTY, b"", b"", False),
    (CasCheckType.VALUE_NOT_EMPTY, b"v", b"", True),
    (CasCheckType.VALUE_MATCH_ANYWHERE, b"hello", b"ell", True),
    (CasCheckType.VALUE_MATCH_ANYWHERE, b"hello", b"xyz", False),
    (CasCheckType.VALUE_MATCH_PREFIX, b"hello", b"he", True),
    (CasCheckType.VALUE_MATCH_PREFIX, b"hello", b"lo", False),
    (CasCheckType.VALUE_MATCH_POSTFIX, b"hello", b"lo", True),
    (CasCheckType.VALUE_MATCH_POSTFIX, b"hello", b"he", False),
    (CasCheckType.VALUE_BYTES_LESS, b"abc", b"abd", True),
    (CasCheckType.VALUE_BYTES_LESS, b"abc", b"abc", False),
    (CasCheckType.VALUE_BYTES_LESS_OR_EQUAL, b"abc", b"abc", True),
    (CasCheckType.VALUE_BYTES_EQUAL, b"abc", b"abc", True),
    (CasCheckType.VALUE_BYTES_EQUAL, b"abc", b"abd", False),
    (CasCheckType.VALUE_BYTES_GREATER_OR_EQUAL, b"abd", b"abc", True),
    (CasCheckType.VALUE_BYTES_GREATER, b"abd", b"abc", True),
    (CasCheckType.VALUE_BYTES_GREATER, b"abc", b"abc", False),
    (CasCheckType.VALUE_INT_LESS, b"5", b"10", True),
    (CasCheckType.VALUE_INT_LESS, b"10", b"5", False),
    (CasCheckType.VALUE_INT_LESS_OR_EQUAL, b"10", b"10", True),
    (CasCheckType.VALUE_INT_EQUAL, b"-3", b"-3", True),
    (CasCheckType.VALUE_INT_GREATER_OR_EQUAL, b"10", b"10", True),
    (CasCheckType.VALUE_INT_GREATER, b"11", b"10", True),
    (CasCheckType.VALUE_INT_GREATER, b"10", b"10", False),
]


@pytest.mark.parametrize("ct,existing,operand,expect", CAS_CASES)
def test_check_and_set_matrix(srv, ct, existing, operand, expect):
    hk = b"cas%d" % int(ct)
    if existing is not None:
        put(srv, hk, b"ck", existing)
    r = write(srv, RPC_CHECK_AND_SET, msg.CheckAndSetRequest(
        hash_key=hk, check_sort_key=b"ck", check_type=ct,
        check_operand=operand, set_diff_sort_key=True, set_sort_key=b"out",
        set_value=b"WROTE"))
    if expect:
        assert r.error == Status.OK
        assert get(srv, hk, b"out") == b"WROTE"
    else:
        assert r.error == Status.TRY_AGAIN
        assert get(srv, hk, b"out") is None


def test_check_and_set_int_invalid_argument(srv):
    put(srv, b"casx", b"ck", b"notint")
    r = write(srv, RPC_CHECK_AND_SET, msg.CheckAndSetRequest(
        hash_key=b"casx", check_sort_key=b"ck",
        check_type=CasCheckType.VALUE_INT_EQUAL, check_operand=b"5",
        set_diff_sort_key=True, set_sort_key=b"out", set_value=b"x"))
    assert r.error == Status.INVALID_ARGUMENT


def test_check_and_set_same_sortkey_reads_old_value(srv):
    put(srv, b"cassame", b"k", b"old")
    r = write(srv, RPC_CHECK_AND_SET, msg.CheckAndSetRequest(
        hash_key=b"cassame", check_sort_key=b"k",
        check_type=CasCheckType.VALUE_BYTES_EQUAL, check_operand=b"old",
        set_diff_sort_key=False, set_sort_key=b"k", set_value=b"new",
        return_check_value=True))
    assert r.error == Status.OK
    assert r.check_value == b"old"
    assert get(srv, b"cassame", b"k") == b"new"


def test_check_and_mutate_multi_ops(srv):
    put(srv, b"cam", b"g", b"42")
    r = write(srv, RPC_CHECK_AND_MUTATE, msg.CheckAndMutateRequest(
        hash_key=b"cam", check_sort_key=b"g",
        check_type=CasCheckType.VALUE_INT_GREATER_OR_EQUAL, check_operand=b"40",
        mutate_list=[msg.Mutate(msg.MutateOperation.PUT, b"a", b"1", 0),
                     msg.Mutate(msg.MutateOperation.PUT, b"b", b"2", 0),
                     msg.Mutate(msg.MutateOperation.DELETE, b"g")]))
    assert r.error == Status.OK
    assert get(srv, b"cam", b"a") == b"1"
    assert get(srv, b"cam", b"b") == b"2"
    assert get(srv, b"cam", b"g") is None


def test_check_and_mutate_failed_check_mutates_nothing(srv):
    put(srv, b"cam2", b"g", b"1")
    r = write(srv, RPC_CHECK_AND_MUTATE, msg.CheckAndMutateRequest(
        hash_key=b"cam2", check_sort_key=b"g",
        check_type=CasCheckType.VALUE_INT_GREATER, check_operand=b"5",
        mutate_list=[msg.Mutate(msg.MutateOperation.PUT, b"a", b"1", 0)]))
    assert r.error == Status.TRY_AGAIN
    assert get(srv, b"cam2", b"a") is None


def test_check_and_mutate_empty_mutations_invalid(srv):
    r = write(srv, RPC_CHECK_AND_MUTATE, msg.CheckAndMutateRequest(
        hash_key=b"cam3", check_sort_key=b"g", check_type=CasCheckType.NO_CHECK,
        check_operand=b"", mutate_list=[]))
    assert r.error == Status.INVALID_ARGUMENT


# ---------------------------------------------------------------- multi_get

def fill_range(srv, hk, n=10):
    for i in range(n):
        put(srv, hk, b"s%02d" % i, b"v%02d" % i)


def test_multi_get_range_inclusivity(srv):
    fill_range(srv, b"mg")
    req = msg.MultiGetRequest(b"mg", start_sortkey=b"s02", stop_sortkey=b"s05",
                              start_inclusive=True, stop_inclusive=True)
    r = srv.on_multi_get(req)
    assert [kv.key for kv in r.kvs] == [b"s02", b"s03", b"s04", b"s05"]
    req = msg.MultiGetRequest(b"mg", start_sortkey=b"s02", stop_sortkey=b"s05",
                              start_inclusive=False, stop_inclusive=False)
    r = srv.on_multi_get(req)
    assert [kv.key for kv in r.kvs] == [b"s03", b"s04"]


def test_multi_get_sortkey_filter(srv):
    put(srv, b"mgf", b"aa1", b"x")
    put(srv, b"mgf", b"ab2", b"y")
    put(srv, b"mgf", b"bb3", b"z")
    req = msg.MultiGetRequest(b"mgf",
                              sort_key_filter_type=FilterType.MATCH_PREFIX,
                              sort_key_filter_pattern=b"a")
    r = srv.on_multi_get(req)
    assert {kv.key for kv in r.kvs} == {b"aa1", b"ab2"}
    req = msg.MultiGetRequest(b"mgf",
                              sort_key_filter_type=FilterType.MATCH_POSTFIX,
                              sort_key_filter_pattern=b"3")
    r = srv.on_multi_get(req)
    assert {kv.key for kv in r.kvs} == {b"bb3"}


def test_multi_get_forward_limit_keeps_first(srv):
    fill_range(srv, b"mgl")
    r = srv.on_multi_get(msg.MultiGetRequest(b"mgl", max_kv_count=4))
    assert r.error == Status.INCOMPLETE
    assert [kv.key for kv in r.kvs] == [b"s00", b"s01", b"s02", b"s03"]


def test_multi_get_reverse_limit_keeps_last_descending(srv):
    fill_range(srv, b"mgr")
    r = srv.on_multi_get(msg.MultiGetRequest(b"mgr", max_kv_count=4, reverse=True))
    assert r.error == Status.INCOMPLETE
    assert [kv.key for kv in r.kvs] == [b"s09", b"s08", b"s07", b"s06"]
    # complete reverse returns everything, descending
    r = srv.on_multi_get(msg.MultiGetRequest(b"mgr", reverse=True))
    assert r.error == Status.OK
    assert [kv.key for kv in r.kvs] == [b"s%02d" % i for i in range(9, -1, -1)]


def test_multi_get_reverse_with_limiter_returns_tail(srv):
    """code-review r2: the limiter budget must be spent from the range's
    END for reverse reads (the reference iterates Prev() from the stop)."""
    fill_range(srv, b"mgt", 50)
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "10"})
    r = srv.on_multi_get(msg.MultiGetRequest(b"mgt", max_kv_count=5, reverse=True))
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "1000"})
    assert r.error == Status.INCOMPLETE
    # the LAST sort keys, descending — not the head of the range
    assert [kv.key for kv in r.kvs] == [b"s49", b"s48", b"s47", b"s46", b"s45"]


def test_engine_reverse_scan_matches_forward(srv):
    fill_range(srv, b"revscan", 12)
    srv.engine.flush()
    fwd = [k for k, _, _ in srv.engine.scan(b"", None, now=1)]
    rev = [k for k, _, _ in srv.engine.scan(b"", None, now=1, reverse=True)]
    assert rev == list(reversed(fwd)) and len(fwd) >= 12


def test_multi_get_no_value(srv):
    fill_range(srv, b"mgnv", 3)
    r = srv.on_multi_get(msg.MultiGetRequest(b"mgnv", no_value=True))
    assert all(kv.value == b"" for kv in r.kvs) and len(r.kvs) == 3


def test_range_read_limiter_caps_iteration(srv):
    fill_range(srv, b"lim", 50)
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "10"})
    r = srv.on_multi_get(msg.MultiGetRequest(b"lim"))
    assert r.error == Status.INCOMPLETE
    assert len(r.kvs) < 50
    c = srv.on_sortkey_count(b"lim")
    assert c.error == Status.INCOMPLETE
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "1000"})


def test_get_scanner_hashkey_prefix_narrowing(srv):
    put(srv, b"pfx_a", b"s", b"1")
    put(srv, b"pfx_b", b"s", b"2")
    put(srv, b"other", b"s", b"3")
    req = msg.GetScannerRequest(hash_key_filter_type=FilterType.MATCH_PREFIX,
                                hash_key_filter_pattern=b"pfx_",
                                validate_partition_hash=False)
    r = srv.on_get_scanner(req)
    keys = {key_schema.restore_key(kv.key)[0] for kv in r.kvs}
    assert keys == {b"pfx_a", b"pfx_b"}


def test_ttl_expired_read_returns_not_found(srv):
    put(srv, b"exp", b"s", b"v", expire=100)
    assert get(srv, b"exp", b"s", now=99) == b"v"
    assert get(srv, b"exp", b"s", now=101) is None


def test_scan_limiter_partial_batches_resume(srv):
    """A sparse filter over a big range must not pin the read thread: the
    limiter yields partial (even empty) batches that resume by context."""
    for i in range(120):
        put(srv, b"scl", b"s%03d" % i, b"v")
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "25"})
    try:
        req = msg.GetScannerRequest(
            start_key=key_schema.generate_key(b"scl", b""),
            stop_key=key_schema.generate_next_bytes(b"scl"),
            batch_size=1000, validate_partition_hash=False,
            sort_key_filter_type=FilterType.MATCH_POSTFIX,
            sort_key_filter_pattern=b"7")  # 12 of 120 rows match
        r = srv.on_get_scanner(req)
        got = [kv.key for kv in r.kvs]
        rounds = 1
        while r.context_id >= 0:
            r = srv.on_scan(msg.ScanRequest(r.context_id))
            got.extend(kv.key for kv in r.kvs)
            rounds += 1
            assert rounds < 50
        assert rounds >= 4  # the 25-row budget forced several round trips
        from pegasus_tpu.base.key_schema import restore_key
        assert sorted(restore_key(k)[1] for k in got) == \
            sorted(b"s%03d" % i for i in range(120) if (b"s%03d" % i).endswith(b"7"))
    finally:
        srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "1000"})


def test_multi_get_prunes_files_by_hashkey_bloom(tmp_path):
    """VERDICT-r2 item 8: a hashkey-scoped range read on a cold multi-file
    table must load only the file(s) that can hold the hashkey — the
    reference's prefix-bloom pruning (hashkey_transform.h:31-60), which
    min/max-key overlap cannot provide when every file spans the keyspace."""
    from pegasus_tpu.runtime.perf_counters import counters

    path = str(tmp_path / "db")
    opts = EngineOptions(backend="cpu", l0_compaction_trigger=100)
    srv = PegasusServer(path, options=opts)
    # 8 hashkeys, one L0 file each; every file covers a wide sortkey range
    for h in range(8):
        for s in range(20):
            srv.engine.put(key_schema.generate_key(b"user%d" % h, b"sk%05d" % s),
                           SCHEMAS[2].generate_value(0, 0, b"v%d.%d" % (h, s)))
        srv.engine.flush()
    srv.close()
    # cold reopen: headers resident, blocks unloaded
    srv = PegasusServer(path, options=opts)
    assert srv.engine.stats()["l0_files"] == 8
    load = counters.rate("engine.sst_block_load")
    load._value = 0
    resp = srv.on_multi_get(msg.MultiGetRequest(hash_key=b"user3"))
    assert resp.error == Status.OK and len(resp.kvs) == 20
    assert load._value == 1, f"loaded {load._value} files, expected 1"
    # sortkey_count prunes identically
    load._value = 0
    r2 = srv.on_sortkey_count(b"user5")
    assert r2.count == 20
    assert load._value == 1
    # point gets were already pruned (regression guard)
    load._value = 0
    assert srv.on_get(key_schema.generate_key(b"user7", b"sk00001")).error == Status.OK
    assert load._value == 1
    srv.close()


def test_hash_scan_prunes_files_by_hashkey_bloom(tmp_path):
    """on_get_scanner detects a single-hashkey range (the client hash_scan
    shape) and bloom-prunes the file walk."""
    from pegasus_tpu.runtime.perf_counters import counters

    path = str(tmp_path / "db")
    opts = EngineOptions(backend="cpu", l0_compaction_trigger=100)
    srv = PegasusServer(path, options=opts)
    for h in range(6):
        for s in range(10):
            srv.engine.put(key_schema.generate_key(b"hk%d" % h, b"s%03d" % s),
                           SCHEMAS[2].generate_value(0, 0, b"x"))
        srv.engine.flush()
    srv.close()
    srv = PegasusServer(path, options=opts)
    load = counters.rate("engine.sst_block_load")
    load._value = 0
    req = msg.GetScannerRequest(
        start_key=key_schema.generate_key(b"hk2", b""),
        stop_key=key_schema.generate_next_bytes(b"hk2"),
        batch_size=100)
    resp = srv.on_get_scanner(req)
    assert len(resp.kvs) == 10
    assert load._value == 1, f"loaded {load._value} files, expected 1"
    srv.close()


def test_capacity_units_per_op_semantics(tmp_path):
    """Per-op CU accounting (reference capacity_unit_calculator.h:31-117):
    read-modify-write ops charge BOTH pools; multi-ops weigh hotkey capture
    by kv count; scans charge read CU without hotkey capture."""
    from pegasus_tpu.runtime.perf_counters import counters

    srv = PegasusServer(str(tmp_path / "db"), app_id=77, pidx=0,
                        options=EngineOptions(backend="cpu"))
    rcu = counters.rate("app.77.0.recent_read_cu")
    wcu = counters.rate("app.77.0.recent_write_cu")

    def delta(fn):
        r0, w0 = rcu._value, wcu._value
        fn()
        return rcu._value - r0, wcu._value - w0

    # plain put: write only
    r, w = delta(lambda: srv.write_service.put(
        1, msg.UpdateRequest(key_schema.generate_key(b"h", b"s"), b"v", 0)))
    assert r == 0 and w >= 1
    # incr: read + write
    r, w = delta(lambda: srv.write_service.incr(
        2, msg.IncrRequest(key_schema.generate_key(b"h", b"c"), 1)))
    assert r >= 1 and w >= 1
    # check_and_set: read + write
    req = msg.CheckAndSetRequest(
        hash_key=b"h", check_sort_key=b"s",
        check_type=CasCheckType.VALUE_EXIST,
        set_diff_sort_key=True, set_sort_key=b"s2", set_value=b"nv")
    r, w = delta(lambda: srv.write_service.check_and_set(3, req))
    assert r >= 1 and w >= 1
    # get: read only, and the per-op bytes counter moves
    gb = counters.rate("app.77.0.get_bytes")
    b0 = gb._value
    r, w = delta(lambda: srv.on_get(key_schema.generate_key(b"h", b"s")))
    assert r >= 1 and w == 0 and gb._value > b0
    srv.close()


def test_scan_session_survives_manual_compact(srv):
    """SURVEY §7 hard part (f): a scan session opened before a compaction
    must keep iterating its snapshot correctly after the compaction swaps
    and UNLINKS every input file mid-session — pinned-iterator semantics
    (the reference pins RocksDB iterators; here readers hold cached
    SSTable blocks across the swap)."""
    for i in range(80):
        put(srv, b"scc", b"s%03d" % i, b"v%d" % i)
    srv.engine.flush()
    for i in range(80, 160):
        put(srv, b"scc", b"s%03d" % i, b"v%d" % i)
    srv.engine.flush()
    srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "30"})
    try:
        req = msg.GetScannerRequest(
            start_key=key_schema.generate_key(b"scc", b""),
            stop_key=key_schema.generate_next_bytes(b"scc"),
            batch_size=25, validate_partition_hash=False)
        r = srv.on_get_scanner(req)
        got = [(kv.key, kv.value) for kv in r.kvs]
        compacted = False
        rounds = 0
        while r.context_id >= 0:
            if not compacted and len(got) >= 25:
                # mid-session: full manual compaction rewrites + unlinks
                # every file the scan context's snapshot points at
                srv.engine.manual_compact(now=1)
                # and a second write burst + flush + compact churns again
                for i in range(160, 200):
                    put(srv, b"scc", b"s%03d" % i, b"x")
                srv.engine.flush()
                srv.engine.manual_compact(now=1)
                compacted = True
            r = srv.on_scan(msg.ScanRequest(r.context_id))
            got.extend((kv.key, kv.value) for kv in r.kvs)
            rounds += 1
            assert rounds < 100
        assert compacted
        from pegasus_tpu.base.key_schema import restore_key

        # the session's snapshot: exactly the 160 pre-compaction rows, in
        # order, with their values intact (rows written mid-scan are not
        # required to appear — snapshot semantics)
        sks = [restore_key(k)[1] for k, _ in got]
        assert sks == sorted(sks)
        base = {b"s%03d" % i: b"v%d" % i for i in range(160)}
        for k, v in got:
            sk = restore_key(k)[1]
            if sk in base:
                assert v == base[sk], sk
        assert len([s for s in sks if s in base]) == 160
    finally:
        srv.update_app_envs({consts.ROCKSDB_ITERATION_THRESHOLD_COUNT: "1000"})
