"""Ecosystem-layer tests: service-app container, shell, collector,
reporter, hotkey detection — driven against a real in-process onebox."""

import io
import json
import time
import urllib.request

import pytest

from pegasus_tpu.collector import (AvailableDetector, CounterReporter,
                                   InfoCollector, hotspot_partitions,
                                   prometheus_text)
from pegasus_tpu.engine.hotkey_collector import (COARSE, FINE, FINISHED,
                                                 HotkeyCollector, STOPPED)
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.service_app import ServiceAppContainer
from pegasus_tpu.shell.main import Shell

ONEBOX_INI = """
[apps.meta]
type = meta
run = true
port = 0
state_dir = %{root}/meta

[apps.replica1]
type = replica
run = true
port = 0
data_dir = %{root}/replica1

[apps.replica2]
type = replica
run = true
port = 0
data_dir = %{root}/replica2

[apps.replica3]
type = replica
run = true
port = 0
data_dir = %{root}/replica3

[pegasus.server]
meta_servers = %{meta}

[failure_detector]
beacon_interval_seconds = 0.2
grace_seconds = 60
check_interval_seconds = 3600
"""


@pytest.fixture(scope="module")
def onebox(tmp_path_factory):
    root = tmp_path_factory.mktemp("toolbox")
    cfg_meta = Config(text=ONEBOX_INI, variables={"root": str(root), "meta": "x"})
    container = ServiceAppContainer(cfg_meta)
    container.start(only=["meta"])
    meta_addr = container.apps["meta"].address
    cfg_rest = Config(text=ONEBOX_INI,
                      variables={"root": str(root), "meta": meta_addr})
    container2 = ServiceAppContainer(cfg_rest)
    container2.start(only=["replica1", "replica2", "replica3"])
    time.sleep(0.3)  # beacons land
    yield meta_addr
    container2.stop()
    container.stop()


@pytest.fixture
def shell(onebox):
    out = io.StringIO()
    sh = Shell([onebox], out=out)
    return sh, out


def text(out):
    return out.getvalue()


def test_shell_ddl_and_data_ops(shell):
    sh, out = shell
    sh.run_line("create shelltest -p 4 -r 3")
    assert "succeed" in text(out)
    sh.run_line("use shelltest")
    sh.run_line("ls")
    assert "shelltest" in text(out)
    sh.run_line("app shelltest")
    assert "pidx" in text(out)
    sh.run_line('set user1 sk1 "hello world"')
    sh.run_line("get user1 sk1")
    assert "hello world" in text(out)
    sh.run_line("exist user1 sk1")
    sh.run_line("ttl user1 sk1")
    assert "no ttl" in text(out)
    sh.run_line("incr user1 counter 5")
    sh.run_line("multi_set mh a 1 b 2 c 3")
    sh.run_line("multi_get mh")
    assert '"a" : "1"' in text(out)
    sh.run_line("sortkey_count mh")
    sh.run_line("hash_scan mh")
    sh.run_line("multi_del mh a b")
    sh.run_line("del user1 sk1")
    sh.run_line("get user1 sk1")
    assert "not found" in text(out)


def test_shell_cluster_admin(shell):
    sh, out = shell
    sh.run_line("cluster_info")
    assert "node_count" in text(out)
    sh.run_line("nodes")
    assert "ALIVE" in text(out)
    sh.run_line("server_info")
    assert "pegasus-tpu" in text(out)
    sh.run_line("server_stat")


def test_shell_full_scan_and_copy(shell):
    sh, out = shell
    sh.run_line("create copysrc -p 2")
    sh.run_line("create copydst -p 2")
    sh.run_line("use copysrc")
    for i in range(6):
        sh.run_line(f"set h{i} s v{i}")
    sh.run_line("count_data")
    assert "6 rows" in text(out)
    sh.run_line("copy_data copydst")
    assert "copied 6 rows" in text(out)
    sh.run_line("use copydst")
    sh.run_line("get h3 s")
    assert "v3" in text(out)
    sh.run_line("full_scan")


def test_shell_envs_and_manual_compact(shell):
    sh, out = shell
    sh.run_line("create envtest -p 2")
    sh.run_line("use envtest")
    sh.run_line("set k s v")
    sh.run_line("set_app_envs rocksdb.usage_scenario prefer_write")
    assert "set 1 envs OK" in text(out)
    sh.run_line("get_app_envs")
    assert "prefer_write" in text(out)
    sh.run_line("manual_compact")
    assert "triggered" in text(out)
    sh.run_line("query_compact_state")
    assert "idle" in text(out) or "running" in text(out)


def test_shell_remote_and_counters(shell, onebox):
    sh, out = shell
    sh.run_line("create cnttest -p 2")
    sh.run_line("use cnttest")
    sh.run_line("set hot s v")
    nodes = [n.address for n in sh._nodes() if n.alive]
    sh.run_line(f"perf_counters {nodes[0]} app.")
    sh.run_line("remote_command all describe")
    assert "replicas" in text(out)


def test_hotkey_state_machine():
    hc = HotkeyCollector("read", coarse_threshold=50, fine_threshold=30)
    assert hc.state == STOPPED
    hc.start()
    assert hc.state == COARSE
    # one dominant key among background noise
    for i in range(200):
        hc.capture(b"HOT" if i % 2 == 0 else b"bg%d" % i)
    assert hc.state == FINISHED
    assert hc.result == b"HOT"
    assert b"HOT" in hc.query().encode()
    hc.stop()
    assert hc.state == STOPPED


def test_hotkey_uniform_load_finds_nothing():
    hc = HotkeyCollector("write", coarse_threshold=50)
    hc.start()
    for i in range(300):
        hc.capture(b"k%d" % i)
    assert hc.state in (COARSE, FINE)  # never FINISHED on uniform load


def test_detect_hotkey_via_shell(shell):
    sh, out = shell
    sh.run_line("create hottest -p 1 -r 3")
    sh.run_line("use hottest")
    cfg = sh._meta_call.__self__  # noqa: simple access below instead
    # find the node serving partition 0
    import pegasus_tpu.meta.messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_QUERY_CONFIG

    qc = sh._meta_call(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest("hottest"),
                       mm.QueryConfigResponse)
    node = qc.partitions[0].primary
    app_id = qc.app.app_id
    sh.run_line(f"detect_hotkey {node} {app_id}.0 read start")
    assert "started" in text(out)
    for i in range(300):
        sh.run_line("get hotkey1 s" if i % 2 == 0 else f"get cold{i} s")
    sh.run_line(f"detect_hotkey {node} {app_id}.0 read query")
    assert "hotkey1" in text(out)


def test_hotspot_partition_analysis():
    qps = {i: 10.0 for i in range(8)}
    assert hotspot_partitions(qps) == []
    qps[3] = 500.0
    assert hotspot_partitions(qps) == [3]


def test_counter_reporter_prometheus(onebox):
    from pegasus_tpu.runtime.perf_counters import counters

    counters.number("reporter.test_metric").set(42)
    rep = CounterReporter().start()
    try:
        host, port = rep.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5).read().decode()
        assert "reporter_test_metric 42.0" in body
        cjson = urllib.request.urlopen(
            f"http://{host}:{port}/counters", timeout=5).read().decode()
        assert json.loads(cjson)["reporter.test_metric"] == 42
    finally:
        rep.stop()


def test_info_collector_aggregates(onebox, shell):
    sh, out = shell
    sh.run_line("create colltest -p 2")
    sh.run_line("use colltest")
    for i in range(10):
        sh.run_line(f"set ck{i} s v")
        sh.run_line(f"get ck{i} s")
    coll = InfoCollector([onebox], interval_seconds=3600)
    summary = coll.collect_once()
    assert "colltest" in summary
    assert summary["colltest"]["get_qps"] >= 0
    coll.stop()


def test_available_detector_probe(onebox, shell):
    sh, _ = shell
    sh.run_line("create test -p 2")  # the canary's default table
    det = AvailableDetector([onebox], interval_seconds=3600)
    assert det.probe_once() is True
    rep = det.report()
    assert rep["minute"] == 1.0
    det.stop()
