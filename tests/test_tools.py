"""Ecosystem-layer tests: service-app container, shell, collector,
reporter, hotkey detection — driven against a real in-process onebox."""

import io
import json
import time
import urllib.request

import pytest

from pegasus_tpu.collector import (AvailableDetector, CounterReporter,
                                   InfoCollector, hotspot_partitions,
                                   prometheus_text)
from pegasus_tpu.engine.hotkey_collector import (COARSE, FINE, FINISHED,
                                                 HotkeyCollector, STOPPED)
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.service_app import ServiceAppContainer
from pegasus_tpu.shell.main import Shell

ONEBOX_INI = """
[apps.meta]
type = meta
run = true
port = 0
state_dir = %{root}/meta

[apps.replica1]
type = replica
run = true
port = 0
data_dir = %{root}/replica1

[apps.replica2]
type = replica
run = true
port = 0
data_dir = %{root}/replica2

[apps.replica3]
type = replica
run = true
port = 0
data_dir = %{root}/replica3

[pegasus.server]
meta_servers = %{meta}

[failure_detector]
beacon_interval_seconds = 0.2
grace_seconds = 60
check_interval_seconds = 3600
"""


@pytest.fixture(scope="module")
def onebox(tmp_path_factory):
    root = tmp_path_factory.mktemp("toolbox")
    cfg_meta = Config(text=ONEBOX_INI, variables={"root": str(root), "meta": "x"})
    container = ServiceAppContainer(cfg_meta)
    container.start(only=["meta"])
    meta_addr = container.apps["meta"].address
    cfg_rest = Config(text=ONEBOX_INI,
                      variables={"root": str(root), "meta": meta_addr})
    container2 = ServiceAppContainer(cfg_rest)
    container2.start(only=["replica1", "replica2", "replica3"])
    time.sleep(0.3)  # beacons land
    yield meta_addr
    container2.stop()
    container.stop()


@pytest.fixture
def shell(onebox):
    out = io.StringIO()
    sh = Shell([onebox], out=out)
    return sh, out


def text(out):
    return out.getvalue()


def test_shell_ddl_and_data_ops(shell):
    sh, out = shell
    sh.run_line("create shelltest -p 4 -r 3")
    assert "succeed" in text(out)
    sh.run_line("use shelltest")
    sh.run_line("ls")
    assert "shelltest" in text(out)
    sh.run_line("app shelltest")
    assert "pidx" in text(out)
    sh.run_line('set user1 sk1 "hello world"')
    sh.run_line("get user1 sk1")
    assert "hello world" in text(out)
    sh.run_line("exist user1 sk1")
    sh.run_line("ttl user1 sk1")
    assert "no ttl" in text(out)
    sh.run_line("incr user1 counter 5")
    sh.run_line("multi_set mh a 1 b 2 c 3")
    sh.run_line("multi_get mh")
    assert '"a" : "1"' in text(out)
    sh.run_line("sortkey_count mh")
    sh.run_line("hash_scan mh")
    sh.run_line("multi_del mh a b")
    sh.run_line("del user1 sk1")
    sh.run_line("get user1 sk1")
    assert "not found" in text(out)


def test_shell_cluster_admin(shell):
    sh, out = shell
    sh.run_line("cluster_info")
    assert "node_count" in text(out)
    sh.run_line("nodes")
    assert "ALIVE" in text(out)
    sh.run_line("server_info")
    assert "pegasus-tpu" in text(out)
    sh.run_line("server_stat")


def test_shell_full_scan_and_copy(shell):
    sh, out = shell
    sh.run_line("create copysrc -p 2")
    sh.run_line("create copydst -p 2")
    sh.run_line("use copysrc")
    for i in range(6):
        sh.run_line(f"set h{i} s v{i}")
    sh.run_line("count_data")
    assert "6 rows" in text(out)
    sh.run_line("copy_data copydst")
    assert "copied 6 rows" in text(out)
    sh.run_line("use copydst")
    sh.run_line("get h3 s")
    assert "v3" in text(out)
    sh.run_line("full_scan")


def test_shell_envs_and_manual_compact(shell):
    sh, out = shell
    sh.run_line("create envtest -p 2")
    sh.run_line("use envtest")
    sh.run_line("set k s v")
    sh.run_line("set_app_envs rocksdb.usage_scenario prefer_write")
    assert "set 1 envs OK" in text(out)
    sh.run_line("get_app_envs")
    assert "prefer_write" in text(out)
    sh.run_line("manual_compact")
    assert "triggered" in text(out)
    sh.run_line("query_compact_state")
    assert "idle" in text(out) or "running" in text(out)


def test_shell_remote_and_counters(shell, onebox):
    sh, out = shell
    sh.run_line("create cnttest -p 2")
    sh.run_line("use cnttest")
    sh.run_line("set hot s v")
    nodes = [n.address for n in sh._nodes() if n.alive]
    sh.run_line(f"perf_counters {nodes[0]} app.")
    sh.run_line("remote_command all describe")
    assert "replicas" in text(out)


def test_hotkey_state_machine():
    hc = HotkeyCollector("read", coarse_threshold=50, fine_threshold=30)
    assert hc.state == STOPPED
    hc.start()
    assert hc.state == COARSE
    # one dominant key among background noise
    for i in range(200):
        hc.capture(b"HOT" if i % 2 == 0 else b"bg%d" % i)
    assert hc.state == FINISHED
    assert hc.result == b"HOT"
    assert b"HOT" in hc.query().encode()
    hc.stop()
    assert hc.state == STOPPED


def test_hotkey_uniform_load_finds_nothing():
    hc = HotkeyCollector("write", coarse_threshold=50)
    hc.start()
    hc.max_seconds = 0.0
    hc._deadline = 0.0  # already past: next capture must self-terminate
    hc.capture(b"k")
    assert "STOPPED" in hc.query()
    hc = HotkeyCollector("write", coarse_threshold=50)
    hc.start()
    for i in range(300):
        hc.capture(b"k%d" % i)
    assert hc.state in (COARSE, FINE)  # never FINISHED on uniform load


def test_detect_hotkey_via_shell(shell):
    sh, out = shell
    sh.run_line("create hottest -p 1 -r 3")
    sh.run_line("use hottest")
    cfg = sh._meta_call.__self__  # noqa: simple access below instead
    # find the node serving partition 0
    import pegasus_tpu.meta.messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_QUERY_CONFIG

    qc = sh._meta_call(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest("hottest"),
                       mm.QueryConfigResponse)
    node = qc.partitions[0].primary
    app_id = qc.app.app_id
    sh.run_line(f"detect_hotkey {node} {app_id}.0 read start")
    assert "started" in text(out)
    for i in range(300):
        sh.run_line("get hotkey1 s" if i % 2 == 0 else f"get cold{i} s")
    sh.run_line(f"detect_hotkey {node} {app_id}.0 read query")
    assert "hotkey1" in text(out)


def test_hotspot_partition_analysis():
    qps = {i: 10.0 for i in range(8)}
    assert hotspot_partitions(qps) == []
    qps[3] = 500.0
    assert hotspot_partitions(qps) == [3]


class _FakeHotkeyNode:
    """Scripted detect_hotkey endpoint for the closed-loop driver."""

    def __init__(self, answers):
        self.answers = list(answers)
        self.calls = []

    def remote_command(self, addr, command, args):
        if command == "set-read-residency":
            # a read verdict drives the partition's device read residency
            # (PR 7); recorded like every other call
            self.calls.append((addr, (command,) + tuple(args)))
            return f"read residency {args[1]} for {args[0]}"
        assert command == "detect_hotkey"
        self.calls.append((addr, tuple(args)))
        action = args[2]
        if action == "start":
            return "read hotkey detection started (coarse)"
        if action == "stop":
            return "read hotkey detection stopped"
        return self.answers.pop(0)


def test_hotkey_loop_state_machine():
    """A partition flagged hotkey_rounds consecutive rounds gets the
    automatic detect_hotkey start/query/stop sequence; the verdict is
    republished as collector.app.<name>.hotkey.* counters."""
    from pegasus_tpu.runtime.perf_counters import counters

    coll = InfoCollector(["x:1"], hotkey_rounds=2)
    fake = _FakeHotkeyNode(["read detection state: FINE_DETECTING",
                            "read hotkey: b'HOT'"])
    coll.remote_command = fake.remote_command
    primaries = {3: "node-a:34801"}
    # round 1: flagged, streak below threshold -> nothing issued
    coll.drive_hotkey_loop("happ", 9, [3], primaries, {3: 100.0}, {3: 1.0})
    assert fake.calls == []
    # round 2: streak reaches 2 -> start (read kind: read qps dominates),
    # the first query follows in the same round and is unconverged
    coll.drive_hotkey_loop("happ", 9, [3], primaries, {3: 100.0}, {3: 1.0})
    assert fake.calls[0] == ("node-a:34801", ("9.3", "read", "start"))
    assert fake.calls[-1][1] == ("9.3", "read", "query")
    assert ("happ", 3) in coll._detections
    # round 3: verdict -> republished, detection stopped, streak cleared
    coll.drive_hotkey_loop("happ", 9, [3], primaries, {3: 100.0}, {3: 1.0})
    assert fake.calls[-1][1] == ("9.3", "read", "stop")
    assert ("happ", 3) not in coll._detections
    assert coll.hotkey_results["happ"][3]["key"] == "b'HOT'"
    assert coll.hotkey_results["happ"][3]["kind"] == "read"
    snap = counters.snapshot(prefix="collector.app.happ.hotkey.")
    assert snap["collector.app.happ.hotkey.3.hot"] == 1
    assert snap["collector.app.happ.hotkey.active_detections"] == 0
    assert snap["collector.app.happ.hotkey.found_count"] > 0
    # the read verdict drove the partition's device read residency on
    assert ("node-a:34801", ("set-read-residency", "9.3", "on")) in fake.calls
    assert ("happ", 3) in coll.read_residency
    # the partition calms: the verdict gauge must clear, not page forever
    # — and the residency pin is released with it
    coll.drive_hotkey_loop("happ", 9, [], primaries)
    snap = counters.snapshot(prefix="collector.app.happ.hotkey.")
    assert snap["collector.app.happ.hotkey.3.hot"] == 0
    assert ("node-a:34801", ("set-read-residency", "9.3", "off")) in fake.calls
    assert ("happ", 3) not in coll.read_residency
    coll.stop()


def test_hotkey_loop_survives_dead_or_moved_primary():
    """An unreachable node must not pin a detection forever (failed query
    rounds burn the query budget), and a moved primary abandons the
    detection so a fresh streak can restart it on the new node."""
    from pegasus_tpu.rpc.transport import RpcError

    coll = InfoCollector(["x:1"], hotkey_rounds=1, hotkey_query_limit=2)

    calls = []

    def unreachable(addr, command, args):
        calls.append(tuple(args))
        if args[2] == "start":
            return "read hotkey detection started (coarse)"
        raise RpcError(7, "connection refused")

    coll.remote_command = unreachable
    primaries = {0: "dead-node:1"}
    coll.drive_hotkey_loop("dapp", 4, [0], primaries)   # start + failed query
    assert ("dapp", 0) in coll._detections
    coll.drive_hotkey_loop("dapp", 4, [0], primaries)   # failed query 2
    coll.drive_hotkey_loop("dapp", 4, [0], primaries)   # over budget: expire
    assert ("dapp", 0) not in coll._detections

    # primary move: detection abandoned (stop goes to the OLD node)
    coll2 = InfoCollector(["x:1"], hotkey_rounds=1)
    fake = _FakeHotkeyNode(["read detection state: COARSE_DETECTING"])
    coll2.remote_command = fake.remote_command
    coll2.drive_hotkey_loop("mapp", 6, [0], {0: "node-a:1"})
    assert ("mapp", 0) in coll2._detections
    coll2.drive_hotkey_loop("mapp", 6, [0], {0: "node-b:1"})
    assert ("mapp", 0) not in coll2._detections
    assert fake.calls[-1] == ("node-a:1", ("6.0", "read", "stop"))
    coll.stop()
    coll2.stop()


def test_hotkey_loop_streak_resets_when_calm():
    coll = InfoCollector(["x:1"], hotkey_rounds=3)
    fake = _FakeHotkeyNode(["write detection state: COARSE_DETECTING"])
    coll.remote_command = fake.remote_command
    primaries = {0: "n:1"}
    coll.drive_hotkey_loop("capp", 5, [0], primaries)
    coll.drive_hotkey_loop("capp", 5, [0], primaries)
    coll.drive_hotkey_loop("capp", 5, [], primaries)   # calm round resets
    coll.drive_hotkey_loop("capp", 5, [0], primaries)
    coll.drive_hotkey_loop("capp", 5, [0], primaries)
    assert fake.calls == []  # never reached 3 consecutive rounds
    # write-dominant partitions get a write-kind detection
    coll.drive_hotkey_loop("capp", 5, [0], primaries, {0: 1.0}, {0: 50.0})
    assert fake.calls[0][1] == ("5.0", "write", "start")
    coll.stop()


def test_hotkey_loop_closed_against_live_node(shell):
    """End to end: the driver starts a REAL detection on the node serving
    the partition, hot traffic converges it, the next round publishes the
    verdict."""
    sh, out = shell
    sh.run_line("create hotloop -p 1 -r 3")
    sh.run_line("use hotloop")
    import pegasus_tpu.meta.messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_QUERY_CONFIG

    qc = sh._meta_call(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest("hotloop"),
                       mm.QueryConfigResponse)
    node, app_id = qc.partitions[0].primary, qc.app.app_id
    coll = InfoCollector(sh.meta_addrs, hotkey_rounds=1)
    try:
        coll.drive_hotkey_loop("hotloop", app_id, [0], {0: node},
                               {0: 500.0}, {0: 1.0})
        assert ("hotloop", 0) in coll._detections
        for i in range(300):  # one dominant key among noise
            sh.run_line("get hotkey1 s" if i % 2 == 0 else f"get cold{i} s")
        coll.drive_hotkey_loop("hotloop", app_id, [0], {0: node},
                               {0: 500.0}, {0: 1.0})
        assert coll.hotkey_results["hotloop"][0]["key"].startswith("b'hotkey1")
    finally:
        coll.stop()


def test_metric_names_lint_clean():
    """tools/check_metric_names.py wired into the test run: every counter
    name registered in source is documented in README.md's metric table."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(repo / "tools" / "check_metric_names.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr


def test_remote_commands_lint_clean():
    """tools/check_remote_commands.py wired into the test run: every
    registered remote command is documented in README.md's
    Remote-command table, and every table row still names a registered
    command (both directions, like the fail-point lint)."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(repo / "tools" / "check_remote_commands.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr


def test_remote_commands_lint_flags_undocumented(monkeypatch):
    """Both lint directions have teeth: an unregistered README row and an
    undocumented registration each produce an error."""
    from tools import check_remote_commands as cc

    real_src = cc.source_commands()
    monkeypatch.setattr(cc, "source_commands",
                        lambda: real_src | {"ghost-command"})
    errors = cc.run_lint()
    assert any("ghost-command" in e and "missing from README" in e
               for e in errors)
    monkeypatch.setattr(cc, "source_commands",
                        lambda: real_src - {"cluster-doctor"})
    errors = cc.run_lint()
    assert any("cluster-doctor" in e and "no matching registration" in e
               for e in errors)


def test_counter_reporter_prometheus(onebox):
    from pegasus_tpu.runtime.perf_counters import counters

    counters.number("reporter.test_metric").set(42)
    rep = CounterReporter().start()
    try:
        host, port = rep.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5).read().decode()
        assert "reporter_test_metric 42.0" in body
        cjson = urllib.request.urlopen(
            f"http://{host}:{port}/counters", timeout=5).read().decode()
        assert json.loads(cjson)["reporter.test_metric"] == 42
    finally:
        rep.stop()


def test_info_collector_aggregates(onebox, shell):
    sh, out = shell
    sh.run_line("create colltest -p 2")
    sh.run_line("use colltest")
    for i in range(10):
        sh.run_line(f"set ck{i} s v")
        sh.run_line(f"get ck{i} s")
    coll = InfoCollector([onebox], interval_seconds=3600)
    summary = coll.collect_once()
    assert "colltest" in summary
    assert summary["colltest"]["get_qps"] >= 0
    coll.stop()


def test_available_detector_probe(onebox, shell):
    sh, _ = shell
    sh.run_line("create test -p 2")  # the canary's default table
    det = AvailableDetector([onebox], interval_seconds=3600)
    assert det.probe_once() is True
    rep = det.report()
    assert rep["minute"] == 1.0
    det.stop()


def test_toollets_trace_profile_inject(onebox, shell):
    from pegasus_tpu.runtime import fail_points
    from pegasus_tpu.runtime.perf_counters import counters
    from pegasus_tpu.runtime.toollets import install_toollets
    from pegasus_tpu.rpc.transport import RpcServer, RpcConnection, RpcError
    from pegasus_tpu.runtime.remote_command import RemoteCommandService

    srv = RpcServer().start()
    cmds = RemoteCommandService()
    srv.register("RPC_TEST_ECHO", lambda h, b: b)
    srv.register("RPC_CLI_CLI_CALL", cmds.rpc_handler)
    tools = install_toollets(srv, ["tracer", "profiler", "fault_injector"],
                             command_service=cmds)
    conn = RpcConnection(srv.address)
    try:
        _, out = conn.call("RPC_TEST_ECHO", b"hello", timeout=5)
        assert out == b"hello"
        assert counters.snapshot()["profiler.RPC_TEST_ECHO.qps"] >= 0
        assert "RPC_TEST_ECHO" in tools["tracer"].dump()
        # fault injection drops the call
        fail_points.setup()
        fail_points.cfg("rpc.RPC_TEST_ECHO", "return()")
        import pytest as _pytest
        with _pytest.raises(RpcError):
            conn.call("RPC_TEST_ECHO", b"x", timeout=5)
        fail_points.teardown()
        _, out = conn.call("RPC_TEST_ECHO", b"ok", timeout=5)
        assert out == b"ok"
    finally:
        conn.close()
        srv.stop()


def test_slow_query_log_and_counter(tmp_path, capsys):
    from pegasus_tpu.base import consts, key_schema
    from pegasus_tpu.engine import EngineOptions
    from pegasus_tpu.engine.server_impl import PegasusServer
    from pegasus_tpu.runtime.perf_counters import counters

    srv = PegasusServer(str(tmp_path / "sq"), app_id=99, pidx=0,
                        options=EngineOptions(backend="cpu"))
    srv.update_app_envs({consts.ENV_SLOW_QUERY_THRESHOLD: "0"})
    srv.on_get(key_schema.generate_key(b"h", b"s"))
    # threshold 0 disables the log entirely
    assert "app.99.0.recent_abnormal_count" not in counters.snapshot()
    # a sub-microsecond threshold flags every get
    srv._app_envs[consts.ENV_SLOW_QUERY_THRESHOLD] = "-1"
    srv._check_slow_query("get", b"h", elapsed_us=50_000)  # forced sample
    srv.update_app_envs({consts.ENV_SLOW_QUERY_THRESHOLD: "1"})
    srv._check_slow_query("get", b"h", elapsed_us=50_000)
    assert counters.snapshot()["app.99.0.recent_abnormal_count"] >= 0
    assert "[slow-query]" in capsys.readouterr().out
    srv.close()


def test_offline_debuggers(tmp_path, shell):
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine.db import EngineOptions, LsmEngine
    from pegasus_tpu.replication.mutation_log import LogMutation, MutationLog

    sh, out = shell
    eng = LsmEngine(str(tmp_path / "ldb"), EngineOptions(backend="cpu"))
    for i in range(5):
        eng.put(generate_key(b"oh", b"s%d" % i),
                SCHEMAS[2].generate_value(0, 0, b"val%d" % i))
    eng.flush()
    sst = eng._l0[0].path
    sh.run_line(f"sst_dump {sst}")
    assert "records=5" in text(out)
    sh.run_line(f'local_get {tmp_path / "ldb"} oh s2')
    assert "val2" in text(out)
    log = MutationLog(str(tmp_path / "plog"))
    log.append(LogMutation(decree=1, codes=["RPC_RRDB_RRDB_PUT"], bodies=[b"x"]))
    log.close()
    sh.run_line(f'mlog_dump {tmp_path / "plog"}')
    assert "decree=1" in text(out)


def test_client_factory_singleton(onebox, shell):
    from pegasus_tpu.client import get_client

    sh, _ = shell
    sh.run_line("create facttest -p 2")
    c1 = get_client(onebox, "facttest")
    c2 = get_client([onebox], "facttest")
    assert c1 is c2
    c1.set(b"f", b"s", b"v")
    assert c2.get(b"f", b"s") == b"v"


def test_block_service_local_provider(tmp_path):
    from pegasus_tpu.runtime.block_service import create_block_service

    bs = create_block_service("local_service", str(tmp_path / "store"))
    src = tmp_path / "f.txt"
    src.write_bytes(b"hello")
    bs.upload(str(src), "backups/1/f.txt")
    assert bs.exists("backups/1/f.txt")
    assert bs.read("backups/1/f.txt") == b"hello"
    assert bs.list_dir("backups/1") == ["f.txt"]
    dst = tmp_path / "out" / "f.txt"
    bs.download("backups/1/f.txt", str(dst))
    assert dst.read_bytes() == b"hello"
    bs.write("direct/x.bin", b"\x00\x01")
    assert bs.read("direct/x.bin") == b"\x00\x01"
    import pytest as _p
    with _p.raises(ValueError):
        bs.upload(str(src), "../escape.txt")


def test_throttling_controller_parse_and_consume():
    from pegasus_tpu.engine.throttling import (ThrottleReject,
                                               ThrottlingController)

    t = ThrottlingController()
    assert t.parse_from_env("5*delay*0,8*reject*0")
    for _ in range(5):
        t.consume(1)          # under both thresholds
    t.consume(1)              # 6th: delayed (0ms — just counted)
    assert t.delayed_count == 1
    for _ in range(2):
        t.consume(1)
    try:
        t.consume(1)          # 9th: past reject threshold
        raise AssertionError("expected ThrottleReject")
    except ThrottleReject:
        pass
    assert t.rejected_count == 1
    # bare number = reject-only; malformed input keeps the old setting
    assert t.parse_from_env("3")
    assert t.reject_units == 3 and t.delay_units == 0
    assert not t.parse_from_env("nonsense*x*1")
    assert t.reject_units == 3
    assert t.parse_from_env("")   # empty disables
    assert not t.enabled


def test_trace_overhead_bench_smoke():
    """tools/trace_overhead_bench.py (ROADMAP: quantify tracing overhead
    before revisiting PEGASUS_TRACE_SAMPLE_EVERY): runs at a tiny N and
    emits sane per-span costs. The real numbers + guidance live in
    README's Observability section."""
    import tools.trace_overhead_bench as tob

    out = tob.run(n=500)
    assert set(out) == {"n", "stage_span_us", "stage_span_in_session_us",
                        "stage_event_us", "request_trace_us",
                        "table_ledger_us", "event_emit_us",
                        "history_sample_us"}
    for k, v in out.items():
        assert v > 0, (k, v)
    # a stage span must stay far below the stages it wraps (>=10ms each):
    # even on a loaded CI box, 1ms/span would mean the probe is broken
    assert out["stage_span_us"] < 1000, out
    # the flight recorder's emit rides transition edges of hot paths and
    # stays on in tier-1 — counter-increment territory, not span territory
    assert out["event_emit_us"] < 100, out
    # the tenant ledger bills every served request — same territory
    assert out["table_ledger_us"] < 100, out


def test_metric_lint_reverse_pass_flags_stale_rows(monkeypatch):
    """The reverse direction of tools/check_metric_names.py: README rows
    parse into wildcard name variants, and a row whose counter was
    deleted from source is flagged (a documented metric no scrape will
    ever return again)."""
    from tools import check_metric_names as cm

    rows = cm.readme_metric_rows()
    assert "rpc.server.qps" in rows                      # plain row
    assert "plog.append.group_size" in rows              # this PR's rows
    assert any(r.startswith("app.*") for r in rows)      # <holes> -> *
    monkeypatch.setattr(cm, "readme_metric_rows",
                        lambda: rows + ["ghost.deleted_counter_qps"])
    errs = cm.run_lint()
    assert any("ghost.deleted_counter_qps" in e for e in errs)


def test_fsck_clean_corrupt_and_orphan(tmp_path, capsys):
    """tools/fsck.py (ISSUE 17): the offline half of the integrity plane.
    Clean dir -> exit 0; a bit-flipped SST -> exit 1 with a typed
    `corrupt` finding; an orphan SST alone stays exit 0 (info, not rot);
    a MANIFEST reference to a missing file -> exit 1."""
    import glob
    import os
    import shutil

    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import SCHEMAS
    from pegasus_tpu.engine import EngineOptions, LsmEngine
    from tools.fsck import main as fsck_main

    d = str(tmp_path / "db")
    eng = LsmEngine(d, EngineOptions(backend="cpu"))
    for i in range(30):
        eng.put(generate_key(b"hk", b"sk%03d" % i),
                SCHEMAS[2].generate_value(0, 0, b"v%d" % i))
    eng.flush()
    eng.close()

    assert fsck_main([d]) == 0
    capsys.readouterr()

    ssts = sorted(glob.glob(os.path.join(d, "*.sst")))
    assert ssts
    # orphan: an unreferenced copy is waste, not rot -> still exit 0
    shutil.copy(ssts[0], os.path.join(d, "999999.sst"))
    assert fsck_main([d, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert any(f["kind"] == "orphan" and f["severity"] == "info"
               for f in out["findings"])

    # bit-flip -> error finding, exit 1, machine-readable shape
    size = os.path.getsize(ssts[0])
    with open(ssts[0], "r+b") as f:
        f.seek(size - 8)
        tail = f.read(8)
        f.seek(size - 8)
        f.write(bytes(b ^ 0xFF for b in tail))
    assert fsck_main([d, "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["errors"] >= 1
    assert any(f["kind"] == "corrupt" and f["path"] == ssts[0]
               for f in out["findings"])

    # walk mode: the node root finds the data dir below it; a missing
    # manifest reference is an error too
    os.remove(ssts[0])
    assert fsck_main([str(tmp_path)]) == 1
    err = capsys.readouterr().err
    assert "manifest_missing" in err
    assert fsck_main(["/nonexistent/fsck/root"]) == 1
