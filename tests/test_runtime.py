"""Runtime core tests: config/flags, fail points, counters, tasking."""

import threading
import time

import pytest

from pegasus_tpu.runtime import config as cfg_mod
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.perf_counters import PerfCounters
from pegasus_tpu.runtime.tasking import TaskPools, ThreadPool, Timer, define_task_code


def test_config_ini_and_substitution():
    text = """
[apps.replica]
name = replica
ports = 34801
run = true

[pegasus.server]
rocksdb_block_cache_capacity = 1024
ratio = 0.5
dirs = /data/a, /data/b
cluster = %{cluster.name}
"""
    c = Config(text=text, variables={"cluster.name": "onebox"})
    assert c.get_string("apps.replica", "name") == "replica"
    assert c.get_int("apps.replica", "ports") == 34801
    assert c.get_bool("apps.replica", "run") is True
    assert c.get_float("pegasus.server", "ratio") == 0.5
    assert c.get_list("pegasus.server", "dirs") == ["/data/a", "/data/b"]
    assert c.get_string("pegasus.server", "cluster") == "onebox"
    assert c.get_string("missing", "key", "dflt") == "dflt"


def test_flags_with_validator():
    cfg_mod.define_flag("test_flag_x", 10, validator=lambda v: v > 0)
    assert cfg_mod.get_flag("test_flag_x") == 10
    cfg_mod.set_flag("test_flag_x", 5)
    assert cfg_mod.get_flag("test_flag_x") == 5
    with pytest.raises(ValueError):
        cfg_mod.set_flag("test_flag_x", -1)
    with pytest.raises(KeyError):
        cfg_mod.set_flag("undefined_flag_y", 1)


def test_fail_points():
    fp.setup()
    try:
        fp.cfg("p1", "return(err)")
        assert fp.fail_point("p1") == ("return", "err")
        fp.cfg("p1", "off()")
        assert fp.fail_point("p1") is None
        # count-limited: exactly 2 triggers
        fp.cfg("p2", "2*return()")
        hits = sum(1 for _ in range(10) if fp.fail_point("p2"))
        assert hits == 2
        # probabilistic: ~10% of 2000
        fp.cfg("p3", "10%return()")
        hits = sum(1 for _ in range(2000) if fp.fail_point("p3"))
        assert 100 < hits < 320
        assert fp.fail_point("unarmed") is None
    finally:
        fp.teardown()
    assert fp.fail_point("p1") is None  # disabled after teardown


def test_perf_counters():
    pc = PerfCounters()
    pc.number("n").increment(3)
    assert pc.number("n").value() == 3
    v = pc.volatile_number("v")
    v.increment(5)
    assert v.value() == 5
    assert v.value() == 0  # reads reset
    p = pc.percentile("lat")
    for i in range(100):
        p.set(i)
    assert p.percentile(0.5) == 50
    assert p.percentile(0.99) == 99
    snap = pc.snapshot(prefix="n")
    assert snap == {"n": 3}
    assert "lat" in pc.snapshot(substr="a")


def test_thread_pool_executes_and_delays():
    pool = ThreadPool("t", 2)
    try:
        done = threading.Event()
        results = []
        pool.enqueue(lambda: (results.append(1), done.set()))
        assert done.wait(2)
        assert results == [1]
        t0 = time.monotonic()
        done2 = threading.Event()
        pool.enqueue(done2.set, delay_s=0.15)
        assert done2.wait(2)
        assert time.monotonic() - t0 >= 0.14
    finally:
        pool.stop()


def test_task_pools_and_timer():
    pools = TaskPools({"THREAD_POOL_DEFAULT": 1})
    try:
        code = define_task_code("LPC_TEST", pool="THREAD_POOL_DEFAULT")
        fired = []
        timer = pools.enqueue_timer(code, 0.05, lambda: fired.append(time.monotonic()))
        time.sleep(0.3)
        timer.cancel()
        n = len(fired)
        assert n >= 3
        time.sleep(0.15)
        assert len(fired) <= n + 1  # no further firing after cancel
    finally:
        pools.stop()


def test_priority_orders_runnable_tasks():
    pool = ThreadPool("prio", 1)
    try:
        gate = threading.Event()
        order = []
        done = threading.Event()
        pool.enqueue(gate.wait)  # hold the single worker
        for i in range(3):
            pool.enqueue(lambda i=i: order.append(("low", i)), priority=0)
        pool.enqueue(lambda: order.append(("high", 0)), priority=2)
        pool.enqueue(done.set, priority=0)
        gate.set()
        assert done.wait(2)
        assert order[0] == ("high", 0)
    finally:
        pool.stop()


def test_stop_discards_pending_and_returns_promptly():
    pool = ThreadPool("stopper", 1)
    ran = []
    pool.enqueue(lambda: ran.append(1), delay_s=60.0)
    t0 = time.monotonic()
    pool.stop()
    assert time.monotonic() - t0 < 2
    assert ran == []


def test_rate_counter_concurrent_scrapers_see_the_same_value():
    """Regression for the destructive RateCounter.value(): reading used
    to reset the window, so concurrent scrapers (/metrics, remote
    command, info collector) each saw a fraction of the true rate. Reads
    must be non-destructive: every scraper observes the same, non-zero
    value."""
    pc = PerfCounters()
    r = pc.rate("qps")
    r.MIN_WINDOW = 0.5
    for _ in range(100):
        r.increment()
    time.sleep(0.55)  # let the window become rollable
    barrier = threading.Barrier(4)
    seen = []

    def scrape():
        barrier.wait()
        seen.append(r.value())

    threads = [threading.Thread(target=scrape) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == 4
    assert all(v > 0 for v in seen), seen
    assert len(set(seen)) == 1, f"scrapers disagree: {seen}"
    # and the value survives yet another read (non-destructive)
    assert r.value() == seen[0]


def test_rate_counter_rolls_windows():
    pc = PerfCounters()
    r = pc.rate("roll")
    r.MIN_WINDOW = 0.05
    for _ in range(10):
        r.increment()
    time.sleep(0.06)
    first = r.value()
    assert first > 0
    # a later idle window decays the published rate toward 0
    time.sleep(0.06)
    assert r.value() == 0.0
    # idle-then-burst: a scrape milliseconds into the fresh window keeps
    # publishing the finished window (0), never _value/10ms spikes
    r.increment(5)
    assert r.value() == 0.0
    time.sleep(0.06)
    assert r.value() > 0


def test_percentile_snapshot_exports_full_quantile_dict():
    pc = PerfCounters()
    p = pc.percentile("lat_us")
    for i in range(1000):
        p.set(i)
    snap = pc.snapshot(prefix="lat_us")
    d = snap["lat_us"]
    assert set(d) == {"p50", "p90", "p95", "p99", "p999"}
    assert d["p50"] == 500 and d["p99"] == 990 and d["p999"] == 999
    assert d["p50"] <= d["p90"] <= d["p95"] <= d["p99"] <= d["p999"]


def test_counter_kind_collision_raises():
    pc = PerfCounters()
    pc.number("x")
    with pytest.raises(TypeError):
        pc.rate("x")


def test_config_empty_value_falls_back_to_default():
    c = Config(text="[s]\nk =\n")
    assert c.get_int("s", "k", 7) == 7
    assert c.get_float("s", "k", 1.5) == 1.5


def test_task_exception_does_not_kill_worker():
    pool = ThreadPool("t2", 1)
    try:
        pool.enqueue(lambda: 1 / 0)
        done = threading.Event()
        pool.enqueue(done.set)
        assert done.wait(2)
    finally:
        pool.stop()


def test_config_strips_inline_comments(tmp_path):
    from pegasus_tpu.runtime.config import Config

    p = tmp_path / "c.ini"
    p.write_text("[pegasus.server]\n"
                 "compaction_backend = tpu   # offload merges to the chip\n"
                 "meta_servers = 127.0.0.1:34601 ; primary meta\n")
    cfg = Config(str(p))
    assert cfg.get_string("pegasus.server", "compaction_backend", "") == "tpu"
    assert cfg.get_list("pegasus.server", "meta_servers", []) == \
        ["127.0.0.1:34601"]


def test_frame_reader_fragmented_and_large():
    """_FrameReader must parse frames regardless of how the kernel chops
    the byte stream: 1-byte drips, segment-straddling boundaries, frames
    bigger than the 64KB refill, and multiple frames per chunk."""
    import struct

    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcHeader, _FrameReader

    def make_frame(seq, body):
        h = codec.encode(RpcHeader(seq=seq, code="RPC_T"))
        payload = struct.pack("<I", len(h)) + h + body
        return struct.pack("<I", len(payload)) + payload

    bodies = [b"", b"x", b"y" * 10, b"z" * 200_000, b"tail"]
    stream = b"".join(make_frame(i, b) for i, b in enumerate(bodies))

    class FakeSock:
        """Feeds the stream in adversarial chunk sizes."""

        def __init__(self, data, sizes):
            self.data = data
            self.off = 0
            self.sizes = sizes
            self.i = 0

        def recv(self, n):
            if self.off >= len(self.data):
                return b""
            take = min(n, self.sizes[self.i % len(self.sizes)],
                       len(self.data) - self.off)
            self.i += 1
            chunk = self.data[self.off : self.off + take]
            self.off += take
            return chunk

    for sizes in ([1], [3, 7, 11], [65536], [5, 100000], [2, 65536, 9]):
        r = _FrameReader(FakeSock(stream, sizes))
        for i, body in enumerate(bodies):
            header, got = r.frame()
            assert header.seq == i and got == body, (sizes, i)
        # stream exhausted -> peer-closed surfaces as ConnectionError
        import pytest as _pytest

        with _pytest.raises(ConnectionError):
            r.frame()
