"""Self-healing data integrity (ISSUE 17): typed corruption errors,
background scrub, quarantine, and audit-driven re-seed.

Pinned here:
  - every on-disk failure mode (zero-length, bad magic, truncated,
    bit-flipped) surfaces as a typed CorruptionError — never a raw
    struct.error or JSONDecodeError — at open AND mid-read;
  - legacy pre-checksum headers stay readable (upgrade compatibility);
  - engine scrub reports findings without acting, keeps chaos-injected
    scrub faults (`scrub.verify`) out of the findings list, and never
    touches the lane guards' breakers;
  - the full onebox drill: corrupt one replica's SST on disk ->
    scrub detects -> replica quarantined (forensics dir + QUARANTINED
    beacon) -> meta re-seeds -> zero wrong reads throughout;
  - the collector auto-healer's interlocks: off by default, acts only
    on a critical verdict whose audit evidence isolates EXACTLY ONE odd
    replica, rate-limited — plus the end-to-end audit-driven heal.
"""

import glob
import json
import os
import struct
import time

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import SCHEMAS
from pegasus_tpu.collector.auto_heal import AUTO_HEALER, AutoHealer
from pegasus_tpu.collector.cluster_doctor import (run_cluster_audit,
                                                  run_cluster_doctor)
from pegasus_tpu.engine import EngineOptions, LsmEngine
from pegasus_tpu.engine.sstable import (MAGIC, CorruptionError, read_sst,
                                        verify_sst)
from pegasus_tpu.meta import messages as mm
from pegasus_tpu.meta.meta_server import RPC_CM_QUERY_CONFIG
from pegasus_tpu.runtime import fail_points as fp
from pegasus_tpu.runtime.lane_guard import LANE_GUARD, READ_LANE_GUARD
from pegasus_tpu.runtime.perf_counters import counters

from tests.test_satellites import MiniCluster


def enc(payload: bytes, expire: int = 0) -> bytes:
    return SCHEMAS[2].generate_value(expire, 0, payload)


def make_filled_engine(path, n=60):
    eng = LsmEngine(str(path), EngineOptions(backend="cpu"))
    keys = []
    for i in range(n):
        k = generate_key(b"hk%d" % (i % 5), b"sk%04d" % i)
        eng.put(k, enc(b"val%d" % i))
        keys.append((k, enc(b"val%d" % i)))
    eng.flush()
    return eng, keys


def ssts_in(path) -> list:
    return sorted(glob.glob(os.path.join(str(path), "*.sst")),
                  key=os.path.getmtime)


def flip_tail(path: str, nbytes: int = 8) -> None:
    """Corrupt the end of the payload (the last section's bytes) so the
    header still parses and the finding is a crc mismatch, like real rot."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - nbytes)
        tail = f.read(nbytes)
        f.seek(size - nbytes)
        f.write(bytes(b ^ 0xFF for b in tail))


def strip_crcs(path: str) -> None:
    """Rewrite the header WITHOUT crc32 keys — the on-disk shape every
    pre-checksum SST in an upgraded cluster still has."""
    with open(path, "rb") as f:
        assert f.read(len(MAGIC)) == MAGIC
        (hlen,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hlen))
        payload = f.read()
    for sec in header["sections"].values():
        sec.pop("crc32", None)
    raw = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(raw)))
        f.write(raw)
        f.write(payload)


# ------------------------------------------------- typed corruption matrix


def test_corruption_matrix_at_open(tmp_path):
    """Every open-time failure mode is a typed CorruptionError carrying
    path + detail — never a raw struct.error / JSONDecodeError."""
    eng, _ = make_filled_engine(tmp_path / "db")
    eng.close()
    good = ssts_in(tmp_path / "db")[-1]

    cases = {}
    z = tmp_path / "zero.sst"
    z.write_bytes(b"")
    cases["zero-length"] = str(z)
    m = tmp_path / "magic.sst"
    m.write_bytes(b"NOTANSST" + b"\x00" * 64)
    cases["bad-magic"] = str(m)
    th = tmp_path / "trunc_hdr.sst"
    th.write_bytes(MAGIC + struct.pack("<I", 4096) + b"{\"n\": 1")
    cases["truncated-header"] = str(th)
    uh = tmp_path / "unparseable.sst"
    uh.write_bytes(MAGIC + struct.pack("<I", 8) + b"not json")
    cases["unparseable-header"] = str(uh)
    ts = tmp_path / "trunc_sec.sst"
    raw = open(good, "rb").read()
    ts.write_bytes(raw[: len(raw) - len(raw) // 4])
    cases["truncated-section"] = str(ts)
    fl = tmp_path / "flipped.sst"
    fl.write_bytes(raw)
    flip_tail(str(fl))
    cases["bit-flip"] = str(fl)

    for name, path in cases.items():
        with pytest.raises(CorruptionError) as ei:
            verify_sst(path)
        assert ei.value.path == path, name
        assert ei.value.detail, name
        with pytest.raises(CorruptionError):
            read_sst(path)
    with pytest.raises(CorruptionError) as ei:
        verify_sst(cases["bit-flip"])
    assert "crc32 mismatch" in ei.value.detail


def test_corruption_mid_read_is_typed_and_hooked(tmp_path):
    """Corruption that lands AFTER open (header cached, block not yet
    materialized): the serving read raises the typed error and fires the
    engine's corruption hook exactly as the stub's quarantine path needs."""
    eng, keys = make_filled_engine(tmp_path / "db")
    eng.close()
    flip_tail(ssts_in(tmp_path / "db")[-1])

    eng2 = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    seen = []
    eng2.corruption_hook = seen.append
    before = counters.rate("engine.corruption_count").total()
    with pytest.raises(CorruptionError):
        eng2.get(keys[0][0], now=10)
    assert seen and isinstance(seen[0], CorruptionError)
    assert counters.rate("engine.corruption_count").total() > before
    eng2.close()


def test_legacy_header_without_crc_stays_readable(tmp_path):
    """Upgrade pin: SSTs written before per-section checksums carry no
    crc32 keys — they read and verify structurally, unchecked."""
    eng, keys = make_filled_engine(tmp_path / "db", n=20)
    eng.close()
    sst = ssts_in(tmp_path / "db")[0]
    block0, _ = read_sst(sst)
    strip_crcs(sst)
    block1, header = read_sst(sst)
    assert all("crc32" not in s for s in header["sections"].values())
    assert block1.n == block0.n
    assert verify_sst(sst) > 0
    # and the engine itself reopens + serves the legacy file
    eng2 = LsmEngine(str(tmp_path / "db"), EngineOptions(backend="cpu"))
    assert eng2.get(keys[0][0], now=10) == keys[0][1]
    eng2.close()


# ----------------------------------------------------------- engine scrub


def test_scrub_clean_then_finds_corruption(tmp_path):
    eng, _ = make_filled_engine(tmp_path / "db")
    try:
        res = eng.scrub()
        assert res["files"] >= 1 and res["bytes"] > 0
        assert res["findings"] == [] and res["errors"] == []
        victim = ssts_in(tmp_path / "db")[-1]
        flip_tail(victim)
        res = eng.scrub()
        assert any(f["path"] == victim and "crc32 mismatch" in f["detail"]
                   for f in res["findings"]), res
    finally:
        eng.close()


def test_scrub_failpoint_is_an_error_not_a_finding(tmp_path):
    """Chaos interlock: an injected `scrub.verify` fault means the file
    was NOT verified — it must land in `errors` (retry next cadence),
    never in `findings` (a finding quarantines the healthy replica)."""
    eng, _ = make_filled_engine(tmp_path / "db")
    fp.setup()
    try:
        fp.cfg("scrub.verify", "raise(chaos)")
        res = eng.scrub()
        assert res["findings"] == []
        assert res["errors"] and all("chaos" in e["detail"]
                                     for e in res["errors"])
        fp.cfg("scrub.verify", "off()")
        res = eng.scrub()
        assert res["errors"] == [] and res["findings"] == []
    finally:
        fp.teardown()
        eng.close()


# ----------------------------------------------------- onebox heal drills


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(tmp_path)
    yield c
    c.stop()


@pytest.fixture
def failpoints():
    fp.setup()
    yield fp
    fp.teardown()


def _members(cluster, app_name, pidx):
    cfg = cluster.ddl(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest(app_name),
                      mm.QueryConfigResponse)
    pc = cfg.partitions[pidx]
    return cfg.app.app_id, pc.primary, list(pc.secondaries)


def _drive_heal(cluster, stub, gpid, app_name, pidx, deadline_s=60.0):
    """Meta repair loop (what the MetaApp FD tick does in production):
    reconfigure around the quarantined copy, re-seed, wait until the
    partition is back to 3 members and the forensics record is acked."""
    app_id = int(gpid.partition(".")[0])
    stubs = {s.address: s for s in cluster.stubs}
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        cluster.meta.repair_quarantined()
        cluster.meta.repair_under_replication()
        with stub._lock:
            acked = gpid not in stub._quarantined
        _, primary, secondaries = _members(cluster, app_name, pidx)
        members = [primary] + secondaries if primary else []
        hosting = all(
            (app_id, pidx) in stubs[n]._replicas
            for n in members if n in stubs)
        if acked and primary and len(secondaries) == 2 and hosting:
            return True
        time.sleep(0.5)
    return False


def test_onebox_corruption_drill(cluster):
    """The tier-1 acceptance drill: flip bytes in one replica's live SST
    -> scrub detects -> quarantine (typed refusal + forensics dir +
    QUARANTINED beacon) -> meta re-seeds -> every row reads back right.
    Lane-guard breakers stay untouched end to end."""
    trips0 = (LANE_GUARD.breaker_trip_count,
              READ_LANE_GUARD.breaker_trip_count)
    cli = cluster.create("drill", partitions=2)
    rows = {}
    for i in range(48):
        hk, sk, v = b"dk%02d" % i, b"s", b"dv%d" % i
        cli.set(hk, sk, v)
        rows[(hk, sk)] = v

    stub = reps = None
    for s in cluster.stubs:
        with s._lock:
            reps = dict(s._replicas)
        if reps:
            stub = s
            break
    assert stub is not None
    (app_id, pidx), rep = sorted(reps.items())[0]
    gpid = f"{app_id}.{pidx}"
    rep.server.engine.flush()
    ssts = sorted(glob.glob(os.path.join(rep.path, "data", "*.sst")),
                  key=os.path.getmtime)
    assert ssts, "flush landed no SST to corrupt"
    flip_tail(ssts[-1])

    out = json.loads(stub._cmd_scrub_replica([gpid]))
    assert out[gpid]["quarantined"] is True
    assert any("crc32 mismatch" in f["detail"]
               for f in out[gpid]["findings"]), out
    with stub._lock:
        assert gpid in stub._quarantined
        assert stub._quarantined[gpid]["source"] == "scrub"
    qroot = os.path.join(stub.root, "quarantine")
    assert any(d.startswith(gpid + ".") for d in os.listdir(qroot)), \
        "quarantined data dir not retained for forensics"

    # mid-window reads must be right or a typed error — never garbage
    for (hk, sk), v in list(rows.items())[:8]:
        try:
            got = cli.get(hk, sk)
        except Exception:
            continue
        assert got == v

    assert _drive_heal(cluster, stub, gpid, "drill", pidx), \
        "quarantined replica was not re-seeded in time"
    for (hk, sk), v in rows.items():
        assert cli.get(hk, sk) == v, "wrong read after heal"
    assert (LANE_GUARD.breaker_trip_count,
            READ_LANE_GUARD.breaker_trip_count) == trips0, \
        "integrity plane must never touch the lane breakers"
    cli.close()


# --------------------------------------------------- auto-heal interlocks


class _FakeCaller:
    def __init__(self):
        self.calls = []

    def remote_command(self, node, cmd, args):
        self.calls.append((node, cmd, list(args)))
        return "{}"


def _verdict(mismatches, verdict="critical"):
    return {"verdict": verdict,
            "evidence": {"audit": {"mismatches": mismatches}}}


def test_autoheal_interlocks(monkeypatch):
    m = {"gpid": "2.1", "node": "n1:1", "decree": 7,
         "digest": "a" * 32, "expected": "b" * 32}

    # gated off by default: no env, no action
    monkeypatch.delenv("PEGASUS_AUTOHEAL", raising=False)
    h, c = AutoHealer(), _FakeCaller()
    assert h.observe_verdict(_verdict([m]), c) == [] and not c.calls

    monkeypatch.setenv("PEGASUS_AUTOHEAL", "1")
    # exactly one odd replica -> targeted quarantine
    h, c = AutoHealer(), _FakeCaller()
    assert h.observe_verdict(_verdict([m]), c) == \
        [{"gpid": "2.1", "node": "n1:1"}]
    assert c.calls == [("n1:1", "quarantine-replica",
                        ["2.1", c.calls[0][2][1]])]
    assert "decree 7" in c.calls[0][2][1]

    # two replicas disagreeing -> the reference is suspect: veto
    h, c = AutoHealer(), _FakeCaller()
    assert h.observe_verdict(
        _verdict([m, dict(m, node="n2:1")]), c) == []
    assert not c.calls

    # non-critical verdicts never act, whatever the evidence says
    h, c = AutoHealer(), _FakeCaller()
    assert h.observe_verdict(_verdict([m], "inconclusive"), c) == []
    assert h.observe_verdict(_verdict([m], "degraded"), c) == []
    assert not c.calls

    # process-wide rate limit: one quarantine per window
    monkeypatch.setenv("PEGASUS_AUTOHEAL_MIN_INTERVAL_S", "3600")
    h, c = AutoHealer(), _FakeCaller()
    assert len(h.observe_verdict(_verdict([m]), c)) == 1
    assert h.observe_verdict(_verdict([dict(m, gpid="2.0")]), c) == []
    assert len(c.calls) == 1


def test_autoheal_end_to_end(cluster, failpoints, monkeypatch):
    """Audit-driven heal: the `audit.digest` fail point rots exactly one
    secondary's digest -> doctor critical -> auto-healer quarantines THAT
    replica -> meta re-seeds -> re-audit conclusive and mismatch-free."""
    monkeypatch.setenv("PEGASUS_AUTOHEAL", "1")
    cli = cluster.create("ahl", partitions=2)
    rows = {}
    for i in range(40):
        hk, v = b"ak%02d" % i, b"av%d" % i
        cli.set(hk, b"s", v)
        rows[hk] = v
    app_id, _, secondaries = _members(cluster, "ahl", 0)
    victim = secondaries[0]
    gpid = f"{app_id}.0"

    failpoints.cfg("audit.digest", f"return({victim}@{gpid})")
    report = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
    assert len(report["mismatches"]) == 1
    time.sleep(0.6)  # corrupted digest rides the next beacons
    counters.number("compact.lane.breaker_open").set(0)
    counters.number("read.lane.breaker_open").set(0)
    counters.number("rpc.server.dispatch_queue_depth").set(0)
    with AUTO_HEALER._lock:
        AUTO_HEALER._last_action = None  # earlier tests must not rate-limit
    verdict = run_cluster_doctor([cluster.meta_addr])
    assert verdict["verdict"] == "critical"
    assert verdict.get("autoheal") == [{"gpid": gpid, "node": victim}], \
        verdict.get("autoheal")
    stub = next(s for s in cluster.stubs if s.address == victim)
    with stub._lock:
        assert gpid in stub._quarantined
        assert stub._quarantined[gpid]["source"] == "command"

    failpoints.cfg("audit.digest", "off()")
    assert _drive_heal(cluster, stub, gpid, "ahl", 0), \
        "auto-quarantined replica was not re-seeded in time"
    # the re-seeded secondary may still be applying its backlog for a
    # beat — the equal-decree rule keeps it pending (inconclusive), never
    # a false mismatch; retry until the audit is conclusive
    for _ in range(6):
        report = run_cluster_audit([cluster.meta_addr], wait_s=20.0)
        assert report["mismatches"] == []
        if gpid in report["ok"]:
            break
        time.sleep(1.0)
    assert gpid in report["ok"], report
    for hk, v in rows.items():
        assert cli.get(hk, b"s") == v
    cli.close()


def test_scrub_tick_rotates_under_short_cadence():
    """A scrub cadence SHORTER than the maintenance interval leaves every
    replica past due at every tick; selection must still rotate through
    all of them (oldest-first), not re-scrub dict-order-first forever."""
    import threading
    import types

    from pegasus_tpu.replication.replica_stub import ReplicaStub

    class _Rep:
        def __init__(self, app_id, pidx):
            self.app_id, self.pidx = app_id, pidx

    reps = [_Rep(1, i) for i in range(4)]
    fake = types.SimpleNamespace(
        _lock=threading.Lock(),
        _replicas={(r.app_id, r.pidx): r for r in reps},
        _last_scrub={},
        _scrub_interval=0.001,  # << the tick spacing: always past due
        scrubbed=[],
    )
    fake._scrub_replica = lambda rep: fake.scrubbed.append(
        (rep.app_id, rep.pidx))
    tick = types.MethodType(ReplicaStub._scrub_tick, fake)
    for _ in range(8):
        time.sleep(0.002)
        tick(reps)
    # two full rotations: every replica scrubbed exactly twice, in order
    assert fake.scrubbed == [(1, 0), (1, 1), (1, 2), (1, 3)] * 2
