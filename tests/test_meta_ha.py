"""Meta-server HA: election over shared storage, follower redirection,
takeover state reload (VERDICT-r3 missing #1; reference runs 3 ZK-backed
metas — config.ini:160-167, run.sh META_COUNT=3).

The SIGKILL tier lives in tests/test_process_kill.py::test_meta_leader_kill;
these tests cover the mechanism in-process: exactly-one-leader under
contention, ERR_FORWARD_TO_PRIMARY from followers, and a takeover that
reloads every acknowledged DDL from the shared state file.
"""

import os
import time

import pytest

from pegasus_tpu.meta.election import MetaElection
from pegasus_tpu.rpc.transport import ERR_FORWARD_TO_PRIMARY, RpcError
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.service_app import ServiceAppContainer


def _wait(pred, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def test_election_exactly_one_leader(tmp_path):
    lock = str(tmp_path / "meta.lock")
    els = [MetaElection(lock, f"127.0.0.1:{3460 + i}", lease_seconds=1.0,
                        settle_seconds=0.05) for i in range(3)]
    for e in els:
        e.start()
    try:
        assert _wait(lambda: sum(e.is_leader() for e in els) == 1)
        # stable: still exactly one a few lease rounds later
        time.sleep(1.2)
        assert sum(e.is_leader() for e in els) == 1
        leader = next(e for e in els if e.is_leader())
        for e in els:
            assert e.leader() == leader.my_addr
        # graceful stop hands leadership off without waiting out staleness
        leader.stop()
        rest = [e for e in els if e is not leader]
        assert _wait(lambda: sum(e.is_leader() for e in rest) == 1)
    finally:
        for e in els:
            e.stop()


def test_election_takeover_after_silent_death(tmp_path):
    """A SIGKILLed leader refreshes nothing; the lease goes stale and a
    standby claims it — simulated by just never starting the 'dead'
    holder's heartbeat."""
    lock = str(tmp_path / "meta.lock")
    dead = MetaElection(lock, "127.0.0.1:9999", lease_seconds=0.8,
                        settle_seconds=0.05)
    dead._write_lease()  # holds the lease but never heartbeats
    live = MetaElection(lock, "127.0.0.1:8888", lease_seconds=0.8,
                        settle_seconds=0.05).start()
    try:
        assert not live.is_leader()  # fresh foreign lease is honored
        assert _wait(lambda: live.is_leader(), timeout=5.0)
    finally:
        live.stop()


THREE_META_INI = """
[apps.meta1]
type = meta
run = true
port = %{mp1}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.meta2]
type = meta
run = true
port = %{mp2}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.meta3]
type = meta
run = true
port = %{mp3}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.replica1]
type = replica
run = true
port = 0
data_dir = %{root}/replica1

[apps.replica2]
type = replica
run = true
port = 0
data_dir = %{root}/replica2

[apps.replica3]
type = replica
run = true
port = 0
data_dir = %{root}/replica3

[pegasus.server]
meta_servers = %{metas}

[failure_detector]
beacon_interval_seconds = 0.2
grace_seconds = 60
check_interval_seconds = 3600
"""


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def ha_box(tmp_path):
    """3 metas (shared state dir, elected leader) + 3 replicas, one
    process. Meta ports are pre-allocated: every app must know the full
    meta list up front (it is what switches HA mode on)."""
    mp = _free_ports(3)
    metas = [f"127.0.0.1:{p}" for p in mp]
    cfg = Config(text=THREE_META_INI,
                 variables={"root": str(tmp_path), "metas": ",".join(metas),
                            "mp1": str(mp[0]), "mp2": str(mp[1]),
                            "mp3": str(mp[2])})
    container = ServiceAppContainer(cfg)
    container.start()
    apps = [container.apps[n] for n in ("meta1", "meta2", "meta3")]
    assert _wait(lambda: sum(a.election.is_leader() for a in apps) == 1)
    yield container, metas, apps
    container.stop()


def _leader_and_followers(apps):
    leader = next(a for a in apps if a.election.is_leader())
    return leader, [a for a in apps if a is not leader]


def test_follower_redirects_and_failover_keeps_ddl(ha_box):
    from pegasus_tpu.client import MetaResolver
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import (RPC_CM_CREATE_APP,
                                              RPC_CM_QUERY_CONFIG)
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    container, metas, apps = ha_box
    leader, followers = _leader_and_followers(apps)

    def call(app, code, req, resp_cls):
        host, port = app.rpc.address
        conn = RpcConnection((host, port))
        try:
            _, body = conn.call(code, codec.encode(req), timeout=5)
            return codec.decode(resp_cls, body)
        finally:
            conn.close()

    # wait until the leader sees the replicas' beacons
    assert _wait(lambda: len(leader.meta._alive_nodes_locked()) == 3)

    # follower refuses DDL with the redirect error
    with pytest.raises(RpcError) as ei:
        call(followers[0], RPC_CM_CREATE_APP,
             mm.CreateAppRequest(app_name="t", partition_count=4),
             mm.CreateAppResponse)
    assert ei.value.err == ERR_FORWARD_TO_PRIMARY
    assert leader.address in ei.value.text  # redirect hint names the leader

    # DDL through the resolver fall-through lands on the leader
    resp = call(leader, RPC_CM_CREATE_APP,
                mm.CreateAppRequest(app_name="t", partition_count=4),
                mm.CreateAppResponse)
    assert resp.error == 0

    # graceful leader handoff: DDL state must be visible to the new leader
    leader.stop()
    assert _wait(lambda: sum(a.election.is_leader() for a in followers) == 1)
    new_leader, _ = _leader_and_followers(followers)
    got = call(new_leader, RPC_CM_QUERY_CONFIG,
               mm.QueryConfigRequest("t"), mm.QueryConfigResponse)
    assert got.error == 0 and got.app.partition_count == 4
    # and the follower-aware resolver finds the new leader on its own
    r = MetaResolver(metas, "t")
    assert r.partition_count == 4
