"""Meta-server HA: election over shared storage, follower redirection,
takeover state reload (VERDICT-r3 missing #1; reference runs 3 ZK-backed
metas — config.ini:160-167, run.sh META_COUNT=3).

The SIGKILL tier lives in tests/test_process_kill.py::test_meta_leader_kill;
these tests cover the mechanism in-process: exactly-one-leader under
contention, ERR_FORWARD_TO_PRIMARY from followers, and a takeover that
reloads every acknowledged DDL from the shared state file.
"""

import os
import time

import pytest

from pegasus_tpu.meta.election import MetaElection
from pegasus_tpu.rpc.transport import ERR_FORWARD_TO_PRIMARY, RpcError
from pegasus_tpu.runtime.config import Config
from pegasus_tpu.runtime.service_app import ServiceAppContainer


def _wait(pred, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def test_election_exactly_one_leader(tmp_path):
    lock = str(tmp_path / "meta.lock")
    els = [MetaElection(lock, f"127.0.0.1:{3460 + i}", lease_seconds=1.0,
                        settle_seconds=0.05) for i in range(3)]
    for e in els:
        e.start()
    try:
        assert _wait(lambda: sum(e.is_leader() for e in els) == 1)
        # stable: still exactly one a few lease rounds later
        time.sleep(1.2)
        assert sum(e.is_leader() for e in els) == 1
        leader = next(e for e in els if e.is_leader())
        for e in els:
            assert e.leader() == leader.my_addr
        # graceful stop hands leadership off without waiting out staleness
        leader.stop()
        rest = [e for e in els if e is not leader]
        assert _wait(lambda: sum(e.is_leader() for e in rest) == 1)
    finally:
        for e in els:
            e.stop()


def test_election_takeover_after_silent_death(tmp_path):
    """A SIGKILLed leader refreshes nothing; the lease goes stale and a
    standby claims it — simulated by just never starting the 'dead'
    holder's heartbeat."""
    lock = str(tmp_path / "meta.lock")
    dead = MetaElection(lock, "127.0.0.1:9999", lease_seconds=0.8,
                        settle_seconds=0.05)
    dead._write_lease()  # holds the lease but never heartbeats
    live = MetaElection(lock, "127.0.0.1:8888", lease_seconds=0.8,
                        settle_seconds=0.05).start()
    try:
        assert not live.is_leader()  # fresh foreign lease is honored
        assert _wait(lambda: live.is_leader(), timeout=5.0)
    finally:
        live.stop()


def test_election_stop_before_start(tmp_path):
    """stop() after a failed start() must not join a never-started thread
    (that raises RuntimeError and masks the original error)."""
    el = MetaElection(str(tmp_path / "meta.lock"), "127.0.0.1:1")
    el.stop()  # no raise


def test_stale_leader_persist_is_fenced(tmp_path):
    """A leader stalled past its lease must not clobber state a newer
    leader wrote: verify_for_persist re-reads the lease last-moment, the
    persist RAISES (the DDL must not be acked) and the stale holder
    demotes in place."""
    import json

    from pegasus_tpu.meta.meta_server import MetaServer

    lock = str(tmp_path / "meta.lock")
    state = str(tmp_path / "state.json")
    old = MetaElection(lock, "127.0.0.1:1", lease_seconds=60.0,
                       settle_seconds=0.01)
    old._try_claim()
    assert old.is_leader() and old.epoch == 1
    ms_old = MetaServer(state, election=old)
    ms_old._persist()
    assert json.load(open(state))["epoch"] == 1

    # takeover: B fences A with a higher epoch and persists its own state
    new = MetaElection(lock, "127.0.0.1:2", lease_seconds=60.0,
                       settle_seconds=0.01)
    new._try_claim(lease_epoch=new._read()[2])
    assert new.is_leader() and new.epoch == 2
    ms_new = MetaServer(state, election=new)
    ms_new.level = "steady"
    ms_new._persist()

    # stale A wakes up mid-persist: lease re-check fences it
    ms_old.level = "blind"
    with pytest.raises(RuntimeError, match="fenced"):
        ms_old._persist()
    assert not old.is_leader()
    st = json.load(open(state))
    assert st["level"] == "steady" and st["epoch"] == 2


def test_persist_refuses_newer_state_epoch(tmp_path):
    """Even when the lease read races in the stale leader's favor, a state
    file carrying a newer epoch is never overwritten (the fencing token
    itself, ADVICE-r4 medium) — and the fence releases the lease carrying
    the newer lineage forward so the cluster does not livelock."""
    import json

    from pegasus_tpu.meta.meta_server import MetaServer

    lock = str(tmp_path / "meta.lock")
    state = str(tmp_path / "state.json")
    el = MetaElection(lock, "127.0.0.1:1", lease_seconds=60.0,
                      settle_seconds=0.01)
    el._try_claim()
    ms = MetaServer(state, election=el)
    ms._persist()
    # a newer leader's state lands while A still (wrongly) holds the lease
    newer = json.load(open(state))
    newer["epoch"], newer["level"] = 7, "steady"
    json.dump(newer, open(state, "w"))
    ms.level = "lively"
    with pytest.raises(RuntimeError, match="fenced"):
        ms._persist()  # fenced by epoch comparison
    assert not el.is_leader()
    st = json.load(open(state))
    assert st["level"] == "steady" and st["epoch"] == 7
    # the released lease carries epoch 7: the next claim exceeds it
    holder, _, epoch = el._read()
    assert holder is None and epoch == 7
    el._try_claim(lease_epoch=epoch)
    assert el.is_leader() and el.epoch == 8
    ms.level = "lively"
    ms._persist()  # no longer fenced
    assert json.load(open(state))["epoch"] == 8


def test_graceful_release_keeps_epoch_lineage(tmp_path):
    """r5 review finding: a graceful stop() must not reset the epoch
    lineage — the next claimant's epoch has to exceed the persisted state
    epoch or every later persist would fence forever (livelock)."""
    import json

    from pegasus_tpu.meta.meta_server import MetaServer

    lock = str(tmp_path / "meta.lock")
    state = str(tmp_path / "state.json")

    a = MetaElection(lock, "127.0.0.1:1", lease_seconds=1.0,
                     settle_seconds=0.02,
                     claim_floor=lambda: MetaServer(state)._state_epoch)
    a.start()
    assert _wait(lambda: a.is_leader())
    ms_a = MetaServer(state, election=a)
    ms_a._persist()
    persisted = json.load(open(state))["epoch"]
    a.stop()  # graceful: clears the holder, KEEPS the lineage

    holder, _, kept = a._read()
    assert holder is None and kept >= persisted

    b = MetaElection(lock, "127.0.0.1:2", lease_seconds=1.0,
                     settle_seconds=0.02,
                     claim_floor=lambda: MetaServer(state)._state_epoch)
    b.start()
    assert _wait(lambda: b.is_leader())
    assert b.epoch > persisted
    ms_b = MetaServer(state, election=b)
    ms_b.level = "steady"
    ms_b._persist()  # must NOT fence
    assert json.load(open(state))["level"] == "steady"
    b.stop()


def test_beacon_never_persists(tmp_path):
    """Beacons reach followers too (the leader guard exempts them); a
    follower absorbing a beacon from an unknown node must not write its
    stale DDL snapshot over the shared state file (ADVICE-r4 high). The
    beacon path now never persists — _load() rebuilds the node map from
    re-beacons anyway."""
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import MetaServer
    from pegasus_tpu.rpc import codec

    state = str(tmp_path / "state.json")
    ms = MetaServer(state)
    body = codec.encode(mm.BeaconRequest(node="127.0.0.1:7777"))
    ms._on_beacon(None, body)
    assert "127.0.0.1:7777" in ms._nodes
    assert not os.path.exists(state)


THREE_META_INI = """
[apps.meta1]
type = meta
run = true
port = %{mp1}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.meta2]
type = meta
run = true
port = %{mp2}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.meta3]
type = meta
run = true
port = %{mp3}
state_dir = %{root}/meta
election_lease_seconds = 1.0

[apps.replica1]
type = replica
run = true
port = 0
data_dir = %{root}/replica1

[apps.replica2]
type = replica
run = true
port = 0
data_dir = %{root}/replica2

[apps.replica3]
type = replica
run = true
port = 0
data_dir = %{root}/replica3

[pegasus.server]
meta_servers = %{metas}

[failure_detector]
beacon_interval_seconds = 0.2
grace_seconds = 60
check_interval_seconds = 3600
"""


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def ha_box(tmp_path):
    """3 metas (shared state dir, elected leader) + 3 replicas, one
    process. Meta ports are pre-allocated: every app must know the full
    meta list up front (it is what switches HA mode on)."""
    mp = _free_ports(3)
    metas = [f"127.0.0.1:{p}" for p in mp]
    cfg = Config(text=THREE_META_INI,
                 variables={"root": str(tmp_path), "metas": ",".join(metas),
                            "mp1": str(mp[0]), "mp2": str(mp[1]),
                            "mp3": str(mp[2])})
    container = ServiceAppContainer(cfg)
    container.start()
    apps = [container.apps[n] for n in ("meta1", "meta2", "meta3")]
    assert _wait(lambda: sum(a.election.is_leader() for a in apps) == 1)
    yield container, metas, apps
    container.stop()


def _leader_and_followers(apps):
    leader = next(a for a in apps if a.election.is_leader())
    return leader, [a for a in apps if a is not leader]


def test_follower_redirects_and_failover_keeps_ddl(ha_box):
    from pegasus_tpu.client import MetaResolver
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import (RPC_CM_CREATE_APP,
                                              RPC_CM_QUERY_CONFIG)
    from pegasus_tpu.rpc import codec
    from pegasus_tpu.rpc.transport import RpcConnection

    container, metas, apps = ha_box
    leader, followers = _leader_and_followers(apps)

    def call(app, code, req, resp_cls):
        host, port = app.rpc.address
        conn = RpcConnection((host, port))
        try:
            _, body = conn.call(code, codec.encode(req), timeout=5)
            return codec.decode(resp_cls, body)
        finally:
            conn.close()

    # wait until the leader sees the replicas' beacons
    assert _wait(lambda: len(leader.meta._alive_nodes_locked()) == 3)

    # follower refuses DDL with the redirect error
    with pytest.raises(RpcError) as ei:
        call(followers[0], RPC_CM_CREATE_APP,
             mm.CreateAppRequest(app_name="t", partition_count=4),
             mm.CreateAppResponse)
    assert ei.value.err == ERR_FORWARD_TO_PRIMARY
    assert leader.address in ei.value.text  # redirect hint names the leader

    # DDL through the resolver fall-through lands on the leader
    resp = call(leader, RPC_CM_CREATE_APP,
                mm.CreateAppRequest(app_name="t", partition_count=4),
                mm.CreateAppResponse)
    assert resp.error == 0

    # graceful leader handoff: DDL state must be visible to the new leader
    leader.stop()
    assert _wait(lambda: sum(a.election.is_leader() for a in followers) == 1)
    new_leader, _ = _leader_and_followers(followers)
    got = call(new_leader, RPC_CM_QUERY_CONFIG,
               mm.QueryConfigRequest("t"), mm.QueryConfigResponse)
    assert got.error == 0 and got.app.partition_count == 4
    # and the follower-aware resolver finds the new leader on its own
    r = MetaResolver(metas, "t")
    assert r.partition_count == 4


def test_persist_caches_state_epoch_until_external_write(tmp_path):
    """ADVICE r5: the persist fence must not re-parse the whole state file
    on every acked DDL. Repeat persists from one process serve the epoch
    from cache (zero full re-reads); an external writer changes the stat
    fingerprint and forces exactly one re-read — which still fences."""
    import json

    from pegasus_tpu.meta.meta_server import MetaServer

    lock = str(tmp_path / "meta.lock")
    state = str(tmp_path / "state.json")
    el = MetaElection(lock, "127.0.0.1:1", lease_seconds=60.0,
                      settle_seconds=0.01)
    el._try_claim()
    ms = MetaServer(state, election=el)
    reads = []
    orig = ms._read_state_epoch
    ms._read_state_epoch = lambda: (reads.append(1), orig())[1]
    for _ in range(5):
        ms._persist()
    assert reads == []  # fingerprint matched every time: cache served
    # external writer (a newer leader) lands a higher-epoch state
    newer = json.load(open(state))
    newer["epoch"] = 9
    json.dump(newer, open(state, "w"))
    with pytest.raises(RuntimeError, match="fenced"):
        ms._persist()
    assert reads == [1]  # the fingerprint miss forced ONE full re-read
