"""Cluster-wide compaction scheduler: debt-driven timing, placement,
and admission control (ISSUE 10).

RESYSTANCE (PAPERS.md) shows most LSM compaction headroom is
*scheduling*, not kernels. PR 1-9 made every signal a scheduler needs
exportable — per-partition compaction debt (beacon-folded into the
meta's one-RPC ``RPC_CM_QUERY_CLUSTER_STATE`` snapshot), hotkey
verdicts / read-residency pins, per-partition committed/applied lag,
``compact.lane.*`` breaker state — and this module *concludes* from
them, the way the cluster doctor (PR 8) folds the same snapshot into a
verdict:

- ``fold_decisions``: the pure, deterministic CLUSTER-level fold — per
  partition one of ``defer | normal | urgent`` with the reasons that
  drove it:

    * L0 debt at/over the hard ceiling -> **urgent** (``debt_ceiling``;
      the engine-local trigger fires there regardless — the scheduler
      merely agrees);
    * confirmed hot READ traffic (hotkey verdict pinned the partition
      device-resident) -> **defer** (``hot_read``): compacting a
      read-hot partition evicts the resident runs its device reads
      serve from, for no urgency;
    * committed-vs-applied backlog over the threshold -> **urgent**
      (``apply_backlog``, plus ``slow_requests`` when the cluster
      slow-request rollup is non-empty — backlog is what drives the
      slow ledger);
    * L0 debt at/over the urgent threshold -> **urgent** (``l0_debt``).

- ``localize_decisions``: the per-NODE half, applied at delivery for
  each receiving node (every replica compacts independently):

    * a receiver whose compact-lane breaker is open never gets an
      urgent token (``breaker_open``): its device lane is degraded to
      host — never promote work onto it;
    * per-node urgent budget: at most ``max_urgent_per_node``
      non-ceiling urgents per receiver (highest debt first; the rest
      demote to ``node_cap``) so promotions cannot convoy one node's
      TPU lane;
    * defer tokens land on the PRIMARY only
      (``defer_primary_only``): the read-residency pin that justifies
      holding compaction lives on the primary's engine — a deferring
      secondary would pay the debt for zero read benefit.

- ``run_scheduler_tick``: one control-loop round over the live RPC
  surfaces — snapshot + breaker scrapes in, decisions delivered to every
  alive node over the ``compact-sched-policy`` remote command.

- ``CompactScheduler``: the collector-hosted loop (armed by
  ``PEGASUS_SCHED=1``), wiring the info collector's hotkey verdicts and
  slow-request rollup into the fold.

Failure semantics: decisions are *leases*. Each delivered token expires
after ``ttl_s`` back to ``normal`` inside the engine, and the hard debt
ceiling overrides ``defer`` engine-side — so a wedged, crashed or
partitioned scheduler degrades the cluster to exactly the engine-local
trigger behavior it had before this module existed (the ``compact.sched``
fail point + chaos test pin that). The scheduler can only ever *shape*
compaction timing, never block it.
"""

import json
import os
import threading

from ..rpc.transport import RpcError
from ..runtime import lockrank
from ..runtime.fail_points import inject
from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread
from .cluster_doctor import ClusterCaller


def _knobs() -> dict:
    """Scheduler policy knobs, re-read per tick (cheap; lets tests and
    operators retune a live scheduler without a restart)."""
    return {
        # L0 files at/over which a partition promotes to urgent
        "urgent_l0": int(os.environ.get("PEGASUS_SCHED_URGENT_L0", "4")),
        # committed-applied decree gap that promotes to urgent
        "backlog_urgent": int(os.environ.get(
            "PEGASUS_SCHED_BACKLOG_URGENT", "64")),
        # urgent budget per node (0 = unbounded)
        "max_urgent_per_node": int(os.environ.get(
            "PEGASUS_SCHED_MAX_URGENT_PER_NODE", "2")),
        # per-node concurrent device-compaction cap delivered with the
        # decisions (0 = leave the node's gate alone)
        "max_device": int(os.environ.get(
            "PEGASUS_SCHED_MAX_DEVICE_COMPACT", "0")),
        # decision lease: engines revert to local triggers this many
        # seconds after the last delivery
        "ttl_s": float(os.environ.get("PEGASUS_SCHED_TTL_S", "30")),
        # compaction-offload placement (ISSUE 14): the rack's device-
        # owning compaction services; each tick scrapes their free merge
        # budget and the fold assigns (when, where) pairs against it
        "offload_services": [s.strip() for s in os.environ.get(
            "PEGASUS_OFFLOAD_SERVICES", "").split(",") if s.strip()],
        # feedback tuning (ISSUE 14 satellite): PEGASUS_SCHED_AUTOTUNE=1
        # replaces the static urgent thresholds with ones tuned from the
        # measured compact.stage.* durations (EWMA over the nodes'
        # metric-history rings)
        "autotune": os.environ.get("PEGASUS_SCHED_AUTOTUNE", "") == "1",
        "tune_alpha": float(os.environ.get("PEGASUS_SCHED_TUNE_ALPHA",
                                           "0.3")),
        "tune_slow_us": float(os.environ.get("PEGASUS_SCHED_TUNE_SLOW_US",
                                             "2000000")),
        "tune_fast_us": float(os.environ.get("PEGASUS_SCHED_TUNE_FAST_US",
                                             "250000")),
    }


# the stage series the feedback tuner folds: one whole-merge cost is
# (approximately) the sum of the per-stage p99s a node's metric-history
# ring sampled in the window
_STAGE_SERIES = tuple(f"compact.stage.{s}.duration_us.p99"
                      for s in ("pack", "h2d", "device", "gather",
                                "sst_write"))


def stage_cost_us(window: dict) -> float:
    """Worst observed whole-merge stage cost in one metrics-history
    window (``{"samples": [{"ts", "values": {...}}]}``): per sample the
    compact.stage.* duration p99s sum to ~one merge's wall cost; the max
    over the window is the recent worst. 0.0 = no compaction ran."""
    worst = 0.0
    for s in window.get("samples", ()):
        vals = s.get("values", {})
        worst = max(worst, sum(float(vals.get(k, 0.0))
                               for k in _STAGE_SERIES))
    return worst


def tune_knobs(ewma_us: float, knobs: dict) -> tuple:
    """Feedback-tune the fold's urgency thresholds from the measured
    merge cost (EWMA of stage_cost_us across ticks). Pure. Rationale:
    expensive merges (slow device/tunnel, big partitions) amortize their
    fixed cost over more debt — promote LATER (doubled thresholds);
    cheap merges should keep read amplification low — promote EARLIER
    (halved thresholds, floored). -> (tuned knobs, report dict)."""
    k = dict(knobs)
    if ewma_us >= k["tune_slow_us"]:
        mode = "slow_merges"
        k["urgent_l0"] = k["urgent_l0"] * 2
        k["backlog_urgent"] = k["backlog_urgent"] * 2
    elif 0.0 < ewma_us <= k["tune_fast_us"]:
        mode = "fast_merges"
        k["urgent_l0"] = max(2, k["urgent_l0"] // 2)
        k["backlog_urgent"] = max(8, k["backlog_urgent"] // 2)
    else:
        mode = "base"
    return k, {"ewma_us": round(ewma_us, 1), "mode": mode,
               "urgent_l0": k["urgent_l0"],
               "backlog_urgent": k["backlog_urgent"]}


def assign_placements(decisions: dict, places: dict,
                      weights: dict = None) -> dict:
    """The WHERE half of the fold (ISSUE 14): hand each service's free
    merge budget to the partitions that need compaction most. Pure and
    deterministic: non-defer partitions with debt, highest debt first,
    fill the service with the most remaining slots (address tie-break);
    everyone else keeps ``where == ""`` (compact locally). ``weights``
    ({gpid: replica count, default 1}) sizes each placement honestly:
    the token is delivered to EVERY replica of the partition and each
    compacts independently, so one placement can present up to
    replica-count concurrent merges at the service — it is charged
    min(weight, remaining) slots (never refused outright: the budget is
    advisory, the service's admission gate is the hard bound). Mutates
    and returns `decisions` (each entry gains "where")."""
    free = {a: max(0, int(n)) for a, n in (places or {}).items()}
    weights = weights or {}
    for d in decisions.values():
        d.setdefault("where", "")
    if not free:
        return decisions
    order = sorted(
        (g for g, d in decisions.items()
         if d["policy"] != "defer"
         and (d["l0_files"] > 0 or d["debt_bytes"] > 0)),
        key=lambda g: (decisions[g]["debt_bytes"],
                       decisions[g]["l0_files"], g),
        reverse=True)
    for g in order:
        addr = sorted(free, key=lambda a: (-free[a], a))[0]
        if free[addr] <= 0:
            break
        free[addr] -= min(max(1, int(weights.get(g, 1))), free[addr])
        decisions[g]["where"] = addr
        decisions[g]["reasons"] = list(decisions[g]["reasons"]) \
            + ["offload_budget"]
    return decisions


def fold_decisions(parts: dict, hot=(), slow_count: int = 0,
                   knobs: dict = None, places: dict = None,
                   weights: dict = None) -> dict:
    """The deterministic CLUSTER-level decision fold — what each
    partition needs, independent of which node serves it. Pure: no RPC,
    no clock. Per-NODE bounding (breaker-open skip, the urgent budget)
    happens at delivery in ``localize_decisions``, per receiving node:
    every replica compacts independently, so those rules must bind at
    each receiver, not at the primary the fold would otherwise key on.

    ``parts``: {gpid: {"node", "l0_files", "debt_bytes",
    "pending_installs", "apply_gap", "ceiling_files"}} — the primary's
    beacon-reported debt/lag state. ``hot``: gpids with a confirmed
    read-hot verdict. ``slow_count``: size of the cluster slow-request
    rollup. ``places``: {offload service addr: free merge slots} — when
    given, the fold also decides WHERE (ISSUE 14): the debtiest
    non-defer partitions are placed onto services with free device
    budget (``assign_placements``), so each decision is a (when, where)
    pair. -> {gpid: {"policy", "reasons", "node", "l0_files",
    "debt_bytes", "where"}}."""
    k = dict(_knobs(), **(knobs or {}))
    hot = set(hot)
    out = {}
    for gpid, st in sorted(parts.items()):
        l0 = int(st.get("l0_files", 0))
        ceiling = int(st.get("ceiling_files", 0)) or max(
            1, k["urgent_l0"] * 3)
        reasons = []
        if l0 >= ceiling:
            # the engine-local trigger fires here no matter what the
            # scheduler says; agreeing keeps the status surface truthful
            # and lets manual compactions jump the queue
            policy = "urgent"
            reasons.append("debt_ceiling")
        elif gpid in hot:
            policy = "defer"
            reasons.append("hot_read")
        else:
            policy = "normal"
            if int(st.get("apply_gap", 0)) >= k["backlog_urgent"]:
                policy = "urgent"
                reasons.append("apply_backlog")
                if slow_count > 0:
                    reasons.append("slow_requests")
            if l0 >= k["urgent_l0"]:
                policy = "urgent"
                reasons.append("l0_debt")
        out[gpid] = {"policy": policy, "reasons": reasons,
                     "node": st.get("node", ""), "l0_files": l0,
                     "debt_bytes": int(st.get("debt_bytes", 0))}
    return assign_placements(out, places, weights=weights)


def localize_decisions(decisions: dict, hosts: dict, node: str,
                       breaker_open: bool = False, cap: int = 0) -> dict:
    """Per-receiving-node half of the decision pipeline: the fold says
    what each partition needs; this bounds what ONE node is asked to do.
    Urgent tokens demote to normal (reason appended) for a breaker-open
    receiver (never promote onto a degraded device lane) and past the
    receiver's urgent budget of `cap` non-ceiling urgents (highest debt
    first, deterministic gpid tie-break); ceiling urgents pass through
    untouched (the engine-local trigger fires there regardless). A
    healthy receiver with free budget keeps every promotion — the
    demotions are per node, never global. DEFER tokens land on the
    PRIMARY only (the fold's `node`): the read-residency pin that
    justifies holding compaction lives on the primary's engine, so a
    secondary deferring would ride its debt to the ceiling's inline
    apply-path stall for zero read benefit (`defer_primary_only`).
    -> {gpid: {"policy", "reasons"}} for the partitions `node` hosts."""
    order = sorted((g for g in decisions if node in hosts.get(g, ())),
                   key=lambda g: (decisions[g]["debt_bytes"],
                                  decisions[g]["l0_files"], g),
                   reverse=True)
    mine = {}
    urgent_sent = 0
    for g in order:
        d = decisions[g]
        policy, reasons = d["policy"], list(d["reasons"])
        if policy == "urgent" and "debt_ceiling" not in reasons:
            if breaker_open:
                policy = "normal"
                reasons.append("breaker_open")
            elif cap > 0 and urgent_sent >= cap:
                policy = "normal"
                reasons.append("node_cap")
            else:
                urgent_sent += 1
        elif policy == "defer" and d.get("node") and node != d["node"]:
            policy = "normal"
            reasons.append("defer_primary_only")
        # the WHERE half passes through untouched: every replica of the
        # partition ships to the same service (content-addressed staging
        # dedups the runs they share)
        mine[g] = {"policy": policy, "reasons": reasons,
                   "where": d.get("where", ""),
                   # the job-trace id rides the lease (ISSUE 16): every
                   # receiver gets the SAME id, so whichever replica's
                   # trigger fires first continues the decision's timeline
                   "job": d.get("job", "")}
    return mine


def run_scheduler_tick(meta_addrs, pool=None, hot_gpids=None,
                       slow_count: int = 0, caller: ClusterCaller = None,
                       deliver: bool = True, knobs: dict = None,
                       tune_state: dict = None) -> dict:
    """One scheduler round over the live cluster. -> report dict:
    ``{"decisions": {gpid: {...}}, "delivered": {node: {gpid: policy}},
    "nodes": N, "services": {addr: {...}}, "errors": [...]}`` (plus
    ``"autotune"`` when the feedback tuner is armed).

    Folds the meta's cluster-state snapshot (partition configs + the
    beacon-carried per-replica ``compact`` debt and committed/applied
    decrees) with per-node compact-lane breaker scrapes and — when
    ``PEGASUS_OFFLOAD_SERVICES`` names compaction services — their free
    merge budget, then delivers each alive node the (when, where)
    decisions for every partition it hosts (primary AND secondaries —
    each replica compacts independently) over ``compact-sched-policy``.
    ``tune_state`` (a dict the caller keeps across ticks, holding
    ``ewma_us``) arms the feedback tuner when the autotune knob is on.
    Every failure is an entry in ``errors``, never an exception: a
    half-delivered round is strictly better than none, and undelivered
    tokens simply expire."""
    inject("compact.sched")  # chaos seam: a wedged/crashed tick must
    # never block writes or compactions (engine-local triggers + token
    # expiry are the fallback; see tests/test_compact_scheduler.py)
    counters.rate("sched.tick_count").increment()
    own = caller is None
    caller = caller or ClusterCaller(meta_addrs, pool=pool)
    report = {"decisions": {}, "delivered": {}, "nodes": 0,
              "services": {}, "errors": []}
    k = dict(_knobs(), **(knobs or {}))
    try:
        state = caller.meta_state()
        if state is None:
            report["errors"].append("no meta reachable")
            return report
        nodes = state.get("nodes", {})
        alive = sorted(a for a, n in nodes.items() if n.get("alive"))
        report["nodes"] = len(alive)
        breakers = {}
        for node in alive:
            try:
                snap = json.loads(caller.remote_command(
                    node, "perf-counters-by-substr",
                    ["compact.lane.breaker_open"]))
                breakers[node] = bool(snap.get("compact.lane.breaker_open"))
            except (RpcError, OSError, ValueError):
                # unknown lane state: treat as healthy — a scrape hiccup
                # must not strip a node of promotions it may need
                breakers[node] = False
        # offload services (ISSUE 14): free device budget per service; a
        # dead/unreachable service simply gets no placements this round
        places = {}
        for svc in k["offload_services"]:
            try:
                st = json.loads(caller.remote_command(svc, "offload-status",
                                                      []))
                places[svc] = int(st.get("free_slots", 0))
                report["services"][svc] = {
                    "free_slots": places[svc],
                    "running_merges": st.get("running_merges", 0),
                    "jobs": st.get("jobs", 0)}
            except (RpcError, OSError, ValueError) as e:
                report["services"][svc] = {"error": str(e)}
                report["errors"].append(f"offload {svc}: {e}")
        if k["autotune"] and tune_state is not None:
            # feedback tuning (ISSUE 14 satellite): fold the nodes'
            # recorded compact.stage.* durations into an EWMA of the
            # whole-merge cost and rescale the urgency thresholds
            obs = 0.0
            for node in alive:
                try:
                    hist = json.loads(caller.remote_command(
                        node, "metrics-history",
                        ["60", "compact.stage."]))
                    for window in hist.values():  # pid-keyed per process
                        obs = max(obs, stage_cost_us(window))
                except (RpcError, OSError, ValueError):
                    continue  # a scrape hiccup must not zero the EWMA
            if obs > 0.0:
                prev = tune_state.get("ewma_us")
                alpha = k["tune_alpha"]
                tune_state["ewma_us"] = obs if prev is None else \
                    alpha * obs + (1.0 - alpha) * prev
            k, tuned = tune_knobs(tune_state.get("ewma_us", 0.0), k)
            report["autotune"] = tuned
            counters.number("sched.autotune.urgent_l0").set(k["urgent_l0"])
        parts, hosts = {}, {}
        rs = state.get("replica_states", {})
        for app in state.get("apps", {}).values():
            for pc in app.get("partitions", []):
                gpid = f"{app['app_id']}.{pc['pidx']}"
                members = [m for m in [pc.get("primary")]
                           + pc.get("secondaries", []) if m and m in alive]
                primary = pc.get("primary")
                st = rs.get(primary, {}).get(gpid) if primary else None
                if not members or not st:
                    continue  # unserved / not yet beaconed: nothing to say
                debt = st.get("compact") or {}
                parts[gpid] = {
                    "node": primary,
                    "l0_files": debt.get("l0_files", 0),
                    "debt_bytes": debt.get("debt_bytes", 0),
                    "pending_installs": debt.get("pending_installs", 0),
                    "ceiling_files": debt.get("ceiling_files", 0),
                    "apply_gap": max(0, st.get("committed", 0)
                                     - st.get("applied", 0)),
                }
                hosts[gpid] = members
        decisions = fold_decisions(parts, hot=hot_gpids or (),
                                   slow_count=slow_count, knobs=k,
                                   places=places,
                                   # a placement reaches every replica,
                                   # each compacting independently —
                                   # budget it by member count
                                   weights={g: len(m)
                                            for g, m in hosts.items()})
        report["decisions"] = decisions
        counters.number("sched.decisions.defer").set(
            sum(1 for d in decisions.values() if d["policy"] == "defer"))
        counters.number("sched.decisions.urgent").set(
            sum(1 for d in decisions.values() if d["policy"] == "urgent"))
        if not deliver:
            return report
        # causal job tracing (ISSUE 16): one id per (gpid, tick) decision,
        # minted BEFORE the per-node loop so a partition delivered to
        # several replicas shares one id. The scheduler only DECIDES —
        # it never finishes these jobs (the engine whose trigger adopts
        # the token does); scheduler-local records for decisions that
        # never fire age out of the tracer's bounded active set.
        from ..runtime.job_trace import JOB_TRACER

        for gpid, d in decisions.items():
            d["job"] = JOB_TRACER.begin("sched", gpid=gpid)
            JOB_TRACER.note("sched.decide", job_id=d["job"], gpid=gpid,
                            policy=d["policy"],
                            reasons=",".join(d["reasons"]),
                            where=d.get("where", ""))
        for node in alive:
            mine = localize_decisions(decisions, hosts, node,
                                      breaker_open=breakers.get(node, False),
                                      cap=k["max_urgent_per_node"])
            if not mine:
                continue
            body = {"ttl_s": k["ttl_s"], "decisions": mine}
            if k["max_device"] > 0:
                body["max_device"] = k["max_device"]
            try:
                out = caller.remote_command(node, "compact-sched-policy",
                                            [json.dumps(body)])
                report["delivered"][node] = json.loads(out)
                for g, dec in mine.items():
                    if dec.get("job"):
                        JOB_TRACER.note("sched.deliver", job_id=dec["job"],
                                        gpid=g, node=node)
            except (RpcError, OSError, ValueError) as e:
                counters.rate("sched.deliver_errors").increment()
                report["errors"].append(f"{node}: {e}")
    finally:
        if own:
            caller.close()
    return report


class CompactScheduler:
    """The collector-hosted control loop: one ``run_scheduler_tick`` per
    interval, the info collector's read-residency pins and slow-request
    rollup wired into the fold. Armed by ``PEGASUS_SCHED=1`` (the
    CollectorApp constructs it); ``compact-sched-status`` on the
    collector and collector-info's ``compact_sched`` key expose the last
    round's decisions."""

    def __init__(self, meta_addrs, pool=None, interval_seconds: float = None,
                 hot_fn=None, slow_fn=None):
        self.meta_addrs = list(meta_addrs)
        self.pool = pool
        self.interval = (float(os.environ.get("PEGASUS_SCHED_INTERVAL_S",
                                              "5"))
                         if interval_seconds is None else interval_seconds)
        self.hot_fn = hot_fn or (lambda: ())
        self.slow_fn = slow_fn or (lambda: 0)
        self._stop = threading.Event()
        # leaf lock over the published report (the loop writes, the
        # status command reads on an RPC thread)
        self._lock = lockrank.named_lock("sched.state")
        self._last = {}  #: guarded_by self._lock
        # feedback-tuner state (EWMA of measured merge cost), carried
        # across ticks; only the loop thread touches it
        self._tune_state = {}
        self._thread = spawn_thread(self._loop, daemon=True, start=False,
                                    name="compact-sched")

    def start(self) -> "CompactScheduler":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the loop and JOIN it (bounded): the caller closes the
        shared pool next, and an in-flight tick racing that close would
        spray false tick/deliver errors through every clean shutdown."""
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=timeout)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # a failed tick must never kill the
                # loop — the next interval retries, and engine tokens
                # expiring is the designed degradation
                counters.rate("sched.tick_errors").increment()
                print(f"[compact-sched] tick failed: {e!r}", flush=True)

    def tick(self) -> dict:
        report = run_scheduler_tick(self.meta_addrs, pool=self.pool,
                                    hot_gpids=self.hot_fn(),
                                    slow_count=self.slow_fn(),
                                    tune_state=self._tune_state)
        with self._lock:
            self._last = report
        return report

    def status(self) -> dict:
        """The last round's report (decisions with reasons, delivery map,
        errors) — JSON-ready."""
        with self._lock:
            return dict(self._last)
