"""Cluster-wide compaction scheduler: debt-driven timing, placement,
and admission control (ISSUE 10).

RESYSTANCE (PAPERS.md) shows most LSM compaction headroom is
*scheduling*, not kernels. PR 1-9 made every signal a scheduler needs
exportable — per-partition compaction debt (beacon-folded into the
meta's one-RPC ``RPC_CM_QUERY_CLUSTER_STATE`` snapshot), hotkey
verdicts / read-residency pins, per-partition committed/applied lag,
``compact.lane.*`` breaker state — and this module *concludes* from
them, the way the cluster doctor (PR 8) folds the same snapshot into a
verdict:

- ``fold_decisions``: the pure, deterministic CLUSTER-level fold — per
  partition one of ``defer | normal | urgent`` with the reasons that
  drove it:

    * L0 debt at/over the hard ceiling -> **urgent** (``debt_ceiling``;
      the engine-local trigger fires there regardless — the scheduler
      merely agrees);
    * confirmed hot READ traffic (hotkey verdict pinned the partition
      device-resident) -> **defer** (``hot_read``): compacting a
      read-hot partition evicts the resident runs its device reads
      serve from, for no urgency;
    * committed-vs-applied backlog over the threshold -> **urgent**
      (``apply_backlog``, plus ``slow_requests`` when the cluster
      slow-request rollup is non-empty — backlog is what drives the
      slow ledger);
    * L0 debt at/over the urgent threshold -> **urgent** (``l0_debt``).

- ``localize_decisions``: the per-NODE half, applied at delivery for
  each receiving node (every replica compacts independently):

    * a receiver whose compact-lane breaker is open never gets an
      urgent token (``breaker_open``): its device lane is degraded to
      host — never promote work onto it;
    * per-node urgent budget: at most ``max_urgent_per_node``
      non-ceiling urgents per receiver (highest debt first; the rest
      demote to ``node_cap``) so promotions cannot convoy one node's
      TPU lane;
    * defer tokens land on the PRIMARY only
      (``defer_primary_only``): the read-residency pin that justifies
      holding compaction lives on the primary's engine — a deferring
      secondary would pay the debt for zero read benefit.

- ``run_scheduler_tick``: one control-loop round over the live RPC
  surfaces — snapshot + breaker scrapes in, decisions delivered to every
  alive node over the ``compact-sched-policy`` remote command.

- ``CompactScheduler``: the collector-hosted loop (armed by
  ``PEGASUS_SCHED=1``), wiring the info collector's hotkey verdicts and
  slow-request rollup into the fold.

Failure semantics: decisions are *leases*. Each delivered token expires
after ``ttl_s`` back to ``normal`` inside the engine, and the hard debt
ceiling overrides ``defer`` engine-side — so a wedged, crashed or
partitioned scheduler degrades the cluster to exactly the engine-local
trigger behavior it had before this module existed (the ``compact.sched``
fail point + chaos test pin that). The scheduler can only ever *shape*
compaction timing, never block it.
"""

import json
import os
import threading

from ..rpc.transport import RpcError
from ..runtime import lockrank
from ..runtime.fail_points import inject
from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread
from .cluster_doctor import ClusterCaller


def _knobs() -> dict:
    """Scheduler policy knobs, re-read per tick (cheap; lets tests and
    operators retune a live scheduler without a restart)."""
    return {
        # L0 files at/over which a partition promotes to urgent
        "urgent_l0": int(os.environ.get("PEGASUS_SCHED_URGENT_L0", "4")),
        # committed-applied decree gap that promotes to urgent
        "backlog_urgent": int(os.environ.get(
            "PEGASUS_SCHED_BACKLOG_URGENT", "64")),
        # urgent budget per node (0 = unbounded)
        "max_urgent_per_node": int(os.environ.get(
            "PEGASUS_SCHED_MAX_URGENT_PER_NODE", "2")),
        # per-node concurrent device-compaction cap delivered with the
        # decisions (0 = leave the node's gate alone)
        "max_device": int(os.environ.get(
            "PEGASUS_SCHED_MAX_DEVICE_COMPACT", "0")),
        # decision lease: engines revert to local triggers this many
        # seconds after the last delivery
        "ttl_s": float(os.environ.get("PEGASUS_SCHED_TTL_S", "30")),
    }


def fold_decisions(parts: dict, hot=(), slow_count: int = 0,
                   knobs: dict = None) -> dict:
    """The deterministic CLUSTER-level decision fold — what each
    partition needs, independent of which node serves it. Pure: no RPC,
    no clock. Per-NODE bounding (breaker-open skip, the urgent budget)
    happens at delivery in ``localize_decisions``, per receiving node:
    every replica compacts independently, so those rules must bind at
    each receiver, not at the primary the fold would otherwise key on.

    ``parts``: {gpid: {"node", "l0_files", "debt_bytes",
    "pending_installs", "apply_gap", "ceiling_files"}} — the primary's
    beacon-reported debt/lag state. ``hot``: gpids with a confirmed
    read-hot verdict. ``slow_count``: size of the cluster slow-request
    rollup. -> {gpid: {"policy", "reasons", "node", "l0_files",
    "debt_bytes"}}."""
    k = dict(_knobs(), **(knobs or {}))
    hot = set(hot)
    out = {}
    for gpid, st in sorted(parts.items()):
        l0 = int(st.get("l0_files", 0))
        ceiling = int(st.get("ceiling_files", 0)) or max(
            1, k["urgent_l0"] * 3)
        reasons = []
        if l0 >= ceiling:
            # the engine-local trigger fires here no matter what the
            # scheduler says; agreeing keeps the status surface truthful
            # and lets manual compactions jump the queue
            policy = "urgent"
            reasons.append("debt_ceiling")
        elif gpid in hot:
            policy = "defer"
            reasons.append("hot_read")
        else:
            policy = "normal"
            if int(st.get("apply_gap", 0)) >= k["backlog_urgent"]:
                policy = "urgent"
                reasons.append("apply_backlog")
                if slow_count > 0:
                    reasons.append("slow_requests")
            if l0 >= k["urgent_l0"]:
                policy = "urgent"
                reasons.append("l0_debt")
        out[gpid] = {"policy": policy, "reasons": reasons,
                     "node": st.get("node", ""), "l0_files": l0,
                     "debt_bytes": int(st.get("debt_bytes", 0))}
    return out


def localize_decisions(decisions: dict, hosts: dict, node: str,
                       breaker_open: bool = False, cap: int = 0) -> dict:
    """Per-receiving-node half of the decision pipeline: the fold says
    what each partition needs; this bounds what ONE node is asked to do.
    Urgent tokens demote to normal (reason appended) for a breaker-open
    receiver (never promote onto a degraded device lane) and past the
    receiver's urgent budget of `cap` non-ceiling urgents (highest debt
    first, deterministic gpid tie-break); ceiling urgents pass through
    untouched (the engine-local trigger fires there regardless). A
    healthy receiver with free budget keeps every promotion — the
    demotions are per node, never global. DEFER tokens land on the
    PRIMARY only (the fold's `node`): the read-residency pin that
    justifies holding compaction lives on the primary's engine, so a
    secondary deferring would ride its debt to the ceiling's inline
    apply-path stall for zero read benefit (`defer_primary_only`).
    -> {gpid: {"policy", "reasons"}} for the partitions `node` hosts."""
    order = sorted((g for g in decisions if node in hosts.get(g, ())),
                   key=lambda g: (decisions[g]["debt_bytes"],
                                  decisions[g]["l0_files"], g),
                   reverse=True)
    mine = {}
    urgent_sent = 0
    for g in order:
        d = decisions[g]
        policy, reasons = d["policy"], list(d["reasons"])
        if policy == "urgent" and "debt_ceiling" not in reasons:
            if breaker_open:
                policy = "normal"
                reasons.append("breaker_open")
            elif cap > 0 and urgent_sent >= cap:
                policy = "normal"
                reasons.append("node_cap")
            else:
                urgent_sent += 1
        elif policy == "defer" and d.get("node") and node != d["node"]:
            policy = "normal"
            reasons.append("defer_primary_only")
        mine[g] = {"policy": policy, "reasons": reasons}
    return mine


def run_scheduler_tick(meta_addrs, pool=None, hot_gpids=None,
                       slow_count: int = 0, caller: ClusterCaller = None,
                       deliver: bool = True, knobs: dict = None) -> dict:
    """One scheduler round over the live cluster. -> report dict:
    ``{"decisions": {gpid: {...}}, "delivered": {node: {gpid: policy}},
    "nodes": N, "errors": [...]}``.

    Folds the meta's cluster-state snapshot (partition configs + the
    beacon-carried per-replica ``compact`` debt and committed/applied
    decrees) with per-node compact-lane breaker scrapes, then delivers
    each alive node the decisions for every partition it hosts (primary
    AND secondaries — each replica compacts independently) over
    ``compact-sched-policy``. Every failure is an entry in ``errors``,
    never an exception: a half-delivered round is strictly better than
    none, and undelivered tokens simply expire."""
    inject("compact.sched")  # chaos seam: a wedged/crashed tick must
    # never block writes or compactions (engine-local triggers + token
    # expiry are the fallback; see tests/test_compact_scheduler.py)
    counters.rate("sched.tick_count").increment()
    own = caller is None
    caller = caller or ClusterCaller(meta_addrs, pool=pool)
    report = {"decisions": {}, "delivered": {}, "nodes": 0, "errors": []}
    k = dict(_knobs(), **(knobs or {}))
    try:
        state = caller.meta_state()
        if state is None:
            report["errors"].append("no meta reachable")
            return report
        nodes = state.get("nodes", {})
        alive = sorted(a for a, n in nodes.items() if n.get("alive"))
        report["nodes"] = len(alive)
        breakers = {}
        for node in alive:
            try:
                snap = json.loads(caller.remote_command(
                    node, "perf-counters-by-substr",
                    ["compact.lane.breaker_open"]))
                breakers[node] = bool(snap.get("compact.lane.breaker_open"))
            except (RpcError, OSError, ValueError):
                # unknown lane state: treat as healthy — a scrape hiccup
                # must not strip a node of promotions it may need
                breakers[node] = False
        parts, hosts = {}, {}
        rs = state.get("replica_states", {})
        for app in state.get("apps", {}).values():
            for pc in app.get("partitions", []):
                gpid = f"{app['app_id']}.{pc['pidx']}"
                members = [m for m in [pc.get("primary")]
                           + pc.get("secondaries", []) if m and m in alive]
                primary = pc.get("primary")
                st = rs.get(primary, {}).get(gpid) if primary else None
                if not members or not st:
                    continue  # unserved / not yet beaconed: nothing to say
                debt = st.get("compact") or {}
                parts[gpid] = {
                    "node": primary,
                    "l0_files": debt.get("l0_files", 0),
                    "debt_bytes": debt.get("debt_bytes", 0),
                    "pending_installs": debt.get("pending_installs", 0),
                    "ceiling_files": debt.get("ceiling_files", 0),
                    "apply_gap": max(0, st.get("committed", 0)
                                     - st.get("applied", 0)),
                }
                hosts[gpid] = members
        decisions = fold_decisions(parts, hot=hot_gpids or (),
                                   slow_count=slow_count, knobs=k)
        report["decisions"] = decisions
        counters.number("sched.decisions.defer").set(
            sum(1 for d in decisions.values() if d["policy"] == "defer"))
        counters.number("sched.decisions.urgent").set(
            sum(1 for d in decisions.values() if d["policy"] == "urgent"))
        if not deliver:
            return report
        for node in alive:
            mine = localize_decisions(decisions, hosts, node,
                                      breaker_open=breakers.get(node, False),
                                      cap=k["max_urgent_per_node"])
            if not mine:
                continue
            body = {"ttl_s": k["ttl_s"], "decisions": mine}
            if k["max_device"] > 0:
                body["max_device"] = k["max_device"]
            try:
                out = caller.remote_command(node, "compact-sched-policy",
                                            [json.dumps(body)])
                report["delivered"][node] = json.loads(out)
            except (RpcError, OSError, ValueError) as e:
                counters.rate("sched.deliver_errors").increment()
                report["errors"].append(f"{node}: {e}")
    finally:
        if own:
            caller.close()
    return report


class CompactScheduler:
    """The collector-hosted control loop: one ``run_scheduler_tick`` per
    interval, the info collector's read-residency pins and slow-request
    rollup wired into the fold. Armed by ``PEGASUS_SCHED=1`` (the
    CollectorApp constructs it); ``compact-sched-status`` on the
    collector and collector-info's ``compact_sched`` key expose the last
    round's decisions."""

    def __init__(self, meta_addrs, pool=None, interval_seconds: float = None,
                 hot_fn=None, slow_fn=None):
        self.meta_addrs = list(meta_addrs)
        self.pool = pool
        self.interval = (float(os.environ.get("PEGASUS_SCHED_INTERVAL_S",
                                              "5"))
                         if interval_seconds is None else interval_seconds)
        self.hot_fn = hot_fn or (lambda: ())
        self.slow_fn = slow_fn or (lambda: 0)
        self._stop = threading.Event()
        # leaf lock over the published report (the loop writes, the
        # status command reads on an RPC thread)
        self._lock = lockrank.named_lock("sched.state")
        self._last = {}  #: guarded_by self._lock
        self._thread = spawn_thread(self._loop, daemon=True, start=False,
                                    name="compact-sched")

    def start(self) -> "CompactScheduler":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the loop and JOIN it (bounded): the caller closes the
        shared pool next, and an in-flight tick racing that close would
        spray false tick/deliver errors through every clean shutdown."""
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=timeout)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # a failed tick must never kill the
                # loop — the next interval retries, and engine tokens
                # expiring is the designed degradation
                counters.rate("sched.tick_errors").increment()
                print(f"[compact-sched] tick failed: {e!r}", flush=True)

    def tick(self) -> dict:
        report = run_scheduler_tick(self.meta_addrs, pool=self.pool,
                                    hot_gpids=self.hot_fn(),
                                    slow_count=self.slow_fn())
        with self._lock:
            self._last = report
        return report

    def status(self) -> dict:
        """The last round's report (decisions with reasons, delivery map,
        errors) — JSON-ready."""
        with self._lock:
            return dict(self._last)
