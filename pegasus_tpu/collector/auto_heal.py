"""Audit-driven auto-heal (ISSUE 17): close the detect->quarantine->heal
loop from the collector side.

PR 8's decree-anchored audits already NAME the divergent replica (the
doctor's ``evidence.audit.mismatches`` carries gpid + node + decree +
both digests), and the quarantine plane gives every replica node a
``quarantine-replica`` remote command that converts a named copy into a
forensics dir + a beacon-reported QUARANTINED state the meta re-seeds.
This module is the small, deliberately paranoid driver in between: it
watches doctor verdicts and, when the evidence isolates EXACTLY ONE odd
replica, quarantines that replica so the existing repair machinery
rebuilds it from the healthy quorum via the block-shipped delta learn.

Interlocks — an auto-healer's first duty is to never make things worse:

* gated off entirely unless ``PEGASUS_AUTOHEAL=1``;
* only CRITICAL verdicts act; inconclusive verdicts and pending audit
  evidence (unequal decrees, no-majority ties) never reach the
  mismatch list in the first place (`_check_audit` guarantees that);
* per partition, every mismatch must name the SAME single node — two
  replicas disagreeing with the reference means the reference itself is
  suspect, so no action;
* process-wide rate limit (``PEGASUS_AUTOHEAL_MIN_INTERVAL_S``, default
  60s): at most one quarantine per window — a systemic corruption wave
  (bad disk firmware, a poisoned write path) must not let the healer
  serially destroy every copy the cluster has.
"""

import os
import time

from ..runtime import events, lockrank
from ..runtime.perf_counters import counters


class AutoHealer:
    """Doctor-verdict observer: audit mismatch -> targeted quarantine."""

    def __init__(self):
        self._lock = lockrank.named_lock("autoheal.state")
        # None = never acted (monotonic starts near 0 on a fresh boot —
        # a 0.0 sentinel would falsely rate-limit the FIRST heal)
        self._last_action = None  #: guarded_by self._lock

    @staticmethod
    def _enabled() -> bool:
        return os.environ.get("PEGASUS_AUTOHEAL", "") == "1"

    @staticmethod
    def _min_interval() -> float:
        return float(os.environ.get("PEGASUS_AUTOHEAL_MIN_INTERVAL_S", "60"))

    def observe_verdict(self, verdict: dict, caller) -> list:
        """-> list of {"gpid", "node"} actions taken (empty when gated,
        interlocked, rate-limited, or nothing to heal)."""
        if not self._enabled() or verdict.get("verdict") != "critical":
            return []
        mismatches = verdict.get("evidence", {}) \
                            .get("audit", {}).get("mismatches") or []
        if not mismatches:
            return []
        by_gpid = {}
        for m in mismatches:
            by_gpid.setdefault(m["gpid"], []).append(m)
        actions = []
        for gpid, ms in sorted(by_gpid.items()):
            odd = {m["node"] for m in ms}
            if len(odd) != 1:
                # quorum does not isolate one replica: the reference
                # digest itself is suspect — never quarantine on it
                counters.rate("autoheal.vetoed_count").increment()
                events.emit("autoheal.veto", "warn", gpid=gpid,
                            nodes=sorted(odd),
                            reason="mismatch names multiple replicas")
                continue
            now = time.monotonic()
            with self._lock:
                if self._last_action is not None \
                        and now - self._last_action < self._min_interval():
                    counters.rate("autoheal.vetoed_count").increment()
                    continue  # rate-limited: next doctor round retries
                self._last_action = now
            node = next(iter(odd))
            reason = (f"audit digest mismatch at decree {ms[0]['decree']} "
                      f"(got {ms[0]['digest'][:16]} want "
                      f"{ms[0]['expected'][:16]})")
            try:
                caller.remote_command(node, "quarantine-replica",
                                      [gpid, reason])
            except Exception as e:  # noqa: BLE001 - heal is best-effort;
                # the replica may already be quarantined or the node gone
                print(f"[autoheal] {gpid}@{node}: {e!r}", flush=True)
                continue
            counters.rate("autoheal.quarantine_count").increment()
            events.emit("autoheal.quarantine", "warn", gpid=gpid,
                        node=node, reason=reason)
            actions.append({"gpid": gpid, "node": node})
        return actions


AUTO_HEALER = AutoHealer()
