"""Info collector: cluster-wide stat scraping + hotspot analysis.

The standalone "collector" service app (SURVEY.md §2.2 'Info collector
app'; reference src/server/info_collector.{h,cpp} +
hotspot_partition_calculator.h:37-70): on a timer it lists apps from meta,
scrapes every replica node's perf counters via the `perf-counters` remote
command, aggregates per-app row stats, republishes them as
`collector.app.<name>.*` counters, and runs the sigma-based hotspot
analysis over per-partition QPS — partitions more than 3 standard
deviations above the mean are flagged (and can be fed to detect_hotkey).
"""

import json
import threading

from ..meta import messages as mm
from ..meta.meta_server import RPC_CM_LIST_APPS, RPC_CM_QUERY_CONFIG
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError
from ..runtime.perf_counters import counters
from ..runtime.remote_command import RemoteCommandRequest, RemoteCommandResponse


class InfoCollector:
    def __init__(self, meta_addrs, interval_seconds: float = 10.0):
        self.meta_addrs = list(meta_addrs)
        self.interval = interval_seconds
        self.pool = ConnectionPool()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.hotspots = {}   # app_name -> [pidx...] flagged last round
        self.app_stats = {}  # app_name -> aggregated dict
        self.compact_stats = {}  # cluster-summed compact.*/engine.* counters
        self._cluster_published = set()  # gauge names set last round

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.pool.close()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.collect_once()
            except (RpcError, OSError):
                continue

    # ------------------------------------------------------------- scrape

    def _call(self, addr: str, code: str, req):
        host, _, port = addr.rpartition(":")
        conn = self.pool.get((host, int(port)))
        _, body = conn.call(code, codec.encode(req), timeout=5.0)
        return body

    def _meta_call(self, code, req, resp_cls):
        last = None
        for m in self.meta_addrs:
            try:
                return codec.decode(resp_cls, self._call(m, code, req))
            except (RpcError, OSError) as e:
                last = e
        raise last

    def scrape_node(self, addr: str, prefix: str = "") -> dict:
        req = RemoteCommandRequest("perf-counters-by-prefix", [prefix])
        body = self._call(addr, "RPC_CLI_CLI_CALL", req)
        out = codec.decode(RemoteCommandResponse, body)
        return json.loads(out.output)

    def collect_compact_stats(self, nodes) -> dict:
        """Sum every node's compaction-pipeline telemetry (compact.* stage
        spans + watchdog, engine.* flush/compaction/sst-write counters —
        runtime/tracing.py naming) and republish the cluster totals as
        `collector.cluster.*`, so one scrape of the collector answers
        'where is compaction time going cluster-wide'."""
        agg = {}
        for node in sorted(nodes):
            for prefix in ("compact.", "engine."):
                try:
                    snap = self.scrape_node(node, prefix=prefix)
                except (RpcError, OSError, ValueError):
                    continue
                for name, v in snap.items():
                    agg[name] = agg.get(name, 0.0) + float(v)
        for name, v in agg.items():
            counters.number(f"collector.cluster.{name}").set(v)
        # a counter that stops being reported (node restarted, scrape
        # failing) must not freeze at its last sum — a stale
        # collector.cluster.compact.watchdog.wedged=1 would page forever
        for name in self._cluster_published - set(agg):
            counters.number(f"collector.cluster.{name}").set(0.0)
        self._cluster_published = set(agg)
        self.compact_stats = agg
        return agg

    def collect_once(self) -> dict:
        apps = self._meta_call(RPC_CM_LIST_APPS, mm.ListAppsRequest(),
                               mm.ListAppsResponse).apps
        summary = {}
        all_nodes = set()
        for app in apps:
            cfg = self._meta_call(RPC_CM_QUERY_CONFIG,
                                  mm.QueryConfigRequest(app.app_name),
                                  mm.QueryConfigResponse)
            per_partition_qps = {}
            agg = {"get_qps": 0.0, "put_qps": 0.0, "multi_get_qps": 0.0,
                   "scan_qps": 0.0, "recent_read_cu": 0.0,
                   "recent_write_cu": 0.0,
                   # throttling activity (reference row_data
                   # recent_*_throttling_*_count, info_collector.h:73-81)
                   "recent_write_throttling_delay_count": 0.0,
                   "recent_write_throttling_reject_count": 0.0}
            nodes = {pc.primary for pc in cfg.partitions if pc.primary}
            all_nodes |= nodes
            for node in nodes:
                try:
                    snap = self.scrape_node(node, prefix=f"app.{app.app_id}.")
                except (RpcError, OSError, ValueError):
                    continue
                for name, v in snap.items():
                    # app.<id>.<pidx>.<counter>
                    parts = name.split(".")
                    if len(parts) < 4:
                        continue
                    pidx, cname = int(parts[2]), ".".join(parts[3:])
                    if cname in agg:
                        agg[cname] += v
                    if cname in ("get_qps", "put_qps", "multi_get_qps"):
                        per_partition_qps[pidx] = per_partition_qps.get(pidx, 0.0) + v
            for cname, v in agg.items():
                counters.number(f"collector.app.{app.app_name}.{cname}").set(v)
            self.hotspots[app.app_name] = hotspot_partitions(per_partition_qps)
            summary[app.app_name] = agg
        self.collect_compact_stats(all_nodes)
        self.app_stats = summary
        return summary


def hotspot_partitions(per_partition_qps: dict, sigmas: float = 3.0) -> list:
    """Sigma analysis of per-partition load (reference
    hotspot_partition_calculator::stat_histories_analyse). Each candidate is
    tested against mean + sigmas*stddev of the OTHER partitions so a single
    extreme outlier cannot inflate the threshold that hides it."""
    if len(per_partition_qps) < 3:
        return []
    out = []
    for p, v in per_partition_qps.items():
        rest = [x for q, x in per_partition_qps.items() if q != p]
        mean = sum(rest) / len(rest)
        var = sum((x - mean) ** 2 for x in rest) / len(rest)
        stddev = var ** 0.5
        if v > mean + sigmas * stddev and v > mean:
            out.append(p)
    return sorted(out)
