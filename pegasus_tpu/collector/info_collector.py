"""Info collector: cluster-wide stat scraping + hotspot analysis.

The standalone "collector" service app (SURVEY.md §2.2 'Info collector
app'; reference src/server/info_collector.{h,cpp} +
hotspot_partition_calculator.h:37-70): on a timer it lists apps from meta,
scrapes every replica node's perf counters via the `perf-counters` remote
command, aggregates per-app row stats, republishes them as
`collector.app.<name>.*` counters, and runs the sigma-based hotspot
analysis over per-partition QPS — partitions more than 3 standard
deviations above the mean are flagged, and a partition that stays flagged
for `hotkey_rounds` consecutive rounds automatically gets the
detect_hotkey start/query/stop sequence driven against its primary, the
verdict republished as `collector.app.<name>.hotkey.*` counters (the
closed hotspot loop).
"""

import configparser
import json
import os
import threading
import time

from ..meta import messages as mm
from ..meta.meta_server import RPC_CM_LIST_APPS, RPC_CM_QUERY_CONFIG
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError
from ..runtime import events, lockrank
from ..runtime.perf_counters import counters
from ..runtime.remote_command import RemoteCommandRequest, RemoteCommandResponse
from ..runtime.tasking import spawn_thread


# most recent per-table SLO verdicts computed IN THIS PROCESS (the
# collector is the evaluator; every other node's slo-status answers {}).
# Rebound wholesale by evaluate_slos — lock-free readers (the slo-status
# remote command, the doctor's _check_slo) always see a stable dict.
_SLO_LATEST = {}


def latest_slo() -> dict:
    """Per-table SLO verdicts from the last evaluate_slos() round in
    this process: {table: {"verdict": ok|warn|burning, ...evidence}}."""
    return _SLO_LATEST


def reset_slo() -> None:
    """Test hook: forget the verdicts (they otherwise outlive the
    cluster that produced them within one pytest process)."""
    global _SLO_LATEST
    _SLO_LATEST = {}


def _slo_config(tables) -> dict:
    """Resolve each table's SLO targets: the optional PEGASUS_SLO_CONFIG
    ini file's [slo] section (keys ``table.<name>.availability`` /
    ``table.<name>.p99_us``) over the PEGASUS_SLO_AVAIL /
    PEGASUS_SLO_P99_US env defaults (p99 0 = latency SLO disabled)."""
    avail = float(os.environ.get("PEGASUS_SLO_AVAIL", "0.999"))
    p99 = float(os.environ.get("PEGASUS_SLO_P99_US", "0"))
    per = {t: {"availability": avail, "p99_us": p99} for t in tables}
    path = os.environ.get("PEGASUS_SLO_CONFIG", "")
    if path:
        cp = configparser.ConfigParser()
        try:
            cp.read(path)
        except configparser.Error:
            return per
        if cp.has_section("slo"):
            for key, val in cp.items("slo"):
                parts = key.split(".")
                if len(parts) < 3 or parts[0] != "table":
                    continue
                name, field = ".".join(parts[1:-1]), parts[-1]
                if name in per and field in ("availability", "p99_us"):
                    try:
                        per[name][field] = float(val)
                    except ValueError:
                        pass
    return per


def rollup_slow_requests(fetch, nodes, last: int = 20) -> list:
    """Cluster-wide slow-request rollup (ISSUE 8 satellite): the ledger is
    node-local — merge every node's `slow-requests` output (a JSON list
    of traces with full span breakdowns; a partition-group router already
    concatenates its workers' lists through the structural fan-out merge)
    into ONE worst-first top-N, each trace tagged with the node it came
    from. `fetch(node)` is the transport (remote command) — it may return
    the raw JSON text or an already-parsed list; nodes that fail to
    answer are skipped — a rollup must degrade, not raise."""
    merged = []
    for node in nodes:
        try:
            raw = fetch(node)
            traces = json.loads(raw) if isinstance(raw, str) else raw
        except (RpcError, OSError, ValueError):
            continue
        if not isinstance(traces, list):
            continue
        for t in traces:
            if isinstance(t, dict):
                merged.append(dict(t, node=node))
    merged.sort(key=lambda t: t.get("duration_us", 0), reverse=True)
    return merged[:last]


class InfoCollector:
    def __init__(self, meta_addrs, interval_seconds: float = 10.0,
                 hotkey_rounds: int = 3, hotkey_query_limit: int = 8):
        self.meta_addrs = list(meta_addrs)
        self.interval = interval_seconds
        self.pool = ConnectionPool()
        self._stop = threading.Event()
        self._thread = spawn_thread(self._loop, daemon=True, start=False)
        self.hotspots = {}   # app_name -> [pidx...] flagged last round
        self.app_stats = {}  # app_name -> aggregated dict
        self.compact_stats = {}  # cluster-summed compact.*/engine.* counters
        self._cluster_published = set()  # gauge names set last round
        # closed hotspot loop: a partition flagged hotkey_rounds CONSECUTIVE
        # rounds gets an automatic detect_hotkey start/query/stop sequence
        # against its primary; the verdict republishes as
        # collector.app.<name>.hotkey.* counters + self.hotkey_results
        self.hotkey_rounds = hotkey_rounds
        self.hotkey_query_limit = hotkey_query_limit
        # hotkey-loop bookkeeping below is driven from the collector
        # timer thread but also reachable through remote commands /
        # collector-info reads — one leaf lock covers it
        self._lock = lockrank.named_lock("collector.hotkey")
        # (app_name, pidx) -> consecutive rounds
        self._hot_streak = {}      #: guarded_by self._lock
        # (app_name, pidx) -> in-flight state
        self._detections = {}      #: guarded_by self._lock
        # app_name -> {pidx: {"kind","key","ts"}}. WRITES hold the lock;
        # published copy-on-write (rebound wholesale, never mutated in
        # place) so lock-free readers (collector-info on an RPC thread)
        # always iterate a stable snapshot and never block behind a
        # detection round's RPCs
        self.hotkey_results = {}   #: guarded_by self._lock
        # read-residency the hotkey loop switched on: (app_name, pidx) ->
        # {"node", "gpid"} — turned off again when the partition calms,
        # closing the loop that decides which partitions' SSTs stay
        # HBM-resident for the device read path (ISSUE 7)
        self.read_residency = {}  #: guarded_by self._lock
        # cluster-wide observability rollups (ISSUE 8): worst-first top-N
        # slow requests merged across nodes, and the replication-lag
        # worst-offender summary the doctor reads
        self.cluster_slow_requests = []
        self.lag_stats = {}
        # tenant plane (ISSUE 18): cluster-folded per-table ledgers, the
        # top-k capacity attribution, and the burn-rate bookkeeping.
        # table_stats/table_top are rebound wholesale (copy-on-write like
        # hotkey_results) so the /tables route and shell read lock-free.
        self.table_stats = {}
        self.table_top = {}
        self._table_published = set()   # collector.table.* gauges set
        self._slo_samples = {}   # table -> [(ts, requests, errors), ...]
        self._slo_burning = set()  # tables burning last round (edge det.)
        # scrape robustness (ISSUE 12 satellite): a node dying
        # mid-collect_once must COUNT, not silently vanish from the
        # round's aggregates — the counter + event make a blind round
        # distinguishable from a quiet one
        self._c_scrape_err = counters.rate("collector.scrape.error_count")

    def _scrape_failed(self, node: str, what: str, err) -> None:
        self._c_scrape_err.increment()
        events.emit("collector.scrape_failed", severity="warn", node=node,
                    what=what, error=repr(err)[:200])

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.pool.close()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.collect_once()
            except (RpcError, OSError):
                continue

    # ------------------------------------------------------------- scrape

    def _call(self, addr: str, code: str, req):
        host, _, port = addr.rpartition(":")
        conn = self.pool.get((host, int(port)))
        _, body = conn.call(code, codec.encode(req), timeout=5.0)
        return body

    def _meta_call(self, code, req, resp_cls):
        last = None
        for m in self.meta_addrs:
            try:
                return codec.decode(resp_cls, self._call(m, code, req))
            except (RpcError, OSError) as e:
                last = e
        raise last

    def remote_command(self, addr: str, command: str, args) -> str:
        """Raw remote-command invocation against one node."""
        req = RemoteCommandRequest(command, list(args))
        body = self._call(addr, "RPC_CLI_CLI_CALL", req)
        return codec.decode(RemoteCommandResponse, body).output

    def scrape_node(self, addr: str, prefix: str = "") -> dict:
        return json.loads(self.remote_command(
            addr, "perf-counters-by-prefix", [prefix]))

    def collect_compact_stats(self, nodes) -> dict:
        """Sum every node's compaction-pipeline telemetry (compact.* stage
        spans + watchdog, engine.* flush/compaction/sst-write counters —
        runtime/tracing.py naming) and republish the cluster totals as
        `collector.cluster.*`, so one scrape of the collector answers
        'where is compaction time going cluster-wide'."""
        agg = {}
        for node in sorted(nodes):
            for prefix in ("compact.", "engine."):
                try:
                    snap = self.scrape_node(node, prefix=prefix)
                except (RpcError, OSError, ValueError) as e:
                    self._scrape_failed(node, f"perf-counters:{prefix}", e)
                    continue
                for name, v in snap.items():
                    if isinstance(v, dict):
                        # percentile counters export {p50..p999}: flatten
                        # to <name>.<q>; MAX across nodes — a cluster-wide
                        # latency quantile is "the worst node", never a sum
                        for q, qv in v.items():
                            key = f"{name}.{q}"
                            agg[key] = max(agg.get(key, 0.0), float(qv))
                    else:
                        agg[name] = agg.get(name, 0.0) + float(v)
        for name, v in agg.items():
            counters.number(f"collector.cluster.{name}").set(v)
        # a counter that stops being reported (node restarted, scrape
        # failing) must not freeze at its last sum — a stale
        # collector.cluster.compact.watchdog.wedged=1 would page forever
        for name in self._cluster_published - set(agg):
            counters.number(f"collector.cluster.{name}").set(0.0)
        self._cluster_published = set(agg)
        self.compact_stats = agg
        return agg

    def collect_lag_stats(self, nodes) -> dict:
        """Replication-lag plane, aggregated (ISSUE 8): scrape every
        node's per-partition `replica.*` decree gauges + `dup.lag.*`
        ship-lag gauges and republish cluster-level WORST-OFFENDER series
        (a lag quantile summed across nodes is meaningless — the signal
        is the single worst replica, named):

          collector.cluster.lag.secondary_gap_max   worst prepare lag
          collector.cluster.lag.apply_gap_max       worst committed-applied
          collector.cluster.lag.backlog_max         worst staged backlog
          collector.cluster.dup.lag_max             worst duplicator lag

        self.lag_stats keeps {series: {"value", "node", "name"}} so the
        doctor (and collector-info) can point at the offender."""
        worst = {"secondary_gap_max": (0.0, "", ""),
                 "apply_gap_max": (0.0, "", ""),
                 "backlog_max": (0.0, "", ""),
                 "dup_lag_max": (0.0, "", "")}

        def offer(series, value, node, name):
            if value > worst[series][0]:
                worst[series] = (float(value), node, name)

        for node in sorted(nodes):
            try:
                # ONE scrape per node: perf-counters-by-prefix matches
                # any of its arguments
                snap = json.loads(self.remote_command(
                    node, "perf-counters-by-prefix",
                    ["replica.", "dup.lag."]))
            except (RpcError, OSError, ValueError) as e:
                self._scrape_failed(node, "perf-counters:replica", e)
                continue
            committed, applied = {}, {}
            for name, v in snap.items():
                if isinstance(v, dict):
                    continue
                if name.startswith("dup.lag."):
                    offer("dup_lag_max", v, node, name)
                elif name.endswith(".secondary_gap_max"):
                    offer("secondary_gap_max", v, node, name)
                elif name.endswith(".backlog"):
                    offer("backlog_max", v, node, name)
                elif name.endswith(".committed_decree"):
                    committed[name[:-len(".committed_decree")]] = v
                elif name.endswith(".applied_decree"):
                    applied[name[:-len(".applied_decree")]] = v
            for part, c in committed.items():
                offer("apply_gap_max", c - applied.get(part, c), node,
                      part)
        out = {}
        for series, (value, node, name) in worst.items():
            if series == "dup_lag_max":
                counters.number("collector.cluster.dup.lag_max").set(value)
            else:
                counters.number("collector.cluster.lag." + series).set(value)
            out[series] = {"value": value, "node": node, "name": name}
        self.lag_stats = out
        return out

    def collect_slow_requests(self, nodes, last: int = 20) -> list:
        """Cluster-wide top-N slow requests (the node-local ledger merged
        worst-first; see rollup_slow_requests). Republishes the count as
        collector.cluster.slow_request_count."""
        def fetch(n):
            try:
                # parse here (rollup accepts the parsed list): a
                # truncated/garbage reply (node died mid-answer) must
                # COUNT like a refused connection does
                return json.loads(
                    self.remote_command(n, "slow-requests", [str(last)]))
            except (RpcError, OSError, ValueError) as e:
                self._scrape_failed(n, "slow-requests", e)
                raise  # rollup_slow_requests skips the node either way

        self.cluster_slow_requests = rollup_slow_requests(
            fetch, sorted(nodes), last=last)
        counters.number("collector.cluster.slow_request_count").set(
            len(self.cluster_slow_requests))
        return self.cluster_slow_requests

    def collect_table_stats(self, nodes) -> dict:
        """Tenant fold (ISSUE 18): pull every node's `table-stats`
        fragments (pid-keyed per process — a grouped node's router merge
        already concatenated its workers'), fold them cluster-wide
        (totals sum, latency percentiles MAX) and republish as
        `collector.table.<name>.*` gauges so the series land in metric
        history. Also computes the top-k capacity attribution
        (PEGASUS_TABLE_TOPK, default 5) by ops / bytes / device-seconds
        / HBM."""
        from ..runtime.table_stats import fold_snapshots, top_k

        frags = []
        for node in sorted(nodes):
            try:
                reply = json.loads(
                    self.remote_command(node, "table-stats", []))
            except (RpcError, OSError, ValueError) as e:
                self._scrape_failed(node, "table-stats", e)
                continue
            if isinstance(reply, dict):
                frags.extend(v for v in reply.values() if isinstance(v, dict))
        folded = fold_snapshots(frags)
        published = set()
        for table, m in folded.items():
            ops = (m.get("read_qps", 0) + m.get("write_qps", 0)
                   + m.get("scan_qps", 0))
            # explicit cumulative series for the slow burn window: the
            # fold ships ledger TOTALS, so first/last deltas over a
            # metric-history window are true request/error counts
            m = dict(m, ops_total=ops,
                     errors_total=m.get("errors", 0))
            for k, v in m.items():
                if isinstance(v, dict):
                    for q, qv in v.items():
                        counters.number(
                            f"collector.table.{table}.{k}.{q}").set(
                                float(qv))
                        published.add(f"collector.table.{table}.{k}.{q}")
                else:
                    counters.number(
                        f"collector.table.{table}.{k}").set(float(v))
                    published.add(f"collector.table.{table}.{k}")
            folded[table] = m
        # stale-clear (same rule as collect_compact_stats): a dropped
        # table's gauges must not freeze at their last totals
        for name in self._table_published - published:
            counters.number(name).set(0.0)
        self._table_published = published
        self.table_top = top_k(
            folded, int(os.environ.get("PEGASUS_TABLE_TOPK", "5")))
        self.table_stats = folded
        return folded

    def evaluate_slos(self) -> dict:
        """Declarative per-table SLOs with multi-window burn rate
        (ISSUE 18). For each table the error-budget burn is computed on
        a FAST window (~PEGASUS_SLO_FAST_S, from the live fold samples
        this collector keeps round to round) and a SLOW window
        (~PEGASUS_SLO_SLOW_S, first/last deltas of the republished
        cumulative series in metric history; falls back to the fast
        burn until the window holds two samples — cold start). Verdict:
        burning when BOTH windows burn >= PEGASUS_SLO_BURN_CRIT (or the
        p99 latency bound burns past it), warn at >= PEGASUS_SLO_BURN_WARN,
        ok otherwise. Each verdict carries named evidence; entering
        `burning` emits an `slo.burning` event (the flight recorder's
        trigger chain) and the slo.<table>.* gauges track the numbers."""
        global _SLO_LATEST

        now = time.time()
        fast_s = float(os.environ.get("PEGASUS_SLO_FAST_S", "300"))
        slow_s = float(os.environ.get("PEGASUS_SLO_SLOW_S", "3600"))
        warn = float(os.environ.get("PEGASUS_SLO_BURN_WARN", "1.0"))
        crit = float(os.environ.get("PEGASUS_SLO_BURN_CRIT", "2.0"))
        folded = self.table_stats
        targets = _slo_config(folded)
        verdicts = {}
        for table, m in folded.items():
            requests = m.get("ops_total", 0) + m.get("errors_total", 0)
            errors = m.get("errors_total", 0)
            hist = self._slo_samples.setdefault(table, [])
            hist.append((now, requests, errors))
            while len(hist) > 2 and hist[1][0] <= now - fast_s:
                hist.pop(0)
            budget = max(1e-9, 1.0 - targets[table]["availability"])
            # baseline = the oldest retained sample (the trim above keeps
            # at most one sample older than the window start, so this is
            # "the window's entry point", never the sample just appended)
            r0 = hist[0]
            dreq = max(0, requests - r0[1])
            derr = max(0, errors - r0[2])
            fast_burn = (derr / max(1, dreq)) / budget
            slow_burn = self._slow_burn(table, slow_s, budget, fast_burn)
            p99_bound = targets[table]["p99_us"]
            p99 = max(m.get("read_latency_us", {}).get("p99", 0),
                      m.get("write_latency_us", {}).get("p99", 0))
            lat_burn = (p99 / p99_bound) if p99_bound > 0 else 0.0
            if (fast_burn >= crit and slow_burn >= crit) or lat_burn >= crit:
                verdict = "burning"
            elif (fast_burn >= warn and slow_burn >= warn) \
                    or lat_burn >= warn:
                verdict = "warn"
            else:
                verdict = "ok"
            verdicts[table] = {
                "verdict": verdict,
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "latency_burn": round(lat_burn, 3),
                "requests_fast": dreq, "errors_fast": derr,
                "availability_target": targets[table]["availability"],
                "p99_us": p99, "p99_bound_us": p99_bound,
            }
            counters.number(f"slo.{table}.fast_burn").set(fast_burn)
            counters.number(f"slo.{table}.slow_burn").set(slow_burn)
            counters.number(f"slo.{table}.verdict").set(
                {"ok": 0, "warn": 1, "burning": 2}[verdict])
            if verdict == "burning" and table not in self._slo_burning:
                events.emit("slo.burning", severity="warn", table=table,
                            fast_burn=round(fast_burn, 3),
                            slow_burn=round(slow_burn, 3),
                            latency_burn=round(lat_burn, 3))
        self._slo_burning = {t for t, v in verdicts.items()
                             if v["verdict"] == "burning"}
        for table in set(self._slo_samples) - set(folded):
            del self._slo_samples[table]
        _SLO_LATEST = verdicts
        return verdicts

    def _slow_burn(self, table: str, slow_s: float, budget: float,
                   fallback: float) -> float:
        """Slow-window burn from metric history first/last deltas of the
        republished cumulative series; `fallback` (the fast burn) until
        the window holds two samples of the table's series."""
        from ..runtime.metric_history import HISTORY

        pfx = f"collector.table.{table}."
        win = HISTORY.window(seconds=slow_s, prefix=pfx)
        samples = [s for s in win.get("samples", [])
                   if pfx + "ops_total" in s.get("values", {})]
        if len(samples) < 2:
            return fallback
        first, last = samples[0]["values"], samples[-1]["values"]
        dreq = max(0, (last.get(pfx + "ops_total", 0)
                       + last.get(pfx + "errors_total", 0))
                   - (first.get(pfx + "ops_total", 0)
                      + first.get(pfx + "errors_total", 0)))
        derr = max(0, last.get(pfx + "errors_total", 0)
                   - first.get(pfx + "errors_total", 0))
        return (derr / max(1, dreq)) / budget

    def collect_once(self) -> dict:
        apps = self._meta_call(RPC_CM_LIST_APPS, mm.ListAppsRequest(),
                               mm.ListAppsResponse).apps
        summary = {}
        all_nodes = set()
        for app in apps:
            cfg = self._meta_call(RPC_CM_QUERY_CONFIG,
                                  mm.QueryConfigRequest(app.app_name),
                                  mm.QueryConfigResponse)
            per_partition_qps = {}
            read_qps, write_qps = {}, {}  # pidx splits for the hotkey kind
            agg = {"get_qps": 0.0, "put_qps": 0.0, "multi_get_qps": 0.0,
                   "scan_qps": 0.0, "recent_read_cu": 0.0,
                   "recent_write_cu": 0.0,
                   # throttling activity (reference row_data
                   # recent_*_throttling_*_count, info_collector.h:73-81)
                   "recent_write_throttling_delay_count": 0.0,
                   "recent_write_throttling_reject_count": 0.0}
            primaries = {pc.pidx: pc.primary for pc in cfg.partitions
                         if pc.primary}
            nodes = set(primaries.values())
            all_nodes |= nodes
            for node in nodes:
                try:
                    snap = self.scrape_node(node, prefix=f"app.{app.app_id}.")
                except (RpcError, OSError, ValueError) as e:
                    self._scrape_failed(node, f"perf-counters:app.{app.app_id}", e)
                    continue
                for name, v in snap.items():
                    if isinstance(v, dict):  # percentile counters: not qps
                        continue
                    # app.<id>.<pidx>.<counter>
                    parts = name.split(".")
                    if len(parts) < 4:
                        continue
                    pidx, cname = int(parts[2]), ".".join(parts[3:])
                    if cname in agg:
                        agg[cname] += v
                    if cname in ("get_qps", "put_qps", "multi_get_qps"):
                        per_partition_qps[pidx] = per_partition_qps.get(pidx, 0.0) + v
                        split = write_qps if cname == "put_qps" else read_qps
                        split[pidx] = split.get(pidx, 0.0) + v
            for cname, v in agg.items():
                counters.number(f"collector.app.{app.app_name}.{cname}").set(v)
            flagged = hotspot_partitions(per_partition_qps)
            self.hotspots[app.app_name] = flagged
            with self._lock:
                self.drive_hotkey_loop(app.app_name, app.app_id, flagged,
                                       primaries, read_qps, write_qps)
            summary[app.app_name] = agg
        self.collect_compact_stats(all_nodes)
        self.collect_lag_stats(all_nodes)
        self.collect_slow_requests(all_nodes)
        self.collect_table_stats(all_nodes)
        self.evaluate_slos()
        self.app_stats = summary
        return summary


    # ------------------------------------------------- closed hotspot loop

    #: requires self._lock
    def drive_hotkey_loop(self, app_name: str, app_id: int, flagged: list,
                          primaries: dict, read_qps: dict = None,
                          write_qps: dict = None) -> None:
        """The hotspot verdict used to dead-end in a docstring ("can be fed
        to detect_hotkey"); now it IS fed: a partition flagged
        `hotkey_rounds` consecutive rounds gets detect_hotkey started on
        its primary (read or write kind by whichever QPS dominates), every
        later round queries it, and a FINISHED verdict is republished as
        collector.app.<name>.hotkey.* counters + self.hotkey_results
        before the detection is stopped. Scrape failures skip a round, the
        detection survives."""
        read_qps, write_qps = read_qps or {}, write_qps or {}
        flagged_set = set(flagged)
        # streak bookkeeping: consecutive rounds flagged, reset when calm
        for pidx in flagged_set:
            self._hot_streak[(app_name, pidx)] = \
                self._hot_streak.get((app_name, pidx), 0) + 1
        for key in [k for k in self._hot_streak
                    if k[0] == app_name and k[1] not in flagged_set]:
            del self._hot_streak[key]
        # a published verdict gauge must clear once the partition calms
        # (the streak entry is gone by then — key off the verdicts, or a
        # fixed hot key would page as hot forever); calming also releases
        # the read residency the verdict switched on
        for pidx in self.hotkey_results.get(app_name, {}):
            if pidx not in flagged_set and (app_name, pidx) not in self._detections:
                counters.number(
                    f"collector.app.{app_name}.hotkey.{pidx}.hot").set(0)
                self._set_read_residency(app_name, pidx, on=False)
        # start a detection once the streak proves the hotspot persistent
        for pidx in sorted(flagged_set):
            key = (app_name, pidx)
            if (self._hot_streak.get(key, 0) < self.hotkey_rounds
                    or key in self._detections or pidx not in primaries):
                continue
            kind = ("write" if write_qps.get(pidx, 0.0)
                    > read_qps.get(pidx, 0.0) else "read")
            gpid = f"{app_id}.{pidx}"
            try:
                out = self.remote_command(primaries[pidx], "detect_hotkey",
                                          [gpid, kind, "start"])
            except (RpcError, OSError):
                continue
            if "started" in out:
                self._detections[key] = {"node": primaries[pidx],
                                         "gpid": gpid, "kind": kind,
                                         "queries": 0}
                counters.rate(
                    f"collector.app.{app_name}.hotkey.detections_started"
                ).increment()
        # query in-flight detections; republish + stop on a verdict
        for key, det in [(k, d) for k, d in self._detections.items()
                         if k[0] == app_name]:
            pidx = key[1]
            if primaries.get(pidx, det["node"]) != det["node"]:
                # primary moved: the detector state died with the old
                # node — abandon so a fresh streak can restart detection
                # against the new primary
                self._finish_detection(key, det)
                continue
            try:
                out = self.remote_command(det["node"], "detect_hotkey",
                                          [det["gpid"], det["kind"], "query"])
            except (RpcError, OSError):
                # an unreachable node must not pin the detection forever:
                # failed rounds count against the same query budget
                det["queries"] += 1
                if det["queries"] > self.hotkey_query_limit:
                    self._finish_detection(key, det)
                continue
            if "hotkey:" in out:
                hotkey = out.split("hotkey:", 1)[1].strip()
                per_app = dict(self.hotkey_results.get(app_name, {}))
                per_app[pidx] = {"kind": det["kind"], "key": hotkey,
                                 "ts": time.time()}
                self.hotkey_results = {**self.hotkey_results,
                                       app_name: per_app}
                counters.rate(
                    f"collector.app.{app_name}.hotkey.found_count").increment()
                counters.number(
                    f"collector.app.{app_name}.hotkey.{pidx}.hot").set(1)
                if det["kind"] == "read":
                    # a confirmed read hotspot pins the partition's SSTs
                    # HBM-resident so its point reads serve from the
                    # device lookup path (released when it calms)
                    self._set_read_residency(app_name, pidx, on=True,
                                             node=det["node"],
                                             gpid=det["gpid"])
                self._finish_detection(key, det)
            elif "STOPPED" in out:    # detector timed out without an outlier
                self._finish_detection(key, det, stop=False)
            else:
                det["queries"] += 1
                if det["queries"] > self.hotkey_query_limit:
                    self._finish_detection(key, det)
        counters.number(
            f"collector.app.{app_name}.hotkey.active_detections").set(
            sum(1 for k in self._detections if k[0] == app_name))

    #: requires self._lock
    def _set_read_residency(self, app_name: str, pidx: int, on: bool,
                            node: str = None, gpid: str = None) -> None:
        """Flip one partition's device read residency on its primary via
        the set-read-residency remote command; bookkeeping in
        self.read_residency so calming turns off exactly what a verdict
        turned on. Failures are dropped — the next verdict (or calm
        round) retries, and residency is a hint, not state."""
        key = (app_name, pidx)
        if on:
            target = {"node": node, "gpid": gpid}
        else:
            target = self.read_residency.get(key)
            if target is None:
                return  # never switched on (or already released)
        try:
            self.remote_command(target["node"], "set-read-residency",
                                [target["gpid"], "on" if on else "off"])
        except (RpcError, OSError):
            # state untouched either way: a failed ON is not resident (a
            # later verdict retries), a failed OFF keeps its bookkeeping
            # so the next calm round resends the release — the server's
            # flag must not stay hot because one RPC was dropped
            return
        # copy-on-write publish (see hotkey_results): readers are free
        rr = dict(self.read_residency)
        if on:
            rr[key] = target
        else:
            rr.pop(key, None)
        self.read_residency = rr
        counters.number(
            f"collector.app.{app_name}.hotkey.{pidx}.device_resident").set(
            1 if on else 0)

    def _finish_detection(self, key, det, stop: bool = True) -> None:  #: requires self._lock
        self._detections.pop(key, None)
        self._hot_streak.pop(key, None)
        if stop:
            try:
                self.remote_command(det["node"], "detect_hotkey",
                                    [det["gpid"], det["kind"], "stop"])
            except (RpcError, OSError):
                pass


def hotspot_partitions(per_partition_qps: dict, sigmas: float = 3.0) -> list:
    """Sigma analysis of per-partition load (reference
    hotspot_partition_calculator::stat_histories_analyse). Each candidate is
    tested against mean + sigmas*stddev of the OTHER partitions so a single
    extreme outlier cannot inflate the threshold that hides it."""
    if len(per_partition_qps) < 3:
        return []
    out = []
    for p, v in per_partition_qps.items():
        rest = [x for q, x in per_partition_qps.items() if q != p]
        mean = sum(rest) / len(rest)
        var = sum((x - mean) ** 2 for x in rest) / len(rest)
        stddev = var ** 0.5
        if v > mean + sigmas * stddev and v > mean:
            out.append(p)
    return sorted(out)
