"""Flight recorder: automatic incident capture + first-cause correlation
(ISSUE 12's conclusion layer).

The event ring (runtime/events.py) and the metric-history ring
(runtime/metric_history.py) give every PROCESS a recorded past; this
module is the cluster-level consumer that turns them into ONE retained
artifact the moment something goes wrong, instead of asking an operator
to re-reproduce a transient:

  * ``capture()`` pulls every alive node's event ring (``events-dump``),
    metric-history window (``metrics-history``), slow-request ledger and
    recent request traces, adds the CAPTURING process's own ring (the
    doctor/audit verdict events land there), aligns everything on one
    wall-clock anchor, runs the first-cause heuristic — the EARLIEST
    event inside the window from the classes that start failure
    cascades (fail-point arm, breaker trip, scheduler-lease expiry, meta
    election/epoch bump) — and writes one JSON artifact into the
    retained incident directory (bounded: oldest pruned past
    ``PEGASUS_INCIDENT_KEEP``).

  * ``observe_verdict()`` is the doctor hook: a healthy→degraded/critical
    transition auto-captures (cooldown-bounded so a flapping cluster
    cannot spam artifacts), and the incident id is embedded in the
    doctor's verdict so every surface that shows the verdict points at
    the evidence bundle.

  * chaos wiring: ``EventJournal.on_fail`` (pegasus_tpu/chaos/journal.py)
    lets tools/pressure_test.py capture on the FIRST named failure of a
    run — the artifact then rides the journal, and a falsification run
    (``--inject-fault audit.digest=return(...)``) yields an incident
    whose first cause names the planted fault's arm event.

Surfaces: ``GET /incidents`` (meta + collector http), the collector's
``trigger-incident`` remote command, the shell's ``flight_recorder``.
Counters: ``incident.capture_count``.
"""

import json
import os
import tempfile
import time

from ..rpc.transport import RpcError
from ..runtime import events, lockrank
from ..runtime.job_trace import JOB_TRACER
from ..runtime.perf_counters import counters
from .cluster_doctor import ClusterCaller

# event names that START failure cascades, in no particular order — the
# heuristic picks the EARLIEST one inside the window, which is exactly
# what "first cause" means on an aligned timeline
FIRST_CAUSE_NAMES = frozenset((
    "failpoint.arm",
    "lane.breaker_trip",
    "sched.token_expired",
    "meta.election",
    "meta.epoch_bump",
))


def _incident_dir() -> str:
    return os.environ.get("PEGASUS_INCIDENT_DIR") or os.path.join(
        tempfile.gettempdir(), "pegasus-incidents")


def _keep() -> int:
    return max(1, int(os.environ.get("PEGASUS_INCIDENT_KEEP", "16")))


def _window_s() -> float:
    return float(os.environ.get("PEGASUS_INCIDENT_WINDOW_S", "120"))


def _cooldown_s() -> float:
    return float(os.environ.get("PEGASUS_INCIDENT_COOLDOWN_S", "30"))


class FlightRecorder:
    def __init__(self):
        self._lock = lockrank.named_lock("flight.recorder")
        self._seq = 0                 #: guarded_by self._lock
        self._last_verdict = None     #: guarded_by self._lock
        self._last_capture_ts = 0.0   #: guarded_by self._lock
        self._last_incident_id = None  #: guarded_by self._lock
        self._c_capture = counters.rate("incident.capture_count")

    # ------------------------------------------------------------- capture

    def capture(self, meta_addrs, reason: str, trigger: str = "manual",
                pool=None, caller: ClusterCaller = None,
                window_s: float = None) -> dict:
        """Pull, align, conclude, retain. Never raises on a partially
        reachable cluster — whatever could not be scraped is listed under
        ``errors`` and the artifact still lands (a flight recorder that
        needs a healthy cluster to record records nothing useful)."""
        window_s = _window_s() if window_s is None else float(window_s)
        anchor = time.time()
        own = caller is None
        caller = caller or ClusterCaller(meta_addrs, pool=pool, timeout=3.0)
        nodes_detail, errors, timeline = {}, [], []
        try:
            state = caller.meta_state()
            alive = sorted(a for a, n in (state or {}).get("nodes", {}).items()
                           if n.get("alive"))
            if state is None:
                errors.append("no meta reachable: artifact holds the "
                              "capturing process's ring only")
            for node in alive:
                nodes_detail[node] = self._pull_node(
                    caller, node, window_s, anchor, timeline, errors)
        finally:
            if own:
                caller.close()
        # the capturing process's own ring: audit/doctor verdict events,
        # plus (in an in-process onebox harness) every local subsystem
        local = f"local:{os.getpid()}"
        local_events = events.EVENTS.snapshot(since=anchor - window_s)
        for ev in local_events:
            timeline.append(dict(ev, node=local, pid=f"pid:{os.getpid()}"))
        timeline.sort(key=lambda e: (e["ts"], e.get("node", ""),
                                     e.get("seq", 0)))
        # dedup by (pid, seq, name, ts): in an in-process onebox every
        # "node" answers events-dump from the SAME ring, and the
        # capturing process's own snapshot is that ring again — one copy
        # of each event keeps the timeline honest (the surviving node
        # label says which scrape reached the shared process first).
        # name+ts stay in the key so two HOSTS whose OS pids happen to
        # collide never collapse distinct events into one.
        seen, deduped = set(), []
        for ev in timeline:
            key = (ev.get("pid"), ev.get("seq"), ev.get("name"),
                   ev.get("ts"))
            if key in seen:
                continue
            seen.add(key)
            deduped.append(ev)
        timeline = deduped
        for ev in timeline:
            ev["t_rel"] = round(ev["ts"] - anchor, 3)
        first_cause = next((e for e in timeline
                            if e["name"] in FIRST_CAUSE_NAMES), None)
        with self._lock:
            self._seq += 1
            incident_id = f"inc-{int(anchor)}-{os.getpid()}-{self._seq}"
        incident = {
            "id": incident_id,
            "anchor_ts": anchor,
            "window_s": window_s,
            "reason": reason,
            "trigger": trigger,
            "first_cause": first_cause,
            "timeline": timeline,
            "nodes": nodes_detail,
            "local_events": len(local_events),
            # the capturing process's own in-window job timelines — in an
            # in-process onebox this is every plane's shared tracer view
            "jobs": JOB_TRACER.window(window_s),
            "errors": errors,
        }
        # tenant plane (ISSUE 18): when a table is burning its SLO, the
        # artifact embeds that table's in-window series (the ledger's
        # table.<name>.* charges plus the evaluator's slo.<name>.* burn
        # gauges from the local history ring) — the incident names the
        # tenant AND carries the numbers that convicted it
        try:
            from ..runtime.metric_history import HISTORY
            from .info_collector import latest_slo

            slo_tables = {}
            for table, v in latest_slo().items():
                if v.get("verdict") != "burning":
                    continue
                slo_tables[table] = {
                    "verdict": v,
                    "series": HISTORY.window(seconds=window_s,
                                             prefix=f"table.{table}."),
                    "slo_series": HISTORY.window(seconds=window_s,
                                                 prefix=f"slo.{table}."),
                }
            if slo_tables:
                incident["slo_tables"] = slo_tables
        except Exception as e:  # noqa: BLE001 - embed is best-effort
            errors.append(f"slo_tables: {e!r}")
        incident["path"] = self._retain(incident)
        self._c_capture.increment()
        events.emit("incident.captured", severity="warn", id=incident_id,
                    reason=reason[:200], trigger=trigger,
                    first_cause=(first_cause or {}).get("name", ""))
        with self._lock:
            self._last_capture_ts = anchor
            self._last_incident_id = incident_id
        return incident

    def _pull_node(self, caller, node, window_s, anchor, timeline,
                   errors) -> dict:
        """One node's share of the artifact: events merged into the
        timeline, history/slow/trace kept per node."""
        detail = {}
        try:
            dumped = json.loads(caller.remote_command(
                node, "events-dump", []))
            n_events = 0
            for pid_key, evs in dumped.items():
                for ev in evs:
                    if ev.get("ts", 0) >= anchor - window_s:
                        timeline.append(dict(ev, node=node, pid=pid_key))
                        n_events += 1
            detail["events"] = n_events
        except (RpcError, OSError, ValueError) as e:
            errors.append(f"{node}: events-dump: {e}")
        try:
            detail["history"] = json.loads(caller.remote_command(
                node, "metrics-history", [str(window_s)]))
        except (RpcError, OSError, ValueError) as e:
            errors.append(f"{node}: metrics-history: {e}")
        try:
            detail["slow_requests"] = json.loads(caller.remote_command(
                node, "slow-requests", ["10"]))
        except (RpcError, OSError, ValueError) as e:
            errors.append(f"{node}: slow-requests: {e}")
        try:
            detail["traces"] = json.loads(caller.remote_command(
                node, "request-trace-dump", ["10"]))
        except (RpcError, OSError, ValueError) as e:
            errors.append(f"{node}: request-trace-dump: {e}")
        try:
            # the background-job timelines (ISSUE 16): a first-cause
            # event can name the compaction/offload/learn job it wedged
            detail["jobs"] = json.loads(caller.remote_command(
                node, "job-trace", ["20"]))
        except (RpcError, OSError, ValueError) as e:
            errors.append(f"{node}: job-trace: {e}")
        return detail

    # ----------------------------------------------------------- retention

    def _retain(self, incident: dict) -> str:
        """Write the artifact; prune to the newest PEGASUS_INCIDENT_KEEP.
        A failed write degrades to an unretained (in-memory) incident —
        the capture still returns."""
        d = _incident_dir()
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, incident["id"] + ".json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(incident, f, indent=1, default=str)
            os.replace(tmp, path)
            kept = sorted(
                (p for p in os.listdir(d) if p.endswith(".json")),
                key=lambda p: os.path.getmtime(os.path.join(d, p)))
            for stale in kept[:-_keep()]:
                try:
                    os.unlink(os.path.join(d, stale))
                except OSError:
                    pass
            return path
        except OSError as e:
            incident["errors"].append(f"retention failed: {e}")
            return ""

    def list_incidents(self) -> list:
        """Retained artifacts, newest first: [{id, ts, reason, trigger,
        first_cause}] — the light listing GET /incidents serves."""
        d = _incident_dir()
        out = []
        try:
            names = sorted(
                (p for p in os.listdir(d) if p.endswith(".json")),
                key=lambda p: os.path.getmtime(os.path.join(d, p)),
                reverse=True)
        except OSError:
            return out
        for name in names:
            try:
                with open(os.path.join(d, name)) as f:
                    inc = json.load(f)
            except (OSError, ValueError):
                continue
            out.append({"id": inc.get("id", name[:-5]),
                        "ts": inc.get("anchor_ts"),
                        "reason": inc.get("reason"),
                        "trigger": inc.get("trigger"),
                        "first_cause": (inc.get("first_cause") or {}
                                        ).get("name")})
        return out

    def load(self, incident_id: str):
        """One full artifact by id, or None. The id is caller-supplied
        (GET /incidents?id=...), so anything that could escape the
        incident dir is rejected, not joined."""
        if (not incident_id or ".." in incident_id
                or incident_id != os.path.basename(incident_id)):
            return None
        path = os.path.join(_incident_dir(), incident_id + ".json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ---------------------------------------------------------- auto-wire

    def observe_verdict(self, verdict: dict, meta_addrs,
                        caller: ClusterCaller = None):
        """The cluster doctor's hook: capture on a healthy→degraded/
        critical transition (cooldown-bounded); while the cluster STAYS
        unhealthy inside the cooldown the last incident's id keeps
        riding the verdict, so a second doctor run minutes into the same
        incident points at the same artifact. -> incident id or None."""
        v = verdict.get("verdict")
        now = time.time()
        with self._lock:
            prev, self._last_verdict = self._last_verdict, v
            if v not in ("degraded", "critical"):
                return None
            if prev in ("degraded", "critical"):
                # the SAME incident continuing: keep pointing at it
                return self._last_incident_id
            if now - self._last_capture_ts < _cooldown_s():
                # a FRESH transition inside the cooldown (flapping
                # cluster): no capture — and no id either, because the
                # last artifact documents a different excursion and
                # attaching it here would mislabel the evidence
                return None
        inc = self.capture(meta_addrs,
                           reason="doctor verdict "
                                  f"{prev or 'unseen'} -> {v}: "
                           + "; ".join(c["cause"] for c in
                                       verdict.get("causes", [])[:3]),
                           trigger="doctor", caller=caller)
        return inc["id"]

    def reset(self) -> None:
        """Test hook: forget verdict/cooldown state (artifacts stay)."""
        with self._lock:
            self._last_verdict = None
            self._last_capture_ts = 0.0
            self._last_incident_id = None


# process-wide recorder (verdict-transition state is per process, like
# the event ring it correlates)
RECORDER = FlightRecorder()
