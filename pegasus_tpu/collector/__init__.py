from .available_detector import AvailableDetector
from .info_collector import InfoCollector, hotspot_partitions
from .reporter import CounterReporter, falcon_payload, prometheus_text

__all__ = ["AvailableDetector", "InfoCollector", "hotspot_partitions",
           "CounterReporter", "falcon_payload", "prometheus_text"]
