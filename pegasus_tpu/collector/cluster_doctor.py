"""Cluster doctor: fold everything the cluster exports into ONE verdict.

ISSUE 8's conclusion layer. PR 1-7 made the system export everything —
stage spans, request traces, the slow-request ledger, per-partition
inflight/backlog, lane/breaker state, HBM residency — but nothing
*concluded* anything from it. This module holds the two consumers:

- ``run_cluster_audit``: the decree-anchored consistency audit driver.
  For every partition it fires the ``trigger-audit`` remote command on
  the primary (a no-op mutation riding the normal PacificA prepare path,
  so primary and every secondary compute an order-independent engine
  digest at the SAME applied decree), then collects each secondary's
  digest via ``query-audit`` and compares AT EQUAL DECREES ONLY. A node
  that cannot report (dead, reconfiguring, never applied) degrades that
  partition to *inconclusive* — never a false mismatch.

- ``run_cluster_doctor``: one structured verdict
  (``healthy | degraded | critical | inconclusive``) with named causes
  and evidence pointers, folded from the meta's one-RPC cluster-state
  snapshot (liveness + partition configs + beacon-folded lag/audit
  states) plus per-node scrapes (lane/breaker state, dispatch queue
  depth) and the cluster-wide slow-request rollup. Served as
  ``GET /health/cluster``, the ``cluster-doctor`` remote command on the
  collector, and the shell's ``cluster_doctor``.

Both are pure functions over RPC surfaces: the collector app, the shell,
``bench.py`` and ``tools/pressure_test.py`` all call the same code.
"""

import json
import os
import threading
import time

from ..base.utils import epoch_now
from ..meta import messages as mm
from ..meta.meta_server import RPC_CM_QUERY_CLUSTER_STATE
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError
from ..runtime import events
from ..runtime.perf_counters import counters
from ..runtime.remote_command import (RemoteCommandRequest,
                                      RemoteCommandResponse)

HEALTHY = "healthy"
DEGRADED = "degraded"
CRITICAL = "critical"
INCONCLUSIVE = "inconclusive"
_VERDICT_GAUGE = {HEALTHY: 0, DEGRADED: 1, CRITICAL: 2, INCONCLUSIVE: -1}


class ClusterCaller:
    """Thin RPC helper the audit and the doctor share: meta cluster-state
    query + remote commands against nodes. Pass an existing pool (the
    collector's) or let it own one (shell / tools one-shots)."""

    def __init__(self, meta_addrs, pool: ConnectionPool = None,
                 timeout: float = 5.0):
        self.meta_addrs = list(meta_addrs)
        self._own_pool = pool is None
        self.pool = pool or ConnectionPool()
        self.timeout = timeout

    def close(self):
        if self._own_pool:
            self.pool.close()

    def _call(self, addr: str, code: str, body: bytes) -> bytes:
        host, _, port = addr.rpartition(":")
        conn = self.pool.get((host, int(port)))
        _, out = conn.call(code, body, timeout=self.timeout)
        return out

    def meta_state(self):
        """The meta's cluster-state snapshot, or None when no meta
        answers (the doctor then reports inconclusive, not healthy)."""
        body = codec.encode(mm.QueryClusterStateRequest())
        for m in self.meta_addrs:
            try:
                resp = codec.decode(mm.QueryClusterStateResponse,
                                    self._call(m, RPC_CM_QUERY_CLUSTER_STATE,
                                               body))
                return json.loads(resp.state_json)
            except (RpcError, OSError, ValueError):
                continue
        return None

    def remote_command(self, addr: str, command: str, args) -> str:
        body = self._call(addr, "RPC_CLI_CLI_CALL", codec.encode(
            RemoteCommandRequest(command, list(args))))
        return codec.decode(RemoteCommandResponse, body).output


# =========================================================== audit driver


def run_cluster_audit(meta_addrs, pool: ConnectionPool = None,
                      apps: list = None, wait_s: float = 5.0,
                      caller: ClusterCaller = None, now: int = None) -> dict:
    """Trigger + verify a decree-anchored consistency audit across every
    partition of every (or the named) app. -> report dict:

    ``{"partitions": N, "ok": [gpid...], "mismatches": [{app, app_id,
    pidx, gpid, node, decree, digest, expected}...], "inconclusive":
    [{gpid, node?, reason}...], "digests": {gpid: {node: {decree,
    digest}}}, "primaries": {gpid: {node, decree, digest, records}}}``

    Zero mismatches with every partition in ``ok`` means every replica
    held byte-equivalent logical state at the same applied decree —
    the pass criterion the production-sim scenario builds on.
    ``primaries`` carries each partition's anchor (the primary's digest
    + record count at the anchoring decree): the cross-cluster compare
    folds these into one table-level digest. `now` (epoch seconds)
    overrides each primary's own expiry clock — the cross-cluster
    compare passes ONE instant to both clusters so a TTL record
    expiring between the two audits cannot fake a mismatch."""
    own = caller is None
    caller = caller or ClusterCaller(meta_addrs, pool=pool)
    report = {"partitions": 0, "ok": [], "mismatches": [],
              "inconclusive": [], "digests": {}, "primaries": {}}
    try:
        state = caller.meta_state()
        if state is None:
            report["inconclusive"].append(
                {"gpid": "*", "reason": "no meta reachable"})
            return report
        for app_name, app in sorted(state.get("apps", {}).items()):
            if apps and app_name not in apps:
                continue
            for pc in app.get("partitions", []):
                report["partitions"] += 1
                _audit_partition(caller, report, app_name, app["app_id"],
                                 pc, wait_s, now)
    finally:
        if own:
            caller.close()
    return report


def _audit_partition(caller, report, app_name, app_id, pc, wait_s, now=None):
    gpid = f"{app_id}.{pc['pidx']}"
    if not pc.get("primary"):
        report["inconclusive"].append(
            {"gpid": gpid, "reason": "no primary assigned"})
        return
    args = [gpid] if now is None else [gpid, f"now={int(now)}"]
    try:
        out = caller.remote_command(pc["primary"], "trigger-audit", args)
    except (RpcError, OSError) as e:
        report["inconclusive"].append(
            {"gpid": gpid, "node": pc["primary"],
             "reason": f"primary unreachable: {e}"})
        return
    try:
        primary_audit = json.loads(out) if out else {}
    except ValueError:
        primary_audit = {}
    if not primary_audit or primary_audit.get("error"):
        report["inconclusive"].append(
            {"gpid": gpid, "node": pc["primary"],
             "reason": primary_audit.get("error", "no trigger-audit reply")})
        return
    decree = primary_audit["decree"]
    expected = primary_audit["digest"]
    digests = {pc["primary"]: {"decree": decree, "digest": expected}}
    report["digests"][gpid] = digests
    report["primaries"][gpid] = {
        "node": pc["primary"], "decree": decree, "digest": expected,
        "records": primary_audit.get("records", 0)}
    clean = True
    for node in pc.get("secondaries", []):
        got = _poll_secondary_audit(caller, node, gpid, decree, wait_s)
        if got is None:
            report["inconclusive"].append(
                {"gpid": gpid, "node": node,
                 "reason": f"no digest at decree {decree} within "
                           f"{wait_s:.1f}s (dead / reconfiguring / "
                           "superseded)"})
            clean = False
            continue
        digests[node] = got
        if got["digest"] != expected:
            report["mismatches"].append(
                {"app": app_name, "app_id": app_id, "pidx": pc["pidx"],
                 "gpid": gpid, "node": node, "decree": decree,
                 "digest": got["digest"], "expected": expected})
            events.emit("audit.mismatch", severity="error", gpid=gpid,
                        node=node, decree=decree)
            clean = False
    if clean:
        report["ok"].append(gpid)


def _poll_secondary_audit(caller, node, gpid, decree, wait_s):
    """-> {"decree", "digest"} once the node reports an audit AT `decree`,
    or None on timeout/unreachable/superseded. Comparing at EQUAL decrees
    only is what makes a group kill degrade to inconclusive instead of a
    false mismatch."""
    deadline = time.monotonic() + wait_s
    while True:
        try:
            out = caller.remote_command(node, "query-audit", [gpid])
            ent = json.loads(out).get(gpid, {})
            audit = ent.get("audit")
            if audit and audit.get("decree", 0) >= decree:
                if audit["decree"] != decree:
                    return None  # superseded by a newer audit: inconclusive
                if not audit.get("digest"):
                    return None  # digest computation failed: inconclusive
                return {"decree": audit["decree"],
                        "digest": audit["digest"]}
        except (RpcError, OSError, ValueError):
            pass
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)


# ============================================== cross-cluster audit (dup)


def fold_table_digest(entries) -> dict:
    """Commutative table-level fold of per-partition engine digests.
    Each per-partition digest is ``{xor:016x}{add:016x}`` over one crc64
    per live record (engine.state_digest) — both combines are
    commutative AND associative, so folding partitions (xor of xors,
    sum of adds, sum of counts) yields the digest of the whole table's
    record SET, independent of how records are partitioned. That is what
    makes the cross-cluster compare survive a mid-run partition split:
    the source may hold 2N partitions while the remote still holds N,
    but the folded table digests compare 1:1."""
    xor = add = n = 0
    for digest, records in entries:
        xor ^= int(digest[:16], 16)
        add = (add + int(digest[16:32], 16)) & 0xFFFFFFFFFFFFFFFF
        n += int(records)
    return {"digest": f"{xor:016x}{add:016x}", "records": n}


def run_cross_cluster_audit(src_meta_addrs, dst_meta_addrs, app: str,
                            dupid: int = None, wait_s: float = 20.0,
                            confirm_wait_s: float = 30.0,
                            pool: ConnectionPool = None) -> dict:
    """Cross-CLUSTER consistency compare for a duplication leg (ISSUE
    11), anchored at the duplicator's confirmed decree. Requires the
    caller to have QUIESCED writes to `app` (the chaos harness runs it
    after the load stops): shipping is asynchronous, so the compare
    waits for the duplicators to confirm through the anchor rather than
    assuming they are caught up.

    Protocol:

    1. decree-anchored audit on the SOURCE cluster: every partition's
       primary digests its owned live state at an anchor decree;
    2. wait until the meta's beacon-folded dup ``confirmed`` decree
       reaches each partition's anchor — every mutation below the
       anchor has then been shipped AND acked by the remote cluster
       (the remote acks only after its own PacificA commit+apply);
    3. decree-anchored audit on the DESTINATION cluster;
    4. fold both sides' per-partition digests into one table-level
       digest each (fold_table_digest) and compare.

    -> ``{"app", "match": True|False|None, "src", "dst",
    "anchors": {gpid: decree}, "confirmed": {pidx: decree},
    "inconclusive": [reason...], "mismatches": [...]}`` — ``match`` is
    None when any step was inconclusive (never a false mismatch)."""
    report = {"app": app, "match": None, "src": None, "dst": None,
              "anchors": {}, "confirmed": {}, "inconclusive": [],
              "mismatches": []}
    caller = ClusterCaller(src_meta_addrs, pool=pool)
    try:
        state = caller.meta_state()
        if state is None or app not in state.get("apps", {}):
            report["inconclusive"].append(
                f"source cluster state unavailable or no app {app!r}")
            return report
        app_id = state["apps"][app]["app_id"]
        entry = _pick_dup_entry(state, app_id, dupid)
        if entry is None:
            report["inconclusive"].append(
                f"no active duplication on {app!r} "
                f"(dupid={dupid if dupid is not None else 'any'})")
            return report
        report["dupid"] = entry["dupid"]
        # ONE expiry anchor for both sides: the audits run seconds apart,
        # and a TTL record expiring in between would otherwise diverge
        # the two digests on byte-identical data (false mismatch)
        audit_now = epoch_now()
        src_audit = run_cluster_audit(src_meta_addrs, apps=[app],
                                      wait_s=wait_s, pool=pool,
                                      now=audit_now)
        if len(src_audit["ok"]) != src_audit["partitions"] \
                or not src_audit["primaries"]:
            report["inconclusive"].append(
                "source audit incomplete: "
                f"{len(src_audit['ok'])}/{src_audit['partitions']} "
                "partitions conclusive")
            report["src_audit"] = {k: src_audit[k]
                                   for k in ("mismatches", "inconclusive")}
            return report
        report["anchors"] = {g: p["decree"]
                             for g, p in src_audit["primaries"].items()}
        lagging = _wait_confirmed(caller, app, app_id, entry["dupid"],
                                  src_audit["primaries"], confirm_wait_s,
                                  report)
        if lagging:
            report["inconclusive"].append(
                "duplicator confirmed decree never reached the anchor "
                f"within {confirm_wait_s:.0f}s for partition(s) {lagging}")
            return report
    finally:
        caller.close()
    dst_audit = run_cluster_audit(dst_meta_addrs, apps=[app], wait_s=wait_s,
                                  now=audit_now)
    if len(dst_audit["ok"]) != dst_audit["partitions"] \
            or not dst_audit["primaries"]:
        report["inconclusive"].append(
            "destination audit incomplete: "
            f"{len(dst_audit['ok'])}/{dst_audit['partitions']} "
            "partitions conclusive")
        return report
    report["src"] = fold_table_digest(
        (p["digest"], p["records"]) for p in src_audit["primaries"].values())
    report["dst"] = fold_table_digest(
        (p["digest"], p["records"]) for p in dst_audit["primaries"].values())
    report["match"] = report["src"]["digest"] == report["dst"]["digest"] \
        and report["src"]["records"] == report["dst"]["records"]
    if not report["match"]:
        report["mismatches"].append(
            {"app": app, "src": report["src"], "dst": report["dst"],
             "anchors": report["anchors"]})
    return report


def _pick_dup_entry(state, app_id: int, dupid):
    for e in state.get("dups", {}).get(str(app_id), []):
        if dupid is not None and e.get("dupid") != dupid:
            continue
        if dupid is not None or e.get("status") == "start":
            return e
    return None


def _wait_confirmed(caller, app, app_id, dupid, primaries, confirm_wait_s,
                    report):
    """Poll the source meta until the dup entry's beacon-folded confirmed
    decree reaches every partition's anchor. -> list of lagging pidx
    (empty = fully confirmed)."""
    anchors = {int(g.split(".")[1]): p["decree"] for g, p in primaries.items()}
    deadline = time.monotonic() + confirm_wait_s
    while True:
        state = caller.meta_state()
        conf = {}
        if state is not None:
            e = _pick_dup_entry(state, app_id, dupid)
            conf = (e or {}).get("confirmed", {})
        report["confirmed"] = conf
        lagging = [p for p, d in sorted(anchors.items())
                   if int(conf.get(str(p), 0)) < d]
        if not lagging or time.monotonic() >= deadline:
            return lagging
        time.sleep(0.2)


# ===================================================== periodic audit rounds


class AuditRounds:
    """Periodic decree-anchored audit cadence for pressure/chaos runs
    (ISSUE 11 satellite): instead of ONE audit at t/2 — which a mismatch
    introduced late in the run slips past — a background thread audits
    every `every_s` seconds with per-round conclusive/vacuous
    bookkeeping. A round is *conclusive* when every partition landed in
    ``ok``; zero mismatches without full coverage is *vacuous* and says
    nothing. Counters: ``audit.round.count`` / ``.conclusive`` /
    ``.vacuous`` / ``.mismatch_count``.

    `journal` is any object with ``record(kind, **fields)`` and
    ``fail(name, **fields)`` (chaos.journal.EventJournal); None = no
    journaling."""

    def __init__(self, meta_addrs, apps=None, every_s: float = 5.0,
                 wait_s: float = 5.0, journal=None,
                 pool: ConnectionPool = None):
        from ..runtime import lockrank
        from ..runtime.tasking import spawn_thread

        self.meta_addrs = list(meta_addrs)
        self.apps = list(apps) if apps else None
        self.every_s = every_s
        self.wait_s = wait_s
        self.journal = journal
        self.pool = pool
        self._lock = lockrank.named_lock("audit.rounds")
        self.rounds = []   #: guarded_by self._lock
        self._stop = threading.Event()
        self._thread = spawn_thread(self._loop, daemon=True, start=False,
                                    name="audit-rounds")

    def start(self) -> "AuditRounds":
        self._thread.start()
        return self

    def stop(self, final_round: bool = True) -> dict:
        """Stop the cadence (joining the loop); final_round runs one more
        audit AFTER the caller quiesced — the round that catches a
        mismatch introduced in the last window. -> summary()."""
        self._stop.set()
        self._thread.join(timeout=max(30.0, self.wait_s * 4))
        if final_round:
            self._run_round(final=True)
        return self.summary()

    def _loop(self):
        while not self._stop.wait(self.every_s):
            try:
                self._run_round()
            except Exception as e:  # noqa: BLE001 - cadence must survive
                # mid-chaos RPC storms; the round is recorded as vacuous
                with self._lock:
                    self.rounds.append({"error": repr(e), "conclusive": False,
                                        "mismatches": []})
                if self.journal is not None:
                    self.journal.record("audit.round.error", error=repr(e))

    def _run_round(self, final: bool = False):
        report = run_cluster_audit(self.meta_addrs, apps=self.apps,
                                   wait_s=self.wait_s, pool=self.pool)
        rnd = {"ok": len(report["ok"]), "partitions": report["partitions"],
               "mismatches": report["mismatches"],
               "inconclusive": report["inconclusive"],
               "conclusive": (report["partitions"] > 0
                              and len(report["ok"]) == report["partitions"]),
               "final": final}
        counters.rate("audit.round.count").increment()
        if rnd["conclusive"]:
            counters.rate("audit.round.conclusive").increment()
        else:
            counters.rate("audit.round.vacuous").increment()
        if rnd["mismatches"]:
            counters.rate("audit.round.mismatch_count").increment(
                len(rnd["mismatches"]))
        with self._lock:
            self.rounds.append(rnd)
        if self.journal is not None:
            self.journal.record("audit.round", ok=rnd["ok"],
                                partitions=rnd["partitions"],
                                conclusive=rnd["conclusive"], final=final,
                                mismatches=len(rnd["mismatches"]))
            for m in rnd["mismatches"]:
                self.journal.fail("audit.mismatch", **m)

    def summary(self) -> dict:
        with self._lock:
            rounds = list(self.rounds)
        mismatches = [m for r in rounds for m in r["mismatches"]]
        return {"rounds": len(rounds),
                "conclusive": sum(1 for r in rounds if r["conclusive"]),
                "vacuous": sum(1 for r in rounds if not r["conclusive"]),
                "mismatches": mismatches}


# ================================================================ doctor


def _gap_threshold() -> int:
    return int(os.environ.get("PEGASUS_DOCTOR_GAP_DEGRADED", "128"))


def _queue_threshold() -> int:
    return int(os.environ.get("PEGASUS_DOCTOR_QUEUE_DEGRADED", "64"))


def run_cluster_doctor(meta_addrs, pool: ConnectionPool = None,
                       scrape: bool = True, slow_last: int = 10,
                       caller: ClusterCaller = None) -> dict:
    """ONE structured health verdict for the whole cluster.

    -> ``{"verdict": healthy|degraded|critical|inconclusive,
          "causes": [{"severity", "cause", "evidence"}...],
          "evidence": {nodes, partitions, lag, audit, scrapes,
                       slow_requests}, "ts": unix_seconds}``

    Severity folding: any critical cause -> ``critical``; else any
    degraded cause -> ``degraded``; else ``healthy``. A cluster whose
    state cannot be read at all (no meta) is ``inconclusive``. Audit
    evidence can only come from digests at EQUAL decrees; members that
    have not reported yet are listed under ``evidence.audit.pending``
    and never count as mismatches."""
    own = caller is None
    caller = caller or ClusterCaller(meta_addrs, pool=pool)
    causes, evidence = [], {}
    try:
        state = caller.meta_state()
        if state is None:
            verdict = {"verdict": INCONCLUSIVE,
                       "causes": [{"severity": INCONCLUSIVE,
                                   "cause": "no meta server reachable",
                                   "evidence": "meta"}],
                       "evidence": {"meta_addrs": list(meta_addrs)},
                       "ts": time.time()}
            _export_verdict(verdict)
            return verdict
        _check_nodes(state, causes, evidence)
        _check_partitions(state, causes, evidence)
        _check_lag(state, causes, evidence)
        _check_audit(state, causes, evidence)
        _check_quarantine(state, causes, evidence)
        _check_slo(causes, evidence)
        if scrape:
            _scrape_nodes(caller, state, causes, evidence, slow_last)
        verdict = CRITICAL if any(c["severity"] == CRITICAL
                                  for c in causes) \
            else DEGRADED if causes else HEALTHY
        out = {"verdict": verdict, "causes": causes, "evidence": evidence,
               "ts": time.time()}
        _export_verdict(out)
        # flight recorder (ISSUE 12): a healthy->degraded/critical
        # transition auto-captures an incident artifact; the id rides the
        # verdict so every doctor surface (HTTP, remote command, shell,
        # bench, pressure_test) can point at the evidence bundle
        try:
            from .flight_recorder import RECORDER

            incident = RECORDER.observe_verdict(out, list(meta_addrs),
                                                caller=caller)
            if incident:
                out["incident"] = incident
        except Exception as e:  # noqa: BLE001 - capture is best-effort;
            # the verdict must never fail because evidence gathering did
            print(f"[doctor] incident capture failed: {e!r}", flush=True)
        # audit-driven auto-heal (ISSUE 17): gated off unless
        # PEGASUS_AUTOHEAL=1, interlocked + rate-limited inside — the
        # verdict must never fail because the heal attempt did
        try:
            from .auto_heal import AUTO_HEALER

            healed = AUTO_HEALER.observe_verdict(out, caller=caller)
            if healed:
                out["autoheal"] = healed
        except Exception as e:  # noqa: BLE001 - heal is best-effort
            print(f"[doctor] auto-heal failed: {e!r}", flush=True)
        return out
    finally:
        if own:
            caller.close()


def _export_verdict(out: dict) -> None:
    counters.rate("doctor.run_count").increment()
    counters.number("doctor.verdict").set(_VERDICT_GAUGE[out["verdict"]])
    events.emit("doctor.verdict",
                severity={CRITICAL: "error", DEGRADED: "warn"}.get(
                    out["verdict"], "info"),
                verdict=out["verdict"], causes=len(out.get("causes", ())))


def _check_nodes(state, causes, evidence) -> None:
    nodes = state.get("nodes", {})
    dead = sorted(a for a, n in nodes.items() if not n["alive"])
    evidence["nodes"] = {"total": len(nodes), "dead": dead}
    for addr in dead:
        causes.append({"severity": DEGRADED,
                       "cause": f"node {addr} dead "
                                f"(last beacon "
                                f"{nodes[addr]['last_beacon_ago_s']:.0f}s "
                                "ago)",
                       "evidence": "nodes.dead"})


def _check_partitions(state, causes, evidence) -> None:
    nodes = state.get("nodes", {})
    alive = {a for a, n in nodes.items() if n["alive"]}
    unserved, under = [], []
    for app_name, app in state.get("apps", {}).items():
        want = app.get("replica_count", 0)
        for pc in app.get("partitions", []):
            gpid = f"{app['app_id']}.{pc['pidx']}"
            members = [m for m in [pc.get("primary")]
                       + pc.get("secondaries", []) if m]
            live = [m for m in members if m in alive]
            if not pc.get("primary") or pc["primary"] not in alive:
                unserved.append({"app": app_name, "gpid": gpid,
                                 "primary": pc.get("primary", "")})
            elif want and len(live) < want:
                under.append({"app": app_name, "gpid": gpid,
                              "live": len(live), "want": want})
    evidence["partitions"] = {"unserved": unserved,
                              "under_replicated": under}
    for u in unserved:
        causes.append({"severity": CRITICAL,
                       "cause": f"partition {u['app']}.{u['gpid']} has no "
                                "live primary — writes are down",
                       "evidence": "partitions.unserved"})
    for u in under:
        causes.append({"severity": DEGRADED,
                       "cause": f"partition {u['app']}.{u['gpid']} "
                                f"under-replicated ({u['live']}/{u['want']})",
                       "evidence": "partitions.under_replicated"})


def _check_lag(state, causes, evidence) -> None:
    """Replication-lag plane over the beacon-folded per-replica states:
    commit lag and apply lag are distinct causes, and BOTH are measured
    within one replica's own snapshot — commit lag as prepared-committed
    (decrees the replica staged but whose commit point never reached
    it), apply lag as committed-applied (the engine behind replication).
    Cross-node frontier compares are deliberately NOT used as causes:
    beacons are asynchronous per node, so two nodes' committed counters
    are sampled at different instants and any healthy cluster writing
    faster than the beacon interval would read as degraded. (Behind on
    PREPARE is the primary's secondary_gap_max gauge, measured at one
    instant by the primary itself.)"""
    nodes = state.get("nodes", {})
    per_gpid = {}
    for node, states in state.get("replica_states", {}).items():
        # a dead node's states are frozen at its last beacon: folding
        # them would report ever-growing lag forever — its death is
        # already a cause of its own (_check_nodes)
        if not nodes.get(node, {}).get("alive", True):
            continue
        for gpid, st in states.items():
            per_gpid.setdefault(gpid, {})[node] = st
    thr = _gap_threshold()
    worst = {"commit_gap": 0, "apply_gap": 0}
    offenders = []
    for gpid, members in per_gpid.items():
        for node, st in members.items():
            commit_gap = st.get("prepared", 0) - st.get("committed", 0)
            apply_gap = st.get("committed", 0) - st.get("applied", 0)
            worst["commit_gap"] = max(worst["commit_gap"], commit_gap)
            worst["apply_gap"] = max(worst["apply_gap"], apply_gap)
            if commit_gap >= thr:
                offenders.append({"gpid": gpid, "node": node,
                                  "kind": "commit", "gap": commit_gap})
                causes.append({"severity": DEGRADED,
                               "cause": f"replica {gpid}@{node} behind on "
                                        f"COMMIT by {commit_gap} decrees "
                                        "(staged but uncommitted)",
                               "evidence": "lag.offenders"})
            if apply_gap >= thr:
                offenders.append({"gpid": gpid, "node": node,
                                  "kind": "apply", "gap": apply_gap})
                causes.append({"severity": DEGRADED,
                               "cause": f"replica {gpid}@{node} behind on "
                                        f"APPLY by {apply_gap} decrees",
                               "evidence": "lag.offenders"})
    evidence["lag"] = {"worst": worst, "offenders": offenders,
                       "threshold": thr}


def _check_audit(state, causes, evidence) -> None:
    """Compare beacon-reported digests per partition, at EQUAL decrees
    only. The reference digest is the primary's when it reported at that
    decree, else the majority value; every disagreeing node is named."""
    primaries = {}
    for app in state.get("apps", {}).values():
        for pc in app.get("partitions", []):
            primaries[f"{app['app_id']}.{pc['pidx']}"] = pc.get("primary")
    nodes = state.get("nodes", {})
    per_gpid = {}
    for node, states in state.get("replica_states", {}).items():
        if not nodes.get(node, {}).get("alive", True):
            continue  # frozen states of a dead node (see _check_lag)
        for gpid, st in states.items():
            # a failed digest computation (empty digest / error) is not
            # comparable evidence — it must read as pending, never as a
            # mismatch
            if st.get("audit", {}).get("digest"):
                per_gpid.setdefault(gpid, {})[node] = st["audit"]
    mismatches, pending, checked = [], [], []
    for gpid, audits in sorted(per_gpid.items()):
        latest = max(a["decree"] for a in audits.values())
        at = {n: a for n, a in audits.items() if a["decree"] == latest}
        behind = sorted(set(audits) - set(at))
        if behind:
            pending.append({"gpid": gpid, "decree": latest, "nodes": behind})
        if len(at) < 2:
            continue  # nothing to compare yet
        prim = primaries.get(gpid)
        if prim in at:
            ref = at[prim]["digest"]
        else:
            # primary hasn't reported at this decree: a STRICT majority
            # picks the reference; a tie (e.g. two secondaries, 1-1) is
            # not attributable — naming either node would be iteration-
            # order luck — so it waits for the primary's beacon
            votes = {}
            for a in at.values():
                votes[a["digest"]] = votes.get(a["digest"], 0) + 1
            ref = max(votes, key=votes.get)
            if votes[ref] * 2 <= len(at):
                pending.append({"gpid": gpid, "decree": latest,
                                "nodes": sorted(at),
                                "reason": "digests disagree with no "
                                          "majority and no primary report "
                                          "yet — not attributable"})
                continue
        checked.append(gpid)
        for node, a in sorted(at.items()):
            if a["digest"] != ref:
                mismatches.append({"gpid": gpid, "node": node,
                                   "decree": latest,
                                   "digest": a["digest"], "expected": ref})
    evidence["audit"] = {"checked": checked, "mismatches": mismatches,
                         "pending": pending}
    for m in mismatches:
        causes.append({"severity": CRITICAL,
                       "cause": f"consistency digest MISMATCH at partition "
                                f"{m['gpid']} on node {m['node']} "
                                f"(decree {m['decree']})",
                       "evidence": "audit.mismatches"})


def _check_quarantine(state, causes, evidence) -> None:
    """Beacon-reported QUARANTINED partitions (ISSUE 17): a node pulled a
    corrupt copy off the serving path and is waiting for the meta's
    repair_quarantined re-seed. Degraded, not critical — the healthy
    members keep serving; the cause names node, partition and reason so
    an operator (or the incident artifact) sees WHY the copy vanished."""
    quarantined = []
    for node, states in state.get("replica_states", {}).items():
        for gpid, st in states.items():
            if st.get("status") != "QUARANTINED":
                continue
            q = st.get("quarantine", {})
            quarantined.append({"gpid": gpid, "node": node,
                                "reason": q.get("reason", ""),
                                "source": q.get("source", ""),
                                "dir": q.get("dir", "")})
    evidence["quarantine"] = quarantined
    for q in sorted(quarantined, key=lambda x: (x["gpid"], x["node"])):
        causes.append({"severity": DEGRADED,
                       "cause": f"replica {q['gpid']} on node {q['node']} "
                                f"quarantined ({q['source']}: "
                                f"{q['reason'] or 'corruption'})",
                       "evidence": "quarantine"})


def _check_slo(causes, evidence) -> None:
    """Tenant SLO verdicts (ISSUE 18): a table whose multi-window burn
    rate says `burning` is a degraded cause NAMING the table — the
    first doctor signal keyed on what users see (a tenant), not on a
    node or partition. The verdicts are the ones the in-process
    evaluator (collector.evaluate_slos) computed last round; a process
    that never evaluates SLOs contributes nothing here."""
    from .info_collector import latest_slo

    verdicts = latest_slo()
    if not verdicts:
        return
    evidence["slo"] = verdicts
    for table in sorted(verdicts):
        v = verdicts[table]
        if v.get("verdict") != "burning":
            continue
        causes.append({
            "severity": DEGRADED,
            "cause": f"table {table} SLO burning "
                     f"(fast_burn={v.get('fast_burn')} "
                     f"slow_burn={v.get('slow_burn')} "
                     f"latency_burn={v.get('latency_burn')} "
                     f"errors_fast={v.get('errors_fast')})",
            "evidence": "slo"})


def _scrape_nodes(caller, state, causes, evidence, slow_last) -> None:
    """Per-node health scrapes: lane breakers, dispatch queue depth, and
    the cluster-wide slow-request rollup. Scrape failures are evidence
    (node listed under scrape_failed), not crashes."""
    from .info_collector import rollup_slow_requests

    alive = sorted(a for a, n in state.get("nodes", {}).items()
                   if n["alive"])
    scrapes, failed = {}, []
    qthr = _queue_threshold()
    for node in alive:
        try:
            snap = json.loads(caller.remote_command(
                node, "perf-counters-by-substr",
                ["lane.breaker_open", "dispatch_queue_depth"]))
        except (RpcError, OSError, ValueError):
            failed.append(node)
            continue
        scrapes[node] = snap
        for lane in ("compact", "read"):
            if snap.get(f"{lane}.lane.breaker_open"):
                causes.append({"severity": DEGRADED,
                               "cause": f"{lane} lane circuit breaker OPEN "
                                        f"on node {node} (device lane "
                                        "degraded to host)",
                               "evidence": "scrapes"})
        depth = snap.get("rpc.server.dispatch_queue_depth", 0)
        if depth >= qthr:
            causes.append({"severity": DEGRADED,
                           "cause": f"dispatch queue depth {depth:.0f} on "
                                    f"node {node} (>= {qthr}: serving "
                                    "saturated)",
                           "evidence": "scrapes"})
    evidence["scrapes"] = scrapes
    if failed:
        evidence["scrape_failed"] = failed

    def fetch(node):
        return caller.remote_command(node, "slow-requests", [str(slow_last)])

    evidence["slow_requests"] = rollup_slow_requests(fetch, alive,
                                                     last=slow_last)
