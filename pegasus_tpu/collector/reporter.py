"""Counter reporter: Prometheus text exposition of the perf-counter registry.

Mirror of src/reporter/pegasus_counter_reporter.{h,cpp}: the reference
pushes counters to Falcon (HTTP JSON) or exposes/pushes Prometheus; here a
lightweight HTTP exposer serves `/metrics` in Prometheus text format and
`/counters` as JSON from the process-wide registry, plus a push helper
producing the Falcon-style JSON payload for an external pusher.
"""

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _flatten(snap: dict):
    """Yield (name, float) pairs; percentile counters snapshot as a
    {p50..p999} dict and flatten to `<name>.<quantile>` series."""
    for name, value in sorted(snap.items()):
        if isinstance(value, dict):
            for q, v in value.items():
                yield f"{name}.{q}", float(v)
        else:
            yield name, float(value)


def prometheus_text(snapshot: dict = None) -> str:
    snap = counters.snapshot() if snapshot is None else snapshot
    lines = []
    for name, value in _flatten(snap):
        metric = _NAME_RE.sub("_", name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {value}")
    return "\n".join(lines) + "\n"


def falcon_payload(endpoint: str, snapshot: dict = None) -> str:
    """Falcon push body (list of metric dicts), reference
    pegasus_counter_reporter.cpp falcon_gauge JSON shape."""
    snap = counters.snapshot() if snapshot is None else snapshot
    out = [{"endpoint": endpoint, "metric": name, "value": v,
            "step": 60, "counterType": "GAUGE", "tags": ""}
           for name, v in _flatten(snap)]
    return json.dumps(out)


class CounterReporter:
    """HTTP exposer on (host, port); port 0 picks an ephemeral port.

    Beyond /metrics and /counters, server roles mount extra routes
    (version/info endpoints — the reference's rDSN http_service surface,
    e.g. /version, /meta/cluster_info): `routes` maps an EXACT path to
    `fn(full_path_with_query) -> JSON-serializable` (or raw bytes)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, routes=None):
        routes = dict(routes or {})

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                # exact routes FIRST: the /metrics prefix fallback must
                # not shadow a mounted subpath (/metrics/history)
                fn = routes.get(self.path.split("?")[0])
                if fn is None and self.path.startswith("/metrics"):
                    body = prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif fn is None and self.path.startswith("/counters"):
                    body = json.dumps(counters.snapshot(), indent=1).encode()
                    ctype = "application/json"
                else:
                    if fn is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    try:
                        out = fn(self.path)
                        if isinstance(out, bytes):
                            body, ctype = out, "application/octet-stream"
                        else:
                            # dumps inside the try: an unserializable route
                            # result must 500, not drop the connection
                            body = json.dumps(out, indent=1).encode()
                            ctype = "application/json"
                    except Exception as e:  # surface, don't kill the server
                        self.send_response(500)
                        self.end_headers()
                        self.wfile.write(repr(e).encode())
                        return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self.address = self._srv.server_address
        self._thread = spawn_thread(self._srv.serve_forever, daemon=True,
                                    start=False)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
