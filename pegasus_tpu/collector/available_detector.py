"""Availability canary: continuous end-to-end probe of a cluster.

Mirror of src/server/available_detector.{h,cpp} + result_writer.{h,cpp}:
write a timestamped probe row, read it back, across all partitions of a
detect table; track minute/hour/day success ratios and persist recent
results into the detect table itself (the result_writer role) so external
monitors can read availability out of the store it measures.
"""

import threading
import time

from ..client import MetaResolver, PegasusClient, PegasusError
from ..rpc.transport import RpcError
from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread


class AvailableDetector:
    def __init__(self, meta_addrs, table_name: str = "test",
                 interval_seconds: float = 1.0):
        self.meta_addrs = list(meta_addrs)
        self.table_name = table_name
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread = spawn_thread(self._loop, daemon=True, start=False)
        self._lock = threading.Lock()
        self._window = []  # (ts, ok)
        self.client = None

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _ensure_client(self):
        if self.client is None:
            self.client = PegasusClient(
                MetaResolver(self.meta_addrs, self.table_name))
        return self.client

    def probe_once(self) -> bool:
        """One write+read round-trip across a rotating partition hash."""
        ts = int(time.time() * 1000)
        hk = b"detect_available_p%d" % (ts % 64)
        sk = b"ts"
        val = str(ts).encode()
        try:
            cli = self._ensure_client()
            cli.set(hk, sk, val)
            ok = cli.get(hk, sk) == val
        except (PegasusError, RpcError, OSError):
            # RpcError covers "table does not exist (yet)" from the meta
            # resolver — a canary whose table lags its own boot must count
            # failures, not die (its loop thread has no other guard)
            ok = False
            self.client = None  # rebuild routing next round
        with self._lock:
            self._window.append((time.time(), ok))
            cutoff = time.time() - 86400
            while self._window and self._window[0][0] < cutoff:
                self._window.pop(0)
        counters.rate("detector.probe_total").increment()
        if not ok:
            counters.rate("detector.probe_fail").increment()
        # persist the result into the probe table (result_writer role)
        if ok:
            try:
                cli.set(b"detect_available_result", b"last",
                        b"%d:%d" % (ts, 1))
            except (PegasusError, OSError):
                pass
        return ok

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.probe_once()
            except Exception as e:  # the canary must outlive ANY error
                print(f"[detector] probe error: {e!r}", flush=True)

    def availability(self, seconds: float) -> float:
        """Success ratio over the trailing window (minute/hour/day views)."""
        cutoff = time.time() - seconds
        with self._lock:
            rows = [ok for ts, ok in self._window if ts >= cutoff]
        if not rows:
            return 1.0
        return sum(rows) / len(rows)

    def report(self) -> dict:
        with self._lock:
            samples = len(self._window)
        return {
            "minute": self.availability(60),
            "hour": self.availability(3600),
            "day": self.availability(86400),
            # no-data reads as 1.0 (benefit of the doubt, reference
            # behavior); consumers needing proof of life check samples
            "samples": samples,
        }
