"""Process entry: `python -m pegasus_tpu.server --config cfg.ini [--app ...]`
boots the ini-declared service apps (the dsn_run/main.cpp role)."""
