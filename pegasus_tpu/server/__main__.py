import argparse

from ..runtime.config import Config
from ..runtime.service_app import ServiceAppContainer


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pegasus-server")
    ap.add_argument("--config", required=True, help="ini config path")
    ap.add_argument("--app", default="", help="comma-separated app names "
                    "(default: every [apps.*] with run=true)")
    ns = ap.parse_args(argv)
    container = ServiceAppContainer(Config(ns.config))
    only = [a for a in ns.app.split(",") if a] or None
    apps = container.start(only)
    for name, app in apps.items():
        addr = getattr(app, "address", "")
        print(f"[pegasus-tpu] app {name} started {addr}", flush=True)
    try:
        container.wait_forever()
    except KeyboardInterrupt:
        container.stop()


main()
