import argparse
import os

from ..runtime.config import Config
from ..runtime.service_app import ServiceAppContainer


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pegasus-server")
    ap.add_argument("--config", required=True, help="ini config path")
    ap.add_argument("--app", default="", help="comma-separated app names "
                    "(default: every [apps.*] with run=true)")
    ns = ap.parse_args(argv)
    cfg = Config(ns.config)
    if (os.environ.get("JAX_PLATFORMS")
            and cfg.get_string("pegasus.server", "compaction_backend",
                               "cpu") == "tpu"):
        # honor an explicit platform request BEFORE the engine touches jax:
        # some images re-assert their own platform over the env var, and a
        # tpu-backend engine would otherwise wedge on a dead device tunnel.
        # Gated on the tpu backend — a cpu-backend server never imports
        # jax, and this import costs seconds of boot on small hosts.
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    container = ServiceAppContainer(cfg)
    only = [a for a in ns.app.split(",") if a] or None
    apps = container.start(only)
    for name, app in apps.items():
        addr = getattr(app, "address", "")
        print(f"[pegasus-tpu] app {name} started {addr}", flush=True)
    try:
        container.wait_forever()
    except KeyboardInterrupt:
        container.stop()


main()
