import argparse
import os
import sys


def main(argv=None):
    from ..runtime.config import Config
    from ..runtime.service_app import ServiceAppContainer

    ap = argparse.ArgumentParser(prog="pegasus-server")
    ap.add_argument("--config", required=True, help="ini config path")
    ap.add_argument("--app", default="", help="comma-separated app names "
                    "(default: every [apps.*] with run=true)")
    ns = ap.parse_args(argv)
    cfg = Config(ns.config)
    if (os.environ.get("JAX_PLATFORMS")
            and cfg.get_string("pegasus.server", "compaction_backend",
                               "cpu") == "tpu"):
        # honor an explicit platform request BEFORE the engine touches jax:
        # some images re-assert their own platform over the env var, and a
        # tpu-backend engine would otherwise wedge on a dead device tunnel.
        # Gated on the tpu backend — a cpu-backend server never imports
        # jax, and this import costs seconds of boot on small hosts.
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    container = ServiceAppContainer(cfg)
    only = [a for a in ns.app.split(",") if a] or None
    apps = container.start(only)
    for name, app in apps.items():
        addr = getattr(app, "address", "")
        print(f"[pegasus-tpu] app {name} started {addr}", flush=True)
    try:
        container.wait_forever()
    except KeyboardInterrupt:
        container.stop()


def group_worker_main(spec_path: str):
    """One partition-group executor (replication/serve_groups.py): a full
    ReplicaStub on an ephemeral localhost port owning this group's share
    of the node's partitions. Prints GROUP_READY <port> once serving; the
    parent's control-channel EOF (watched by the stub's adoption loop) is
    the exit signal, so an orphan worker can never outlive its node."""
    import json
    import threading

    with open(spec_path) as f:
        spec = json.load(f)
    from ..engine import EngineOptions
    from ..replication.replica_stub import ReplicaStub

    def options_factory():
        return EngineOptions(
            backend=spec.get("backend", "cpu"),
            compression=spec.get("compression", "none"),
            sharded_compaction=bool(spec.get("sharded_compaction")))

    stub = ReplicaStub(
        spec["root"], list(spec["metas"]), host="127.0.0.1", port=0,
        options_factory=options_factory,
        remote_clusters=spec.get("remote_clusters") or {},
        cluster_id=int(spec.get("cluster_id", 1)), group_spec=spec)
    stub.start()
    print(f"GROUP_READY {stub.rpc.address[1]}", flush=True)
    threading.Event().wait()


if "--group-worker" in sys.argv[1:]:
    group_worker_main(sys.argv[sys.argv.index("--group-worker") + 1])
else:
    main()
