"""Minimal client usage (the reference's src/sample/main.cpp role):

    python -m pegasus_tpu.sample <meta host:port> <table>
"""

import sys

from ..client import get_client


def main():
    meta, table = sys.argv[1], sys.argv[2]
    client = get_client(meta, table)

    client.set(b"pegasus", b"cloud", b"engine")
    value = client.get(b"pegasus", b"cloud")
    print(f"get(pegasus, cloud) -> {value!r}")

    client.multi_set(b"fruits", {b"apple": b"red", b"banana": b"yellow"})
    complete, kvs = client.multi_get(b"fruits")
    print(f"multi_get(fruits) -> {kvs}")

    print(f"incr(counter) -> {client.incr(b'stats', b'counter', 1)}")

    for hk, sk, v in client.get_scanner(b"fruits"):
        print(f"scan: {sk!r} = {v!r}")

    client.delete(b"pegasus", b"cloud")
    print(f"after del: {client.get(b'pegasus', b'cloud')!r}")


main()
