from .main import Shell, main

__all__ = ["Shell", "main"]
