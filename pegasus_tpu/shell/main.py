"""pegasus shell: admin + data CLI over the meta server and replica nodes.

The src/shell surface (command table src/shell/main.cpp:42-..., impls
src/shell/commands/*.cpp) rebuilt over this stack's client/meta RPCs. Runs
as a REPL (`python -m pegasus_tpu.shell --meta host:port`) or one-shot
(`... --meta host:port -- app ls`). Commands cover cluster info, table
DDL, node view, data ops (set/get/del/multi_*/ttl/incr/scan/count_data/
copy_data), app-envs (incl. the manual-compact and usage-scenario control
surface), remote commands, and perf-counter scraping.
"""

import argparse
import json
import shlex
import sys
import time

from ..base.utils import c_escape_string
from ..client import MetaResolver, PegasusClient, PegasusError
from ..meta import messages as mm
from ..meta.meta_server import (RPC_CM_CREATE_APP, RPC_CM_DROP_APP,
                                RPC_CM_LIST_APPS, RPC_CM_LIST_NODES,
                                RPC_CM_QUERY_CONFIG, RPC_CM_SET_APP_ENVS)
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError
from ..runtime.remote_command import RemoteCommandRequest, RemoteCommandResponse


class Shell:
    def __init__(self, meta_addrs, out=sys.stdout):
        self.meta_addrs = list(meta_addrs)
        self.pool = ConnectionPool()
        self.out = out
        self.current_app = None
        self._clients = {}
        self.commands = {
            "help": (self.cmd_help, "list commands"),
            "cluster_info": (self.cmd_cluster_info, "meta + node summary"),
            "ls": (self.cmd_ls, "list tables"),
            "app": (self.cmd_app, "app <name> — show partition table"),
            "create": (self.cmd_create, "create <name> [-p N] [-r N]"),
            "drop": (self.cmd_drop,
                     "drop <name> [-r seconds] — -r keeps it recallable"),
            "recall": (self.cmd_recall,
                       "recall <app_id> [new_name] — restore a soft-dropped app"),
            "use": (self.cmd_use, "use <name> — select table for data ops"),
            "nodes": (self.cmd_nodes, "list replica nodes"),
            "set": (self.cmd_set, "set <hk> <sk> <value> [ttl]"),
            "get": (self.cmd_get, "get <hk> <sk>"),
            "del": (self.cmd_del, "del <hk> <sk>"),
            "exist": (self.cmd_exist, "exist <hk> <sk>"),
            "ttl": (self.cmd_ttl, "ttl <hk> <sk>"),
            "incr": (self.cmd_incr, "incr <hk> <sk> [by]"),
            "multi_set": (self.cmd_multi_set, "multi_set <hk> <sk> <v> [<sk> <v>...]"),
            "multi_get": (self.cmd_multi_get, "multi_get <hk> [sk...]"),
            "multi_del": (self.cmd_multi_del, "multi_del <hk> <sk> [sk...]"),
            "sortkey_count": (self.cmd_sortkey_count, "sortkey_count <hk>"),
            "count": (self.cmd_sortkey_count,
                      "count <hk> — sort key count (alias of sortkey_count)"),
            "check_and_set": (self.cmd_check_and_set,
                              "check_and_set <hk> <check_sk> <check_type> "
                              "<operand> <set_sk> <set_value> [ttl]"),
            "check_and_mutate": (self.cmd_check_and_mutate,
                                 "check_and_mutate <hk> <check_sk> <check_type> "
                                 "<operand> set <sk> <v> | del <sk> [...]"),
            "hash_scan": (self.cmd_hash_scan, "hash_scan <hk> [start] [stop]"),
            "full_scan": (self.cmd_full_scan, "full_scan [max_rows]"),
            "count_data": (self.cmd_count_data, "count rows in current table"),
            "copy_data": (self.cmd_copy_data, "copy_data <dest_table>"),
            "get_app_envs": (self.cmd_get_app_envs, "show current table envs"),
            "set_app_envs": (self.cmd_set_app_envs, "set_app_envs <k> <v> [...]"),
            "del_app_envs": (self.cmd_del_app_envs, "del_app_envs <k> [...]"),
            "manual_compact": (self.cmd_manual_compact,
                               "trigger once manual compaction via app envs"),
            "query_compact_state": (self.cmd_query_compact,
                                    "query manual compact state on nodes"),
            "compact_sched": (self.cmd_compact_sched,
                              "compact_sched [node|all] [gpid] — per-"
                              "partition compaction-scheduler decisions "
                              "(defer/normal/urgent + the reasons that "
                              "drove them + live debt) from every node's "
                              "compact-sched-status"),
            "offload_status": (self.cmd_offload_status,
                               "offload_status <host:port> — a compaction-"
                               "offload service's free merge budget, "
                               "running merges, jobs and staged bytes"),
            "remote_command": (self.cmd_remote_command,
                               "remote_command <node|all> <cmd> [args...]"),
            "server_info": (self.cmd_server_info, "server-info on every node"),
            "server_stat": (self.cmd_server_stat, "server-stat on every node"),
            "perf_counters": (self.cmd_perf_counters,
                              "perf_counters <node> [prefix]"),
            "compact_trace": (self.cmd_compact_trace,
                              "compact_trace [node] [last] — recent "
                              "compaction stage spans (pack/h2d/device/"
                              "gather) from the tracing ring buffer"),
            "device_health": (self.cmd_device_health,
                              "device-health watchdog + lane-guard state on "
                              "every node (last_ok / wedged_at_stage / "
                              "breaker / cpu-fallback totals)"),
            "quarantine_status": (self.cmd_quarantine_status,
                                  "quarantine_status [node] — replicas "
                                  "fenced for on-disk corruption (reason, "
                                  "source, forensics dir) per node"),
            "scrub_replica": (self.cmd_scrub_replica,
                              "scrub_replica <node|all> [gpid] — force one "
                              "integrity scrub pass now (checksum-verify "
                              "live SSTs off the serving path; corrupt "
                              "replicas quarantine themselves)"),
            "request_trace": (self.cmd_request_trace,
                              "request_trace [node] [last] — recent sampled "
                              "request traces (client/rpc/replication/engine "
                              "stage timelines)"),
            "slow_requests": (self.cmd_slow_requests,
                              "slow_requests [node|--cluster] [last] — the "
                              "slow-request ledger; --cluster merges every "
                              "node's ledger into one worst-first top-N"),
            "job_trace": (self.cmd_job_trace,
                          "job_trace [node] [last|<job-id>] — background-"
                          "job timelines (compaction/offload/learn/dup "
                          "hops, one causal id across nodes)"),
            "events": (self.cmd_events,
                       "events [node] [last] [prefix] — the structured "
                       "event ring (flight recorder): breaker trips, "
                       "scheduler tokens, elections, splits, fail-point "
                       "arms... per process, pid-keyed"),
            "flight_recorder": (self.cmd_flight_recorder,
                                "flight_recorder [list|show <id>|capture "
                                "[reason]] — retained incident artifacts "
                                "(auto-captured on doctor degradation / "
                                "chaos failures) or a manual capture now"),
            "trigger_audit": (self.cmd_trigger_audit,
                              "trigger_audit [app] — decree-anchored "
                              "consistency audit: every replica digests its "
                              "state at the same applied decree; mismatches "
                              "name the exact (app, pidx, node)"),
            "cluster_doctor": (self.cmd_cluster_doctor,
                               "cluster_doctor [last] — ONE cluster health "
                               "verdict (healthy|degraded|critical) with "
                               "named causes + evidence"),
            "tables": (self.cmd_tables,
                       "tables [k] — cluster-folded per-table tenant "
                       "ledgers (ops/latency/bytes/throttle/device/HBM) "
                       "+ top-k capacity attribution, from every alive "
                       "node's table-stats"),
            "slo": (self.cmd_slo,
                    "slo [node] — per-table SLO burn-rate verdicts "
                    "(ok|warn|burning + named evidence) from every "
                    "node's slo-status (the collector evaluates)"),
            "detect_hotkey": (self.cmd_detect_hotkey,
                              "detect_hotkey <node> <app_id.pidx> <read|write> <start|stop|query>"),
            "set_fail_point": (self.cmd_set_fail_point,
                               "set_fail_point <node|all> <name> <action> — "
                               "arm/heal a fail point in live server "
                               "processes (chaos harness; action e.g. "
                               "'sleep(40)', '20%raise(x)', 'off()')"),
            "cross_cluster_audit": (self.cmd_cross_cluster_audit,
                                    "cross_cluster_audit <app> "
                                    "<dst_meta[,dst_meta...]> [dupid] — "
                                    "table-level digest compare against a "
                                    "duplication target cluster, anchored "
                                    "at the duplicator's confirmed decree "
                                    "(quiesce writes first)"),
            "propose": (self.cmd_propose,
                        "propose <pidx> <target_node> — move primary"),
            "balance": (self.cmd_balance, "equalize primary counts"),
            "add_dup": (self.cmd_add_dup,
                        "add_dup <app> <remote_cluster> [-f] — freeze=no ship yet"),
            "query_dup": (self.cmd_query_dup, "query_dup <app>"),
            "start_dup": (self.cmd_start_dup, "start_dup <app> <dupid>"),
            "pause_dup": (self.cmd_pause_dup, "pause_dup <app> <dupid>"),
            "remove_dup": (self.cmd_remove_dup, "remove_dup <app> <dupid>"),
            "set_dup_fail_mode": (self.cmd_set_dup_fail_mode,
                                  "set_dup_fail_mode <app> <dupid> <slow|skip>"),
            "backup_app": (self.cmd_backup_app,
                           "backup_app <app> <backup_root> — one-shot backup"),
            "restore_app": (self.cmd_restore_app,
                            "restore_app <backup_root> <backup_id> <old_app> <new_app>"),
            "add_backup_policy": (self.cmd_add_backup_policy,
                                  "add_backup_policy <name> <backup_root> <apps,csv> "
                                  "<interval_s> [history_count] — backups land in "
                                  "<backup_root>/<name>/<backup_id>/"),
            "ls_backup_policy": (self.cmd_ls_backup_policy,
                                 "ls_backup_policy [name]"),
            "modify_backup_policy": (self.cmd_modify_backup_policy,
                                     "modify_backup_policy <name> [-i sec] [-c count] "
                                     "[--add app,..] [--remove app,..]"),
            "enable_backup_policy": (self.cmd_enable_backup_policy,
                                     "enable_backup_policy <name>"),
            "disable_backup_policy": (self.cmd_disable_backup_policy,
                                      "disable_backup_policy <name>"),
            "start_bulk_load": (self.cmd_start_bulk_load,
                                "start_bulk_load <app> <provider_root> [-a] "
                                "— -a = async session (query/pause/cancel)"),
            "query_bulk_load_status": (self.cmd_query_bulk_load,
                                       "query_bulk_load_status <app>"),
            "pause_bulk_load": (self.cmd_pause_bulk_load,
                                "pause_bulk_load <app>"),
            "restart_bulk_load": (self.cmd_restart_bulk_load,
                                  "restart_bulk_load <app> — resume a paused session"),
            "cancel_bulk_load": (self.cmd_cancel_bulk_load,
                                 "cancel_bulk_load <app>"),
            "recover": (self.cmd_recover,
                        "recover <node> [node...] — rebuild meta state from nodes"),
            "ddd_diagnose": (self.cmd_ddd_diagnose,
                             "ddd_diagnose [app] [-f] — find/fix double-dead partitions"),
            "version": (self.cmd_version, "server + shell version"),
            "timeout": (self.cmd_timeout,
                        "timeout [ms] — get/set the data-op client timeout"),
            "hash": (self.cmd_hash,
                     "hash <hk> <sk> — partition hash + routed pidx"),
            "app_stat": (self.cmd_app_stat,
                         "per-app qps/cu aggregates scraped from primaries"),
            "app_disk": (self.cmd_app_disk,
                         "app_disk [app] — per-replica disk usage by node"),
            "multi_get_sortkeys": (self.cmd_multi_get_sortkeys,
                                   "multi_get_sortkeys <hk> — sortkeys only"),
            "multi_get_range": (self.cmd_multi_get_range,
                                "multi_get_range <hk> <start_sk> <stop_sk>"),
            "multi_del_range": (self.cmd_multi_del_range,
                                "multi_del_range <hk> <start_sk> <stop_sk>"),
            "clear_app_envs": (self.cmd_clear_app_envs,
                               "reset every app env of the current table"),
            "clear_data": (self.cmd_clear_data,
                           "clear_data <table> yes — delete EVERY row"),
            "get_meta_level": (self.cmd_get_meta_level,
                               "meta function level (blind/freezed/steady/lively)"),
            "set_meta_level": (self.cmd_set_meta_level,
                               "set_meta_level <blind|freezed|steady|lively>"),
            "query_backup_policy": (self.cmd_ls_backup_policy,
                                    "alias of ls_backup_policy"),
            "batched_manual_compact": (self.cmd_batched_manual_compact,
                                       "batched_manual_compact <node|all> — "
                                       "node-level batched device compaction"),
            "sst_dump": (self.cmd_sst_dump,
                         "sst_dump <file.sst> [max_rows] — offline SST reader"),
            "mlog_dump": (self.cmd_mlog_dump,
                          "mlog_dump <plog_dir> [from_decree] — offline log reader"),
            "local_get": (self.cmd_local_get,
                          "local_get <replica_data_dir> <hashkey> <sortkey>"),
            "cc": (self.cmd_cc,
                   "cc <meta1[,meta2...]> — change to another cluster"),
            "escape_all": (self.cmd_escape_all,
                           "escape_all [true|false] — escape all bytes, not "
                           "just invisible ones"),
            "flush_log": (self.cmd_flush_log,
                          "flush_log <node|all> — fsync mutation logs"),
            "rdb_key_str2hex": (self.cmd_rdb_key_str2hex,
                                "rdb_key_str2hex <hashkey> <sortkey>"),
            "rdb_key_hex2str": (self.cmd_rdb_key_hex2str,
                                "rdb_key_hex2str <rdb_key_hex>"),
            "rdb_value_hex2str": (self.cmd_rdb_value_hex2str,
                                  "rdb_value_hex2str <value_hex>"),
            "query_restore_status": (self.cmd_query_restore_status,
                                     "query_restore_status <new_app>"),
            "exit": (None, "quit"),
            "quit": (None, "quit"),
        }

    # ----------------------------------------------------------- plumbing

    def _meta_call(self, code, req, resp_cls):
        last = None
        for m in self.meta_addrs:
            host, _, port = m.rpartition(":")
            try:
                conn = self.pool.get((host, int(port)))
                _, body = conn.call(code, codec.encode(req), timeout=10.0)
                return codec.decode(resp_cls, body)
            except (RpcError, OSError) as e:
                last = e
        raise RpcError(7, f"no meta reachable: {last}")

    def _node_command(self, node, command, args):
        host, _, port = node.rpartition(":")
        conn = self.pool.get((host, int(port)))
        _, body = conn.call("RPC_CLI_CLI_CALL",
                            codec.encode(RemoteCommandRequest(command, args)),
                            timeout=10.0)
        return codec.decode(RemoteCommandResponse, body).output

    def _client(self, app=None) -> PegasusClient:
        app = app or self.current_app
        if app is None:
            raise PegasusError(4, "no table selected (use <name>)")
        if app not in self._clients:
            self._clients[app] = PegasusClient(
                MetaResolver(self.meta_addrs, app, self.pool),
                timeout=getattr(self, "_default_timeout", 10.0))
        return self._clients[app]

    def _nodes(self):
        r = self._meta_call(RPC_CM_LIST_NODES, mm.ListNodesRequest(),
                            mm.ListNodesResponse)
        return r.nodes

    def p(self, *args):
        print(*args, file=self.out)

    def _esc(self, data: bytes) -> str:
        return c_escape_string(data, getattr(self, "escape_all", False))

    # ----------------------------------------------------------- commands

    def cmd_help(self, args):
        for name, (_, doc) in sorted(self.commands.items()):
            self.p(f"  {name:<22} {doc}")

    def cmd_cluster_info(self, args):
        apps = self._meta_call(RPC_CM_LIST_APPS, mm.ListAppsRequest(),
                               mm.ListAppsResponse).apps
        nodes = self._nodes()
        self.p(f"meta_servers       : {','.join(self.meta_addrs)}")
        self.p(f"app_count          : {len(apps)}")
        self.p(f"node_count         : {len(nodes)} "
               f"({sum(1 for n in nodes if n.alive)} alive)")

    def cmd_ls(self, args):
        apps = self._meta_call(RPC_CM_LIST_APPS, mm.ListAppsRequest(),
                               mm.ListAppsResponse).apps
        self.p(f"{'app_id':>6}  {'status':<14} {'app_name':<24} "
               f"{'pcount':>6} {'rcount':>6}")
        for a in sorted(apps, key=lambda x: x.app_id):
            self.p(f"{a.app_id:>6}  {a.status:<14} {a.app_name:<24} "
                   f"{a.partition_count:>6} {a.replica_count:>6}")

    def cmd_app(self, args):
        name = args[0] if args else self.current_app
        cfg = self._meta_call(RPC_CM_QUERY_CONFIG, mm.QueryConfigRequest(name),
                              mm.QueryConfigResponse)
        if cfg.error:
            self.p(f"ERROR: {cfg.error_text}")
            return
        self.p(f"app {cfg.app.app_name} id={cfg.app.app_id} "
               f"partitions={cfg.app.partition_count}")
        self.p(f"{'pidx':>4} {'ballot':>6}  {'primary':<22} secondaries")
        for pc in cfg.partitions:
            self.p(f"{pc.pidx:>4} {pc.ballot:>6}  {pc.primary:<22} "
                   f"{','.join(pc.secondaries)}")

    def cmd_create(self, args):
        ap = argparse.ArgumentParser(prog="create")
        ap.add_argument("name")
        ap.add_argument("-p", "--partition_count", type=int, default=8)
        ap.add_argument("-r", "--replica_count", type=int, default=3)
        ns = ap.parse_args(args)
        r = self._meta_call(RPC_CM_CREATE_APP,
                            mm.CreateAppRequest(ns.name, ns.partition_count,
                                                ns.replica_count),
                            mm.CreateAppResponse)
        self.p(f"ERROR: {r.error_text}" if r.error
               else f"create app {ns.name} succeed, id={r.app_id}")

    def cmd_drop(self, args):
        ap = argparse.ArgumentParser(prog="drop", add_help=False)
        ap.add_argument("name")
        ap.add_argument("-r", "--reserve_seconds", type=int, default=0)
        try:
            ns = ap.parse_args(args)
        except SystemExit:
            raise ValueError(args)
        r = self._meta_call(RPC_CM_DROP_APP,
                            mm.DropAppRequest(ns.name, ns.reserve_seconds),
                            mm.DropAppResponse)
        self._clients.pop(ns.name, None)
        self.p(f"ERROR: {r.error_text}" if r.error
               else f"drop app {ns.name} succeed")

    def cmd_recall(self, args):
        from ..meta.meta_server import RPC_CM_RECALL_APP

        new_name = args[1] if len(args) > 1 else ""
        r = self._meta_call(RPC_CM_RECALL_APP,
                            mm.RecallAppRequest(int(args[0]), new_name),
                            mm.RecallAppResponse)
        self.p(f"recall app {args[0]} failed, error={r.error_text}" if r.error
               else f"recall app {args[0]} succeed, name={r.app_name}")

    def cmd_use(self, args):
        self.current_app = args[0]
        self.p(f"OK, table: {args[0]}")

    def cmd_nodes(self, args):
        self.p(f"{'address':<22} {'status':<8} {'replica_count':>13}")
        for n in self._nodes():
            self.p(f"{n.address:<22} {'ALIVE' if n.alive else 'UNALIVE':<8} "
                   f"{n.replica_count:>13}")

    # data ops ------------------------------------------------------------

    def cmd_set(self, args):
        ttl = int(args[3]) if len(args) > 3 else 0
        self._client().set(args[0].encode(), args[1].encode(),
                           args[2].encode(), ttl_seconds=ttl)
        self.p("OK")

    def cmd_get(self, args):
        v = self._client().get(args[0].encode(), args[1].encode())
        self.p("not found" if v is None else f'"{self._esc(v)}"')

    def cmd_del(self, args):
        self._client().delete(args[0].encode(), args[1].encode())
        self.p("OK")

    def cmd_exist(self, args):
        self.p(str(self._client().exist(args[0].encode(), args[1].encode())).lower())

    def cmd_ttl(self, args):
        t = self._client().ttl(args[0].encode(), args[1].encode())
        self.p("not found" if t is None
               else ("no ttl" if t < 0 else f"{t} seconds"))

    def cmd_incr(self, args):
        by = int(args[2]) if len(args) > 2 else 1
        self.p(str(self._client().incr(args[0].encode(), args[1].encode(), by)))

    def cmd_multi_set(self, args):
        hk, rest = args[0].encode(), args[1:]
        kvs = {rest[i].encode(): rest[i + 1].encode()
               for i in range(0, len(rest) - 1, 2)}
        self._client().multi_set(hk, kvs)
        self.p(f"OK, {len(kvs)} kvs")

    def cmd_multi_get(self, args):
        hk = args[0].encode()
        sks = [a.encode() for a in args[1:]] or None
        complete, kvs = self._client().multi_get(hk, sort_keys=sks)
        for sk in sorted(kvs):
            self.p(f'"{self._esc(sk)}" : "{self._esc(kvs[sk])}"')
        self.p(f"{len(kvs)} rows{'' if complete else ' (incomplete)'}")

    def cmd_multi_del(self, args):
        n = self._client().multi_del(args[0].encode(),
                                     [a.encode() for a in args[1:]])
        self.p(f"OK, {n} deleted")

    def cmd_sortkey_count(self, args):
        self.p(str(self._client().sortkey_count(args[0].encode())))

    @staticmethod
    def _cas_check_type(token: str) -> int:
        from ..rpc.messages import CasCheckType

        try:
            return int(token)
        except ValueError:
            return CasCheckType[token.upper()].value

    def cmd_check_and_set(self, args):
        """check_and_set <hk> <check_sk> <check_type> <operand> <set_sk>
        <set_value> [ttl] (reference shell data_operations check_and_set)."""
        ct = self._cas_check_type(args[2])
        ttl = int(args[6]) if len(args) > 6 else 0
        r = self._client().check_and_set(
            args[0].encode(), args[1].encode(), ct, args[3].encode(),
            args[4].encode(), args[5].encode(), set_ttl_seconds=ttl,
            return_check_value=True)
        from ..rpc.messages import Status

        self.p(f"set_succeed: {str(r.error == Status.OK).lower()}")
        if r.check_value_returned and r.check_value_exist:
            self.p(f'check_value: "{self._esc(r.check_value)}"')

    def cmd_check_and_mutate(self, args):
        """check_and_mutate <hk> <check_sk> <check_type> <operand>
        set <sk> <v> | del <sk> [...]."""
        ct = self._cas_check_type(args[2])
        muts, i = [], 4
        while i < len(args):
            if args[i] == "set":
                muts.append(("set", args[i + 1].encode(),
                             args[i + 2].encode(), 0))
                i += 3
            elif args[i] == "del":
                muts.append(("del", args[i + 1].encode()))
                i += 2
            else:
                self.p(f"bad mutation token {args[i]!r}")
                return
        if not muts:
            self.p("no mutations given")
            return
        r = self._client().check_and_mutate(
            args[0].encode(), args[1].encode(), ct, args[3].encode(), muts,
            return_check_value=True)
        from ..rpc.messages import Status

        self.p(f"mutate_succeed: {str(r.error == Status.OK).lower()}")
        if r.check_value_returned and r.check_value_exist:
            self.p(f'check_value: "{self._esc(r.check_value)}"')

    def cmd_hash_scan(self, args):
        hk = args[0].encode()
        start = args[1].encode() if len(args) > 1 else b""
        stop = args[2].encode() if len(args) > 2 else b""
        n = 0
        for _, sk, v in self._client().get_scanner(hk, start, stop):
            self.p(f'"{self._esc(sk)}" : "{self._esc(v)}"')
            n += 1
        self.p(f"{n} rows")

    def cmd_full_scan(self, args):
        limit = int(args[0]) if args else 1 << 30
        n = 0
        for sc in self._client().get_unordered_scanners():
            for hk, sk, v in sc:
                self.p(f'"{self._esc(hk)}" : "{self._esc(sk)}" => '
                       f'"{self._esc(v)}"')
                n += 1
                if n >= limit:
                    self.p(f"{n} rows (limited)")
                    return
        self.p(f"{n} rows")

    def cmd_count_data(self, args):
        n = 0
        for sc in self._client().get_unordered_scanners():
            for _ in sc:
                n += 1
        self.p(f"{n} rows")

    def cmd_copy_data(self, args):
        dest = self._client(args[0])
        n = 0
        for sc in self._client().get_unordered_scanners():
            for hk, sk, v in sc:
                dest.set(hk, sk, v)
                n += 1
        self.p(f"copied {n} rows to {args[0]}")

    # env / admin ---------------------------------------------------------

    def _set_envs(self, envs: dict):
        r = self._meta_call(RPC_CM_SET_APP_ENVS,
                            mm.SetAppEnvsRequest(self.current_app,
                                                 json.dumps(envs)),
                            mm.SetAppEnvsResponse)
        if r.error:
            self.p(f"ERROR: {r.error_text}")
        return r.error == 0

    def cmd_get_app_envs(self, args):
        cfg = self._meta_call(RPC_CM_QUERY_CONFIG,
                              mm.QueryConfigRequest(self.current_app),
                              mm.QueryConfigResponse)
        self.p(json.dumps(json.loads(cfg.app.envs_json), indent=1))

    def cmd_set_app_envs(self, args):
        envs = {args[i]: args[i + 1] for i in range(0, len(args) - 1, 2)}
        if self._set_envs(envs):
            self.p(f"set {len(envs)} envs OK")

    def cmd_del_app_envs(self, args):
        # empty value removes at the replica layer; meta keeps the tombstone
        if self._set_envs({k: "" for k in args}):
            self.p("OK")

    def cmd_manual_compact(self, args):
        if self._set_envs({"manual_compact.once.trigger_time":
                           str(int(time.time()))}):
            self.p("manual compact triggered")

    def cmd_query_compact(self, args):
        for n in self._nodes():
            if n.alive:
                self.p(f"[{n.address}]")
                self.p(self._node_command(n.address, "query-compact-state", []))

    def cmd_compact_sched(self, args):
        """Per-partition compaction-scheduler decisions, one line per
        gpid: the policy token, the reasons that drove it (which signal
        deferred/promoted it) and the live debt behind it."""
        target = args[0] if args else "all"
        rest = args[1:]
        nodes = ([n.address for n in self._nodes() if n.alive]
                 if target == "all" else [target])
        for node in nodes:
            try:
                out = self._node_command(node, "compact-sched-status", rest)
                doc = json.loads(out)
            except (RpcError, OSError, ValueError) as e:
                self.p(f"[{node}] unreachable/bad reply: {e}")
                continue
            self.p(f"[{node}]")
            if not isinstance(doc, dict) or not doc:
                self.p("  no partitions")
                continue
            for gpid, d in sorted(doc.items()):
                if not isinstance(d, dict) or "policy" not in d:
                    self.p(f"  {gpid}: {d}")
                    continue
                reasons = ",".join(d.get("reasons", [])) or "-"
                where = d.get("offload") or "local"
                self.p(f"  {gpid}: {d['policy']:<7} where={where} "
                       f"reasons={reasons} "
                       f"l0={d.get('l0_files', 0)}"
                       f"/{d.get('ceiling_files', '?')} "
                       f"debt_bytes={d.get('debt_bytes', 0)} "
                       f"pending={d.get('pending_installs', 0)} "
                       f"expires_in={d.get('expires_in_s', 0)}s")

    def cmd_offload_status(self, args):
        """One compaction-offload service's live state: free merge
        budget (what the scheduler's placement fold consumes), running
        merges, active jobs, staged bytes."""
        if not args:
            self.p("usage: offload_status <host:port>")
            return
        self.p(self._node_command(args[0], "offload-status", []))

    def cmd_remote_command(self, args):
        target, cmd, rest = args[0], args[1], args[2:]
        nodes = ([n.address for n in self._nodes() if n.alive]
                 if target == "all" else [target])
        for node in nodes:
            self.p(f"[{node}]")
            self.p(self._node_command(node, cmd, rest))

    def cmd_server_info(self, args):
        self.cmd_remote_command(["all", "server-info"])

    def cmd_server_stat(self, args):
        self.cmd_remote_command(["all", "server-stat"])

    def cmd_perf_counters(self, args):
        node = args[0]
        cmd = "perf-counters-by-prefix" if len(args) > 1 else "perf-counters"
        self.p(self._node_command(node, cmd, args[1:]))

    def cmd_compact_trace(self, args):
        if args:
            self.p(self._node_command(args[0], "compact-trace-dump",
                                      args[1:]))
        else:
            self.cmd_remote_command(["all", "compact-trace-dump"])

    def cmd_device_health(self, args):
        self.cmd_remote_command(["all", "device-health"])

    def cmd_quarantine_status(self, args):
        if args:
            self.p(self._node_command(args[0], "quarantine-status", args[1:]))
        else:
            self.cmd_remote_command(["all", "quarantine-status"])

    def cmd_scrub_replica(self, args):
        if not args:
            self.p("usage: scrub_replica <node|all> [gpid]")
            return
        self.cmd_remote_command([args[0], "scrub-replica"] + args[1:])

    def cmd_request_trace(self, args):
        if args:
            self.p(self._node_command(args[0], "request-trace-dump", args[1:]))
        else:
            self.cmd_remote_command(["all", "request-trace-dump"])

    def cmd_job_trace(self, args):
        if args:
            self.p(self._node_command(args[0], "job-trace", args[1:]))
        else:
            self.cmd_remote_command(["all", "job-trace"])

    def cmd_slow_requests(self, args):
        if args and args[0] == "--cluster":
            from ..collector.info_collector import rollup_slow_requests

            last = int(args[1]) if len(args) > 1 else 20
            nodes = [n.address for n in self._nodes() if n.alive]
            merged = rollup_slow_requests(
                lambda n: self._node_command(n, "slow-requests", [str(last)]),
                nodes, last=last)
            self.p(json.dumps(merged, indent=1))
        elif args:
            self.p(self._node_command(args[0], "slow-requests", args[1:]))
        else:
            self.cmd_remote_command(["all", "slow-requests"])

    def cmd_events(self, args):
        if args:
            self.p(self._node_command(args[0], "events-dump", args[1:]))
        else:
            self.cmd_remote_command(["all", "events-dump"])

    def cmd_flight_recorder(self, args):
        from ..collector.flight_recorder import RECORDER

        sub = args[0] if args else "list"
        if sub == "capture":
            reason = " ".join(args[1:]) or "shell capture"
            inc = RECORDER.capture(self.meta_addrs, reason=reason,
                                   trigger="shell", pool=self.pool)
            self.p(json.dumps({"id": inc["id"], "path": inc["path"],
                               "first_cause": inc["first_cause"],
                               "timeline_events": len(inc["timeline"]),
                               "errors": inc["errors"]}, indent=1))
        elif sub == "show" and len(args) > 1:
            inc = RECORDER.load(args[1])
            self.p(json.dumps(inc, indent=1) if inc
                   else f"no retained incident {args[1]!r}")
        else:
            incidents = RECORDER.list_incidents()
            if not incidents:
                self.p("no retained incidents")
            for i in incidents:
                self.p(f"{i['id']}  trigger={i['trigger']} "
                       f"first_cause={i['first_cause']}  {i['reason']}")

    def cmd_trigger_audit(self, args):
        from ..collector.cluster_doctor import run_cluster_audit

        apps = [args[0]] if args else (
            [self.current_app] if self.current_app else None)
        report = run_cluster_audit(self.meta_addrs, pool=self.pool,
                                   apps=apps)
        self.p(json.dumps(report, indent=1))
        if report["mismatches"]:
            self.p(f"AUDIT FAILED: {len(report['mismatches'])} digest "
                   "mismatch(es)")
        elif report["inconclusive"]:
            self.p("audit inconclusive for "
                   f"{len(report['inconclusive'])} partition(s)")
        else:
            self.p(f"audit OK: {len(report['ok'])} partition(s), all "
                   "replicas identical at identical decrees")

    def cmd_cluster_doctor(self, args):
        from ..collector.cluster_doctor import run_cluster_doctor

        last = int(args[0]) if args else 10
        verdict = run_cluster_doctor(self.meta_addrs, pool=self.pool,
                                     slow_last=last)
        self.p(json.dumps(verdict, indent=1))
        self.p(f"cluster verdict: {verdict['verdict'].upper()}"
               + (f" ({len(verdict['causes'])} cause(s))"
                  if verdict["causes"] else ""))

    def cmd_tables(self, args):
        from ..runtime.table_stats import fold_snapshots, top_k

        k = int(args[0]) if args else 5
        frags = []
        for node in [n.address for n in self._nodes() if n.alive]:
            try:
                reply = json.loads(
                    self._node_command(node, "table-stats", []))
            except ValueError:
                continue
            if isinstance(reply, dict):
                frags.extend(v for v in reply.values()
                             if isinstance(v, dict))
        folded = fold_snapshots(frags)
        self.p(json.dumps({"tables": folded, "top": top_k(folded, k)},
                          indent=1))

    def cmd_slo(self, args):
        if args:
            self.p(self._node_command(args[0], "slo-status", args[1:]))
            return
        merged = {}
        for node in [n.address for n in self._nodes() if n.alive]:
            try:
                reply = json.loads(self._node_command(node, "slo-status", []))
            except ValueError:
                continue
            if isinstance(reply, dict):
                for verdicts in reply.values():
                    if isinstance(verdicts, dict):
                        merged.update(verdicts)
        self.p(json.dumps(merged, indent=1))
        burning = sorted(t for t, v in merged.items()
                         if isinstance(v, dict)
                         and v.get("verdict") == "burning")
        if burning:
            self.p("BURNING: " + ", ".join(burning))

    def cmd_detect_hotkey(self, args):
        node, rest = args[0], args[1:]
        self.p(self._node_command(node, "detect_hotkey", rest))

    def cmd_set_fail_point(self, args):
        if len(args) < 3:
            self.p("usage: set_fail_point <node|all> <name> <action>")
            return
        target, rest = args[0], args[1:]
        nodes = ([n.address for n in self._nodes() if n.alive]
                 if target == "all" else [target])
        for node in nodes:
            self.p(f"[{node}] "
                   + self._node_command(node, "set-fail-point", rest))

    def cmd_cross_cluster_audit(self, args):
        from ..collector.cluster_doctor import run_cross_cluster_audit

        if len(args) < 2:
            self.p("usage: cross_cluster_audit <app> "
                   "<dst_meta[,dst_meta...]> [dupid]")
            return
        app, dst = args[0], args[1].split(",")
        dupid = int(args[2]) if len(args) > 2 else None
        report = run_cross_cluster_audit(self.meta_addrs, dst, app,
                                         dupid=dupid)
        self.p(json.dumps(report, indent=1))
        if report["match"] is True:
            self.p(f"cross-cluster audit OK: {report['src']['records']} "
                   "records, table digests identical at the confirmed "
                   "decree anchors")
        elif report["match"] is False:
            self.p("cross-cluster audit MISMATCH")
        else:
            self.p("cross-cluster audit inconclusive: "
                   + "; ".join(report["inconclusive"]))

    def cmd_propose(self, args):
        from ..meta.meta_server import RPC_CM_PROPOSE

        r = self._meta_call(RPC_CM_PROPOSE,
                            mm.ProposeRequest(self.current_app, int(args[0]),
                                              args[1]),
                            mm.ProposeResponse)
        self.p(f"ERROR: {r.error_text}" if r.error else "OK")

    def cmd_balance(self, args):
        from ..meta.meta_server import RPC_CM_BALANCE

        r = self._meta_call(RPC_CM_BALANCE, mm.BalanceRequest(),
                            mm.BalanceResponse)
        if r.error:
            self.p(f"ERROR: {r.error_text or 'balance refused'}")
        else:
            self.p(f"moved {r.moved} primaries")

    # duplication ---------------------------------------------------------
    # (reference src/shell/commands/duplication.cpp:32-260)

    def cmd_add_dup(self, args):
        from ..meta.meta_server import RPC_CM_ADD_DUPLICATION

        freeze = "-f" in args or "--freeze" in args
        pos = [a for a in args if not a.startswith("-")]
        r = self._meta_call(RPC_CM_ADD_DUPLICATION,
                            mm.AddDuplicationRequest(pos[0], pos[1], freeze),
                            mm.AddDuplicationResponse)
        if r.error:
            self.p(f"adding duplication failed: {r.error_text}")
        else:
            self.p(f"adding duplication succeed [app: {pos[0]}, remote: "
                   f"{pos[1]}, appid: {r.app_id}, dupid: {r.dupid}, "
                   f"freeze: {str(freeze).lower()}]")

    def cmd_query_dup(self, args):
        from ..meta.meta_server import RPC_CM_QUERY_DUPLICATION

        r = self._meta_call(RPC_CM_QUERY_DUPLICATION,
                            mm.QueryDuplicationRequest(args[0]),
                            mm.QueryDuplicationResponse)
        if r.error:
            self.p(f"ERROR: {r.error_text}")
            return
        self.p(f"duplications of app [{args[0]}]:")
        for e in r.entries:
            created = time.strftime("%Y-%m-%d %H:%M:%S",
                                    time.localtime(e.create_ts_ms / 1000))
            self.p(f"  dupid={e.dupid} status={e.status} remote={e.remote} "
                   f"fail_mode={e.fail_mode} create_time={created}")
        if not r.entries:
            self.p("  (none)")

    def _modify_dup(self, app, dupid, status="", fail_mode="", verb=""):
        from ..meta.meta_server import RPC_CM_MODIFY_DUPLICATION

        r = self._meta_call(RPC_CM_MODIFY_DUPLICATION,
                            mm.ModifyDuplicationRequest(
                                app, int(dupid), status, fail_mode),
                            mm.ModifyDuplicationResponse)
        self.p(f"{verb} failed: {r.error_text}" if r.error else f"{verb} succeed")

    def cmd_start_dup(self, args):
        self._modify_dup(args[0], args[1], status="start",
                         verb=f"starting duplication({args[1]})")

    def cmd_pause_dup(self, args):
        self._modify_dup(args[0], args[1], status="pause",
                         verb=f"pausing duplication({args[1]})")

    def cmd_remove_dup(self, args):
        self._modify_dup(args[0], args[1], status="removed",
                         verb=f"removing duplication({args[1]})")

    def cmd_set_dup_fail_mode(self, args):
        if args[2] not in ("slow", "skip"):
            self.p('fail_mode must be "slow" or "skip"')
            return
        self._modify_dup(args[0], args[1], fail_mode=args[2],
                         verb=f"setting fail_mode({args[2]})")

    # backup / restore ----------------------------------------------------
    # (reference src/shell/commands/cold_backup.cpp incl. policy surface)

    def cmd_backup_app(self, args):
        from ..meta.meta_server import RPC_CM_BACKUP_APP

        r = self._meta_call(RPC_CM_BACKUP_APP,
                            mm.BackupAppRequest(args[0], args[1]),
                            mm.BackupAppResponse)
        if r.error:
            self.p(f"backup failed: {r.error_text}")
        else:
            self.p(f"backup succeed, backup_id={r.backup_id}")

    def cmd_restore_app(self, args):
        from ..meta.meta_server import RPC_CM_RESTORE_APP

        r = self._meta_call(RPC_CM_RESTORE_APP,
                            mm.RestoreAppRequest(args[0], int(args[1]),
                                                 args[2], args[3]),
                            mm.RestoreAppResponse)
        if r.error:
            self.p(f"restore failed: {r.error_text}")
        else:
            self.p(f"restore succeed, new app_id={r.app_id}")

    def cmd_add_backup_policy(self, args):
        from ..meta.meta_server import RPC_CM_ADD_BACKUP_POLICY

        pol = mm.BackupPolicyInfo(
            name=args[0], backup_root=args[1], apps=args[2].split(","),
            interval_seconds=int(args[3]),
            history_count=int(args[4]) if len(args) > 4 else 3)
        r = self._meta_call(RPC_CM_ADD_BACKUP_POLICY,
                            mm.AddBackupPolicyRequest(pol),
                            mm.AddBackupPolicyResponse)
        self.p(f"ERROR: {r.error_text}" if r.error else "OK")

    def cmd_ls_backup_policy(self, args):
        from ..meta.meta_server import RPC_CM_LS_BACKUP_POLICY

        r = self._meta_call(RPC_CM_LS_BACKUP_POLICY,
                            mm.LsBackupPolicyRequest(args[0] if args else ""),
                            mm.LsBackupPolicyResponse)
        if r.error:
            self.p(f"ERROR: {r.error_text}")
            return
        for p in r.policies:
            self.p(f"name={p.name} enabled={p.enabled} "
                   f"interval={p.interval_seconds}s history={p.history_count} "
                   f"root={p.backup_root}")
            self.p(f"  apps: {','.join(p.apps)}")
            self.p(f"  recent backups: {p.recent_backup_ids}")
        if not r.policies:
            self.p("(no policies)")

    def _modify_policy(self, req):
        from ..meta.meta_server import RPC_CM_MODIFY_BACKUP_POLICY

        r = self._meta_call(RPC_CM_MODIFY_BACKUP_POLICY, req,
                            mm.ModifyBackupPolicyResponse)
        self.p(f"ERROR: {r.error_text}" if r.error else "OK")

    def cmd_modify_backup_policy(self, args):
        req = mm.ModifyBackupPolicyRequest(name=args[0])
        i = 1
        while i < len(args):
            if args[i] == "-i":
                req.interval_seconds = int(args[i + 1]); i += 2
            elif args[i] == "-c":
                req.history_count = int(args[i + 1]); i += 2
            elif args[i] == "--add":
                req.add_apps = args[i + 1].split(","); i += 2
            elif args[i] == "--remove":
                req.remove_apps = args[i + 1].split(","); i += 2
            else:
                raise ValueError(args[i])
        self._modify_policy(req)

    def cmd_enable_backup_policy(self, args):
        self._modify_policy(mm.ModifyBackupPolicyRequest(name=args[0],
                                                         enabled=1))

    def cmd_disable_backup_policy(self, args):
        self._modify_policy(mm.ModifyBackupPolicyRequest(name=args[0],
                                                         enabled=0))

    # bulk load / disaster recovery ---------------------------------------
    # (reference src/shell/commands/{bulk_load,recovery}.cpp)

    def cmd_start_bulk_load(self, args):
        from ..meta.meta_server import RPC_CM_START_BULK_LOAD

        async_start = "-a" in args
        args = [a for a in args if a != "-a"]
        r = self._meta_call(RPC_CM_START_BULK_LOAD,
                            mm.StartBulkLoadRequest(args[0], args[1],
                                                    async_start=async_start),
                            mm.StartBulkLoadResponse)
        if r.error:
            self.p(f"bulk load failed: {r.error_text}")
        elif async_start:
            self.p("bulk load session started "
                   "(query_bulk_load_status to follow)")
        else:
            self.p(f"bulk load succeed, ingested {r.ingested_records} records")

    def cmd_query_bulk_load(self, args):
        from ..meta.meta_server import RPC_CM_QUERY_BULK_LOAD

        r = self._meta_call(RPC_CM_QUERY_BULK_LOAD,
                            mm.QueryBulkLoadRequest(args[0]),
                            mm.QueryBulkLoadResponse)
        if r.error:
            self.p(f"query failed: {r.error_text}")
        else:
            extra = f" ({r.error_text})" if r.error_text else ""
            self.p(f"bulk load of {args[0]}: {r.status}{extra}, "
                   f"{r.done_partitions}/{r.total_partitions} partitions, "
                   f"{r.ingested_records} records")

    def _control_bulk_load(self, app, action):
        from ..meta.meta_server import RPC_CM_CONTROL_BULK_LOAD

        r = self._meta_call(RPC_CM_CONTROL_BULK_LOAD,
                            mm.ControlBulkLoadRequest(app, action),
                            mm.ControlBulkLoadResponse)
        self.p(f"{action} failed: {r.error_text}" if r.error
               else f"{action} OK")

    def cmd_pause_bulk_load(self, args):
        self._control_bulk_load(args[0], "pause")

    def cmd_restart_bulk_load(self, args):
        self._control_bulk_load(args[0], "restart")

    def cmd_cancel_bulk_load(self, args):
        self._control_bulk_load(args[0], "cancel")

    def cmd_query_restore_status(self, args):
        from ..meta.meta_server import RPC_CM_QUERY_RESTORE

        r = self._meta_call(RPC_CM_QUERY_RESTORE,
                            mm.QueryRestoreRequest(args[0]),
                            mm.QueryRestoreResponse)
        if r.status == "none":
            self.p(f"no restore recorded for {args[0]}")
        else:
            self.p(f"restore of {args[0]}: {r.status}, from "
                   f"{r.old_app_name}@{r.backup_id}, "
                   f"{r.done_partitions}/{r.total_partitions} partitions")

    def cmd_recover(self, args):
        from ..meta.meta_server import RPC_CM_RECOVER

        r = self._meta_call(RPC_CM_RECOVER, mm.RecoverRequest(list(args)),
                            mm.RecoverResponse)
        if r.error:
            self.p(f"recover failed: {r.error_text}")
        else:
            self.p(f"recovered apps: {r.recovered_apps or '(none)'}")

    def cmd_ddd_diagnose(self, args):
        from ..meta.meta_server import RPC_CM_DDD_DIAGNOSE

        force = "-f" in args or "--force" in args
        pos = [a for a in args if not a.startswith("-")]
        r = self._meta_call(RPC_CM_DDD_DIAGNOSE,
                            mm.DddDiagnoseRequest(pos[0] if pos else "", force),
                            mm.DddDiagnoseResponse)
        if r.error:
            self.p(f"ERROR: {r.error_text}")
            return
        if not r.partitions:
            self.p("no double-dead partitions")
            return
        for d in r.partitions:
            self.p(f"[{d.app_name}.{d.pidx}] {d.reason}")
            for c in d.candidates:
                self.p(f"  candidate: {c}")
            self.p(f"  action: {d.action or '(none; rerun with -f to fix)'}")

    # misc admin / data utilities -----------------------------------------

    def cmd_version(self, args):
        from ..runtime.remote_command import VERSION

        self.p(VERSION)
        for n in self._nodes():
            try:
                self.p(f"{n.address}: {self._node_command(n.address, 'server-info', [])}")
            except (RpcError, OSError) as e:
                self.p(f"{n.address}: unreachable ({e})")

    def cmd_timeout(self, args):
        if args:
            ms = int(args[0])
            for cli in self._clients.values():
                cli.timeout = ms / 1000.0
            self._default_timeout = ms / 1000.0
        cur = getattr(self, "_default_timeout", 10.0)
        self.p(f"timeout: {int(cur * 1000)} ms")

    def cmd_hash(self, args):
        from ..base.key_schema import generate_key, key_hash

        key = generate_key(args[0].encode(), args[1].encode())
        h = key_hash(key)
        line = f"hash: {h}"
        if self.current_app:
            n = self._client().resolver.partition_count
            line += f"  partition: {h % n} (of {n})"
        self.p(line)

    def cmd_app_stat(self, args):
        from ..collector.info_collector import InfoCollector

        coll = InfoCollector(self.meta_addrs)
        try:
            summary = coll.collect_once()
        finally:
            coll.stop()
        hdr = ["get_qps", "put_qps", "multi_get_qps", "scan_qps",
               "recent_read_cu", "recent_write_cu"]
        self.p(f"{'app':<16} " + " ".join(f"{h:>15}" for h in hdr))
        for app, agg in sorted(summary.items()):
            self.p(f"{app:<16} " + " ".join(f"{agg.get(h, 0):>15.1f}"
                                            for h in hdr))

    def cmd_app_disk(self, args):
        want_app = args[0] if args else None
        app_ids = {}
        r = self._meta_call(RPC_CM_LIST_APPS, mm.ListAppsRequest(),
                            mm.ListAppsResponse)
        for a in r.apps:
            app_ids[str(a.app_id)] = a.app_name
        totals = {}
        for n in self._nodes():
            if not n.alive:
                continue
            try:
                snap = json.loads(self._node_command(n.address,
                                                     "replica-disk", []))
            except (RpcError, OSError, ValueError):
                self.p(f"{n.address} UNREACHABLE — totals below are "
                       f"incomplete")
                continue
            for key, info in snap.items():
                app = app_ids.get(key.split(".")[0], key.split(".")[0])
                if want_app and app != want_app:
                    continue
                t = totals.setdefault(app, {"sst_bytes": 0, "replicas": 0})
                t["sst_bytes"] += info["sst_bytes"]
                t["replicas"] += 1
                self.p(f"{n.address} {app}.{key.split('.')[1]} "
                       f"{info['sst_bytes']}B {info['records']} records "
                       f"{'P' if info['primary'] else 'S'}")
        for app, t in sorted(totals.items()):
            self.p(f"total {app}: {t['sst_bytes']}B across "
                   f"{t['replicas']} replicas")

    def cmd_multi_get_sortkeys(self, args):
        complete, kvs = self._client().multi_get(args[0].encode(),
                                                 no_value=True)
        for sk in sorted(kvs):
            self.p(f'"{self._esc(sk)}"')
        self.p(f"{len(kvs)} sortkeys"
               + ("" if complete else " (INCOMPLETE: server limit hit)"))

    def cmd_multi_get_range(self, args):
        complete, kvs = self._client().multi_get(
            args[0].encode(), start_sortkey=args[1].encode(),
            stop_sortkey=args[2].encode())
        for sk in sorted(kvs):
            self.p(f'"{self._esc(sk)}" : "{self._esc(kvs[sk])}"')
        self.p(f"{len(kvs)} rows"
               + ("" if complete else " (INCOMPLETE: server limit hit)"))

    def cmd_multi_del_range(self, args):
        cli = self._client()
        hk = args[0].encode()
        start, stop = args[1].encode(), args[2].encode()
        deleted = 0
        inclusive = True
        while True:
            # the server's RangeReadLimiter truncates big ranges: page from
            # the last deleted sortkey until the read completes, or a
            # 5000-row range would silently lose its tail
            complete, kvs = cli.multi_get(hk, start_sortkey=start,
                                          stop_sortkey=stop, no_value=True,
                                          start_inclusive=inclusive)
            if kvs:
                deleted += cli.multi_del(hk, list(kvs))
            if complete or not kvs:
                break
            start, inclusive = max(kvs), False
        self.p(f"deleted {deleted} rows")

    def cmd_clear_app_envs(self, args):
        if not self.current_app:
            raise PegasusError(4, "no table selected (use <name>)")
        cfg = self._meta_call(RPC_CM_QUERY_CONFIG,
                              mm.QueryConfigRequest(self.current_app),
                              mm.QueryConfigResponse)
        if cfg.error:
            self.p(f"ERROR: {cfg.error_text}")
            return
        envs = [k for k, v in json.loads(cfg.app.envs_json).items() if v]
        if not envs:
            self.p("no envs set")
            return
        self.cmd_del_app_envs(envs)

    def cmd_clear_data(self, args):
        """Destructive: requires `clear_data <table> yes`."""
        if len(args) < 2 or args[1] != "yes":
            self.p("refusing: run `clear_data <table> yes` to confirm")
            return
        cli = PegasusClient(MetaResolver(self.meta_addrs, args[0], self.pool))
        removed = 0
        for scanner in cli.get_unordered_scanners():
            batch = {}
            for hk, sk, _ in scanner:
                batch.setdefault(hk, []).append(sk)
            for hk, sks in batch.items():
                removed += cli.multi_del(hk, sks)
        self.p(f"cleared {removed} rows from {args[0]}")

    def cmd_get_meta_level(self, args):
        from ..meta.meta_server import RPC_CM_CONTROL_META

        r = self._meta_call(RPC_CM_CONTROL_META, mm.ControlMetaRequest(),
                            mm.ControlMetaResponse)
        self.p(f"meta level: {r.level}")

    def cmd_set_meta_level(self, args):
        from ..meta.meta_server import RPC_CM_CONTROL_META

        r = self._meta_call(RPC_CM_CONTROL_META,
                            mm.ControlMetaRequest(set_level=args[0]),
                            mm.ControlMetaResponse)
        self.p(f"ERROR: {r.error_text}" if r.error
               else f"meta level: {r.level}")

    def cmd_batched_manual_compact(self, args):
        targets = ([n.address for n in self._nodes() if n.alive]
                   if not args or args[0] == "all" else [args[0]])
        for node in targets:
            self.p(f"[{node}] "
                   + self._node_command(node, "batched-manual-compact", []))

    # offline debuggers ---------------------------------------------------
    # (reference src/shell/commands/debugger.cpp: sst_dump / mlog_dump /
    #  local_get read files directly, no cluster needed)

    def cmd_sst_dump(self, args):
        from ..base.key_schema import restore_key
        from ..engine.sstable import SSTable

        sst = SSTable(args[0])
        limit = int(args[1]) if len(args) > 1 else 50
        self.p(f"records={sst.n} level={sst.meta.get('level')} "
               f"decree={sst.meta.get('last_flushed_decree')} "
               f"bytes={sst.data_bytes}")
        b = sst.block()
        for i in range(min(sst.n, limit)):
            hk, sk = restore_key(b.key(i))
            flags = "DEL" if b.deleted[i] else f"exp={int(b.expire_ts[i])}"
            self.p(f'"{self._esc(hk)}" : "{self._esc(sk)}" '
                   f'[{flags}] => {len(b.value(i))}B')
        if sst.n > limit:
            self.p(f"... {sst.n - limit} more")

    def cmd_mlog_dump(self, args):
        import glob
        import os

        from ..replication.mutation_log import MutationLog

        frm = int(args[1]) if len(args) > 1 else 0
        root = args[0]
        # accept a single plog dir OR a replica-node root holding many
        # replicas (<app_id>.<pidx>/plog) — dump each in turn
        if glob.glob(os.path.join(root, "log.*")):
            targets = [("", root)]
        else:
            targets = sorted(
                (os.path.basename(d), os.path.join(d, "plog"))
                for d in glob.glob(os.path.join(root, "*"))
                if os.path.isdir(os.path.join(d, "plog")))
            if not targets:
                self.p(f"no plog under {root}")
                return
        for label, plog_dir in targets:
            if label:
                self.p(f"[replica {label}]")
            log = MutationLog(plog_dir)
            n = 0
            for m in log.replay(frm):
                self.p(f"decree={m.decree} ballot={m.ballot} ts={m.timestamp_us} "
                       f"ops={[c.rsplit('_', 1)[-1] for c in m.codes]}")
                n += 1
            self.p(f"{n} mutations")
            log.close()

    def cmd_cc(self, args):
        """cc <meta1[,meta2...]> — point the shell at another cluster
        (reference cc_command)."""
        self.meta_addrs = args[0].split(",")
        self.current_app = None
        self._clients = {}
        self.p(f"cluster changed to {','.join(self.meta_addrs)}")

    def cmd_escape_all(self, args):
        """escape_all [true|false] — toggle escaping of every output byte
        (reference process_escape_all)."""
        if args:
            self.escape_all = args[0].lower() in ("true", "1", "on", "yes")
        else:
            self.escape_all = not getattr(self, "escape_all", False)
        self.p(f"escape_all: {str(self.escape_all).lower()}")

    def cmd_flush_log(self, args):
        """flush_log <node|all> — fsync mutation logs on replica nodes."""
        targets = ([n.address for n in self._nodes() if n.alive]
                   if args[0] == "all" else [args[0]])
        for node in targets:
            self.p(f"{node}: {self._node_command(node, 'flush-log', [])}")

    def cmd_rdb_key_str2hex(self, args):
        """rdb_key_str2hex <hashkey> <sortkey> — engine key bytes as hex."""
        from ..base import key_schema

        key = key_schema.generate_key(args[0].encode(), args[1].encode())
        self.p(key.hex().upper())

    def cmd_rdb_key_hex2str(self, args):
        """rdb_key_hex2str <hex> — decode an engine key to hash/sort keys."""
        from ..base import key_schema

        try:
            hk, sk = key_schema.restore_key(bytes.fromhex(args[0]))
        except (ValueError, IndexError) as e:
            self.p(f"bad key hex: {e}")
            return
        self.p(f'hash_key: "{self._esc(hk)}"')
        self.p(f'sort_key: "{self._esc(sk)}"')

    def cmd_rdb_value_hex2str(self, args):
        """rdb_value_hex2str <hex> — decode a stored value (schema v0/v1/v2:
        user data + expire timestamp)."""
        from ..base.utils import epoch_begin
        from ..base.value_schema import ValueSchemaManager

        try:
            raw = bytes.fromhex(args[0])
            # self-describing first byte when present, else latest schema
            schema = ValueSchemaManager().get_value_schema(
                2 if raw and raw[0] & 0x80 else 0, raw)
            user = schema.extract_user_data(raw)
            expire = schema.extract_expire_ts(raw)
        except (ValueError, IndexError) as e:
            self.p(f"bad value hex: {e}")
            return
        self.p(f'user_data: "{self._esc(user)}"')
        if expire:
            self.p(f"expire_ts: {expire} (unix {expire + epoch_begin})")
        else:
            self.p("expire_ts: 0 (no ttl)")

    def cmd_local_get(self, args):
        from ..base.key_schema import generate_key
        from ..base.value_schema import SCHEMAS
        from ..engine.db import EngineOptions, LsmEngine

        eng = LsmEngine(args[0], EngineOptions(backend="cpu"))
        raw = eng.get(generate_key(args[1].encode(), args[2].encode()))
        if raw is None:
            self.p("not found")
        else:
            data = SCHEMAS[eng.data_version()].extract_user_data(raw)
            self.p(f'"{self._esc(data)}"')
        eng.close()

    # ---------------------------------------------------------------- run

    def run_line(self, line: str) -> bool:
        """-> False when the shell should exit."""
        parts = shlex.split(line)
        if not parts:
            return True
        name, args = parts[0], parts[1:]
        if name in ("exit", "quit"):
            return False
        ent = self.commands.get(name)
        if ent is None:
            self.p(f"unknown command {name!r} (try help)")
            return True
        try:
            ent[0](args)
        except (PegasusError, RpcError, OSError) as e:
            self.p(f"ERROR: {e}")
        except (IndexError, ValueError):
            self.p(f"usage: {ent[1]}")
        return True

    def repl(self):
        self.p("pegasus-tpu shell; 'help' for commands")
        while True:
            try:
                prompt = f"{self.current_app or ''}> "
                line = input(prompt)
            except EOFError:
                break
            if not self.run_line(line):
                break


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pegasus-shell")
    ap.add_argument("--meta", default="127.0.0.1:34601",
                    help="comma-separated meta server list")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="one-shot command (flags after the command name "
                         "pass through, e.g. create t -p 8)")
    ns = ap.parse_args(argv)
    sh = Shell(ns.meta.split(","))
    if ns.command:
        sh.run_line(shlex.join(ns.command))
    else:
        sh.repl()


if __name__ == "__main__":
    main()
