"""pegasus-tpu: a from-scratch, TPU-native distributed key-value store.

Capabilities modeled on XiaoMi/pegasus (hash-partitioned tables, PacificA
replication, LSM storage engine with TTL/versioned value schemas), re-designed
TPU-first: the storage engine's flush sort and SST compaction (comparator sort,
k-way level merge, TTL/version/user-rule filtering) run as JAX kernels over
HBM-resident columnar key-value blocks, hash-range-sharded across a device mesh.

Package map (reference layer in parentheses, see SURVEY.md):
  base/        key & value codecs                  (src/base)
  runtime/     config, tasking, counters, failpts  (rDSN runtime slice)
  engine/      LSM storage engine                  (src/server over RocksDB)
  ops/         device sort/merge/filter kernels    (the compaction_backend=tpu path)
  parallel/    mesh-sharded compaction             (hash partitioning across chips)
  replication/ mutation log + PacificA             (rDSN replication)
  rpc/         framed TCP RPC + task codes         (rDSN rpc)
  client/      client library + partition resolver (src/client_lib)
  shell/       admin CLI                           (src/shell)
"""

__version__ = "0.1.0"
