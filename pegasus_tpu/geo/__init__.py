from .cells import cell_id, cell_token, covering_cells, haversine_m, morton
from .geo_client import GeoClient
from .latlng_codec import LatlngCodec

__all__ = ["GeoClient", "LatlngCodec", "cell_id", "cell_token",
           "covering_cells", "haversine_m", "morton"]
