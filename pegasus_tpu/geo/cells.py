"""Spatial cell scheme: Morton (Z-order) curve over quantized lat/lng.

The reference indexes with s2geometry cell ids (src/geo/lib/geo_client.h:
hash_key = level-`min_level` S2 cell, sort_key = deeper cell path). That
library isn't available here, so this build uses an equivalent scheme of
its own: interleave the bits of quantized latitude/longitude into a Morton
code; a "cell at level L" is the top 2*L bits. Morton cells share S2's key
property for this workload — nearby points share prefixes — so the same
dual-table layout and covering-scan search work unchanged.

Level semantics: level L splits the world into 2^L x 2^L cells; cell edge
is ~(180/2^L) degrees of latitude (~20000km/2^L at the equator).
"""

import math

EARTH_RADIUS_M = 6371000.9
_BITS = 30  # quantization bits per axis


def _quantize(v: float, lo: float, hi: float) -> int:
    x = (v - lo) / (hi - lo)
    return min((1 << _BITS) - 1, max(0, int(x * (1 << _BITS))))


def _spread(v: int) -> int:
    """Insert a zero bit between every bit of v (30 -> 60 bits)."""
    out = 0
    for i in range(_BITS):
        out |= ((v >> i) & 1) << (2 * i)
    return out


def morton(lat: float, lng: float) -> int:
    """60-bit interleaved cell code, lat bits even, lng bits odd."""
    return _spread(_quantize(lat, -90.0, 90.0)) | (
        _spread(_quantize(lng, -180.0, 180.0)) << 1)


def cell_id(lat: float, lng: float, level: int) -> int:
    """Top 2*level bits of the Morton code: the level-L cell."""
    return morton(lat, lng) >> (2 * (_BITS - level))


def cell_token(cid: int, level: int) -> bytes:
    """Fixed-width printable token for use as a hash_key."""
    width = -(-2 * level // 4)  # hex digits
    return b"%0*x" % (width, cid)


def haversine_m(lat1, lng1, lat2, lng2) -> float:
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lng2 - lng1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(a)))


MAX_COVERING_CELLS = 4096


def covering_cells(lat: float, lng: float, radius_m: float, level: int) -> list:
    """Level-L cells covering the search circle's bounding box (the
    gen_search_cap covering role). The sample grid step matches the cell
    edge so no covering cell between samples is skipped, whatever the
    radius/level ratio; the cell count is capped (huge radii should use a
    coarser level, like S2 covering limits). Returns sorted unique ids."""
    dlat = math.degrees(radius_m / EARTH_RADIUS_M)
    coslat = max(0.01, math.cos(math.radians(lat)))
    dlng = math.degrees(radius_m / (EARTH_RADIUS_M * coslat))
    cell_h = 180.0 / (1 << level)   # cell edge in latitude degrees
    cell_w = 360.0 / (1 << level)
    # one sample per cell edge, uncapped — the MAX_COVERING_CELLS early
    # return below bounds the work; capping the STEP spacing instead would
    # silently skip cells between samples
    steps_lat = int(2 * dlat / cell_h) + 2
    steps_lng = int(2 * dlng / cell_w) + 2
    cells = set()
    for i in range(steps_lat + 1):
        for j in range(steps_lng + 1):
            la = min(90.0, max(-90.0, lat - dlat + 2 * dlat * i / steps_lat))
            ln = lng - dlng + 2 * dlng * j / steps_lng
            ln = (ln + 180.0) % 360.0 - 180.0
            cells.add(cell_id(la, ln, level))
            if len(cells) >= MAX_COVERING_CELLS:
                return sorted(cells)
    return sorted(cells)
