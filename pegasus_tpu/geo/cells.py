"""Spatial cell scheme: Morton (Z-order) curve over quantized lat/lng.

The reference indexes with s2geometry cell ids (src/geo/lib/geo_client.h:
hash_key = level-`min_level` S2 cell, sort_key = deeper cell path). That
library isn't available here, so this build uses an equivalent scheme of
its own: interleave the bits of quantized latitude/longitude into a Morton
code; a "cell at level L" is the top 2*L bits. Morton cells share S2's key
property for this workload — nearby points share prefixes — so the same
dual-table layout and covering-scan search work unchanged.

Level semantics: level L splits the world into 2^L x 2^L cells; cell edge
is ~(180/2^L) degrees of latitude (~20000km/2^L at the equator).
"""

import math

EARTH_RADIUS_M = 6371000.9
_BITS = 30  # quantization bits per axis


def _quantize(v: float, lo: float, hi: float) -> int:
    x = (v - lo) / (hi - lo)
    return min((1 << _BITS) - 1, max(0, int(x * (1 << _BITS))))


def _spread(v: int) -> int:
    """Insert a zero bit between every bit of v (30 -> 60 bits)."""
    out = 0
    for i in range(_BITS):
        out |= ((v >> i) & 1) << (2 * i)
    return out


def morton(lat: float, lng: float) -> int:
    """60-bit interleaved cell code, lat bits even, lng bits odd."""
    return _spread(_quantize(lat, -90.0, 90.0)) | (
        _spread(_quantize(lng, -180.0, 180.0)) << 1)


def cell_id(lat: float, lng: float, level: int) -> int:
    """Top 2*level bits of the Morton code: the level-L cell."""
    return morton(lat, lng) >> (2 * (_BITS - level))


def cell_token(cid: int, level: int) -> bytes:
    """Fixed-width printable token for use as a hash_key."""
    width = -(-2 * level // 4)  # hex digits
    return b"%0*x" % (width, cid)


def haversine_m(lat1, lng1, lat2, lng2) -> float:
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lng2 - lng1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(a)))


def cell_bounds(cid: int, level: int):
    """-> (lat_lo, lat_hi, lng_lo, lng_hi) of a level-L cell.

    A Morton cell is a rectangle in quantized (lat, lng) space: at level L
    the cell id carries L lat bits (even positions) and L lng bits (odd)."""
    lat_q = lng_q = 0
    for i in range(level):
        lat_q |= ((cid >> (2 * i)) & 1) << i
        lng_q |= ((cid >> (2 * i + 1)) & 1) << i
    span_lat = 180.0 / (1 << level)
    span_lng = 360.0 / (1 << level)
    lat_lo = -90.0 + lat_q * span_lat
    lng_lo = -180.0 + lng_q * span_lng
    return lat_lo, lat_lo + span_lat, lng_lo, lng_lo + span_lng


def cell_intersects_circle(cid: int, level: int, lat: float, lng: float,
                           radius_m: float) -> bool:
    """True when the cell rectangle and the search circle overlap: the
    haversine distance from the center to the nearest point of the
    rectangle is within the radius (no longitude wraparound — callers
    search city-scale radii)."""
    lat_lo, lat_hi, lng_lo, lng_hi = cell_bounds(cid, level)
    nlat = min(lat_hi, max(lat_lo, lat))
    nlng = min(lng_hi, max(lng_lo, lng))
    return haversine_m(lat, lng, nlat, nlng) <= radius_m


MAX_COVERING_CELLS = 4096


def covering_cells(lat: float, lng: float, radius_m: float, level: int) -> list:
    """Level-L cells covering the search circle's bounding box (the
    gen_search_cap covering role). The sample grid step matches the cell
    edge so no covering cell between samples is skipped, whatever the
    radius/level ratio; the cell count is capped (huge radii should use a
    coarser level, like S2 covering limits). Returns sorted unique ids."""
    dlat = math.degrees(radius_m / EARTH_RADIUS_M)
    coslat = max(0.01, math.cos(math.radians(lat)))
    dlng = math.degrees(radius_m / (EARTH_RADIUS_M * coslat))
    cell_h = 180.0 / (1 << level)   # cell edge in latitude degrees
    cell_w = 360.0 / (1 << level)
    # one sample per cell edge, uncapped — the MAX_COVERING_CELLS early
    # return below bounds the work; capping the STEP spacing instead would
    # silently skip cells between samples
    steps_lat = int(2 * dlat / cell_h) + 2
    steps_lng = int(2 * dlng / cell_w) + 2
    cells = set()
    for i in range(steps_lat + 1):
        for j in range(steps_lng + 1):
            la = min(90.0, max(-90.0, lat - dlat + 2 * dlat * i / steps_lat))
            ln = lng - dlng + 2 * dlng * j / steps_lng
            ln = (ln + 180.0) % 360.0 - 180.0
            cells.add(cell_id(la, ln, level))
            if len(cells) >= MAX_COVERING_CELLS:
                return sorted(cells)
    return sorted(cells)


MAX_RANGES_PER_CELL = 4    # like S2RegionCoverer's max_cells budget
FULL_SCAN_FRACTION = 0.5   # ranges covering most of a cell -> scan it all


def covering_ranges(lat: float, lng: float, radius_m: float, level: int,
                    max_level: int) -> dict:
    """Two-level covering for range-narrowed scans (the reference's
    gen_start_sort_key/gen_stop_sort_key, geo_client.cpp:433-454): cover
    the circle with level-`max_level` cells, then group them under their
    level-`level` ancestors.

    -> {ancestor_cell_id: None | [(start_morton, stop_morton)]}: None means
    the whole ancestor cell intersects (scan it all); otherwise the sorted,
    merged list of full-60-bit Morton ranges (stop exclusive) covering the
    circle inside that cell — everything outside the ranges is provably
    outside the circle and is never read."""
    if max_level <= level:
        return {c: None for c in covering_cells(lat, lng, radius_m, level)}
    shift_m = 2 * (_BITS - max_level)
    rel = 2 * (max_level - level)
    raw = covering_cells(lat, lng, radius_m, max_level)
    # a capped covering at max_level has holes (early return mid-grid);
    # fall back to whole-cell scans at the coarse level rather than
    # silently missing results — the cap must be tested BEFORE the circle
    # filter, which can shrink an incomplete covering back under the cap
    if len(raw) >= MAX_COVERING_CELLS:
        return {c: None for c in covering_cells(lat, lng, radius_m, level)}
    deep = [c for c in raw
            if cell_intersects_circle(c, max_level, lat, lng, radius_m)]
    out = {}
    full = 1 << rel  # descendants per ancestor
    by_anc = {}
    for c in deep:
        by_anc.setdefault(c >> rel, []).append(c)
    for anc, children in by_anc.items():
        # a scan task costs a round trip: nearly-full cells scan whole, and
        # the range count per cell is budgeted by merging the smallest gaps
        # (the role of S2RegionCoverer's max_cells budget)
        if len(children) >= full * FULL_SCAN_FRACTION:
            out[anc] = None
            continue
        ranges = []
        start = prev = children[0]
        for c in children[1:]:
            if c == prev + 1:
                prev = c
                continue
            ranges.append([start, prev + 1])
            start = prev = c
        ranges.append([start, prev + 1])
        while len(ranges) > MAX_RANGES_PER_CELL:
            gaps = [(ranges[i + 1][0] - ranges[i][1], i)
                    for i in range(len(ranges) - 1)]
            _, i = min(gaps)
            ranges[i][1] = ranges[i + 1][1]
            del ranges[i + 1]
        out[anc] = [(s << shift_m, e << shift_m) for s, e in ranges]
    return out
