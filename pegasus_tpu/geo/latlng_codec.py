"""Extract latitude/longitude from '|'-separated value fields.

Mirror of src/geo/lib/latlng_codec.{h,cpp}: geo values carry coordinates in
two configurable indices of a '|'-separated string; the codec pulls them
out (and can patch them back) without understanding the rest of the value.
"""


class LatlngCodec:
    def __init__(self, lat_index: int = 5, lng_index: int = 4):
        self.lat_index = lat_index
        self.lng_index = lng_index

    def decode(self, value: bytes):
        """-> (lat, lng) or None when the fields are absent/invalid."""
        parts = value.split(b"|")
        hi = max(self.lat_index, self.lng_index)
        if len(parts) <= hi:
            return None
        try:
            lat = float(parts[self.lat_index])
            lng = float(parts[self.lng_index])
        except ValueError:
            return None
        if not (-90.0 <= lat <= 90.0 and -180.0 <= lng <= 180.0):
            return None
        return lat, lng

    def encode(self, value: bytes, lat: float, lng: float) -> bytes:
        parts = value.split(b"|")
        hi = max(self.lat_index, self.lng_index)
        while len(parts) <= hi:
            parts.append(b"")
        parts[self.lat_index] = repr(lat).encode()
        parts[self.lng_index] = repr(lng).encode()
        return b"|".join(parts)
