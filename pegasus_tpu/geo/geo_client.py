"""Geo client: dual-table spatial index over the KV store.

Mirror of src/geo/lib/geo_client.{h,cpp} with the Morton cell scheme
(geo/cells.py) in place of S2: every geo point is written twice,
non-atomically like the reference (geo_client.h:83 'two tables, the update
of which is not atomic'):

  common table: (hash_key, sort_key) -> value           (the user's data)
  geo table:    hash_key = level-L cell token,
                sort_key = full-depth morton hex + 4-hex hash_key length
                           + hash_key + sort_key        (deeper cell path;
                the length field makes parsing exact for keys containing
                any byte value, including NUL)
                -> value

Searches cover the circle with level-L cells, hash-scan each cell,
filter by precise haversine distance, and sort/limit (the reference's
cap-covering + parallel scans, geo_client.cpp:257-330).
"""

import threading
from concurrent.futures import ThreadPoolExecutor

from ..client import PegasusClient
from . import cells
from .latlng_codec import LatlngCodec

_MORTON_HEX = 15  # 60-bit morton code as fixed-width hex


def _split_geo_sort_key(gsk: bytes):
    """-> (hash_key, sort_key) or None when malformed."""
    if len(gsk) < _MORTON_HEX + 4:
        return None
    try:
        hk_len = int(gsk[_MORTON_HEX:_MORTON_HEX + 4], 16)
    except ValueError:
        return None
    body = gsk[_MORTON_HEX + 4:]
    if len(body) < hk_len:
        return None
    return body[:hk_len], body[hk_len:]


class GeoClient:
    def __init__(self, common_client: PegasusClient, geo_client: PegasusClient,
                 min_level: int = 12, max_level: int = 16,
                 codec: LatlngCodec = None, scan_threads: int = 8):
        self.common = common_client
        self.geo = geo_client
        self.min_level = min_level
        # searches narrow each covered cell to level-`max_level` sub-ranges
        # of the Morton sort key (the reference's min_level/max_level pair,
        # geo_client.h:83; S2 16 ~= Morton 16 at city scale)
        self.max_level = max_level
        self.codec = codec or LatlngCodec()
        self.scan_threads = scan_threads
        self._pool = None
        self._pool_lock = threading.Lock()

    def _executor(self):
        if self._pool is None:
            with self._pool_lock:
                if self._pool is None:
                    from ..runtime.tasking import tracked_executor

                    self._pool = tracked_executor(
                        self.scan_threads, thread_name_prefix="geo-scan")
        return self._pool

    def close(self) -> None:
        """Shut down the scan pool (the clients are closed by their owner)."""
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- indexing

    def _geo_keys(self, lat: float, lng: float, hash_key: bytes,
                  sort_key: bytes):
        cid = cells.cell_id(lat, lng, self.min_level)
        ghk = cells.cell_token(cid, self.min_level)
        full = b"%015x" % cells.morton(lat, lng)
        if len(hash_key) > 0xFFFF:
            raise ValueError("hash_key too long for the geo index")
        gsk = full + b"%04x" % len(hash_key) + hash_key + sort_key
        return ghk, gsk

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> None:
        """Write data + index (non-atomic pair, like the reference)."""
        latlng = self.codec.decode(value)
        if latlng is None:
            raise ValueError("value carries no decodable lat/lng")
        self.common.set(hash_key, sort_key, value, ttl_seconds)
        ghk, gsk = self._geo_keys(latlng[0], latlng[1], hash_key, sort_key)
        self.geo.set(ghk, gsk, value, ttl_seconds)

    def set_geo_data(self, lat: float, lng: float, hash_key: bytes,
                     sort_key: bytes, value: bytes, ttl_seconds: int = 0):
        """Set with explicit coordinates (patches them into the value)."""
        self.set(hash_key, sort_key,
                 self.codec.encode(value, lat, lng), ttl_seconds)

    def get(self, hash_key: bytes, sort_key: bytes):
        return self.common.get(hash_key, sort_key)

    def delete(self, hash_key: bytes, sort_key: bytes) -> None:
        value = self.common.get(hash_key, sort_key)
        self.common.delete(hash_key, sort_key)
        if value is None:
            return
        latlng = self.codec.decode(value)
        if latlng is not None:
            ghk, gsk = self._geo_keys(latlng[0], latlng[1], hash_key, sort_key)
            self.geo.delete(ghk, gsk)

    # -------------------------------------------------------------- search

    def _scan_one(self, ghk: bytes, start_sk: bytes, stop_sk: bytes,
                  lat: float, lng: float, radius_m: float) -> list:
        out = []
        for _, gsk, value in self.geo.get_scanner(
                ghk, start_sort_key=start_sk, stop_sort_key=stop_sk,
                batch_size=500):
            latlng = self.codec.decode(value)
            if latlng is None:
                continue
            d = cells.haversine_m(lat, lng, latlng[0], latlng[1])
            if d > radius_m:
                continue
            keys = _split_geo_sort_key(gsk)
            if keys is None:
                continue
            out.append((d, keys[0], keys[1], value))
        return out

    def search_radial(self, lat: float, lng: float, radius_m: float,
                      count: int = -1, sort_by_distance: bool = True) -> list:
        """-> [(distance_m, hash_key, sort_key, value)] within the circle.

        Each covered min_level cell is narrowed to the Morton sort-key
        ranges that intersect the circle at max_level (reference
        gen_start/stop_sort_key, geo_client.cpp:433-454), and the range
        scans run concurrently (the reference's parallel cell scans,
        geo_client.cpp:257-330)."""
        tasks = []
        ranges = cells.covering_ranges(lat, lng, radius_m,
                                       self.min_level, self.max_level)
        for cid, spans in sorted(ranges.items()):
            ghk = cells.cell_token(cid, self.min_level)
            if spans is None:
                tasks.append((ghk, b"", b""))
                continue
            for start_m, stop_m in spans:
                stop_sk = (b"" if stop_m >= (1 << 60)
                           else b"%015x" % stop_m)
                tasks.append((ghk, b"%015x" % start_m, stop_sk))
        if len(tasks) > 1 and self.scan_threads > 1:
            chunks = self._executor().map(
                lambda t: self._scan_one(*t, lat, lng, radius_m), tasks)
            out = [r for chunk in chunks for r in chunk]
        else:
            out = [r for t in tasks
                   for r in self._scan_one(*t, lat, lng, radius_m)]
        if sort_by_distance:
            out.sort(key=lambda t: t[0])
        if count > 0:
            out = out[:count]
        return out

    def search_radial_by_key(self, hash_key: bytes, sort_key: bytes,
                             radius_m: float, count: int = -1) -> list:
        value = self.common.get(hash_key, sort_key)
        if value is None:
            return []
        latlng = self.codec.decode(value)
        if latlng is None:
            return []
        return self.search_radial(latlng[0], latlng[1], radius_m, count)

    def distance(self, hk1: bytes, sk1: bytes, hk2: bytes, sk2: bytes):
        """-> meters between two stored points, or None."""
        v1 = self.common.get(hk1, sk1)
        v2 = self.common.get(hk2, sk2)
        if v1 is None or v2 is None:
            return None
        p1, p2 = self.codec.decode(v1), self.codec.decode(v2)
        if p1 is None or p2 is None:
            return None
        return cells.haversine_m(p1[0], p1[1], p2[0], p2[1])
