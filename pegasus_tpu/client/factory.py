"""Client factory: one cached client per (cluster, app).

Mirror of pegasus_client_factory (src/client_lib/client_factory.cpp +
pegasus_client_factory_impl): get_client returns a process-wide singleton
per (meta list, app name), sharing one connection pool.
"""

import threading

from ..rpc.transport import ConnectionPool
from .client import PegasusClient
from .meta_resolver import MetaResolver

_lock = threading.Lock()
_clients = {}
_pool = ConnectionPool()


def get_client(meta_servers, app_name: str) -> PegasusClient:
    """meta_servers: list or comma-separated string of host:port."""
    if isinstance(meta_servers, str):
        meta_servers = [m for m in meta_servers.split(",") if m]
    key = (tuple(meta_servers), app_name)
    with _lock:
        cli = _clients.get(key)
        if cli is None:
            cli = PegasusClient(MetaResolver(list(meta_servers), app_name,
                                             _pool), pool=_pool)
            _clients[key] = cli
        return cli


def close_all() -> None:
    with _lock:
        _clients.clear()
    _pool.close()
