"""Meta-backed partition resolver: query, cache, refresh on reconfiguration.

The partition_resolver role (src/include/rrdb/rrdb.client.h:41-52): the
client asks the meta server for the app's partition table once, caches it,
and re-queries when a call fails with a routing error — which is how the
client survives primary failover transparently.
"""

import os
import threading
import time

from ..meta import messages as mm
from ..meta.meta_server import RPC_CM_QUERY_CONFIG
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError


class MetaResolver:
    def __init__(self, meta_addrs, app_name: str, pool: ConnectionPool = None):
        self.meta_addrs = list(meta_addrs)
        self.app_name = app_name
        self.pool = pool or ConnectionPool()
        self._lock = threading.Lock()
        self._app = None
        self._partitions = None
        self._refresh()

    @property
    def app_id(self) -> int:
        with self._lock:
            return self._app.app_id

    @property
    def partition_count(self) -> int:
        with self._lock:
            return self._app.partition_count

    def refresh(self) -> None:
        self._refresh()

    def secondaries(self, pidx: int) -> list:
        """(host, port) of the partition's secondaries — the backup-request
        targets (reads only; may serve slightly stale data)."""
        with self._lock:
            secs = list(self._partitions[pidx].secondaries)
        out = []
        for s in secs:
            host, _, port = s.rpartition(":")
            out.append((host, int(port)))
        return out

    def resolve(self, pidx: int, refresh: bool = False):
        if refresh:
            self._refresh()
        with self._lock:
            primary = self._partitions[pidx].primary
        if not primary:
            raise RpcError(4, f"partition {pidx} unassigned")
        host, _, port = primary.rpartition(":")
        return (host, int(port))

    def _refresh(self):
        """Query the partition table, trying every meta address over
        PEGASUS_META_RESOLVE_ROUNDS rounds (default 3) with a short
        backoff between rounds. One pass used to be the whole budget, and
        a FRESH connection's first call can transiently exceed its
        timeout when the meta's accept loop lags behind a loaded host
        (the parallel-suite flake: connect() completes inside listen's
        backlog before the server thread ever accept()s, so the request
        sits unread until the timeout). A wedged connection is also
        INVALIDATED before the retry — reusing the half-open socket would
        just time out again and turn one slow accept into a permanent
        'no meta server reachable'."""
        rounds = max(1, int(os.environ.get("PEGASUS_META_RESOLVE_ROUNDS",
                                           "3")))
        last = None
        for attempt in range(rounds):
            if attempt:
                time.sleep(0.05 * attempt)
            for meta in self.meta_addrs:
                host, _, port = meta.rpartition(":")
                addr = (host, int(port))
                try:
                    conn = self.pool.get(addr)
                    _, body = conn.call(RPC_CM_QUERY_CONFIG,
                                        codec.encode(mm.QueryConfigRequest(self.app_name)),
                                        timeout=5.0)
                    resp = codec.decode(mm.QueryConfigResponse, body)
                    if resp.error:
                        raise RpcError(resp.error, resp.error_text)
                    with self._lock:
                        self._app = resp.app
                        self._partitions = resp.partitions
                    return
                except (RpcError, OSError) as e:
                    last = e
                    self.pool.invalidate(addr)
        raise RpcError(7, f"no meta server reachable: {last}")
