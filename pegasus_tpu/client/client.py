"""Pegasus client: hashkey/sortkey API with partition-hash routing.

The pegasus_client surface (src/include/pegasus/client.h:40-380) over this
build's RPC transport: every call encodes (hash_key, sort_key) into a stored
key (base.key_schema), computes partition_hash = pegasus_key_hash(key)
(reference: src/client_lib/pegasus_client_impl.cpp:106), resolves
pidx = hash % partition_count, and calls the partition's serving node.

Partition resolution is pluggable: a StaticResolver pins a fixed
pidx -> address map (onebox tests); the meta-server resolver queries and
caches the routing table and retries once on reconfiguration
(the partition_resolver role, src/include/rrdb/rrdb.client.h:41-52).
"""

import time

from ..base import key_schema
from ..rpc import codec
from ..rpc import messages as msg
from ..rpc.messages import Status
from ..rpc.transport import (ConnectionPool, ERR_BUSY, ERR_INVALID_STATE,
                             ERR_NETWORK_FAILURE, ERR_OBJECT_NOT_FOUND,
                             ERR_TIMEOUT, RpcError)
from ..engine import replica_service as codes
from ..engine.server_impl import (RPC_CHECK_AND_MUTATE, RPC_CHECK_AND_SET,
                                  RPC_INCR, RPC_MULTI_PUT, RPC_MULTI_REMOVE,
                                  RPC_PUT, RPC_REMOVE)


class PegasusError(Exception):
    def __init__(self, status, text=""):
        super().__init__(f"pegasus error {status}: {text}")
        self.status = status


class StaticResolver:
    """Fixed pidx -> (host, port) map (single-node / onebox)."""

    def __init__(self, app_id: int, addresses):
        self.app_id = app_id
        self._addresses = list(addresses)

    @property
    def partition_count(self) -> int:
        return len(self._addresses)

    def refresh(self) -> None:
        pass  # static map: nothing to re-query

    def secondaries(self, pidx: int) -> list:
        return []  # static maps carry no membership info

    def resolve(self, pidx: int, refresh: bool = False):
        return self._addresses[pidx]


_READ_CODES = frozenset({codes.RPC_GET, codes.RPC_MULTI_GET, codes.RPC_TTL,
                         codes.RPC_SORTKEY_COUNT})


class PegasusClient:
    """Synchronous client for one table (app).

    backup_request=True sends failed READS to a secondary before waiting
    on reconfiguration (the reference's backup-request path: lower tail
    latency and availability at the cost of possibly-stale reads; scans
    stay on the primary — their sessions are server-pinned)."""

    def __init__(self, resolver, pool: ConnectionPool = None,
                 timeout: float = 10.0, backup_request: bool = False):
        import threading

        self.resolver = resolver
        self.pool = pool or ConnectionPool()
        self.timeout = timeout
        self.backup_request = backup_request
        self._async_pool = None
        self._async_lock = threading.Lock()

    # ------------------------------------------------------------ internals

    def _route(self, key: bytes):
        h = key_schema.key_hash(key)
        pidx = h % self.resolver.partition_count
        return pidx, h

    def _call(self, code: str, pidx: int, phash: int, req_obj, resp_cls):
        # every client op opens (or joins) a request trace: the context
        # rides the RPC header from here down through replication and the
        # engine (runtime/tracing.py RequestTracer)
        from ..runtime.tracing import REQUEST_TRACER

        with REQUEST_TRACER.root(code):
            return self._call_traced(code, pidx, phash, req_obj, resp_cls)

    def _call_traced(self, code, pidx, phash, req_obj, resp_cls):
        body = codec.encode(req_obj)
        last = None
        for attempt in range(3):
            if attempt > 0:
                try:
                    self.resolver.refresh()
                except (RpcError, OSError):
                    # a transient meta hiccup must not kill a DATA-op
                    # retry: the cached routing is still the best guess,
                    # and the op either succeeds against it or fails with
                    # its own (actionable) error below
                    pass
                if phash:
                    # reconfiguration may have CHANGED the partition count
                    # (split): recompute the route, not just the address
                    pidx = phash % self.resolver.partition_count
            addr = self.resolver.resolve(pidx)
            try:
                # one connection per (node, partition): the partition-group
                # serving node hands sharded connections to the owning
                # group executor, taking the router out of the data path
                conn = self.pool.get(addr, shard=pidx)
                _, rbody = conn.call(code, body, app_id=self.resolver.app_id,
                                     partition_index=pidx, partition_hash=phash,
                                     timeout=self.timeout)
                return codec.decode(resp_cls, rbody) if resp_cls else None
            except OSError as e:  # dead node: connect refused/reset
                last = e
                self.pool.invalidate(addr)
                backup = self._try_backup_read(code, body, pidx, phash, resp_cls)
                if backup is not None:
                    return backup[0]
                continue
            except RpcError as e:
                last = e
                if e.err in (ERR_NETWORK_FAILURE, ERR_TIMEOUT,
                             ERR_OBJECT_NOT_FOUND, ERR_INVALID_STATE):
                    self.pool.invalidate(addr)
                    if e.err in (ERR_NETWORK_FAILURE, ERR_TIMEOUT):
                        backup = self._try_backup_read(code, body, pidx,
                                                       phash, resp_cls)
                        if backup is not None:
                            return backup[0]
                    continue  # re-resolve (reconfiguration / failover)
                if e.err == ERR_BUSY:
                    # throttled (reference PERR_APP_BUSY): the caller decides
                    # whether to back off and retry — no transparent retry
                    raise PegasusError(Status.TRY_AGAIN, str(e))
                raise PegasusError(Status.IO_ERROR, str(e))
        raise PegasusError(Status.TRY_AGAIN, str(last))

    def _try_backup_read(self, code, body, pidx, phash, resp_cls):
        """-> (decoded,) from a secondary, or None. Reads only."""
        if not self.backup_request or code not in _READ_CODES:
            return None
        for addr in self.resolver.secondaries(pidx):
            try:
                conn = self.pool.get(addr, shard=pidx)
                _, rbody = conn.call(code, body, app_id=self.resolver.app_id,
                                     partition_index=pidx, partition_hash=phash,
                                     timeout=self.timeout)
                return (codec.decode(resp_cls, rbody) if resp_cls else None,)
            except (RpcError, OSError):
                self.pool.invalidate(addr)
                continue
        return None

    def _key_call(self, code, hash_key, sort_key, resp_cls):
        key = key_schema.generate_key(hash_key, sort_key)
        pidx, h = self._route(key)
        return self._call(code, pidx, h, msg.KeyRequest(key), resp_cls)

    def _hash_call(self, code, hash_key, req_obj, resp_cls):
        key = key_schema.generate_key(hash_key, b"")
        pidx, h = self._route(key)
        return self._call(code, pidx, h, req_obj, resp_cls)

    @staticmethod
    def _ok(resp, *accept):
        if resp.error not in (Status.OK, *accept):
            raise PegasusError(resp.error)
        return resp

    # ------------------------------------------------------------- data ops

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> None:
        key = key_schema.generate_key(hash_key, sort_key)
        pidx, h = self._route(key)
        expire = key_schema.expire_ts_from_ttl(ttl_seconds)
        resp = self._call(RPC_PUT, pidx, h,
                          msg.UpdateRequest(key, value, expire),
                          msg.UpdateResponse)
        self._ok(resp)

    def get(self, hash_key: bytes, sort_key: bytes):
        """-> value bytes or None when absent."""
        resp = self._key_call(codes.RPC_GET, hash_key, sort_key, msg.ReadResponse)
        if resp.error == Status.NOT_FOUND:
            return None
        self._ok(resp)
        return resp.value

    def exist(self, hash_key: bytes, sort_key: bytes) -> bool:
        return self.get(hash_key, sort_key) is not None

    def delete(self, hash_key: bytes, sort_key: bytes) -> None:
        resp = self._key_call(RPC_REMOVE, hash_key, sort_key, msg.UpdateResponse)
        self._ok(resp)

    # del is reserved; keep the reference's name too
    def del_(self, hash_key: bytes, sort_key: bytes) -> None:
        self.delete(hash_key, sort_key)

    def ttl(self, hash_key: bytes, sort_key: bytes):
        """-> remaining seconds, -1 if no ttl, None if absent."""
        resp = self._key_call(codes.RPC_TTL, hash_key, sort_key, msg.TTLResponse)
        if resp.error == Status.NOT_FOUND:
            return None
        self._ok(resp)
        return resp.ttl_seconds

    def incr(self, hash_key: bytes, sort_key: bytes, increment: int,
             ttl_seconds: int = 0) -> int:
        key = key_schema.generate_key(hash_key, sort_key)
        pidx, h = self._route(key)
        expire = (key_schema.expire_ts_from_ttl(ttl_seconds)
                  if ttl_seconds > 0 else ttl_seconds)
        resp = self._call(RPC_INCR, pidx, h,
                          msg.IncrRequest(key, increment, expire),
                          msg.IncrResponse)
        self._ok(resp)
        return resp.new_value

    def batch_get(self, items, timeout: float = None):
        """Multi-partition point-read fan-out: items is [(hash_key,
        sort_key), ...] -> [value | None, ...] in order.

        Keys group by their (node, partition) connection and each group's
        requests leave as ONE pipelined call_many wave — send phase first
        across every connection, then collect, so k partitions' worth of
        server work runs concurrently and each direction costs one
        syscall per partition instead of one per key. A failed wave falls
        back to the per-key retrying path for just its keys."""
        out = [None] * len(items)
        groups = {}   # (addr, pidx) -> [(i, body, phash)]
        for i, (hk, sk) in enumerate(items):
            key = key_schema.generate_key(hk, sk)
            pidx, h = self._route(key)
            addr = tuple(self.resolver.resolve(pidx))
            groups.setdefault((addr, pidx), []).append(
                (i, codec.encode(msg.KeyRequest(key)), h))
        pends = []
        for (addr, pidx), entries in groups.items():
            calls = [(codes.RPC_GET, body, self.resolver.app_id, pidx, h)
                     for _, body, h in entries]
            try:
                conn = self.pool.get(addr, shard=pidx)
                pends.append((conn, calls, entries,
                              conn.call_many_send(calls)))
            except (RpcError, OSError):
                pends.append((None, calls, entries, None))
        for conn, calls, entries, handle in pends:
            results = None
            if handle is not None:
                try:
                    results = conn.call_many_collect(
                        handle, calls, timeout or self.timeout)
                except (RpcError, OSError):
                    results = None
            if results is None:   # wave failed: per-key retrying fallback
                for i, _, _ in entries:
                    hk, sk = items[i]
                    out[i] = self.get(hk, sk)
                continue
            for (i, _, _), (_, rbody) in zip(entries, results):
                resp = codec.decode(msg.ReadResponse, rbody)
                if resp.error == Status.NOT_FOUND:
                    out[i] = None
                elif resp.error != Status.OK:
                    raise PegasusError(resp.error)
                else:
                    out[i] = resp.value
        return out

    def multi_set(self, hash_key: bytes, kvs: dict, ttl_seconds: int = 0) -> None:
        req = msg.MultiPutRequest(
            hash_key,
            [msg.KeyValue(sk, v) for sk, v in kvs.items()],
            key_schema.expire_ts_from_ttl(ttl_seconds),
        )
        resp = self._hash_call(RPC_MULTI_PUT, hash_key, req, msg.UpdateResponse)
        self._ok(resp)

    def multi_get(self, hash_key: bytes, sort_keys=None, max_kv_count: int = 0,
                  max_kv_size: int = 0, **range_opts):
        """-> (complete, {sort_key: value}). With sort_keys=None fetches the
        (optionally bounded) range under hash_key."""
        req = msg.MultiGetRequest(hash_key, list(sort_keys or []),
                                  max_kv_count, max_kv_size, **range_opts)
        resp = self._hash_call(codes.RPC_MULTI_GET, hash_key, req,
                               msg.MultiGetResponse)
        self._ok(resp, Status.INCOMPLETE)
        return resp.error == Status.OK, {kv.key: kv.value for kv in resp.kvs}

    def multi_del(self, hash_key: bytes, sort_keys) -> int:
        req = msg.MultiRemoveRequest(hash_key, list(sort_keys))
        resp = self._hash_call(RPC_MULTI_REMOVE, hash_key, req,
                               msg.MultiRemoveResponse)
        self._ok(resp)
        return resp.count

    def sortkey_count(self, hash_key: bytes) -> int:
        key = key_schema.generate_key(hash_key, b"")
        pidx, h = self._route(key)
        resp = self._call(codes.RPC_SORTKEY_COUNT, pidx, h,
                          msg.KeyRequest(hash_key), msg.CountResponse)
        self._ok(resp, Status.INCOMPLETE)
        return resp.count

    def check_and_set(self, hash_key: bytes, check_sort_key: bytes,
                      check_type: int, check_operand: bytes,
                      set_sort_key: bytes, set_value: bytes,
                      set_ttl_seconds: int = 0, return_check_value: bool = False):
        req = msg.CheckAndSetRequest(
            hash_key, check_sort_key, check_type, check_operand,
            set_diff_sort_key=set_sort_key != check_sort_key,
            set_sort_key=set_sort_key, set_value=set_value,
            set_expire_ts_seconds=key_schema.expire_ts_from_ttl(set_ttl_seconds),
            return_check_value=return_check_value)
        resp = self._hash_call(RPC_CHECK_AND_SET, hash_key, req,
                               msg.CheckAndSetResponse)
        if resp.error not in (Status.OK, Status.TRY_AGAIN):
            raise PegasusError(resp.error)
        return resp

    def check_and_mutate(self, hash_key: bytes, check_sort_key: bytes,
                         check_type: int, check_operand: bytes,
                         mutations, return_check_value: bool = False):
        """mutations: list of ("set", sort_key, value, ttl) | ("del", sort_key)."""
        ml = []
        for m in mutations:
            if m[0] == "set":
                _, sk, v, ttl = m
                ml.append(msg.Mutate(msg.MutateOperation.PUT, sk, v,
                                     key_schema.expire_ts_from_ttl(ttl)))
            else:
                ml.append(msg.Mutate(msg.MutateOperation.DELETE, m[1]))
        req = msg.CheckAndMutateRequest(hash_key, check_sort_key, check_type,
                                        check_operand, ml, return_check_value)
        resp = self._hash_call(RPC_CHECK_AND_MUTATE, hash_key, req,
                               msg.CheckAndMutateResponse)
        if resp.error not in (Status.OK, Status.TRY_AGAIN):
            raise PegasusError(resp.error)
        return resp

    # --------------------------------------------------------------- scans

    def get_scanner(self, hash_key: bytes = b"", start_sort_key: bytes = b"",
                    stop_sort_key: bytes = b"", batch_size: int = 1000,
                    **opts):
        """Scanner over one hash_key's range (hash scanner). For a full-table
        scan use get_unordered_scanners."""
        if hash_key:
            start = key_schema.generate_key(hash_key, start_sort_key)
            stop = (key_schema.generate_key(hash_key, stop_sort_key)
                    if stop_sort_key else key_schema.generate_next_bytes(hash_key))
            pidx, h = self._route(start)
            return Scanner(self, [pidx], start, stop, batch_size, phash=h, **opts)
        return Scanner(self, list(range(self.resolver.partition_count)),
                       b"", b"", batch_size, **opts)

    def get_unordered_scanners(self, max_split_count: int = 0,
                               batch_size: int = 1000,
                               prefetch: bool = True):
        """One scanner per partition group (full-table scan, reference
        client.h:322-380). prefetch=True (default) opens every
        partition's scan session up front as a batched fan-out: all the
        get_scanner requests leave before any response is awaited
        (call_many send/collect split), so the partitions build their
        first batches concurrently instead of serially on first use —
        and every scanner keeps pipelining its CONTINUATION batches the
        same way (Scanner prefetch: the next RPC_SCAN is on the wire
        while the current batch drains). A failed prefetch degrades that
        scanner to lazy fetching."""
        n = self.resolver.partition_count
        scanners = [Scanner(self, [p], b"", b"", batch_size,
                            prefetch=prefetch)
                    for p in range(n)]
        if not prefetch:
            return scanners
        pends = []
        for sc in scanners:
            pidx = sc.pidxs[0]
            req = msg.GetScannerRequest(batch_size=batch_size,
                                        validate_partition_hash=False)
            calls = [(codes.RPC_GET_SCANNER, codec.encode(req),
                      self.resolver.app_id, pidx, 0)]
            try:
                conn = self.pool.get(self.resolver.resolve(pidx),
                                     shard=pidx)
                pends.append((sc, conn, calls, conn.call_many_send(calls)))
            except (RpcError, OSError):
                continue
        for sc, conn, calls, handle in pends:
            try:
                (_, rbody), = conn.call_many_collect(handle, calls,
                                                     self.timeout)
                resp = codec.decode(msg.ScanResponse, rbody)
            except (RpcError, OSError):
                continue
            if resp.error == Status.OK:
                sc._preload(resp)
        return scanners

    # -------------------------------------------------------------- async
    # The reference API is half async_* callbacks over its rDSN task pool
    # (client.h:283-320 + async_get/async_set/... declarations). The
    # tpu-native redesign returns concurrent.futures.Future from a shared
    # executor — awaitable/composable — and still accepts the reference's
    # callback idiom: callback(error_code, result), error_code 0 on
    # success, the PegasusError status otherwise. The RPC transport is
    # pipelined + thread-safe, so concurrent futures share connections.

    _MAX_ASYNC_WORKERS = 8

    def _executor(self):
        from ..runtime.tasking import tracked_executor

        if self._async_pool is None:
            with self._async_lock:
                if self._async_pool is None:
                    self._async_pool = tracked_executor(
                        self._MAX_ASYNC_WORKERS,
                        thread_name_prefix="pegasus-async")
        return self._async_pool

    def _submit(self, fn, callback, *args, **kwargs):
        future = self._executor().submit(fn, *args, **kwargs)
        if callback is not None:
            def _done(f):
                err = f.exception()
                if err is None:
                    callback(0, f.result())
                elif isinstance(err, PegasusError):
                    callback(err.status, None)
                else:
                    callback(-1, None)

            future.add_done_callback(_done)
        return future

    def async_set(self, hash_key, sort_key, value, ttl_seconds=0,
                  callback=None):
        return self._submit(self.set, callback, hash_key, sort_key, value,
                            ttl_seconds)

    def async_get(self, hash_key, sort_key, callback=None):
        return self._submit(self.get, callback, hash_key, sort_key)

    def async_del(self, hash_key, sort_key, callback=None):
        return self._submit(self.delete, callback, hash_key, sort_key)

    def async_multi_set(self, hash_key, kvs, ttl_seconds=0, callback=None):
        return self._submit(self.multi_set, callback, hash_key, kvs,
                            ttl_seconds)

    def async_multi_get(self, hash_key, sort_keys=None, max_kv_count=0,
                        max_kv_size=0, callback=None):
        return self._submit(self.multi_get, callback, hash_key, sort_keys,
                            max_kv_count, max_kv_size)

    def async_multi_del(self, hash_key, sort_keys, callback=None):
        return self._submit(self.multi_del, callback, hash_key, sort_keys)

    def async_incr(self, hash_key, sort_key, increment, ttl_seconds=0,
                   callback=None):
        return self._submit(self.incr, callback, hash_key, sort_key,
                            increment, ttl_seconds)

    def async_check_and_set(self, hash_key, check_sort_key, check_type,
                            check_operand, set_sort_key, set_value,
                            ttl_seconds=0, return_check_value=False,
                            callback=None):
        return self._submit(self.check_and_set, callback, hash_key,
                            check_sort_key, check_type, check_operand,
                            set_sort_key, set_value, ttl_seconds,
                            return_check_value)

    def async_check_and_mutate(self, hash_key, check_sort_key, check_type,
                               check_operand, mutations,
                               return_check_value=False, callback=None):
        return self._submit(self.check_and_mutate, callback, hash_key,
                            check_sort_key, check_type, check_operand,
                            mutations, return_check_value)

    def async_sortkey_count(self, hash_key, callback=None):
        return self._submit(self.sortkey_count, callback, hash_key)

    def close(self):
        if self._async_pool is not None:
            self._async_pool.shutdown(wait=True)
            self._async_pool = None
        self.pool.close()


class Scanner:
    """Iterates (hash_key, sort_key, value) across partitions sequentially
    (reference pegasus_scanner_impl walks partitions in order).

    prefetch=True pipelines continuation batches: as soon as a batch with
    a live server session is absorbed, the next RPC_SCAN leaves on the
    wire (call_many send/collect split) and is collected when iteration
    drains the current batch — the server builds batch N+1 (one
    device-served range dispatch per batch) while the client consumes
    batch N. A failed prefetch degrades that fetch to the retrying lazy
    path, so semantics are unchanged."""

    def __init__(self, client: PegasusClient, pidxs, start_key, stop_key,
                 batch_size, phash: int = 0, **opts):
        self.client = client
        self.pidxs = list(pidxs)
        self.start_key = start_key
        self.stop_key = stop_key
        self.batch_size = batch_size
        self.phash = phash
        self._prefetch = bool(opts.pop("prefetch", False))
        self.opts = opts
        self._cur = 0
        self._ctx = None
        self._batch = []
        self._bi = 0
        self._done = False
        self._pending = None  # in-flight continuation (conn, calls, handle,
        #                       pidx, ctx) — collected by the next _fetch

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._bi < len(self._batch):
                kv = self._batch[self._bi]
                self._bi += 1
                hk, sk = key_schema.restore_key(kv.key)
                return hk, sk, kv.value
            if self._done:
                raise StopIteration
            self._fetch()

    def _fetch(self):
        from ..base import consts

        if self._cur >= len(self.pidxs):
            self._done = True
            return
        pidx = self.pidxs[self._cur]
        if self._collect_prefetch(pidx):
            return
        if self._ctx is None:
            req = msg.GetScannerRequest(
                start_key=self.start_key, stop_key=self.stop_key,
                batch_size=self.batch_size,
                validate_partition_hash=False, **self.opts)
            resp = self.client._call(codes.RPC_GET_SCANNER, pidx, self.phash,
                                     req, msg.ScanResponse)
        else:
            resp = self.client._call(codes.RPC_SCAN, pidx, self.phash,
                                     msg.ScanRequest(self._ctx), msg.ScanResponse)
        if resp.error not in (Status.OK,):
            raise PegasusError(resp.error)
        self._absorb(resp)

    def _absorb(self, resp):
        from ..base import consts

        self._batch = resp.kvs
        self._bi = 0
        if resp.context_id == consts.SCAN_CONTEXT_ID_COMPLETED:
            self._ctx = None
            self._cur += 1
        else:
            # an EMPTY batch can still be incomplete: the server's range
            # limiter may spend its whole budget on filtered-out rows —
            # keep the session and fetch again
            self._ctx = resp.context_id
            if self._prefetch:
                self._send_prefetch()

    def _send_prefetch(self):
        """Fire the next RPC_SCAN for the live session without waiting
        (best effort: any failure just leaves the lazy path to do the
        fetch with its full retry machinery)."""
        pidx = self.pidxs[self._cur]
        calls = [(codes.RPC_SCAN, codec.encode(msg.ScanRequest(self._ctx)),
                  self.client.resolver.app_id, pidx, self.phash)]
        try:
            conn = self.client.pool.get(self.client.resolver.resolve(pidx),
                                        shard=pidx)
            self._pending = (conn, calls, conn.call_many_send(calls),
                             pidx, self._ctx)
        except (RpcError, OSError):
            self._pending = None

    def _collect_prefetch(self, pidx) -> bool:
        """Absorb an in-flight prefetched batch. -> True when it served
        this fetch; False degrades to the lazy path (stale target after a
        partition transition, send/collect failure, server-side error)."""
        if self._pending is None:
            return False
        conn, calls, handle, ppidx, pctx = self._pending
        self._pending = None
        if ppidx != pidx or pctx != self._ctx:
            return False
        try:
            (_, rbody), = conn.call_many_collect(handle, calls,
                                                 self.client.timeout)
            resp = codec.decode(msg.ScanResponse, rbody)
        except (RpcError, OSError):
            return False
        if resp.error != Status.OK:
            return False
        self._absorb(resp)
        return True

    def _preload(self, resp):
        """Absorb a fan-out-prefetched first batch (get_unordered_scanners
        opened this partition's session before iteration started)."""
        if self._cur == 0 and self._ctx is None and not self._batch:
            self._absorb(resp)

    def close(self):
        if self._ctx is not None and self._cur < len(self.pidxs):
            try:
                self.client._call(codes.RPC_CLEAR_SCANNER, self.pidxs[self._cur],
                                  self.phash, msg.ScanRequest(self._ctx), None)
            except (PegasusError, RpcError):
                pass
            self._ctx = None
