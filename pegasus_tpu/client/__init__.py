from .client import PegasusClient, PegasusError, Scanner, StaticResolver

__all__ = ["PegasusClient", "PegasusError", "Scanner", "StaticResolver"]
from .meta_resolver import MetaResolver  # noqa: E402

__all__.append("MetaResolver")
from .factory import close_all, get_client  # noqa: E402

__all__ += ["get_client", "close_all"]
