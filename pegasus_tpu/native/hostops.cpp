// Native host-runtime kernels for the TPU-native KV engine.
//
// The reference implements its entire runtime in C++; this build keeps the
// device compute in JAX/XLA and implements the host runtime's hot loops
// here: CRC-64 partition hashing (reference consumes dsn::utils::crc64_calc,
// src/base/pegasus_key_schema.h:162), variable-length arena gather (the
// output-SST materialization step of every flush/compaction), sorted-run
// merge ranking, and big-endian prefix packing for the device sort columns.
//
// Built as a plain shared library (no pybind11 in the image); the Python
// side binds with ctypes (pegasus_tpu/native/__init__.py) and falls back to
// the numpy implementations when the toolchain is unavailable.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libhostops.so hostops.cpp

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------- crc64

// CRC-64/XZ (reflected 0xC96C5795D7870F42), matching base/crc64.py.
static uint64_t CRC_TABLE[8][256];
static bool crc_init_done = false;

static void crc_init() {
    const uint64_t poly = 0xC96C5795D7870F42ULL;
    for (int i = 0; i < 256; i++) {
        uint64_t crc = (uint64_t)i;
        for (int k = 0; k < 8; k++)
            crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
        CRC_TABLE[0][i] = crc;
    }
    // slice-by-8 tables
    for (int t = 1; t < 8; t++)
        for (int i = 0; i < 256; i++)
            CRC_TABLE[t][i] = CRC_TABLE[0][CRC_TABLE[t - 1][i] & 0xFF] ^
                              (CRC_TABLE[t - 1][i] >> 8);
    crc_init_done = true;
}

static inline uint64_t crc64_one(const uint8_t* p, int64_t len, uint64_t crc) {
    crc = ~crc;
    while (len >= 8) {
        uint64_t w;
        memcpy(&w, p, 8);
        crc ^= w;
        crc = CRC_TABLE[7][crc & 0xFF] ^ CRC_TABLE[6][(crc >> 8) & 0xFF] ^
              CRC_TABLE[5][(crc >> 16) & 0xFF] ^ CRC_TABLE[4][(crc >> 24) & 0xFF] ^
              CRC_TABLE[3][(crc >> 32) & 0xFF] ^ CRC_TABLE[2][(crc >> 40) & 0xFF] ^
              CRC_TABLE[1][(crc >> 48) & 0xFF] ^ CRC_TABLE[0][crc >> 56];
        p += 8;
        len -= 8;
    }
    while (len-- > 0)
        crc = CRC_TABLE[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

// out[i] = crc64 of arena[offsets[i] .. offsets[i]+lengths[i])
void crc64_batch(const uint8_t* arena, const int64_t* offsets,
                 const int64_t* lengths, int64_t n, uint64_t* out) {
    if (!crc_init_done) crc_init();
    for (int64_t i = 0; i < n; i++)
        out[i] = crc64_one(arena + offsets[i], lengths[i], 0);
}

// ---------------------------------------------------------- arena gather

// Compact the variable-length slices idx[0..nidx) of (arena, off, len32)
// into out (caller sized it as sum of selected lengths); writes the new
// offsets as it goes. Single pass of memcpy — the materialization step of
// every compaction output block.
void gather_arena(const uint8_t* arena, const int64_t* off,
                  const int32_t* len32, const int64_t* idx, int64_t nidx,
                  uint8_t* out, int64_t* out_off) {
    int64_t pos = 0;
    for (int64_t i = 0; i < nidx; i++) {
        int64_t j = idx[i];
        int64_t l = (int64_t)len32[j];
        out_off[i] = pos;
        memcpy(out + pos, arena + off[j], (size_t)l);
        pos += l;
    }
}

// ------------------------------------------------------- prefix packing

// Big-endian pack of each record's first 4*w key bytes into w uint32 lanes
// (zero padded), column-major output: out[col * n + i]. Mirrors
// ops/packing.pack_key_prefixes.
void pack_prefixes(const uint8_t* arena, const int64_t* off,
                   const int32_t* len32, int64_t n, int32_t w,
                   uint32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        const uint8_t* p = arena + off[i];
        int64_t len = (int64_t)len32[i];
        for (int32_t c = 0; c < w; c++) {
            uint32_t v = 0;
            int64_t base = (int64_t)c * 4;
            for (int b = 0; b < 4; b++) {
                int64_t k = base + b;
                uint32_t byte = (k < len) ? p[k] : 0;
                v = (v << 8) | byte;
            }
            out[(int64_t)c * n + i] = v;
        }
    }
}

// ------------------------------------------------ fused uniform gather

// Materialize a compaction output block from uniform-width records in ONE
// pass over the survivor index: keys (klen bytes each), values (vlen),
// expire/hash32 (u32) and deleted (u8) move together, so idx is read once
// and the random-access source rows are software-prefetched ahead of use.
// The separate-pass form (5 fancy-index sweeps) was measured 2.0-2.9s at
// 8.5M survivors on the 1-core dev host — DRAM-latency-bound on the
// dependent row loads; prefetching + fusion cuts most of the stalls.
void gather_block_uniform(const uint8_t* key_arena, int64_t klen,
                          const uint8_t* val_arena, int64_t vlen,
                          const uint32_t* expire, const uint32_t* hash32,
                          const uint8_t* deleted, const int32_t* idx,
                          int64_t n, uint8_t* out_keys, uint8_t* out_vals,
                          uint32_t* out_expire, uint32_t* out_hash32,
                          uint8_t* out_deleted) {
    const int64_t AHEAD = 24;
    for (int64_t i = 0; i < n; i++) {
        if (i + AHEAD < n) {
            int64_t ja = (int64_t)idx[i + AHEAD];
            __builtin_prefetch(key_arena + ja * klen, 0, 0);
            __builtin_prefetch(val_arena + ja * vlen, 0, 0);
            // values can span multiple lines; touch the middle + tail too
            if (vlen > 64)
                __builtin_prefetch(val_arena + ja * vlen + 64, 0, 0);
            if (vlen > 128)
                __builtin_prefetch(val_arena + ja * vlen + vlen - 1, 0, 0);
            __builtin_prefetch(expire + ja, 0, 0);
            __builtin_prefetch(hash32 + ja, 0, 0);
            __builtin_prefetch(deleted + ja, 0, 0);
        }
        int64_t j = (int64_t)idx[i];
        memcpy(out_keys + i * klen, key_arena + j * klen, (size_t)klen);
        memcpy(out_vals + i * vlen, val_arena + j * vlen, (size_t)vlen);
        out_expire[i] = expire[j];
        out_hash32[i] = hash32[j];
        out_deleted[i] = deleted[j];
    }
}

// Keys-and-aux-only variant: the device-value-residency materialization
// (ops/compact.py materialize_device_survivors) downloads value rows from
// HBM while the host gathers only keys + fixed-width aux — the two halves
// overlap, so this loop must not touch the value arena at all.
void gather_keys_uniform(const uint8_t* key_arena, int64_t klen,
                         const uint32_t* expire, const uint32_t* hash32,
                         const uint8_t* deleted, const int32_t* idx,
                         int64_t n, uint8_t* out_keys, uint32_t* out_expire,
                         uint32_t* out_hash32, uint8_t* out_deleted) {
    const int64_t AHEAD = 32;
    for (int64_t i = 0; i < n; i++) {
        if (i + AHEAD < n) {
            int64_t ja = (int64_t)idx[i + AHEAD];
            __builtin_prefetch(key_arena + ja * klen, 0, 0);
            __builtin_prefetch(expire + ja, 0, 0);
            __builtin_prefetch(hash32 + ja, 0, 0);
            __builtin_prefetch(deleted + ja, 0, 0);
        }
        int64_t j = (int64_t)idx[i];
        memcpy(out_keys + i * klen, key_arena + j * klen, (size_t)klen);
        out_expire[i] = expire[j];
        out_hash32[i] = hash32[j];
        out_deleted[i] = deleted[j];
    }
}

// ----------------------------------------------------- sorted-run merge

// Count, for each record of run A (fixed-width keys, itemsize bytes,
// memcmp order), how many records of run B are smaller (side=0, "left") or
// smaller-or-equal (side=1, "right"). Both runs ascending. Galloping two-
// pointer pass: O(na + nb) memcmps instead of numpy's O(na log nb) searches.
void merge_counts(const uint8_t* a, int64_t na, const uint8_t* b, int64_t nb,
                  int64_t itemsize, int32_t side, int64_t* out) {
    int64_t j = 0;
    for (int64_t i = 0; i < na; i++) {
        const uint8_t* ka = a + i * itemsize;
        if (side == 0) {
            while (j < nb && memcmp(b + j * itemsize, ka, (size_t)itemsize) < 0)
                j++;
        } else {
            while (j < nb && memcmp(b + j * itemsize, ka, (size_t)itemsize) <= 0)
                j++;
        }
        out[i] = j;
    }
}

}  // extern "C"
