"""ctypes bindings for the native host-ops library (hostops.cpp).

Compiles the shared library on first use with the in-image g++ (no pip, no
pybind11 — plain `extern "C"` + ctypes, the SURVEY §2 requirement that
runtime hot paths be native like the reference's C++). Every binding has a
numpy fallback; `available()` reports whether the native path is active.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "hostops.cpp")
_SO = os.path.join(_DIR, "libhostops.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        try:
            _bind(lib)
        except AttributeError:
            # a stale prebuilt .so that predates a symbol (mtime passed on
            # clock skew / shipped artifact): rebuild once, else degrade to
            # the numpy fallbacks instead of crashing available(). The
            # rebuilt library must load from a UNIQUE path — dlopen dedupes
            # by pathname, so re-CDLLing _SO would return the stale handle
            if not _build():
                return None
            import shutil
            import tempfile

            tmp = tempfile.NamedTemporaryFile(prefix="libhostops_",
                                              suffix=".so", delete=False)
            tmp.close()
            try:
                shutil.copy(_SO, tmp.name)
                lib = ctypes.CDLL(tmp.name)
                _bind(lib)
            except (OSError, AttributeError):
                return None
        _lib = lib
        return _lib


def _bind(lib) -> None:
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C")
    lib.crc64_batch.argtypes = [u8p, i64p, i64p, ctypes.c_int64, u64p]
    lib.gather_arena.argtypes = [u8p, i64p, i32p, i64p, ctypes.c_int64,
                                 u8p, i64p]
    lib.pack_prefixes.argtypes = [u8p, i64p, i32p, ctypes.c_int64,
                                  ctypes.c_int32, u32p]
    lib.merge_counts.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64,
                                 ctypes.c_int64, ctypes.c_int32, i64p]
    boolp = np.ctypeslib.ndpointer(np.bool_, flags="C")
    lib.gather_block_uniform.argtypes = [
        u8p, ctypes.c_int64, u8p, ctypes.c_int64, u32p, u32p, boolp,
        i32p, ctypes.c_int64, u8p, u8p, u32p, u32p, boolp]
    lib.gather_keys_uniform.argtypes = [
        u8p, ctypes.c_int64, u32p, u32p, boolp,
        i32p, ctypes.c_int64, u8p, u32p, u32p, boolp]


def available() -> bool:
    return _load() is not None


def native_on() -> bool:
    """The one knob for the native read data plane (ISSUE 20):
    ``PEGASUS_NATIVE=0`` forces the byte-identical pure-Python twins for
    frame dispatch, vectored reply writes, and mmap SST reads. Read live
    per call (not cached) so a test or bench A/B can flip it in-process
    between connections."""
    return os.environ.get("PEGASUS_NATIVE", "1") != "0"


# ------------------------------------------------------------- fastcodec
# The RPC wire codec's C interpreter (fastcodec.c): a true CPython
# extension (needs Python.h, unlike hostops' plain ctypes), compiled on
# first use and imported from its file path. rpc.codec falls back to the
# pure-Python closures when this returns None.

_FC_SRC = os.path.join(_DIR, "fastcodec.c")
_fc_lock = threading.Lock()
_fc_mod = None
_fc_tried = False


def fastcodec():
    """-> the compiled fastcodec extension module, or None."""
    global _fc_mod, _fc_tried
    with _fc_lock:
        if _fc_tried:
            return _fc_mod
        _fc_tried = True
        import sysconfig

        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        so = os.path.join(_DIR, "fastcodec" + suffix)

        def try_load(path):
            try:
                import importlib.util

                spec = importlib.util.spec_from_file_location("fastcodec",
                                                              path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                return mod
            except Exception:  # noqa: BLE001 - load failure -> rebuild/None
                return None

        mod = None
        try:
            fresh = (os.path.exists(so)
                     and os.path.getmtime(so) >= os.path.getmtime(_FC_SRC))
        except OSError:  # e.g. source missing but artifact present:
            fresh = os.path.exists(so)  # trust the artifact, else None
        if fresh:
            mod = try_load(so)
        if mod is None and not os.path.exists(_FC_SRC):
            return None  # nothing to build from
        if mod is None:
            # build to a per-process tmp then atomically replace: several
            # server processes may race the first build, and gcc writing
            # the final path directly could leave a corrupt (and
            # fresher-than-source, so never rebuilt) artifact
            tmp = f"{so}.{os.getpid()}.tmp"
            inc = sysconfig.get_paths()["include"]
            cmd = ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}",
                   "-o", tmp, _FC_SRC]
            try:
                res = subprocess.run(cmd, capture_output=True, timeout=120)
                if res.returncode != 0:
                    return None
                os.replace(tmp, so)
            except (OSError, subprocess.TimeoutExpired):
                return None
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            mod = try_load(so)
        _fc_mod = mod
        return _fc_mod


def crc64_batch(arena, offsets, lengths):
    """uint64[n] crc64 of each slice; native slice-by-8 when available."""
    lib = _load()
    n = len(offsets)
    if lib is None or n == 0:
        from ..base.crc64 import crc64_batch_numpy

        return crc64_batch_numpy(arena, offsets, lengths)
    out = np.empty(n, np.uint64)
    lib.crc64_batch(np.ascontiguousarray(arena, np.uint8),
                    np.ascontiguousarray(offsets, np.int64),
                    np.ascontiguousarray(lengths, np.int64), n, out)
    return out


def gather_arena(arena, off, len32, idx):
    """-> (out_arena, out_off) compacted selection; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    idx = np.ascontiguousarray(idx, np.int64)
    len32 = np.ascontiguousarray(len32, np.int32)
    total = int(len32[idx].astype(np.int64).sum())
    out = np.empty(total, np.uint8)
    out_off = np.empty(len(idx), np.int64)
    lib.gather_arena(np.ascontiguousarray(arena, np.uint8),
                     np.ascontiguousarray(off, np.int64),
                     len32, idx, len(idx), out, out_off)
    return out, out_off


def pack_prefixes(arena, off, len32, w):
    """-> uint32[n, w] big-endian packed prefixes; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(off)
    out = np.empty((w, n), np.uint32)
    lib.pack_prefixes(np.ascontiguousarray(arena, np.uint8),
                      np.ascontiguousarray(off, np.int64),
                      np.ascontiguousarray(len32, np.int32), n, w,
                      out.reshape(-1))
    return out.T


def gather_block_uniform(key_arena, klen, val_arena, vlen, expire, hash32,
                         deleted, idx, out_keys, out_vals, out_expire,
                         out_hash32, out_deleted) -> bool:
    """Fused one-pass gather of a uniform-record block into preallocated
    outputs (keys, values, expire, hash32, deleted) with source-row
    prefetching. idx is int32. Returns False if the library is absent
    (caller falls back to per-array fancy indexing)."""
    lib = _load()
    if lib is None:
        return False
    lib.gather_block_uniform(
        np.ascontiguousarray(key_arena, np.uint8), int(klen),
        np.ascontiguousarray(val_arena, np.uint8), int(vlen),
        np.ascontiguousarray(expire, np.uint32),
        np.ascontiguousarray(hash32, np.uint32),
        np.ascontiguousarray(deleted, np.bool_),
        np.ascontiguousarray(idx, np.int32), len(idx),
        out_keys, out_vals, out_expire, out_hash32, out_deleted)
    return True


def gather_keys_uniform(key_arena, klen, expire, hash32, deleted, idx,
                        out_keys, out_expire, out_hash32,
                        out_deleted) -> bool:
    """Keys+aux half of the uniform gather (no values — they come off the
    device in the value-residency path). Returns False if the library is
    absent (caller falls back to fancy indexing)."""
    lib = _load()
    if lib is None:
        return False
    lib.gather_keys_uniform(
        np.ascontiguousarray(key_arena, np.uint8), int(klen),
        np.ascontiguousarray(expire, np.uint32),
        np.ascontiguousarray(hash32, np.uint32),
        np.ascontiguousarray(deleted, np.bool_),
        np.ascontiguousarray(idx, np.int32), len(idx),
        out_keys, out_expire, out_hash32, out_deleted)
    return True


def merge_counts(a_sbytes, b_sbytes, side: str):
    """Counts of b-items < (side='left') / <= (side='right') each a-item.
    Both inputs ascending fixed-width byte arrays; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a_sbytes)
    b = np.ascontiguousarray(b_sbytes)
    out = np.empty(len(a), np.int64)
    lib.merge_counts(a.view(np.uint8).reshape(-1), len(a),
                     b.view(np.uint8).reshape(-1), len(b),
                     a.dtype.itemsize, 1 if side == "right" else 0, out)
    return out
